#!/usr/bin/env python
"""Headline benchmark: epidemic write-storm convergence (BASELINE config #5).

North star (BASELINE.json): simulate 100k-node p99 time-to-convergence in
<60 s wall-clock, matching 3-node ground truth.  Prints ONE JSON line::

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

``value`` is steady-state wall-clock seconds for a full convergence run
(compile excluded via an identically-shaped warmup compile).
``vs_baseline`` = target/value where target pro-rates the 60 s @ 100k-node
goal linearly in node count (target = 60 * n/100k), so a step-down
measurement can never inflate the score; 0.0 if nothing converged.

Round-1 hardening (VERDICT.md "next round" item 1): the round-1 bench died
with rc=1 because `jax.devices()` on the wedged axon/TPU backend hung
forever and nothing defended against it.  This orchestrator therefore:

- never imports JAX itself — every backend-touching step runs in a
  bench_child.py subprocess with a hard timeout (kill -9 on expiry);
- preflights the backend (devices + tiny matmul) with bounded retries and
  falls back to CPU if the TPU platform is truly wedged;
- climbs a node ladder SMALL→LARGE (4k → 25k → 100k) so some measured
  point always lands, then reports the largest converged size;
- prints the best-so-far result on SIGTERM/SIGINT, so a driver-imposed
  deadline still yields a number;
- records every attempt (incl. failures, distinguishing env-broken from
  sim-broken) in BENCH_DIAG.json and the aux configs #2-#4 in
  BENCH_CONFIGS.json.

Env overrides: BENCH_NODES (cap ladder), BENCH_PAYLOADS, BENCH_PLATFORM
(force platform, e.g. cpu for debug), BENCH_BUDGET_S (total wall budget,
default 1500), BENCH_PREFLIGHT_TIMEOUT, BENCH_AUX=0 (skip configs #2-#4).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))
CHILD = os.path.join(REPO, "bench_child.py")
CACHE_DIR = os.path.join(REPO, ".cache", "jax")

T0 = time.monotonic()
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1500"))

# best-so-far, printed exactly once (normal exit or signal)
_best: dict | None = None
_secondary: dict | None = None
_fault_storm: dict | None = None
_tier_1m: dict | None = None
_serving: dict | None = None
_serving_mp: dict | None = None
_topo_frontier: dict | None = None
_proto_frontier: dict | None = None
_printed = False
_diag: dict = {"attempts": [], "preflight": None, "started_unix": time.time()}


def _remaining() -> float:
    return BUDGET_S - (time.monotonic() - T0)


def _emit_and_exit(code: int = 0) -> None:
    """Print the single JSON result line (best so far, or a zero record)."""
    global _printed
    if _printed:
        os._exit(code)
    _printed = True
    if _best is not None:
        out = dict(_best)
    else:
        out = {
            "metric": "sim_write_storm_p99_convergence_wallclock",
            "value": 0.0,
            "unit": "s",
            "vs_baseline": 0.0,
        }
    # the adversarial gapstress rung rides the same line as a secondary
    # record (VERDICT r3 item 3: both rungs official, each with its own
    # vs_baseline); the driver's primary schema is unchanged
    if _secondary is not None:
        out["secondary"] = _secondary
    # fault-storm rung (ISSUE 4): the 100k storm under a loss+partition
    # FaultPlan on the packed path, tracked as its own secondary record
    if _fault_storm is not None:
        out["packed_fault_storm"] = _fault_storm
    # the 1M-node tier (ISSUE 7): the storm schedule at a million nodes,
    # node-axis-sharded, defensible-wall verified — the "millions of
    # users" scale claim as a measured number
    if _tier_1m is not None:
        out["fault_storm_1m"] = _tier_1m
    # host-serving rung (ISSUE 8): publish→subscriber-visible latency
    # through the real serving path (HTTP → broadcast → apply →
    # subscription fan-out), faultless + FaultPlan, with the
    # instrumentation-overhead fraction recorded like the sim rung's
    if _serving is not None:
        out["serving_loadgen"] = _serving
    # multi-process serving rung (ISSUE 13): ≥1000 writer lanes sharded
    # across loadgen worker processes against a real devcluster —
    # faultless p99, kill+restart with zero acked writes lost, and an
    # overload condition whose 429 counts prove graceful degradation
    if _serving_mp is not None:
        out["serving_loadgen_mp"] = _serving_mp
    # peer-sampler frontier rung (ISSUE 9): uniform vs PeerSwap
    # convergence-rounds × wire-bytes across two topology families —
    # the paper-grounded sampler comparison, tracked per bench run
    if _topo_frontier is not None:
        out["peer_sampler_frontier"] = _topo_frontier
    # protocol frontier rung (ISSUE 11): four named protocol variants ×
    # two topologies reduced to per-family rounds/wire ratios vs the
    # baseline point, plus the storm-scale PeerSwap sampler cell — the
    # protocol-space Pareto, tracked per bench run
    if _proto_frontier is not None:
        out["protocol_frontier"] = _proto_frontier
    print(json.dumps(out), flush=True)
    _write_diag()
    os._exit(code)


def _write_diag() -> None:
    _diag["elapsed_s"] = round(time.monotonic() - T0, 1)
    try:
        with open(os.path.join(REPO, "BENCH_DIAG.json"), "w") as f:
            json.dump(_diag, f, indent=1, default=str)
    except OSError:
        pass


def _on_signal(signum, frame):  # noqa: ANN001
    _diag["killed_by_signal"] = signum
    _emit_and_exit(0)


def run_child(spec: dict, timeout: float) -> dict:
    """Run one bench_child.py attempt with a hard timeout; always returns a
    result dict (``ok=False`` + reason on timeout/crash)."""
    fd, out_path = tempfile.mkstemp(prefix="bench_", suffix=".json")
    os.close(fd)
    os.unlink(out_path)
    spec = dict(spec, out=out_path, cache_dir=CACHE_DIR)
    t0 = time.monotonic()
    try:
        proc = subprocess.Popen(
            [sys.executable, CHILD, json.dumps(spec)],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
            cwd=REPO,
        )
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            return {
                "ok": False,
                "error": f"timeout after {timeout:.0f}s (backend hang or too slow)",
                "timeout": True,
                "wall_s": round(time.monotonic() - t0, 1),
            }
        if os.path.exists(out_path):
            with open(out_path) as f:
                res = json.load(f)
            res["wall_s"] = round(time.monotonic() - t0, 1)
            return res
        return {
            "ok": False,
            "error": f"child exited rc={proc.returncode} with no result file",
            "wall_s": round(time.monotonic() - t0, 1),
        }
    finally:
        if os.path.exists(out_path):
            os.unlink(out_path)


def kill_stale_device_holders(
    markers: tuple = ("bench_child.py", "coo_spike"),
    repo: str | None = None,
) -> list[int]:
    """Offensive wedge defense (VERDICT r2 item 8): a TPU client process
    that survived an earlier bench/pytest run keeps the single tunneled
    chip's context alive and is the documented way the backend degrades
    across a session (doc/experiments/TPU_BACKEND_NOTES.md).  Before
    preflight, SIGKILL any python process that (a) is running this repo's
    bench_child.py / coo_spike (the only spawns that touch the chip —
    repo pytest runs are CPU-pinned by tests/conftest.py and deliberately
    spared), and (b) is not this process or an ancestor.  Best-effort:
    /proc scan, never raises."""
    me = os.getpid()
    ancestors = set()
    pid = me
    for _ in range(32):
        try:
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().split(")")[-1].split()[1])
        except (OSError, ValueError, IndexError):
            break
        ancestors.add(pid)
        if ppid <= 1:
            break
        pid = ppid
    # default markers cover only processes that actually touch the TPU
    # device: bench children and spike scripts.  Repo pytest runs are
    # pinned to CPU by tests/conftest.py and never hold the chip —
    # killing them would hurt a concurrent developer for no benefit.
    # (markers/repo are injectable so tests can exercise the mechanism
    # in a sandbox without shooting a real bench run.)
    repo = repo or REPO
    killed: list[int] = []
    if os.environ.get("BENCH_NO_KILL") == "1":
        # opt-out (ADVICE r3): a concurrent healthy bench / a developer
        # debugging bench_child under pdb must not be shot
        return killed
    min_age = float(os.environ.get("BENCH_KILL_MIN_AGE_S", "0"))
    try:
        pids = [int(d) for d in os.listdir("/proc") if d.isdigit()]
    except OSError:
        return killed
    for pid in pids:
        if pid == me or pid in ancestors:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode("utf-8", "replace").replace("\0", " ")
            if "python" not in cmd:
                continue
            if not any(m in cmd for m in markers):
                continue
            cwd = os.readlink(f"/proc/{pid}/cwd")
            if cwd != repo and not cwd.startswith(repo + os.sep):
                continue
            if min_age > 0:
                # spare freshly-started processes (likely a live bench,
                # not a stale remnant)
                age = time.time() - os.stat(f"/proc/{pid}").st_mtime
                if age < min_age:
                    continue
            os.kill(pid, signal.SIGKILL)
            killed.append(pid)
        except (OSError, ValueError):
            continue
    if killed:
        time.sleep(2.0)  # let the device context actually tear down
    return killed


def preflight() -> tuple[str, str] | None:
    """Probe backends in a subprocess; returns (requested_platform,
    actual_platform) or None.  ``actual_platform`` is what the child's
    `jax.devices()[0].platform` reported — the ladder/metric naming must
    key off reality, not the request (a default platform can silently
    resolve to CPU when the TPU plugin is absent).

    Retries the default (TPU) platform with growing timeouts — transient
    tunnel wedges were the round-1 killer — then falls back to CPU so the
    benchmark still lands a measured (if slower) point.

    Each retry backs off exponentially, bounded by
    BENCH_PREFLIGHT_BACKOFF_CAP_S (default 10 s) so a dead backend can
    never silently eat the storm budget in sleeps; every attempt lands
    in ``_diag["preflight_attempts"]`` — requested platform, timeout,
    outcome, backoff — flushed to BENCH_DIAG.json as it happens so a
    killed run still shows how far preflight got.
    """
    forced = os.environ.get("BENCH_PLATFORM")
    base_t = float(os.environ.get("BENCH_PREFLIGHT_TIMEOUT", "150"))
    backoff_cap = float(
        os.environ.get("BENCH_PREFLIGHT_BACKOFF_CAP_S", "10")
    )
    candidates = [forced] if forced else [None, None, None, "cpu"]
    trail: list[dict] = []
    _diag["preflight_attempts"] = trail
    for i, plat in enumerate(candidates):
        timeout = min(base_t * (1 + i * 0.5), max(30.0, _remaining() * 0.4))
        if _remaining() < 30:
            trail.append(
                {"attempt": i + 1, "skipped": "budget exhausted"}
            )
            _write_diag()
            break
        res = run_child(
            {"mode": "preflight", "platform": plat}, timeout=timeout
        )
        res["requested_platform"] = plat or "default(axon/tpu)"
        _diag["preflight"] = res
        _diag["attempts"].append({"phase": "preflight", **res})
        entry = {
            "attempt": i + 1,
            "of": len(candidates),
            "requested_platform": res["requested_platform"],
            "timeout_s": round(timeout, 1),
            "ok": bool(res.get("ok")),
            "wall_s": res.get("wall_s"),
        }
        if not res.get("ok"):
            entry["error"] = res.get("error")
        trail.append(entry)
        _write_diag()
        if res.get("ok"):
            return plat or "", str(res.get("platform", plat or ""))
        backoff = min(backoff_cap, float(2**i))
        entry["backoff_s"] = backoff
        time.sleep(backoff)
    return None


def main() -> int:
    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    global _best

    if os.environ.get("BENCH_PLATFORM") != "cpu":
        # a cpu-forced bench holds no device context worth defending
        _diag["stale_killed"] = kill_stale_device_holders()
    pf = preflight()
    if pf is None:
        _diag["verdict"] = "env-broken: no JAX backend initialised in time"
        _emit_and_exit(0)
    plat, actual = pf

    cap = int(os.environ.get("BENCH_NODES", "100000"))
    n_payloads = int(os.environ.get("BENCH_PAYLOADS", "512"))
    on_cpu = actual == "cpu"
    ladder = [n for n in (4_000, 25_000, 100_000) if n <= cap] or [cap]
    # the CPU fallback climbs the FULL ladder since round 3's kernel
    # work (unmetered provably-unbinding budgets + 2-slot delay ring):
    # the 100k storm converges in ~39-45 s wall on CPU (load-dependent,
    # 27 rounds × 1.5-1.6 s/round) — under the 60 s north-star target,
    # integrity verdict ok
    _diag["platform"] = actual or plat or "default(axon/tpu)"
    _diag["ladder"] = ladder

    ladder_i = 0
    while ladder_i < len(ladder):
        n = ladder[ladder_i]
        ladder_i += 1
        rem = _remaining()
        if rem < 60:
            _diag["attempts"].append(
                {"phase": "storm", "nodes": n, "skipped": "budget exhausted"}
            )
            break
        # first ladder rung pays full compile; leave room for later rungs
        timeout = min(rem - 30, max(240.0, rem * 0.5))
        res = run_child(
            {
                "mode": "storm",
                "platform": plat or None,
                "nodes": n,
                "payloads": n_payloads,
                # optional jax.profiler capture around the storm rung
                # (ISSUE 5): BENCH_XLA_PROFILE=DIR mirrors the CLI's
                # --xla-profile
                "xla_profile": os.environ.get("BENCH_XLA_PROFILE"),
            },
            timeout=timeout,
        )
        _diag["attempts"].append({"phase": "storm", "nodes": n, **res})
        _write_diag()
        if res.get("timeout") and not on_cpu:
            # mid-ladder wedge: the chip survived preflight but hung on a
            # real shape (the documented degradation mode,
            # TPU_BACKEND_NOTES.md) — drop to CPU and retry this rung
            # rather than burning the rest of the budget on a dead
            # device.  Only TIMEOUTS divert (a deterministic sim failure
            # would fail identically on CPU); any earlier TPU rung's
            # record stays and a larger converged CPU rung supersedes it.
            _diag["midladder_cpu_fallback_at"] = n
            plat, actual, on_cpu = "cpu", "cpu", True
            _diag["platform"] = "cpu"
            _diag.setdefault("stale_killed", []).extend(
                kill_stale_device_holders()
            )
            ladder_i -= 1
            continue
        if res.get("ok") and res.get("metrics", {}).get("converged"):
            m = res["metrics"]
            value = round(float(m["wall_clock_s"]), 3)
            target = 60.0 * (n / 100_000.0)
            suffix = "_cpu_fallback" if on_cpu else ""
            _best = {
                "metric": f"sim_write_storm_{n // 1000}k_p99_convergence_wallclock{suffix}",
                "value": value,
                "unit": "s",
                "vs_baseline": round(target / value, 3) if value > 0 else 0.0,
            }
            _diag["best"] = {"nodes": n, **m}
        elif res.get("timeout") and _best is not None:
            break  # bigger sizes will only be slower; keep what we have

    # official rung #2: the ADVERSARIAL storm (VERDICT r3 item 3) — mixed
    # 1 B-8 KiB payloads so the byte-budget actually meters, 30% loss,
    # burst injection overflowing the K gap slots (gap_overflow > 0), at
    # 10k nodes.  The friendly 100k rung stays the primary metric; this
    # rung is the same machinery with every limiter engaged, reported as
    # the `secondary` record with its own budget-derived vs_baseline.
    global _secondary
    # r5: the limiter class runs PACKED (sim/packed.py budget_prefix_words
    # + per-edge loss words), so the adversarial rung scales with the
    # platform — 25.6k nodes on a healthy chip, the r4-calibrated 4096 on
    # CPU fallback (184.6 s packed vs 227 s dense, r5 measurement).  The
    # target pro-rates the 4k/240 s budget linearly in nodes.
    gs_nodes = int(
        os.environ.get(
            "BENCH_GAPSTRESS_NODES", "4096" if on_cpu else "25600"
        )
    )
    gs_target = float(
        os.environ.get(
            "BENCH_GAPSTRESS_TARGET_S", str(240.0 * (gs_nodes / 4096.0))
        )
    )
    if _remaining() > 240:
        res = run_child(
            {
                "mode": "aux",
                "platform": plat or None,
                "fn": "config_write_storm_gapstress",
                "seed": 1,
                "kwargs": {"n_nodes": gs_nodes},
            },
            timeout=min(_remaining() - 60, 900.0),
        )
        _diag["attempts"].append({"phase": "gapstress", "nodes": gs_nodes, **res})
        m = res.get("metrics") or {}
        if res.get("ok") and m.get("converged"):
            value = round(float(m["wall_clock_s"]), 3)
            suffix = "_cpu_fallback" if on_cpu else ""
            _secondary = {
                "metric": (
                    f"sim_write_storm_gapstress_{gs_nodes // 1000}k_"
                    f"p99_convergence_wallclock{suffix}"
                ),
                "value": value,
                "unit": "s",
                "vs_baseline": round(gs_target / value, 3) if value > 0 else 0.0,
                "gap_overflow_frac_max": m.get("gap_overflow_frac_max"),
            }
            _diag["gapstress"] = {"nodes": gs_nodes, **m}
        _write_diag()

    # host-serving rung (ISSUE 8): the serving path under load — an
    # in-process 3-node cluster flooded by the measured loadgen driver,
    # recording publish→subscriber-visible p50/p95/p99 (faultless AND
    # under the serving FaultPlan) plus the instrumentation-overhead
    # fraction (interleaved per-variant-min A/B, the sim telemetry
    # rung's discipline).  Cheap (~15 s) and pure-host, but still its
    # own child so a hang can never eat the storm budget.
    global _serving
    if os.environ.get("BENCH_SERVING", "1") != "0" and _remaining() > 120:
        sv_nodes = int(os.environ.get("BENCH_SERVING_NODES", "3"))
        sv_writes = int(os.environ.get("BENCH_SERVING_WRITES", "192"))
        res = run_child(
            {
                "mode": "aux",
                "platform": "cpu",  # pure host path: never wake the chip
                "fn": "config_serving_loadgen",
                "seed": 1,
                "kwargs": {"n_nodes": sv_nodes, "n_writes": sv_writes},
            },
            timeout=min(_remaining() - 30, 300.0),
        )
        _diag["attempts"].append(
            {"phase": "serving_loadgen", "nodes": sv_nodes, **res}
        )
        m = res.get("metrics") or {}
        if res.get("ok") and m.get("converged"):
            vl = m.get("publish_visible_s") or {}
            _serving = {
                "metric": (
                    f"serving_loadgen_{sv_nodes}node_"
                    "publish_visible_p99"
                ),
                "value": vl.get("p99"),
                "unit": "s",
                "p50": vl.get("p50"),
                "p95": vl.get("p95"),
                "throughput_wps": m.get("throughput_wps"),
                "consistent": m.get("consistent"),
                "instrumentation_overhead_frac": m.get(
                    "instrumentation_overhead_frac"
                ),
                "faulted_p99_s": (m.get("faulted") or {})
                .get("publish_visible_s", {})
                .get("p99"),
            }
            _diag["serving_loadgen"] = {"nodes": sv_nodes, **m}
        _write_diag()

    # multi-process serving rung (ISSUE 13): the ≥1000-writer form over
    # REAL processes (devcluster agents + sharded loadgen workers) with
    # a kill+restart FaultPlan and an overload (429) condition.  Pure
    # host path, its own child so a wedged devcluster can never eat the
    # storm budget.
    global _serving_mp
    if os.environ.get("BENCH_SERVING_MP", "1") != "0" and _remaining() > 180:
        mp_writers = int(os.environ.get("BENCH_SERVING_MP_WRITERS", "1024"))
        mp_workers = int(os.environ.get("BENCH_SERVING_MP_WORKERS", "8"))
        res = run_child(
            {
                "mode": "aux",
                "platform": "cpu",  # pure host path: never wake the chip
                "fn": "config_serving_loadgen_mp",
                "seed": 1,
                "kwargs": {
                    "n_writers": mp_writers,
                    "n_workers": mp_workers,
                    "n_writes": 2 * mp_writers,
                },
            },
            timeout=min(_remaining() - 30, 600.0),
        )
        _diag["attempts"].append(
            {"phase": "serving_loadgen_mp", "writers": mp_writers, **res}
        )
        m = res.get("metrics") or {}
        if res.get("ok") and m.get("converged"):
            vl = m.get("publish_visible_s") or {}
            _serving_mp = {
                "metric": (
                    f"serving_loadgen_mp_{mp_writers}writers_"
                    "publish_visible_p99"
                ),
                "value": vl.get("p99"),
                "unit": "s",
                "p50": vl.get("p50"),
                "p95": vl.get("p95"),
                "writers": mp_writers,
                "workers": mp_workers,
                "throughput_wps": m.get("throughput_wps"),
                "lost_writes": m.get("lost_writes"),
                "crash_consistent": (m.get("crash") or {}).get("consistent"),
                "crash_p99_s": (m.get("crash") or {})
                .get("publish_visible_s", {})
                .get("p99"),
                "overload_retries_429": (m.get("overload") or {}).get(
                    "retries_429"
                ),
                "overload_rejected": (m.get("overload") or {}).get(
                    "admission_rejected_total"
                ),
            }
            _diag["serving_loadgen_mp"] = {"writers": mp_writers, **m}
        _write_diag()

    # peer-sampler frontier rung (ISSUE 9): the uniform-vs-PeerSwap
    # campaign (both samplers × wan-3x2 × hetero-degree, wire bytes
    # banded) reduced to per-family rounds/wire ratios.  A small dense
    # CPU campaign (~96 nodes) — never wakes the chip, its own child so
    # a hang can't eat the storm budget.
    global _topo_frontier
    if os.environ.get("BENCH_TOPO", "1") != "0" and _remaining() > 180:
        tf_nodes = int(os.environ.get("BENCH_TOPO_NODES", "96"))
        res = run_child(
            {
                "mode": "aux",
                "platform": "cpu",
                "fn": "config_peer_sampler_frontier",
                "seed": 1,
                "kwargs": {"n_nodes": tf_nodes},
            },
            timeout=min(_remaining() - 60, 600.0),
        )
        _diag["attempts"].append(
            {"phase": "peer_sampler_frontier", "nodes": tf_nodes, **res}
        )
        m = res.get("metrics") or {}
        if res.get("ok") and m.get("converged"):
            _topo_frontier = {
                "metric": f"peer_sampler_frontier_{tf_nodes}node",
                "families": m.get("families"),
                "spec_hash": m.get("spec_hash"),
                "result_digest": m.get("result_digest"),
                "wall_clock_s": m.get("wall_clock_s"),
            }
            _diag["peer_sampler_frontier"] = {"nodes": tf_nodes, **m}
        _write_diag()

    # protocol frontier rung (ISSUE 11): the protocol-variant campaign
    # (baseline / swarm-aggressive / push-pull / lab-ordered × wan-3x2 ×
    # flat-lossy, wire bytes banded) reduced to per-family rounds/wire
    # ratios vs baseline, PLUS a storm-scale (≥25k-node) PeerSwap
    # sampler cell so the sampler frontier's 96-node rung stops being
    # the only sampler number.  CPU-pinned like the sampler rung (the
    # campaign is small-dense; the storm cell is the packed CPU shape
    # the gapstress rung already budgets), its own child so a hang
    # can't eat the storm budget.
    global _proto_frontier
    if os.environ.get("BENCH_PROTO", "1") != "0" and _remaining() > 300:
        pf_nodes = int(os.environ.get("BENCH_PROTO_NODES", "96"))
        pf_storm = int(os.environ.get("BENCH_PROTO_STORM_NODES", "25600"))
        res = run_child(
            {
                "mode": "aux",
                "platform": "cpu",
                "fn": "config_protocol_frontier",
                "seed": 1,
                "kwargs": {
                    "n_nodes": pf_nodes,
                    "sampler_storm_nodes": pf_storm,
                },
            },
            timeout=min(_remaining() - 60, 900.0),
        )
        _diag["attempts"].append(
            {"phase": "protocol_frontier", "nodes": pf_nodes, **res}
        )
        m = res.get("metrics") or {}
        if res.get("ok") and m.get("converged"):
            _proto_frontier = {
                "metric": f"protocol_frontier_{pf_nodes}node",
                "families": m.get("families"),
                "sampler_storm": m.get("sampler_storm"),
                "spec_hash": m.get("spec_hash"),
                "result_digest": m.get("result_digest"),
                "wall_clock_s": m.get("wall_clock_s"),
            }
            _diag["protocol_frontier"] = {"nodes": pf_nodes, **m}
        _write_diag()

    # fault-storm rung (ISSUE 4): the headline storm shape under a
    # loss burst + half-split partition + crash-with-wipe FaultPlan,
    # on the PACKED round path (run_fault_plan dispatches packed over
    # the bitpack envelope since this PR).  The child runs the fault
    # storm AND a faultless packed run of the same scenario on the same
    # platform, with the defensible-wall machinery (sim/perf.verify_wall)
    # applied to the fault side — acceptance holds the fault wall ≤ 2×
    # the faultless wall.  Reported as its own secondary record so the
    # fault-path trajectory is tracked from this PR on.
    global _fault_storm
    if os.environ.get("BENCH_FAULT_STORM", "1") != "0" and _remaining() > 300:
        fs_nodes = int(
            os.environ.get(
                "BENCH_FAULT_STORM_NODES",
                str(_diag.get("best", {}).get("nodes", min(cap, 100_000))),
            )
        )
        res = run_child(
            {
                "mode": "aux",
                "platform": plat or None,
                "fn": "config_packed_fault_storm",
                "seed": 1,
                "kwargs": {"n_nodes": fs_nodes, "n_payloads": n_payloads},
                "xla_profile": os.environ.get("BENCH_XLA_PROFILE"),
            },
            timeout=min(_remaining() - 60, 900.0),
        )
        _diag["attempts"].append(
            {"phase": "fault_storm", "nodes": fs_nodes, **res}
        )
        m = res.get("metrics") or {}
        if res.get("ok") and m.get("converged"):
            value = round(float(m["wall_clock_s"]), 3)
            suffix = "_cpu_fallback" if on_cpu else ""
            _fault_storm = {
                "metric": (
                    f"sim_packed_fault_storm_{fs_nodes // 1000}k_"
                    f"convergence_wallclock{suffix}"
                ),
                "value": value,
                "unit": "s",
                "round_path": m.get("round_path"),
                "wall_verdict": m.get("sanity", {}).get("verdict"),
                "faultless_wall_clock_s": m.get("faultless_wall_clock_s"),
                # the acceptance ratio: defensible fault wall over the
                # faultless packed wall, same platform both sides
                "fault_over_faultless": round(
                    float(m.get("fault_over_faultless", 0.0)), 3
                ),
            }
            _diag["fault_storm"] = {"nodes": fs_nodes, **m}
        _write_diag()

        # flight-recorder rung (ISSUE 5): the SAME storm schedule with
        # RoundTrace telemetry on — records the per-round coverage-curve
        # digest + bytes/round summary into the bench record, and the
        # defensible per-round overhead ratio vs the plain fault body
        # (acceptance bar: ≤ 10%).  A separate child, so a timeout here
        # can never lose the headline fault-storm record above.
        if (
            os.environ.get("BENCH_TELEMETRY", "1") != "0"
            and _fault_storm is not None
            and _remaining() > 240
        ):
            res = run_child(
                {
                    "mode": "aux",
                    "platform": plat or None,
                    "fn": "config_fault_storm_telemetry",
                    "seed": 1,
                    "kwargs": {
                        "n_nodes": fs_nodes, "n_payloads": n_payloads,
                    },
                    "xla_profile": os.environ.get("BENCH_XLA_PROFILE"),
                },
                timeout=min(_remaining() - 60, 900.0),
            )
            _diag["attempts"].append(
                {"phase": "fault_storm_telemetry", "nodes": fs_nodes, **res}
            )
            m = res.get("metrics") or {}
            if res.get("ok") and m.get("converged"):
                tel_wall = float(m["wall_clock_s"])
                _fault_storm["telemetry"] = {
                    "wall_clock_s": round(tel_wall, 3),
                    # full-run ratio (informational) + the defensible
                    # per-round microbench ratio (the acceptance form)
                    "telemetry_over_plain": round(
                        tel_wall / _fault_storm["value"], 3
                    )
                    if _fault_storm["value"] > 0
                    else None,
                    "per_round_overhead_frac": m.get(
                        "per_round_overhead_frac"
                    ),
                    "coverage_curve_digest": m.get("telemetry", {}).get(
                        "coverage_curve_digest"
                    ),
                    "bytes_per_round": m.get("telemetry", {}).get(
                        "wire_bytes", {}
                    ).get("per_round_mean"),
                }
                _diag["fault_storm_telemetry"] = {"nodes": fs_nodes, **m}
            _write_diag()

        # sharded fault-storm rung (ISSUE 7): the SAME storm schedule
        # with the packed carry's node axis split across the device
        # mesh.  On a real multi-chip slice this is the headline scale
        # path; on a single-device host BENCH_SHARDED_DEVICES=N arms a
        # virtual N-device CPU mesh so the GSPMD partitioning is still
        # exercised (validation, not speed — virtual devices share the
        # host's cores).  At ≤ 8192 nodes the rung re-runs unsharded
        # and asserts bit-equality inside the record itself.
        n_devs = int((_diag.get("preflight") or {}).get("n_devices", 1))
        virt = int(os.environ.get("BENCH_SHARDED_DEVICES", "0"))
        if (
            os.environ.get("BENCH_SHARDED", "1") != "0"
            and (n_devs > 1 or virt > 1)
            and _fault_storm is not None
            and _remaining() > 300
        ):
            res = run_child(
                {
                    "mode": "aux",
                    "platform": plat or None,
                    "fn": "config_packed_fault_storm_sharded",
                    "seed": 1,
                    "kwargs": {
                        "n_nodes": fs_nodes, "n_payloads": n_payloads,
                    },
                    "virtual_devices": virt if n_devs <= 1 else None,
                    "xla_profile": os.environ.get("BENCH_XLA_PROFILE"),
                },
                timeout=min(_remaining() - 60, 900.0),
            )
            _diag["attempts"].append(
                {"phase": "fault_storm_sharded", "nodes": fs_nodes, **res}
            )
            m = res.get("metrics") or {}
            if res.get("ok") and m.get("converged"):
                _fault_storm["sharded"] = {
                    "wall_clock_s": round(float(m["wall_clock_s"]), 3),
                    "n_devices": m.get("n_devices"),
                    "mesh": m.get("mesh"),
                    "round_path": m.get("round_path"),
                    "wall_verdict": m.get("sanity", {}).get("verdict"),
                    "sharded_matches_single": m.get(
                        "sharded_matches_single"
                    ),
                }
                _diag["fault_storm_sharded"] = {"nodes": fs_nodes, **m}
            _write_diag()

    # the 1M-node tier (ISSUE 7): the storm fault schedule at a million
    # nodes, node-axis-sharded over every device, ground-truth
    # membership, under the defensible-wall protocol.  Its own child +
    # budget so a timeout can never lose the rungs above; the wall is a
    # tier entry (tracked trajectory), not a pass/fail gate.
    global _tier_1m
    if os.environ.get("BENCH_1M", "1") != "0" and _remaining() > 700:
        m_nodes = int(os.environ.get("BENCH_1M_NODES", "1000000"))
        res = run_child(
            {
                "mode": "aux",
                "platform": plat or None,
                "fn": "config_fault_storm_1m",
                "seed": 1,
                "kwargs": {"n_nodes": m_nodes, "n_payloads": n_payloads},
                "xla_profile": os.environ.get("BENCH_XLA_PROFILE"),
            },
            timeout=min(_remaining() - 60, 1800.0),
        )
        _diag["attempts"].append(
            {"phase": "fault_storm_1m", "nodes": m_nodes, **res}
        )
        m = res.get("metrics") or {}
        if res.get("ok") and m.get("converged"):
            value = round(float(m["wall_clock_s"]), 3)
            suffix = "_cpu_fallback" if on_cpu else ""
            # name by real node count (an override like
            # BENCH_1M_NODES=250000 must not record a "0m" metric):
            # whole millions read "1m", anything else reads "250k"
            scale = (
                f"{m_nodes // 1_000_000}m"
                if m_nodes % 1_000_000 == 0
                else f"{m_nodes // 1000}k"
            )
            _tier_1m = {
                "metric": (
                    f"sim_fault_storm_{scale}_"
                    f"convergence_wallclock{suffix}"
                ),
                "value": value,
                "unit": "s",
                "n_devices": m.get("n_devices"),
                "mesh": m.get("mesh"),
                "round_path": m.get("round_path"),
                "membership": m.get("membership"),
                "rounds": m.get("rounds"),
                "wall_verdict": m.get("sanity", {}).get("verdict"),
            }
            _diag["fault_storm_1m"] = {"nodes": m_nodes, **m}
        _write_diag()

    # packed-vs-dense A/B on the headline shape (VERDICT r3 item 2: the
    # realized speedup belongs in BENCH_DIAG, not just the spike doc)
    if os.environ.get("BENCH_AB", "1") != "0" and _remaining() > 420:
        res = run_child(
            {
                "mode": "aux",
                "platform": plat or None,
                "fn": "config_storm_ab",
                "seed": 1,
                "kwargs": {"n_nodes": cap, "n_payloads": n_payloads},
            },
            timeout=min(_remaining() - 60, 900.0),
        )
        _diag["storm_ab"] = res.get("metrics") or {
            "ok": False, "error": res.get("error")
        }
        _write_diag()

    # aux configs #2-#4 (VERDICT item 1: "record configs #2-#4 outputs")
    if os.environ.get("BENCH_AUX", "1") != "0" and _remaining() > 90:
        aux = {}
        for fn in (
            "config_swim_churn_64",
            "config_swim_churn_partial",  # #2 at the partial-view tier
            "config_broadcast_1k",
            "config_partition_heal_10k",
            "config_gapstress_distortion",  # #5b: V≫K overflow + control
        ):
            rem = _remaining()
            if rem < 60:
                aux[fn] = {"ok": False, "error": "budget exhausted"}
                continue
            res = run_child(
                {"mode": "aux", "platform": plat or None, "fn": fn},
                timeout=min(rem - 20, 420.0),
            )
            aux[fn] = res
        try:
            with open(os.path.join(REPO, "BENCH_CONFIGS.json"), "w") as f:
                json.dump(aux, f, indent=1, default=str)
        except OSError:
            pass
        _diag["aux_done"] = True

    _emit_and_exit(0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
