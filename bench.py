#!/usr/bin/env python
"""Headline benchmark: 100k-node epidemic write-storm convergence.

BASELINE.json north star: simulate 100k-node p99 time-to-convergence in
<60 s wall-clock, matching 3-node ground truth.  This runs config #5
(16 writers, 4-chunk versions, broadcast + anti-entropy) to full
convergence on the real chip and prints ONE JSON line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

value = steady-state wall-clock seconds for the full convergence run
(compile excluded: an identically-shaped warmup run primes the XLA cache,
matching how the reference's long-lived agents amortise startup).
vs_baseline = 60 / value (>1 ⇒ beating the 60 s target); 0 if unconverged.

Env overrides: BENCH_NODES, BENCH_PAYLOADS, BENCH_PLATFORM=cpu (debug).
"""

import json
import os
import sys


def main() -> int:
    if os.environ.get("BENCH_PLATFORM"):
        import jax

        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    n_nodes = int(os.environ.get("BENCH_NODES", "100000"))
    n_payloads = int(os.environ.get("BENCH_PAYLOADS", "512"))

    from corrosion_tpu.sim.runner import config_write_storm_100k

    # warmup: AOT lower+compile only (primes the cache without running a
    # whole convergence loop)
    config_write_storm_100k(
        seed=0, n_nodes=n_nodes, n_payloads=n_payloads, compile_only=True
    )
    # measured steady-state run
    m = config_write_storm_100k(seed=1, n_nodes=n_nodes, n_payloads=n_payloads)

    value = round(m["wall_clock_s"], 3)
    converged = bool(m["converged"])
    out = {
        "metric": f"sim_write_storm_{n_nodes // 1000}k_p99_convergence_wallclock",
        "value": value,
        "unit": "s",
        "vs_baseline": round(60.0 / value, 3) if converged and value > 0 else 0.0,
    }
    print(json.dumps(out))
    # context for humans on stderr (driver reads stdout only)
    print(
        f"# rounds={m['rounds']} p99_payload_latency={m['p99_payload_latency_rounds']}r "
        f"p99_node_conv_round={m['p99_node_convergence_round']} "
        f"converged={converged} nodes={n_nodes} payloads={n_payloads}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
