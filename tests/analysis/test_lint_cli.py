"""CLI hygiene + baseline workflow + the self-lint gate (ISSUE 10).

- ``sim lint`` / ``python -m corrosion_tpu.analysis`` exit 0 clean,
  1 on non-baselined findings, 2 on usage errors;
- findings print as clickable ``file:line`` refs;
- ``--baseline-write`` is deterministic (byte-identical reruns) and
  content-stable (fingerprints survive line-number shifts);
- the repo itself lints CLEAN against the committed baseline — the
  acceptance gate CI runs (an injected violation turns it red).
"""

import json
import os
import textwrap

import pytest

from corrosion_tpu.analysis import (
    BASELINE_NAME,
    load_baseline,
    run_lint,
)
from corrosion_tpu.analysis.__main__ import lint_main
from corrosion_tpu.analysis.core import write_baseline

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def write(root, rel, source):
    path = root / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return path


@pytest.fixture()
def repo(tmp_path):
    (tmp_path / "corrosion_tpu").mkdir()
    (tmp_path / "corrosion_tpu" / "__init__.py").write_text("")
    return tmp_path


_VIOLATION = """
def f(x):
    try:
        return x()
    except Exception:
        pass
"""


# -- exit codes --------------------------------------------------------------


def test_exit_zero_on_clean_tree(repo, capsys):
    assert lint_main(["--root", str(repo)]) == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_exit_one_on_injected_violation(repo, capsys):
    """The CI gate's red: a fresh violation NOT in the baseline fails
    the run (this is the injected-violation acceptance check)."""
    write(repo, "corrosion_tpu/agent/bad.py", _VIOLATION)
    assert lint_main(["--root", str(repo)]) == 1
    out = capsys.readouterr().out
    # clickable file:line ref, rule code attached
    assert "corrosion_tpu/agent/bad.py:5: CT006" in out


def test_exit_two_on_usage_errors(repo, capsys, tmp_path):
    assert lint_main(["--frobnicate"]) == 2
    assert lint_main(["--root", str(tmp_path / "nowhere")]) == 2
    # explicit --baseline pointing nowhere is a usage error, not an
    # empty baseline: CI must not silently pass on a typo'd path
    assert (
        lint_main(
            ["--root", str(repo), "--baseline", str(tmp_path / "nope.json")]
        )
        == 2
    )


def test_exit_two_on_corrupt_baseline(repo, tmp_path, capsys):
    """A truncated / merge-conflicted baseline must be a usage error
    (exit 2), not a traceback and not a fake findings-red."""
    bad = tmp_path / "corrupt.json"
    bad.write_text("{not json")
    assert lint_main(["--root", str(repo), "--baseline", str(bad)]) == 2
    assert "unreadable baseline" in capsys.readouterr().err


def test_cli_sim_lint_dispatch(capsys):
    """`sim lint` routes to the same implementation jax-free (exit 0
    against the committed repo baseline) and refuses subcommands."""
    from corrosion_tpu.cli.main import main

    assert main(["sim", "lint"]) == 0
    assert main(["sim", "lint", "run"]) == 2


def test_json_format(repo, capsys):
    write(repo, "corrosion_tpu/agent/bad.py", _VIOLATION)
    assert lint_main(["--root", str(repo), "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["clean"] is False
    (finding,) = payload["findings"]
    assert finding["rule"] == "CT006"
    assert finding["path"] == "corrosion_tpu/agent/bad.py"
    assert finding["fingerprint"]


# -- baseline workflow -------------------------------------------------------


def test_baseline_write_then_clean(repo, capsys):
    write(repo, "corrosion_tpu/agent/bad.py", _VIOLATION)
    bl = repo / BASELINE_NAME
    assert lint_main(["--root", str(repo), "--baseline-write"]) == 0
    assert bl.exists()
    # the accepted finding no longer fails the gate...
    assert lint_main(["--root", str(repo)]) == 0
    # ...but --no-baseline still reports it
    assert lint_main(["--root", str(repo), "--no-baseline"]) == 1


def test_baseline_write_deterministic(repo, capsys):
    write(repo, "corrosion_tpu/agent/bad.py", _VIOLATION)
    write(repo, "corrosion_tpu/agent/worse.py", _VIOLATION + _VIOLATION)
    bl = repo / BASELINE_NAME
    assert lint_main(["--root", str(repo), "--baseline-write"]) == 0
    first = bl.read_bytes()
    assert lint_main(["--root", str(repo), "--baseline-write"]) == 0
    assert bl.read_bytes() == first  # byte-identical regeneration


def test_fingerprints_survive_line_shifts(repo):
    path = write(repo, "corrosion_tpu/agent/bad.py", _VIOLATION)
    res1 = run_lint(str(repo))
    # prepend unrelated lines: line numbers move, identity must not
    path.write_text("# a comment\n\nX = 1\n" + path.read_text())
    res2 = run_lint(str(repo))
    assert [f.fingerprint for f in res1.findings] == [
        f.fingerprint for f in res2.findings
    ]
    assert res1.findings[0].line != res2.findings[0].line


def test_identical_lines_get_distinct_stable_fingerprints(repo):
    write(repo, "corrosion_tpu/agent/worse.py", _VIOLATION + _VIOLATION)
    res = run_lint(str(repo))
    prints = [f.fingerprint for f in res.findings]
    assert len(prints) == 2 and len(set(prints)) == 2
    # editing the FLAGGED line re-surfaces it (identity folds the text)
    res2 = run_lint(str(repo))
    assert [f.fingerprint for f in res2.findings] == prints


def test_baseline_roundtrip(repo, tmp_path):
    write(repo, "corrosion_tpu/agent/bad.py", _VIOLATION)
    res = run_lint(str(repo))
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), res)
    loaded = load_baseline(str(bl))
    assert set(loaded) == {f.fingerprint for f in res.findings}
    res2 = run_lint(str(repo), baseline=loaded)
    assert res2.clean and len(res2.baselined) == 1


# -- the self-lint gate ------------------------------------------------------


def test_repo_is_clean_against_committed_baseline():
    """THE acceptance gate: zero non-baselined findings at HEAD.  A new
    violation anywhere in corrosion_tpu/ (or a drifted campaign
    baseline) fails this test — and the CI lint job — until it is
    fixed, pragma'd with a justification, or deliberately baselined."""
    baseline = load_baseline(os.path.join(REPO_ROOT, BASELINE_NAME))
    result = run_lint(REPO_ROOT, baseline=baseline)
    assert result.findings == [], "\n".join(
        f"{f.ref()}: {f.rule} {f.message}" for f in result.findings
    )
    # the framework actually looked at the repo
    assert result.checked_files > 50


def test_committed_baseline_is_current():
    """Every committed baseline entry still matches a live finding —
    stale entries (the finding was fixed but the baseline kept the
    amnesty) would silently re-admit the bug class."""
    baseline = load_baseline(os.path.join(REPO_ROOT, BASELINE_NAME))
    result = run_lint(REPO_ROOT, baseline=baseline)
    live = {f.fingerprint for f in result.baselined}
    assert set(baseline) == live
