"""Unit tests for the jit-seeded call graph behind CT002 (ISSUE 10):
seed detection across this repo's decorator spellings, cross-module
edge resolution through relative imports, function-reference edges
(loop bodies), and the nested-def reachability contract."""

import textwrap

from corrosion_tpu.analysis.callgraph import CallGraph, ModuleIndex, module_name
from corrosion_tpu.analysis.core import SourceFile


def sf(tmp_path, rel, source):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return SourceFile(str(tmp_path), rel)


def test_module_name_mapping():
    assert module_name("corrosion_tpu/sim/round.py") == "corrosion_tpu.sim.round"
    assert module_name("corrosion_tpu/topo/__init__.py") == "corrosion_tpu.topo"


def test_canonical_name_resolution(tmp_path):
    f = sf(
        tmp_path,
        "corrosion_tpu/sim/m.py",
        """
        import numpy as np
        from jax import random as jrandom

        def f(key):
            np.asarray(key)
            jrandom.bits(key, (4,))
        """,
    )
    idx = ModuleIndex(f)
    import ast

    calls = [n for n in ast.walk(f.tree) if isinstance(n, ast.Call)]
    assert sorted(idx.canonical(c.func) for c in calls) == [
        "jax.random.bits",
        "numpy.asarray",
    ]


def test_seed_detection_all_decorator_spellings(tmp_path):
    f = sf(
        tmp_path,
        "corrosion_tpu/sim/m.py",
        """
        import functools

        import jax
        from functools import partial

        @jax.jit
        def a(x):
            return x

        @functools.partial(jax.jit, static_argnames=("cfg",))
        def b(x, cfg):
            return x

        @partial(jax.jit, static_argnames=("n",))
        def c(x, n):
            return x

        def host(x):
            return x
        """,
    )
    g = CallGraph([f])
    assert sorted(s.qualname for s in g.seeds()) == ["a", "b", "c"]


def test_cross_module_reachability_via_relative_import(tmp_path):
    helpers = sf(
        tmp_path,
        "corrosion_tpu/sim/helpers.py",
        """
        def inner(x):
            return x

        def outer(x):
            return inner(x)
        """,
    )
    kern = sf(
        tmp_path,
        "corrosion_tpu/sim/kern.py",
        """
        import jax

        from .helpers import outer

        @jax.jit
        def run(x):
            return outer(x)

        def host(x):
            return outer(x)
        """,
    )
    g = CallGraph([helpers, kern])
    reach = g.reachable_from_jit()
    assert ("corrosion_tpu.sim.helpers", "outer") in reach
    assert ("corrosion_tpu.sim.helpers", "inner") in reach  # transitive
    assert ("corrosion_tpu.sim.kern", "host") not in reach


def test_function_reference_args_are_edges(tmp_path):
    kern = sf(
        tmp_path,
        "corrosion_tpu/sim/kern.py",
        """
        import jax

        def body(i, c):
            return c

        @jax.jit
        def run(x):
            return jax.lax.fori_loop(0, 3, body, x)
        """,
    )
    g = CallGraph([kern])
    assert ("corrosion_tpu.sim.kern", "body") in g.reachable_from_jit()


def test_nested_defs_of_seed_are_reachable(tmp_path):
    kern = sf(
        tmp_path,
        "corrosion_tpu/sim/kern.py",
        """
        import jax

        @jax.jit
        def run(x):
            def body(i, c):
                return c
            return jax.lax.fori_loop(0, 3, body, x)

        def host(x):
            def local(y):
                return y
            return local(x)
        """,
    )
    g = CallGraph([kern])
    reach = g.reachable_from_jit()
    assert ("corrosion_tpu.sim.kern", "run.body") in reach
    # nested defs of NON-reachable hosts stay out
    assert ("corrosion_tpu.sim.kern", "host.local") not in reach


def test_package_init_relative_imports_resolve_at_package_level(tmp_path):
    """Regression: a package __init__ IS its own package — its
    `from .x import y` must resolve to corrosion_tpu.sim.x, not one
    level too high (which silently dropped CT002 edges through
    package re-exports)."""
    helpers = sf(
        tmp_path,
        "corrosion_tpu/sim/helpers.py",
        """
        def fold(c):
            return c.item()
        """,
    )
    init = sf(
        tmp_path,
        "corrosion_tpu/sim/__init__.py",
        """
        import jax

        from .helpers import fold

        @jax.jit
        def run(x):
            return fold(x)
        """,
    )
    idx = ModuleIndex(init)
    assert idx.aliases["fold"] == "corrosion_tpu.sim.helpers.fold"
    g = CallGraph([helpers, init])
    assert ("corrosion_tpu.sim.helpers", "fold") in g.reachable_from_jit()


def test_real_repo_round_loops_are_covered():
    """The graph over the real sim tier must see the round kernels —
    the CT002 'zero findings' verdict is only meaningful if the seeds
    and the hot path actually resolve (a silently empty graph would
    pass everything)."""
    import os

    from corrosion_tpu.analysis.core import LintContext, collect_files
    from corrosion_tpu.analysis.rules import SIM_TIER

    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    ctx = LintContext(root, collect_files(root))
    files = [f for f in ctx.under(*SIM_TIER) if f.tree is not None]
    g = CallGraph(files)
    assert len(g.seeds()) >= 4  # run_to_convergence/run_fault_plan/...
    reach = g.reachable_from_jit()
    for key in [
        ("corrosion_tpu.sim.round", "round_step"),
        ("corrosion_tpu.sim.packed", "packed_round_step"),
        ("corrosion_tpu.sim.broadcast", "broadcast_step"),
        ("corrosion_tpu.sim.topology", "aligned_u8_bits"),
        ("corrosion_tpu.topo.sampler", "peerswap_step"),
    ]:
        assert key in reach, key
