"""Per-rule fixture tests for corrolint (ISSUE 10).

Each rule gets the bad-snippet-flagged / good-snippet-clean /
pragma-suppresses triple over a synthetic repo tree, so rule scope and
pragma semantics are pinned independently of the real repo's state
(tests/analysis/test_lint_cli.py pins THAT via the self-lint test).
"""

import json
import textwrap

import pytest

from corrosion_tpu.analysis import run_lint
from corrosion_tpu.analysis.rules import (
    BlockingCallInAsync,
    BroadExceptSwallow,
    HostSyncInKernel,
    MetaKeyShadow,
    NondeterminismInSimTier,
    UnalignedU8Draw,
)
from corrosion_tpu.analysis.specdrift import SpecHashDrift


def write(root, rel, source):
    path = root / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return path


@pytest.fixture()
def repo(tmp_path):
    """A minimal fixture repo: just the package dir the walker needs."""
    (tmp_path / "corrosion_tpu").mkdir()
    (tmp_path / "corrosion_tpu" / "__init__.py").write_text("")
    return tmp_path


def lint(repo, rule_cls):
    return run_lint(str(repo), rules=[rule_cls()])


# -- CT001 unaligned-u8-draw -------------------------------------------------


def test_ct001_flags_raw_bits_draw(repo):
    write(
        repo,
        "corrosion_tpu/sim/draws.py",
        """
        import jax
        import jax.numpy as jnp

        def loss_mask(key, shape):
            return jax.random.bits(key, shape, dtype=jnp.uint8)
        """,
    )
    res = lint(repo, UnalignedU8Draw)
    assert [f.rule for f in res.findings] == ["CT001"]
    assert "aligned_u8_bits" in res.findings[0].message


def test_ct001_aliased_import_cannot_dodge(repo):
    write(
        repo,
        "corrosion_tpu/sim/draws.py",
        """
        from jax import random as jrandom

        def loss_mask(key, shape):
            return jrandom.bits(key, shape)
        """,
    )
    assert len(lint(repo, UnalignedU8Draw).findings) == 1


def test_ct001_blessed_site_and_good_draws_clean(repo):
    # the ONE blessed implementation is exempt...
    write(
        repo,
        "corrosion_tpu/sim/topology.py",
        """
        import jax
        import jax.numpy as jnp

        def aligned_u8_bits(key, shape):
            return jax.random.bits(key, (4,), dtype=jnp.uint32)
        """,
    )
    # ...and non-bits draws (randint/uniform: word-atom dtypes) are fine
    write(
        repo,
        "corrosion_tpu/sim/kernels.py",
        """
        import jax

        def pick(key, n):
            return jax.random.randint(key, (n,), 0, n)
        """,
    )
    assert lint(repo, UnalignedU8Draw).clean


def test_ct001_host_tier_out_of_scope(repo):
    write(
        repo,
        "corrosion_tpu/agent/hosty.py",
        """
        import jax

        def f(key):
            return jax.random.bits(key, (4,))
        """,
    )
    assert lint(repo, UnalignedU8Draw).clean


def test_pragma_star_disables_all_rules(repo):
    write(
        repo,
        "corrosion_tpu/sim/draws.py",
        """
        import jax

        def f(key):
            return jax.random.bits(key, (4,))  # corrolint: disable=*
        """,
    )
    res = lint(repo, UnalignedU8Draw)
    assert res.clean and res.suppressed == 1


def test_ct001_pragma_suppresses(repo):
    write(
        repo,
        "corrosion_tpu/sim/draws.py",
        """
        import jax

        def f(key):
            # corrolint: disable=CT001 — fixture-justified exception
            return jax.random.bits(key, (4,))
        """,
    )
    res = lint(repo, UnalignedU8Draw)
    assert res.clean and res.suppressed == 1


# -- CT002 host-sync-in-kernel ----------------------------------------------


def test_ct002_flags_sync_reachable_from_jit(repo):
    write(
        repo,
        "corrosion_tpu/sim/kern.py",
        """
        import functools

        import jax
        import numpy as np

        def helper(x):
            return np.asarray(x)

        def host_only(x):
            return np.asarray(x)

        @functools.partial(jax.jit, static_argnames=("n",))
        def run(x, n):
            return helper(x)
        """,
    )
    res = lint(repo, HostSyncInKernel)
    assert len(res.findings) == 1
    assert "helper" in res.findings[0].message  # host_only NOT flagged


def test_ct002_cross_module_and_loop_body_reachability(repo):
    write(
        repo,
        "corrosion_tpu/sim/helpers.py",
        """
        def fold(c):
            return c.item()
        """,
    )
    write(
        repo,
        "corrosion_tpu/sim/kern.py",
        """
        import jax

        from .helpers import fold

        @jax.jit
        def run(x):
            def body(i, c):
                return fold(c)
            return jax.lax.fori_loop(0, 3, body, x)
        """,
    )
    res = lint(repo, HostSyncInKernel)
    assert [f.path for f in res.findings] == ["corrosion_tpu/sim/helpers.py"]
    assert ".item()" in res.findings[0].message


def test_ct002_unreachable_sync_clean(repo):
    write(
        repo,
        "corrosion_tpu/sim/runner2.py",
        """
        import jax
        import numpy as np

        @jax.jit
        def run(x):
            return x + 1

        def measure(x):
            out = run(x)
            jax.block_until_ready(out)
            return np.asarray(out)
        """,
    )
    assert lint(repo, HostSyncInKernel).clean


# -- CT003 nondeterminism-in-sim-tier ---------------------------------------


def test_ct003_flags_ambient_entropy(repo):
    write(
        repo,
        "corrosion_tpu/campaign/sched.py",
        """
        import os
        import random
        import time

        import numpy as np

        def jitter():
            return time.time() + random.random() + np.random.rand()

        def token():
            return os.urandom(8)
        """,
    )
    res = lint(repo, NondeterminismInSimTier)
    assert sorted(
        m for f in res.findings for m in [f.message.split()[1]]
    ) == ["numpy.random.rand", "os.urandom", "random.random", "time.time"]


def test_ct003_monotonic_wall_clock_allowed(repo):
    write(
        repo,
        "corrosion_tpu/sim/walls.py",
        """
        import time

        def wall():
            t0 = time.monotonic()
            return time.monotonic() - t0, time.perf_counter()
        """,
    )
    assert lint(repo, NondeterminismInSimTier).clean


def test_ct003_jax_random_not_confused_with_stdlib(repo):
    write(
        repo,
        "corrosion_tpu/sim/rng.py",
        """
        from jax import random

        def draw(key):
            return random.uniform(key, (4,))
        """,
    )
    assert lint(repo, NondeterminismInSimTier).clean


def test_ct003_host_tier_out_of_scope(repo):
    write(
        repo,
        "corrosion_tpu/agent/clocky.py",
        """
        import time

        def now():
            return time.time()
        """,
    )
    assert lint(repo, NondeterminismInSimTier).clean


# -- CT004 meta-key-shadow ---------------------------------------------------


_SIMCONFIG_FIXTURE = """
import dataclasses

@dataclasses.dataclass(frozen=True)
class SimConfig:
    n_nodes: int
    n_writers: int = 1
    fanout: int = 3
"""


def test_ct004_undeclared_shadow_flagged(repo):
    write(repo, "corrosion_tpu/sim/state.py", _SIMCONFIG_FIXTURE)
    write(
        repo,
        "corrosion_tpu/campaign/spec.py",
        """
        _SCENARIO_META_KEYS = (
            "serving",
            "n_writers",
        )
        _TOPOLOGY_KEYS = ("loss",)
        """,
    )
    res = lint(repo, MetaKeyShadow)
    assert len(res.findings) == 1
    assert "n_writers" in res.findings[0].message
    # anchored at the offending key's own line
    assert res.findings[0].line == 4


def test_ct004_forwarded_declaration_clean(repo):
    write(repo, "corrosion_tpu/sim/state.py", _SIMCONFIG_FIXTURE)
    write(
        repo,
        "corrosion_tpu/campaign/spec.py",
        """
        _SCENARIO_META_KEYS = ("serving", "n_writers")
        _TOPOLOGY_KEYS = ("loss",)
        FORWARDED_META_KEYS = ("n_writers",)
        """,
    )
    assert lint(repo, MetaKeyShadow).clean


def test_ct004_topology_keys_checked_too(repo):
    write(repo, "corrosion_tpu/sim/state.py", _SIMCONFIG_FIXTURE)
    write(
        repo,
        "corrosion_tpu/campaign/spec.py",
        """
        _SCENARIO_META_KEYS = ("serving",)
        _TOPOLOGY_KEYS = ("fanout",)
        FORWARDED_META_KEYS = ("n_writers",)
        """,
    )
    res = lint(repo, MetaKeyShadow)
    assert len(res.findings) == 1 and "fanout" in res.findings[0].message


# -- CT005 blocking-call-in-async -------------------------------------------


def test_ct005_flags_blocking_in_async(repo):
    write(
        repo,
        "corrosion_tpu/agent/loopy.py",
        """
        import sqlite3
        import time

        async def tick(conn):
            time.sleep(0.1)
            conn.set_authorizer(None)
            db = sqlite3.connect(":memory:")
            return db
        """,
    )
    res = lint(repo, BlockingCallInAsync)
    hits = sorted(f.message.split()[1] for f in res.findings)
    assert hits == [".set_authorizer(...)", "sqlite3.connect", "time.sleep"]


def test_ct005_sync_def_and_executor_nested_clean(repo):
    write(
        repo,
        "corrosion_tpu/agent/loopy.py",
        """
        import asyncio
        import time

        def sync_tick():
            time.sleep(0.1)

        async def tick():
            def blocking():
                time.sleep(0.1)  # runs on an executor thread
            await asyncio.to_thread(blocking)
            await asyncio.sleep(0.1)
        """,
    )
    assert lint(repo, BlockingCallInAsync).clean


def test_ct005_sim_tier_out_of_scope(repo):
    write(
        repo,
        "corrosion_tpu/sim/async_util.py",
        """
        import time

        async def tick():
            time.sleep(0.1)
        """,
    )
    assert lint(repo, BlockingCallInAsync).clean


def test_ct005_pragma_suppresses(repo):
    write(
        repo,
        "corrosion_tpu/agent/loopy.py",
        """
        import time

        async def tick():
            # corrolint: disable=CT005 — fixture-justified exception
            time.sleep(0.1)
        """,
    )
    res = lint(repo, BlockingCallInAsync)
    assert res.clean and res.suppressed == 1


# -- CT006 broad-except-swallow ---------------------------------------------


def test_ct006_flags_silent_swallow(repo):
    write(
        repo,
        "corrosion_tpu/agent/swallow.py",
        """
        def f(x):
            try:
                return x()
            except Exception:
                pass
        """,
    )
    res = lint(repo, BroadExceptSwallow)
    assert [f.rule for f in res.findings] == ["CT006"]


def test_ct006_bare_except_flagged_narrow_clean(repo):
    write(
        repo,
        "corrosion_tpu/agent/swallow.py",
        """
        def f(x):
            try:
                return x()
            except:
                pass

        def g(x):
            try:
                return x()
            except KeyError:
                pass
        """,
    )
    res = lint(repo, BroadExceptSwallow)
    assert len(res.findings) == 1 and res.findings[0].line == 5


def test_ct006_log_raise_or_bound_use_clean(repo):
    write(
        repo,
        "corrosion_tpu/agent/handled.py",
        """
        import logging

        log = logging.getLogger(__name__)

        def logged(x):
            try:
                return x()
            except Exception:
                log.debug("failed", exc_info=True)

        def reraised(x):
            try:
                return x()
            except Exception:
                raise

        def routed(x, report):
            try:
                return x()
            except Exception as e:
                report.append(repr(e))
        """,
    )
    assert lint(repo, BroadExceptSwallow).clean


def test_ct006_pragma_in_comment_block_above(repo):
    write(
        repo,
        "corrosion_tpu/agent/swallow.py",
        """
        def f(x):
            try:
                return x()
            # corrolint: disable=CT006 — fixture: two-line justified
            # comment directly above the handler
            except Exception:
                pass
        """,
    )
    res = lint(repo, BroadExceptSwallow)
    assert res.clean and res.suppressed == 1


def test_ct006_sim_tier_out_of_scope(repo):
    write(
        repo,
        "corrosion_tpu/sim/simmy.py",
        """
        def f(x):
            try:
                return x()
            except Exception:
                pass
        """,
    )
    assert lint(repo, BroadExceptSwallow).clean


# -- CT007 spec-hash drift ---------------------------------------------------


def _spec_artifact():
    from corrosion_tpu.campaign.spec import builtin_spec

    spec = builtin_spec("fault-parity-3node")
    return {"spec": spec.to_dict(), "spec_hash": spec.spec_hash()}


def test_ct007_matching_baseline_clean(repo):
    art = _spec_artifact()
    write(
        repo,
        "doc/experiments/CAMPAIGN_BASELINE_fault-parity-3node.json",
        json.dumps(art),
    )
    assert lint(repo, SpecHashDrift).clean


def test_ct007_hash_drift_flagged(repo):
    art = _spec_artifact()
    art["spec_hash"] = "0" * 16
    write(
        repo,
        "doc/experiments/CAMPAIGN_BASELINE_fault-parity-3node.json",
        json.dumps(art),
    )
    res = lint(repo, SpecHashDrift)
    assert len(res.findings) == 1
    assert "spec-hash drift" in res.findings[0].message


def test_ct007_builtin_drift_flagged(repo):
    # the embedded spec self-hashes fine, but no longer matches the
    # builtin of the same name — the changed-builtin-without-baseline-
    # regeneration case
    art = _spec_artifact()
    art["spec"]["max_rounds"] = art["spec"]["max_rounds"] + 1
    from corrosion_tpu.campaign.spec import CampaignSpec

    art["spec_hash"] = CampaignSpec.from_dict(art["spec"]).spec_hash()
    write(
        repo,
        "doc/experiments/CAMPAIGN_BASELINE_fault-parity-3node.json",
        json.dumps(art),
    )
    res = lint(repo, SpecHashDrift)
    assert len(res.findings) == 1
    assert "builtin drift" in res.findings[0].message


# -- CT008 unbounded-queue-in-host-tier --------------------------------------


def test_ct008_flags_unbounded_queue_and_deque(repo):
    from corrosion_tpu.analysis.rules import UnboundedQueueInHostTier

    write(
        repo,
        "corrosion_tpu/pubsub/fanout.py",
        """
        import asyncio
        from collections import deque

        def make():
            q = asyncio.Queue()
            z = asyncio.Queue(0)       # asyncio: maxsize<=0 is INFINITE
            y = asyncio.Queue(maxsize=0)
            w = asyncio.Queue(-1)      # negative literal, same class
            d = deque()
            return q, z, y, w, d
        """,
    )
    res = lint(repo, UnboundedQueueInHostTier)
    assert [f.rule for f in res.findings] == ["CT008"] * 5
    assert "maxsize" in res.findings[0].message
    assert "unbounded" in res.findings[1].message
    assert "asyncio.Queue(-1)" in res.findings[3].message


def test_ct008_bounded_and_aliased_clean(repo):
    from corrosion_tpu.analysis.rules import UnboundedQueueInHostTier

    write(
        repo,
        "corrosion_tpu/api/server.py",
        """
        import asyncio
        import collections

        def make(cap):
            # keyword, positional, and module-attribute spellings all
            # count as bounded
            a = asyncio.Queue(maxsize=cap)
            b = asyncio.Queue(cap)
            c = collections.deque([], cap)
            d = collections.deque(maxlen=cap)
            return a, b, c, d
        """,
    )
    assert lint(repo, UnboundedQueueInHostTier).clean


def test_ct008_out_of_scope_tiers_clean(repo):
    """The sim tier and operator tooling are not serving paths."""
    from corrosion_tpu.analysis.rules import UnboundedQueueInHostTier

    for rel in ("corrosion_tpu/sim/runner2.py", "corrosion_tpu/cli/tool.py"):
        write(
            repo,
            rel,
            """
            import asyncio

            def make():
                return asyncio.Queue()
            """,
        )
    assert lint(repo, UnboundedQueueInHostTier).clean


def test_ct008_pragma_documents_external_bound(repo):
    from corrosion_tpu.analysis.rules import UnboundedQueueInHostTier

    write(
        repo,
        "corrosion_tpu/agent/lanes.py",
        """
        import asyncio

        def make():
            # bounded by the drop-oldest policy at enqueue
            # corrolint: disable=CT008
            return asyncio.Queue()
        """,
    )
    assert lint(repo, UnboundedQueueInHostTier).clean


# -- CT009 unbounded-network-await --------------------------------------------


def test_ct009_flags_bare_network_awaits(repo):
    from corrosion_tpu.analysis.rules import UnboundedNetworkAwait

    write(
        repo,
        "corrosion_tpu/agent/neto.py",
        """
        import asyncio

        async def pump(reader, loop, sock):
            hdr = await reader.readexactly(4)
            line = await reader.readline()
            raw = await loop.sock_recv(sock, 4096)
            r, w = await asyncio.open_connection("h", 1)
            return hdr, line, raw, r, w
        """,
    )
    res = lint(repo, UnboundedNetworkAwait)
    assert [f.rule for f in res.findings] == ["CT009"] * 4
    hits = sorted(f.message.split()[3] for f in res.findings)
    assert hits == [
        ".readexactly(...)", ".readline(...)", ".sock_recv(...)",
        "asyncio.open_connection",
    ]


def test_ct009_wait_for_and_timeout_ctx_clean(repo):
    from corrosion_tpu.analysis.rules import UnboundedNetworkAwait

    write(
        repo,
        "corrosion_tpu/agent/neto.py",
        """
        import asyncio

        async def bounded(reader):
            # wrapped op: the await's direct operand is wait_for
            hdr = await asyncio.wait_for(reader.readexactly(4), 2.0)
            async with asyncio.timeout(5.0):
                body = await reader.readexactly(16)
            return hdr, body
        """,
    )
    assert lint(repo, UnboundedNetworkAwait).clean


def test_ct009_nested_def_not_covered_by_outer_timeout(repo):
    """A timeout ctx bounds call SITES in its body, not the body of a
    nested def that may run elsewhere later."""
    from corrosion_tpu.analysis.rules import UnboundedNetworkAwait

    write(
        repo,
        "corrosion_tpu/agent/neto.py",
        """
        import asyncio

        async def outer(reader):
            async with asyncio.timeout(5.0):
                async def escapee():
                    return await reader.readexactly(4)
                return escapee
        """,
    )
    res = lint(repo, UnboundedNetworkAwait)
    assert len(res.findings) == 1
    assert "escapee" in res.findings[0].message


def test_ct009_sync_defs_wrappers_and_other_tiers_clean(repo):
    from corrosion_tpu.analysis.rules import UnboundedNetworkAwait

    # repo wrappers with internal timeouts (bi.recv) are not listed,
    # and sync defs / non-agent tiers are out of scope
    write(
        repo,
        "corrosion_tpu/agent/neto.py",
        """
        async def wrapped(bi):
            return await bi.recv(30.0)
        """,
    )
    write(
        repo,
        "corrosion_tpu/api/neto.py",
        """
        async def pump(reader):
            return await reader.readexactly(4)
        """,
    )
    assert lint(repo, UnboundedNetworkAwait).clean


def test_ct009_pragma_suppresses(repo):
    from corrosion_tpu.analysis.rules import UnboundedNetworkAwait

    write(
        repo,
        "corrosion_tpu/agent/neto.py",
        """
        async def serve(reader):
            # server read: idle peers are normal, SWIM owns liveness
            # corrolint: disable=CT009
            return await reader.readexactly(1)
        """,
    )
    res = lint(repo, UnboundedNetworkAwait)
    assert res.clean and res.suppressed == 1


# -- CT010 unregistered-phase-scope -------------------------------------------

PROFILE_STUB = """\
_SCOPE_PREFIX = "corro."
PHASES = {
    "sampler": "peer sampling",
    "sync": "version sync",
}


def phase_scope(phase):
    raise NotImplementedError


def scope_name(phase):
    raise NotImplementedError
"""


def _write_registry(repo):
    write(repo, "corrosion_tpu/sim/profile.py", PROFILE_STUB)


def test_ct010_flags_unregistered_scope_and_key(repo):
    from corrosion_tpu.analysis.rules import UnregisteredPhaseScope

    _write_registry(repo)
    write(
        repo,
        "corrosion_tpu/sim/kern.py",
        """
        import jax

        from .profile import phase_scope

        def round_step(x):
            with jax.named_scope("corro.mystery"):
                x = x + 1
            with phase_scope("handshake"):
                x = x * 2
            return x
        """,
    )
    res = lint(repo, UnregisteredPhaseScope)
    assert [f.rule for f in res.findings] == ["CT010"] * 2
    assert "unattributed residual" in res.findings[0].message
    assert "handshake" in res.findings[1].message


def test_ct010_registered_and_dynamic_scopes_clean(repo):
    from corrosion_tpu.analysis.rules import UnregisteredPhaseScope

    _write_registry(repo)
    write(
        repo,
        "corrosion_tpu/sim/kern.py",
        """
        import jax

        from . import profile as prof
        from .profile import phase_scope, scope_name

        def round_step(x, name):
            with jax.named_scope("corro.sampler"):
                x = x + 1
            with phase_scope("sync"):
                x = x * 2
            with prof.phase_scope("sampler"):
                x = x / 2
            label = scope_name("sync")
            with jax.named_scope(name):  # dynamic: out of static reach
                x = x - 1
            return x, label
        """,
    )
    assert lint(repo, UnregisteredPhaseScope).clean


def test_ct010_profile_and_host_tier_out_of_scope(repo):
    from corrosion_tpu.analysis.rules import UnregisteredPhaseScope

    # profile.py composes the scope string dynamically (exempt by
    # path); host-tier named_scope strings are not phase annotations
    _write_registry(repo)
    write(
        repo,
        "corrosion_tpu/agent/loopy.py",
        """
        import jax

        def host_probe(x):
            with jax.named_scope("whatever"):
                return x
        """,
    )
    assert lint(repo, UnregisteredPhaseScope).clean


def test_ct010_missing_registry_stays_silent(repo):
    from corrosion_tpu.analysis.rules import UnregisteredPhaseScope

    # no sim/profile.py in the tree: the rule must not flag the whole
    # tier on a registry it cannot read
    write(
        repo,
        "corrosion_tpu/sim/kern.py",
        """
        import jax

        def round_step(x):
            with jax.named_scope("corro.mystery"):
                return x
        """,
    )
    assert lint(repo, UnregisteredPhaseScope).clean


def test_ct010_pragma_suppresses(repo):
    from corrosion_tpu.analysis.rules import UnregisteredPhaseScope

    _write_registry(repo)
    write(
        repo,
        "corrosion_tpu/sim/kern.py",
        """
        import jax

        def round_step(x):
            # corrolint: disable=CT010 — fixture-justified experiment
            with jax.named_scope("corro.experimental"):
                return x
        """,
    )
    res = lint(repo, UnregisteredPhaseScope)
    assert res.clean and res.suppressed == 1


# -- CT011 per-bit-reduction-loop ----------------------------------------------


def test_ct011_flags_loop_and_comprehension_forms(repo):
    from corrosion_tpu.analysis.rules import PerBitReductionLoop

    write(
        repo,
        "corrosion_tpu/sim/kern.py",
        """
        import jax.numpy as jnp

        def bit_counts(words):
            cols = [
                jnp.sum((words >> jnp.uint32(j)) & 1, axis=0)
                for j in range(32)
            ]
            return jnp.stack(cols, axis=-1)

        def byte_totals(words, nb):
            tot = jnp.zeros(words.shape[0], jnp.int32)
            for j in range(32):
                bit = ((words >> jnp.uint32(j)) & 1).sum(axis=-1)
                tot = tot + bit * nb[j]
            return tot
        """,
    )
    res = lint(repo, PerBitReductionLoop)
    assert [f.rule for f in res.findings] == ["CT011"] * 2
    assert "32 memory passes" in res.findings[0].message
    assert "sim/fused.py" in res.findings[0].message


def test_ct011_one_pass_and_out_of_scope_forms_clean(repo):
    from corrosion_tpu.analysis.rules import PerBitReductionLoop

    write(
        repo,
        "corrosion_tpu/sim/kern.py",
        """
        import jax.numpy as jnp

        _SHIFTS = jnp.arange(32)

        def fused_counts(words):
            # a single reduction over a bit-plane axis: not a range(32)
            # loop, so out of the rule's shape even outside fused.py
            return jnp.sum((words[..., None] >> _SHIFTS) & 1, axis=0)

        def elementwise_accumulate(words):
            # 32 iterations but NO reduction call — an elementwise
            # accumulation pattern (budget prefix walk), not a re-read
            acc = jnp.zeros_like(words, jnp.int32)
            for j in range(32):
                acc = acc + ((words >> jnp.uint32(j)) & 1)
            return acc

        def pack(bits):
            # left shift builds words; only >> re-reads per bit
            tot = 0
            for j in range(32):
                tot = tot + (bits[..., j].astype(jnp.uint32) << j).sum()
            return tot

        def small_unroll(words, k):
            # non-32 static unroll (gap slots): different loop class
            outs = [
                jnp.sum((words >> jnp.uint32(j)) & 1) for j in range(8)
            ]
            return outs
        """,
    )
    write(
        repo,
        "corrosion_tpu/agent/hostside.py",
        """
        def host_popcount(words):
            return sum((int(w) >> j) & 1 for j in range(32) for w in words)
        """,
    )
    assert lint(repo, PerBitReductionLoop).clean


def test_ct011_fused_module_keeps_the_oracle(repo):
    from corrosion_tpu.analysis.rules import PerBitReductionLoop

    # sim/fused.py is the one sanctioned home for the legacy loop form:
    # it is the CORRO_FUSED_ROUND oracle the fused forms are pinned to
    write(
        repo,
        "corrosion_tpu/sim/fused.py",
        """
        import jax.numpy as jnp

        def word_bit_counts_legacy(words):
            cols = [
                jnp.sum((words >> jnp.uint32(j)) & 1, axis=0)
                for j in range(32)
            ]
            return jnp.stack(cols, axis=-1)
        """,
    )
    assert lint(repo, PerBitReductionLoop).clean


def test_ct011_pragma_suppresses(repo):
    from corrosion_tpu.analysis.rules import PerBitReductionLoop

    write(
        repo,
        "corrosion_tpu/sim/kern.py",
        """
        import jax.numpy as jnp

        def diag_counts(words):
            # corrolint: disable=CT011 — one-shot diagnostic, not a round kernel
            cols = [jnp.sum(words >> j) for j in range(32)]
            return cols
        """,
    )
    res = lint(repo, PerBitReductionLoop)
    assert res.clean and res.suppressed == 1
