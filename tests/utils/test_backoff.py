"""Backoff: retry cap / give-up signal, reset, seeded determinism
(ISSUE 2 satellite: reconnect loops need a bounded-retries mode)."""

import random

import pytest

from corrosion_tpu.utils.backoff import Backoff


def test_uncapped_backoff_never_gives_up():
    b = Backoff(0.01, 0.1, rng=random.Random(1))
    for _ in range(100):
        assert 0.01 <= next(b) <= 0.1
    assert not b.gave_up


def test_max_retries_cap_raises_stopiteration_and_signals_give_up():
    b = Backoff(0.01, 0.1, rng=random.Random(1), max_retries=5)
    draws = list(b)  # a for-loop over the backoff simply ends at the cap
    assert len(draws) == 5
    assert b.gave_up
    with pytest.raises(StopIteration):
        next(b)
    assert b.attempts == 5  # a refused draw spends no budget


def test_reset_restores_interval_and_retry_budget():
    b = Backoff(0.01, 10.0, rng=random.Random(7), max_retries=3)
    for _ in range(3):
        next(b)
    assert b.gave_up
    b.reset()
    assert not b.gave_up and b.attempts == 0
    # interval restarts from min_s: first post-reset draw is bounded by
    # uniform(min_s, min_s * factor), not by the grown interval
    assert next(b) <= 0.01 * 3.0


def test_seeded_rng_replays_exact_schedule():
    a = list(Backoff(0.05, 2.0, rng=random.Random(42), max_retries=16))
    b = list(Backoff(0.05, 2.0, rng=random.Random(42), max_retries=16))
    assert a == b
    # and a different seed diverges (the draws are really rng-driven)
    c = list(Backoff(0.05, 2.0, rng=random.Random(43), max_retries=16))
    assert a != c


def test_growth_respects_min_max_envelope():
    b = Backoff(0.5, 1.0, rng=random.Random(3))
    seq = [next(b) for _ in range(50)]
    assert all(0.5 <= s <= 1.0 for s in seq)
