"""Backoff: retry cap / give-up signal, reset, seeded determinism
(ISSUE 2 satellite: reconnect loops need a bounded-retries mode)."""

import random

import pytest

from corrosion_tpu.utils.backoff import Backoff


def test_uncapped_backoff_never_gives_up():
    b = Backoff(0.01, 0.1, rng=random.Random(1))
    for _ in range(100):
        assert 0.01 <= next(b) <= 0.1
    assert not b.gave_up


def test_max_retries_cap_raises_stopiteration_and_signals_give_up():
    b = Backoff(0.01, 0.1, rng=random.Random(1), max_retries=5)
    draws = list(b)  # a for-loop over the backoff simply ends at the cap
    assert len(draws) == 5
    assert b.gave_up
    with pytest.raises(StopIteration):
        next(b)
    assert b.attempts == 5  # a refused draw spends no budget


def test_reset_restores_interval_and_retry_budget():
    b = Backoff(0.01, 10.0, rng=random.Random(7), max_retries=3)
    for _ in range(3):
        next(b)
    assert b.gave_up
    b.reset()
    assert not b.gave_up and b.attempts == 0
    # interval restarts from min_s: first post-reset draw is bounded by
    # uniform(min_s, min_s * factor), not by the grown interval
    assert next(b) <= 0.01 * 3.0


def test_seeded_rng_replays_exact_schedule():
    a = list(Backoff(0.05, 2.0, rng=random.Random(42), max_retries=16))
    b = list(Backoff(0.05, 2.0, rng=random.Random(42), max_retries=16))
    assert a == b
    # and a different seed diverges (the draws are really rng-driven)
    c = list(Backoff(0.05, 2.0, rng=random.Random(43), max_retries=16))
    assert a != c


def test_growth_respects_min_max_envelope():
    b = Backoff(0.5, 1.0, rng=random.Random(3))
    seq = [next(b) for _ in range(50)]
    assert all(0.5 <= s <= 1.0 for s in seq)


# -- wall budget + Retry-After clamp (ISSUE 15 satellite) ----------------


def test_clamp_caps_server_retry_after_to_remaining_budget():
    b = Backoff(0.01, 0.1, rng=random.Random(1), give_up_s=5.0)
    # a bogus Retry-After: 3600 must not park the caller past its deadline
    assert b.clamp(3600.0) <= 5.0
    # small hints pass through untouched
    assert b.clamp(0.25) == 0.25


def test_clamp_is_identity_when_unbudgeted():
    b = Backoff(0.01, 0.1, rng=random.Random(1))
    assert b.remaining_s() is None
    assert b.clamp(3600.0) == 3600.0


def test_wall_budget_exhaustion_signals_give_up(monkeypatch):
    import corrosion_tpu.utils.backoff as mod

    now = [100.0]
    monkeypatch.setattr(mod.time, "monotonic", lambda: now[0])
    b = Backoff(0.01, 0.1, rng=random.Random(1), give_up_s=2.0)
    assert not b.gave_up
    assert b.remaining_s() == 2.0
    now[0] += 1.5
    assert b.remaining_s() == pytest.approx(0.5)
    assert b.clamp(3600.0) == pytest.approx(0.5)
    now[0] += 1.0
    assert b.remaining_s() == 0.0  # never negative
    assert b.clamp(3600.0) == 0.0
    assert b.gave_up
    with pytest.raises(StopIteration):
        next(b)


def test_reset_refreshes_wall_budget(monkeypatch):
    import corrosion_tpu.utils.backoff as mod

    now = [0.0]
    monkeypatch.setattr(mod.time, "monotonic", lambda: now[0])
    b = Backoff(0.01, 0.1, rng=random.Random(1), give_up_s=1.0)
    now[0] += 2.0
    assert b.gave_up
    b.reset()  # a success restores the wall budget: it bounds CONSECUTIVE failures
    assert not b.gave_up
    assert b.remaining_s() == 1.0
