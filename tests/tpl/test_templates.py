"""Template engine: sql()/sql_json()/hostname() rendering + watch re-render.

Spec: corro-tpl (crates/corro-tpl/src/lib.rs:444+) — templates query cluster
state and re-render when any watched query's results change.
"""

import asyncio
import socket

from corrosion_tpu.api.client import ApiClient
from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.testing import Cluster
from corrosion_tpu.tpl import TemplateEngine, render_to_file, watch_and_render


async def _with_api(fn):
    cluster = Cluster(1)
    await cluster.start()
    srv = ApiServer(cluster.agents[0])
    await srv.start()
    client = ApiClient(srv.addr)
    try:
        await fn(cluster, client)
    finally:
        await srv.stop()
        await cluster.stop()


def test_render_sql_rows_and_json():
    async def body(cluster, client):
        await client.execute(
            [
                ["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "alpha"]],
                ["INSERT INTO tests (id, text) VALUES (?, ?)", [2, "beta"]],
            ]
        )
        engine = TemplateEngine(client)
        out = await engine.render(
            "{% for row in sql(\"SELECT id, text FROM tests ORDER BY id\") %}"
            "{{ row.id }}={{ row.text }};{% endfor %}"
        )
        assert out == "1=alpha;2=beta;"
        assert engine.queries_used == ["SELECT id, text FROM tests ORDER BY id"]

        out = await engine.render(
            '{{ sql_json("SELECT id FROM tests WHERE id = 1") }}'
        )
        assert out == '[{"id": 1}]'

        out = await engine.render("{{ hostname() }}")
        assert out == socket.gethostname()

        # to_csv / pretty-json parity (corro-tpl lib.rs:487-489,
        # template.example.csv.rhai)
        out = await engine.render(
            '{{ sql_csv("SELECT id, text FROM tests ORDER BY id") }}'
        )
        assert out == "id,text\n1,alpha\n2,beta\n"
        out = await engine.render(
            '{{ sql_json("SELECT id FROM tests WHERE id = 1", pretty=True) }}'
        )
        assert out == '[\n  {\n    "id": 1\n  }\n]'
        # zero-row CSV keeps its header line (consumers parse headered CSV)
        out = await engine.render(
            '{{ sql_csv("SELECT id, text FROM tests WHERE 1=0") }}'
        )
        assert out == "id,text\n"

    asyncio.run(_with_api(body))


def test_render_to_file_and_row_access_styles(tmp_path):
    async def body(cluster, client):
        await client.execute(
            [["INSERT INTO tests (id, text) VALUES (?, ?)", [5, "x"]]]
        )
        tpl = tmp_path / "cfg.tpl"
        tpl.write_text(
            "{% for r in sql(\"SELECT id, text FROM tests\") %}"
            "{{ r[0] }} {{ r['text'] }} {{ r.text }}{% endfor %}"
        )
        out = tmp_path / "cfg"
        queries = await render_to_file(client, str(tpl), str(out))
        assert out.read_text() == "5 x x"
        assert queries == ["SELECT id, text FROM tests"]

    asyncio.run(_with_api(body))


def test_watch_rerenders_on_change(tmp_path):
    async def body(cluster, client):
        await client.execute(
            [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "v1"]]]
        )
        tpl = tmp_path / "cfg.tpl"
        tpl.write_text(
            "{% for r in sql(\"SELECT text FROM tests ORDER BY id\") %}"
            "{{ r.text }};{% endfor %}"
        )
        out = tmp_path / "cfg"

        renders = []

        async def mutate_after_first_render():
            while not renders:
                await asyncio.sleep(0.01)
            await client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [2, "v2"]]]
            )

        mut = asyncio.create_task(mutate_after_first_render())
        n = await asyncio.wait_for(
            watch_and_render(
                client, str(tpl), str(out),
                on_render=lambda i: renders.append(i),
                max_renders=2,
            ),
            timeout=10,
        )
        await mut
        assert n == 2
        assert out.read_text() == "v1;v2;"

    asyncio.run(_with_api(body))


def test_static_template_watch_returns(tmp_path):
    async def body(cluster, client):
        tpl = tmp_path / "static.tpl"
        tpl.write_text("nothing dynamic")
        out = tmp_path / "static"
        n = await asyncio.wait_for(
            watch_and_render(client, str(tpl), str(out)), timeout=5
        )
        assert n == 1
        assert out.read_text() == "nothing dynamic"

    asyncio.run(_with_api(body))
