"""Gap-algebra spec test, ported from reference
`corro-types/src/agent.rs:1605-1922` (`test_booked_insert_db`).

The reference persists gaps to the `__corro_bookkeeping_gaps` SQLite table
(PK (actor_id, start), so scans come back start-ordered); our sink here is a
dict keyed the same way, checked after every insert exactly like the
reference's `expect_gaps`."""

from corrosion_tpu.core.bookkeeping import BookedVersions, PartialVersion
from corrosion_tpu.core.intervals import RangeSet
from corrosion_tpu.core.types import ActorId


class DictSink:
    """Stand-in for the gaps table; enforces the reference's invariants:
    deletions must hit exactly one stored row, insertions must not collide."""

    def __init__(self):
        self.rows = {}  # (actor, start) -> end

    def delete_gap(self, actor_id, lo, hi):
        assert self.rows.pop((actor_id, lo), None) == hi, (
            f"ineffective deletion of gap {lo}..={hi}"
        )

    def insert_gap(self, actor_id, lo, hi):
        assert (actor_id, lo) not in self.rows, f"already had gaps entry at {lo}"
        self.rows[(actor_id, lo)] = hi

    def sorted_gaps(self):
        return [(lo, hi) for (_, lo), hi in sorted(self.rows.items())]


def insert_everywhere(sink, bv, all_versions, versions):
    for r in versions:
        all_versions.insert(*r)
    snap = bv.snapshot()
    snap.insert_db(sink, RangeSet(versions))
    bv.commit_snapshot(snap)


def expect_gaps(sink, bv, all_versions, expected):
    assert sink.sorted_gaps() == expected
    for r in all_versions:
        assert bv.contains_all(r, None)
    for lo, hi in expected:
        for v in range(lo, hi + 1):
            assert not bv.contains(v, None), f"expected not to contain {v}"
            assert bv.needed().contains(v), f"expected needed to contain {v}"
    assert bv.last() == all_versions.last(), "expected last version not to increment"


def test_booked_insert_db():
    actor_id = ActorId()

    sink = DictSink()
    bv = BookedVersions(actor_id)
    all_v = RangeSet()

    insert_everywhere(sink, bv, all_v, [(1, 20)])
    expect_gaps(sink, bv, all_v, [])

    insert_everywhere(sink, bv, all_v, [(1, 10)])
    expect_gaps(sink, bv, all_v, [])

    # from an empty state again
    sink = DictSink()
    bv = BookedVersions(actor_id)
    all_v = RangeSet()

    # create 2..=3 gap
    insert_everywhere(sink, bv, all_v, [(1, 1), (4, 4)])
    expect_gaps(sink, bv, all_v, [(2, 3)])

    # fill gap
    insert_everywhere(sink, bv, all_v, [(3, 3), (2, 2)])
    expect_gaps(sink, bv, all_v, [])

    # from an empty state again
    sink = DictSink()
    bv = BookedVersions(actor_id)
    all_v = RangeSet()

    # insert a non-1 first version
    insert_everywhere(sink, bv, all_v, [(5, 20)])
    expect_gaps(sink, bv, all_v, [(1, 4)])

    # further change not overlapping a gap
    insert_everywhere(sink, bv, all_v, [(6, 7)])
    expect_gaps(sink, bv, all_v, [(1, 4)])

    # further change overlapping a gap
    insert_everywhere(sink, bv, all_v, [(3, 7)])
    expect_gaps(sink, bv, all_v, [(1, 2)])

    insert_everywhere(sink, bv, all_v, [(1, 2)])
    expect_gaps(sink, bv, all_v, [])

    insert_everywhere(sink, bv, all_v, [(25, 25)])
    expect_gaps(sink, bv, all_v, [(21, 24)])

    insert_everywhere(sink, bv, all_v, [(30, 35)])
    expect_gaps(sink, bv, all_v, [(21, 24), (26, 29)])

    # overlapping partially from the end
    insert_everywhere(sink, bv, all_v, [(19, 22)])
    expect_gaps(sink, bv, all_v, [(23, 24), (26, 29)])

    # overlapping partially from the start
    insert_everywhere(sink, bv, all_v, [(24, 25)])
    expect_gaps(sink, bv, all_v, [(23, 23), (26, 29)])

    # overlapping 2 ranges
    insert_everywhere(sink, bv, all_v, [(23, 27)])
    expect_gaps(sink, bv, all_v, [(28, 29)])

    # ineffective insert of already known ranges
    insert_everywhere(sink, bv, all_v, [(1, 20)])
    expect_gaps(sink, bv, all_v, [(28, 29)])

    # overlapping no ranges, but encompassing a full range
    insert_everywhere(sink, bv, all_v, [(27, 30)])
    expect_gaps(sink, bv, all_v, [])

    # touching multiple ranges, partially
    insert_everywhere(sink, bv, all_v, [(40, 45)])  # creates gap 36..=39
    insert_everywhere(sink, bv, all_v, [(50, 55)])  # creates gap 46..=49
    insert_everywhere(sink, bv, all_v, [(38, 47)])
    expect_gaps(sink, bv, all_v, [(36, 37), (48, 49)])

    # rebuild from the persisted sink state ("from_conn" equivalence)
    bv2 = BookedVersions(actor_id)
    snap = bv2.snapshot()
    snap.insert_gaps(sink.sorted_gaps())
    snap.max = 55
    bv2.commit_snapshot(snap)
    assert bv2.needed() == bv.needed()
    assert bv2.last() == bv.last()


def test_partials():
    actor = ActorId()
    bv = BookedVersions(actor)
    p = bv.insert_partial(5, PartialVersion(seqs=RangeSet([(0, 10)]), last_seq=100))
    assert not p.is_complete()
    assert bv.last() == 5
    assert bv.get_partial(5) is not None
    # merging more seqs
    p = bv.insert_partial(5, PartialVersion(seqs=RangeSet([(11, 100)]), last_seq=100))
    assert p.is_complete()
    assert p.gap_list() == []
    # contains() with seq ranges consults the partial
    snap = bv.snapshot()
    snap.insert_db(__import__("corrosion_tpu.core.bookkeeping", fromlist=["NULL_SINK"]).NULL_SINK, RangeSet([(5, 5)]))
    bv.commit_snapshot(snap)
    assert bv.contains(5, (0, 100))
