"""Chunker spec test, ported from reference
`corro-types/src/change.rs:266-401` (`test_change_chunker`)."""

from corrosion_tpu.core.changes import ChunkedChanges
from corrosion_tpu.core.types import ActorId, Change


def mk(seq):
    return Change(
        table="", pk=b"", cid="", val=None,
        col_version=0, db_version=0, seq=seq, site_id=ActorId(), cl=0,
    )


def test_change_chunker():
    # empty iterator
    chunks = list(ChunkedChanges([], 0, 100, 50))
    assert chunks == [([], (0, 100))]

    changes = [mk(seq) for seq in range(100)]
    sz = changes[0].estimated_byte_size()

    # 2 iterations
    chunks = list(
        ChunkedChanges([changes[0], changes[1], changes[2]], 0, 100, 2 * sz)
    )
    assert chunks == [
        ([changes[0], changes[1]], (0, 1)),
        ([changes[2]], (2, 100)),
    ]

    # last_seq reached: stop early even with more rows buffered
    chunks = list(ChunkedChanges([changes[0], changes[1]], 0, 0, sz))
    assert chunks == [([changes[0]], (0, 0))]

    # gaps absorbed into a single chunk
    chunks = list(ChunkedChanges([changes[0], changes[2]], 0, 100, 2 * sz))
    assert chunks == [([changes[0], changes[2]], (0, 100))]

    # gaps, everything fits
    chunks = list(
        ChunkedChanges(
            [changes[2], changes[4], changes[7], changes[8]], 0, 100, 100000
        )
    )
    assert chunks == [([changes[2], changes[4], changes[7], changes[8]], (0, 100))]

    # gaps, split in two
    chunks = list(
        ChunkedChanges([changes[2], changes[4], changes[7], changes[8]], 0, 10, 2 * sz)
    )
    assert chunks == [
        ([changes[2], changes[4]], (0, 4)),
        ([changes[7], changes[8]], (5, 10)),
    ]
