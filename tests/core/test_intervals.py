"""RangeSet algebra tests — semantics must match rangemap::RangeInclusiveSet
as used by the reference (coalescing adjacency, splitting removes, gaps)."""

from corrosion_tpu.core.intervals import RangeSet


def test_insert_coalesces_overlapping_and_adjacent():
    rs = RangeSet()
    rs.insert(1, 3)
    rs.insert(5, 7)
    assert list(rs) == [(1, 3), (5, 7)]
    rs.insert(4, 4)  # adjacent on both sides -> one range
    assert list(rs) == [(1, 7)]
    rs.insert(7, 10)  # overlapping
    assert list(rs) == [(1, 10)]
    rs.insert(12, 12)
    assert list(rs) == [(1, 10), (12, 12)]
    rs.insert(11, 11)
    assert list(rs) == [(1, 12)]


def test_remove_splits():
    rs = RangeSet([(1, 10)])
    rs.remove(4, 6)
    assert list(rs) == [(1, 3), (7, 10)]
    rs.remove(1, 1)
    assert list(rs) == [(2, 3), (7, 10)]
    rs.remove(8, 20)
    assert list(rs) == [(2, 3), (7, 7)]
    rs.remove(0, 100)
    assert list(rs) == []


def test_remove_noop_outside():
    rs = RangeSet([(5, 8)])
    rs.remove(1, 4)
    rs.remove(9, 12)
    assert list(rs) == [(5, 8)]


def test_get_contains():
    rs = RangeSet([(2, 4), (8, 9)])
    assert rs.get(3) == (2, 4)
    assert rs.get(8) == (8, 9)
    assert rs.get(5) is None
    assert rs.contains(2) and rs.contains(9)
    assert not rs.contains(1) and not rs.contains(7)


def test_overlapping():
    rs = RangeSet([(1, 3), (5, 7), (10, 12)])
    assert list(rs.overlapping(3, 10)) == [(1, 3), (5, 7), (10, 12)]
    assert list(rs.overlapping(4, 4)) == []
    assert list(rs.overlapping(8, 9)) == []
    assert list(rs.overlapping(6, 6)) == [(5, 7)]


def test_gaps():
    rs = RangeSet([(3, 5), (8, 9)])
    assert list(rs.gaps(1, 12)) == [(1, 2), (6, 7), (10, 12)]
    assert list(rs.gaps(3, 9)) == [(6, 7)]
    assert list(rs.gaps(4, 8)) == [(6, 7)]
    assert list(RangeSet().gaps(1, 5)) == [(1, 5)]
    full = RangeSet([(0, 100)])
    assert list(full.gaps(0, 100)) == []


def test_covers_and_span():
    rs = RangeSet([(1, 5), (7, 8)])
    assert rs.covers(2, 5)
    assert not rs.covers(4, 7)
    assert rs.span_count() == 7
    assert rs.first() == 1 and rs.last() == 8


def test_copy_independent():
    rs = RangeSet([(1, 5)])
    c = rs.copy()
    c.remove(1, 5)
    assert list(rs) == [(1, 5)]
    assert list(c) == []
