"""HLC tests: monotonicity, remote merge, drift guard
(reference setup.rs:101-106: ±300 ms max delta)."""

import pytest

from corrosion_tpu.core.hlc import (
    HLC,
    ClockDriftError,
    ntp64_from_unix_ns,
    ntp64_to_unix_ns,
)


def test_ntp64_roundtrip():
    for ns in [0, 1, 1_000_000_000, 1_721_000_000_123_456_789]:
        assert abs(ntp64_to_unix_ns(ntp64_from_unix_ns(ns)) - ns) < 2


def test_monotonic_even_with_frozen_wall_clock():
    t = [1_000_000_000_000]
    clock = HLC(_now_ns=lambda: t[0])
    stamps = [clock.now() for _ in range(100)]
    assert stamps == sorted(set(stamps)), "timestamps must be strictly increasing"


def test_update_advances_past_remote():
    t = [1_000_000_000_000]
    clock = HLC(_now_ns=lambda: t[0])
    local = clock.now()
    remote = local + 1000  # slightly ahead, within drift
    clock.update(remote)
    assert clock.now() > remote


def test_update_rejects_large_drift():
    t = [1_000_000_000_000]
    clock = HLC(_now_ns=lambda: t[0])
    too_far = ntp64_from_unix_ns(t[0] + 10_000_000_000)  # 10 s ahead
    with pytest.raises(ClockDriftError):
        clock.update(too_far)
