"""CRDT merge-rule tests against the documented cr-sqlite semantics
(reference doc/crdts.md:171-248: col_version, then value, then site_id;
the worked 'started' vs 'destroyed' example is reproduced verbatim)."""

from corrosion_tpu.core.crdt import (
    MergeOutcome,
    merge_cell,
    merge_row_cl,
    row_alive,
    value_cmp,
)
from corrosion_tpu.core.types import ActorId

SITE_A = ActorId(bytes.fromhex("D5F143E7BA65421C938C850CE78FC9F2"))
SITE_B = ActorId(bytes.fromhex("75D983BA38A644E987735592FB89CA70"))


def test_value_ordering_sqlite_semantics():
    # NULL < numeric < text < blob
    assert value_cmp(None, -(10**9)) < 0
    assert value_cmp(5, "a") < 0
    assert value_cmp("z", b"\x00") < 0
    # numeric compares across int/real
    assert value_cmp(1, 1.5) < 0
    assert value_cmp(2.0, 2) == 0
    # text is binary-collated utf-8
    assert value_cmp("destroyed", "started") < 0
    assert value_cmp("a", "ab") < 0
    # blobs memcmp
    assert value_cmp(b"\x01", b"\x01\x00") < 0
    assert value_cmp(None, None) == 0


def test_doc_example_started_beats_destroyed():
    # node1 wrote status='started' (col_version 2), node2 'destroyed' (col_version 2).
    # 'started' > 'destroyed' lexicographically => started wins on both nodes.
    on_node2 = merge_cell((2, "destroyed", SITE_B), (2, "started", SITE_A))
    assert on_node2 == MergeOutcome.WIN
    on_node1 = merge_cell((2, "started", SITE_A), (2, "destroyed", SITE_B))
    assert on_node1 == MergeOutcome.LOSE


def test_col_version_dominates():
    assert merge_cell((1, "zzz", SITE_B), (2, "aaa", SITE_A)) == MergeOutcome.WIN
    assert merge_cell((3, None, SITE_A), (2, b"big", SITE_B)) == MergeOutcome.LOSE


def test_site_id_breaks_full_tie():
    # SITE_A (0xD5...) > SITE_B (0x75...): bigger incoming site id wins
    assert merge_cell((1, "x", SITE_B), (1, "x", SITE_A)) == MergeOutcome.WIN
    # smaller incoming site id: metadata-only merge (merge-equal-values)
    assert merge_cell((1, "x", SITE_A), (1, "x", SITE_B)) == MergeOutcome.EQUAL_METADATA
    # without merge-equal-values the loser is simply dropped
    assert (
        merge_cell((1, "x", SITE_A), (1, "x", SITE_B), merge_equal_values=False)
        == MergeOutcome.LOSE
    )


def test_empty_cell_always_loses_to_incoming():
    assert merge_cell(None, (1, "v", SITE_A)) == MergeOutcome.WIN


def test_causal_length():
    assert row_alive(1) and not row_alive(2) and row_alive(3)
    assert merge_row_cl(1, 2) == 2  # delete wins over insert
    assert merge_row_cl(3, 2) == 3  # resurrect wins over delete
    assert merge_row_cl(2, 2) == 2


def test_merge_is_commutative_and_idempotent():
    import itertools
    import random

    rng = random.Random(7)
    sites = [SITE_A, SITE_B, ActorId.random()]
    values = [None, 0, 1, -3, 2.5, "a", "b", b"a", b"b"]
    cells = [
        (cv, v, s)
        for cv, v, s in itertools.product([1, 2], values, sites)
    ]
    for _ in range(300):
        a, b = rng.choice(cells), rng.choice(cells)

        def winner(x, y):
            return y if merge_cell(x, y) == MergeOutcome.WIN else x

        # order of arrival must not affect the surviving value
        ab = winner(a, b)
        ba = winner(b, a)
        assert ab == ba or (
            # EQUAL_METADATA means identical (cv, value); site metadata converges
            ab[:2] == ba[:2]
        )
        # idempotent
        assert winner(a, a)[:2] == a[:2]
