"""Need-computation spec test, ported from reference
`corro-types/src/sync.rs:386-500` (`test_compute_available_needs`).
Every assertion mirrors the original exactly."""

from corrosion_tpu.core.sync import compute_available_needs
from corrosion_tpu.core.types import ActorId, SyncNeed, SyncState


def test_compute_available_needs():
    actor1 = ActorId.random()

    ours = SyncState()
    ours.heads[actor1] = 10

    other = SyncState()
    other.heads[actor1] = 13

    assert compute_available_needs(ours, other) == {
        actor1: [SyncNeed.full(11, 13)]
    }

    ours.need.setdefault(actor1, []).append((2, 5))
    ours.need.setdefault(actor1, []).append((7, 7))

    assert compute_available_needs(ours, other) == {
        actor1: [
            SyncNeed.full(2, 5),
            SyncNeed.full(7, 7),
            SyncNeed.full(11, 13),
        ]
    }

    ours.partial_need[actor1] = {9: [(100, 120), (130, 132)]}

    assert compute_available_needs(ours, other) == {
        actor1: [
            SyncNeed.full(2, 5),
            SyncNeed.full(7, 7),
            SyncNeed.partial(9, [(100, 120), (130, 132)]),
            SyncNeed.full(11, 13),
        ]
    }

    other.partial_need[actor1] = {9: [(100, 110), (130, 130)]}

    assert compute_available_needs(ours, other) == {
        actor1: [
            SyncNeed.full(2, 5),
            SyncNeed.full(7, 7),
            SyncNeed.partial(9, [(111, 120), (131, 132)]),
            SyncNeed.full(11, 13),
        ]
    }


def test_own_actor_and_zero_head_skipped():
    me = ActorId.random()
    peer = ActorId.random()
    ours = SyncState(actor_id=me)
    other = SyncState(actor_id=peer)
    other.heads[me] = 50  # their view of us: never request our own origin
    other.heads[peer] = 0  # zero head: ignored
    assert compute_available_needs(ours, other) == {}
