"""Test-wide environment: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding paths are exercised without TPU hardware (the driver
separately dry-runs `__graft_entry__.dryrun_multichip`; bench.py keeps the
real chip).

Must run before jax is used anywhere.  NOTE: this image's profile pins
JAX_PLATFORMS=axon and the plugin wins over the env var, so the platform is
forced via jax.config, which does take precedence.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: tier-1 wall time on the 1-CPU CI box is
# dominated by recompiling the same packed/sharded kernels every run (the
# suite re-jits identical HLO for the virtual 8-device mesh each session).
# Executables are keyed by HLO hash, so cache hits are bit-identical to
# fresh compiles — determinism/byte-equality tests are unaffected.  The
# threshold is 0 because these kernels are many small compiles rather than
# a few big ones (the default 1 s floor would cache almost nothing).  The
# directory is gitignored scratch; deleting it only costs one cold run.
_cache_dir = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".cache", "xla"
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# invariant violations in the suite are bugs, not warnings: strict mode
# raises (the reference fails these under its deterministic simulator)
from corrosion_tpu.invariants import CATALOG  # noqa: E402

CATALOG.strict = True

# a wedged test (deadlocked event loop, stuck TLS handshake) should dump
# every thread's traceback instead of stalling CI silently: re-armed per
# test by the autouse fixture below; 300 s is far above the slowest test
import faulthandler  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _hang_watchdog():
    faulthandler.dump_traceback_later(300, exit=True)
    yield
    faulthandler.cancel_dump_traceback_later()
