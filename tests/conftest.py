"""Test-wide environment: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding paths are exercised without TPU hardware (the driver
separately dry-runs `__graft_entry__.dryrun_multichip`; bench.py keeps the
real chip).

Must run before jax is used anywhere.  NOTE: this image's profile pins
JAX_PLATFORMS=axon and the plugin wins over the env var, so the platform is
forced via jax.config, which does take precedence.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# invariant violations in the suite are bugs, not warnings: strict mode
# raises (the reference fails these under its deterministic simulator)
from corrosion_tpu.invariants import CATALOG  # noqa: E402

CATALOG.strict = True

# a wedged test (deadlocked event loop, stuck TLS handshake) should dump
# every thread's traceback instead of stalling CI silently: re-armed per
# test by the autouse fixture below; 300 s is far above the slowest test
import faulthandler  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _hang_watchdog():
    faulthandler.dump_traceback_later(300, exit=True)
    yield
    faulthandler.cancel_dump_traceback_later()
