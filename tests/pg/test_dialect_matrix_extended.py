"""Extended PG-dialect matrix (VERDICT r2 item 6): table-driven cases
through the real wire protocol, including failures asserted by SQLSTATE.

Together with test_pg_dialect_matrix.py and test_psql_describe.py this
brings the matrix to ~100 distinct dialect cases — the observable
surface of the reference's AST translation (corro-pg/src/lib.rs:546-1906).

Case forms:
    ("ok", sql)                      — must succeed
    ("rows", sql, [row, ...])        — succeed with exactly these rows
    ("row0", sql, value)             — succeed, first column of first row
    ("tag", sql, tag)                — succeed with this command tag
    ("err", sql, sqlstate)           — fail with this SQLSTATE
"""

import asyncio
import sqlite3

import pytest

from corrosion_tpu.pg import PgServer
from corrosion_tpu.pg.client import PgClient, PgClientError
from corrosion_tpu.testing import TEST_SCHEMA, Cluster

SETUP = [
    "CREATE TABLE kv (k TEXT PRIMARY KEY NOT NULL, v TEXT, n INTEGER DEFAULT 0)",
    "CREATE TABLE nums (id INTEGER PRIMARY KEY NOT NULL, x REAL)",
]

CASES = [
    # -- literals, casts, expressions (reads) ---------------------------
    ("row0", "SELECT 1", "1"),
    ("row0", "SELECT 1 + 2 * 3", "7"),
    ("row0", "SELECT '5'::int + 1", "6"),
    ("row0", "SELECT 1::text", "1"),
    ("row0", "SELECT 1::bigint::text", "1"),
    ("row0", "SELECT '3.5'::double precision * 2", "7.0"),
    ("row0", "SELECT '7'::numeric", "7.0"),
    ("row0", "SELECT TRUE", "1"),
    ("row0", "SELECT FALSE", "0"),
    ("row0", "SELECT NOT TRUE", "0"),
    ("row0", "SELECT CAST('9' AS int4)", "9"),
    ("row0", "SELECT CAST(3.7 AS integer)", "3"),
    ("row0", "SELECT CAST('ab' AS varchar(10))", "ab"),
    ("row0", "SELECT 'it''s'", "it's"),
    ("row0", "SELECT E'a\\nb'", "a\nb"),  # E-string escapes decode
    ("row0", "SELECT $$dollar quoted$$", "dollar quoted"),
    ("row0", "SELECT $tag$nested $$ inside$tag$", "nested $$ inside"),
    ("row0", "SELECT 'x' || 'y'", "xy"),
    ("row0", "SELECT length('abc')", "3"),
    ("row0", "SELECT coalesce(NULL, 'd')", "d"),
    ("row0", "SELECT nullif(1, 1)", None),
    ("row0", "SELECT CASE WHEN 1 > 0 THEN 'yes' ELSE 'no' END", "yes"),
    ("row0", "SELECT CASE 2 WHEN 1 THEN 'a' WHEN 2 THEN 'b' END", "b"),
    ("row0", "SELECT 1 WHERE 1 IS NOT NULL", "1"),
    ("row0", "SELECT 2 WHERE 1 IS DISTINCT FROM 2", "2"),
    ("row0", "SELECT 'a' WHERE 'abc' LIKE 'a%'", "a"),
    ("row0", "SELECT 'a' WHERE 'ABC' ILIKE 'a%'", "a"),
    ("row0", "SELECT 3 WHERE 2 BETWEEN 1 AND 3", "3"),
    ("row0", "SELECT 4 WHERE 2 IN (1, 2, 3)", "4"),
    ("row0", "SELECT '{\"a\": 1}'::jsonb ->> 'a'", "1"),
    ("row0", "SELECT json_extract('{\"a\": 2}', '$.a')", "2"),
    ("ok", "SELECT now()"),
    ("ok", "SELECT current_timestamp"),
    ("row0", "SELECT pg_catalog.version()",
     "PostgreSQL 14.0 (corrosion-tpu)"),
    ("row0", "SELECT current_database()", "corrosion"),
    ("row0", "SELECT to_regclass('kv') IS NOT NULL", "1"),
    ("row0", "SELECT to_regclass('pg_catalog.pg_class') IS NOT NULL", "1"),
    # -- comments and whitespace ---------------------------------------
    ("row0", "SELECT /* block /* nested */ comment */ 11", "11"),
    ("row0", "SELECT 12 -- trailing", "12"),
    # -- select shapes --------------------------------------------------
    ("rows", "VALUES (1, 'a'), (2, 'b')", [("1", "a"), ("2", "b")]),
    ("rows", "TABLE nums", []),
    ("rows", "SELECT * FROM (VALUES (1), (2)) AS t(c) ORDER BY c DESC",
     [("2",), ("1",)]),
    ("rows", "SELECT 1 UNION SELECT 2 ORDER BY 1", [("1",), ("2",)]),
    ("rows", "SELECT 1 INTERSECT SELECT 1", [("1",)]),
    ("rows", "SELECT 1 EXCEPT SELECT 1", []),
    ("rows", "SELECT DISTINCT 5 FROM (VALUES (1), (2)) v", [("5",)]),
    ("row0", "SELECT count(*) FROM (VALUES (1), (2), (3)) v", "3"),
    ("row0",
     "SELECT sum(c) FROM (VALUES (1), (2)) AS v(c) GROUP BY 1 > 0 "
     "HAVING sum(c) > 2", "3"),
    ("row0", "SELECT EXISTS (SELECT 1)", "1"),
    ("row0", "SELECT (SELECT 42)", "42"),
    ("row0", "SELECT c FROM (VALUES (1), (2), (3)) AS v(c) "
             "ORDER BY c LIMIT 1 OFFSET 1", "2"),
    ("row0", "WITH t AS (SELECT 7 AS c) SELECT c FROM t", "7"),
    ("row0",
     "WITH RECURSIVE cnt(x) AS (SELECT 1 UNION ALL SELECT x + 1 FROM cnt "
     "WHERE x < 5) SELECT max(x) FROM cnt", "5"),
    ("row0", "WITH a AS (SELECT 1 AS x), b AS (SELECT x + 1 AS y FROM a) "
             "SELECT y FROM b", "2"),
    # -- writes ---------------------------------------------------------
    ("tag", "INSERT INTO kv (k, v) VALUES ('a', '1')", "INSERT 0 1"),
    ("tag", "INSERT INTO kv (k, v) VALUES ('b', '2'), ('c', '3')",
     "INSERT 0 2"),
    ("tag", "INSERT INTO kv VALUES ('d', '4', 0)", "INSERT 0 1"),
    ("tag", "UPDATE kv SET v = '9' WHERE k = 'a'", "UPDATE 1"),
    ("tag", "DELETE FROM kv WHERE k = 'd'", "DELETE 1"),
    ("row0", "INSERT INTO kv (k, v) VALUES ('e', '5') RETURNING k", "e"),
    ("tag",
     "INSERT INTO kv (k, v) VALUES ('a', 'up') "
     "ON CONFLICT (k) DO UPDATE SET v = excluded.v", "INSERT 0 1"),
    ("row0", "SELECT v FROM kv WHERE k = 'a'", "up"),
    ("tag",
     "INSERT INTO kv (k, v) VALUES ('a', 'ignored') "
     "ON CONFLICT (k) DO NOTHING", "INSERT 0 0"),
    ("tag",
     "INSERT INTO kv (k, v) VALUES ('a', 'con') "
     "ON CONFLICT ON CONSTRAINT kv_pkey DO UPDATE SET v = excluded.v",
     "INSERT 0 1"),
    ("tag", "INSERT INTO nums SELECT 1, 0.5", "INSERT 0 1"),
    ("tag", "UPDATE kv SET n = n + 1 WHERE k IN (SELECT k FROM kv)",
     "UPDATE 4"),
    ("row0",
     "WITH doomed AS (SELECT 'e' AS k) "
     "DELETE FROM kv WHERE k IN (SELECT k FROM doomed) RETURNING k", "e"),
    ("tag", "UPDATE kv SET v = upper(v) WHERE FALSE", "UPDATE 0"),
    # -- DDL with PG types ---------------------------------------------
    ("ok", "CREATE TABLE typed (id bigserial PRIMARY KEY NOT NULL, "
           "name varchar(32) NOT NULL DEFAULT '', flag boolean, "
           "blob_c bytea, doc jsonb, uid uuid, amount numeric(10,2), "
           "ratio double precision, at timestamptz)"),
    ("ok", "CREATE INDEX typed_name_idx ON typed (name)"),
    # unique indexes are rejected for CRRs (schema.rs:164 semantics)
    ("err", "CREATE UNIQUE INDEX typed_uid_key ON typed (uid)", "0A000"),
    ("tag", "INSERT INTO typed (id, name, flag) VALUES (1, 'n', TRUE)",
     "INSERT 0 1"),
    ("row0", "SELECT flag FROM typed WHERE id = 1", "1"),
    # migration-file-first posture: destructive/alter DDL is rejected
    # over the bridge with guidance (0A000)
    ("err", "ALTER TABLE typed ADD COLUMN extra int4", "0A000"),
    ("err", "DROP INDEX typed_name_idx", "0A000"),
    ("err", "DROP TABLE typed", "0A000"),
    # -- session statements ---------------------------------------------
    ("tag", "SET application_name = 'matrix'", "SET"),
    ("row0", "SHOW application_name", "matrix"),
    ("tag", "SET SESSION statement_timeout TO 0", "SET"),
    ("row0", "SHOW server_version", "14.0 (corrosion-tpu)"),
    ("row0", "SHOW transaction_isolation", "serializable"),
    ("tag", "RESET application_name", "RESET"),
    ("tag", "DISCARD ALL", "DISCARD"),
    ("ok", "SELECT set_config('search_path', 'public', false)"),
    # -- transactions ----------------------------------------------------
    ("tag", "BEGIN", "BEGIN"),
    ("tag", "INSERT INTO kv (k, v) VALUES ('tx', 't')", "INSERT 0 1"),
    ("tag", "COMMIT", "COMMIT"),
    ("row0", "SELECT v FROM kv WHERE k = 'tx'", "t"),
    ("tag", "START TRANSACTION", "BEGIN"),
    ("tag", "DELETE FROM kv WHERE k = 'tx'", "DELETE 1"),
    ("tag", "ROLLBACK", "ROLLBACK"),
    ("row0", "SELECT count(*) FROM kv WHERE k = 'tx'", "1"),
    # -- introspection reads --------------------------------------------
    ("ok", "PRAGMA table_info(kv)"),
    ("row0",
     "SELECT count(*) FROM pg_catalog.pg_class WHERE relname = 'kv'", "1"),
    ("row0",
     "SELECT count(*) FROM pg_catalog.pg_attribute a, pg_catalog.pg_class c "
     "WHERE c.relname = 'kv' AND a.attrelid = c.oid AND a.attnum > 0", "3"),
    ("row0", "SELECT nspname FROM pg_namespace WHERE oid = 2200", "public"),
    # -- failures: SQLSTATE asserted ------------------------------------
    ("err", "SELEC 1", "42601"),
    ("err", "SELECT 'unterminated", "42601"),
    ("err", "SELECT $1blah$ FROM kv", "42601"),
    ("err", "WITH x AS (SELECT 1)", "42601"),
    ("err", "SELECT * FROM no_such_table", "42P01"),
    ("err", "SELECT no_such_col FROM kv", "42703"),
    ("err", "INSERT INTO kv (k) VALUES ('a') "
            "ON CONFLICT ON CONSTRAINT bogus DO NOTHING", "42704"),
    ("err", "INSERT INTO kv (k, v) VALUES ('a', 'dup')", "23505"),
    ("err", "INSERT INTO kv (k) VALUES (NULL)", "23502"),
    ("err", "PRAGMA journal_mode = DELETE", "0A000"),
    ("err", "PRAGMA synchronous", "0A000"),
    # PG: COMMIT outside a tx is a WARNING, not an error
    ("tag", "COMMIT", "COMMIT"),
]



# this container's sqlite (post-rebuild) may predate features these
# statements translate to: RETURNING needs >= 3.35, the -> / ->> JSON
# operators need >= 3.38.  The pg layer targets modern sqlite (CI runs
# >= 3.37); on an older runtime the tests gate rather than fail.
_needs_sqlite = lambda *v: pytest.mark.skipif(  # noqa: E731
    sqlite3.sqlite_version_info < v,
    reason=f"sqlite {sqlite3.sqlite_version} lacks the translated feature",
)

@_needs_sqlite(3, 38, 0)
def test_extended_dialect_matrix():
    async def body():
        cluster = Cluster(
            1, schema=TEST_SCHEMA + ";".join(SETUP) + ";", use_swim=False
        )
        await cluster.start()
        agent = cluster.agents[0]
        srv = PgServer(agent)
        await srv.start()
        c = PgClient("127.0.0.1", srv._port)
        await c.connect()
        failures = []
        try:
            for case in CASES:
                form, sql = case[0], case[1]
                try:
                    res = await c.query(sql)
                except PgClientError as e:
                    if form == "err":
                        if e.code != case[2]:
                            failures.append(
                                (sql, f"sqlstate {e.code} != {case[2]}")
                            )
                    else:
                        failures.append((sql, f"unexpected error {e}"))
                    continue
                if form == "err":
                    failures.append((sql, f"expected {case[2]}, succeeded"))
                elif form == "rows":
                    if res[0].rows != case[2]:
                        failures.append((sql, f"rows {res[0].rows}"))
                elif form == "row0":
                    got = res[0].rows[0][0] if res[0].rows else "<no rows>"
                    if got != case[2]:
                        failures.append((sql, f"row0 {got!r} != {case[2]!r}"))
                elif form == "tag":
                    if res[0].tag != case[2]:
                        failures.append((sql, f"tag {res[0].tag}"))
            assert not failures, "\n".join(f"{s!r}: {m}" for s, m in failures)
            print(f"extended matrix: {len(CASES)} cases green")
        finally:
            await c.close()
            await srv.stop()
            await cluster.stop()

    asyncio.run(body())


def test_constraint_columns_explicit_names():
    """constraint_columns resolves explicit CONSTRAINT names, PG default
    names, and unique indexes (the ON CONFLICT ON CONSTRAINT sources) —
    against raw SQLite, since the CRR layer (faithfully, schema.rs:164)
    rejects UNIQUE table constraints on replicated tables."""
    import sqlite3

    from corrosion_tpu.pg.catalog import constraint_columns

    conn = sqlite3.connect(":memory:")
    conn.executescript(
        """
        CREATE TABLE t (id INTEGER PRIMARY KEY, a INT, b INT,
                        CONSTRAINT t_ab_unique UNIQUE (a, b));
        CREATE UNIQUE INDEX t_b_idx ON t (b);
        """
    )
    assert constraint_columns(conn, "t", "t_ab_unique") == ["a", "b"]
    assert constraint_columns(conn, "t", "t_pkey") == ["id"]
    assert constraint_columns(conn, "t", "t_b_key") == ["b"]
    assert constraint_columns(conn, "t", "t_b_idx") == ["b"]
    assert constraint_columns(conn, "t", "nope") == []
    conn.close()
