"""SAVEPOINT / RELEASE / ROLLBACK TO + SQL-level PREPARE/EXECUTE/
DEALLOCATE + COMMENT ON (round-4 grammar depth; corro-pg parses these
through sqlparser, lib.rs:546-1906)."""

import asyncio

import pytest

from corrosion_tpu.pg import sql_state
from corrosion_tpu.pg.client import PgClientError

from .test_pg import _with_pg


def test_savepoint_nested_rollback():
    """psycopg's nested-transaction pattern: an error inside a savepoint
    rolls back to it and the OUTER tx keeps going and commits."""

    async def body(cluster, clients):
        c = clients[0]
        await c.query(
            "CREATE TABLE sp (id INTEGER PRIMARY KEY, v TEXT) WITHOUT ROWID"
        )
        await c.query("BEGIN")
        await c.query("INSERT INTO sp VALUES (1, 'outer')")
        await c.query("SAVEPOINT nest")
        await c.query("INSERT INTO sp VALUES (2, 'inner')")
        # dup pk -> tx enters failed state
        with pytest.raises(PgClientError) as ei:
            await c.query("INSERT INTO sp VALUES (1, 'dup')")
        assert ei.value.code == sql_state.UNIQUE_VIOLATION
        # ordinary statements are refused while aborted
        with pytest.raises(PgClientError) as ei2:
            await c.query("SELECT 1")
        assert ei2.value.code == sql_state.IN_FAILED_SQL_TRANSACTION
        # ROLLBACK TO recovers the tx (clears the failed state)
        await c.query("ROLLBACK TO SAVEPOINT nest")
        r = await c.query("SELECT count(*) FROM sp")
        assert r[0].rows[0][0] == "1"  # inner insert rolled back too
        await c.query("INSERT INTO sp VALUES (3, 'after')")
        await c.query("COMMIT")
        r = await c.query("SELECT id FROM sp ORDER BY id")
        assert [row[0] for row in r[0].rows] == ["1", "3"]

    asyncio.run(_with_pg(1, body))


def test_savepoint_release_and_partial_keep():
    async def body(cluster, clients):
        c = clients[0]
        await c.query(
            "CREATE TABLE sp2 (id INTEGER PRIMARY KEY, v TEXT) WITHOUT ROWID"
        )
        await c.query("BEGIN")
        await c.query("INSERT INTO sp2 VALUES (1, 'a')")
        await c.query("SAVEPOINT s1")
        await c.query("INSERT INTO sp2 VALUES (2, 'b')")
        await c.query("RELEASE SAVEPOINT s1")  # merges into outer tx
        # releasing again: gone
        with pytest.raises(PgClientError):
            await c.query("RELEASE SAVEPOINT s1")
        await c.query("ROLLBACK")  # failed tx -> whole tx rolls back
        r = await c.query("SELECT count(*) FROM sp2")
        assert r[0].rows[0][0] == "0"

    asyncio.run(_with_pg(1, body))


def test_savepoint_outside_tx_errors():
    async def body(cluster, clients):
        c = clients[0]
        with pytest.raises(PgClientError) as ei:
            await c.query("SAVEPOINT lonely")
        assert ei.value.code == sql_state.NO_ACTIVE_SQL_TRANSACTION

    asyncio.run(_with_pg(1, body))


def test_prepare_execute_deallocate():
    async def body(cluster, clients):
        c = clients[0]
        await c.query(
            "CREATE TABLE pe (id INTEGER PRIMARY KEY, v TEXT) WITHOUT ROWID"
        )
        await c.query("PREPARE ins (int, text) AS INSERT INTO pe VALUES ($1, $2)")
        await c.query("EXECUTE ins(1, 'one')")
        await c.query("EXECUTE ins(2, 'two')")
        r = await c.query("PREPARE q AS SELECT v FROM pe WHERE id = $1")
        r = await c.query("EXECUTE q(2)")
        assert r[0].rows == [("two",)]
        # duplicate name -> 42P05
        with pytest.raises(PgClientError) as ei:
            await c.query("PREPARE q AS SELECT 1")
        assert ei.value.code == sql_state.DUPLICATE_PREPARED_STATEMENT
        # wrong arity
        with pytest.raises(PgClientError):
            await c.query("EXECUTE q(1, 2)")
        await c.query("DEALLOCATE q")
        with pytest.raises(PgClientError) as ei2:
            await c.query("EXECUTE q(1)")
        assert ei2.value.code == sql_state.INVALID_SQL_STATEMENT_NAME
        # DEALLOCATE ALL clears the namespace
        await c.query("DEALLOCATE ALL")
        with pytest.raises(PgClientError):
            await c.query("EXECUTE ins(3, 'x')")

    asyncio.run(_with_pg(1, body))


def test_comment_on_noop():
    async def body(cluster, clients):
        c = clients[0]
        await c.query(
            "CREATE TABLE cm (id INTEGER PRIMARY KEY) WITHOUT ROWID"
        )
        r = await c.query("COMMENT ON TABLE cm IS 'service registry'")
        assert r[0].tag == "COMMENT"

    asyncio.run(_with_pg(1, body))


def test_execute_extended_protocol_describe():
    """Extended-protocol EXECUTE of a SQL-prepared SELECT must carry a
    RowDescription (review r4 finding: NoData + DataRow is a protocol
    violation), and expression arguments evaluate (E-strings, casts,
    negatives)."""

    async def body(cluster, clients):
        c = clients[0]
        await c.query(
            "CREATE TABLE px (id INTEGER PRIMARY KEY, v TEXT) WITHOUT ROWID"
        )
        await c.query("INSERT INTO px VALUES (-3, E'caf\\u00e9')")
        await c.query("PREPARE gx AS SELECT v FROM px WHERE id = $1")
        # extended protocol (Parse/Bind/Describe/Execute) of the EXECUTE
        r = await c.execute("EXECUTE gx(-3)")
        assert r.columns and r.columns[0][0] == "v"
        assert r.rows == [("café",)]
        # expression args: E-string + cast + arithmetic
        await c.query("PREPARE ins2 AS INSERT INTO px VALUES ($1, $2)")
        await c.query("EXECUTE ins2(1 + 1, E'a\\nb')")
        r2 = await c.query("SELECT v FROM px WHERE id = 2")
        assert r2[0].rows == [("a\nb",)]

    asyncio.run(_with_pg(1, body))
