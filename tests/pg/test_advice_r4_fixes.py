"""Regression tests for the round-4 advisor findings (ADVICE.md r4):
operator-precedence guards on the lookahead rewrites, correlated
generate_series rejection, exact div(), statement/transaction-stable
now(), and the pg_sleep cap."""

import sqlite3
import time

import pytest

from corrosion_tpu.pg import runtime
from corrosion_tpu.pg.translate import UnsupportedStatement, translate


@pytest.fixture()
def conn():
    c = sqlite3.connect(":memory:")
    runtime.register(c)
    c.execute("CREATE TABLE t (a INTEGER, j TEXT)")
    c.executemany("INSERT INTO t VALUES (?,?)", [(1, '{"k":1}'), (2, '{"k":2}')])
    yield c
    runtime.thaw_now(c)
    c.close()


def q(conn, sql, params=()):
    return conn.execute(translate(sql).sql, params).fetchall()


# -- parser precedence guards (ADVICE: parser.py:1642) -----------------------

def test_arith_glued_to_containment_is_rejected():
    # PG parses `x + a @> b` as `(x + a) @> b` (+ binds tighter); the
    # single-operand lookahead would regroup it — must refuse, not emit
    with pytest.raises(UnsupportedStatement, match="parenthesize"):
        translate("SELECT x + a @> b FROM t")
    with pytest.raises(UnsupportedStatement, match="parenthesize"):
        translate("SELECT a @> b + x FROM t")


def test_parenthesized_containment_still_translates(conn):
    assert q(conn, "SELECT j @> '{\"k\":1}' FROM t ORDER BY a") == [(1,), (0,)]
    # the guard only fires on glued arithmetic; parens disambiguate
    t = translate("SELECT (a + a) @> b FROM t")
    assert "pg_jsonb_contains" in t.sql


def test_arith_glued_to_interval_chain_is_rejected():
    with pytest.raises(UnsupportedStatement, match="parenthesize"):
        translate("SELECT a - b - interval '1 hour' FROM t")
    # trailing * binds the interval first in PG → regroup → refuse
    with pytest.raises(UnsupportedStatement, match="parenthesize"):
        translate("SELECT ts + interval '1 hour' * 2 FROM t")


def test_interval_chain_plain_still_works(conn):
    assert q(conn, "SELECT '2026-07-15 12:00:00' - interval '1 hour'") == [
        ("2026-07-15 11:00:00",)
    ]
    # trailing +/- of a non-interval is left-assoc: grouping unchanged
    t = translate("SELECT interval '1 hour' + 5")
    assert t.sql


# -- correlated generate_series (ADVICE: parser.py:1811) ---------------------

def test_correlated_generate_series_rejected_cleanly():
    with pytest.raises(UnsupportedStatement, match="correlated generate_series"):
        translate("SELECT * FROM t, generate_series(1, t.a) AS g")


def test_literal_generate_series_still_works(conn):
    assert q(conn, "SELECT g FROM generate_series(1, 3) AS g") == [
        (1,), (2,), (3,)
    ]


# -- div() exactness (ADVICE: runtime.py:991) --------------------------------

def test_div_exact_beyond_double_precision(conn):
    big = 9007199254740993  # 2^53 + 1: float division loses the low bit
    assert q(conn, f"SELECT div({big}, 1)") == [(big,)]
    assert q(conn, f"SELECT div({big * 3 + 2}, 3)") == [(big * 3 // 3,)]


def test_div_truncates_toward_zero(conn):
    assert q(conn, "SELECT div(7, 2), div(-7, 2), div(7, -2), div(-7, -2)") == [
        (3, -3, -3, 3)
    ]
    # non-integer inputs fall back to float truncation (PG numeric trunc)
    assert q(conn, "SELECT div(7.5, 2)") == [(3,)]


def test_div_by_zero_raises(conn):
    with pytest.raises(sqlite3.OperationalError):
        q(conn, "SELECT div(1, 0)")


# -- now() stability (ADVICE: runtime.py:923) --------------------------------

def test_now_frozen_is_stable_across_rows_and_statements(conn):
    assert runtime.freeze_now(conn) is True
    # nested freeze does NOT re-freeze (transaction beats statement)
    assert runtime.freeze_now(conn) is False
    rows = q(conn, "SELECT now() FROM t")
    assert rows[0] == rows[1]
    time.sleep(0.002)
    assert q(conn, "SELECT now()")[0] == rows[0]
    frozen_val = rows[0][0]
    runtime.thaw_now(conn)
    time.sleep(0.002)
    (live,) = q(conn, "SELECT now()")[0]
    assert live != frozen_val  # thawed clock moves again


# -- pg_sleep cap (ADVICE: runtime.py:926) -----------------------------------

def test_pg_sleep_capped(conn):
    t0 = time.monotonic()
    q(conn, "SELECT pg_sleep(30)")
    assert time.monotonic() - t0 < 3.0


def test_statement_scope_overrides_foreign_freeze(conn):
    """Shared-writer-conn fallback: a statement from session B must see
    its OWN statement time while session A's transaction freeze stays
    intact underneath (code-review r5 finding)."""
    assert runtime.freeze_now(conn) is True
    (frozen,) = q(conn, "SELECT now()")[0]
    time.sleep(0.002)
    with runtime.statement_now(conn):
        (stmt,) = q(conn, "SELECT now()")[0]
        assert stmt != frozen
    # the foreign transaction's freeze is restored, not cleared
    assert q(conn, "SELECT now()")[0] == (frozen,)


def test_register_installs_fresh_cell(conn):
    """id(conn) values recycle: re-registering must never inherit a
    stale (possibly frozen) cell (code-review r5 finding)."""
    assert runtime.freeze_now(conn) is True
    runtime.register(conn)
    assert runtime.freeze_now(conn) is True  # fresh cell, not frozen


def test_release_now_prunes_cell(conn):
    runtime.freeze_now(conn)
    runtime.release_now(conn)
    # no cell → freeze is a no-op and now() is live again
    assert runtime.freeze_now(conn) is False
