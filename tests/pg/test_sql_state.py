"""SQLSTATE fidelity (VERDICT r3 item 5).

The reference carries the complete PG error-code space
(corro-pg/src/sql_state.rs:1-1336) because client libraries match on
codes — psycopg's ``errors.lookup(code)`` resolves a code to an
exception class via exactly this table.  psycopg itself isn't in the
test image, so the lookup contract is asserted directly: the table is
complete (class coverage, key conditions), and the server emits the
right codes — with the ErrorResponse `P` position field for syntax
errors — over a real wire connection.
"""

import asyncio

import pytest

from corrosion_tpu.pg import sql_state
from corrosion_tpu.pg.client import PgClientError

from .test_pg import _with_pg


def lookup(code: str) -> str:
    """psycopg's errors.lookup analog: code -> condition name."""
    name = sql_state.CODE_TO_NAME.get(code)
    if name is None:
        raise KeyError(code)
    return name


# -- the table itself -------------------------------------------------------


def test_table_is_complete():
    # the upstream errcodes list the reference generates from has 260+
    # conditions across 43 classes; the rebuild must carry all of them
    assert len(sql_state.ALL_CODES) >= 260
    classes = {c[:2] for c in sql_state.ALL_CODES.values()}
    assert len(classes) >= 40
    # every code is a 5-char SQLSTATE in the PG alphabet
    alphabet = set("0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ")
    for name, code in sql_state.ALL_CODES.items():
        assert len(code) == 5 and set(code) <= alphabet, (name, code)


@pytest.mark.parametrize(
    "name,code",
    [
        ("SUCCESSFUL_COMPLETION", "00000"),
        ("PROTOCOL_VIOLATION", "08P01"),
        ("FEATURE_NOT_SUPPORTED", "0A000"),
        ("INTEGRITY_CONSTRAINT_VIOLATION", "23000"),
        ("FOREIGN_KEY_VIOLATION", "23503"),
        ("UNIQUE_VIOLATION", "23505"),
        ("T_R_SERIALIZATION_FAILURE", "40001"),
        ("SYNTAX_ERROR", "42601"),
        ("UNDEFINED_TABLE", "42P01"),
        ("UNDEFINED_FUNCTION", "42883"),
        ("INSUFFICIENT_PRIVILEGE", "42501"),
        ("DIVISION_BY_ZERO", "22012"),
        ("NUMERIC_VALUE_OUT_OF_RANGE", "22003"),
        ("ADMIN_SHUTDOWN", "57P01"),
        ("QUERY_CANCELED", "57014"),
        ("LOCK_NOT_AVAILABLE", "55P03"),
        ("DISK_FULL", "53100"),
        ("T_R_DEADLOCK_DETECTED", "40P01"),
        ("INVALID_PASSWORD", "28P01"),
        ("IO_ERROR", "58030"),
    ],
)
def test_key_conditions_present(name, code):
    assert getattr(sql_state, name) == code
    assert lookup(code) == name or sql_state.ALL_CODES[name] == code


def test_lookup_roundtrip_every_code():
    for name, code in sql_state.ALL_CODES.items():
        # every emitted code must be resolvable back to a condition name
        assert lookup(code) in sql_state.ALL_CODES
        assert sql_state.ALL_CODES[lookup(code)] == code


# -- wire-level emission ----------------------------------------------------


def _error_from(client_call):
    async def run(cluster, clients):
        with pytest.raises(PgClientError) as ei:
            await client_call(clients[0])
        run.err = ei.value

    return run


def test_syntax_error_code_and_position():
    async def body(cluster, clients):
        c = clients[0]
        # a query OUR parser rejects (with a token position), not one
        # that limps through to SQLite (whose errors carry no position)
        q = "INSERT INTO t VALUES (1,"
        with pytest.raises(PgClientError) as ei:
            await c.query(q)
        e = ei.value
        assert e.code == sql_state.SYNTAX_ERROR
        assert lookup(e.code) == "SYNTAX_ERROR"
        # P field: 1-based char position inside the query string, at or
        # after the bogus token ("psql's error caret")
        assert e.position == len(q) + 1  # EOF position, 1-based
        assert e.fields.get("S") == "ERROR"
        # sqlite-surfaced syntax errors still carry the right code,
        # just no position
        with pytest.raises(PgClientError) as ei2:
            await c.query("SELECT * FROMM t")
        assert ei2.value.code == sql_state.SYNTAX_ERROR
        assert ei2.value.position == 0

    asyncio.run(_with_pg(1, body))


def test_undefined_table_code():
    async def body(cluster, clients):
        with pytest.raises(PgClientError) as ei:
            await clients[0].query("SELECT * FROM never_created")
        assert ei.value.code == sql_state.UNDEFINED_TABLE
        assert lookup(ei.value.code) == "UNDEFINED_TABLE"

    asyncio.run(_with_pg(1, body))


def test_unique_violation_code():
    async def body(cluster, clients):
        c = clients[0]
        await c.query(
            "CREATE TABLE uv (id INTEGER PRIMARY KEY, v TEXT) WITHOUT ROWID"
        )
        await c.query("INSERT INTO uv VALUES (1, 'a')")
        with pytest.raises(PgClientError) as ei:
            await c.query("INSERT INTO uv VALUES (1, 'b')")
        assert ei.value.code == sql_state.UNIQUE_VIOLATION
        assert lookup(ei.value.code) == "UNIQUE_VIOLATION"

    asyncio.run(_with_pg(1, body))


def test_in_failed_transaction_code():
    async def body(cluster, clients):
        c = clients[0]
        await c.query("BEGIN")
        with pytest.raises(PgClientError):
            await c.query("SELECT * FROM never_created")
        # any statement in an aborted tx must fail 25P02 (the sticky
        # state psycopg maps to InFailedSqlTransaction)
        with pytest.raises(PgClientError) as ei:
            await c.query("SELECT 1")
        assert ei.value.code == sql_state.IN_FAILED_SQL_TRANSACTION
        assert lookup(ei.value.code) == "IN_FAILED_SQL_TRANSACTION"
        await c.query("ROLLBACK")

    asyncio.run(_with_pg(1, body))


def test_batch_syntax_error_position_offset():
    """In a multi-statement simple-Query batch, the P field must index
    the ORIGINAL query string, not the split substring."""
    async def body(cluster, clients):
        c = clients[0]
        q = "SELECT 1; INSERT INTO t VALUES (1,"
        with pytest.raises(PgClientError) as ei:
            await c.query(q)
        e = ei.value
        assert e.code == sql_state.SYNTAX_ERROR
        # EOF of the second statement, 1-based in the full string
        assert e.position == len(q) + 1

    asyncio.run(_with_pg(1, body))
