r"""psql `\d <table>` against the PG front-end (VERDICT r2 item 6).

psql implements \d as a fixed sequence of pg_catalog queries; these are
the literal shapes psql 14 sends (captured from describe.c), exercising
the parser's OPERATOR(pg_catalog.~) / COLLATE pg_catalog.default /
chained-::-cast handling and the pg_attribute / pg_index /
pg_constraint / pg_attrdef catalog tables."""

import asyncio

from corrosion_tpu.pg.client import PgClient

from .test_pg import _with_pg  # the shared agent+pg fixture

SCHEMA_EXTRA = (
    "CREATE TABLE IF NOT EXISTS described ("
    " id INTEGER PRIMARY KEY NOT NULL,"
    " label TEXT NOT NULL DEFAULT 'x',"
    " score REAL);"
    "CREATE UNIQUE INDEX IF NOT EXISTS described_label_key ON described (label);"
)

Q_RESOLVE = (
    "SELECT c.oid,\n  n.nspname,\n  c.relname\n"
    "FROM pg_catalog.pg_class c\n"
    "     LEFT JOIN pg_catalog.pg_namespace n ON n.oid = c.relnamespace\n"
    "WHERE c.relname OPERATOR(pg_catalog.~) '^(described)$' COLLATE pg_catalog.default\n"
    "  AND pg_catalog.pg_table_is_visible(c.oid)\n"
    "ORDER BY 2, 3;"
)

Q_RELFLAGS = (
    "SELECT c.relchecks, c.relkind, c.relhasindex, c.relhasrules, "
    "c.relhastriggers, c.relrowsecurity, c.relforcerowsecurity, "
    "false AS relhasoids, c.relispartition, '', c.reltablespace, "
    "CASE WHEN c.reloftype = 0 THEN '' ELSE "
    "c.reloftype::pg_catalog.regtype::pg_catalog.text END, "
    "c.relpersistence, c.relreplident, am.amname\n"
    "FROM pg_catalog.pg_class c\n"
    " LEFT JOIN pg_catalog.pg_am am ON (c.relam = am.oid)\n"
    "WHERE c.oid = '{oid}';"
)

Q_COLUMNS = (
    "SELECT a.attname,\n"
    "  pg_catalog.format_type(a.atttypid, a.atttypmod),\n"
    "  (SELECT pg_catalog.pg_get_expr(d.adbin, d.adrelid, true)\n"
    "   FROM pg_catalog.pg_attrdef d\n"
    "   WHERE d.adrelid = a.attrelid AND d.adnum = a.attnum AND a.atthasdef),\n"
    "  a.attnotnull,\n"
    "  (SELECT c.collname FROM pg_catalog.pg_collation c, pg_catalog.pg_type t\n"
    "   WHERE c.oid = a.attcollation AND t.oid = a.atttypid "
    "AND a.attcollation <> t.typcollation) AS attcollation,\n"
    "  a.attidentity,\n"
    "  a.attgenerated\n"
    "FROM pg_catalog.pg_attribute a\n"
    "WHERE a.attrelid = '{oid}' AND a.attnum > 0 AND NOT a.attisdropped\n"
    "ORDER BY a.attnum;"
)

Q_INDEXES = (
    "SELECT c2.relname, i.indisprimary, i.indisunique, i.indisclustered, "
    "i.indisvalid, pg_catalog.pg_get_indexdef(i.indexrelid, 0, true),\n"
    "  pg_catalog.pg_get_constraintdef(con.oid, true), contype, "
    "condeferrable, condeferred, i.indisreplident, c2.reltablespace\n"
    "FROM pg_catalog.pg_class c, pg_catalog.pg_class c2, "
    "pg_catalog.pg_index i\n"
    "  LEFT JOIN pg_catalog.pg_constraint con ON (conrelid = i.indrelid "
    "AND conindid = i.indexrelid AND contype IN ('p','u','x'))\n"
    "WHERE c.oid = '{oid}' AND c.oid = i.indrelid AND i.indexrelid = c2.oid\n"
    "ORDER BY i.indisprimary DESC, c2.relname;"
)


def test_psql_backslash_d_sequence():
    async def body(cluster, clients):
        c: PgClient = clients[0]
        for stmt in SCHEMA_EXTRA.rstrip(";").split(";"):
            cluster.agents[0].store.conn.execute(stmt)

        # psql startup also runs set_config for search_path
        res = await c.query(
            "SELECT pg_catalog.set_config('search_path', '', false)"
        )
        assert res[0].rows

        # 1. name resolution (regex operator + collate + visibility UDF)
        res = await c.query(Q_RESOLVE)
        assert len(res[0].rows) == 1, res[0].rows
        oid, nsp, relname = res[0].rows[0]
        assert relname == "described" and nsp == "public"

        # 2. relation flags (chained :: casts inside CASE)
        res = await c.query(Q_RELFLAGS.format(oid=oid))
        row = res[0].rows[0]
        assert row[1] == "r"  # relkind
        assert row[2] == "1"  # relhasindex (pkey + unique index)
        assert row[14] == "heap"  # am.amname

        # 3. column list with types, defaults, not-null
        res = await c.query(Q_COLUMNS.format(oid=oid))
        cols = {r[0]: r for r in res[0].rows}
        assert set(cols) == {"id", "label", "score"}
        assert cols["id"][3] == "1"  # pk ⇒ not null
        assert cols["label"][2] == "'x'"  # default expression text
        assert cols["label"][3] == "1"
        assert cols["score"][3] == "0"
        assert cols["id"][1] == "int8"  # format_type of the affinity oid

        # 4. index + constraint listing
        res = await c.query(Q_INDEXES.format(oid=oid))
        by_name = {r[0]: r for r in res[0].rows}
        assert "described_pkey" in by_name, by_name
        pkey = by_name["described_pkey"]
        assert pkey[1] == "1" and pkey[2] == "1"  # primary, unique
        assert "ON described" in pkey[5]
        assert pkey[6] == "PRIMARY KEY (id)"
        assert pkey[7] == "p"
        uniq = by_name["described_label_key"]
        assert uniq[1] == "0" and uniq[2] == "1"
        assert "UNIQUE" in uniq[5]

    asyncio.run(_with_pg(1, body))
