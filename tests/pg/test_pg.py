"""PG wire-protocol front-end tests — a real client speaking the v3
protocol over TCP against the server, with writes verified to gossip to
a second node (the reference drives corro-pg with tokio-postgres,
corro-pg/src/lib.rs:3440+)."""

import asyncio

import pytest

from corrosion_tpu.pg import PgServer
from corrosion_tpu.pg.client import PgClient, PgClientError
from corrosion_tpu.testing import Cluster


async def _with_pg(n, fn):
    cluster = Cluster(n, use_swim=False)
    await cluster.start()
    servers, clients = [], []
    try:
        for agent in cluster.agents:
            srv = PgServer(agent)
            await srv.start()
            servers.append(srv)
            c = PgClient("127.0.0.1", srv._port)
            await c.connect()
            clients.append(c)
        await fn(cluster, clients)
    finally:
        for c in clients:
            try:
                await c.close()
            except Exception:
                pass
        for srv in servers:
            await srv.stop()
        await cluster.stop()


def test_simple_query_roundtrip():
    async def body(cluster, clients):
        res = await clients[0].query(
            "INSERT INTO tests (id, text) VALUES (1, 'pg')"
        )
        assert res[0].tag == "INSERT 0 1"
        res = await clients[0].query("SELECT id, text FROM tests")
        assert res[0].columns == ["id", "text"]
        assert res[0].rows == [("1", "pg")]
        assert res[0].tag == "SELECT 1"

    asyncio.run(_with_pg(1, body))


def test_extended_protocol_params():
    async def body(cluster, clients):
        res = await clients[0].execute(
            "INSERT INTO tests (id, text) VALUES ($1, $2)", [5, "param"]
        )
        assert res.tag == "INSERT 0 1"
        res = await clients[0].execute(
            "SELECT text FROM tests WHERE id = $1", [5]
        )
        assert res.rows == [("param",)]

    asyncio.run(_with_pg(1, body))


def test_explicit_transaction_commit_and_gossip():
    async def body(cluster, clients):
        res = await clients[0].query(
            "BEGIN; "
            "INSERT INTO tests (id, text) VALUES (10, 'a'); "
            "INSERT INTO tests (id, text) VALUES (11, 'b'); "
            "COMMIT"
        )
        assert [r.tag for r in res] == ["BEGIN", "INSERT 0 1", "INSERT 0 1", "COMMIT"]
        # one version for the whole tx, replicated to node 1
        for _ in range(200):
            rows = cluster.agents[1].store.query(
                "SELECT id FROM tests WHERE id IN (10, 11) ORDER BY id"
            )
            if len(rows) == 2:
                break
            await asyncio.sleep(0.05)
        assert [r[0] for r in rows] == [10, 11]

    asyncio.run(_with_pg(2, body))


def test_rollback_discards():
    async def body(cluster, clients):
        await clients[0].query(
            "BEGIN; INSERT INTO tests (id, text) VALUES (20, 'x'); ROLLBACK"
        )
        rows = cluster.agents[0].store.query(
            "SELECT id FROM tests WHERE id = 20"
        )
        assert rows == []

    asyncio.run(_with_pg(1, body))


def test_failed_transaction_is_sticky():
    async def body(cluster, clients):
        c = clients[0]
        await c.query("BEGIN")
        with pytest.raises(PgClientError) as ei:
            await c.query("SELECT * FROM nonexistent_table")
        assert ei.value.code == "42P01"
        # further statements refused with 25P02 until rollback
        with pytest.raises(PgClientError) as ei:
            await c.query("SELECT 1")
        assert ei.value.code == "25P02"
        await c.query("ROLLBACK")
        res = await c.query("SELECT 1")
        assert res[0].rows == [("1",)]

    asyncio.run(_with_pg(1, body))


def test_error_sqlstate_mapping():
    async def body(cluster, clients):
        with pytest.raises(PgClientError) as ei:
            await clients[0].query("SELECT * FROM missing_tbl")
        assert ei.value.code == "42P01"
        with pytest.raises(PgClientError) as ei:
            await clients[0].query("SELEKT 1")
        assert ei.value.code == "42601"

    asyncio.run(_with_pg(1, body))


def test_set_show_and_introspection():
    async def body(cluster, clients):
        c = clients[0]
        res = await c.query("SET application_name = 'myapp'")
        assert res[0].tag == "SET"
        res = await c.query("SHOW application_name")
        assert res[0].rows == [("myapp",)]
        res = await c.query("SELECT version()")
        assert "corrosion-tpu" in res[0].rows[0][0]
        # pg_catalog emulation: typname lookup + user tables in pg_class
        res = await c.query(
            "SELECT typname FROM pg_catalog.pg_type WHERE oid = 25"
        )
        assert res[0].rows == [("text",)]
        res = await c.query(
            "SELECT relname FROM pg_class WHERE relkind = 'r' ORDER BY relname"
        )
        assert ("tests",) in res[0].rows

    asyncio.run(_with_pg(1, body))


def test_pg_write_visible_over_store_and_broadcast_path():
    async def body(cluster, clients):
        # writes via PG ride the same changeset machinery: version bump +
        # crdt clock rows exist
        await clients[0].execute(
            "INSERT INTO tests (id, text) VALUES ($1, $2)", [30, "w"]
        )
        agent = cluster.agents[0]
        assert agent.store.db_version() >= 1
        changes = agent.store.changes_for_version(
            agent.actor_id, agent.store.db_version()
        )
        assert any(ch.table == "tests" for ch in changes)

    asyncio.run(_with_pg(1, body))


def test_create_table_over_pg_is_crr():
    async def body(cluster, clients):
        res = await clients[0].query(
            "CREATE TABLE pgmade (id bigint primary key, note text)"
        )
        assert res[0].tag == "CREATE TABLE"
        res = await clients[0].execute(
            "INSERT INTO pgmade (id, note) VALUES ($1, $2)", [1, "hi"]
        )
        assert res.tag == "INSERT 0 1"
        # it's a CRR: changes captured for broadcast
        agent = cluster.agents[0]
        changes = agent.store.changes_for_version(
            agent.actor_id, agent.store.db_version()
        )
        assert any(ch.table == "pgmade" for ch in changes)

    asyncio.run(_with_pg(1, body))


def test_portal_suspension_max_rows():
    async def body(cluster, clients):
        c = clients[0]
        for i in range(8):
            await c.execute(
                "INSERT INTO tests (id, text) VALUES ($1, $2)", [100 + i, "r"]
            )
        # manual extended flow with max_rows=3: expect 2 suspensions
        import struct

        from corrosion_tpu.pg.client import _frame

        w = c.writer
        sql = b"SELECT id FROM tests ORDER BY id\x00"
        w.write(_frame(b"P", b"\x00" + sql + struct.pack("!h", 0)))
        w.write(
            _frame(
                b"B",
                b"\x00\x00" + struct.pack("!hhh", 0, 0, 0),
            )
        )
        for _ in range(3):
            w.write(_frame(b"E", b"\x00" + struct.pack("!i", 3)))
        w.write(_frame(b"S", b""))
        await w.drain()
        suspended = rows = 0
        while True:
            tag, body = await c._read_backend()
            if tag == b"s":
                suspended += 1
            elif tag == b"D":
                rows += 1
            elif tag == b"Z":
                break
        assert suspended == 2
        assert rows == 8

    asyncio.run(_with_pg(1, body))


def test_writable_cte_routes_through_write_path():
    """Advisor r1-high: WITH x AS (...) INSERT must be versioned +
    broadcastable, not slip through the read path with a stale db_version."""

    async def body(cluster, clients):
        agent = cluster.agents[0]
        v0 = agent.store.db_version()
        res = await clients[0].query(
            "WITH src AS (SELECT 40 AS id, 'cte' AS t) "
            "INSERT INTO tests (id, text) SELECT id, t FROM src"
        )
        assert res[0].tag == "INSERT 0 1"
        assert agent.store.db_version() == v0 + 1
        changes = agent.store.changes_for_version(
            agent.actor_id, agent.store.db_version()
        )
        assert any(ch.table == "tests" for ch in changes)
        # read-only CTE still classified (and served) as a read
        res = await clients[0].query(
            "WITH c AS (SELECT count(*) AS n FROM tests) SELECT n FROM c"
        )
        assert res[0].tag == "SELECT 1"

    asyncio.run(_with_pg(1, body))


def test_pragma_policy_over_pg():
    """Advisor r1-high: state-mutating PRAGMAs must be rejected; harmless
    introspection PRAGMAs stay available on the read lane."""

    async def body(cluster, clients):
        c = clients[0]
        for bad in (
            "PRAGMA journal_mode = DELETE",
            "PRAGMA synchronous = OFF",
            "PRAGMA journal_mode",  # read form of a connection-state pragma
        ):
            with pytest.raises(PgClientError) as ei:
                await c.query(bad)
            assert ei.value.code == "0A000"
        res = await c.query("PRAGMA table_info(tests)")
        assert any("id" in r for r in res[0].rows)

    asyncio.run(_with_pg(1, body))


def test_extended_error_rfq_only_on_sync():
    """Advisor r1-medium: after an extended-protocol error the server must
    swallow messages until Sync and answer THAT with ReadyForQuery — a
    premature RFQ desyncs Flush-pipelining drivers."""

    async def body(cluster, clients):
        import struct

        from corrosion_tpu.pg.client import _frame

        c = clients[0]
        w = c.writer
        # Parse a statement rejected at Parse time, then Flush (no Sync yet)
        w.write(
            _frame(
                b"P",
                b"\x00" + b"PRAGMA journal_mode = DELETE\x00" + struct.pack("!h", 0),
            )
        )
        w.write(_frame(b"H", b""))
        await w.drain()
        tag, _ = await c._read_backend()
        assert tag == b"E"  # ErrorResponse...
        # ...and NOTHING else yet: a Bind sent now must be discarded silently
        w.write(
            _frame(b"B", b"\x00\x00" + struct.pack("!hhh", 0, 0, 0))
        )
        w.write(_frame(b"S", b""))
        await w.drain()
        tag, body_ = await c._read_backend()
        assert tag == b"Z"  # RFQ arrives only in response to Sync
        # session still usable afterwards
        res = await c.query("SELECT 1")
        assert res[0].rows == [("1",)]

    asyncio.run(_with_pg(1, body))


def test_now_transaction_stable_over_wire():
    """PG's now() is transaction-stable (ADVICE r4): every statement in
    a BEGIN..COMMIT block sees the BEGIN timestamp; after COMMIT the
    clock moves again."""

    async def body(cluster, clients):
        c = clients[0]
        await c.query("BEGIN")
        (first,) = (await c.query("SELECT now()"))[0].rows[0]
        await asyncio.sleep(0.005)
        (second,) = (await c.query("SELECT now()"))[0].rows[0]
        assert first == second
        await c.query("COMMIT")
        await asyncio.sleep(0.005)
        (after,) = (await c.query("SELECT now()"))[0].rows[0]
        assert after != first

    asyncio.run(_with_pg(1, body))


def test_now_thawed_after_client_drops_mid_tx():
    """A client dropping mid-BEGIN must not leave now() frozen on the
    shared writer connection (code-review r5: _abort_open_tx leak)."""

    async def body(cluster, clients):
        c = clients[0]
        await c.query("BEGIN")
        (frozen,) = (await c.query("SELECT now()"))[0].rows[0]
        await c.close()  # abrupt end: session abort path
        c2 = PgClient(c.host, c.port)
        await c2.connect()
        try:
            await asyncio.sleep(0.01)
            (after,) = (await c2.query("SELECT now()"))[0].rows[0]
            assert after != frozen
            await asyncio.sleep(0.005)
            (after2,) = (await c2.query("SELECT now()"))[0].rows[0]
            assert after2 != after  # clock is genuinely live again
        finally:
            await c2.close()

    asyncio.run(_with_pg(1, body))
