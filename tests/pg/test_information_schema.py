"""information_schema introspection over the PG bridge (round 4).

ORMs (SQLAlchemy, Rails, knex) and migration tools introspect
``information_schema.tables`` / ``columns`` / ``key_column_usage``
rather than pg_catalog.  SQLite forbids cross-database views, so the
schema is served as ``is_*`` views inside the attached pg_catalog
database, with ``information_schema.X`` mapped at emit time
(parser.emit_name) — these tests drive the full wire path.
"""

import asyncio

from corrosion_tpu.pg import PgServer
from corrosion_tpu.pg.client import PgClient
from corrosion_tpu.testing import Cluster


def _with_pg(fn):
    async def body():
        cluster = Cluster(1, use_swim=False)
        await cluster.start()
        srv = PgServer(cluster.agents[0])
        await srv.start()
        c = PgClient("127.0.0.1", srv._port)
        await c.connect()
        try:
            await fn(c)
        finally:
            await c.close()
            await srv.stop()
            await cluster.stop()

    asyncio.run(body())


def test_tables_view():
    async def body(c):
        r = await c.query(
            "SELECT table_name, table_type FROM information_schema.tables "
            "WHERE table_schema = 'public' ORDER BY table_name"
        )
        names = [row[0] for row in r[0].rows]
        assert "tests" in names
        assert all(row[1] == "BASE TABLE" for row in r[0].rows)

    _with_pg(body)


def test_columns_view():
    async def body(c):
        r = await c.query(
            "SELECT column_name, data_type, is_nullable, ordinal_position "
            "FROM information_schema.columns WHERE table_name = 'tests' "
            "ORDER BY ordinal_position"
        )
        cols = {row[0]: (row[1], row[2]) for row in r[0].rows}
        assert cols["id"][0] == "bigint"
        assert cols["id"][1] == "NO"  # primary key => not nullable
        assert cols["text"][0] == "text"
        # ordinal positions are 1-based and dense
        assert [row[3] for row in r[0].rows] == [
            str(i + 1) for i in range(len(r[0].rows))
        ]

    _with_pg(body)


def test_key_column_usage_and_constraints():
    async def body(c):
        await c.query(
            "CREATE TABLE pairs (a INTEGER, b INTEGER, v TEXT, "
            "PRIMARY KEY (a, b))"
        )
        # the schema-qualified join shape knex/Prisma emit (constraint
        # names are only unique per schema in PG)
        r = await c.query(
            "SELECT kcu.column_name, kcu.ordinal_position "
            "FROM information_schema.key_column_usage kcu "
            "JOIN information_schema.table_constraints tc "
            "  ON tc.constraint_name = kcu.constraint_name "
            "  AND tc.constraint_schema = kcu.constraint_schema "
            "WHERE tc.table_name = 'pairs' "
            "  AND tc.constraint_type = 'PRIMARY KEY' "
            "ORDER BY kcu.ordinal_position"
        )
        assert [tuple(row) for row in r[0].rows] == [("a", "1"), ("b", "2")]

    _with_pg(body)


def test_unique_constraint_surfaces_catalog_level():
    """UNIQUE constraints are forbidden on CRRs (schema.rs:164 parity),
    so this can't be driven over the bridge — exercise the catalog
    mirror directly on a raw store shape."""
    import sqlite3

    from corrosion_tpu.pg import catalog

    conn = sqlite3.connect(":memory:")
    conn.execute(
        "CREATE TABLE uniq_t (id INTEGER PRIMARY KEY, email TEXT, "
        "UNIQUE (email))"
    )
    catalog.attach(conn, "corrosion")
    catalog.register_functions(conn, "corrosion")
    try:
        catalog.refresh_pg_class(conn)
        rows = conn.execute(
            "SELECT constraint_name, constraint_type "
            "FROM pg_catalog.is_table_constraints "
            "WHERE table_name = 'uniq_t' ORDER BY constraint_type"
        ).fetchall()
        assert ("uniq_t_pkey", "PRIMARY KEY") in rows
        assert ("uniq_t_email_key", "UNIQUE") in rows
        kcu = conn.execute(
            "SELECT column_name, constraint_schema "
            "FROM pg_catalog.is_key_column_usage "
            "WHERE constraint_name = 'uniq_t_email_key'"
        ).fetchall()
        assert kcu == [("email", "public")]
    finally:
        catalog.release_functions(conn)
        conn.close()


def test_duplicate_unique_first_column_disambiguates():
    """Two UNIQUE constraints sharing a first column must not merge into
    one bogus key_column_usage constraint (PG appends a numeric
    suffix)."""
    import sqlite3

    from corrosion_tpu.pg import catalog

    conn = sqlite3.connect(":memory:")
    conn.execute(
        "CREATE TABLE t2 (a INTEGER, b INTEGER, c INTEGER, "
        "UNIQUE (a, b), UNIQUE (a, c))"
    )
    catalog.attach(conn, "corrosion")
    catalog.register_functions(conn, "corrosion")
    try:
        catalog.refresh_pg_class(conn)
        names = [
            r[0] for r in conn.execute(
                "SELECT DISTINCT constraint_name "
                "FROM pg_catalog.is_table_constraints "
                "WHERE table_name = 't2' AND constraint_type = 'UNIQUE' "
                "ORDER BY constraint_name"
            )
        ]
        assert len(names) == 2 and len(set(names)) == 2, names
        for cname in names:
            cols = conn.execute(
                "SELECT ordinal_position FROM pg_catalog.is_key_column_usage "
                "WHERE constraint_name = ? ORDER BY ordinal_position",
                (cname,),
            ).fetchall()
            assert [c[0] for c in cols] == [1, 2], (cname, cols)
    finally:
        catalog.release_functions(conn)
        conn.close()


def test_dbname_with_quote_stays_literal():
    import sqlite3

    from corrosion_tpu.pg import catalog

    conn = sqlite3.connect(":memory:")
    catalog.attach(conn, "o'brien")
    try:
        assert conn.execute(
            "SELECT datname FROM pg_catalog.pg_database"
        ).fetchone() == ("o'brien",)
        assert conn.execute(
            "SELECT DISTINCT catalog_name FROM pg_catalog.is_schemata"
        ).fetchone() == ("o'brien",)
    finally:
        conn.close()


def test_view_columns_resolve_catalog_level():
    """Views can't be created over the bridge (CRR-only migrations),
    but a store MAY carry them; the catalog must reflect their columns
    (PRAGMA table_info works on views)."""
    import sqlite3

    from corrosion_tpu.pg import catalog

    conn = sqlite3.connect(":memory:")
    conn.execute("CREATE TABLE vt (id INTEGER PRIMARY KEY, name TEXT)")
    conn.execute("CREATE VIEW v_vt AS SELECT id, name FROM vt")
    catalog.attach(conn, "corrosion")
    catalog.register_functions(conn, "corrosion")
    try:
        catalog.refresh_pg_class(conn)
        assert conn.execute(
            "SELECT table_type FROM pg_catalog.is_tables "
            "WHERE table_name = 'v_vt'"
        ).fetchall() == [("VIEW",)]
        assert conn.execute(
            "SELECT column_name FROM pg_catalog.is_columns "
            "WHERE table_name = 'v_vt' ORDER BY ordinal_position"
        ).fetchall() == [("id",), ("name",)]
    finally:
        catalog.release_functions(conn)
        conn.close()


def test_schemata():
    async def body(c):
        r = await c.query(
            "SELECT schema_name FROM information_schema.schemata "
            "ORDER BY schema_name"
        )
        names = [row[0] for row in r[0].rows]
        assert "public" in names and "pg_catalog" in names

    _with_pg(body)
