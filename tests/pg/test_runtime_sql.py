"""Execution-level PG dialect fidelity (round 4).

The reference's PG layer runs on PG's own function library; ours runs
on SQLite, so every PG scalar/aggregate/SRF a client calls must either
exist as a UDF (corrosion_tpu/pg/runtime.py) or be rewritten to a
SQLite equivalent at emit time (parser.py Emitter).  These tests drive
``translate()`` + a runtime-registered connection end-to-end: the
assertion is on RESULT ROWS, not on emitted SQL text — parse-level
permissiveness was never the gap (VERDICT r3 graded corro-pg partial
for depth), execution was.
"""

import sqlite3

import pytest

from corrosion_tpu.pg import runtime
from corrosion_tpu.pg.translate import UnsupportedStatement, translate



# this container's sqlite (post-rebuild) may predate features these
# statements translate to: RETURNING needs >= 3.35, the -> / ->> JSON
# operators need >= 3.38.  The pg layer targets modern sqlite (CI runs
# >= 3.37); on an older runtime the tests gate rather than fail.
_needs_sqlite = lambda *v: pytest.mark.skipif(  # noqa: E731
    sqlite3.sqlite_version_info < v,
    reason=f"sqlite {sqlite3.sqlite_version} lacks the translated feature",
)

@pytest.fixture()
def conn():
    c = sqlite3.connect(":memory:")
    runtime.register(c)
    c.execute(
        "CREATE TABLE t (a INTEGER, b TEXT, name TEXT, ts TEXT, x INTEGER)"
    )
    c.executemany(
        "INSERT INTO t VALUES (?,?,?,?,?)",
        [
            (1, "b1", "Ann", "2026-07-01 10:30:45", 5),
            (2, "b2", "bob", "2026-07-15 22:00:00", -1),
        ],
    )
    c.execute("CREATE TABLE u (a INTEGER)")
    c.execute("INSERT INTO u VALUES (1)")
    yield c
    c.close()


def q(conn, sql, params=()):
    return conn.execute(translate(sql).sql, params).fetchall()


# -- timestamps & intervals --------------------------------------------------

def test_now_is_iso_utc_text(conn):
    (val,) = q(conn, "SELECT now()")[0]
    assert val[4] == "-" and val[10] == " " and len(val) >= 19


def test_extract_epoch_and_fields(conn):
    rows = q(conn, "SELECT EXTRACT(YEAR FROM ts), EXTRACT(dow FROM ts) FROM t")
    assert rows[0] == (2026.0, 3.0)  # 2026-07-01 is a Wednesday
    (epoch,) = q(conn, "SELECT EXTRACT(EPOCH FROM '1970-01-01 00:01:00')")[0]
    assert epoch == 60.0


def test_interval_arithmetic_is_calendar_aware(conn):
    assert q(conn, "SELECT ts + interval '1 day 2 hours' FROM t LIMIT 1") == [
        ("2026-07-02 12:30:45",)
    ]
    # month arithmetic must not be 30-day arithmetic
    assert q(conn, "SELECT '2026-01-31 00:00:00' + interval '1 month'") == [
        ("2026-02-28 00:00:00",)
    ]
    # chained ± intervals apply left-to-right
    assert q(
        conn, "SELECT '2026-07-15 12:00:00' - interval '1 hour' + interval '30 min'"
    ) == [("2026-07-15 11:30:00",)]
    # leap handling
    assert q(conn, "SELECT '2024-02-29 00:00:00' + interval '1 year'") == [
        ("2025-02-28 00:00:00",)
    ]


def test_standalone_interval_is_epoch_seconds(conn):
    assert q(conn, "SELECT interval '90 min'") == [(5400.0,)]
    assert q(conn, "SELECT '1 hour'::interval") == [(3600.0,)]
    assert q(conn, "SELECT interval '01:30:00'") == [(5400.0,)]


def test_recent_rows_window(conn):
    # the monitoring-dashboard idiom — rows pinned RELATIVE to now() so
    # the assertion is wall-clock independent
    conn.execute(
        translate(
            "INSERT INTO t VALUES (8, 'w', 'w', now() - interval '10 min', 0)"
        ).sql
    )
    conn.execute(
        translate(
            "INSERT INTO t VALUES (9, 'w', 'w', now() - interval '2 hours', 0)"
        ).sql
    )
    assert q(
        conn,
        "SELECT count(*) FROM t WHERE b = 'w' "
        "AND ts > now() - interval '1 hour'",
    ) == [(1,)]
    assert q(
        conn,
        "SELECT count(*) FROM t WHERE b <> 'w' "
        "AND ts > '2026-07-01' - interval '1 hour'",
    ) == [(2,)]


def test_date_trunc_and_part(conn):
    assert q(conn, "SELECT date_trunc('month', '2026-07-15 22:10:09')") == [
        ("2026-07-01 00:00:00",)
    ]
    assert q(conn, "SELECT date_trunc('week', '2026-07-15')") == [
        ("2026-07-13 00:00:00",)
    ]
    assert q(conn, "SELECT date_part('quarter', '2026-07-15')") == [(3.0,)]


def test_to_char_and_to_timestamp(conn):
    assert q(
        conn, "SELECT to_char('2026-07-15 22:04:05', 'YYYY-MM-DD HH24:MI')"
    ) == [("2026-07-15 22:04",)]
    assert q(conn, "SELECT to_char(1234.5, 'FM9,999.99')") == [("1,234.50",)]
    assert q(conn, "SELECT to_timestamp(86400)") == [("1970-01-02 00:00:00",)]
    assert q(conn, "SELECT age('2026-07-02', '2026-07-01')") == [(86400.0,)]


# -- strings -----------------------------------------------------------------

def test_keyword_argument_call_forms(conn):
    assert q(conn, "SELECT position('b' in 'abc')") == [(2,)]
    assert q(conn, "SELECT substring('abcdef' from 2 for 3)") == [("bcd",)]
    assert q(conn, "SELECT substring('abcdef' for 3)") == [("abc",)]
    assert q(conn, "SELECT substring('foobar' from 'o(.)b')") == [("o",)]
    assert q(conn, "SELECT trim(both 'x' from 'xaxx')") == [("a",)]
    assert q(conn, "SELECT trim(leading 'x' from 'xax')") == [("ax",)]
    assert q(conn, "SELECT trim(trailing 'x' from 'xax')") == [("xa",)]
    assert q(conn, "SELECT overlay('abcdef' placing 'XY' from 2 for 3)") == [
        ("aXYef",)
    ]


def test_left_right_are_join_keywords_and_functions(conn):
    assert q(conn, "SELECT left('abcd', 2), right('abcd', -1)") == [("ab", "bcd")]
    # ...without breaking actual LEFT JOIN
    assert q(conn, "SELECT t.a FROM t LEFT JOIN u ON t.a = u.a ORDER BY t.a") == [
        (1,), (2,)
    ]


def test_string_function_pack(conn):
    assert q(conn, "SELECT split_part('a,b,c', ',', 2)") == [("b",)]
    assert q(conn, "SELECT split_part('a,b,c', ',', -1)") == [("c",)]
    assert q(conn, "SELECT starts_with('abc', 'ab')") == [(1,)]
    assert q(conn, "SELECT initcap('hello wORLD')") == [("Hello World",)]
    assert q(conn, "SELECT lpad('7', 3, '0'), rpad('7', 3, '0')") == [
        ("007", "700")
    ]
    assert q(conn, "SELECT reverse('abc'), repeat('ab', 2)") == [("cba", "abab")]
    assert q(conn, "SELECT translate('abcde', 'ace', '12')") == [("1b2d",)]
    assert q(conn, "SELECT concat('a', NULL, 'b'), concat_ws('-', 'a', NULL, 'b')") == [
        ("ab", "a-b")
    ]
    assert q(conn, "SELECT md5('abc')") == [
        ("900150983cd24fb0d6963f7d28e17f72",)
    ]


def test_regex_operators(conn):
    assert q(conn, "SELECT name FROM t WHERE name ~ '^A'") == [("Ann",)]
    assert q(conn, "SELECT name FROM t WHERE name ~* '^B'") == [("bob",)]
    assert q(conn, "SELECT name FROM t WHERE name !~ '^A'") == [("bob",)]
    assert q(conn, "SELECT name FROM t WHERE name !~* '^a'") == [("bob",)]
    assert q(conn, "SELECT regexp_replace('aaa', 'a', 'b', 'g')") == [("bbb",)]
    assert q(conn, "SELECT regexp_replace('aaa', 'a', 'b')") == [("baa",)]


# -- arrays (JSON-text model) ------------------------------------------------

def test_array_literal_and_agg(conn):
    assert q(conn, "SELECT ARRAY[1,2,3]") == [("[1,2,3]",)]
    assert q(conn, "SELECT array_agg(a) FROM t") == [("[1,2]",)]
    assert q(conn, "SELECT string_agg(b, ',') FROM t") == [("b1,b2",)]


def test_any_all_accept_pg_array_literals(conn):
    assert q(conn, "SELECT a FROM t WHERE a = ANY('{1,3}')") == [(1,)]
    assert q(conn, "SELECT a FROM t WHERE a <> ALL('{2}')") == [(1,)]
    # the psycopg shape: a parameter, PG array literal text
    assert conn.execute(
        translate("SELECT a FROM t WHERE a = ANY($1) ORDER BY a").sql, ("{1,2,9}",)
    ).fetchall() == [(1,), (2,)]


def test_any_subquery_form(conn):
    # = ANY(subquery) is IN, not an array scan
    assert q(conn, "SELECT a FROM t WHERE a = ANY(SELECT a FROM u)") == [(1,)]
    assert q(conn, "SELECT a FROM t WHERE a <> ALL(SELECT a FROM u)") == [(2,)]


def test_interval_cast_form_in_arithmetic(conn):
    # '1 day'::interval must behave like interval '1 day' in ± context
    # (NOT fold to 86400.0 and numerically corrupt the text timestamp)
    assert q(conn, "SELECT '2026-07-01 10:00:00' + '1 day'::interval") == [
        ("2026-07-02 10:00:00",)
    ]
    assert q(
        conn, "SELECT count(*) FROM t WHERE ts > '2026-07-02' - '1 day'::interval"
    ) == [(2,)]


def test_any_with_typed_array_param(conn):
    # $1::int[] — the cast would destroy the array text before
    # pg_array_json parses it; it must be stripped
    assert conn.execute(
        translate("SELECT a FROM t WHERE a = ANY($1::int[]) ORDER BY a").sql,
        ("{1,2}",),
    ).fetchall() == [(1,), (2,)]


def test_unsupported_quantified_comparisons_rejected(conn):
    for sql in (
        "SELECT a FROM t WHERE a <> ANY('{1}')",
        "SELECT a FROM t WHERE a = ALL('{1}')",
        "SELECT a FROM t WHERE a > ANY('{1}')",
        "SELECT a FROM t WHERE b LIKE ANY('{b%}')",
        "SELECT a FROM t WHERE b ~ ANY('{x}')",
    ):
        with pytest.raises(UnsupportedStatement):
            translate(sql)


def test_string_agg_order_by_stripped(conn):
    # SQLite group_concat has no ordered form; the multiset is identical
    assert sorted(
        q(conn, "SELECT string_agg(b, ',' ORDER BY b DESC) FROM t")[0][0]
        .split(",")
    ) == ["b1", "b2"]
    assert q(conn, "SELECT array_agg(a ORDER BY a) FROM t") == [("[1,2]",)]


def test_with_ordinality_rejected(conn):
    with pytest.raises(UnsupportedStatement):
        translate("SELECT * FROM unnest('{1,2}') WITH ORDINALITY AS u(v, i)")


def test_string_agg_distinct(conn):
    conn.execute("INSERT INTO t VALUES (3, 'b1', 'Cy', '2026-07-20', 0)")
    assert q(conn, "SELECT string_agg(DISTINCT b, ',') FROM t") == [("b1,b2",)]
    with pytest.raises(UnsupportedStatement):
        translate("SELECT string_agg(DISTINCT b, '-') FROM t")


def test_div_truncates_toward_zero(conn):
    assert q(conn, "SELECT div(-7, 2), div(7, 2)") == [(-3, 3)]


def test_to_json_null_is_null(conn):
    assert q(conn, "SELECT to_json(NULL) IS NULL") == [(1,)]


def test_array_helpers(conn):
    assert q(conn, "SELECT array_length('{a,b,c}', 1)") == [(3,)]
    assert q(conn, "SELECT cardinality('{}')") == [(0,)]
    assert q(conn, "SELECT array_to_string('{1,2,3}', '+')") == [("1+2+3",)]
    assert q(conn, "SELECT array_position('{a,b}', 'b')") == [(2,)]


def test_unnest_in_from(conn):
    assert q(conn, "SELECT x FROM unnest(ARRAY[10,20]) AS x") == [(10,), (20,)]
    assert q(conn, "SELECT v FROM unnest('{7,8}') AS s(v) ORDER BY v") == [
        (7,), (8,)
    ]


# -- set-returning generate_series -------------------------------------------

def test_jsonb_srf_family(conn):
    assert q(
        conn,
        "SELECT e FROM jsonb_array_elements('[1, 2]') AS e ORDER BY e",
    ) == [("1",), ("2",)]
    # nested containers round-trip as jsonb text; scalars re-quote
    assert q(
        conn,
        "SELECT e FROM jsonb_array_elements('[\"a\", {\"k\": 1}]') AS e "
        "ORDER BY e",
    ) == [('"a"',), ('{"k":1}',)]
    assert q(
        conn,
        "SELECT t FROM json_array_elements_text('[\"a\", \"b\"]') AS t "
        "ORDER BY t",
    ) == [("a",), ("b",)]
    assert q(
        conn,
        "SELECT k FROM jsonb_object_keys('{\"x\": 1, \"y\": 2}') AS k "
        "ORDER BY k",
    ) == [("x",), ("y",)]
    # the lateral-ish filter shape
    assert q(
        conn,
        "SELECT count(*) FROM jsonb_array_elements('[1,2,3]') AS e "
        "WHERE e > '1'",
    ) == [(2,)]
    # booleans/null keep their JSON spelling; _text maps null -> NULL
    assert q(
        conn,
        "SELECT e FROM jsonb_array_elements('[true, false, null]') AS e",
    ) == [("true",), ("false",), ("null",)]
    assert q(
        conn,
        "SELECT t FROM jsonb_array_elements_text('[true, null, 1]') AS t",
    ) == [("true",), (None,), ("1",)]
    # wrong container kind yields zero rows (PG raises; we guard)
    assert q(conn, "SELECT k FROM jsonb_object_keys('[5, 6]') AS k") == []
    assert q(
        conn, "SELECT e FROM jsonb_array_elements('{\"a\": 1}') AS e"
    ) == []


@_needs_sqlite(3, 38, 0)
def test_jsonb_srf_lateral_correlated(conn):
    """The dominant real-world shape: per-row expansion of a jsonb
    column — `FROM t, jsonb_array_elements(t.col) AS e` — requires the
    SRF to see earlier FROM entries (SQLite's bare json_each can)."""
    conn.execute("CREATE TABLE docs (id INTEGER PRIMARY KEY, data TEXT)")
    conn.executemany(
        "INSERT INTO docs VALUES (?, ?)",
        [
            (1, '{"tags": ["a", "b"]}'),
            (2, '{"tags": ["b"]}'),
            (3, '{"tags": []}'),
        ],
    )
    assert q(
        conn,
        "SELECT docs.id, e FROM docs, "
        "jsonb_array_elements_text(docs.data -> 'tags') AS e "
        "ORDER BY docs.id, e",
    ) == [(1, "a"), (1, "b"), (2, "b")]
    # aggregation over the expansion
    assert q(
        conn,
        "SELECT e, count(*) FROM docs, "
        "jsonb_array_elements_text(docs.data -> 'tags') AS e "
        "GROUP BY e ORDER BY e",
    ) == [("a", 1), ("b", 2)]
    # unnest correlates too
    conn.execute("CREATE TABLE lists (id INTEGER PRIMARY KEY, vals TEXT)")
    conn.execute("INSERT INTO lists VALUES (1, '{10,20}')")
    assert q(
        conn,
        "SELECT v FROM lists, unnest(lists.vals) AS v ORDER BY v",
    ) == [(10,), (20,)]
    # uncorrelated args take the renaming-subquery form, which leaks
    # NO json_each columns — unqualified ORDER BY id stays unambiguous
    assert q(
        conn,
        "SELECT id, e FROM docs, jsonb_array_elements_text('[\"q\"]') "
        "AS e ORDER BY id",
    ) == [(1, "q"), (2, "q"), (3, "q")]


def test_generate_series(conn):
    assert q(conn, "SELECT * FROM generate_series(1, 5)") == [
        (1,), (2,), (3,), (4,), (5,)
    ]
    assert q(conn, "SELECT g FROM generate_series(2, 8, 2) AS g") == [
        (2,), (4,), (6,), (8,)
    ]
    assert q(conn, "SELECT n FROM generate_series(3, 1, -1) AS s(n)") == [
        (3,), (2,), (1,)
    ]
    assert q(conn, "SELECT * FROM generate_series(5, 1)") == []


def test_generate_series_dynamic_step_rejected(conn):
    with pytest.raises(UnsupportedStatement):
        translate("SELECT * FROM generate_series(1, 5, $1)")


def test_generate_series_zero_step_rejected(conn):
    # PG errors; emitting it would spin the recursive CTE forever
    with pytest.raises(UnsupportedStatement):
        translate("SELECT * FROM generate_series(1, 5, 0)")


def test_generate_series_keeps_integer_type(conn):
    rows = q(conn, "SELECT g FROM generate_series(2, 6, 2) AS g")
    assert rows == [(2,), (4,), (6,)]
    assert all(isinstance(v, int) for (v,) in rows)


# -- aggregates --------------------------------------------------------------

def test_bool_and_stat_aggregates(conn):
    assert q(conn, "SELECT bool_and(x > -5), bool_or(x < 0) FROM t") == [(1, 1)]
    assert q(conn, "SELECT every(a >= 1) FROM t") == [(1,)]
    assert q(conn, "SELECT stddev_pop(a), var_pop(a) FROM t") == [(0.5, 0.25)]
    (sd,) = q(conn, "SELECT stddev_samp(a) FROM t")[0]
    assert abs(sd - 0.7071) < 1e-3
    (c,) = q(conn, "SELECT corr(a, x) FROM t")[0]
    assert abs(c + 1.0) < 1e-9  # perfectly anti-correlated 2-point set


# -- statement shapes --------------------------------------------------------

def test_for_update_stripped(conn):
    assert q(conn, "SELECT a FROM t ORDER BY a FOR UPDATE SKIP LOCKED") == [
        (1,), (2,)
    ]
    assert q(conn, "SELECT a FROM t ORDER BY a FOR NO KEY UPDATE OF t NOWAIT") == [
        (1,), (2,)
    ]


def test_delete_using(conn):
    tr = translate("DELETE FROM t USING u WHERE t.a = u.a")
    assert tr.kind == "write"
    conn.execute(tr.sql)
    assert conn.execute("SELECT a FROM t").fetchall() == [(2,)]


@_needs_sqlite(3, 38, 0)
def test_delete_using_with_alias_and_returning(conn):
    tr = translate("DELETE FROM t AS x USING u WHERE x.a = u.a RETURNING x.a")
    assert conn.execute(tr.sql).fetchall() == [(1,)]


def test_truncate_is_replicated_delete(conn):
    tr = translate("TRUNCATE TABLE ONLY u RESTART IDENTITY CASCADE")
    assert tr.kind == "write"  # must ride the CRDT broadcast path
    assert tr.tag == "TRUNCATE TABLE"
    conn.execute(tr.sql)
    assert conn.execute("SELECT count(*) FROM u").fetchone() == (0,)
    with pytest.raises(UnsupportedStatement):
        translate("TRUNCATE t, u")


def test_distinct_on_rejected_cleanly(conn):
    with pytest.raises(UnsupportedStatement):
        translate("SELECT DISTINCT ON (a) a, b FROM t ORDER BY a, b")


def test_session_name_keywords(conn):
    assert q(conn, "SELECT current_user") == [("postgres",)]
    (val,) = q(conn, "SELECT localtimestamp")[0]
    assert val[4] == "-"


def test_misc_functions(conn):
    assert q(conn, "SELECT div(7, 2)") == [(3,)]
    (r,) = q(conn, "SELECT random()")[0]
    assert 0.0 <= r < 1.0  # PG semantics, not SQLite's int64
    (u,) = q(conn, "SELECT gen_random_uuid()")[0]
    assert len(u) == 36 and u.count("-") == 4


def test_greatest_least_ignore_nulls(conn):
    # PG: NULL args are ignored; SQLite's scalar MAX would return NULL
    assert q(conn, "SELECT greatest(1, NULL, 3), least(NULL, 2)") == [(3, 2)]
    assert q(conn, "SELECT greatest(NULL, NULL)") == [(None,)]


def test_advisory_locks_are_noops(conn):
    # migration tools (Flyway, sqlx, Rails) take these on startup
    assert q(conn, "SELECT pg_advisory_lock(42)") == [(None,)]
    assert q(conn, "SELECT pg_try_advisory_lock(1, 2)") == [(1,)]
    assert q(conn, "SELECT pg_advisory_unlock(42)") == [(1,)]


def test_to_date_month_pattern(conn):
    # 'Month' must map before 'Mon' (longest-first replace)
    assert q(conn, "SELECT to_date('15 January 2026', 'DD Month YYYY')") == [
        ("2026-01-15",)
    ]
    assert q(conn, "SELECT to_date('15 Jan 2026', 'DD Mon YYYY')") == [
        ("2026-01-15",)
    ]


def test_quote_literal(conn):
    assert q(conn, "SELECT quote_literal('it''s')") == [("'it''s'",)]


def test_jsonb_containment_operators(conn):
    # recursive jsonb containment
    assert q(conn, """SELECT '{"a": 1, "b": {"c": 2}}' @> '{"b": {"c": 2}}'""") == [(1,)]
    assert q(conn, """SELECT '{"a": 1}' @> '{"a": 2}'""") == [(0,)]
    assert q(conn, "SELECT '[1, 2, 3]' @> '[1, 3]'") == [(1,)]
    assert q(conn, "SELECT '[1, 2, 3]' @> '4'") == [(0,)]
    assert q(conn, """SELECT '{"a": 1}' <@ '{"a": 1, "b": 2}'""") == [(1,)]
    # PG array literals coerce through the array model
    assert q(conn, "SELECT '{1,2,3}' @> '{1,3}'") == [(1,)]
    assert q(conn, "SELECT '{1,2}' && '{2,9}'") == [(1,)]
    assert q(conn, "SELECT '{1,2}' && '{8,9}'") == [(0,)]


def test_jsonb_key_existence(conn):
    assert q(conn, """SELECT '{"a": 1}' ? 'a', '{"a": 1}' ? 'z'""") == [(1, 0)]
    assert q(conn, """SELECT '{"a": 1, "b": 2}' ?| '{z,b}'""") == [(1,)]
    assert q(conn, """SELECT '{"a": 1, "b": 2}' ?& '{a,b}'""") == [(1,)]
    assert q(conn, """SELECT '{"a": 1}' ?& '{a,b}'""") == [(0,)]
    # filter usage against a column
    conn.execute("UPDATE t SET b = '{\"tag\": 1}' WHERE a = 1")
    assert q(conn, "SELECT a FROM t WHERE b @> '{\"tag\": 1}'") == [(1,)]


@_needs_sqlite(3, 38, 0)
def test_containment_lhs_arrow_chain(conn):
    # THE canonical idiom: the @>'s LHS is the whole arrow chain
    # (a jsonb column holds valid JSON in every row, as in PG)
    conn.execute(
        "UPDATE t SET b = '{\"meta\": {\"tags\": [\"x\", \"y\"]}}' WHERE a = 1"
    )
    conn.execute("UPDATE t SET b = '{\"meta\": {}}' WHERE a <> 1")
    assert q(
        conn,
        "SELECT a FROM t WHERE b -> 'meta' -> 'tags' @> '[\"x\"]'",
    ) == [(1,)]
    assert q(
        conn,
        "SELECT a FROM t WHERE b -> 'meta' @> '{\"tags\": [\"y\"]}'",
    ) == [(1,)]


def test_containment_pg_edge_semantics(conn):
    # jsonb: scalar-in-array exception is TOP LEVEL only
    assert q(conn, "SELECT '[1, 2]' @> '1'") == [(1,)]
    assert q(conn, "SELECT '[[1, 2]]' @> '[1]'") == [(0,)]
    # jsonb nested array containment stays recursive (PG doc example)
    assert q(conn, "SELECT '[[1, 2]]' @> '[[1, 2, 2]]'") == [(1,)]
    # numeric cross-width equality; bools stay distinct from numbers
    assert q(conn, "SELECT '[1]' @> '1.0', '[true]' @> '1'") == [(1, 0)]


def test_array_type_semantics_ignore_dimensionality(conn):
    # PG ARRAY operators consider only base elements, never dims:
    # literals ('{..}') and ARRAY[...] constructors pin array semantics
    assert q(conn, "SELECT '{{1,2},{3,4}}' && '{{1,9}}'") == [(1,)]
    assert q(conn, "SELECT '{{1,2},{3,4}}' && '{{8,9}}'") == [(0,)]
    assert q(conn, "SELECT '{{1,2},{3,4}}' @> '{{1,4}}'") == [(1,)]
    assert q(conn, "SELECT ARRAY[1, 2] && ARRAY[2, 9]") == [(1,)]
    assert q(conn, "SELECT ARRAY[1, 2] @> ARRAY[2]") == [(1,)]
    assert q(conn, "SELECT '{a,b}' @> ARRAY['b']") == [(1,)]
    assert q(conn, "SELECT ARRAY[1] <@ '{1,2}'") == [(1,)]


def test_jsonb_scalar_key_existence(conn):
    # PG: '"foo"'::jsonb ? 'foo' is true (string scalar equality)
    assert q(conn, "SELECT '\"foo\"' ? 'foo', '\"foo\"' ? 'bar'") == [(1, 0)]


def test_array_empty_and_null_semantics(conn):
    # '{}' in array context is the empty array — contained in everything
    assert q(conn, "SELECT '{1,2}' @> '{}'") == [(1,)]
    assert q(conn, "SELECT ARRAY[1, 2] @> '{}'") == [(1,)]
    # ARRAY-type equality: NULL never matches
    assert q(conn, "SELECT '{1,NULL}' @> '{NULL}'") == [(0,)]
    assert q(conn, "SELECT '{1,NULL}' && '{NULL}'") == [(0,)]
    # jsonb null IS an ordinary value
    assert q(conn, "SELECT '[null]' @> 'null'") == [(1,)]


def test_array_concat_in_containment_chain(conn):
    # `||` between array operands is ARRAY CONCAT, and the whole chain
    # is the containment LHS (left-assoc)
    assert q(conn, "SELECT '{a}' || ARRAY['b'] @> ARRAY['a','b']") == [(1,)]
    assert q(conn, "SELECT ARRAY['a'] || '{b}' @> ARRAY['z']") == [(0,)]
    assert q(conn, "SELECT ARRAY[1] || ARRAY[2] && '{2}'") == [(1,)]
    # ...but links LEFT of the first array stay TEXT concat: PG types
    # each || left-to-right ('{a}' || 'b' = text '{a}b')
    assert q(conn, "SELECT '{a}' || 'b' || ARRAY['c'] @> ARRAY['b']") == [(0,)]
    assert q(conn, "SELECT '{a}' || 'b' || ARRAY['c'] @> ARRAY['c']") == [(1,)]


def test_typed_array_cast_in_containment(conn):
    # $1::int[] must not emit CAST(? AS INTEGER) around the array text
    assert conn.execute(
        translate("SELECT $1::int[] @> $2::int[]").sql, ("{1,2}", "{3}")
    ).fetchall() == [(0,)]
    assert conn.execute(
        translate("SELECT $1::int[] @> $2::int[]").sql, ("{1,2}", "{1}")
    ).fetchall() == [(1,)]
    assert q(conn, "SELECT '{1,2}'::int[] @> '{1}'") == [(1,)]


def test_srf_inside_exists_and_scalar_subquery(conn):
    """SRF renames must apply inside Call-wrapped subqueries (EXISTS
    parses its SELECT flat into call args) — the canonical jsonb filter
    idiom."""
    conn.execute("CREATE TABLE items (id INTEGER PRIMARY KEY, data TEXT)")
    conn.executemany(
        "INSERT INTO items VALUES (?, ?)",
        [(1, '["a", "b"]'), (2, '["c"]')],
    )
    assert q(
        conn,
        "SELECT id FROM items WHERE EXISTS (SELECT 1 FROM "
        "jsonb_array_elements_text(items.data) AS e WHERE e = 'a')",
    ) == [(1,)]
    assert q(
        conn,
        "SELECT coalesce((SELECT e FROM "
        "jsonb_array_elements_text('[\"z\"]') AS e LIMIT 1), 'none')",
    ) == [("z",)]


def test_srf_rename_does_not_hijack_inner_scopes(conn):
    """A subquery with its OWN FROM resolves its names against its own
    tables — an outer SRF alias must not capture them."""
    conn.execute("CREATE TABLE other (e TEXT)")
    conn.execute("INSERT INTO other VALUES ('sub-col')")
    conn.execute("CREATE TABLE items2 (id INTEGER PRIMARY KEY, data TEXT)")
    conn.execute("INSERT INTO items2 VALUES (1, '[\"x\"]')")
    assert q(
        conn,
        "SELECT (SELECT e FROM other) FROM items2, "
        "jsonb_array_elements(items2.data) AS e",
    ) == [("sub-col",)]


def test_srf_after_join_on_comma(conn):
    """``FROM a JOIN b ON cond, srf(...)`` — the comma ends the ON
    clause and returns to the FROM list."""
    conn.execute("CREATE TABLE ja (id INTEGER PRIMARY KEY, data TEXT)")
    conn.execute("CREATE TABLE jb (id INTEGER PRIMARY KEY)")
    conn.execute("INSERT INTO ja VALUES (1, '[\"k\"]')")
    conn.execute("INSERT INTO jb VALUES (1)")
    assert q(
        conn,
        "SELECT e FROM ja JOIN jb ON ja.id = jb.id, "
        "jsonb_array_elements_text(ja.data) AS e",
    ) == [("k",)]


def _make_docs(conn):
    conn.execute("CREATE TABLE docs (id INTEGER PRIMARY KEY, data TEXT)")
    conn.executemany(
        "INSERT INTO docs VALUES (?, ?)",
        [
            (1, '{"tags": ["a", "b"]}'),
            (2, '{"tags": ["b"]}'),
            (3, '{"tags": []}'),
        ],
    )


@_needs_sqlite(3, 38, 0)
def test_srf_rename_skips_defining_positions(conn):
    """`SELECT id AS e`: the alias DEFINITION must not be rewritten to
    the SRF column expression even when an SRF alias `e` exists."""
    _make_docs(conn)
    assert q(
        conn,
        "SELECT docs.id AS e FROM docs, "
        "jsonb_array_elements(docs.data -> 'tags') AS e "
        "WHERE docs.id = 2",
    ) == [(2,)]


@_needs_sqlite(3, 38, 0)
def test_srf_correlated_arg_inside_case(conn):
    _make_docs(conn)
    assert q(
        conn,
        "SELECT e FROM docs, jsonb_array_elements_text("
        "CASE WHEN docs.id = 1 THEN docs.data -> 'tags' ELSE '[]' END"
        ") AS e ORDER BY e",
    ) == [("a",), ("b",)]


@_needs_sqlite(3, 38, 0)
def test_srf_default_column_name_is_value(conn):
    _make_docs(conn)
    # PG: the *_elements family's OUT param names the column `value`
    assert q(
        conn,
        "SELECT value FROM jsonb_array_elements_text('[\"v\"]')",
    ) == [("v",)]
    # correlated form: `value` rewrites to the jsonb-text expression,
    # not json_each's raw column
    assert q(
        conn,
        "SELECT value FROM docs, jsonb_array_elements(docs.data -> 'tags') "
        "WHERE docs.id = 2",
    ) == [('"b"',)]


@_needs_sqlite(3, 38, 0)
def test_srf_scope_edges(conn):
    _make_docs(conn)
    # explicit LATERAL spelling (the canonical PG form) is dropped
    assert q(
        conn,
        "SELECT e FROM docs, LATERAL "
        "jsonb_array_elements_text(docs.data -> 'tags') AS e "
        "WHERE docs.id = 2",
    ) == [("b",)]
    # UNION branches are separate scopes: the second branch's `e` is a
    # real column, not the first branch's SRF alias
    conn.execute("CREATE TABLE uother (e TEXT)")
    conn.execute("INSERT INTO uother VALUES ('plain')")
    rows = q(
        conn,
        "SELECT e FROM docs, "
        "jsonb_array_elements_text(docs.data -> 'tags') AS e "
        "WHERE docs.id = 2 UNION ALL SELECT e FROM uother",
    )
    assert sorted(rows) == [("b",), ("plain",)]
    # bare implicit alias (no AS) is a defining position
    assert q(
        conn,
        "SELECT docs.id value FROM docs, "
        "jsonb_array_elements(docs.data -> 'tags') WHERE docs.id = 2",
    ) == [(2,)]
    # chained SRFs: the second one's argument references the first's
    # output column
    conn.execute(
        "INSERT INTO docs VALUES (4, '{\"m\": [[1, 2], [3]]}')"
    )
    assert q(
        conn,
        "SELECT x FROM docs, jsonb_array_elements(docs.data -> 'm') AS e, "
        "jsonb_array_elements(e) AS x WHERE docs.id = 4 ORDER BY x",
    ) == [("1",), ("2",), ("3",)]


def test_fold_not_started_mid_chain(conn):
    """A fold must never start at the RHS of an already-emitted chain
    operator — `data #>> '{a}' || ARRAY['x']` would otherwise swallow
    the path argument into pg_array_cat('{a}', ...). Mixed-op chains
    fall back to the untyped emission (a documented deviation: PG's
    static operand types are unknowable here), but the grouping must
    stay left-associative."""
    sql = translate(
        "SELECT docs.data #>> '{tags}' || ARRAY['x'] FROM docs"
    ).sql
    assert "#>> pg_array_cat" not in sql
    assert "#>> '{tags}'" in sql


def test_array_concat_outside_containment(conn):
    # the typing fold is not containment-context-only
    assert q(conn, "SELECT ARRAY[1] || ARRAY[2]") == [("[1, 2]",)]
    assert q(conn, "SELECT '{a}' || ARRAY['b']") == [('["a", "b"]',)]


def test_malformed_chain_fragments_terminate(conn):
    """A malformed operator fragment must fail cleanly (or pass through
    to a SQLite error), never wedge the translator's emit loop — a
    hung translate() on client-supplied SQL is a DoS."""
    for sql in (
        "SELECT (a @> b, c ||)",
        "SELECT (a @> b ||)",
        "SELECT a @>",
        "SELECT || b",
    ):
        try:
            translate(sql)  # must RETURN (any error is fine)
        except Exception:
            pass


def test_rhs_is_single_operand_left_assoc(conn):
    # PG parses a ? 'x' || 'y' as (a ? 'x') || 'y' — equal precedence,
    # left-associative; the RHS must not swallow the || chain
    assert q(conn, "SELECT '{\"a\": 1}' ? 'a' || 'b'") == [("1b",)]


def test_json_builders(conn):
    assert q(conn, "SELECT jsonb_build_object('k', 1)") == [('{"k":1}',)]
    assert q(conn, "SELECT json_build_array(1, 'a')") == [('[1,"a"]',)]
    assert q(conn, "SELECT to_json('x')") == [('"x"',)]
