"""PG-dialect statement matrix (VERDICT r1 item 8): 20+ real PG-shaped
statements driven through the wire protocol — RETURNING, upsert,
qualified catalog functions/tables, casts, placeholders, type-aware
binding, writable CTEs, session statements (the observable surface of
corro-pg/src/lib.rs:546-1906)."""

import asyncio

import sqlite3

import pytest

from corrosion_tpu.pg import PgServer
from corrosion_tpu.pg.client import PgClient, PgClientError
from corrosion_tpu.testing import Cluster


async def _with_pg(fn):
    cluster = Cluster(1, use_swim=False)
    await cluster.start()
    servers, clients = [], []
    try:
        agent = cluster.agents[0]
        srv = PgServer(agent)
        await srv.start()
        servers.append(srv)
        c = PgClient("127.0.0.1", srv._port)
        await c.connect()
        clients.append(c)
        await fn(cluster, c)
    finally:
        for c in clients:
            try:
                await c.close()
            except Exception:
                pass
        for srv in servers:
            await srv.stop()
        await cluster.stop()



# this container's sqlite (post-rebuild) may predate features these
# statements translate to: RETURNING needs >= 3.35, the -> / ->> JSON
# operators need >= 3.38.  The pg layer targets modern sqlite (CI runs
# >= 3.37); on an older runtime the tests gate rather than fail.
_needs_sqlite = lambda *v: pytest.mark.skipif(  # noqa: E731
    sqlite3.sqlite_version_info < v,
    reason=f"sqlite {sqlite3.sqlite_version} lacks the translated feature",
)

@_needs_sqlite(3, 35, 0)
def test_returning_clause():
    async def body(cluster, c):
        res = await c.query(
            "INSERT INTO tests (id, text) VALUES (1, 'a'), (2, 'b') RETURNING id, text"
        )
        assert res[0].columns == ["id", "text"]
        assert res[0].rows == [("1", "a"), ("2", "b")]
        assert res[0].tag == "INSERT 0 2"
        res = await c.query(
            "UPDATE tests SET text = 'z' WHERE id = 1 RETURNING id"
        )
        assert res[0].rows == [("1",)]
        assert res[0].tag == "UPDATE 1"
        res = await c.query("DELETE FROM tests WHERE id = 2 RETURNING id")
        assert res[0].rows == [("2",)]

    asyncio.run(_with_pg(body))


def test_upsert_on_conflict():
    async def body(cluster, c):
        await c.query("INSERT INTO tests (id, text) VALUES (1, 'first')")
        res = await c.query(
            "INSERT INTO tests (id, text) VALUES (1, 'second') "
            "ON CONFLICT (id) DO UPDATE SET text = excluded.text"
        )
        assert res[0].tag.startswith("INSERT")
        res = await c.query("SELECT text FROM tests WHERE id = 1")
        assert res[0].rows == [("second",)]
        res = await c.query(
            "INSERT INTO tests (id, text) VALUES (1, 'third') "
            "ON CONFLICT (id) DO NOTHING"
        )
        res = await c.query("SELECT text FROM tests WHERE id = 1")
        assert res[0].rows == [("second",)]
        # constraint-name form resolves via the schema (VERDICT r2 item
        # 6): <table>_pkey names the primary key
        res = await c.query(
            "INSERT INTO tests (id, text) VALUES (1, 'fourth') "
            "ON CONFLICT ON CONSTRAINT tests_pkey DO UPDATE SET text = excluded.text"
        )
        assert res[0].tag.startswith("INSERT")
        res = await c.query("SELECT text FROM tests WHERE id = 1")
        assert res[0].rows == [("fourth",)]
        # unknown constraint name → SQLSTATE 42704 (undefined_object)
        with pytest.raises(PgClientError) as ei:
            await c.query(
                "INSERT INTO tests (id, text) VALUES (1, 'x') "
                "ON CONFLICT ON CONSTRAINT no_such_constraint DO NOTHING"
            )
        assert ei.value.code == "42704", ei.value

    asyncio.run(_with_pg(body))


def test_qualified_catalog_functions_and_tables():
    async def body(cluster, c):
        res = await c.query("SELECT pg_catalog.version()")
        assert "PostgreSQL" in res[0].rows[0][0]
        res = await c.query("SELECT pg_catalog.current_schema()")
        assert res[0].rows == [("public",)]
        # qualified catalog TABLE stays qualified (attached catalog db)
        res = await c.query(
            "SELECT relname FROM pg_catalog.pg_class WHERE relname = 'tests'"
        )
        assert res[0].rows == [("tests",)]
        # public. qualification on user tables is stripped
        await c.query("INSERT INTO public.tests (id, text) VALUES (9, 'q')")
        res = await c.query("SELECT text FROM public.tests WHERE id = 9")
        assert res[0].rows == [("q",)]

    asyncio.run(_with_pg(body))


def test_introspection_functions():
    async def body(cluster, c):
        for sql, want in [
            ("SELECT quote_ident('weird name')", '"weird name"'),
            ("SELECT pg_encoding_to_char(6)", "UTF8"),
            ("SELECT has_schema_privilege('public', 'USAGE')", "1"),
            ("SELECT to_regclass('tests')", "tests"),
            ("SELECT pg_size_pretty(1024)", "1024 bytes"),
        ]:
            res = await c.query(sql)
            assert res[0].rows[0][0] == want, sql

    asyncio.run(_with_pg(body))


@_needs_sqlite(3, 35, 0)
def test_placeholders_casts_booleans():
    async def body(cluster, c):
        res = await c.execute(
            "INSERT INTO tests (id, text) VALUES ($1::int, $2::text) RETURNING id",
            [7, "cast"],
        )
        assert res.rows == [("7",)]
        res = await c.execute("SELECT $1::int + 1", [41])
        assert res.rows == [("42",)]
        res = await c.query("SELECT TRUE, FALSE")
        assert res[0].rows == [("1", "0")]

    asyncio.run(_with_pg(body))


@_needs_sqlite(3, 35, 0)
def test_writable_cte_with_returning():
    async def body(cluster, c):
        res = await c.query(
            "WITH ins AS (SELECT 11 AS id) "
            "INSERT INTO tests (id, text) SELECT id, 'cte' FROM ins RETURNING id"
        )
        assert res[0].rows == [("11",)]
        res = await c.query("SELECT text FROM tests WHERE id = 11")
        assert res[0].rows == [("cte",)]

    asyncio.run(_with_pg(body))


def test_session_statement_matrix():
    async def body(cluster, c):
        r = await c.query("SET application_name = 'matrix'")
        assert r[0].tag == "SET"
        r = await c.query("SHOW application_name")
        assert r[0].rows == [("matrix",)]
        r = await c.query("SHOW server_version")
        assert "14.0" in r[0].rows[0][0]
        r = await c.query("BEGIN")
        assert r[0].tag == "BEGIN"
        await c.query("INSERT INTO tests (id, text) VALUES (20, 'tx')")
        r = await c.query("COMMIT")
        assert r[0].tag == "COMMIT"
        res = await c.query("SELECT count(*) FROM tests WHERE id = 20")
        assert res[0].rows == [("1",)]
        await c.query("BEGIN")
        await c.query("INSERT INTO tests (id, text) VALUES (21, 'rb')")
        await c.query("ROLLBACK")
        res = await c.query("SELECT count(*) FROM tests WHERE id = 21")
        assert res[0].rows == [("0",)]

    asyncio.run(_with_pg(body))


def test_misc_read_shapes():
    async def body(cluster, c):
        await c.query("INSERT INTO tests (id, text) VALUES (1, 'x'), (2, 'y')")
        for sql in [
            "SELECT id FROM tests ORDER BY id DESC LIMIT 1",
            "SELECT id, count(*) FROM tests GROUP BY id HAVING count(*) > 0",
            "SELECT t.id FROM tests t JOIN tests u ON u.id = t.id",
            "SELECT CASE WHEN id > 1 THEN 'big' ELSE 'small' END FROM tests",
            "SELECT id FROM tests WHERE text IN ('x', 'y')",
            "SELECT coalesce(NULL, 'd')",
            "VALUES (1, 2)",
        ]:
            res = await c.query(sql)
            assert res[0].rows, sql

    asyncio.run(_with_pg(body))
