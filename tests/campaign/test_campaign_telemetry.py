"""Campaign-engine flight-recorder integration (ISSUE 5): per-cell
telemetry summaries, span trees with artifact traceparents, per-lane
JSONL traces, and the engine-routed membership-churn cells with
detect-round bands."""

import json
import os

import numpy as np
import pytest

import corrosion_tpu.sim.packed  # noqa: F401  (import before tracing)
from corrosion_tpu.campaign.engine import run_campaign
from corrosion_tpu.campaign.report import compare
from corrosion_tpu.campaign.spec import (
    CampaignSpec,
    builtin_spec,
    swim_churn_64_spec,
    swim_churn_partial_spec,
)
from corrosion_tpu.faults import FaultEvent


def _quick_spec(seeds=(0, 1), **kw):
    kw.setdefault("max_rounds", 200)
    return CampaignSpec(
        name="tel-smoke",
        scenario={
            "n_nodes": 3, "n_payloads": 8, "fanout": 2,
            "sync_interval_rounds": 4, "n_delay_slots": 4,
            "inject_every": 1,
        },
        events=(
            FaultEvent("loss", 0, 10, p=0.3),
            FaultEvent("partition", 2, 8, src=1, dst=0),
        ),
        seeds=tuple(seeds),
        **kw,
    )


@pytest.mark.campaign
def test_cells_carry_telemetry_and_traceparent(tmp_path):
    """Telemetry-on cells gain a deterministic summary block + a span
    traceparent; the result digest is replay-stable (telemetry is
    deterministic, traceparent excluded), and per-lane flight-recorder
    JSONL lands under trace_dir with the traceparent in its header."""
    spec = _quick_spec()
    trace_dir = str(tmp_path / "flight")
    a = run_campaign(
        spec, out_path=str(tmp_path / "a.json"), telemetry=True,
        trace_dir=trace_dir,
    )
    b = run_campaign(spec, out_path=None, telemetry=True)
    cell = a["cells"][0]
    assert "telemetry" in cell and "traceparent" in cell
    tel = cell["telemetry"]["per_seed"]
    assert len(tel) == len(spec.seeds)
    assert tel[0]["rounds"] == cell["per_seed"]["rounds"][0]
    assert tel[0]["fault"]["dropped_frames"] >= 0
    # deterministic replay: same digest even though span ids differ
    # (unseeded runs draw random ids; the digest excludes them)
    assert a["result_digest"] == b["result_digest"]
    if not os.environ.get("CORRO_CAMPAIGN_SEED"):
        # unseeded id streams are random per run; under the seeded
        # replay env the same spec reproduces its traceparents instead
        assert cell["traceparent"] != b["cells"][0]["traceparent"]
    assert cell["traceparent"].startswith("00-")

    files = sorted(os.listdir(trace_dir))
    assert len(files) == len(spec.seeds)
    with open(os.path.join(trace_dir, files[0])) as f:
        head = json.loads(f.readline())
        rows = [json.loads(line) for line in f]
    assert head["kind"] == "flight_recorder"
    assert head["traceparent"] == cell["traceparent"]
    assert head["spec_hash"] == spec.spec_hash()
    assert len(rows) == head["rounds"]

    # telemetry-off cells are unchanged in shape AND in outcome digest
    # relative to each other (per_seed identical to the telemetry run)
    plain = run_campaign(spec, out_path=None)
    assert "telemetry" not in plain["cells"][0]
    assert plain["cells"][0]["per_seed"] == cell["per_seed"]


@pytest.mark.campaign
def test_spec_telemetry_field_hash_compat():
    """spec.telemetry serializes only when True, so every pre-ISSUE-5
    spec hash (committed baselines included) is unchanged."""
    import dataclasses

    spec = _quick_spec()
    on = dataclasses.replace(spec, telemetry=True)
    assert spec.spec_hash() != on.spec_hash()
    assert "telemetry" not in spec.to_dict()
    assert on.to_dict()["telemetry"] is True
    # round trip
    assert CampaignSpec.from_dict(on.to_dict()) == on
    # spec.telemetry drives the engine default
    art = run_campaign(on, out_path=None)
    assert "telemetry" in art["cells"][0]


@pytest.mark.campaign
def test_swim_churn_cells_band_detect_round():
    """Runner configs #2/#2b through the engine (the ROADMAP item): the
    membership cells run the on-device detection loop, band
    ``detect_round`` per seed, and a replay compares clean."""
    spec = swim_churn_64_spec(seeds=(0, 1), n=24)
    a = run_campaign(spec, out_path=None)
    cell = a["cells"][0]
    ps = cell["per_seed"]
    assert all(d >= 0 for d in ps["detect_round"])
    assert all(ps["converged"])
    assert all(f == 1.0 for f in ps["detected_fraction"])
    assert "false_positive_downs" in ps  # full-view extra
    assert "detect_round" in cell["bands"]
    assert cell["bands"]["detect_round"]["p99"] >= cell["bands"][
        "detect_round"
    ]["p50"]
    assert cell["all_converged"]
    # detect-round regressions trip the compare gate like any band
    b = run_campaign(spec, out_path=None)
    rep = compare(a, b)
    assert rep["verdict"] == "pass" and rep["identical_results"]

    # the partial-view tier compiles and detects at a CI-sized cluster
    art = run_campaign(
        swim_churn_partial_spec(seeds=(1,), n=96, max_rounds=600),
        out_path=None,
    )
    ps = art["cells"][0]["per_seed"]
    assert ps["detect_round"][0] >= 0
    assert "false_positive_downs" not in ps  # partial view has no N×N


@pytest.mark.campaign
def test_churn_builtin_specs_registered():
    assert builtin_spec("swim-churn-64").scenario["detect_membership"]
    assert builtin_spec("swim-churn-partial").scenario["kill_every"] == 3


@pytest.mark.campaign
def test_seeded_campaign_reproduces_traceparents(monkeypatch):
    """With CORRO_CAMPAIGN_SEED set, the whole artifact — traceparents
    included — replays identically (the tracing satellite's purpose)."""
    from corrosion_tpu import tracing

    monkeypatch.setenv("CORRO_CAMPAIGN_SEED", "777")
    try:
        spec = _quick_spec(seeds=(0,))
        a = run_campaign(spec, out_path=None)
        b = run_campaign(spec, out_path=None)
        assert a["cells"][0]["traceparent"] == b["cells"][0]["traceparent"]
        assert a["result_digest"] == b["result_digest"]
    finally:
        monkeypatch.delenv("CORRO_CAMPAIGN_SEED", raising=False)
        tracing.seed_trace_ids()


@pytest.mark.campaign
def test_cell_span_tree_shape():
    """cell → lanes → convergence: one campaign_cell root per cell, a
    lane child per seed, each with a convergence leaf."""
    from corrosion_tpu.tracing import TRACER

    # identity snapshot, not a length offset: TRACER.finished is a
    # BOUNDED deque, so earlier campaign-heavy tests (ISSUE 9 added
    # several) can evict entries and break positional slicing under
    # randomized test order.  Holding the `before` LIST keeps the old
    # spans alive for the test's duration, so a new span can never
    # reuse an evicted span's id()
    before = list(TRACER.finished)
    before_ids = {id(s) for s in before}
    spec = _quick_spec(seeds=(0, 1))
    art = run_campaign(spec, out_path=None)
    spans = [s for s in TRACER.finished if id(s) not in before_ids]
    del before
    cells = [s for s in spans if s.name == "campaign_cell"]
    lanes = [s for s in spans if s.name == "lane"]
    convs = [s for s in spans if s.name == "convergence"]
    assert len(cells) == 1 and len(lanes) == 2 and len(convs) == 2
    cell_span = cells[0]
    assert (
        cell_span.context.traceparent() == art["cells"][0]["traceparent"]
    )
    for lane in lanes:
        assert lane.context.trace_id == cell_span.context.trace_id
        assert lane.parent_span_id == cell_span.context.span_id
    for conv in convs:
        assert conv.context.trace_id == cell_span.context.trace_id
