"""Protocol-variant campaign axis (ISSUE 11): the protocol-frontier
through the engine — wire-byte AND ordering-invariant bands recorded
deterministically, loud refusals on cells that can't measure the axis,
and the frontier rung's reduction record."""

import dataclasses

import pytest

from corrosion_tpu.campaign.engine import run_campaign
from corrosion_tpu.campaign.spec import (
    CampaignSpec,
    protocol_frontier_spec,
)

pytestmark = pytest.mark.campaign


def _mini_frontier(protos=("baseline", "push-pull")):
    """The builtin frontier shrunk to the tier-1 budget: 2 cells on the
    flat-lossy family, 2 seeds, 48 nodes."""
    spec = protocol_frontier_spec(seeds=(0, 1), n=48, max_rounds=400)
    return dataclasses.replace(
        spec, grid={
            "proto_family": list(protos),
            "topo_family": ["flat-lossy"],
        },
    )


def test_frontier_cells_band_rounds_and_wire_bytes():
    art = run_campaign(_mini_frontier(), out_path=None)
    assert len(art["cells"]) == 2
    by_proto = {}
    for cell in art["cells"]:
        assert cell["all_converged"], cell["params"]
        ps = cell["per_seed"]
        assert len(ps["wire_bytes"]) == 2
        assert all(w > 0 for w in ps["wire_bytes"])
        assert cell["bands"]["rounds"]["p50"] > 0
        by_proto[cell["params"]["proto_family"]] = cell
    assert set(by_proto) == {"baseline", "push-pull"}
    # the exchange's cost axis: push-pull transmits more wire
    assert (
        by_proto["push-pull"]["bands"]["wire_bytes"]["p50"]
        > by_proto["baseline"]["bands"]["wire_bytes"]["p50"]
    )
    # non-ordering cells carry no violation band (digest compatibility)
    assert "order_violations" not in by_proto["baseline"]["per_seed"]


def test_frontier_digest_stable_across_runs_and_telemetry():
    spec = _mini_frontier()
    a = run_campaign(spec, out_path=None)
    b = run_campaign(spec, out_path=None)
    assert a["result_digest"] == b["result_digest"]
    c = run_campaign(spec, out_path=None, telemetry=True)
    assert c["result_digest"] == a["result_digest"]


def test_ordering_cells_band_the_invariant():
    """An enforced-ordering cell records the on-device delivery-order
    violation totals per lane (all zero) and bands them; the unchecked
    negative control records NONZERO totals — the invariant is a
    first-class campaign metric, regression-gated like any band."""
    art = run_campaign(
        _mini_frontier(("lab-ordered", "lab-ordered-broken")),
        out_path=None,
    )
    cells = {c["params"]["proto_family"]: c for c in art["cells"]}
    enforced = cells["lab-ordered"]
    assert enforced["all_converged"]
    assert enforced["per_seed"]["order_violations"] == [0, 0]
    assert enforced["bands"]["order_violations"]["max"] == 0.0
    broken = cells["lab-ordered-broken"]
    assert all(v > 0 for v in broken["per_seed"]["order_violations"])
    assert broken["bands"]["order_violations"]["min"] > 0


def test_proto_keys_refused_on_serving_cells():
    spec = CampaignSpec(
        name="t",
        scenario={"n_nodes": 3, "serving": True,
                  "proto_family": "push-pull"},
    )
    with pytest.raises(ValueError, match="proto_family"):
        run_campaign(spec, out_path=None)
    spec2 = CampaignSpec(
        name="t",
        scenario={"n_nodes": 3, "serving": True, "ordering": "fifo"},
    )
    with pytest.raises(ValueError, match="ordering"):
        run_campaign(spec2, out_path=None)


def test_proto_keys_refused_on_detect_cells():
    spec = CampaignSpec(
        name="t",
        scenario={
            "n_nodes": 16, "n_payloads": 8, "swim_full_view": True,
            "detect_membership": True, "kill_every": 3,
            "proto_family": "push-pull",
        },
    )
    with pytest.raises(ValueError, match="proto_family"):
        run_campaign(spec, out_path=None)
    spec2 = CampaignSpec(
        name="t",
        scenario={
            "n_nodes": 16, "n_payloads": 8, "swim_full_view": True,
            "detect_membership": True, "kill_every": 3,
            "sync_cadence": "eager",
        },
    )
    with pytest.raises(ValueError, match="sync_cadence"):
        run_campaign(spec2, out_path=None)


def test_frontier_rung_reduction_record():
    """`config_protocol_frontier` reduces the campaign to the bench
    record: per (topology, protocol family) rounds/wire plus ratios vs
    the baseline family (the storm-scale sampler cell is exercised at a
    tier-1-sized shape)."""
    from corrosion_tpu.sim.runner import config_protocol_frontier

    rec = config_protocol_frontier(
        seed=0, n_nodes=48, n_seeds=2, max_rounds=400,
        # tier-1 budget: one topology, two variants, and a small packed
        # storm still exercise every path of the rung end-to-end
        proto_families=("baseline", "lab-ordered"),
        topo_families=("flat-lossy",),
        sampler_storm_nodes=512, sampler_storm_payloads=64,
    )
    assert rec["converged"]
    for fam, d in rec["families"].items():
        assert "baseline" in d, fam
        assert "rounds_ratio" in d["lab-ordered"], fam
        assert "wire_ratio" in d["lab-ordered"], fam
        assert d["lab-ordered"]["order_violations_max"] == 0.0
    storm = rec["sampler_storm"]
    assert storm["sampler"] == "peerswap"
    assert storm["converged"]
    assert storm["n_nodes"] == 512
