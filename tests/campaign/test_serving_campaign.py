"""Host-serving campaign cells + the budgeted parity-lanes knob
(ISSUE 8): latency bands through the band machinery, skeleton-only
replay digests, per-lane host flight artifacts, and the parity_seeds
satellite."""

import dataclasses
import json
import os

import pytest

from corrosion_tpu.campaign.engine import (
    _lane_trace_path,
    run_campaign,
)
from corrosion_tpu.campaign.report import BAND_METRICS, compare
from corrosion_tpu.campaign.spec import (
    CampaignSpec,
    builtin_spec,
    fault_parity_3node_spec,
    serving_3node_spec,
)
from corrosion_tpu.faults import FaultEvent


def _small_serving_spec(seeds=(0,), **scenario_over):
    base = serving_3node_spec(seeds=seeds)
    scenario = {**base.scenario, "n_writes": 12, "rate_hz": 0.0}
    scenario.update(scenario_over)
    return dataclasses.replace(base, scenario=scenario)


@pytest.mark.campaign
def test_spec_hash_stability_and_serialization():
    """New fields never shift existing spec hashes: parity defaults and
    serving keys serialize only when set."""
    d = fault_parity_3node_spec().to_dict()
    assert "parity_seeds" not in d and "parity_budget_s" not in d
    # the committed parity baseline still matches its spec
    base = json.load(
        open("doc/experiments/CAMPAIGN_BASELINE_fault-parity-3node.json")
    )
    assert fault_parity_3node_spec().spec_hash() == base["spec_hash"]

    tuned = dataclasses.replace(
        fault_parity_3node_spec(), parity_seeds=3, parity_budget_s=5.0
    )
    d2 = tuned.to_dict()
    assert d2["parity_seeds"] == 3 and d2["parity_budget_s"] == 5.0
    rt = CampaignSpec.from_dict(d2)
    assert rt.parity_seeds == 3 and rt.parity_budget_s == 5.0

    sv = builtin_spec("serving-3node")
    assert sv.serving({}) and sv.spec_hash() == serving_3node_spec().spec_hash()
    assert sv.serving_faults({"use_faults": 0}) is False
    assert sv.serving_faults({"use_faults": 1}) is True
    assert "n_writes" in sv.serving_params({})


@pytest.mark.campaign
def test_serving_cells_band_latency_and_stay_consistent(tmp_path):
    spec = _small_serving_spec()
    trace_dir = str(tmp_path / "flight")
    art = run_campaign(
        spec, out_path=str(tmp_path / "art.json"), telemetry=True,
        trace_dir=trace_dir,
    )
    assert len(art["cells"]) == 2  # use_faults ∈ {0, 1}
    for cell in art["cells"]:
        assert cell["kind"] == "host-serving"
        assert cell["all_converged"], cell
        assert all(cell["per_seed"]["consistent"])
        for m in (
            "publish_visible_p50_s", "publish_visible_p95_s",
            "publish_visible_p99_s",
        ):
            assert m in BAND_METRICS
            band = cell["bands"][m]
            assert band["p99"] is not None and band["p99"] > 0
        assert cell["bands"]["throughput_wps"]["p50"] > 0
        # per-lane host flight artifact, sim naming scheme
        for seed in spec.seeds:
            path = _lane_trace_path(
                trace_dir, spec, cell["cell_index"], seed
            )
            assert os.path.exists(path)
            head = json.loads(open(path).readline())
            assert head["tier"] == "host"
            assert head["campaign"] == spec.name
        # the telemetry summary rode into the artifact
        assert cell["telemetry"]["per_seed"][0]["stages"]["visible"] > 0
    faulted = next(
        c for c in art["cells"] if c["params"]["use_faults"] == 1
    )
    assert faulted["use_faults"] and faulted["plan_horizon"] > 0

    # the serving runs joined the cell's trace tree: serving_loadgen
    # spans share the campaign_cell span's trace id (ISSUE 8 acceptance)
    from corrosion_tpu.tracing import TRACER, extract

    ctx = extract(art["cells"][0]["traceparent"])
    serving_spans = TRACER.find(
        name="serving_loadgen", trace_id=ctx.trace_id
    )
    assert serving_spans, "serving spans must parent under the cell span"
    assert all(
        s.parent_span_id is not None for s in serving_spans
    )

    # serving lanes are wall-clock measurements: the digest covers only
    # the experiment identity, so a re-run replays it exactly and
    # compare certifies identical_results
    art2 = run_campaign(spec, out_path=None)
    assert art2["result_digest"] == art["result_digest"]
    rep = compare(art, art2)
    assert rep["verdict"] == "pass", rep["regressions"]
    assert rep["identical_results"]


@pytest.mark.campaign
def test_serving_report_cli_includes_latency_bands(tmp_path, capsys):
    from corrosion_tpu.cli.main import main

    spec = _small_serving_spec()
    out = str(tmp_path / "art.json")
    run_campaign(spec, out_path=out, telemetry=True)
    rc = main(
        ["sim", "campaign", "report", "--in", out, "--telemetry"]
    )
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    for cell in rep["cells"]:
        assert cell["kind"] == "host-serving"
        assert cell["round_path"] == "host"
        assert cell["consistent"] == [True]
        assert cell["bands"]["publish_visible_p99_s"]["p99"] > 0
        assert "telemetry" in cell


def _quick_parity_spec(seeds, **kw):
    return CampaignSpec(
        name="parity-lanes-smoke",
        scenario={
            "n_nodes": 3, "n_payloads": 4, "fanout": 2,
            "sync_interval_rounds": 4, "n_delay_slots": 4,
            "inject_every": 1,
        },
        events=(
            FaultEvent("loss", 0, 8, p=0.3),
            FaultEvent("partition", 2, 6, src=1, dst=0),
        ),
        seeds=tuple(seeds),
        max_rounds=200,
        host_parity=True,
        **kw,
    )


@pytest.mark.campaign
def test_parity_seeds_replays_k_lanes():
    """Satellite: parity_seeds=2 replays two seed lanes and records the
    lane count; legacy top-level keys stay readable."""
    art = run_campaign(
        _quick_parity_spec((0, 1), parity_seeds=2, parity_budget_s=120.0),
        out_path=None,
    )
    hp = art["cells"][0]["host_parity"]
    assert hp["lanes_requested"] == 2
    assert hp["lanes_run"] == 2
    assert len(hp["lanes"]) == 2
    assert {l["plan_seed"] for l in hp["lanes"]} == {0, 1}
    assert hp["heads_match"] == all(l["heads_match"] for l in hp["lanes"])
    # legacy single-point keys = first lane
    assert hp["plan_seed"] == hp["lanes"][0]["plan_seed"]
    assert "heads" in hp and "converged" in hp


@pytest.mark.campaign
def test_parity_budget_bounds_extra_lanes():
    """A zero budget still runs the FIRST lane (the pre-knob contract);
    the budget bounds only the extras — and the truncation is visible."""
    art = run_campaign(
        _quick_parity_spec((0, 1, 2), parity_seeds=3, parity_budget_s=0.0),
        out_path=None,
    )
    hp = art["cells"][0]["host_parity"]
    assert hp["lanes_requested"] == 3
    assert hp["lanes_run"] == 1
    assert len(hp["lanes"]) == 1
