"""Campaign engine + spec + report: replay identity, resumable
artifacts, wall budgeting, regression bands, and the CLI surface.

Marker discipline (pytest.ini): ``campaign`` tags the subsystem; the
tier-1 quick smoke (3-node, 4 seeds, CPU) runs in the default
``-m 'not slow'`` selection, and ``-m "campaign and slow"`` is the
nightly seed-swept entry over the parity plan (≥8 seeds + host-tier
parity points + the CLI run/compare round trip)."""

import json
import os
import subprocess
import sys

import pytest

from corrosion_tpu.campaign.engine import run_campaign
from corrosion_tpu.campaign.report import artifact_digest, bands, compare
from corrosion_tpu.campaign.spec import (
    CampaignSpec,
    builtin_spec,
    fault_parity_3node_spec,
    load_spec,
    save_spec,
)
from corrosion_tpu.faults import FaultEvent

CLI = [sys.executable, "-m", "corrosion_tpu.cli.main"]
ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}


def _quick_spec(seeds=(0, 1, 2, 3), **kw):
    """The tier-1 campaign shape: 3 nodes, tiny payload set, short
    horizon — one compile, seconds of wall."""
    kw.setdefault("max_rounds", 200)
    return CampaignSpec(
        name="quick-smoke",
        scenario={
            "n_nodes": 3, "n_payloads": 8, "fanout": 2,
            "sync_interval_rounds": 4, "n_delay_slots": 4,
            "inject_every": 1,
        },
        events=(
            FaultEvent("loss", 0, 10, p=0.3),
            FaultEvent("partition", 2, 8, src=1, dst=0),
        ),
        seeds=tuple(seeds),
        **kw,
    )


# -- spec ------------------------------------------------------------------


def test_spec_roundtrip_hash_and_grid():
    spec = _quick_spec(grid={"fanout": [2, 3], "loss": [0.0, 0.1]})
    d = spec.to_dict()
    again = CampaignSpec.from_dict(d)
    assert again == spec
    assert again.spec_hash() == spec.spec_hash()
    # grid expansion is a pure sorted-key cartesian product
    cells = spec.cells()
    assert cells == [
        {"fanout": 2, "loss": 0.0}, {"fanout": 2, "loss": 0.1},
        {"fanout": 3, "loss": 0.0}, {"fanout": 3, "loss": 0.1},
    ]
    # grid keys route to the right layer
    assert spec.sim_config(cells[2]).fanout == 3
    assert spec.topo(cells[1]).loss == 0.1
    # the hash moves with ANY field
    import dataclasses

    assert dataclasses.replace(spec, seeds=(9,)).spec_hash() != spec.spec_hash()
    # topology keys riding a flat `scenario` dict still reach Topology
    # (and are stripped from SimConfig kwargs) — a spec file naming
    # loss=0.2 must never silently measure a loss-free network
    flat = CampaignSpec(
        name="flat",
        scenario={"n_nodes": 3, "n_payloads": 4, "loss": 0.2},
    )
    assert flat.topo({}).loss == 0.2
    assert flat.sim_config({}).n_nodes == 3
    with pytest.raises(ValueError, match="both scenario and topology"):
        CampaignSpec(
            name="dup", scenario={"n_nodes": 3, "n_payloads": 4,
                                  "loss": 0.2},
            topology={"loss": 0.1},
        ).topo({})


def test_spec_file_roundtrip(tmp_path):
    spec = fault_parity_3node_spec(seeds=range(4))
    path = tmp_path / "spec.json"
    save_spec(spec, str(path))
    assert load_spec(str(path)) == spec


# -- report ----------------------------------------------------------------


def test_bands_and_compare_verdicts():
    b = bands([30, 31, 32, 33, 40])
    assert b["p50"] == 32 and b["max"] == 40 and b["min"] == 30
    assert b["p99"] in (33, 40)  # 'lower' method: an observed value

    def art(p99):
        cell = {
            "params": {"fanout": 2},
            "per_seed": {"rounds": [p99 - 1, p99]},
            "bands": {
                "rounds": {"p50": p99 - 1, "p95": p99, "p99": p99},
                "p99_node_convergence_round": {
                    "p50": 10, "p95": 11, "p99": 12
                },
            },
            "all_converged": True,
        }
        return {
            "spec_hash": "x", "cells": [cell],
            "result_digest": artifact_digest([cell]),
        }

    # within tolerance (10% + 2 rounds): pass
    rep = compare(art(30), art(33))
    assert rep["verdict"] == "pass" and not rep["regressions"]
    # beyond tolerance: regress, and the offending band is named
    rep = compare(art(30), art(40))
    assert rep["verdict"] == "regress"
    assert any(r["metric"] == "rounds.p99" for r in rep["regressions"])
    # a candidate missing a baseline cell regresses (budget-starved
    # re-runs must not silently pass)
    empty = {"spec_hash": "x", "cells": [], "result_digest": "d"}
    assert compare(art(30), empty)["verdict"] == "regress"
    # improvements never regress
    assert compare(art(40), art(30))["verdict"] == "pass"


# -- engine ----------------------------------------------------------------


@pytest.mark.campaign
def test_quick_smoke_replay_reproduces_artifact_digest(tmp_path):
    """Tier-1 quick smoke (3-node, 4 seeds, CPU): the campaign runs,
    bands come out, and a replay of the same content hash reproduces the
    result digest exactly — zero regressions by construction."""
    spec = _quick_spec()
    a = run_campaign(spec, out_path=str(tmp_path / "a.json"))
    b = run_campaign(spec, out_path=str(tmp_path / "b.json"))
    assert a["spec_hash"] == b["spec_hash"] == spec.spec_hash()
    assert a["result_digest"] == b["result_digest"]
    cell = a["cells"][0]
    assert cell["all_converged"], cell["per_seed"]
    assert cell["bands"]["rounds"]["p99"] >= cell["bands"]["rounds"]["p50"]
    assert len(cell["per_seed"]["rounds"]) == 4
    assert cell["wall_verdict"] == "ok"
    rep = compare(a, b)
    assert rep["verdict"] == "pass" and rep["identical_results"]
    assert not rep["regressions"]


@pytest.mark.campaign
def test_resume_and_wall_budget(tmp_path):
    """A zero budget skips every cell; the resumed run completes only
    the remainder and the final artifact matches an unbudgeted run's
    digest (cells are deterministic, so resume composes)."""
    spec = _quick_spec(seeds=(0, 1), grid={"fanout": [2, 3]})
    out = str(tmp_path / "art.json")
    starved = run_campaign(spec, out_path=out, wall_budget_s=0.0)
    assert starved["skipped_cells"] == [0, 1]
    assert starved["cells"] == []
    resumed = run_campaign(spec, out_path=out)  # no budget: completes
    assert resumed["skipped_cells"] == []
    assert [c["cell_index"] for c in resumed["cells"]] == [0, 1]
    # the artifact on disk is the resumed one
    with open(out) as f:
        on_disk = json.load(f)
    assert on_disk["result_digest"] == resumed["result_digest"]
    # a fresh no-resume run agrees bit-for-bit on the deterministic part
    fresh = run_campaign(spec, out_path=None)
    assert fresh["result_digest"] == resumed["result_digest"]


@pytest.mark.campaign
def test_campaign_selects_packed_path_for_fault_cells(tmp_path):
    """ISSUE 4 acceptance: a fault campaign whose cells sit inside the
    bitpack envelope runs the PACKED round kernels (recorded per cell as
    ``round_path`` — dense fallbacks visible, not silent), the replay
    digest is unchanged on re-run across the path switch, and forcing
    the dense path reproduces identical per-seed outcomes."""
    import dataclasses

    spec = CampaignSpec(
        name="packed-fault-smoke",
        scenario={
            "n_nodes": 16, "n_payloads": 64, "n_writers": 2,
            "chunks_per_version": 2, "fanout": 2,
            "sync_interval_rounds": 4, "n_delay_slots": 4,
            "rate_limit_bytes_round": None, "sync_budget_bytes": None,
            "packed_min_cells": 0, "inject_every": 1,
        },
        events=(
            FaultEvent("loss", 0, 10, p=0.3),
            FaultEvent("partition", 2, 8, src=1, dst=0),
        ),
        seeds=(0, 1),
        max_rounds=300,
    )
    a = run_campaign(spec, out_path=str(tmp_path / "a.json"))
    cell = a["cells"][0]
    assert cell["round_path"] == "packed"
    assert cell["all_converged"], cell["per_seed"]
    # determinism across the path switch: the replay digest holds
    b = run_campaign(spec, out_path=None)
    assert a["result_digest"] == b["result_digest"]
    # dense forcing: same per-seed trajectories, path recorded as dense
    dense = run_campaign(
        dataclasses.replace(
            spec,
            scenario={**spec.scenario, "allow_packed": False},
        ),
        out_path=None,
    )
    dcell = dense["cells"][0]
    assert dcell["round_path"] == "dense"
    assert dcell["per_seed"] == cell["per_seed"]


# -- nightly (slow) --------------------------------------------------------


@pytest.mark.campaign
@pytest.mark.slow
def test_nightly_seed_swept_parity_plan(tmp_path):
    """The `-m "campaign and slow"` nightly entry: the 3-node
    fault-parity plan at 8 seeds WITH host-tier parity points, then the
    CLI run/compare round trip on the same spec — `sim campaign run`
    twice must compare to a zero-regression pass."""
    import dataclasses

    spec = dataclasses.replace(
        fault_parity_3node_spec(seeds=range(8)), host_parity=True
    )
    art = run_campaign(spec, out_path=str(tmp_path / "nightly.json"))
    cell = art["cells"][0]
    assert cell["all_converged"]
    hp = cell["host_parity"]
    assert hp["heads_match"], hp

    # CLI surface: run twice (resumable artifacts at distinct paths),
    # compare must pass with identical digests
    spec_path = tmp_path / "spec.json"
    save_spec(dataclasses.replace(spec, host_parity=False), str(spec_path))
    outs = []
    for name in ("base.json", "cand.json"):
        out = str(tmp_path / name)
        r = subprocess.run(
            [*CLI, "sim", "campaign", "run", "--spec", str(spec_path),
             "--out", out],
            capture_output=True, text=True, env=ENV, timeout=600,
        )
        assert r.returncode == 0, r.stderr
        outs.append(out)
    r = subprocess.run(
        [*CLI, "sim", "campaign", "compare", "--baseline", outs[0],
         "--candidate", outs[1]],
        capture_output=True, text=True, env=ENV, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(r.stdout)
    assert rep["verdict"] == "pass" and not rep["regressions"]
    assert rep["identical_results"]


@pytest.mark.campaign
def test_sharded_cells_keep_digest_and_record_mesh(tmp_path):
    """mesh_devices (ISSUE 7): a sharded campaign run is a RUN-CONFIG —
    the result digest is byte-identical to the unsharded run of the
    same spec (sharding partitions the math, never changes it), the
    spec hash is untouched, and each cell records the realized mesh.
    The 3-node quick spec degrades to a 3-device mesh (the largest
    divisor of the node axis — cells never pad)."""
    spec = _quick_spec()
    plain = run_campaign(spec)
    sharded = run_campaign(spec, mesh_devices=8, resume=False)
    assert sharded["spec_hash"] == plain["spec_hash"]
    assert sharded["result_digest"] == plain["result_digest"]
    cell = sharded["cells"][0]
    assert cell["n_devices"] == 3
    assert cell["mesh"]["axes"] == {"nodes": 3}
    assert cell["round_path"] in ("dense", "packed")
    assert plain["cells"][0]["mesh"] is None
