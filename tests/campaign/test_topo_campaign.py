"""Topology/sampler campaign axes (ISSUE 9): the peer-sampler frontier
through the engine — wire-byte bands recorded deterministically, the
replay digest stable across runs AND across the --telemetry run-config,
churn axes merging into every lane's plan, and the frontier rung's
reduction record."""

import dataclasses

import pytest

from corrosion_tpu.campaign.engine import run_campaign
from corrosion_tpu.campaign.spec import (
    CampaignSpec,
    peer_sampler_frontier_spec,
)

pytestmark = pytest.mark.campaign


def _mini_frontier():
    """The builtin frontier shrunk to the tier-1 budget: 2 cells
    (uniform vs peerswap on the WAN family), 2 seeds, 48 nodes."""
    spec = peer_sampler_frontier_spec(seeds=(0, 1), n=48, max_rounds=300)
    return dataclasses.replace(
        spec, grid={
            "peer_sampler": ["uniform", "peerswap"],
            "topo_family": ["wan-3x2"],
        },
    )


def test_frontier_cells_band_rounds_and_wire_bytes():
    art = run_campaign(_mini_frontier(), out_path=None)
    assert len(art["cells"]) == 2
    for cell in art["cells"]:
        assert cell["all_converged"], cell["params"]
        ps = cell["per_seed"]
        assert len(ps["wire_bytes"]) == 2
        assert all(w > 0 for w in ps["wire_bytes"])
        assert cell["bands"]["wire_bytes"]["p50"] > 0
        assert cell["bands"]["rounds"]["p50"] > 0
    samplers = {c["params"]["peer_sampler"] for c in art["cells"]}
    assert samplers == {"uniform", "peerswap"}


def test_frontier_digest_stable_and_telemetry_invariant():
    """measure_wire makes wire bytes part of the replay identity: the
    digest must reproduce across runs and must NOT move when the
    --telemetry run-config is flipped (the ISSUE 5 contract extended
    over the internally-armed recorder)."""
    spec = _mini_frontier()
    a = run_campaign(spec, out_path=None)
    b = run_campaign(spec, out_path=None)
    assert a["result_digest"] == b["result_digest"]
    c = run_campaign(spec, out_path=None, telemetry=True)
    assert c["result_digest"] == a["result_digest"]
    # the telemetry block itself only appears under the flag
    assert "telemetry" not in a["cells"][0]
    assert "telemetry" in c["cells"][0]


def test_churn_axis_runs_and_digests():
    """A flash-crowd churn cell: the generated range-selector crash
    events merge into the lane plans (plan_horizon covers the join) and
    the ensemble converges after the cold join."""
    spec = CampaignSpec(
        name="churn-smoke",
        scenario={
            "n_nodes": 32, "n_payloads": 16, "fanout": 2,
            "sync_interval_rounds": 4, "inject_every": 1,
            "churn": "flash-crowd", "churn_frac": 0.25, "churn_round": 6,
        },
        seeds=(0, 1),
        max_rounds=400,
    )
    art = run_campaign(spec, out_path=None)
    cell = art["cells"][0]
    assert cell["plan_horizon"] == 7  # join at 6 ⇒ horizon end+1
    assert cell["all_converged"]
    again = run_campaign(spec, out_path=None)
    assert again["result_digest"] == art["result_digest"]


def test_issue9_axes_refuse_unsupported_cells():
    """The loud-refusal rule: an ISSUE 9 axis on a cell kind that can't
    measure it must raise, never silently band nothing / the wrong
    number."""
    base = {"n_nodes": 8, "n_payloads": 1, "swim_full_view": True,
            "detect_membership": 1, "kill_every": 3}
    with pytest.raises(ValueError, match="measure_wire"):
        run_campaign(
            CampaignSpec(name="x", scenario={**base, "measure_wire": 1}),
            out_path=None,
        )
    with pytest.raises(ValueError, match="churn"):
        run_campaign(
            CampaignSpec(
                name="x", scenario={**base, "churn": "flash-crowd"}
            ),
            out_path=None,
        )
    with pytest.raises(ValueError, match="trace_every"):
        run_campaign(
            CampaignSpec(
                name="x",
                scenario={"n_nodes": 8, "n_payloads": 8,
                          "measure_wire": 1, "trace_every": 2},
            ),
            out_path=None,
        )
    with pytest.raises(ValueError, match="host-serving"):
        run_campaign(
            CampaignSpec(
                name="x",
                scenario={"n_nodes": 3, "serving": 1, "measure_wire": 1},
            ),
            out_path=None,
        )


def test_frontier_rung_record_shape():
    """`config_peer_sampler_frontier` (the bench rung) reduces the
    campaign to per-family sampler comparisons with ratios."""
    from corrosion_tpu.sim.runner import config_peer_sampler_frontier

    m = config_peer_sampler_frontier(seed=0, n_nodes=48, n_seeds=2,
                                     max_rounds=300)
    assert m["converged"]
    assert set(m["families"]) == {"wan-3x2", "hetero-degree"}
    for fam in m["families"].values():
        assert fam["uniform"]["rounds_p50"] > 0
        assert fam["peerswap"]["wire_bytes_p50"] > 0
        assert fam["rounds_ratio"] > 0
        assert fam["wire_ratio"] > 0
    assert m["spec_hash"] and m["result_digest"]
