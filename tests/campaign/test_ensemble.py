"""Ensemble determinism (ISSUE 3 acceptance): a vmapped K-seed run
produces byte-identical per-lane state and metrics to K sequential
single-seed runs — the sequential-equivalence guarantee the campaign
engine's statistics stand on."""

import dataclasses

import numpy as np
import pytest

from corrosion_tpu.campaign.ensemble import run_seed_ensemble
from corrosion_tpu.campaign.spec import fault_parity_3node_spec
from corrosion_tpu.sim.faults import compile_plan, run_fault_plan
from corrosion_tpu.sim.round import new_sim, run_to_convergence
from corrosion_tpu.sim.state import uniform_payloads

LANE_FIELDS = (
    "t", "have", "alive", "heads", "relay_left", "incarnation",
    "sync_backoff", "gap_lo", "gap_hi",
)


@pytest.mark.campaign
def test_vmapped_4seed_ensemble_matches_sequential_runs():
    """The acceptance gate: 4 vmapped lanes of the fault-parity plan ==
    4 sequential `run_fault_plan` runs, byte-for-byte, under
    JAX_PLATFORMS=cpu (conftest forces it)."""
    seeds = (0, 1, 2, 3)
    spec = fault_parity_3node_spec(seeds=seeds)
    cfg, topo = spec.sim_config({}), spec.topo({})
    meta = uniform_payloads(cfg, inject_every=1)
    plan = spec.fault_plan({}, seed=seeds[0])

    finals, metrics = run_seed_ensemble(
        plan, cfg, topo, meta, seeds, max_rounds=spec.max_rounds
    )
    for k, s in enumerate(seeds):
        fp = compile_plan(dataclasses.replace(plan, seed=int(s)), cfg, topo)
        solo, solo_m = run_fault_plan(
            new_sim(cfg, int(s)), meta, cfg, topo, fp, spec.max_rounds
        )
        for name in LANE_FIELDS:
            lane = np.asarray(getattr(finals, name)[k])
            ref = np.asarray(getattr(solo, name))
            assert (lane == ref).all(), (
                f"lane {k} (seed {s}) field {name} diverged from the "
                f"sequential run"
            )
        assert (
            np.asarray(metrics.converged_at[k])
            == np.asarray(solo_m.converged_at)
        ).all()
        assert (
            np.asarray(metrics.coverage_at[k])
            == np.asarray(solo_m.coverage_at)
        ).all()


@pytest.mark.campaign
def test_fault_free_ensemble_matches_run_to_convergence():
    """Without a plan the lanes ride `run_to_convergence` (same packed/
    dense dispatch as a solo run) and stay byte-identical per lane."""
    spec = fault_parity_3node_spec(seeds=(7, 8))
    cfg, topo = spec.sim_config({}), spec.topo({})
    meta = uniform_payloads(cfg, inject_every=1)
    finals, _ = run_seed_ensemble(
        None, cfg, topo, meta, (7, 8), max_rounds=200
    )
    for k, s in enumerate((7, 8)):
        solo, _ = run_to_convergence(new_sim(cfg, s), meta, cfg, topo, 200)
        assert int(finals.t[k]) == int(solo.t)
        assert (np.asarray(finals.have[k]) == np.asarray(solo.have)).all()
