"""Runtime twin of corrolint CT004 (ISSUE 10 satellite): a campaign
meta key that shadows a real SimConfig field must be DECLARED in
``spec.FORWARDED_META_KEYS`` or ``sim_config()`` refuses loudly —
reconstructing the ISSUE 9 ``n_writers`` incident, where the undeclared
collision silently stripped the key from sim cells and the frontier
campaign measured a 1-writer workload for a full PR."""

import pytest

import corrosion_tpu.campaign.spec as spec_mod
from corrosion_tpu.campaign.spec import (
    FORWARDED_META_KEYS,
    CampaignSpec,
    builtin_spec,
)


def test_n_writers_reaches_sim_config():
    """The ISSUE 9 fix, now guarded: the frontier spec's declared
    4-writer workload must land in the cell's SimConfig."""
    spec = builtin_spec("peer-sampler-frontier")
    cfg = spec.sim_config(spec.cells()[0])
    assert cfg.n_writers == 4


def test_forwarded_keys_are_real_meta_and_config_keys():
    """The allowlist only makes sense for keys living in BOTH worlds —
    an entry that stops being a meta key or a SimConfig field is stale
    and should be removed."""
    from corrosion_tpu.sim.state import SimConfig

    fields = SimConfig.__dataclass_fields__
    for k in FORWARDED_META_KEYS:
        assert k in spec_mod._SCENARIO_META_KEYS + spec_mod._TOPOLOGY_KEYS
        assert k in fields


def test_undeclared_shadow_refused(monkeypatch):
    """Incident reconstruction: introduce a meta key colliding with a
    real SimConfig field WITHOUT declaring it forwarded — building any
    sim cell's config must refuse, not silently strip (pre-guard, the
    key would vanish and the cell would measure the wrong workload)."""
    monkeypatch.setattr(
        spec_mod,
        "_SCENARIO_META_KEYS",
        spec_mod._SCENARIO_META_KEYS + ("fanout",),
    )
    spec = CampaignSpec(
        name="guard-test",
        scenario={"n_nodes": 3, "n_payloads": 4, "fanout": 2},
    )
    with pytest.raises(ValueError, match="fanout.*FORWARDED_META_KEYS"):
        spec.sim_config(spec.cells()[0])


def test_declared_forwarding_heals_the_refusal(monkeypatch):
    """Same collision, but DECLARED: the key must flow into SimConfig
    (the allowlist is a forwarding contract, not a mute button)."""
    monkeypatch.setattr(
        spec_mod,
        "_SCENARIO_META_KEYS",
        spec_mod._SCENARIO_META_KEYS + ("fanout",),
    )
    monkeypatch.setattr(
        spec_mod,
        "FORWARDED_META_KEYS",
        spec_mod.FORWARDED_META_KEYS + ("fanout",),
    )
    spec = CampaignSpec(
        name="guard-test",
        scenario={"n_nodes": 3, "n_payloads": 4, "fanout": 2},
    )
    assert spec.sim_config(spec.cells()[0]).fanout == 2
