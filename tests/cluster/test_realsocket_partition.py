"""Partition + heal on REAL sockets (VERDICT r4 missing #3).

The reference's Antithesis rig partitions real containers and then
asserts the bookkeeping property after heal
(.antithesis/config/docker-compose.yaml:1-45,
.antithesis/client/test-templates/check_bookkeeping.py:6-27: every
node's generated sync shows need == 0 ∧ partial_need == 0 and all heads
agree).  The sim tier already has partition-heal distribution checks;
this is the REAL-socket tier: agents on loopback UDP/TCP with a
FaultInjector (transport.faults) standing in for the rig's network
faults — partitions block egress on both sides, loss degrades links,
and after heal the campaign asserts check_bookkeeping verbatim.
"""

import asyncio
import tempfile

import pytest

from corrosion_tpu.agent.agent import Agent
from corrosion_tpu.agent.config import Config
from corrosion_tpu.agent.transport import FaultInjector, UdpTcpTransport
from corrosion_tpu.testing import TEST_SCHEMA, fast_perf


async def _boot(n: int, tmp: str):
    transports = [UdpTcpTransport() for _ in range(n)]
    addrs = [await t.start() for t in transports]
    agents = []
    for i, t in enumerate(transports):
        cfg = Config(
            db_path=f"{tmp}/n{i}.db",
            gossip_addr=addrs[i],
            bootstrap=[a for a in addrs if a != addrs[i]],
            perf=fast_perf(),
        )
        agent = Agent(cfg, t)
        agent.store.execute_schema(TEST_SCHEMA)
        agents.append(agent)
    for a in agents:
        await a.start()
    return agents, addrs


def _check_bookkeeping(agents) -> bool:
    """check_bookkeeping.py:6-27 verbatim: all needs empty, no partials,
    all heads equal, every node knows every writer's head."""
    heads = {}
    for agent in agents:
        s = agent.sync_state()
        if s.need or s.partial_need:
            return False
        for booked in agent.bookie.by_actor.values():
            if booked.partials:
                return False
        for actor, head in s.heads.items():
            if heads.setdefault(actor, head) != head:
                return False
    for agent in agents:
        s = agent.sync_state()
        for w, h in heads.items():
            if w != agent.actor_id and s.heads.get(w) != h:
                return False
    return True


async def _wait_bookkeeping(agents, timeout: float) -> bool:
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if _check_bookkeeping(agents):
            return True
        await asyncio.sleep(0.1)
    return _check_bookkeeping(agents)


def test_partition_heal_on_real_sockets():
    """Split 4 real-socket agents 2|2, write on BOTH sides of the split,
    heal, and assert the check_bookkeeping property plus row equality."""

    async def body():
        with tempfile.TemporaryDirectory() as tmp:
            agents, addrs = await _boot(4, tmp)
            try:
                # pre-partition warmup write so the full mesh is live
                agents[0].exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (0, 'warm')", ())]
                )
                assert await _wait_bookkeeping(agents, 15)

                # partition {0,1} | {2,3}: egress blocked on BOTH sides,
                # the way the rig firewalls each container
                side_a, side_b = (0, 1), (2, 3)
                for side, other in ((side_a, side_b), (side_b, side_a)):
                    for i in side:
                        fi = FaultInjector()
                        fi.partition(*(addrs[j] for j in other))
                        # install_faults also severs established conns —
                        # a real partition cuts in-flight TCP, and a sync
                        # session opened pre-partition would otherwise
                        # keep replicating across the split
                        agents[i].transport.install_faults(fi)

                # writes land on BOTH sides during the split
                for k in range(1, 11):
                    agents[0].exec_transaction(
                        [("INSERT INTO tests (id, text) VALUES (?, ?)",
                          (k, f"side-a-{k}"))]
                    )
                    agents[2].exec_transaction(
                        [("INSERT INTO tests (id, text) VALUES (?, ?)",
                          (100 + k, f"side-b-{k}"))]
                    )
                await asyncio.sleep(1.0)
                # the split is real: side B must not have seen side A's
                # writes (and the injector actually dropped traffic)
                b_rows = agents[2].store.query(
                    "SELECT count(*) FROM tests WHERE id BETWEEN 1 AND 10"
                )
                assert b_rows[0][0] == 0
                assert any(
                    agents[i].transport.faults.dropped > 0 for i in range(4)
                )
                assert not _check_bookkeeping(agents)

                # heal: drop the injectors entirely (rig removes the fault)
                for a in agents:
                    a.transport.install_faults(None)
                assert await _wait_bookkeeping(agents, 30), (
                    "bookkeeping did not re-converge after heal"
                )
                counts = {
                    tuple(a.store.query("SELECT count(*) FROM tests")[0])
                    for a in agents
                }
                assert counts == {(21,)}
            finally:
                for a in agents:
                    await a.stop()

    asyncio.run(body())


def test_degraded_link_loss_converges_on_real_sockets():
    """30% payload loss + 5ms delay on every node: broadcast alone can't
    deliver everything, anti-entropy sync must fill the gaps — and the
    campaign still ends with the bookkeeping property."""

    async def body():
        with tempfile.TemporaryDirectory() as tmp:
            agents, _addrs = await _boot(3, tmp)
            try:
                for i, a in enumerate(agents):
                    a.transport.install_faults(
                        FaultInjector(loss=0.3, latency_s=0.005, seed=i)
                    )
                for k in range(20):
                    agents[k % 3].exec_transaction(
                        [("INSERT INTO tests (id, text) VALUES (?, ?)",
                          (k, f"lossy-{k}"))]
                    )
                assert await _wait_bookkeeping(agents, 45)
                assert any(a.transport.faults.dropped > 0 for a in agents)
                for a in agents:
                    (n,) = a.store.query("SELECT count(*) FROM tests")[0]
                    assert n == 20
            finally:
                for a in agents:
                    await a.stop()

    asyncio.run(body())
