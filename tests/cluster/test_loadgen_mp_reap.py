"""Hung-worker reaping (ISSUE 15 satellite): the mp parent must never
block its gather on a wedged worker process.  Two tripwires — a stale
heartbeat file (worker loop hard-wedged) and an absolute wall deadline
(loop alive but never finishing) — both reap the process and synthesize
a report that `merge_reports` classifies checker_broken (inconclusive),
NEVER lost_writes (the synthetic report carries no acked ids)."""

import asyncio
import sys
import time

from corrosion_tpu import loadgen_mp


def _hang_argv():
    # stands in for the real worker: reads stdin like worker_main, then
    # wedges without ever writing a report line or a heartbeat
    return (
        sys.executable, "-c",
        "import sys, time; sys.stdin.read(); time.sleep(300)",
    )


def test_stale_heartbeat_reaps_worker(monkeypatch, tmp_path):
    monkeypatch.setattr(loadgen_mp, "_WORKER_ARGV", _hang_argv())
    monkeypatch.setattr(loadgen_mp, "WORKER_HEARTBEAT_STALE_S", 1.5)
    task = {
        "worker_index": 0, "n_writers": 4, "n_watchers": 1,
        "heartbeat_path": str(tmp_path / "w0.hb"),  # never written
    }
    t0 = time.monotonic()
    rep = asyncio.run(loadgen_mp._spawn_worker(task, deadline_s=120.0))
    assert time.monotonic() - t0 < 30.0  # reaped, not deadline-bound
    assert rep["reaped"]
    assert "heartbeat stale" in rep["stream_errors"][0]


def test_deadline_reaps_worker_with_live_heartbeat(monkeypatch, tmp_path):
    hb = tmp_path / "w0.hb"
    # the other hang mode: loop alive (heartbeats fresh) but the report
    # never comes — only the absolute deadline catches this one
    monkeypatch.setattr(
        loadgen_mp, "_WORKER_ARGV",
        (
            sys.executable, "-c",
            "import sys, time\n"
            "sys.stdin.read()\n"
            "while True:\n"
            "    open(sys.argv[1], 'w').write(str(time.monotonic()))\n"
            "    time.sleep(0.2)\n",
            str(hb),
        ),
    )
    monkeypatch.setattr(loadgen_mp, "WORKER_HEARTBEAT_STALE_S", 600.0)
    task = {
        "worker_index": 0, "n_writers": 4, "n_watchers": 1,
        "heartbeat_path": str(hb),
    }
    rep = asyncio.run(loadgen_mp._spawn_worker(task, deadline_s=3.0))
    assert rep["reaped"]
    assert "deadline" in rep["stream_errors"][0]
    # the heartbeat really was alive when the deadline fired
    assert hb.exists()


def test_healthy_worker_report_passes_through(monkeypatch):
    monkeypatch.setattr(
        loadgen_mp, "_WORKER_ARGV",
        (
            sys.executable, "-c",
            "import sys, json; json.load(sys.stdin); "
            "print(json.dumps({'ok': 1}))",
        ),
    )
    rep = asyncio.run(
        loadgen_mp._spawn_worker({"worker_index": 0}, deadline_s=60.0)
    )
    assert rep == {"ok": 1}


def test_reaped_report_classifies_checker_broken_never_lost():
    """The classification contract end-to-end through merge_reports: a
    reaped worker is inconclusive, and cannot convict lost writes."""
    healthy = {
        "writers": 4, "watchers": 1, "writes_attempted": 8,
        "writes_ok": 8, "flood_s": 1.0,
        "acked_at": {"10": 0.5}, "write_lat_raw": [0.01],
        "watchers_detail": [
            {"ok": True, "dead": False, "seen_at": {"10": 0.6},
             "snap_seen": []},
        ],
    }
    reaped = loadgen_mp._reaped_report(
        {"worker_index": 1, "n_writers": 4, "n_watchers": 1}, "test reap"
    )
    merged = loadgen_mp.merge_reports([healthy, reaped], {})
    assert merged["reaped_workers"] == 1
    assert merged["checker_broken"]
    assert not merged["lost_writes"]
    assert not merged["consistent"]


def test_worker_heartbeat_file_is_touched(tmp_path):
    """Worker side: the heartbeat loop really touches its file."""
    hb = tmp_path / "hb"

    async def body():
        t = asyncio.ensure_future(loadgen_mp._heartbeat_loop(str(hb)))
        for _ in range(50):
            if hb.exists():
                break
            await asyncio.sleep(0.05)
        t.cancel()
        await asyncio.gather(t, return_exceptions=True)

    asyncio.run(body())
    assert hb.exists() and hb.read_text().strip()
