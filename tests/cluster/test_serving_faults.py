"""Loadgen under a FaultPlan (ISSUE 8 satellite): the measured serving
driver with loss + asymmetric partition + delay replayed by
HostFaultDriver during the flood — zero lost writes once healed, and
the faults demonstrably engaged."""

import asyncio

import pytest

from corrosion_tpu.loadgen import run_serving_cluster_load


@pytest.mark.chaos
def test_serving_load_under_loss_partition_plan():
    from corrosion_tpu.sim.runner import serving_fault_plan

    plan = serving_fault_plan(3, seed=7)
    assert plan.horizon > 0
    out = asyncio.run(
        run_serving_cluster_load(
            n_nodes=3, n_writes=24, n_writers=2, n_watchers=2,
            rate_hz=60.0, settle_timeout_s=45.0, seed=7, plan=plan,
            telemetry=True,
        )
    )
    # the no-lost-writes property under chaos: the driver heals the
    # schedule before the settle check, so consistency must hold
    assert out["writes_ok"] == 24
    assert out["consistent"], out
    assert not out["lost_writes"] and not out["checker_broken"]
    assert out["faults"] and out["plan_horizon"] == plan.horizon
    # the flight recorder saw every write reach visibility
    assert out["telemetry"]["stages"]["visible"] == 24
    assert out["visible_latency_s"]["samples"] >= 24


@pytest.mark.chaos
def test_serving_load_faultless_vs_faulted_comparable():
    """The faultless and faulted runs produce the same report shape —
    the campaign bands compare them cell to cell."""
    from corrosion_tpu.sim.runner import serving_fault_plan

    faultless = asyncio.run(
        run_serving_cluster_load(
            n_nodes=3, n_writes=12, n_writers=2, n_watchers=2,
            rate_hz=0.0, settle_timeout_s=30.0, seed=3,
        )
    )
    faulted = asyncio.run(
        run_serving_cluster_load(
            n_nodes=3, n_writes=12, n_writers=2, n_watchers=2,
            rate_hz=0.0, settle_timeout_s=45.0, seed=3,
            plan=serving_fault_plan(3, seed=3),
        )
    )
    for out in (faultless, faulted):
        assert out["consistent"], out
        assert out["visible_latency_s"]["p99"] > 0
    assert faultless["faults"] is False
    assert faulted["faults"] is True
