"""Crash-recovery classification (ISSUE 13 satellite): deterministic
regression tests that a PROCESS kill can never corrupt the checker's
verdict — a kill mid-write yields ``checker_broken`` (inconclusive) /
retriable write errors, never ``lost_writes``; and a write ACKED before
the kill survives kill -9 + restart on the node's durable state (the
anti-entropy heal the paper guarantees), pinned under a FaultPlan seed.
"""

import asyncio

import pytest

from corrosion_tpu.devcluster import DevCluster, Topology

SCHEMA = (
    "CREATE TABLE tests (id INTEGER PRIMARY KEY NOT NULL, "
    "text TEXT NOT NULL DEFAULT '');"
)


def _cluster(tmp_path, n=2, **kw):
    names = [f"n{i}" for i in range(n)]
    text = (
        "\n".join(f"{a} -> {b}" for a in names for b in names if a != b)
        or names[0]
    )
    schema_dir = tmp_path / "schema"
    schema_dir.mkdir()
    (schema_dir / "schema.sql").write_text(SCHEMA)
    cluster = DevCluster(
        Topology.parse(text), str(tmp_path / "state"), str(schema_dir), **kw
    )
    cluster.write_configs()
    cluster.start(stagger_s=0.1)
    cluster.wait_ready(timeout=30.0)
    return cluster


def test_kill_mid_write_classifies_inconclusive_never_lost(tmp_path):
    """Writer and watcher both pinned to the node that dies mid-flood,
    retries OFF so the kill surfaces raw: the verdict must be
    checker-broken (the watch stream died — inconclusive) plus
    retriable write errors — and lost_writes must stay False, because
    every failed write was UNACKED and the checker convicts on acked
    ids only."""
    from corrosion_tpu.loadgen import LoadGenerator

    cluster = _cluster(tmp_path, n=1)
    try:
        name = cluster.topo.nodes[0]
        addr = cluster.nodes[name].api_addr

        async def body():
            gen = LoadGenerator(addr, retry_writes=False)

            async def killer():
                await asyncio.sleep(0.4)
                cluster.kill_node(name)

            k = asyncio.create_task(killer())
            report = await gen.run(
                n_writes=400, rate_hz=400.0, settle_timeout_s=6.0
            )
            await k
            return report

        report = asyncio.run(body())
        assert report.writes_ok > 0, report.to_dict()  # kill was MID-flood
        assert report.write_errors > 0, report.to_dict()
        # the classification contract: a dead checker is INCONCLUSIVE
        assert report.checker_broken
        assert not report.lost_writes, report.to_dict()
        assert not report.consistent
    finally:
        cluster.stop()


def test_acked_write_survives_kill_and_restart(tmp_path):
    """Ack → SIGKILL → respawn on the same state dir: the acked row
    must be durable (sqlite WAL committed before the ack), and a fresh
    write after restart must also land — the node actually recovered,
    not just restarted."""
    from corrosion_tpu.api.client import ApiClient

    cluster = _cluster(tmp_path, n=1)
    name = cluster.topo.nodes[0]
    addr = cluster.nodes[name].api_addr
    try:
        async def body():
            client = ApiClient(addr)
            await client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "pre"]]]
            )
            cluster.kill_node(name)
            cluster.restart_node(name)
            # wait_ready greps node.log, which still holds the PRE-kill
            # "agent running" line (append mode) — poll the API itself
            rows = None
            for _ in range(150):
                try:
                    rows = await client.query(
                        ["SELECT text FROM tests WHERE id = ?", [1]]
                    )
                    break
                except OSError:
                    await asyncio.sleep(0.1)
            assert rows == [["pre"]], rows  # the acked write SURVIVED
            await client.execute_with_retry(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [2, "post"]]]
            )
            rows = await client.query(["SELECT id FROM tests ORDER BY id", []])
            assert [r[0] for r in rows] == [1, 2]

        asyncio.run(body())
    finally:
        cluster.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_mp_crash_lane_zero_acked_writes_lost(tmp_path):
    """The full multi-process lane under the PINNED FaultPlan seed: a
    kill -9 + respawn mid-flood must end with zero acked writes missing
    from ANY node after the global settle sweep (anti-entropy healed
    the restarted node), writers absorbing the outage as retries and
    failovers — the ISSUE 13 acceptance shape at regression scale."""
    from corrosion_tpu.faults import FaultEvent, FaultPlan
    from corrosion_tpu.loadgen_mp import run_devcluster_load

    plan = FaultPlan(
        n_nodes=3, seed=7,
        events=(FaultEvent("crash", 6, 36, node=2),), round_s=0.05,
    )
    out = asyncio.run(
        run_devcluster_load(
            n_nodes=3, n_workers=2, n_writes=120, n_writers=16,
            n_watchers=2, rate_hz=60.0, settle_timeout_s=30.0,
            global_settle_s=45.0, seed=7, plan=plan,
            state_dir=str(tmp_path / "mp"),
        )
    )
    assert out["killed_nodes"] == [2]
    assert out["consistent"], out
    assert not out["lost_writes"]
    assert not out["checker_broken"]
    assert out["settle_missing"] == {}
    # the outage was REAL: the retry stack absorbed transport errors
    assert out["retries_transport"] > 0 or out["write_failovers"] > 0
