"""Multi-process fault campaign (VERDICT r2 item 5).

The reference runs its kill/backup/restore drivers against real
containers under Antithesis with cluster-wide eventual checkers
(.antithesis/config/docker-compose.yaml:1-45,
.antithesis/client/test-templates/check_bookkeeping.py:6-27,
parallel_driver_backup_node.sh).  This is that campaign against REAL
agent processes spawned by the devcluster harness:

1. continuous write load through the HTTP API (the load-generator role);
2. kill -9 one node mid-storm, restart it on the same state dir (crash
   recovery resumes bookkeeping from tables);
3. back up a node via the CLI under load and restore it onto another
   (stopped) node, which rejoins with a fresh actor identity;
4. eventual checker: cluster-wide `sync generate` over each node's admin
   socket must show need == 0 ∧ partial_need == 0 ∧ equal heads — the
   check_bookkeeping property verbatim — plus equal row counts.

Everything runs over loopback TCP/UDP with per-node tempdir state; the
whole campaign is CI-sized (3 nodes, ~100 writes) but every process,
socket, and CLI invocation is real.
"""

import contextlib
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The campaign seed threads through the environment so a failing
# kill/restore schedule can be replayed exactly:
#   CORRO_CAMPAIGN_SEED=1234 pytest tests/cluster/test_fault_campaign.py
# The seed drives every schedule decision (victim choice, inter-phase
# delays) via one random.Random — the FaultPlan reproducibility
# discipline applied to the real-process tier.
CAMPAIGN_SEED = int(os.environ.get("CORRO_CAMPAIGN_SEED", "0"))


@contextlib.contextmanager
def _phase(name: str, budget_s: float):
    """Per-phase wall-clock guard: a hung node fails THIS phase fast
    with a named error instead of eating the suite-wide watchdog."""
    t0 = time.monotonic()
    yield
    elapsed = time.monotonic() - t0
    assert elapsed < budget_s, (
        f"campaign phase {name!r} took {elapsed:.1f}s (budget {budget_s}s) "
        f"— seed {CAMPAIGN_SEED}"
    )
SCHEMA = """CREATE TABLE tests (
    id INTEGER PRIMARY KEY NOT NULL,
    text TEXT NOT NULL DEFAULT ''
);
"""


def _cli(cfg_path, *args, timeout=30):
    proc = subprocess.run(
        [sys.executable, "-m", "corrosion_tpu.cli.main", "-c", cfg_path, *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"cli {' '.join(args)} rc={proc.returncode}: {proc.stderr[-2000:]}"
        )
    return proc.stdout


def _post(api_addr, body, timeout=5):
    req = urllib.request.Request(
        f"http://{api_addr}/v1/transactions",
        json.dumps(body).encode(),
        {"content-type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode().splitlines()[0])


def _query_count(cfg_path) -> int:
    out = _cli(cfg_path, "query", "SELECT count(*) FROM tests")
    return int(out.strip().splitlines()[-1])


def _sync_state(cfg_path) -> dict:
    return json.loads(_cli(cfg_path, "sync", "generate"))


class LoadGen(threading.Thread):
    """Continuous writer against one node's HTTP API; tolerates the
    target being mid-crash (the campaign kills nodes under it)."""

    def __init__(self, api_addr: str):
        super().__init__(daemon=True)
        self.api_addr = api_addr
        self.committed = 0
        self.errors = 0
        self._halt = threading.Event()

    def run(self):
        i = 0
        while not self._halt.is_set():
            i += 1
            try:
                _post(
                    self.api_addr,
                    [["INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                      [i, f"w{i}"]]],
                )
                self.committed += 1
            except Exception:
                self.errors += 1
                time.sleep(0.05)
            time.sleep(0.01)

    def stop(self):
        self._halt.set()
        self.join(timeout=10)


def _cluster_converged(cfg_paths) -> bool:
    """check_bookkeeping.py:6-27: all needs empty, all heads equal."""
    states = []
    for p in cfg_paths:
        try:
            states.append(_sync_state(p))
        except Exception:
            return False
    heads = {}
    for s in states:
        if any(s["need"].values()) or s["partial_need"]:
            return False
        for actor, head in s["heads"].items():
            if heads.setdefault(actor, head) != head:
                return False
    # every node must know every writer's head
    for s in states:
        for actor, head in heads.items():
            if head and s["heads"].get(actor, 0) != head:
                return False
    return True


def _wait(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.5)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.chaos
def test_fault_campaign_kill_restart_backup_restore():
    # no pytest-timeout in this image; per-phase _phase() guards fail a
    # hung node fast, and the conftest faulthandler watchdog (300 s
    # dump-and-exit) remains the backstop
    from corrosion_tpu.devcluster import DevCluster, Topology

    rng = random.Random(CAMPAIGN_SEED)
    print(f"campaign seed {CAMPAIGN_SEED} (set CORRO_CAMPAIGN_SEED to replay)")
    tmp = tempfile.TemporaryDirectory()
    schema_dir = os.path.join(tmp.name, "schema")
    os.makedirs(schema_dir)
    with open(os.path.join(schema_dir, "tests.sql"), "w") as f:
        f.write(SCHEMA)

    topo = Topology.parse("B -> A\nC -> A\nC -> B")
    dc = DevCluster(topo, os.path.join(tmp.name, "state"), schema_dir)
    dc.write_configs()
    cfg = {
        n: os.path.join(dc.nodes[n].state_dir, "config.toml")
        for n in ("A", "B", "C")
    }
    dc.start()
    try:
        with _phase("boot + initial load", 80):
            dc.wait_ready(45)
            load = LoadGen(dc.nodes["A"].api_addr)
            load.start()
        try:
            with _phase("initial write load", 35):
                _wait(lambda: load.committed > 20, 30, "initial write load")

            # -- phase 1: kill -9 a seed-chosen victim mid-storm, restart
            # on the same state dir.  A writes the load, so the victim is
            # drawn from {B, C}; the restore phase targets the other.
            kill_name, restore_name = rng.sample(["B", "C"], 2)
            degraded_s = 0.5 + rng.random() * 1.5  # schedule jitter, seeded
            with _phase(f"kill -9 {kill_name} + restart", 60):
                b = dc.nodes[kill_name]
                b.proc.send_signal(signal.SIGKILL)
                b.proc.wait(timeout=10)
                time.sleep(degraded_s)  # writes continue against the degraded cluster
                with open(os.path.join(b.state_dir, "node.log"), "a") as log:
                    b.proc = subprocess.Popen(
                        [sys.executable, "-m", "corrosion_tpu.cli.main",
                         "-c", cfg[kill_name], "agent"],
                        stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
                    )
                _wait(
                    lambda: b.proc.poll() is None and load.committed > 40,
                    30, f"restarted {kill_name} + more load",
                )

            # -- phase 2: backup A under load, restore onto the other node
            with _phase(f"backup A → restore {restore_name}", 90):
                backup_path = os.path.join(tmp.name, "a.backup.db")
                _cli(cfg["A"], "backup", backup_path)
                c = dc.nodes[restore_name]
                c.proc.send_signal(signal.SIGTERM)
                c.proc.wait(timeout=15)
                _cli(cfg[restore_name], "restore", backup_path)
                with open(os.path.join(c.state_dir, "node.log"), "a") as log:
                    c.proc = subprocess.Popen(
                        [sys.executable, "-m", "corrosion_tpu.cli.main",
                         "-c", cfg[restore_name], "agent"],
                        stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
                    )
                _wait(
                    lambda: c.proc.poll() is None and load.committed > 60,
                    30, f"restored {restore_name} + more load",
                )
        finally:
            load.stop()

        assert load.committed > 60, (load.committed, load.errors)
        # -- eventual checker: the check_bookkeeping property
        with _phase("eventual convergence checker", 95):
            _wait(
                lambda: _cluster_converged(list(cfg.values())),
                90, "cluster-wide need==0 ∧ equal heads",
            )
        # eventually_check_db analog: every node holds every write
        counts = {n: _query_count(cfg[n]) for n in cfg}
        assert len(set(counts.values())) == 1, counts
        assert counts["A"] >= load.committed * 0.99, (counts, load.committed)
    finally:
        dc.stop()
        tmp.cleanup()
