"""The devcluster as the third FULL fault seam (ISSUE 15 tentpole).

Link faults, the `slow` gray failure, and clock skew replay INSIDE each
agent process via `faults.AgentFaultRuntime`, armed from the ``[faults]``
config section the devcluster parent writes and driven by the round
control file `DevClusterFaultDriver` publishes.  These tests pin the
contract in layers:

- **byte identity**: the per-link LinkModel schedule an agent runtime
  installs (parameters AND ``derive_seed(seed, "link", src, dst,
  epoch)`` seeds) is byte-identical to what `RealSocketFaultDriver`
  installs for the same plan, at every round, with crash and
  clock_skew events in the plan unable to disturb the epoch indices;
- **respawn resume**: a fresh runtime fast-forwarded to round R in one
  `apply_round` call equals a runtime that walked every round — the
  path a kill -9'd node takes when it rejoins mid-plan;
- **control protocol**: a runtime following a real control file applies
  published rounds and clears everything at ``done``;
- **config plumbing**: the plan round-trips exactly through
  ``plan_to_dict`` → ``[faults]`` TOML → ``Config.load`` →
  ``plan_from_dict`` on every node, with the right node_index and the
  gossip addrs in ``topo.nodes`` order;
- **loud refusals**: `slow` without node=/delay_rounds=, `slow` on a
  `RealSocketFaultDriver` without agents=, `slow` on the sim compilers,
  and in-agent kinds on a `DevClusterFaultDriver` whose cluster was not
  built with ``plan=``;
- **the real thing**: a symmetric partition installed mid-flood across
  four REAL agent processes isolates the sides (writes on one side are
  invisible on the other while the cut holds — the devcluster twin of
  tests/cluster/test_realsocket_partition.py), then heals at the
  horizon and anti-entropy converges every process to the full row
  set, which exercises the PR 8 bi-stream re-check across the process
  boundary.
"""

import asyncio
import json
import os

import pytest

from corrosion_tpu.devcluster import (
    DEVCLUSTER_KINDS,
    DevCluster,
    DevClusterFaultDriver,
    Topology,
)
from corrosion_tpu.faults import (
    AGENT_RUNTIME_KINDS,
    AgentFaultRuntime,
    FaultEvent,
    FaultPlan,
    RealSocketFaultDriver,
    derive_seed,
    plan_from_dict,
    plan_to_dict,
)

SCHEMA = (
    "CREATE TABLE tests (id INTEGER PRIMARY KEY NOT NULL, "
    "text TEXT NOT NULL DEFAULT '');"
)


class StubTransport:
    """The one method both fault installers need."""

    def __init__(self):
        self.faults = None

    def install_faults(self, fi):
        self.faults = fi


class _StubClock:
    def __init__(self):
        self._now_ns = lambda: 0


class StubAgent:
    """slow/clock_skew surface of a real Agent."""

    def __init__(self):
        self.slow_inject_s = 0.0
        self.clock = _StubClock()

    def set_slow_inject(self, stall_s):
        self.slow_inject_s = stall_s


def seam_plan(seed: int = 13) -> FaultPlan:
    """Every kind the process seam supports, including the shapes that
    stress epoch indexing: overlapping delay+jitter on one link (two
    epochs as each ends), an asymmetric pair partition AND a WAN-tier
    range rectangle, plus crash and clock_skew events that must flow
    through the walk WITHOUT perturbing any link epoch index."""
    return FaultPlan(
        n_nodes=4, seed=seed, round_s=0.05,
        events=(
            FaultEvent("loss", 0, 20, p=0.35),
            FaultEvent("delay", 2, 14, src=0, dst=1, delay_rounds=1),
            FaultEvent("jitter", 2, 10, src=0, dst=1, delay_rounds=2),
            FaultEvent("duplicate", 4, 16, src=1, dst=2, p=0.25),
            FaultEvent("partition", 6, 12, src=3, dst=0),
            FaultEvent(
                "partition", 8, 12, src="0:2", dst="2:4", symmetric=True
            ),
            FaultEvent("slow", 10, 18, node=2, delay_rounds=3),
            FaultEvent("clock_skew", 0, 20, node=1, skew_ns=50_000_000),
            FaultEvent("crash", 14, 18, node=3),
        ),
    )


def _addrs(n):
    return [f"10.0.0.{i}:9000" for i in range(n)]


def injector_state(fi):
    """Everything observable about an injector's installed schedule:
    per-destination LinkModel parameters INCLUDING the derived seed
    (the byte-identity anchor), plus the egress blocked set."""
    return (
        {
            addr: (lm.latency_s, lm.loss, lm.jitter_s, lm.duplicate, lm.seed)
            for addr, lm in fi.links.items()
        },
        frozenset(fi.blocked_peers),
    )


def test_agent_runtime_schedule_byte_identical_to_realsocket_driver():
    """THE tentpole pin: per round, every node's in-process runtime
    holds exactly the link state (params + derive_seed streams + egress
    blocks + slow gate) the all-nodes RealSocketFaultDriver holds for
    that node — so the devcluster's distributed replay cannot drift
    from the host driver the parity suite trusts."""
    plan = seam_plan()
    addrs = _addrs(plan.n_nodes)

    drv_transports = [StubTransport() for _ in range(plan.n_nodes)]
    drv_agents = [StubAgent() for _ in range(plan.n_nodes)]
    driver = RealSocketFaultDriver(
        plan, drv_transports, addrs, agents=drv_agents
    )

    rt_agents = [StubAgent() for _ in range(plan.n_nodes)]
    runtimes = [
        AgentFaultRuntime(
            plan, i, addrs, StubTransport(), agent=rt_agents[i]
        )
        for i in range(plan.n_nodes)
    ]

    saw_links = saw_blocks = saw_slow = False
    for r in range(plan.horizon + 2):
        driver.apply_round(r)
        for rt in runtimes:
            rt.apply_round(r)
        for i in range(plan.n_nodes):
            drv = injector_state(driver.injectors[i])
            agt = injector_state(runtimes[i].injector)
            assert drv == agt, f"node {i} diverged at round {r}"
            assert drv_agents[i].slow_inject_s == rt_agents[i].slow_inject_s
            saw_links = saw_links or bool(drv[0])
            saw_blocks = saw_blocks or bool(drv[1])
            saw_slow = saw_slow or drv_agents[i].slow_inject_s > 0
    # the comparison was not vacuous: every fault family materialized
    assert saw_links and saw_blocks and saw_slow

    # the seeds really are the documented derivation, with epoch > 0
    # reached (a link whose params changed re-seeded its stream)
    installs = [
        (detail[0], detail[1])
        for _, action, detail in driver.log
        if action == "link"
    ]
    assert any(idx > 0 for _, idx in installs)
    pair, idx = next((p, i) for p, i in installs if i > 0)
    lm = driver.injectors[pair[0]].links.get(addrs[pair[1]])
    if lm is not None:  # last install on this edge may have been CLEAR
        assert lm.seed == derive_seed(
            plan.seed, "link", pair[0], pair[1],
            max(i for p, i in installs if p == pair),
        )


def test_respawn_mid_plan_resumes_exact_state():
    """A node respawned mid-plan arms a FRESH runtime and applies the
    currently-published round once; because the epoch walk is
    cumulative, that single call must reproduce the exact state (and
    epoch indices — checked via the seeds) of a runtime that lived
    through every round, and the two must stay identical as the rest
    of the plan unfolds."""
    plan = seam_plan()
    addrs = _addrs(plan.n_nodes)
    me = 0  # node 0 sends on the busiest link (delay+jitter epochs)

    lived = AgentFaultRuntime(plan, me, addrs, StubTransport(),
                              agent=StubAgent())
    mid = plan.horizon // 2
    for r in range(mid + 1):
        lived.apply_round(r)

    respawned = AgentFaultRuntime(plan, me, addrs, StubTransport(),
                                  agent=StubAgent())
    respawned.apply_round(mid)  # the fast-forward a rejoiner performs

    assert injector_state(lived.injector) == injector_state(
        respawned.injector
    )
    assert lived._epoch_idx == respawned._epoch_idx

    for r in range(mid + 1, plan.horizon + 2):
        lived.apply_round(r)
        respawned.apply_round(r)
        assert injector_state(lived.injector) == injector_state(
            respawned.injector
        ), f"diverged at round {r}"


def test_runtime_follows_control_file_and_clears_on_done(tmp_path):
    """The epoch-advance control protocol end-to-end: a runtime's run()
    loop applies rounds as the parent publishes them (atomic replace,
    the devcluster driver's write shape) and clears everything —
    injector uninstalled, slow gate and skew restored — at done."""
    plan = FaultPlan(
        n_nodes=2, seed=3, round_s=0.02,
        events=(
            FaultEvent("partition", 0, 4, src=0, dst=1),
            FaultEvent("slow", 0, 4, node=0, delay_rounds=2),
        ),
    )
    ctl = str(tmp_path / "faults.round")
    transport, agent = StubTransport(), StubAgent()
    rt = AgentFaultRuntime(
        plan, 0, _addrs(2), transport, agent=agent, control_path=ctl
    )

    def publish(r, done=False):
        tmp = ctl + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps({"round": r, "done": done}))
        os.replace(tmp, ctl)

    async def body():
        task = asyncio.ensure_future(rt.run())
        publish(0)
        for _ in range(100):
            if rt.round >= 0:
                break
            await asyncio.sleep(0.01)
        assert rt.round == 0
        assert _addrs(2)[1] in rt.injector.blocked_peers
        assert agent.slow_inject_s == pytest.approx(2 * plan.round_s)
        publish(plan.horizon + 1, done=True)
        await asyncio.wait_for(task, 5.0)

    asyncio.run(body())
    # done → all-clear: injector uninstalled, gates reset
    assert transport.faults is None
    assert agent.slow_inject_s == 0.0


def test_plan_round_trips_through_faults_config(tmp_path):
    """write_configs ships the plan into every node's [faults] section;
    Config.load on each emitted TOML must hand back the IDENTICAL plan
    (same derive_seed inputs), the node's own index, every gossip addr
    in topo.nodes order, and the cluster's control path."""
    from corrosion_tpu.agent.config import Config

    plan = seam_plan(seed=29)
    names = ["n0", "n1", "n2", "n3"]
    text = "\n".join(
        f"{a} -> {b}" for a in names for b in names if a != b
    )
    cluster = DevCluster(
        Topology.parse(text), str(tmp_path / "state"),
        str(tmp_path / "schema"), plan=plan,
    )
    cluster.write_configs()

    expected_addrs = [
        f"127.0.0.1:{cluster.nodes[n].gossip_port}"
        for n in cluster.topo.nodes
    ]
    for i, name in enumerate(cluster.topo.nodes):
        cfg = Config.load(
            os.path.join(cluster.nodes[name].state_dir, "config.toml")
        )
        assert cfg.faults, f"{name} got no [faults] section"
        assert cfg.faults["node_index"] == i
        assert cfg.faults["gossip_addrs"] == expected_addrs
        assert cfg.faults["control_path"] == cluster.control_path
        assert plan_from_dict(json.loads(cfg.faults["plan"])) == plan

    # and the encoding itself is exact, not just equal-enough
    assert plan_from_dict(plan_to_dict(plan)) == plan


def test_kind_sets_cover_the_full_matrix():
    """DEVCLUSTER_KINDS is the FULL kind set: everything the agents
    replay in-process plus the parent-owned crash — the ISSUE 15 'third
    full fault seam' claim, stated as set algebra."""
    from corrosion_tpu.faults import KINDS

    assert DEVCLUSTER_KINDS == set(KINDS)
    assert DEVCLUSTER_KINDS == AGENT_RUNTIME_KINDS | {"crash"}


def test_loud_refusals_across_the_seams(tmp_path):
    """Every place a fault kind is unsupported must refuse at build
    time, never silently not-inject."""
    # slow needs a node and a stall magnitude
    with pytest.raises(ValueError, match="needs node="):
        FaultEvent("slow", 0, 4)
    with pytest.raises(ValueError, match="delay_rounds"):
        FaultEvent("slow", 0, 4, node=1)

    slow_plan = FaultPlan(
        n_nodes=2, seed=1,
        events=(FaultEvent("slow", 0, 4, node=0, delay_rounds=1),),
    )

    # the socket driver cannot stall an agent it was never handed
    with pytest.raises(ValueError, match="no agents="):
        RealSocketFaultDriver(
            slow_plan, [StubTransport(), StubTransport()], _addrs(2)
        )

    # the devcluster driver refuses in-agent kinds the agents were not
    # configured to replay (cluster built without plan=)
    topo = Topology.parse("a -> b\nb -> a")
    bare = DevCluster(topo, str(tmp_path / "s"), str(tmp_path / "sch"))
    with pytest.raises(ValueError, match=r"plan=<this plan>"):
        DevClusterFaultDriver(slow_plan, bare)
    # crash-only plans predate [faults] and still work without it
    crash_only = FaultPlan(
        n_nodes=2, seed=1, events=(FaultEvent("crash", 0, 4, node=1),)
    )
    DevClusterFaultDriver(crash_only, bare)
    # and a cluster built WITH the plan accepts the full matrix
    armed = DevCluster(
        topo, str(tmp_path / "s2"), str(tmp_path / "sch"), plan=slow_plan
    )
    DevClusterFaultDriver(slow_plan, armed)


def test_sim_compilers_refuse_slow():
    """`slow` is a wall-clock stall — no sim twin (doc/faults.md); both
    sim compilers must refuse it loudly."""
    from corrosion_tpu.sim.faults import compile_plan, compile_plan_factored
    from corrosion_tpu.sim.state import SimConfig

    plan = FaultPlan(
        n_nodes=3, seed=1,
        events=(FaultEvent("slow", 0, 4, node=0, delay_rounds=1),),
    )
    cfg = SimConfig(n_nodes=3, n_payloads=4)
    with pytest.raises(ValueError, match="cannot express `slow`"):
        compile_plan(plan, cfg)
    with pytest.raises(ValueError, match="cannot express `slow`"):
        compile_plan_factored(plan, cfg)


# ---------------------------------------------------------------------------
# the real thing: partition-heal across four REAL agent processes — the
# devcluster twin of tests/cluster/test_realsocket_partition.py


def _boot_cluster(tmp_path, n, plan):
    names = [f"n{i}" for i in range(n)]
    text = "\n".join(f"{a} -> {b}" for a in names for b in names if a != b)
    schema_dir = tmp_path / "schema"
    schema_dir.mkdir()
    (schema_dir / "schema.sql").write_text(SCHEMA)
    cluster = DevCluster(
        Topology.parse(text), str(tmp_path / "state"), str(schema_dir),
        plan=plan,
    )
    cluster.write_configs()
    cluster.start(stagger_s=0.1)
    cluster.wait_ready(timeout=30.0)
    return cluster


async def _counts(client, ids):
    rows = await client.query(
        [
            "SELECT count(*) FROM tests WHERE id BETWEEN ? AND ?",
            [min(ids), max(ids)],
        ]
    )
    return rows[0][0]


@pytest.mark.chaos
def test_partition_heal_on_devcluster(tmp_path):
    """The devcluster twin of test_partition_heal_on_real_sockets,
    across REAL processes: a symmetric {0,1}|{2,3} partition — shipped
    via [faults] and installed by each agent's own runtime when the
    parent publishes the round — isolates the sides mid-flood (side A
    writes invisible on side B while the cut holds), then heals at the
    horizon, and anti-entropy (the PR 8 bi-stream re-check, now running
    between distinct OS processes) converges every node to the full row
    set."""
    from corrosion_tpu.api.client import ApiClient

    # window [round 4, round 56) at 50 ms rounds: opens ~0.2 s after
    # the driver starts (time to flood both sides) and holds ~2.6 s
    plan = FaultPlan(
        n_nodes=4, seed=17, round_s=0.05,
        events=(
            FaultEvent(
                "partition", 4, 56, src="0:2", dst="2:4", symmetric=True
            ),
        ),
    )
    cluster = _boot_cluster(tmp_path, 4, plan)
    try:
        clients = {}

        async def body():
            for i, name in enumerate(cluster.topo.nodes):
                clients[i] = ApiClient(cluster.nodes[name].api_addr)

            # warmup BEFORE any fault: id=0 must reach every process
            await clients[0].execute_with_retry(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [0, "warm"]]]
            )
            for i in range(4):
                for _ in range(200):
                    if await _counts(clients[i], [0, 0]) == 1:
                        break
                    await asyncio.sleep(0.05)
                else:
                    raise AssertionError(f"warmup never reached node {i}")

            driver = cluster.fault_driver(plan)
            drive = asyncio.ensure_future(driver.run())
            # let the cut install: past round 4, plus one poll cadence
            await asyncio.sleep(4 * plan.round_s + 0.2)

            # flood both sides while the partition holds
            for i in range(1, 11):
                await clients[0].execute_with_retry(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [i, f"a{i}"]]]
                )
            for i in range(101, 111):
                await clients[2].execute_with_retry(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [i, f"b{i}"]]]
                )

            # the partition is REAL across processes: nothing crossed
            assert await _counts(clients[2], range(1, 11)) == 0
            assert await _counts(clients[0], range(101, 111)) == 0
            # ...but flowed freely within a side
            for _ in range(100):
                if await _counts(clients[1], range(1, 11)) == 10:
                    break
                await asyncio.sleep(0.05)
            assert await _counts(clients[1], range(1, 11)) == 10

            await drive  # horizon: heals, publishes done, agents clear

            # full convergence on EVERY process: all 21 rows everywhere
            for i in range(4):
                for _ in range(600):
                    rows = await clients[i].query(
                        ["SELECT count(*) FROM tests", []]
                    )
                    if rows[0][0] == 21:
                        break
                    await asyncio.sleep(0.05)
                ids = await clients[i].query(
                    ["SELECT id FROM tests ORDER BY id", []]
                )
                assert [r[0] for r in ids] == (
                    list(range(0, 11)) + list(range(101, 111))
                ), f"node {i} never fully converged"

        asyncio.run(body())
    finally:
        cluster.stop()
