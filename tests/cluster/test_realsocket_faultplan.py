"""FaultPlan → real sockets (ISSUE 3 satellite): the existing
`UdpTcpTransport` `FaultInjector` driven from a compiled FaultPlan
schedule, with the SAME per-link seed derivation as the host-memory and
sim tiers — the third backend of the transport seam."""

import asyncio
import tempfile

import pytest

from corrosion_tpu.faults import (
    CLEAR,
    FaultEvent,
    FaultPlan,
    RealSocketFaultDriver,
    derive_seed,
)
from corrosion_tpu.agent.transport import UdpTcpTransport


def _lossy_plan(seed=11, rounds=4):
    return FaultPlan(
        n_nodes=2, seed=seed, round_s=0.02,
        events=(
            FaultEvent("loss", 0, rounds, src=0, dst=1, p=0.5),
            FaultEvent("partition", rounds, rounds + 2, src=0, dst=1),
        ),
    )


async def _drive_sends(plan, n_frames=40):
    """Boot two bare transports, apply round 0 of the plan, fire
    ``n_frames`` uni frames 0→1, and return the delivered payload set."""
    t0, t1 = UdpTcpTransport(), UdpTcpTransport()
    a0 = await t0.start()
    a1 = await t1.start()
    got = []

    async def on_uni(_addr, data):
        got.append(data)

    async def nop(*_a):
        return None

    t1.set_handlers(nop, on_uni, nop)
    t0.set_handlers(nop, nop, nop)
    try:
        driver = RealSocketFaultDriver(plan, [t0, t1], [a0, a1])
        driver.apply_round(0)
        for k in range(n_frames):
            await t0.send_uni(a1, f"frame-{k}".encode())
        await asyncio.sleep(0.2)  # let the frame pump drain
        dropped = t0.faults.dropped
        # the per-dst stream is derive_seed(seed, "link", 0, 1, epoch=0)
        # — byte-identical to the host tier's derivation
        lm = t0.faults.links[a1]
        assert lm.seed == derive_seed(plan.seed, "link", 0, 1, 0)
        assert lm.loss == 0.5

        # partition window: the same driver blocks 0→1 entirely
        driver.apply_round(plan.events[1].start)
        with pytest.raises(ConnectionError):
            await t0.send_uni(a1, b"through-the-cut")

        # past the horizon the schedule is all-clear
        driver.apply_round(plan.horizon)
        assert not t0.faults.blocked_peers
        assert a1 not in t0.faults.links
        driver.clear()
        assert t0.faults is None
        return [d.decode() for d in got], dropped
    finally:
        await t0.close()
        await t1.close()


def test_faultplan_drives_real_sockets_deterministically():
    """Same plan seed ⇒ the exact same frames survive the lossy link on
    two independent boots (fresh sockets, fresh ports — only the seed
    carries over); a different seed ⇒ a different drop pattern."""
    plan = _lossy_plan(seed=11)
    got_a, dropped_a = asyncio.run(_drive_sends(plan))
    got_b, dropped_b = asyncio.run(_drive_sends(plan))
    assert got_a == got_b
    assert dropped_a == dropped_b
    assert 0 < dropped_a < 40  # the loss actually bit, but not everything

    got_c, _ = asyncio.run(_drive_sends(_lossy_plan(seed=12)))
    assert got_c != got_a


@pytest.mark.chaos
def test_realsocket_campaign_converges_after_schedule():
    """End-to-end: 3 real-socket agents under a compiled FaultPlan
    (loss burst + one-way partition), writes during the schedule, then
    `driver.run()` heals everything and check_bookkeeping must hold —
    the PR 2 parity property on the third tier."""
    from corrosion_tpu.agent.agent import Agent
    from corrosion_tpu.agent.config import Config
    from corrosion_tpu.testing import TEST_SCHEMA, fast_perf

    from .test_realsocket_partition import _wait_bookkeeping

    plan = FaultPlan(
        n_nodes=3, seed=5, round_s=0.04,
        events=(
            FaultEvent("loss", 0, 10, p=0.3),
            FaultEvent("partition", 2, 8, src=1, dst=0),
        ),
    )

    async def body():
        with tempfile.TemporaryDirectory() as tmp:
            transports = [UdpTcpTransport() for _ in range(3)]
            addrs = [await t.start() for t in transports]
            agents = []
            for i, t in enumerate(transports):
                cfg = Config(
                    db_path=f"{tmp}/n{i}.db",
                    gossip_addr=addrs[i],
                    bootstrap=[a for a in addrs if a != addrs[i]],
                    perf=fast_perf(),
                )
                agent = Agent(cfg, t)
                agent.store.execute_schema(TEST_SCHEMA)
                agents.append(agent)
            for a in agents:
                await a.start()
            try:
                driver = RealSocketFaultDriver(plan, transports, addrs)
                drive = asyncio.ensure_future(driver.run())
                for k in range(8):
                    agents[k % 3].exec_transaction(
                        [("INSERT INTO tests (id, text) VALUES (?, ?)",
                          (k, f"rs-{k}"))]
                    )
                    await asyncio.sleep(plan.round_s)
                await drive
                assert all(t.faults is None for t in transports)
                assert await _wait_bookkeeping(agents, 45), (
                    "real-socket tier never re-converged after the plan"
                )
                for a in agents:
                    (n,) = a.store.query("SELECT count(*) FROM tests")[0]
                    assert n == 8
            finally:
                for a in agents:
                    await a.stop()

    asyncio.run(body())
