"""Ground truth under faults (ISSUE 2 tentpole cap): ONE FaultPlan —
one seed, one schedule — runs against BOTH backends of the transport
seam:

- a 3-node in-process host cluster (`testing.Cluster` on a
  `MemoryNetwork`), driven by `HostFaultDriver`;
- the 3-node tpu-sim, via `sim.faults.compile_plan` + the checked
  driver (sim invariant catalog asserted every round).

Both must converge, the eventual heads must match (every node's head
for the writer equals the number of versions written — the ground
truth a dropped write or phantom would break), the invariant catalog
runs in strict mode throughout (conftest turns it on), and every
`sometimes` marker the campaign declares must fire — 100% coverage,
scoped to the campaign window.
"""

import asyncio

import numpy as np
import pytest

from corrosion_tpu.faults import (
    CampaignCoverage,
    FaultEvent,
    FaultPlan,
    HostFaultDriver,
)
from corrosion_tpu.invariants import CATALOG
from corrosion_tpu.testing import Cluster

N_VERSIONS = 12
ROUND_S = 0.05


def parity_plan(seed: int = 7) -> FaultPlan:
    """The shared adversarial schedule.  Node 0 is the writer, so the
    crash victim is node 2 (a reader): its identity can change at wipe
    without perturbing the writer-head ground truth."""
    return FaultPlan(
        n_nodes=3, seed=seed, round_s=ROUND_S,
        events=(
            FaultEvent("loss", 0, 36, p=0.4),
            # asymmetric partition: 2 still hears 0, but 2→0 is cut
            FaultEvent("partition", 6, 18, src=2, dst=0),
            FaultEvent("delay", 4, 24, src=0, dst=1, delay_rounds=1),
            FaultEvent("jitter", 4, 24, src=0, dst=1, delay_rounds=1),
            FaultEvent("duplicate", 0, 24, src=1, dst=2, p=0.3),
            FaultEvent("crash", 24, 34, node=2, wipe=True),
            # +100 ms skew: inside the HLC 300 ms drift ceiling, so
            # convergence must survive it (host tier only; sim has no clock)
            FaultEvent("clock_skew", 0, 36, node=1, skew_ns=100_000_000),
        ),
    )


def run_host_campaign(plan: FaultPlan) -> dict:
    """Host tier: write N_VERSIONS on node 0 while the driver replays
    the schedule; after the horizon, wait for check_bookkeeping
    convergence and return the eventual writer heads."""

    async def body():
        cluster = Cluster(plan.n_nodes, use_swim=False)
        await cluster.start()
        try:
            driver = HostFaultDriver(plan, cluster)
            drive = asyncio.ensure_future(driver.run())
            writer = cluster.agents[0]
            writer_id = writer.actor_id
            for i in range(N_VERSIONS):
                writer.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, f"v{i}"))]
                )
                await asyncio.sleep(plan.round_s)
            await drive
            assert not cluster.down  # every crash was restarted
            assert await cluster.wait_converged(60), "host tier never converged"
            heads = [
                a.sync_state().heads.get(writer_id, 0) for a in cluster.agents
            ]
            rows = [
                cluster.rows(i, "SELECT count(*) FROM tests")[0][0]
                for i in range(plan.n_nodes)
            ]
            return {"heads": heads, "rows": rows, "log": list(driver.log)}
        finally:
            await cluster.stop()

    return asyncio.run(body())


def run_sim_campaign(plan: FaultPlan) -> dict:
    """Sim tier via the jitted driver (one compile; the replay run hits
    the jit cache, so determinism costs ~nothing).  The final state
    passes the sim invariant catalog; the per-ROUND invariant sweep
    under faults is pinned by tests/sim/test_fault_plan.py's
    crash-rejoin test, which drives the same seam eagerly."""
    from corrosion_tpu.sim.faults import compile_plan, run_fault_plan
    from corrosion_tpu.sim.invariants import check_state
    from corrosion_tpu.sim.round import new_sim
    from corrosion_tpu.sim.state import ALIVE, SimConfig, uniform_payloads
    from corrosion_tpu.sim.topology import Topology

    cfg = SimConfig(
        n_nodes=plan.n_nodes, n_payloads=N_VERSIONS, fanout=2,
        sync_interval_rounds=4, n_delay_slots=4,
    )
    meta = uniform_payloads(cfg, inject_every=1)  # writer is node 0
    fplan = compile_plan(plan, cfg, Topology())
    final, metrics = run_fault_plan(
        new_sim(cfg, seed=plan.seed), meta, cfg, Topology(), fplan, 400
    )
    check_state(final, cfg)
    assert (np.asarray(final.alive) == ALIVE).all()
    assert (np.asarray(final.have) > 0).all(), "sim tier never converged"
    return {
        "heads": [int(h) for h in np.asarray(final.heads)[:, 0]],
        "have": np.asarray(final.have).copy(),
        "rounds": int(final.t),
    }


@pytest.mark.chaos
def test_fault_plan_parity_host_vs_sim():
    plan = parity_plan()
    expected = plan.coverage_markers() + ["broadcasts-happen", "sync-happens"]
    assert CATALOG.strict  # the campaign must run with teeth
    with CampaignCoverage(expected) as cov:
        host = run_host_campaign(plan)
        sim = run_sim_campaign(plan)
        # replay: the SAME plan seed reproduces identical per-round sim
        # fault decisions — the second run rides the jit cache, and any
        # divergent decision anywhere in the run would change the final
        # chunk bitmap (the host tier's per-draw replay is pinned by
        # tests/agent/test_link_determinism.py — wall-clock timing makes
        # whole-campaign bit-replay meaningless for real agents)
        sim2 = run_sim_campaign(plan)

    # -- eventual heads match: every node, both tiers, one ground truth
    assert host["heads"] == [N_VERSIONS] * plan.n_nodes, host
    assert sim["heads"] == [N_VERSIONS] * plan.n_nodes, sim
    assert set(host["rows"]) == {N_VERSIONS}, host
    assert (sim2["have"] == sim["have"]).all() and sim2["rounds"] == sim["rounds"]

    # -- 100% sometimes coverage over the campaign, reported
    cov.assert_covered()
    print(
        f"fault parity: heads={N_VERSIONS} on both tiers, sim rounds="
        f"{sim['rounds']}, sometimes coverage {cov.coverage():.0%} "
        f"({len(cov.expected)} markers)"
    )


def run_devcluster_campaign(plan: FaultPlan, tmp_path) -> dict:
    """Process seam (ISSUE 15): the SAME plan against REAL agent
    processes.  Crash stays with the parent driver (kill -9 + wiped
    respawn); loss/partition/delay/jitter/duplicate/clock_skew replay
    INSIDE each agent via the [faults] config section and the round
    control file.  Node 0 takes the same N_VERSIONS writes over HTTP,
    and the eventual per-node row counts are the ground truth the sim
    tier must agree with."""
    import os

    from corrosion_tpu.api.client import ApiClient
    from corrosion_tpu.devcluster import DevCluster
    from corrosion_tpu.devcluster import Topology as DevTopology

    names = [f"n{i}" for i in range(plan.n_nodes)]
    text = "\n".join(f"{a} -> {b}" for a in names for b in names if a != b)
    schema_dir = os.path.join(str(tmp_path), "schema")
    os.makedirs(schema_dir, exist_ok=True)
    with open(os.path.join(schema_dir, "schema.sql"), "w") as f:
        f.write(
            "CREATE TABLE tests (id INTEGER PRIMARY KEY NOT NULL, "
            "text TEXT NOT NULL DEFAULT '');"
        )
    cluster = DevCluster(
        DevTopology.parse(text), os.path.join(str(tmp_path), "state"),
        schema_dir, plan=plan,
    )
    cluster.write_configs()
    cluster.start(stagger_s=0.1)
    cluster.wait_ready(timeout=30.0)
    try:

        async def body():
            clients = [ApiClient(a) for a in cluster.api_addrs]
            driver = cluster.fault_driver(plan)
            drive = asyncio.ensure_future(driver.run())
            for i in range(N_VERSIONS):
                await clients[0].execute_with_retry(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [i, f"v{i}"]]]
                )
                await asyncio.sleep(plan.round_s)
            await drive
            assert not driver.down  # every crash was restarted
            rows = []
            for i in range(plan.n_nodes):
                # the wiped crash victim recovers purely via
                # anti-entropy — give the heal a generous window
                got = -1
                for _ in range(1200):
                    try:
                        got = (await clients[i].query(
                            ["SELECT count(*) FROM tests", []]
                        ))[0][0]
                    except OSError:
                        pass  # respawned node still binding its API
                    if got == N_VERSIONS:
                        break
                    await asyncio.sleep(0.05)
                rows.append(got)
            return {"rows": rows, "log": list(driver.log)}

        return asyncio.run(body())
    finally:
        cluster.stop()


@pytest.mark.chaos
def test_fault_plan_parity_sim_vs_devcluster(tmp_path):
    """ISSUE 15: the parity harness extended to the PROCESS seam — the
    shared 3-node adversarial schedule runs against real agent
    processes (crash via SIGKILL, everything else replayed in-process
    by each agent's fault runtime) and against the sim, and both must
    end at the same ground truth: every node holds all N_VERSIONS."""
    plan = parity_plan()
    with CampaignCoverage(plan.coverage_markers()) as cov:
        dev = run_devcluster_campaign(plan, tmp_path)
    sim = run_sim_campaign(plan)

    assert dev["rows"] == [N_VERSIONS] * plan.n_nodes, dev
    assert sim["heads"] == [N_VERSIONS] * plan.n_nodes, sim
    # the campaign was real: the kill -9 and wiped respawn happened
    kills = [d for _, a, d in dev["log"] if a == "kill"]
    restarts = [d for _, a, d in dev["log"] if a == "restart"]
    assert kills == ["n2"] and restarts == [("n2", True)]
    cov.assert_covered()


@pytest.mark.chaos
def test_wan_tiered_topology_parity_host_vs_sim():
    """ISSUE 9 host-tier parity for a TOPOLOGY FAMILY: a 3-node
    geo-tiered WAN cell (one node per region: cross-region delay 1 +
    10% trunk loss) compiles through `topo.topology_link_events` into
    range-selector link events that BOTH tiers consume — the host
    driver installs them via its range-atom link epochs (no pair
    expansion), the sim via the standard fault compilers — and the
    eventual writer heads must match on both.  Extends the existing
    parity harness (`run_host_campaign`/`run_sim_campaign`) rather than
    adding a new one."""
    from corrosion_tpu.sim.topology import Topology
    from corrosion_tpu.topo import topology_link_events

    topo = Topology(n_regions=3, inter_delay=1, inter_loss=0.1)
    events = topology_link_events(topo, 3, end=30)
    # every selector is a range rectangle and the atoms stay tiny — the
    # "range-atom link epochs" contract the satellite names
    assert events and all(":" in e.src and ":" in e.dst for e in events)
    plan = FaultPlan(n_nodes=3, seed=11, round_s=ROUND_S, events=events)
    assert plan.range_link_epochs()  # the host drivers' install path

    expected = plan.coverage_markers() + ["broadcasts-happen", "sync-happens"]
    with CampaignCoverage(expected) as cov:
        host = run_host_campaign(plan)
        sim = run_sim_campaign(plan)

    assert host["heads"] == [N_VERSIONS] * 3, host
    assert sim["heads"] == [N_VERSIONS] * 3, sim
    assert set(host["rows"]) == {N_VERSIONS}, host
    cov.assert_covered()


@pytest.mark.chaos
def test_chaos_smoke_host_tier():
    """Tier-1-sized host smoke (3 nodes, ≤5 s): a loss burst + short
    asymmetric partition, then convergence — the in-default-selection
    FaultPlan regression canary."""
    plan = FaultPlan(
        n_nodes=3, seed=1, round_s=0.04,
        events=(
            FaultEvent("loss", 0, 10, p=0.3),
            FaultEvent("partition", 2, 8, src=1, dst=0),
        ),
    )

    async def body():
        cluster = Cluster(3, use_swim=False)
        await cluster.start()
        try:
            driver = HostFaultDriver(plan, cluster)
            drive = asyncio.ensure_future(driver.run())
            for i in range(5):
                cluster.agents[0].exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, "x"))]
                )
            await drive
            assert await cluster.wait_converged(10)
        finally:
            await cluster.stop()

    asyncio.run(body())
