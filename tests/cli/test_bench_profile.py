"""bench_child XLA-profile capture path + bench preflight backoff
(ISSUE 16 satellites).

Pins the BENCH_XLA_PROFILE contract at the child seam:

- a plain (non-config-owned) attempt wraps the run in a whole-attempt
  ``jax.profiler`` trace and flushes a trace file into the given dir;
- when a ``phase_map.json`` is staged alongside, the child folds the
  trace into a parsed ``phase_profile`` record;
- capture failures NEVER gate the attempt — both the start_trace
  failure and the post-capture parse failure land in
  ``xla_profile_error`` while ``ok`` stays true;
- capture ownership: rungs whose runner config accepts ``profile_dir``
  run their own scoped capture, so the child must not nest an outer
  trace around them (``_config_owns_profile``).

Plus the preflight retry trail: exponential backoff bounded by
BENCH_PREFLIGHT_BACKOFF_CAP_S, every attempt recorded in
``_diag["preflight_attempts"]``.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import bench  # noqa: E402
import bench_child  # noqa: E402

from corrosion_tpu.sim import profile as prof  # noqa: E402

# ---------------------------------------------------------------------------
# Capture ownership (jax-free).
# ---------------------------------------------------------------------------


def test_config_owns_profile_matrix():
    # the storm rung's verified config runs its own scoped capture
    assert bench_child._config_owns_profile({"mode": "storm"}) is True
    # so does the dedicated phase-profile rung
    assert bench_child._config_owns_profile(
        {"mode": "aux", "fn": "config_phase_profile"}
    ) is True
    # preflight has no config at all → child-owned outer trace
    assert bench_child._config_owns_profile({"mode": "preflight"}) is False
    # unknown fn never gates (ownership check is best-effort)
    assert bench_child._config_owns_profile(
        {"mode": "aux", "fn": "config_does_not_exist"}
    ) is False


# ---------------------------------------------------------------------------
# In-process child runs (preflight mode: one tiny matmul).
# ---------------------------------------------------------------------------


def _run_child(monkeypatch, tmp_path, extra_spec=None):
    out = str(tmp_path / "res.json")
    spec = {"mode": "preflight", "out": out}
    spec.update(extra_spec or {})
    monkeypatch.setattr(sys, "argv", ["bench_child.py", json.dumps(spec)])
    assert bench_child.main() == 0
    with open(out) as f:
        return json.load(f)


def test_child_captures_trace_without_map(monkeypatch, tmp_path):
    pdir = str(tmp_path / "xla_prof")
    res = _run_child(monkeypatch, tmp_path, {"xla_profile": pdir})
    assert res["ok"] is True
    assert res["xla_profile"] == pdir
    assert "xla_profile_error" not in res
    # the trace flushed where the offline parser will look for it
    assert os.path.exists(prof.find_trace_file(pdir))
    # no staged phase_map → no attribution attempted
    assert "phase_profile" not in res


def test_child_attaches_phase_profile_with_staged_map(monkeypatch, tmp_path):
    pdir = str(tmp_path / "xla_prof")
    os.makedirs(pdir)
    # a staged map whose module won't match this attempt's ops: the fold
    # still runs and returns a well-formed (all-residual-zero) record —
    # a stale map attributes nothing rather than lying
    prof.write_phase_map(pdir, [
        'HloModule jit_other\n\nENTRY %main (p0: f32[2]) -> f32[2] {\n'
        '  %x = f32[2] add(f32[2] %p0, f32[2] %p0), '
        'metadata={op_name="jit(r)/corro.sync/add"}\n}\n'
    ])
    res = _run_child(monkeypatch, tmp_path, {"xla_profile": pdir})
    assert res["ok"] is True
    assert "xla_profile_error" not in res
    rec = res["phase_profile"]
    assert rec["kind"] == "phase_profile"
    assert set(rec["phases"]) == set(prof.PHASES)
    assert rec["device_events"] == 0


def test_child_surfaces_parse_failure_without_gating(monkeypatch, tmp_path):
    pdir = str(tmp_path / "xla_prof")
    os.makedirs(pdir)
    # corrupt staged map → parse_phase_profile raises → recorded, run ok
    with open(os.path.join(pdir, "phase_map.json"), "w") as f:
        f.write("{not json")
    res = _run_child(monkeypatch, tmp_path, {"xla_profile": pdir})
    assert res["ok"] is True
    assert "phase_profile" not in res
    assert "JSONDecodeError" in res["xla_profile_error"]


def test_child_surfaces_start_trace_failure_without_gating(
    monkeypatch, tmp_path
):
    import jax

    def boom(*a, **kw):
        raise RuntimeError("profiler backend unavailable")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    pdir = str(tmp_path / "xla_prof")
    res = _run_child(monkeypatch, tmp_path, {"xla_profile": pdir})
    # the attempt itself still lands
    assert res["ok"] is True
    assert res["xla_profile_error"].startswith("RuntimeError")
    assert "xla_profile" not in res and "phase_profile" not in res


# ---------------------------------------------------------------------------
# Preflight retry trail (bench.py, jax-free).
# ---------------------------------------------------------------------------


def _reset_diag(monkeypatch):
    monkeypatch.setitem(bench._diag, "attempts", [])
    monkeypatch.setitem(bench._diag, "preflight_attempts", [])
    monkeypatch.setattr(bench, "_write_diag", lambda: None)
    monkeypatch.delenv("BENCH_PLATFORM", raising=False)


def test_preflight_backoff_trail_bounded(monkeypatch):
    _reset_diag(monkeypatch)
    monkeypatch.setenv("BENCH_PREFLIGHT_BACKOFF_CAP_S", "3")
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: sleeps.append(s))
    monkeypatch.setattr(
        bench, "run_child",
        lambda spec, timeout: {"ok": False, "error": "boom", "wall_s": 0.1},
    )
    assert bench.preflight() is None
    trail = bench._diag["preflight_attempts"]
    assert [t["attempt"] for t in trail] == [1, 2, 3, 4]
    assert all(t["ok"] is False and t["error"] == "boom" for t in trail)
    # exponential, clamped at the cap — a dead backend can't eat the
    # storm budget in sleeps
    assert [t["backoff_s"] for t in trail] == [1.0, 2.0, 3.0, 3.0]
    assert sleeps == [1.0, 2.0, 3.0, 3.0]


def test_preflight_trail_records_success(monkeypatch):
    _reset_diag(monkeypatch)
    calls = {"n": 0}

    def flaky(spec, timeout):
        calls["n"] += 1
        if calls["n"] == 1:
            return {"ok": False, "error": "tunnel wedge", "wall_s": 0.2}
        return {"ok": True, "platform": "cpu", "wall_s": 0.3}

    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setattr(bench, "run_child", flaky)
    assert bench.preflight() == ("", "cpu")
    trail = bench._diag["preflight_attempts"]
    assert len(trail) == 2
    assert trail[0]["ok"] is False and trail[0]["backoff_s"] == 1.0
    assert trail[1]["ok"] is True and "backoff_s" not in trail[1]
