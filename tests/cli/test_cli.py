"""CLI black-box tests (integration-tests/tests/cli_test.rs analog):
`--help`, a full `agent` boot + `query`/`exec` round-trip over a real
config file, plus admin-socket commands against the live agent.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

CLI = [sys.executable, "-m", "corrosion_tpu.cli.main"]
ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}


def run_cli(*args, cwd=None, check=True, timeout=60):
    out = subprocess.run(
        [*CLI, *args], capture_output=True, text=True, cwd=cwd,
        timeout=timeout, env=ENV,
    )
    if check and out.returncode != 0:
        raise AssertionError(
            f"cli {args} failed ({out.returncode}):\n{out.stdout}\n{out.stderr}"
        )
    return out


def test_help():
    out = run_cli("--help")
    for cmd in (
        "agent", "backup", "restore", "query", "exec", "reload", "sync",
        "locks", "cluster", "actor", "subs", "log", "tls", "template",
        "consul", "sim", "db",
    ):
        assert cmd in out.stdout, f"missing command {cmd}"


@pytest.fixture
def live_agent(tmp_path):
    """A real `corrosion-tpu agent` subprocess on loopback with a TOML
    config, API + admin enabled."""
    schema_dir = tmp_path / "schemas"
    schema_dir.mkdir()
    (schema_dir / "base.sql").write_text(
        "CREATE TABLE tests (id INTEGER PRIMARY KEY NOT NULL, "
        "text TEXT NOT NULL DEFAULT '');"
    )
    admin = tmp_path / "admin.sock"
    config = tmp_path / "corrosion.toml"
    config.write_text(
        f"""
[db]
path = "{tmp_path}/agent.db"
schema_paths = ["{schema_dir}"]

[api]
addr = "127.0.0.1:0"

[gossip]
addr = "127.0.0.1:0"

[admin]
path = "{admin}"
"""
    )
    proc = subprocess.Popen(
        [*CLI, "-c", str(config), "agent"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=ENV,
    )
    line = ""
    deadline = time.time() + 30
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "agent running" in line:
            break
        if proc.poll() is not None:
            raise RuntimeError(f"agent died: {proc.stderr.read()}")
    else:
        proc.kill()
        raise RuntimeError("agent did not start in 30s")
    api_addr = line.split("api ")[1].split()[0].strip()
    try:
        yield {"config": str(config), "api": api_addr, "tmp": tmp_path}
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def _cfg_args(env):
    # port-0 API addr resolves at runtime; pass the live one explicitly
    return ["-c", env["config"], "--api-addr", env["api"]]


def test_agent_exec_query_roundtrip(live_agent):
    run_cli(
        *_cfg_args(live_agent), "exec",
        "INSERT INTO tests (id, text) VALUES (1, 'from-cli')",
    )
    out = run_cli(
        *_cfg_args(live_agent), "query", "--columns",
        "SELECT id, text FROM tests",
    )
    assert out.stdout.splitlines() == ["id\ttext", "1\tfrom-cli"]


def test_admin_commands_against_live_agent(live_agent):
    args = _cfg_args(live_agent)

    sync = json.loads(run_cli(*args, "sync", "generate").stdout)
    assert "actor_id" in sync and "heads" in sync

    locks = json.loads(run_cli(*args, "locks", "--top", "5").stdout)
    assert isinstance(locks, list)

    members = json.loads(run_cli(*args, "cluster", "members").stdout)
    assert isinstance(members, list)

    states = json.loads(run_cli(*args, "cluster", "membership-states").stdout)
    assert isinstance(states, list)

    subs = json.loads(run_cli(*args, "subs", "list").stdout)
    assert subs == []

    out = json.loads(run_cli(*args, "log", "set", "debug").stdout)
    assert out == "debug"
    json.loads(run_cli(*args, "log", "reset").stdout)

    recon = json.loads(run_cli(*args, "sync", "reconcile-gaps").stdout)
    assert recon["count"] == 0


def test_reload_applies_new_schema_file(live_agent):
    schema_dir = live_agent["tmp"] / "schemas"
    (schema_dir / "extra.sql").write_text(
        "CREATE TABLE extras (id INTEGER PRIMARY KEY NOT NULL, n INTEGER);"
    )
    out = json.loads(run_cli(*_cfg_args(live_agent), "reload").stdout)
    assert out["new_tables"] == ["extras"]
    run_cli(
        *_cfg_args(live_agent), "exec", "INSERT INTO extras (id, n) VALUES (1, 2)"
    )
    q = run_cli(*_cfg_args(live_agent), "query", "SELECT n FROM extras")
    assert q.stdout.strip() == "2"


def test_actor_version_classification(live_agent):
    run_cli(
        *_cfg_args(live_agent), "exec",
        "INSERT INTO tests (id, text) VALUES (9, 'v')",
    )
    sync = json.loads(run_cli(*_cfg_args(live_agent), "sync", "generate").stdout)
    actor = sync["actor_id"]
    out = json.loads(
        run_cli(*_cfg_args(live_agent), "actor", "version", actor, "1").stdout
    )
    assert out["kind"] == "current"
    out = json.loads(
        run_cli(*_cfg_args(live_agent), "actor", "version", actor, "99").stdout
    )
    assert out["kind"] == "unknown"


def test_backup_restore_via_cli(tmp_path):
    from corrosion_tpu.agent.store import CrrStore
    from corrosion_tpu.core.types import ActorId

    db = str(tmp_path / "n.db")
    s = CrrStore(db, ActorId.random())
    s.execute_schema(
        "CREATE TABLE tests (id INTEGER PRIMARY KEY NOT NULL, "
        "text TEXT NOT NULL DEFAULT '')"
    )
    s.transact([("INSERT INTO tests (id, text) VALUES (1, 'keep')", ())])
    s.close()

    snap = str(tmp_path / "snap.db")
    run_cli("--db-path", db, "backup", snap)
    restored = str(tmp_path / "restored.db")
    out = run_cli("--db-path", restored, "restore", snap)
    assert "as actor" in out.stdout

    s2 = CrrStore(restored, ActorId.random())
    assert s2.query("SELECT text FROM tests")[0][0] == "keep"
    s2.close()


def test_sim_smoke():
    out = run_cli("sim", "ground-truth-3node", timeout=300)
    m = json.loads(out.stdout)
    assert m.get("converged", 0) >= 1 or m.get("rounds", 0) > 0, m


def test_sim_topo_show_cli():
    """`sim topo show` (ISSUE 9): the family registry (jax-free) and a
    tier table (imports the Topology dataclass; runs no jax op)."""
    out = run_cli("sim", "topo", "show")
    assert "wan-3x2" in out.stdout and "hetero-degree" in out.stdout

    out = run_cli(
        "sim", "topo", "show", "--topology", "wan-3x2", "--nodes", "96",
        "--json",
    )
    m = json.loads(out.stdout)
    assert m["n_nodes"] == 96
    assert len(m["az_blocks"]) == 6  # 3 regions × 2 AZs
    assert m["tiers"]["cross-region"]["delay_rounds"] == 2
    assert m["host_link_events"] > 0

    out = run_cli(
        "sim", "topo", "show", "--topology", "no-such-family", check=False
    )
    assert out.returncode != 0


def test_sim_topology_flag_refused_on_axisless_scenario():
    """--topology/--sampler must refuse loudly on scenarios without the
    axis (a silently ignored topology would fake a WAN measurement)."""
    out = run_cli(
        "sim", "swim-churn-64", "--topology", "wan-3x2", check=False
    )
    assert out.returncode == 2
    assert "does not take" in out.stderr


def test_sim_proto_show_cli():
    """`sim proto show` (ISSUE 11): the protocol-family registry —
    entirely jax-free — plus a resolved family view, and the exit-2
    refusal with the family list on an unknown name."""
    out = run_cli("sim", "proto", "show")
    assert "swarm-aggressive" in out.stdout
    assert "lab-ordered" in out.stdout

    out = run_cli("sim", "proto", "show", "--proto", "push-pull", "--json")
    m = json.loads(out.stdout)
    assert m["overlay"] == {"dissemination": "push-pull"}
    assert m["resolved"]["dissemination"] == "push-pull"
    assert m["resolved"]["sync_cadence"] == "periodic"

    out = run_cli(
        "sim", "proto", "show", "--proto", "no-such-family", check=False
    )
    assert out.returncode == 2
    assert "baseline" in out.stderr  # the family list rides the error


def test_sim_proto_flag_validation():
    """--proto on scenario runs (ISSUE 11): refused on axis-less
    scenarios, and an UNKNOWN family exits 2 with the list instead of a
    traceback (the PR 9 --topology rule)."""
    out = run_cli(
        "sim", "swim-churn-64", "--proto", "push-pull", check=False
    )
    assert out.returncode == 2
    assert "does not take" in out.stderr

    out = run_cli(
        "sim", "broadcast-1k", "--proto", "no-such-family", check=False
    )
    assert out.returncode == 2
    assert "unknown protocol family" in out.stderr
    assert "baseline" in out.stderr


def test_sim_trace_show_parity_join(tmp_path):
    """`sim trace show --parity` (ISSUE 11 carried edge): a sim lane
    and its host-parity replay render as ONE joined table — host
    per-write rows bucketed onto sim rounds via --round-s."""
    sim_path = tmp_path / "sim.jsonl"
    host_path = tmp_path / "host.jsonl"
    sim_head = {
        "kind": "flight_recorder", "version": 1, "n_nodes": 3,
        "n_payloads": 4, "rounds": 2, "summary": {},
    }
    sim_rows = [
        {"t": 0, "coverage_frac": 0.5, "delivered": 2, "bcast_bytes": 64.0,
         "sync_sessions": 0},
        {"t": 1, "coverage_frac": 1.0, "delivered": 2, "bcast_bytes": 32.0,
         "sync_sessions": 1},
    ]
    sim_path.write_text(
        "\n".join(json.dumps(r) for r in [sim_head] + sim_rows) + "\n"
    )
    host_head = {
        "kind": "flight_recorder", "version": 1, "tier": "host",
        "n_nodes": 3, "writes": 2, "summary": {},
    }
    host_rows = [
        {"t": 0.01, "actor": "a", "version": 1, "node": 0,
         "publish_to_visible_ms": 12.5, "hlc_lag_ms": 1.0},
        {"t": 0.06, "actor": "a", "version": 2, "node": 0,
         "publish_to_visible_ms": 20.0},
    ]
    host_path.write_text(
        "\n".join(json.dumps(r) for r in [host_head] + host_rows) + "\n"
    )

    out = run_cli(
        "sim", "trace", "show", "--in", str(sim_path),
        "--parity", str(host_path), "--round-s", "0.05", "--json",
    )
    m = json.loads(out.stdout)
    assert m["round_s"] == 0.05
    rounds = m["rounds"]
    assert len(rounds) == 2
    assert rounds[0]["host_writes"] == 1
    assert rounds[0]["host_visible_ms_max"] == 12.5
    assert rounds[0]["coverage_frac"] == 0.5
    assert rounds[1]["host_writes"] == 1
    assert rounds[1]["host_visible_ms_max"] == 20.0

    # the table form renders too
    out = run_cli(
        "sim", "trace", "show", "--in", str(sim_path),
        "--parity", str(host_path),
    )
    assert "host_writes" in out.stdout

    # tier mix-ups refuse loudly instead of joining garbage
    out = run_cli(
        "sim", "trace", "show", "--in", str(host_path),
        "--parity", str(host_path), check=False,
    )
    assert out.returncode == 2
    out = run_cli(
        "sim", "trace", "show", "--in", str(sim_path),
        "--parity", str(sim_path), check=False,
    )
    assert out.returncode == 2


def test_sim_campaign_compare_cli(tmp_path):
    """`sim campaign compare` verdict + exit codes on synthetic
    artifacts (no jax in this path — the spec/report layer is plain
    Python); the full run|compare round trip is the campaign nightly
    (tests/campaign/test_campaign_engine.py)."""
    cell = {
        "params": {}, "per_seed": {"rounds": [30, 31]},
        "bands": {"rounds": {"p50": 30, "p95": 31, "p99": 31}},
        "all_converged": True,
    }
    base = {"spec_hash": "h", "cells": [cell], "result_digest": "d"}
    worse = json.loads(json.dumps(base))
    worse["cells"][0]["bands"]["rounds"]["p99"] = 60
    worse["result_digest"] = "d2"
    p_base, p_same, p_worse = (
        tmp_path / "base.json", tmp_path / "same.json", tmp_path / "worse.json"
    )
    p_base.write_text(json.dumps(base))
    p_same.write_text(json.dumps(base))
    p_worse.write_text(json.dumps(worse))

    out = run_cli(
        "sim", "campaign", "compare",
        "--baseline", str(p_base), "--candidate", str(p_same),
    )
    rep = json.loads(out.stdout)
    assert rep["verdict"] == "pass" and rep["identical_results"]

    out = run_cli(
        "sim", "campaign", "compare",
        "--baseline", str(p_base), "--candidate", str(p_worse),
        check=False,
    )
    assert out.returncode == 1
    assert json.loads(out.stdout)["verdict"] == "regress"
