"""Wedge self-defense (VERDICT r2 item 8): bench.py must SIGKILL stale
repo-spawned TPU-client processes (bench_child remnants) before
preflight, and must NOT touch unrelated processes."""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def test_kills_stale_bench_child_but_spares_others():
    # SANDBOXED: a unique marker + temp repo root so the test can never
    # shoot a concurrently-running real bench child
    import tempfile

    sandbox = tempfile.mkdtemp(prefix="benchdef_")
    marker = "sandbox_fake_child_a7x.py"
    # a fake stale bench child: python process whose cmdline carries the
    # marker (as an inert extra argv) and cwd inside the sandbox repo
    stale = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(120)", marker],
        cwd=sandbox,
    )
    # an unrelated sandbox-cwd python process without any marker
    bystander = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(120)"],
        cwd=sandbox,
    )
    # a marker process OUTSIDE the sandbox (sibling-checkout scenario)
    outside = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(120)", marker],
        cwd="/tmp",
    )
    try:
        time.sleep(0.3)
        killed = bench.kill_stale_device_holders(
            markers=(marker,), repo=sandbox
        )
        assert stale.pid in killed, killed
        deadline = time.time() + 5
        while stale.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        assert stale.poll() is not None, "stale bench child must die"
        assert bystander.poll() is None, "unmarked process must survive"
        assert outside.poll() is None, "outside-repo process must survive"
        assert bystander.pid not in killed
        assert outside.pid not in killed
    finally:
        for p in (stale, bystander, outside):
            if p.poll() is None:
                p.kill()
            p.wait(timeout=5)
