"""devcluster harness tests: topology parsing + a real 3-process cluster
converging through gossip (corro-devcluster/src/main.rs:102-240)."""

import asyncio
import json
import os
import urllib.request

import pytest

from corrosion_tpu.devcluster import DevCluster, Topology, generate_config


def test_topology_parse():
    topo = Topology.parse(
        """
        # A bootstraps to B, B to C, D is a pure responder
        A -> B
        B -> C
        D
        """
    )
    assert topo.nodes == ["A", "B", "C", "D"]
    assert topo.links["A"] == ["B"]
    assert topo.links["C"] == []
    assert topo.links["D"] == []


def test_topology_rejects_garbage():
    with pytest.raises(ValueError):
        Topology.parse("A -> ")
    with pytest.raises(ValueError):
        Topology.parse("   \n# only comments\n")


def test_generate_config_shape():
    cfg = generate_config("/state/A", "/schemas", 7000, 7001, ["127.0.0.1:7002"])
    assert 'path = "/state/A/corrosion.db"' in cfg
    assert 'addr = "127.0.0.1:7000"' in cfg
    assert 'bootstrap = ["127.0.0.1:7002"]' in cfg
    assert 'addr = "127.0.0.1:7001"' in cfg


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"content-type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=10).read())


def test_three_node_line_topology_converges(tmp_path):
    schema_dir = tmp_path / "schemas"
    schema_dir.mkdir()
    (schema_dir / "base.sql").write_text(
        "CREATE TABLE tests (id INTEGER PRIMARY KEY, text TEXT);"
    )
    topo = Topology.parse("A -> B\nB -> C")
    cluster = DevCluster(topo, str(tmp_path / "state"), str(schema_dir))
    cluster.write_configs()
    # every node got a config + distinct ports
    ports = {n.gossip_port for n in cluster.nodes.values()}
    assert len(ports) == 3
    cluster.start(stagger_s=0.1)
    try:
        cluster.wait_ready(timeout=30)
        a, c = cluster.nodes["A"], cluster.nodes["C"]
        _post(
            f"http://{a.api_addr}/v1/transactions",
            [["INSERT INTO tests (id, text) VALUES (1, 'devcluster')", []]],
        )

        # A -> B -> C is a line: the write must hop through B to C
        async def poll():
            from corrosion_tpu.api.client import ApiClient

            client = ApiClient(c.api_addr)
            for _ in range(150):
                rows = await client.query("SELECT text FROM tests WHERE id = 1")
                if rows:
                    return rows
                await asyncio.sleep(0.2)
            return []

        rows = asyncio.run(poll())
        assert rows == [["devcluster"]]
        # node.log exists per node
        for node in cluster.nodes.values():
            assert os.path.exists(os.path.join(node.state_dir, "node.log"))
    finally:
        cluster.stop()
