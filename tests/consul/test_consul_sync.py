"""Consul sync: hash-diff replication of agent services/checks.

Spec: crates/corrosion/src/command/consul/sync.rs (pull → hash → diff →
/v1/transactions) with its inline tests (sync.rs:745-980) as the model:
first pass inserts, unchanged pass writes nothing, changed service updates,
removed service deletes, and check-status flaps respect hash_include notes.
"""

import asyncio
import json

from corrosion_tpu.api.client import ApiClient
from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.consul.client import ConsulClient
from corrosion_tpu.consul.sync import run_sync, setup, sync_pass, _load_hashes
from corrosion_tpu.testing import Cluster

CONSUL_SCHEMA = """
CREATE TABLE consul_services (
    node TEXT NOT NULL,
    id TEXT NOT NULL,
    name TEXT NOT NULL DEFAULT '',
    tags TEXT NOT NULL DEFAULT '[]',
    meta TEXT NOT NULL DEFAULT '{}',
    port INTEGER NOT NULL DEFAULT 0,
    address TEXT NOT NULL DEFAULT '',
    updated_at INTEGER NOT NULL DEFAULT 0,
    source TEXT,
    PRIMARY KEY (node, id)
);
CREATE TABLE consul_checks (
    node TEXT NOT NULL,
    id TEXT NOT NULL,
    service_id TEXT NOT NULL DEFAULT '',
    service_name TEXT NOT NULL DEFAULT '',
    name TEXT NOT NULL DEFAULT '',
    status TEXT NOT NULL DEFAULT '',
    output TEXT NOT NULL DEFAULT '',
    updated_at INTEGER NOT NULL DEFAULT 0,
    source TEXT,
    PRIMARY KEY (node, id)
);
"""


class StubConsul:
    """Canned /v1/agent/{services,checks} responses."""

    def __init__(self):
        self.services = {}
        self.checks = {}
        self.addr = ""
        self._server = None

    async def start(self):
        self._server = await asyncio.start_server(self._on_conn, "127.0.0.1", 0)
        port = self._server.sockets[0].getsockname()[1]
        self.addr = f"127.0.0.1:{port}"

    async def stop(self):
        self._server.close()
        await self._server.wait_closed()

    async def _on_conn(self, reader, writer):
        line = await reader.readline()
        path = line.split()[1].decode()
        while (await reader.readline()) not in (b"\r\n", b""):
            pass
        body = json.dumps(
            self.services if path.endswith("services") else self.checks
        ).encode()
        writer.write(
            b"HTTP/1.1 200 OK\r\ncontent-type: application/json\r\n"
            + f"content-length: {len(body)}\r\n\r\n".encode()
            + body
        )
        await writer.drain()
        writer.close()


async def _env(fn):
    cluster = Cluster(2, schema=CONSUL_SCHEMA)
    await cluster.start()
    srv = ApiServer(cluster.agents[0])
    await srv.start()
    stub = StubConsul()
    await stub.start()
    client = ApiClient(srv.addr)
    try:
        await fn(cluster, client, stub)
    finally:
        await stub.stop()
        await srv.stop()
        await cluster.stop()


SVC1 = {
    "ID": "web-1", "Service": "web", "Tags": ["http"],
    "Meta": {"env": "prod"}, "Port": 8080, "Address": "10.0.0.1",
}
CHK1 = {
    "CheckID": "web-1-alive", "Name": "alive", "Status": "passing",
    "Output": "ok", "ServiceID": "web-1", "ServiceName": "web",
}


def test_first_pass_inserts_then_noop_then_update_then_delete():
    async def body(cluster, client, stub):
        stub.services = {"web-1": SVC1}
        stub.checks = {"web-1-alive": CHK1}
        consul = ConsulClient(stub.addr)
        await setup(client, "nodeA")
        svc_h, chk_h = {}, {}

        s, c = await sync_pass(client, consul, "nodeA", svc_h, chk_h)
        assert (s["upserted"], c["upserted"]) == (1, 1)
        rows = await client.query(
            "SELECT node, id, name, tags, port FROM consul_services"
        )
        assert rows == [["nodeA", "web-1", "web", '["http"]', 8080]]
        rows = await client.query("SELECT id, status FROM consul_checks")
        assert rows == [["web-1-alive", "passing"]]

        # unchanged: nothing written
        s, c = await sync_pass(client, consul, "nodeA", svc_h, chk_h)
        assert (s["upserted"], s["deleted"], c["upserted"]) == (0, 0, 0)

        # service changed: one upsert
        stub.services = {"web-1": {**SVC1, "Port": 9090}}
        s, c = await sync_pass(client, consul, "nodeA", svc_h, chk_h)
        assert s["upserted"] == 1
        rows = await client.query("SELECT port FROM consul_services")
        assert rows == [[9090]]

        # service + check removed: rows deleted
        stub.services, stub.checks = {}, {}
        s, c = await sync_pass(client, consul, "nodeA", svc_h, chk_h)
        assert (s["deleted"], c["deleted"]) == (1, 1)
        assert await client.query("SELECT * FROM consul_services") == []
        assert await client.query("SELECT * FROM consul_checks") == []
        assert svc_h == {} and chk_h == {}

    asyncio.run(_env(body))


def test_hash_state_survives_restart_of_sync():
    async def body(cluster, client, stub):
        stub.services = {"web-1": SVC1}
        consul = ConsulClient(stub.addr)
        await setup(client, "nodeA")
        svc_h, chk_h = {}, {}
        await sync_pass(client, consul, "nodeA", svc_h, chk_h)

        # a fresh sync process reloads hashes from the DB: no rewrites
        svc_h2 = await _load_hashes(client, "__corro_consul_services")
        assert svc_h2 == svc_h
        s, _ = await sync_pass(client, consul, "nodeA", svc_h2, {})
        assert s["upserted"] == 0

    asyncio.run(_env(body))


def test_check_output_flap_ignored_without_notes_directive():
    async def body(cluster, client, stub):
        stub.checks = {"web-1-alive": CHK1}
        consul = ConsulClient(stub.addr)
        await setup(client, "nodeA")
        svc_h, chk_h = {}, {}
        await sync_pass(client, consul, "nodeA", svc_h, chk_h)

        # output changes but status doesn't: default hash ignores output
        stub.checks = {"web-1-alive": {**CHK1, "Output": "ok again"}}
        _, c = await sync_pass(client, consul, "nodeA", svc_h, chk_h)
        assert c["upserted"] == 0

        # with the notes directive, output participates (sync.rs:360-386)
        noted = {
            **CHK1,
            "Notes": json.dumps({"hash_include": ["status", "output"]}),
        }
        stub.checks = {"web-1-alive": noted}
        await sync_pass(client, consul, "nodeA", svc_h, chk_h)
        stub.checks = {"web-1-alive": {**noted, "Output": "different"}}
        _, c = await sync_pass(client, consul, "nodeA", svc_h, chk_h)
        assert c["upserted"] == 1

    asyncio.run(_env(body))


def test_consul_rows_replicate_across_cluster():
    async def body(cluster, client, stub):
        stub.services = {"web-1": SVC1}
        await run_sync(client, consul_addr=stub.addr, node="nodeA", once=True)
        # the second agent receives the service row via gossip
        for _ in range(100):
            rows = cluster.agents[1].store.query(
                "SELECT node, id, port FROM consul_services"
            )
            if rows:
                break
            await asyncio.sleep(0.05)
        assert [tuple(r) for r in rows] == [("nodeA", "web-1", 8080)]

    asyncio.run(_env(body))


def test_setup_rejects_missing_schema():
    async def body():
        cluster = Cluster(1)  # TEST_SCHEMA: no consul tables
        await cluster.start()
        srv = ApiServer(cluster.agents[0])
        await srv.start()
        try:
            client = ApiClient(srv.addr)
            try:
                await setup(client, "n")
                raise AssertionError("setup should have failed")
            except RuntimeError as e:
                assert "consul_services" in str(e)
        finally:
            await srv.stop()
            await cluster.stop()

    asyncio.run(body())
