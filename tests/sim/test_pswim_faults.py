"""FaultPlan through partial-view SWIM (ISSUE 3 satellite): the ROADMAP
gap where pswim probes sailed through partitions while broadcast/sync
honored them is closed — `pswim_step` consumes `RoundFaults` via
`_reachable`, same seam as the full-view kernel."""

import numpy as np
import pytest

from corrosion_tpu.faults import FaultEvent, FaultPlan
from corrosion_tpu.sim.faults import compile_plan, round_faults, run_fault_plan
from corrosion_tpu.sim.round import new_sim, round_step
from corrosion_tpu.sim.state import ALIVE, SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import Topology, regions


def _pcfg(n=12, **kw):
    kw.setdefault("member_slots", 4)
    kw.setdefault("probe_period_rounds", 1)
    kw.setdefault("suspect_timeout_rounds", 2)
    return SimConfig(
        n_nodes=n, n_payloads=1, fanout=2, swim_partial_view=True,
        sync_interval_rounds=4, **kw
    )


@pytest.mark.chaos
def test_pswim_probes_honor_faultplan_partition():
    """A node symmetric-partitioned by a FaultPlan must be detected by
    the partial-view tier: probes to it fail (direct AND relayed), its
    announces never land, so watchers' table entries for it go
    SUSPECT→DOWN — while a fault-free control run of the same seed
    never suspects anyone (no loss, no cuts ⇒ every probe acks)."""
    cfg = _pcfg()
    meta = uniform_payloads(cfg, inject_every=1)
    topo = Topology()
    plan = FaultPlan(
        n_nodes=cfg.n_nodes, seed=4,
        events=(
            FaultEvent("partition", 0, 30, src=0, dst="*", symmetric=True),
        ),
    )
    fplan = compile_plan(plan, cfg, topo)
    final, _ = run_fault_plan(
        new_sim(cfg, seed=9), meta, cfg, topo, fplan, max_rounds=30
    )
    pid = np.asarray(final.pid)
    pkey = np.asarray(final.pkey)
    alive = np.asarray(final.alive)
    assert (alive == ALIVE).all()  # the partition downs nobody for real
    # somebody tracked node 0 and marked it non-ALIVE (it cannot refute:
    # every message it sends is cut)
    about0 = (pid == 0) & (np.arange(cfg.n_nodes)[:, None] != 0)
    assert about0.any()
    assert ((pkey % 4 != ALIVE) & about0).sum() > 0, (
        "no watcher ever suspected the partitioned node — probes are "
        "sailing through the FaultPlan cut"
    )

    # control: same scenario seed, no faults — nobody is ever suspected
    ctl, _ = run_fault_plan(
        new_sim(cfg, seed=9), meta, cfg, topo,
        compile_plan(FaultPlan(n_nodes=cfg.n_nodes, seed=4, events=()),
                     cfg, topo),
        max_rounds=30,
    )
    cpid, cpkey = np.asarray(ctl.pid), np.asarray(ctl.pkey)
    filled = cpid >= 0
    assert (cpkey[filled] % 4 == ALIVE).all()


def test_wipe_empties_membership_beliefs_too():
    """A crash-with-wipe must lose the node's own membership state —
    partial-view: member table back to EMPTY (announce/refill/gossip
    repopulate it); full-view: belief row back to the optimistic init —
    else a 'wiped' node rejoins with a warm member list and campaign
    recovery rounds are under-reported vs the host tier's cold rejoin."""
    import jax.numpy as jnp

    from corrosion_tpu.sim.faults import RoundFaults, apply_node_faults

    for kind in ("partial", "full"):
        cfg = (
            _pcfg(n=6)
            if kind == "partial"
            else SimConfig(n_nodes=6, n_payloads=1, fanout=2,
                           swim_full_view=True)
        )
        state = new_sim(cfg, seed=1)
        n = cfg.n_nodes
        rf = RoundFaults(
            block=jnp.zeros((n, n), bool), loss=jnp.zeros((n, n), jnp.uint8),
            delay=jnp.zeros((n, n), jnp.uint8),
            jitter=jnp.zeros((n, n), jnp.uint8),
            alive=jnp.full((n,), -1, jnp.int8),
            wipe=jnp.arange(n) == 2, seed=jnp.int32(0),
        )
        wiped = apply_node_faults(state, rf)
        if kind == "partial":
            assert (np.asarray(wiped.pid)[2] == -1).all()
            assert (np.asarray(wiped.pkey)[2] == -1).all()
            assert (np.asarray(wiped.pid)[0] == np.asarray(state.pid)[0]).all()
        else:
            assert (np.asarray(wiped.view)[2] == 0).all()
            assert (np.asarray(wiped.vinc)[2] == 0).all()
            assert (
                np.asarray(wiped.view)[0] == np.asarray(state.view)[0]
            ).all()


def test_pswim_all_clear_faults_byte_identical_to_none():
    """RNG compatibility: an all-clear RoundFaults slice must leave the
    pswim phase byte-identical to faults=None — fault keys are fold_in-
    derived inside the `faults is not None` branch, never split from the
    phase keys, so existing seeded partial-view runs replay unchanged."""
    cfg = _pcfg(n=8)
    meta = uniform_payloads(cfg, inject_every=1)
    topo = Topology()
    region = regions(cfg.n_nodes, topo.n_regions)
    fplan = compile_plan(
        FaultPlan(n_nodes=cfg.n_nodes, seed=0, events=()), cfg, topo
    )
    from corrosion_tpu.sim.round import new_metrics

    sa = sb = new_sim(cfg, seed=3)
    ma = mb = new_metrics(cfg)
    for _ in range(6):
        rf = round_faults(fplan, sa.t)
        sa, ma = round_step(sa, ma, meta, cfg, topo, region, faults=rf)
        sb, mb = round_step(sb, mb, meta, cfg, topo, region, faults=None)
    for name in ("pid", "pkey", "psince", "incarnation", "have", "heads"):
        assert (
            np.asarray(getattr(sa, name)) == np.asarray(getattr(sb, name))
        ).all(), f"{name} diverged under an all-clear fault slice"
