"""Topology & peer-sampling subsystem (ISSUE 9).

Three contracts under test:

1. **No-axes byte-identity** — with default Topology and the uniform
   sampler, every kernel compiles to the pre-ISSUE-9 program: final
   states of seeded runs equal digests captured on the pre-change tree
   (dense, packed, fault-seam, and topology-loss paths), and the
   builtin campaign specs keep their hashes — so existing replay
   digests, spec hashes, and committed baselines stand.
2. **Generator correctness** — geo tiers (region × AZ delay/loss
   classes), heterogeneous degree caps, churn schedules compiling to
   range-selector crash events identical in matrix and factored form,
   and the shard-safe `aligned_u8_bits` staying byte-identical to the
   jax u8 draw it replaces.
3. **PeerSwap sampler** — deterministic, self-free views, convergence
   under the seam, and cold-rejoin via wipe + refill.
"""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.faults import FaultEvent, FaultPlan
from corrosion_tpu.sim.faults import compile_plan, run_fault_plan
from corrosion_tpu.sim.round import new_sim, run_to_convergence
from corrosion_tpu.sim.state import ALIVE, SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import (
    Topology,
    aligned_u8_bits,
    apply_degree_caps,
    azs,
    edge_delay,
    edge_loss_thresholds,
    loss_tiered,
    loss_tiers,
    node_degrees,
    regions,
)
from corrosion_tpu.topo import (
    FAMILIES,
    churn_events,
    diurnal_events,
    family_topology,
    flash_crowd_events,
    min_delay_slots,
    topology_link_events,
)


def _digest(state, skip=("pview",)):
    """blake2b over the PRE-ISSUE-9 state fields (pview is the one new
    field; uniform runs carry it zero-width, so excluding it makes the
    digest comparable to constants captured on the pre-change tree)."""
    h = hashlib.blake2b(digest_size=8)
    for f, v in zip(type(state)._fields, state):
        if f in skip:
            continue
        h.update(f.encode())
        h.update(np.ascontiguousarray(np.asarray(v)).tobytes())
    return h.hexdigest()


# -- 1. no-axes byte-identity ------------------------------------------------


def test_default_dense_run_byte_identical_to_pre_topo_tree():
    """Digest captured on the pre-ISSUE-9 tree: the default dense
    kernels must not move a single bit."""
    cfg = SimConfig(n_nodes=24, n_payloads=16, fanout=2, sync_interval_rounds=4)
    meta = uniform_payloads(cfg, inject_every=1)
    final, _ = run_to_convergence(new_sim(cfg, 3), meta, cfg, Topology(), 200)
    assert int(final.t) == 20
    assert _digest(final) == "c5d4e8bcd80cb0ef"


def test_default_packed_run_byte_identical_to_pre_topo_tree():
    cfg = dataclasses.replace(
        SimConfig(n_nodes=64, n_payloads=64, fanout=3), packed_min_cells=0
    )
    meta = uniform_payloads(cfg, inject_every=1)
    final, _ = run_to_convergence(new_sim(cfg, 5), meta, cfg, Topology(), 300)
    assert _digest(final) == "e982c755a7e10cdc"


def test_default_fault_run_byte_identical_to_pre_topo_tree():
    """The fault seam (loss draws ride aligned_u8_bits' padded branch,
    so this also pins the u32-word rewrite's value compatibility)."""
    cfg = SimConfig(
        n_nodes=12, n_payloads=12, fanout=2, sync_interval_rounds=4,
        n_delay_slots=4,
    )
    meta = uniform_payloads(cfg, inject_every=1)
    plan = FaultPlan(
        n_nodes=12, seed=7,
        events=(
            FaultEvent("loss", 0, 12, p=0.3),
            FaultEvent(
                "partition", 2, 8, src="0:4", dst="8:12", symmetric=True
            ),
            FaultEvent("crash", 6, 10, node=1, wipe=True),
        ),
    )
    fplan = compile_plan(plan, cfg, Topology())
    final, _ = run_fault_plan(
        new_sim(cfg, 7), meta, cfg, Topology(), fplan, 300
    )
    assert _digest(final) == "75f3dd63bffb6229"


def test_default_topology_loss_run_byte_identical_to_pre_topo_tree():
    """Flat lossy multi-region topology (the legacy scalar-threshold
    loss kernel + full-view SWIM probes): still the exact old program."""
    topo = Topology(n_regions=2, inter_delay=2, loss=0.2)
    cfg = SimConfig(
        n_nodes=24, n_payloads=16, fanout=2, n_delay_slots=4,
        swim_full_view=True,
    )
    meta = uniform_payloads(cfg, inject_every=1)
    final, _ = run_to_convergence(new_sim(cfg, 9), meta, cfg, topo, 400)
    assert _digest(final) == "2db264c4fed9b337"


def test_builtin_spec_hashes_unchanged():
    """Adding the topo/churn/sampler axes must not move any existing
    builtin's replay identity (hashes captured pre-change)."""
    from corrosion_tpu.campaign.spec import BUILTIN_SPECS

    pinned = {
        "fault-campaign-3node": "b541e15a6f3bbb66",
        "fault-parity-3node": "3f8f271fb5dbe3ec",
        "serving-3node": "287f88dabcfa1791",
        "swim-churn-64": "9d9d65cd293398f1",
        "swim-churn-partial": "ce7b33791aa01fce",
    }
    for name, want in pinned.items():
        assert BUILTIN_SPECS[name]().spec_hash() == want, name


# -- 2. aligned_u8_bits (carried edge: word-atom draws) ----------------------


@pytest.mark.parametrize(
    "shape", [(128,), (3,), (72,), (6, 12), (510,), (96, 64), (1008,)]
)
def test_aligned_u8_bits_matches_jax_u8_draw(shape):
    """The explicit u32-word draw + little-endian unpack must reproduce
    jax's u8 draw byte-for-byte under the unchanged 128-pad rule — the
    value-compat contract that keeps every committed replay digest and
    campaign baseline standing while making the RNG's shardable atoms
    whole words (safe on ANY mesh size, 6 chips included)."""
    key = jax.random.PRNGKey(sum(shape) + 11)
    size = int(np.prod(shape))
    if size % 128 == 0:
        ref = jax.random.bits(key, shape, dtype=jnp.uint8)
    else:
        pad = -(-size // 128) * 128
        ref = jax.random.bits(key, (pad,), dtype=jnp.uint8)[:size].reshape(
            shape
        )
    np.testing.assert_array_equal(
        np.asarray(ref), np.asarray(aligned_u8_bits(key, shape))
    )


# -- 2. geo tiers, degrees, churn --------------------------------------------


def test_az_blocks_and_edge_delay_classes():
    topo = Topology(
        n_regions=3, n_azs=2, intra_delay=0, az_delay=1, inter_delay=2
    )
    n = 96
    reg = np.asarray(regions(n, topo.n_regions))
    az = np.asarray(azs(n, topo))
    # contiguous blocks: 3 regions × 2 AZs of 16 nodes each
    assert (np.diff(az) >= 0).all()
    assert [int((az == a).sum()) for a in range(6)] == [16] * 6
    assert (az // topo.n_azs == reg).all()

    region = regions(n, topo.n_regions)
    src = jnp.asarray([0, 0, 0], jnp.int32)
    dst = jnp.asarray([5, 20, 40], jnp.int32)  # same-az, cross-az, cross-reg
    d = np.asarray(edge_delay(topo, region, src, dst))
    assert list(d) == [0, 1, 2]


def test_edge_loss_tiers_and_thresholds():
    topo = Topology(
        n_regions=2, n_azs=2, loss=0.0, az_loss=0.05, inter_loss=0.2
    )
    assert loss_tiered(topo)
    base, az_t, inter_t = loss_tiers(topo)
    assert (base, az_t, inter_t) == (0, round(0.05 * 256), round(0.2 * 256))
    n = 32
    region = regions(n, topo.n_regions)
    src = jnp.asarray([0, 0, 0], jnp.int32)
    dst = jnp.asarray([1, 10, 20], jnp.int32)
    thr = np.asarray(edge_loss_thresholds(topo, region, src, dst))
    assert list(thr) == [0, az_t, inter_t]
    # tiers that collapse to one class stay on the legacy kernel
    assert not loss_tiered(Topology(n_regions=2, loss=0.1))
    assert not loss_tiered(Topology(loss=0.3))


def test_certainty_tier_severs_probes_and_payloads():
    """A p=1.0 tier saturates the u8 compare at 255/256 — BOTH loss
    seams (per-payload drop and probe/swap reachability) must pin those
    edges fully severed, not leak 1/256 of traffic."""
    from corrosion_tpu.sim.swim import _reachable
    from corrosion_tpu.sim.topology import edge_payload_drop

    topo = Topology(n_regions=2, inter_loss=1.0, loss=0.01)
    assert loss_tiered(topo)
    n = 16
    cfg = SimConfig(n_nodes=n, n_payloads=8, fanout=2)
    state = new_sim(cfg, 0)
    region = regions(n, topo.n_regions)
    # every cross-region probe must fail, at any key
    src = jnp.zeros((64,), jnp.int32)
    dst = jnp.full((64,), 12, jnp.int32)  # other region
    for k in range(3):
        ok = np.asarray(
            _reachable(state, topo, jax.random.PRNGKey(k), src, dst)
        )
        assert not ok.any()
    # and every cross-region payload frame drops
    drop = np.asarray(
        edge_payload_drop(
            topo, jax.random.PRNGKey(1), 64, 8, src=src, dst=dst,
            region=region,
        )
    )
    assert drop.all()


def test_degree_classes_cap_fanout_slots():
    topo = Topology(degree_classes=(3, 2, 1))
    deg = np.asarray(node_degrees(9, topo))
    assert list(deg) == [3, 2, 1] * 3
    targets = jnp.ones((9, 3), jnp.int32) * 5
    capped = np.asarray(apply_degree_caps(targets, topo))
    assert (capped[0] == 5).all()          # degree 3: all slots live
    assert list(capped[1]) == [5, 5, -1]   # degree 2
    assert list(capped[2]) == [5, -1, -1]  # degree 1
    # identity without classes
    assert apply_degree_caps(targets, Topology()) is targets
    # a class above the slot count refuses loudly at validate time
    from corrosion_tpu.sim.round import validate

    with pytest.raises(ValueError, match="degree_classes"):
        validate(
            SimConfig(n_nodes=8, n_payloads=8, fanout=2),
            Topology(degree_classes=(3,)),
        )


def test_churn_schedules_compile_to_range_crash_events():
    evs = flash_crowd_events(100, frac=0.25, join_round=8)
    assert len(evs) == 1 and evs[0].node == "75:100" and evs[0].wipe
    evs = diurnal_events(100, frac=0.2, day_rounds=10, night_rounds=4, cycles=2)
    assert len(evs) == 2
    assert evs[0].start == 10 and evs[0].end == 14
    assert evs[1].start == 24 and evs[1].end == 28
    with pytest.raises(KeyError):
        churn_events("no-such-family", 10)

    # matrix and factored compilers agree on the range-selector crash
    cfg = SimConfig(n_nodes=24, n_payloads=16, fanout=2, n_delay_slots=4)
    plan = FaultPlan(
        n_nodes=24, seed=3,
        events=flash_crowd_events(24, frac=0.25, join_round=6),
    )
    fm = compile_plan(plan, cfg, Topology(), factored=False)
    ff = compile_plan(plan, cfg, Topology(), factored=True)
    np.testing.assert_array_equal(np.asarray(fm.alive), np.asarray(ff.alive))
    np.testing.assert_array_equal(np.asarray(fm.wipe), np.asarray(ff.wipe))
    # the tail is down over the join window and wiped at the join round
    alive = np.asarray(fm.alive)
    assert (alive[0, 18:] == 2).all() and (alive[0, :18] == -1).all()
    assert (alive[6, 18:] == 0).all()
    assert np.asarray(fm.wipe)[6, 18:].all()


def test_flash_crowd_converges_after_join():
    cfg = SimConfig(
        n_nodes=24, n_payloads=16, fanout=2, sync_interval_rounds=4
    )
    meta = uniform_payloads(cfg, inject_every=1)
    plan = FaultPlan(
        n_nodes=24, seed=3,
        events=flash_crowd_events(24, frac=0.25, join_round=6),
    )
    fplan = compile_plan(plan, cfg, Topology())
    final, metrics = run_fault_plan(
        new_sim(cfg, 3), meta, cfg, Topology(), fplan, 400
    )
    conv = np.asarray(metrics.converged_at)
    alive = np.asarray(final.alive)
    assert ((conv >= 0) | (alive != ALIVE)).all()
    assert (np.asarray(final.have) > 0).all()  # joiners recovered fully


def test_topology_link_events_cover_the_tier_rectangles():
    topo = Topology(**family_topology("wan-3x2"))
    evs = topology_link_events(topo, 96, end=30)
    kinds = {e.kind for e in evs}
    assert kinds == {"delay", "loss"}
    # 6 AZ blocks → 30 ordered off-diagonal pairs, each with a delay
    # event; loss events only where the tier threshold is nonzero
    delays = [e for e in evs if e.kind == "delay"]
    assert len(delays) == 30
    # every selector is a range over a contiguous AZ block
    for e in evs:
        assert ":" in e.src and ":" in e.dst
    # the host driver's range atoms accept them without pair expansion
    plan = FaultPlan(n_nodes=96, seed=0, events=evs)
    atoms = plan.range_link_epochs()
    assert 0 < len(atoms) <= 36
    # and the sim's factored compiler takes the same events (disjoint
    # loss rectangles — the non-overlap rule holds by construction)
    cfg = SimConfig(
        n_nodes=96, n_payloads=32, fanout=3,
        n_delay_slots=min_delay_slots(family_topology("wan-3x2")) + 1,
    )
    compile_plan(plan, cfg, Topology(), factored=True)


# -- 3. PeerSwap sampler -----------------------------------------------------


def _pswap_cfg(**kw):
    base = dict(
        n_nodes=24, n_payloads=16, fanout=2, sync_interval_rounds=4,
        peer_sampler="peerswap", view_slots=8,
    )
    base.update(kw)
    return SimConfig(**base)


def test_peerswap_deterministic_and_self_free():
    cfg = _pswap_cfg()
    meta = uniform_payloads(cfg, inject_every=1)
    a, ma = run_to_convergence(new_sim(cfg, 3), meta, cfg, Topology(), 400)
    b, _ = run_to_convergence(new_sim(cfg, 3), meta, cfg, Topology(), 400)
    assert _digest(a, skip=()) == _digest(b, skip=())
    pv = np.asarray(a.pview)
    assert pv.shape == (24, 8)
    assert (pv >= -1).all() and (pv < 24).all()
    assert (pv != np.arange(24)[:, None]).all(), "self entry leaked"
    conv = np.asarray(ma.converged_at)
    assert (conv >= 0).all()


def test_peerswap_views_actually_mix():
    """The swap tick must move entries around: after a run, views differ
    from the seeded initial views on most nodes."""
    cfg = _pswap_cfg()
    meta = uniform_payloads(cfg, inject_every=1)
    init = np.asarray(new_sim(cfg, 3).pview)
    final, _ = run_to_convergence(new_sim(cfg, 3), meta, cfg, Topology(), 400)
    moved = (np.asarray(final.pview) != init).any(axis=1)
    assert moved.mean() > 0.5


def test_peerswap_wipe_rejoins_via_refill():
    """Crash-with-wipe empties the victim's view; incoming swaps plus
    the staggered refill must repopulate it and the node reconverges."""
    cfg = _pswap_cfg()
    meta = uniform_payloads(cfg, inject_every=1)
    plan = FaultPlan(
        n_nodes=24, seed=5,
        events=(FaultEvent("crash", 4, 10, node=3, wipe=True),),
    )
    fplan = compile_plan(plan, cfg, Topology())
    final, metrics = run_fault_plan(
        new_sim(cfg, 5), meta, cfg, Topology(), fplan, 400
    )
    assert (np.asarray(metrics.converged_at) >= 0).all()
    assert (np.asarray(final.pview)[3] >= 0).any(), "wiped view never refilled"


def test_peerswap_packed_matches_dense():
    """The packed round runs the identical swap step: bit-equal final
    state (pview included) against the dense path on the same seed."""
    cfg = dataclasses.replace(
        SimConfig(
            n_nodes=64, n_payloads=64, fanout=3,
            peer_sampler="peerswap", view_slots=8,
        ),
        packed_min_cells=0,
    )
    dense_cfg = dataclasses.replace(cfg, allow_packed=False)
    meta = uniform_payloads(cfg, inject_every=1)
    packed, mp = run_to_convergence(
        new_sim(cfg, 5), meta, cfg, Topology(), 600
    )
    dense, md = run_to_convergence(
        new_sim(dense_cfg, 5), meta, dense_cfg, Topology(), 600
    )
    for x, y in zip(jax.tree.leaves(packed), jax.tree.leaves(dense)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(jax.tree.leaves(mp), jax.tree.leaves(md)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_sampler_validation():
    with pytest.raises(ValueError, match="peer_sampler"):
        SimConfig(n_nodes=8, n_payloads=8, peer_sampler="nope")
    with pytest.raises(ValueError, match="view_slots"):
        SimConfig(n_nodes=8, n_payloads=8, peer_sampler="peerswap",
                  view_slots=1)
    with pytest.raises(ValueError, match="incompatible"):
        SimConfig(
            n_nodes=8, n_payloads=8, peer_sampler="peerswap",
            swim_partial_view=True,
        )


# -- campaign-spec resolution ------------------------------------------------


def test_spec_topo_family_resolution_and_churn_plan():
    from corrosion_tpu.campaign.spec import CampaignSpec

    spec = CampaignSpec(
        name="t",
        scenario={
            "n_nodes": 48, "n_payloads": 16, "churn": "flash-crowd",
            "churn_frac": 0.25, "churn_round": 6,
        },
        grid={"topo_family": ["wan-3x2", "hetero-degree"],
              "inter_loss": [0.05]},
    )
    cells = spec.cells()
    t0 = spec.topo(cells[0])  # hetero-degree first (sorted keys, product)
    fams = {c["topo_family"]: spec.topo(c) for c in cells}
    wan = fams["wan-3x2"]
    assert wan.n_regions == 3 and wan.n_azs == 2
    assert wan.inter_loss == 0.05  # explicit key overrides the family
    het = fams["hetero-degree"]
    assert het.degree_classes == (3, 2, 1)
    assert isinstance(het.degree_classes, tuple)
    # churn merges into every lane's plan
    plan = spec.fault_plan(cells[0], seed=0)
    assert plan is not None
    assert any(e.kind == "crash" and e.node == "36:48" for e in plan.events)
    assert t0 is not None


def test_families_registry_complete():
    for name in FAMILIES:
        topo = Topology(**family_topology(name))
        assert topo.max_delay < min_delay_slots(family_topology(name)) + 1


# -- measured-RTT-matrix family (ISSUE 13 satellite) -------------------------


def test_region_delay_matrix_edge_delay_gather():
    """A measured-RTT matrix replaces the distance rule: per-edge delay
    is the (region[src], region[dst]) gather, validated square and
    n_azs == 1 only."""
    import jax.numpy as jnp

    from corrosion_tpu.sim.topology import edge_delay, regions

    m = ((0, 1, 2), (1, 0, 3), (2, 3, 0))
    topo = Topology(n_regions=3, region_delay_matrix=m)
    n = 6  # 2 nodes per region
    reg = regions(n, 3)
    src = jnp.asarray([0, 0, 0, 2, 4, 5])
    dst = jnp.asarray([1, 2, 4, 5, 0, 3])
    got = np.asarray(edge_delay(topo, reg, src, dst))
    assert got.tolist() == [0, 1, 2, 3, 2, 3]
    assert topo.max_delay == 3
    with pytest.raises(ValueError, match="region_delay_matrix"):
        Topology(n_regions=2, region_delay_matrix=m)  # not 2x2
    with pytest.raises(ValueError, match="n_azs"):
        Topology(n_regions=3, n_azs=2, region_delay_matrix=m)


def test_wan_fly_family_registered_and_quantized():
    """The committed Fly.io RTT table quantizes into the registered
    wan-fly-6r family: symmetric classes, trans-Pacific long pole, and
    a Topology that validates under min_delay_slots (the existing tier
    rule) — plus spec JSON round-trip back to hashable tuples."""
    from corrosion_tpu.topo import family_topology, min_delay_slots
    from corrosion_tpu.topo.families import (
        FLY_MS_PER_ROUND,
        FLY_REGIONS,
        FLY_RTT_MS,
        rtt_matrix_to_delay_classes,
    )

    kw = family_topology("wan-fly-6r")
    topo = Topology(**kw)
    m = topo.region_delay_matrix
    assert len(m) == len(FLY_REGIONS) == topo.n_regions
    # symmetric table → symmetric classes; diagonal is the free class
    for i in range(len(m)):
        assert m[i][i] == 0
        for j in range(len(m)):
            assert m[i][j] == m[j][i]
    # the fra-nrt trans-continental pole carries the deepest class
    fra, nrt = FLY_REGIONS.index("fra"), FLY_REGIONS.index("nrt")
    assert m[fra][nrt] == max(d for row in m for d in row)
    assert topo.max_delay < min_delay_slots(kw) + 1
    # quantization rule pinned: ceil(ms/grain) - 1, floored at 0
    assert rtt_matrix_to_delay_classes(
        ((2.0, 90.0), (90.0, 2.0)), FLY_MS_PER_ROUND
    ) == ((0, 2), (2, 0))
    # a spec cell naming the family round-trips lists back to tuples
    from corrosion_tpu.campaign.spec import CampaignSpec

    spec = CampaignSpec.from_dict(
        {
            "name": "t",
            "scenario": {"n_nodes": 12, "n_payloads": 2},
            "topology": {"topo_family": "wan-fly-6r"},
        }
    )
    t2 = spec.topo({})
    assert t2.region_delay_matrix == m
    assert isinstance(t2.region_delay_matrix[0], tuple)


def test_wan_fly_matrix_converges_and_host_events():
    """A small broadcast over the wan-fly-6r matrix converges, and
    `topology_link_events` lowers the matrix into per-region-pair delay
    rectangles (the host-parity compile path)."""
    from corrosion_tpu.topo.families import FLY_REGIONS

    kw = family_topology("wan-fly-6r")
    topo = Topology(**kw)
    cfg = SimConfig(
        n_nodes=24, n_payloads=4, fanout=3, sync_interval_rounds=4,
        n_delay_slots=min_delay_slots(kw) + 1,
    )
    meta = uniform_payloads(cfg, inject_every=1)
    final, metrics = run_to_convergence(
        new_sim(cfg, 0), meta, cfg, topo, 400
    )
    assert (np.asarray(final.have) > 0).all()
    evs = topology_link_events(topo, 24, end=8)
    delays = [e for e in evs if e.kind == "delay"]
    # every region pair with a non-zero class gets a rectangle
    n_regions = len(FLY_REGIONS)
    m = topo.region_delay_matrix
    want = sum(
        1
        for i in range(n_regions)
        for j in range(n_regions)
        if m[i][j] > 0
    )
    assert len(delays) == want
    # and the rectangle's class matches the matrix entry it came from
    per = 24 // n_regions
    for e in delays:
        r_i = min(int(str(e.src).split(":")[0]) // per, n_regions - 1)
        r_j = min(int(str(e.dst).split(":")[0]) // per, n_regions - 1)
        assert e.delay_rounds == m[r_i][r_j]
