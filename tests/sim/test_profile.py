"""Phase-attribution profiler tests (ISSUE 16, sim/profile.py).

Four contracts pinned here:

1. the scopes are METADATA-ONLY: a kernel compiled with annotations on
   is byte-identical in results to one compiled with
   ``CORRO_PHASE_SCOPES=0`` (the scope string shows up in the HLO's
   op_name metadata and nowhere else);
2. the capture-time HLO → phase map extraction (scope paths, the
   file/function hints for scatter-expanded ops, unanimous-context
   fixpoint inheritance, container exclusion);
3. the offline jax-free trace fold (attribution math, loud residual,
   saturation flag) and the baseline gate (band violations and
   saturated captures go red);
4. ``memory_budget`` snapshots a real ``compiled.memory_analysis()``.
"""

import contextlib
import json
import os

import numpy as np
import pytest

from corrosion_tpu.sim import profile as prof

# ---------------------------------------------------------------------------
# Registry + scope helpers (jax-free).
# ---------------------------------------------------------------------------


def test_scope_name_registry():
    assert prof.scope_name("sampler") == "corro.sampler"
    with pytest.raises(KeyError, match="CT010"):
        prof.scope_name("handshake")


def test_phase_scope_disabled_is_nullcontext(monkeypatch):
    monkeypatch.setenv("CORRO_PHASE_SCOPES", "0")
    ctx = prof.phase_scope("sync")
    assert isinstance(ctx, contextlib.nullcontext)
    # the registry check still fires when disabled: a typo'd key must
    # not ride to production behind the env toggle
    with pytest.raises(KeyError):
        prof.phase_scope("handshake")


# ---------------------------------------------------------------------------
# HLO → phase map extraction (synthetic HLO text).
# ---------------------------------------------------------------------------

SYNTH_HLO = """\
HloModule jit_round, entry_computation_layout={(f32[4]{0})->f32[4]{0}}

%fused_sampler (p0: f32[4]) -> f32[4] {
  %p0 = f32[4] parameter(0)
  %draw = f32[4] add(f32[4] %p0, f32[4] %p0), metadata={op_name="jit(round)/corro.sampler/add" source_file="/repo/sim/pswim.py" source_line=12}
  %glue = f32[4] copy(f32[4] %draw)
}

%round_body (p1: f32[4]) -> f32[4] {
  %p1 = f32[4] parameter(1)
  %nested = f32[4] multiply(f32[4] %p1, f32[4] %p1), metadata={op_name="jit(round)/corro.sync/jit(inner)/corro.sampler/mul"}
  %synced = f32[4] add(f32[4] %nested, f32[4] %p1), metadata={op_name="jit(round)/corro.sync/add"}
  %hinted = f32[4] subtract(f32[4] %synced, f32[4] %p1), metadata={op_name="/sub" source_file="/repo/sim/sync.py" source_line=44}
  %fuse = f32[4] fusion(f32[4] %p1), kind=kLoop, calls=%fused_sampler
  %mystery = f32[4] copy(f32[4] %fuse)
  %looped = (f32[4], s32[]) while((f32[4], s32[]) %fuse), condition=%cond, body=%body
}
"""


def test_hlo_map_scope_extraction_and_fixpoint():
    module, ops = prof.hlo_op_phase_map(SYNTH_HLO)
    assert module == "jit_round"
    # direct scope
    assert ops["draw"]["phase"] == "sampler"
    # innermost scope wins over the enclosing one
    assert ops["nested"]["phase"] == "sampler"
    assert ops["synced"]["phase"] == "sync"
    # single-phase source-file hint relabels a dropped scope
    assert ops["hinted"]["phase"] == "sync"
    # container ops are marked, never folded
    assert ops["looped"].get("container") is True
    # fixpoint: the fusion inherits from its UNANIMOUS called
    # computation (fused_sampler is all-sampler), and the glue copy
    # inside that computation inherits from its enclosing one
    assert ops["fuse"]["phase"] == "sampler"
    assert ops["glue"]["phase"] == "sampler"
    # round_body is MULTI-phase (sampler + sync): its bare member must
    # stay unattributed rather than being guessed at
    assert "phase" not in ops["mystery"]


def test_hlo_map_function_hint_for_multiphase_file(tmp_path):
    # packed.py is multi-phase, so attribution is per enclosing def —
    # resolved by reading the source at capture time
    src = tmp_path / "packed.py"
    src.write_text(
        "def inject_packed(x):\n    return x\n\n\n"
        "def broadcast_packed(x):\n    return x\n"
    )
    hlo = f"""\
HloModule jit_pk

ENTRY %main (p0: f32[4]) -> f32[4] {{
  %p0 = f32[4] parameter(0)
  %inj = f32[4] add(f32[4] %p0, f32[4] %p0), metadata={{op_name="/add" source_file="{src}" source_line=2}}
  %bc = f32[4] multiply(f32[4] %p0, f32[4] %p0), metadata={{op_name="/mul" source_file="{src}" source_line=5}}
  %helper = f32[4] copy(f32[4] %p0), metadata={{op_name="/copy" source_file="{src}" source_line=99}}
}}
"""
    _module, ops = prof.hlo_op_phase_map(hlo)
    assert ops["inj"]["phase"] == "inject"
    assert ops["bc"]["phase"] == "broadcast"
    # line 99 resolves to the LAST def (broadcast_packed) — the hint
    # covers trailing helper lines of the listed kernels
    assert ops["helper"]["phase"] == "broadcast"


def test_hlo_map_duplicate_name_keeps_phased_entry():
    hlo = """\
HloModule jit_dup

%comp_a (p0: f32[4]) -> f32[4] {
  %x = f32[4] add(f32[4] %p0, f32[4] %p0), metadata={op_name="jit(r)/corro.gaps/add"}
}

%comp_b (p1: f32[4]) -> f32[4] {
  %x = f32[4] copy(f32[4] %p1)
}
"""
    _module, ops = prof.hlo_op_phase_map(hlo)
    # the phased twin survives the unphased duplicate
    assert ops["x"]["phase"] == "gaps"


# ---------------------------------------------------------------------------
# Offline trace fold + gate (jax-free, synthetic capture).
# ---------------------------------------------------------------------------


def _write_capture(tmp_path, events):
    prof.write_phase_map(str(tmp_path), [SYNTH_HLO])
    trace = tmp_path / "host.trace.json"
    trace.write_text(json.dumps({"traceEvents": events}))
    return str(tmp_path)


def _ev(op, dur_us, module="jit_round", ph="X"):
    return {
        "ph": ph,
        "dur": dur_us,
        "name": op,
        "args": {"hlo_op": op, "hlo_module": module},
    }


def test_parse_phase_profile_attribution_math(tmp_path):
    pdir = _write_capture(
        tmp_path,
        [
            _ev("draw", 400.0),       # sampler
            _ev("nested", 100.0),     # sampler (innermost)
            _ev("synced", 300.0),     # sync
            _ev("mystery", 200.0),    # residual: multi-phase comp glue
            _ev("looped", 5000.0),    # container: excluded entirely
            _ev("draw", 100.0, module="jit_other"),  # other module: out
            _ev("draw", 100.0, ph="M"),  # metadata event: out
        ],
    )
    rec = prof.parse_phase_profile(pdir)
    assert rec["kind"] == "phase_profile"
    assert rec["device_events"] == 4
    assert rec["trace_saturated"] is False
    assert rec["total_s"] == pytest.approx(1e-3)
    assert rec["phases"]["sampler"]["s"] == pytest.approx(5e-4)
    assert rec["phases"]["sampler"]["frac"] == pytest.approx(0.5)
    assert rec["phases"]["sync"]["frac"] == pytest.approx(0.3)
    assert rec["unattributed"]["frac"] == pytest.approx(0.2)
    assert rec["unattributed"]["top_ops"][0]["op"] == "mystery"
    # every registered phase appears, zero or not (stable record shape)
    assert set(rec["phases"]) == set(prof.PHASES)


def test_saturated_capture_flagged_and_refused(tmp_path, monkeypatch):
    pdir = _write_capture(
        tmp_path, [_ev("draw", 10.0), _ev("synced", 10.0)]
    )
    monkeypatch.setattr(prof, "TRACE_EVENT_CAP", 2)
    rec = prof.parse_phase_profile(pdir)
    assert rec["trace_saturated"] is True
    base = prof.baseline_from_profile(rec, scenario="t")
    failures = prof.compare_profiles(base, rec)
    assert any("saturated" in f for f in failures)


def test_compare_gate_bands_and_residual(tmp_path):
    pdir = _write_capture(
        tmp_path, [_ev("draw", 600.0), _ev("synced", 400.0)]
    )
    rec = prof.parse_phase_profile(pdir)
    base = prof.baseline_from_profile(rec, scenario="t", tol=0.05)
    assert base["kind"] == "profile_baseline"
    assert prof.compare_profiles(base, rec) == []
    # a phase leaving its band goes red
    shifted = json.loads(json.dumps(rec))
    shifted["phases"]["sampler"]["frac"] = 0.7
    fails = prof.compare_profiles(base, shifted)
    assert len(fails) == 1 and "phase sampler" in fails[0]
    # the unattributed residual breaching its ceiling goes red, with
    # the CT010 breadcrumb in the message
    noisy = json.loads(json.dumps(rec))
    noisy["unattributed"]["frac"] = 0.5
    fails = prof.compare_profiles(base, noisy)
    assert len(fails) == 1 and "CT010" in fails[0]


def test_compare_gate_phase_frac_ceiling(tmp_path):
    """The ISSUE 19 one-sided ceiling: a baseline carrying
    phase_frac_max pages when the capped phase GROWS past its cap —
    and only then (shrinking below the two-sided band's floor is the
    band's business, not the ceiling's)."""
    pdir = _write_capture(
        tmp_path, [_ev("draw", 900.0), _ev("synced", 100.0)]
    )
    rec = prof.parse_phase_profile(pdir)
    base = prof.baseline_from_profile(
        rec, scenario="t", tol=0.5,
        extra={"phase_frac_max": {"sync": 0.15}},
    )
    assert base["phase_frac_max"] == {"sync": 0.15}
    assert prof.compare_profiles(base, rec) == []
    grown = json.loads(json.dumps(rec))
    grown["phases"]["sync"]["frac"] = 0.2
    fails = prof.compare_profiles(base, grown)
    assert len(fails) == 1
    assert "phase_frac_max" in fails[0] and "sync" in fails[0]
    # a capped phase that is absent from the candidate counts as zero
    missing = json.loads(json.dumps(rec))
    del missing["phases"]["sync"]
    assert prof.compare_profiles(base, missing) == []
    # the ceiling renders in the human compare output
    out = prof.render_compare(base, grown, fails)
    assert "ceiling" in out


def test_render_tables_smoke(tmp_path):
    pdir = _write_capture(
        tmp_path, [_ev("draw", 600.0), _ev("mystery", 400.0)]
    )
    rec = prof.parse_phase_profile(pdir)
    table = prof.render_phase_table(rec)
    assert "sampler" in table and "unattributed" in table
    assert "above the" in table  # 40% residual breaches the ceiling
    # widen the residual ceiling: this synthetic capture is 40%
    # unattributed by construction
    base = prof.baseline_from_profile(
        rec, scenario="t", unattributed_frac_max=0.5
    )
    out = prof.render_compare(base, rec, prof.compare_profiles(base, rec))
    assert "OK" in out


# ---------------------------------------------------------------------------
# Metadata-only contract + memory budgets (jax; tiny shapes).
# ---------------------------------------------------------------------------


def _tiny_round_cfg():
    from corrosion_tpu.sim.state import SimConfig, uniform_payloads

    cfg = SimConfig.wan_tuned(
        24,
        n_payloads=32,
        n_writers=2,
        chunks_per_version=1,
        fanout=2,
        sync_interval_rounds=4,
        swim_full_view=True,
        rate_limit_bytes_round=None,
        sync_budget_bytes=None,
        packed_min_cells=0,
    )
    return cfg, uniform_payloads(cfg, inject_every=1)


def _run_tiny(cfg, meta, rounds=6, seed=5):
    import jax

    from corrosion_tpu.sim.round import new_metrics, new_sim, round_step
    from corrosion_tpu.sim.topology import Topology, regions

    topo = Topology()
    region = regions(cfg.n_nodes, topo.n_regions)

    @jax.jit
    def step(state, metrics, meta):
        return round_step(state, metrics, meta, cfg, topo, region)

    state, metrics = new_sim(cfg, seed), new_metrics(cfg)
    for _ in range(rounds):
        state, metrics = step(state, metrics, meta)
    lowered = step.lower(state, metrics, meta)
    return state, metrics, lowered.compile()


def test_scopes_are_metadata_only_byte_identity(monkeypatch):
    """Annotations on vs CORRO_PHASE_SCOPES=0: the HLO metadata differs
    (that's the point), the computed state does not — byte-identical.

    The persistent compilation cache must sit out: jax strips op_name /
    source metadata when computing cache keys (metadata-equivalent
    programs share an entry), so the scopes-off compile would HIT the
    scopes-on executable and hand back annotated HLO text."""
    import jax

    cache_dir = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)

    cfg, meta = _tiny_round_cfg()
    s_on, m_on, compiled_on = _run_tiny(cfg, meta)
    assert "corro." in compiled_on.as_text()

    monkeypatch.setenv("CORRO_PHASE_SCOPES", "0")
    jax.clear_caches()
    try:
        s_off, m_off, compiled_off = _run_tiny(cfg, meta)
        assert "corro." not in compiled_off.as_text()
        for field in ("have", "heads", "gap_lo", "gap_hi", "view", "key"):
            a = np.asarray(getattr(s_on, field))
            b = np.asarray(getattr(s_off, field))
            assert (a == b).all(), f"state.{field} diverged"
        for field in ("coverage_at", "converged_at"):
            a = np.asarray(getattr(m_on, field))
            b = np.asarray(getattr(m_off, field))
            assert (a == b).all(), f"metrics.{field} diverged"
    finally:
        monkeypatch.setenv("CORRO_PHASE_SCOPES", "1")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.clear_caches()


def test_memory_budget_from_compiled():
    cfg, meta = _tiny_round_cfg()
    _s, _m, compiled = _run_tiny(cfg, meta, rounds=1)
    rec = prof.memory_budget(compiled, label="tiny round")
    assert rec["kind"] == "memory_budget" and rec["label"] == "tiny round"
    for key in (
        "argument_bytes", "output_bytes", "temp_bytes", "alias_bytes",
        "peak_bytes_est",
    ):
        assert isinstance(rec[key], int), key
    assert rec["peak_bytes_est"] == (
        rec["argument_bytes"]
        + rec["output_bytes"]
        + rec["temp_bytes"]
        - rec["alias_bytes"]
    )
    assert rec["peak_bytes_est"] > 0
    assert "tiny round" in prof.render_memory_table(rec)


def test_phase_map_covers_real_round_kernel():
    """The capture-time extraction on a REAL compiled round: every
    registered phase that the dense round kernel annotates must survive
    compilation into the map (XLA may drop SOME scope paths — the
    fallbacks exist for that — but a wholesale loss of a phase's
    annotations would gut the ledger silently)."""
    cfg, meta = _tiny_round_cfg()
    _s, _m, compiled = _run_tiny(cfg, meta, rounds=1)
    module, ops = prof.hlo_op_phase_map(compiled.as_text())
    assert module is not None
    phases_seen = {e["phase"] for e in ops.values() if "phase" in e}
    # the dense round annotates these unconditionally (round.py); the
    # converge scope wraps the metrics update
    for must in ("sampler", "inject", "broadcast", "sync", "deliver",
                 "swim", "gaps", "converge"):
        assert must in phases_seen, f"phase {must} lost its annotations"
