"""FaultPlan on the sim tier: schedule compilation, replay determinism,
crash-with-state-wipe rejoin under the sim invariant catalog, and the
tier-1-sized chaos smoke (ISSUE 2)."""

import numpy as np
import pytest

from corrosion_tpu.faults import FaultEvent, FaultPlan, derive_seed
from corrosion_tpu.sim.faults import (
    compile_plan,
    run_fault_plan,
    run_fault_plan_checked,
)
from corrosion_tpu.sim.round import new_sim
from corrosion_tpu.sim.state import ALIVE, DOWN, SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import Topology


def _cfg(n_payloads=8, **kw):
    kw.setdefault("n_delay_slots", 4)
    return SimConfig(n_nodes=3, n_payloads=n_payloads, fanout=2,
                     sync_interval_rounds=4, **kw)


def _plan(seed=3):
    return FaultPlan(
        n_nodes=3, seed=seed,
        events=(
            FaultEvent("loss", 0, 30, p=0.4),
            # asymmetric: node 2 still HEARS node 0, but 2→0 is cut
            FaultEvent("partition", 5, 20, src=2, dst=0),
            FaultEvent("delay", 4, 24, src=0, dst=1, delay_rounds=1),
            FaultEvent("jitter", 4, 24, src=0, dst=1, delay_rounds=1),
            FaultEvent("duplicate", 0, 20, src=1, dst=2, p=0.3),
            FaultEvent("crash", 22, 30, node=2, wipe=True),
            FaultEvent("clock_skew", 0, 30, node=1, skew_ns=100_000_000),
        ),
    )


def test_schedule_is_pure_and_deterministic():
    """plan.schedule() is the single source of truth both compilers
    consume: two expansions are equal, and derive_seed is process-stable
    (a salted hash() here would break cross-run replay)."""
    p = _plan()
    assert p.schedule() == p.schedule()
    assert p.horizon == 31
    # blake2b derivation: fixed value, distinct per token path
    assert derive_seed(3, "link", 0, 1) == derive_seed(3, "link", 0, 1)
    assert derive_seed(3, "link", 0, 1) != derive_seed(3, "link", 1, 0)
    assert derive_seed(3, "link", 0, 1) != derive_seed(4, "link", 0, 1)
    # epoch table: the asymmetric partition appears only in the 2→0 slot
    epochs = p.link_epochs()
    assert any(f.blocked for _, f in epochs[(2, 0)])
    assert not any(f.blocked for _, f in epochs.get((0, 2), []))


def test_compile_plan_lowers_schedule_to_tensors():
    cfg = _cfg()
    fp = compile_plan(_plan(), cfg)
    assert fp.block.shape == (32, 3, 3)
    blk = np.asarray(fp.block)
    assert blk[10, 2, 0] and not blk[10, 0, 2]  # asymmetric
    assert not blk[25].any()  # partition healed
    loss = np.asarray(fp.loss)
    assert loss[0, 0, 1] == round(0.4 * 256)
    assert np.asarray(fp.delay)[10, 0, 1] == 1
    assert np.asarray(fp.jitter)[10, 0, 1] == 1
    alive = np.asarray(fp.alive)
    assert alive[22, 2] == DOWN and alive[29, 2] == DOWN
    assert alive[30, 2] == ALIVE  # restart round
    assert np.asarray(fp.wipe)[30, 2]
    assert not np.asarray(fp.block)[31].any()  # final row: all clear
    # near-certain loss cannot ride the u8 threshold: compiles to a cut
    hard = FaultPlan(3, 1, (FaultEvent("loss", 0, 4, src=0, dst=1, p=1.0),))
    assert np.asarray(compile_plan(hard, cfg).block)[1, 0, 1]


def test_compile_rejects_delay_overflowing_the_ring():
    """A fault delay the inflight ring can't represent would deliver
    EARLY, silently — compile must refuse (round.validate's contract)."""
    plan = FaultPlan(
        3, 0, (FaultEvent("delay", 0, 4, delay_rounds=6),)
    )
    with pytest.raises(ValueError, match="n_delay_slots"):
        compile_plan(plan, _cfg(), Topology())
    # partial-view SWIM carries the fault seam since ISSUE 3 (pswim_step
    # consumes RoundFaults): compiling a partial-view campaign is legal
    ok_plan = FaultPlan(3, 0, (FaultEvent("loss", 0, 4, p=0.1),))
    fp = compile_plan(ok_plan, _cfg(swim_partial_view=True), Topology())
    assert fp.loss.shape == (6, 3, 3)  # rounds 0..horizon inclusive


def test_fault_run_replays_identical_per_round_decisions():
    """The replay-determinism acceptance: same seed → identical
    per-round fault decisions and state evolution, different seed →
    different trajectory."""
    cfg = _cfg()
    meta = uniform_payloads(cfg, inject_every=1)
    # 40 rounds cover every scheduled fault; digests don't need
    # convergence, and capping keeps the eager loop tier-1-cheap
    runs = []
    for _ in range(2):
        state = new_sim(cfg, seed=11)
        _, _, digests = run_fault_plan_checked(
            _plan(), state, meta, cfg, max_rounds=40, check_every=8
        )
        runs.append(digests)
    assert runs[0] == runs[1]
    other = run_fault_plan_checked(
        _plan(seed=99), new_sim(cfg, seed=11), meta, cfg, max_rounds=40,
        check_every=8,
    )[2]
    assert other != runs[0]


def test_crash_with_state_wipe_rejoins_via_anti_entropy():
    """ISSUE 2 satellite: a node goes DOWN mid-storm, loses its `have`
    rows at restart, and recovers purely through anti-entropy sync —
    with the sim invariant catalog (no-phantom-data, bookkeeping-heads,
    bookkeeping-gaps, relay-budget) asserted EVERY round by the checked
    driver."""
    cfg = _cfg(n_payloads=12)
    meta = uniform_payloads(cfg, inject_every=1)  # writer is node 0
    plan = FaultPlan(
        n_nodes=3, seed=5,
        events=(FaultEvent("crash", 8, 20, node=2, wipe=True),),
    )
    state = new_sim(cfg, seed=2)
    final, metrics, _ = run_fault_plan_checked(
        plan, state, meta, cfg, max_rounds=300, check_every=1
    )
    have = np.asarray(final.have)
    heads = np.asarray(final.heads)
    assert (np.asarray(final.alive) == ALIVE).all()
    # the wiped node holds EVERY version again, purely via sync (its
    # relay budgets were zeroed, so rebroadcast can't have self-served it
    # — sync-received payloads carry no budget)
    assert (have[2] > 0).all()
    assert (heads[:, 0] == cfg.n_versions).all()


def test_factored_compile_matches_matrix_per_edge():
    """The rank-1 factored form answers every per-edge fault query with
    the same values as the matrix form, round by round — block ORs,
    delay sums, jitter maxes, loss thresholds, self-edges excluded."""
    import itertools

    import jax.numpy as jnp

    from corrosion_tpu.sim.faults import (
        compile_plan,
        fault_edge_block,
        fault_edge_delay,
        fault_edge_jitter,
        fault_edge_loss,
        round_faults,
    )

    cfg = _cfg(n_delay_slots=8)
    plan = FaultPlan(
        n_nodes=3, seed=3,
        events=(
            FaultEvent("loss", 0, 10, p=0.4),
            FaultEvent("partition", 2, 8, src=2, dst=0),
            FaultEvent("partition", 4, 9, src="0:2", dst="2:3",
                       symmetric=True),
            FaultEvent("delay", 1, 6, src=0, dst=1, delay_rounds=1),
            FaultEvent("delay", 3, 7, src="*", dst=1, delay_rounds=2),
            FaultEvent("jitter", 2, 6, src=0, dst="*", delay_rounds=2),
            FaultEvent("crash", 5, 9, node=1, wipe=True),
        ),
    )
    fp_m = compile_plan(plan, cfg, factored=False)
    fp_f = compile_plan(plan, cfg, factored=True)
    pairs = [(s, d) for s, d in itertools.product(range(3), range(3))]
    src = jnp.asarray([p[0] for p in pairs])
    dst = jnp.asarray([p[1] for p in pairs])
    for r in range(plan.horizon + 1):
        rm = round_faults(fp_m, jnp.int32(r))
        rf = round_faults(fp_f, jnp.int32(r))
        assert (np.asarray(rm.alive) == np.asarray(rf.alive)).all(), r
        assert (np.asarray(rm.wipe) == np.asarray(rf.wipe)).all(), r
        blocked = np.asarray(fault_edge_block(rm, src, dst))
        for name, fn in (
            ("block", fault_edge_block), ("loss", fault_edge_loss),
            ("delay", fault_edge_delay), ("jitter", fault_edge_jitter),
        ):
            a, b = fn(rm, src, dst), fn(rf, src, dst)
            a = np.zeros(len(pairs)) if a is None else np.asarray(a)
            b = np.zeros(len(pairs)) if b is None else np.asarray(b)
            if name == "loss":
                # representations legitimately differ ON CUT EDGES: the
                # matrix compiler folds a cut link's loss into `block`
                # (loss=0 there), factored keeps both terms — immaterial
                # to every kernel (ok &= ~block dominates the drop mask)
                a, b = a[~blocked], b[~blocked]
            assert (a == b).all(), (name, r, a, b)


def test_factored_overlapping_loss_matches_matrix():
    """Overlapping loss events compile factored via EXACT subset
    composition (ISSUE 13, closing the PR 4 carried edge): the
    composite factors reproduce the matrix compiler's merged u8
    thresholds bit-exactly on every (round, edge) — including a
    three-way overlap window and a certainty-composing pair."""
    import itertools

    import jax.numpy as jnp

    from corrosion_tpu.sim.faults import (
        compile_plan,
        fault_edge_block,
        fault_edge_loss,
        round_faults,
    )

    cfg = _cfg()
    plan = FaultPlan(
        3, 0,
        events=(
            FaultEvent("loss", 0, 10, p=0.2),
            FaultEvent("loss", 5, 12, p=0.3, src=0, dst=1),
            FaultEvent("loss", 7, 12, p=0.25, src="0:2", dst="*"),
            # 0.9 ∘ 0.9 folds past the u8 grain → the composite must
            # lower to a CUT on the overlap window, like a single p≈1
            FaultEvent("loss", 14, 18, p=0.9, src=2, dst=0),
            FaultEvent("loss", 15, 18, p=0.9, src=2, dst=0),
        ),
    )
    fp_m = compile_plan(plan, cfg, factored=False)
    fp_f = compile_plan(plan, cfg, factored=True)
    pairs = list(itertools.product(range(3), range(3)))
    src = jnp.asarray([p[0] for p in pairs])
    dst = jnp.asarray([p[1] for p in pairs])
    for r in range(plan.horizon + 1):
        rm = round_faults(fp_m, jnp.int32(r))
        rf = round_faults(fp_f, jnp.int32(r))
        bm = fault_edge_block(rm, src, dst)
        bf = fault_edge_block(rf, src, dst)
        bm = np.zeros(len(pairs), bool) if bm is None else np.asarray(bm)
        bf = np.zeros(len(pairs), bool) if bf is None else np.asarray(bf)
        assert (bm == bf).all(), r
        lm = np.asarray(fault_edge_loss(rm, src, dst))
        lf = np.asarray(fault_edge_loss(rf, src, dst))
        # cut edges legitimately differ in the loss channel (the matrix
        # folds their loss into block) — immaterial: ok &= ~block wins
        assert (lm[~bm] == lf[~bm]).all(), (r, lm, lf)


def test_factored_overlapping_loss_storm_scale_and_cap():
    """The storm shape: an overlapping-loss plan at ≥1024 nodes (the
    auto-factor threshold) compiles in factored form; a clique beyond
    MAX_OVERLAPPING_LOSS refuses loudly, naming the matrix fallback."""
    from corrosion_tpu.sim.state import SimConfig
    from corrosion_tpu.sim.faults import (
        MAX_OVERLAPPING_LOSS,
        FactoredFaultPlan,
        compile_plan,
    )

    n = 2048
    cfg = SimConfig(
        n_nodes=n, n_payloads=4, fanout=2, sync_interval_rounds=4,
        n_delay_slots=4,
    )
    plan = FaultPlan(
        n, 0,
        events=(
            FaultEvent("loss", 0, 20, p=0.3),
            FaultEvent("loss", 5, 15, p=0.4, src="0:1024", dst="*"),
            FaultEvent("loss", 8, 12, p=0.2, src="512:1536", dst="0:512"),
        ),
    )
    fp = compile_plan(plan, cfg)  # auto-selects factored at this size
    assert isinstance(fp, FactoredFaultPlan)
    # individual factors + the 3 pairwise composites + the triple
    assert fp.loss_thr.shape[0] == 7
    too_many = FaultPlan(
        n, 0,
        events=tuple(
            FaultEvent("loss", 0, 10, p=0.05)
            for _ in range(MAX_OVERLAPPING_LOSS + 1)
        ),
    )
    with pytest.raises(ValueError, match="factored=False"):
        compile_plan(too_many, cfg)


def test_factored_compile_disjoint_loss_still_compiles():
    """Time- or selector-disjoint loss events compile with no
    composites (the pre-ISSUE 13 legal shapes, unchanged)."""
    from corrosion_tpu.sim.faults import compile_plan_factored

    cfg = _cfg()
    disjoint_time = FaultPlan(
        3, 0,
        events=(
            FaultEvent("loss", 0, 5, p=0.2),
            FaultEvent("loss", 5, 12, p=0.3),
        ),
    )
    compile_plan_factored(disjoint_time, cfg)
    disjoint_links = FaultPlan(
        3, 0,
        events=(
            FaultEvent("loss", 0, 10, p=0.2, src=0, dst=1),
            FaultEvent("loss", 0, 10, p=0.3, src=1, dst=0),
        ),
    )
    compile_plan_factored(disjoint_links, cfg)
    # and the factored ring-envelope validation keeps its teeth
    with pytest.raises(ValueError, match="n_delay_slots"):
        compile_plan_factored(
            FaultPlan(3, 0, (FaultEvent("delay", 0, 4, delay_rounds=6),)),
            _cfg(),
        )


def test_range_selectors_validate_and_expand():
    """"lo:hi" selectors: bounds-checked at plan build, expanded by
    `_pairs` on the host/matrix tier, lowered to node masks factored."""
    from corrosion_tpu.faults import sel_indices

    assert sel_indices("*", 5) == range(5)
    assert sel_indices("1:4", 5) == range(1, 4)
    assert sel_indices(2, 5) == range(2, 3)
    with pytest.raises(ValueError, match="selector"):
        FaultPlan(3, 0, (FaultEvent("loss", 0, 2, p=0.1, src="1:9"),))
    plan = FaultPlan(
        4, 0, (FaultEvent("partition", 0, 2, src="0:2", dst="2:4"),)
    )
    pairs = set(plan._pairs(plan.events[0]))
    assert pairs == {(0, 2), (0, 3), (1, 2), (1, 3)}


@pytest.mark.chaos
def test_chaos_smoke_sim_tier():
    """Tier-1-sized FaultPlan smoke (3 nodes, well under 5 s): converge
    under a loss burst + short asymmetric partition.  Eager driver — the
    jitted `run_fault_plan` is exercised by the parity campaign
    (tests/cluster/test_fault_parity.py); paying a second XLA compile
    here would bust the smoke's 5 s budget for no extra coverage."""
    cfg = _cfg()
    meta = uniform_payloads(cfg, inject_every=1)
    plan = FaultPlan(
        n_nodes=3, seed=1,
        events=(
            FaultEvent("loss", 0, 10, p=0.3),
            FaultEvent("partition", 2, 8, src=1, dst=0),
        ),
    )
    final, _, _ = run_fault_plan_checked(
        plan, new_sim(cfg, seed=0), meta, cfg, max_rounds=120, check_every=8
    )
    assert int(final.t) >= plan.horizon  # no early exit inside the schedule
    assert (np.asarray(final.have) > 0).all()
    assert (np.asarray(final.heads)[:, 0] == cfg.n_versions).all()


def test_range_link_epochs_match_pairwise_exactly():
    """The range-atom walk (ISSUE 7 satellite) is byte-equivalent to the
    pairwise link_epochs expansion: every directed pair lands in exactly
    one atom, and its change list — rounds, parameters, and epoch
    indices (the derive_seed anchor) — is identical."""
    from corrosion_tpu.faults import demo_plan

    plans = [
        demo_plan(),
        demo_plan(n_nodes=7, seed=3),
        FaultPlan(
            n_nodes=64, seed=5,
            events=(
                FaultEvent("loss", 0, 20, p=0.3),
                FaultEvent("partition", 5, 15, src="0:32", dst="32:64"),
                FaultEvent(
                    "partition", 8, 12, src="16:48", dst="0:16",
                    symmetric=True,
                ),
                FaultEvent("delay", 2, 18, src="0:8", dst="*",
                           delay_rounds=2),
                FaultEvent("jitter", 3, 10, src="*", dst="60:64",
                           delay_rounds=1),
                FaultEvent("duplicate", 0, 6, src=1, dst="2:40", p=0.2),
                FaultEvent("crash", 10, 20, node=5, wipe=True),
            ),
        ),
    ]
    for plan in plans:
        pairwise = plan.link_epochs()
        expanded = {}
        for src_r, dst_r, changes in plan.range_link_epochs():
            for s in src_r:
                for d in dst_r:
                    if s != d:
                        assert (s, d) not in expanded, "atoms overlap"
                        expanded[(s, d)] = list(changes)
        assert set(pairwise) == set(expanded)
        for pair in pairwise:
            assert pairwise[pair] == expanded[pair], pair


def test_range_schedule_helpers_match_pairwise():
    """active_kinds_at / blocked_pairs_at — the drivers' O(events)
    per-round views — equal the pairwise RoundSchedule's answers at
    every round of the plan."""
    from corrosion_tpu.faults import demo_plan

    plan = demo_plan(n_nodes=6, seed=2)
    for r in range(plan.horizon + 2):
        sched = plan.schedule_at(r)
        assert plan.active_kinds_at(r) == sched.active_kinds(), r
        blocked = {p for p, f in sched.links.items() if f.blocked}
        assert blocked == set(plan.blocked_pairs_at(r)), r
        # the node-fault-only view skips the pairwise expansion but
        # keeps crash/restart/skew identical
        slim = plan.schedule_at(r, include_links=False)
        assert slim.links == {}
        assert slim.down == sched.down
        assert slim.restart == sched.restart
        assert slim.wipe == sched.wipe
        assert slim.skews == sched.skews


def test_advance_range_epochs_installs_match_pairwise():
    """The two epoch walkers hand identical (src, dst, epoch, params)
    install streams to a driver — per round, as sets (install order
    within a round is not part of the contract; LinkModel installs are
    keyed per edge)."""
    from corrosion_tpu.faults import (
        advance_link_epochs,
        advance_range_epochs,
        demo_plan,
    )

    plan = demo_plan(n_nodes=5, seed=9)
    pw_epochs = plan.link_epochs()
    atoms = plan.range_link_epochs()
    pw_idx, ra_idx = {}, {}
    for r in range(plan.horizon + 1):
        pw_installs, ra_installs = set(), set()
        advance_link_epochs(
            pw_epochs, pw_idx, r,
            lambda s, d, i, p: pw_installs.add((s, d, i, p)),
        )
        advance_range_epochs(
            atoms, ra_idx, r,
            lambda s, d, i, p: ra_installs.add((s, d, i, p)),
        )
        assert pw_installs == ra_installs, r


def test_range_machinery_is_storm_scale():
    """A 100k-node storm-shaped plan ("lo:hi" half-split + "*" loss)
    must never expand pairwise: the atom walk is O(events²), which is
    what lets host-tier drivers replay storm-shaped plans (the carried
    edge from PR 4)."""
    import time

    from corrosion_tpu.sim.runner import storm_fault_plan

    plan = storm_fault_plan(100_000, 1)
    t0 = time.monotonic()
    atoms = plan.range_link_epochs()
    kinds = plan.active_kinds_at(5)
    wall = time.monotonic() - t0
    assert len(atoms) <= 8
    assert "loss" in kinds and "partition" in kinds
    assert wall < 1.0, f"range walk took {wall:.3f}s — pairwise leak?"
