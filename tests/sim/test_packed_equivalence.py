"""Packed u32 round vs dense round: round-by-round bit-for-bit equality.

The bitpacked kernels (sim/packed.py) claim EXACT equivalence with the
dense round over the supported envelope (P % 32 == 0, power-of-two
chunking, statically unmetered budgets, zero loss, max_transmissions < 16).
This test holds them to it: both paths advance the same initial state with
the same PRNG stream, and after EVERY round the packed carry is unpacked
and compared bit-for-bit against the dense state — have, relay counters,
the in-flight delay ring, injected flags, advertised bookkeeping
(heads/gaps), sync countdowns, the full SWIM state, and the convergence
metrics.  Scenarios cover multi-writer chunked storms, partial-view SWIM,
full-view SWIM with node kills, multi-region ring0 tiering, and a
mid-run partition + heal (VERDICT r3 item 2).

Since ISSUE 4 the suite also pins the FAULT SEAM: packed == dense
round-by-round under a FaultPlan — loss, asymmetric partitions,
crash-with-wipe, fault latency, the metered limiter class, the
storm-scale factored plan form, and a 4096-node storm through the
public `run_fault_plan` entry (the acceptance gate).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.sim.packed import (
    PackedCarry,
    pack_bits,
    pack_state,
    packed_round_step,
    packed_supported,
    run_packed,
    shrink_state,
    unpack_bits,
    unpack_into_state,
)
from corrosion_tpu.sim.round import (
    new_metrics,
    new_sim,
    round_step,
    run_to_convergence,
)
from corrosion_tpu.sim.state import (
    ALIVE,
    DOWN,
    SimConfig,
    uniform_payloads,
)
from corrosion_tpu.sim.topology import Topology, regions


def _dense_step(cfg, topo):
    region = regions(cfg.n_nodes, topo.n_regions)

    @jax.jit
    def step(state, metrics, meta):
        return round_step(state, metrics, meta, cfg, topo, region)

    return step


def _packed_step(cfg, topo):
    region = regions(cfg.n_nodes, topo.n_regions)

    @jax.jit
    def step(state, carry, inj, metrics, meta):
        return packed_round_step(
            state, carry, inj, metrics, meta, cfg, topo, region
        )

    return step


def _assert_equal(tag, a, b):
    a = np.asarray(a)
    b = np.asarray(b)
    assert a.dtype == b.dtype or a.shape == b.shape, tag
    if not (a == b).all():
        bad = np.argwhere(a != b)[:5]
        raise AssertionError(
            f"{tag}: {int((a != b).sum())} mismatches, first at {bad.tolist()}"
        )


def _compare_round(t, sd, md, sp, carry, inj, mp, cfg):
    full = unpack_into_state(carry, sp, cfg)
    _assert_equal(f"have@r{t}", sd.have, full.have)
    _assert_equal(f"relay_left@r{t}", sd.relay_left, full.relay_left)
    _assert_equal(f"inflight@r{t}", sd.inflight, full.inflight)
    _assert_equal(f"sync_inflight@r{t}", sd.sync_inflight, full.sync_inflight)
    _assert_equal(
        f"injected@r{t}",
        sd.injected,
        unpack_bits(inj, cfg.n_payloads).astype(sd.injected.dtype),
    )
    _assert_equal(f"heads@r{t}", sd.heads, sp.heads)
    _assert_equal(f"gap_lo@r{t}", sd.gap_lo, sp.gap_lo)
    _assert_equal(f"gap_hi@r{t}", sd.gap_hi, sp.gap_hi)
    _assert_equal(f"sync_countdown@r{t}", sd.sync_countdown, sp.sync_countdown)
    _assert_equal(f"sync_backoff@r{t}", sd.sync_backoff, sp.sync_backoff)
    _assert_equal(f"key@r{t}", sd.key, sp.key)
    _assert_equal(f"view@r{t}", sd.view, sp.view)
    _assert_equal(f"vinc@r{t}", sd.vinc, sp.vinc)
    _assert_equal(f"pid@r{t}", sd.pid, sp.pid)
    _assert_equal(f"pkey@r{t}", sd.pkey, sp.pkey)
    _assert_equal(f"psince@r{t}", sd.psince, sp.psince)
    _assert_equal(f"coverage_at@r{t}", md.coverage_at, mp.coverage_at)
    _assert_equal(f"converged_at@r{t}", md.converged_at, mp.converged_at)
    _assert_equal(f"overflow@r{t}", md.overflow_frac, mp.overflow_frac)


def _run_lockstep(cfg, topo, meta, rounds, seed=0, mutators=None):
    """Advance dense and packed paths side by side, comparing every round.
    ``mutators`` maps round -> fn(state) applied to BOTH paths (partition
    flips, node kills) before that round executes."""
    assert packed_supported(cfg, topo), "scenario must be in the envelope"
    mutators = mutators or {}
    sd = new_sim(cfg, seed)
    md = new_metrics(cfg)
    dense = _dense_step(cfg, topo)
    packed = _packed_step(cfg, topo)

    carry = pack_state(sd, cfg)
    inj = pack_bits(sd.injected)
    sp = shrink_state(sd)
    mp = new_metrics(cfg)

    for t in range(rounds):
        if t in mutators:
            # mutators touch membership/partition fields only; the packed
            # payload carry is unaffected
            sd = mutators[t](sd)
            sp = mutators[t](sp)
        sd, md = dense(sd, md, meta)
        sp, carry, inj, mp = packed(sp, carry, inj, mp, meta)
        _compare_round(t, sd, md, sp, carry, inj, mp, cfg)
    return sd, md


def test_multiwriter_chunked_storm_pswim():
    """The headline-storm shape scaled down: multi-writer, 4-chunk
    versions, partial-view SWIM coupled to dissemination."""
    cfg = SimConfig.wan_tuned(
        48,
        n_payloads=128,  # 8 versions x 4 writers x 4 chunks
        n_writers=4,
        chunks_per_version=4,
        fanout=3,
        sync_interval_rounds=4,
        swim_partial_view=True,
        member_slots=16,
        rate_limit_bytes_round=None,
        sync_budget_bytes=None,
        packed_min_cells=0,
        n_delay_slots=2,
    )
    meta = uniform_payloads(cfg, inject_every=2)
    _run_lockstep(cfg, Topology(), meta, rounds=40, seed=3)


def test_multiregion_ring0_and_delay_ring():
    """Two regions, inter-region delay 2: exercises ring0-first target
    override and multi-slot delay-ring scatter."""
    cfg = SimConfig.wan_tuned(
        32,
        n_payloads=64,  # 16 versions x 2 writers x 2 chunks
        n_writers=2,
        chunks_per_version=2,
        fanout=2,
        sync_interval_rounds=3,
        swim_partial_view=True,
        member_slots=16,
        rate_limit_bytes_round=None,
        sync_budget_bytes=None,
        packed_min_cells=0,
        n_delay_slots=4,
    )
    topo = Topology(n_regions=2, inter_delay=2)
    meta = uniform_payloads(cfg, inject_every=1)
    _run_lockstep(cfg, topo, meta, rounds=40, seed=7)


def test_partition_heal_and_kill_fullview():
    """Full-view SWIM, mid-run partition + heal, plus node kills: the
    membership-coupled eligibility masks must diverge identically."""
    cfg = SimConfig.wan_tuned(
        24,
        n_payloads=32,  # 16 versions x 2 writers x 1 chunk
        n_writers=2,
        chunks_per_version=1,
        fanout=2,
        sync_interval_rounds=4,
        swim_full_view=True,
        rate_limit_bytes_round=None,
        sync_budget_bytes=None,
        packed_min_cells=0,
    )
    meta = uniform_payloads(cfg, inject_every=1)

    def split(state):
        n = cfg.n_nodes
        group = (jnp.arange(n) >= n // 2).astype(jnp.int32)
        return state._replace(group=group)

    def heal_and_kill(state):
        n = cfg.n_nodes
        alive = state.alive.at[1].set(jnp.uint8(DOWN))
        return state._replace(group=jnp.zeros((n,), jnp.int32), alive=alive)

    _run_lockstep(
        cfg, Topology(), meta, rounds=50, seed=11,
        mutators={5: split, 25: heal_and_kill},
    )


def test_burst_injection_gap_overflow():
    """Burst injection (all versions at round 0) drives the gap extractor
    into its K-overflow clamp; the packed bookkeeping refresh must clamp
    identically (overflow_frac compared every round)."""
    cfg = SimConfig.wan_tuned(
        16,
        n_payloads=256,  # 64 versions x 2 writers x 2 chunks, K=4 slots
        n_writers=2,
        chunks_per_version=2,
        gap_slots=4,
        fanout=2,
        sync_interval_rounds=3,
        swim_partial_view=True,
        member_slots=8,
        rate_limit_bytes_round=None,
        sync_budget_bytes=None,
        packed_min_cells=0,
        n_delay_slots=2,
    )
    meta = uniform_payloads(cfg, inject_every=0)
    _run_lockstep(cfg, Topology(), meta, rounds=30, seed=13)


def test_run_to_convergence_dispatches_packed():
    """The public entry routes the storm shape through the packed loop
    and returns the same results as the dense loop forced via a
    budget-metered (but never-binding at sum level... so force via loss)
    equivalent is impractical; instead: run_packed directly vs the dense
    while-loop body, full-run equality of final state and metrics."""
    cfg = SimConfig.wan_tuned(
        32,
        n_payloads=64,
        n_writers=4,
        chunks_per_version=4,
        fanout=3,
        sync_interval_rounds=4,
        swim_partial_view=True,
        member_slots=16,
        rate_limit_bytes_round=None,
        sync_budget_bytes=None,
        packed_min_cells=0,
        n_delay_slots=2,
    )
    topo = Topology()
    meta = uniform_payloads(cfg, inject_every=2)
    assert packed_supported(cfg, topo)

    # packed path through the public (dispatching) entry
    final_p, metrics_p = run_to_convergence(
        new_sim(cfg, 19), meta, cfg, topo, 300
    )
    # dense path, same math, stepped manually with the same seeds
    sd = new_sim(cfg, 19)
    md = new_metrics(cfg)
    dense = _dense_step(cfg, topo)
    t = 0
    while t < int(final_p.t):
        sd, md = dense(sd, md, meta)
        t += 1
    assert int(final_p.t) == int(sd.t)
    _assert_equal("final have", sd.have, final_p.have)
    _assert_equal("final relay", sd.relay_left, final_p.relay_left)
    _assert_equal("final injected", sd.injected, final_p.injected)
    _assert_equal("final coverage", md.coverage_at, metrics_p.coverage_at)
    _assert_equal("final converged", md.converged_at, metrics_p.converged_at)
    # and the run actually converged (the while_loop exit was the
    # convergence predicate, not max_rounds)
    assert (np.asarray(metrics_p.converged_at) >= 0).all()


def test_envelope_gate():
    """packed_supported must reject every envelope violation — and,
    since r5, ACCEPT the limiter class (loss + budgets run packed: the
    reference's governor is always on, broadcast/mod.rs:460-463)."""
    base = dict(
        n_payloads=64, n_writers=2, chunks_per_version=2,
        rate_limit_bytes_round=None, sync_budget_bytes=None,
        packed_min_cells=0,
    )
    ok = SimConfig(n_nodes=8, **base)
    assert packed_supported(ok, Topology())
    assert packed_supported(ok, Topology(loss=0.1))
    assert packed_supported(
        dataclasses.replace(ok, rate_limit_bytes_round=1024), Topology()
    )
    assert packed_supported(
        dataclasses.replace(ok, sync_budget_bytes=1024), Topology()
    )
    assert not packed_supported(
        dataclasses.replace(ok, max_transmissions=16), Topology()
    )
    bad_p = SimConfig(n_nodes=8, n_payloads=72, n_writers=2,
                      chunks_per_version=2, rate_limit_bytes_round=None,
                      sync_budget_bytes=None, packed_min_cells=0)
    assert not packed_supported(bad_p, Topology())
    # the size gate: a tiny scenario under the shipped default threshold
    # stays dense (packing only pays at HBM scale — CPU A/B r4)
    small = dataclasses.replace(ok, packed_min_cells=SimConfig.packed_min_cells)
    assert not packed_supported(small, Topology())


def test_headline_storm_dispatches_packed():
    """The official 100k bench shape must ride the packed path: guards
    the envelope gate constants (payload multiple-of-32, power-of-two
    chunking, optimize_budgets stripping, the size threshold) against
    silent drift."""
    from corrosion_tpu.sim.runner import _write_storm

    cfg, _meta = _write_storm(100_000, 512)
    assert packed_supported(cfg, Topology())
    # the measured crossover (~10M cells): 25k×512 = 12.8M rides packed,
    # 4k×512 = 2.0M stays dense
    cfg25k, _ = _write_storm(25_000, 512)
    assert packed_supported(cfg25k, Topology())
    cfg4k, _ = _write_storm(4_000, 512)
    assert not packed_supported(cfg4k, Topology())


def test_metered_lossy_gapstress_class():
    """The r5 envelope extension: ALL limiters engaged at once — 30%
    payload loss, a binding broadcast governor, a binding sync byte
    budget, mixed 1 B-8 KiB payload sizes, burst injection over K=4 gap
    slots — must stay bit-for-bit equal to the dense round.  This is
    the gapstress scenario class (runner.config_write_storm_gapstress)
    at lockstep-testable scale."""
    from corrosion_tpu.sim.runner import gapstress_payload_sizes

    cfg = SimConfig.wan_tuned(
        24,
        n_payloads=256,  # 16 versions x 4 writers x 4 chunks
        n_writers=4,
        chunks_per_version=4,
        gap_slots=4,
        fanout=2,
        sync_interval_rounds=3,
        swim_partial_view=True,
        member_slots=8,
        # binding budgets: 256 mixed payloads sum to ~590 KiB, so a
        # 32 KiB broadcast tick and a 24 KiB sync grant both clamp
        rate_limit_bytes_round=32 * 1024,
        sync_budget_bytes=24 * 1024,
        packed_min_cells=0,
        n_delay_slots=2,
    )
    meta = uniform_payloads(
        cfg, inject_every=0,
        payload_bytes=gapstress_payload_sizes(cfg.n_payloads),
    )
    topo = Topology(loss=0.3)
    assert packed_supported(cfg, topo)
    _run_lockstep(cfg, topo, meta, rounds=40, seed=29)


# -- the fault seam (ISSUE 4): packed == dense under a FaultPlan ------------


def _fault_lockstep(cfg, topo, plan, meta, rounds, seed=0, factored=False):
    """Advance dense and packed paths side by side UNDER A FAULT
    SCHEDULE, comparing every round: each step slices the round's
    faults, applies node faults (alive/wipe) to both representations,
    and runs the faulted round body."""
    from corrosion_tpu.sim.faults import (
        apply_node_faults,
        compile_plan,
        round_faults,
    )
    from corrosion_tpu.sim.packed import apply_carry_faults

    assert packed_supported(cfg, topo), "scenario must be in the envelope"
    fplan = compile_plan(plan, cfg, topo, factored=factored)
    region = regions(cfg.n_nodes, topo.n_regions)

    @jax.jit
    def dense(state, metrics, meta):
        rf = round_faults(fplan, state.t)
        state = apply_node_faults(state, rf)
        return round_step(state, metrics, meta, cfg, topo, region, faults=rf)

    @jax.jit
    def packed(state, carry, inj, metrics, meta):
        rf = round_faults(fplan, state.t)
        state = apply_node_faults(state, rf)
        carry = apply_carry_faults(carry, rf)
        return packed_round_step(
            state, carry, inj, metrics, meta, cfg, topo, region, faults=rf
        )

    sd = new_sim(cfg, seed)
    md = new_metrics(cfg)
    carry = pack_state(sd, cfg)
    inj = pack_bits(sd.injected)
    sp = shrink_state(sd)
    mp = new_metrics(cfg)
    for t in range(rounds):
        sd, md = dense(sd, md, meta)
        sp, carry, inj, mp = packed(sp, carry, inj, mp, meta)
        _compare_round(t, sd, md, sp, carry, inj, mp, cfg)
    _assert_equal("alive", sd.alive, sp.alive)
    return sd, md


def _fault_cfg(**kw):
    kw.setdefault("n_payloads", 128)  # 8 versions x 4 writers x 4 chunks
    kw.setdefault("n_writers", 4)
    kw.setdefault("chunks_per_version", 4)
    kw.setdefault("fanout", 3)
    kw.setdefault("sync_interval_rounds", 4)
    kw.setdefault("swim_partial_view", True)
    kw.setdefault("member_slots", 16)
    kw.setdefault("rate_limit_bytes_round", None)
    kw.setdefault("sync_budget_bytes", None)
    kw.setdefault("packed_min_cells", 0)
    kw.setdefault("n_delay_slots", 4)
    return SimConfig.wan_tuned(48, **kw)


from corrosion_tpu.faults import FaultEvent, FaultPlan  # noqa: E402


_FAULT_PLANS = {
    "loss": (FaultEvent("loss", 0, 20, p=0.35),),
    "asym-partition": (
        FaultEvent("partition", 2, 16, src="0:24", dst="24:48"),
    ),
    "crash-wipe": (FaultEvent("crash", 6, 18, node=2, wipe=True),),
    "latency": (
        FaultEvent("delay", 2, 16, src="0:8", dst="*", delay_rounds=1),
        FaultEvent("jitter", 2, 16, src="0:8", dst="*", delay_rounds=1),
    ),
    "storm-mix": (
        FaultEvent("loss", 0, 20, p=0.3),
        FaultEvent(
            "partition", 4, 14, src="0:24", dst="24:48", symmetric=True
        ),
        FaultEvent("delay", 2, 16, src="0:8", dst="*", delay_rounds=1),
        FaultEvent("jitter", 2, 16, src="0:8", dst="*", delay_rounds=1),
        FaultEvent("crash", 10, 22, node=2, wipe=True),
    ),
}


@pytest.mark.chaos
@pytest.mark.parametrize("kind", sorted(_FAULT_PLANS))
def test_fault_seam_packed_equals_dense(kind):
    """ISSUE 4 satellite: packed == dense bit-for-bit, round-by-round,
    under each fault class — loss masks on the same per-(edge, payload)
    keys, asymmetric cuts, crash-with-wipe zeroing the packed carry +
    both SWIM tiers, and fault latency stretching the packed sync delay
    ring."""
    cfg = _fault_cfg()
    meta = uniform_payloads(cfg, inject_every=2)
    plan = FaultPlan(n_nodes=48, seed=5, events=_FAULT_PLANS[kind])
    _fault_lockstep(cfg, Topology(), plan, meta, rounds=30, seed=9)


@pytest.mark.chaos
def test_fault_seam_metered_class_packed_equals_dense():
    """The limiter class composes with fault loss on the packed path:
    binding broadcast governor + binding sync budget + mixed payload
    sizes + a loss burst and an asymmetric cut — budget_prefix_words
    spends on the attempt, loss eats the wire, bit-identical to dense."""
    from corrosion_tpu.sim.runner import gapstress_payload_sizes

    cfg = _fault_cfg(
        n_payloads=256,  # 16 versions x 4 writers x 4 chunks
        gap_slots=4,
        rate_limit_bytes_round=32 * 1024,
        sync_budget_bytes=24 * 1024,
    )
    meta = uniform_payloads(
        cfg, inject_every=0,
        payload_bytes=gapstress_payload_sizes(cfg.n_payloads),
    )
    plan = FaultPlan(
        n_nodes=48, seed=11,
        events=(
            FaultEvent("loss", 0, 18, p=0.3),
            FaultEvent("partition", 3, 12, src="0:16", dst="16:48"),
        ),
    )
    _fault_lockstep(cfg, Topology(loss=0.2), plan, meta, rounds=30, seed=17)


@pytest.mark.chaos
def test_fault_seam_factored_form_matches_matrix():
    """The storm-scale FactoredFaultPlan drives the packed round to the
    SAME bits as the matrix form (lockstep vs the matrix-compiled dense
    path): rank-1 factoring is a representation change, not a semantics
    change."""
    cfg = _fault_cfg()
    meta = uniform_payloads(cfg, inject_every=2)
    plan = FaultPlan(
        n_nodes=48, seed=5, events=_FAULT_PLANS["storm-mix"]
    )
    # packed path on the FACTORED plan, dense path on the MATRIX plan
    from corrosion_tpu.sim.faults import (
        apply_node_faults,
        compile_plan,
        round_faults,
    )
    from corrosion_tpu.sim.packed import apply_carry_faults

    topo = Topology()
    fp_m = compile_plan(plan, cfg, topo, factored=False)
    fp_f = compile_plan(plan, cfg, topo, factored=True)
    region = regions(cfg.n_nodes, topo.n_regions)

    @jax.jit
    def dense(state, metrics, meta):
        rf = round_faults(fp_m, state.t)
        state = apply_node_faults(state, rf)
        return round_step(state, metrics, meta, cfg, topo, region, faults=rf)

    @jax.jit
    def packed(state, carry, inj, metrics, meta):
        rf = round_faults(fp_f, state.t)
        state = apply_node_faults(state, rf)
        carry = apply_carry_faults(carry, rf)
        return packed_round_step(
            state, carry, inj, metrics, meta, cfg, topo, region, faults=rf
        )

    sd = new_sim(cfg, 9)
    md = new_metrics(cfg)
    carry = pack_state(sd, cfg)
    inj = pack_bits(sd.injected)
    sp = shrink_state(sd)
    mp = new_metrics(cfg)
    for t in range(30):
        sd, md = dense(sd, md, meta)
        sp, carry, inj, mp = packed(sp, carry, inj, mp, meta)
        _compare_round(t, sd, md, sp, carry, inj, mp, cfg)


@pytest.mark.chaos
@pytest.mark.slow
def test_fault_storm_4096_packed_vs_dense():
    """The acceptance storm: 4096 nodes under a nontrivial FaultPlan
    (loss burst + half-split symmetric partition + crash-with-wipe)
    converge bit-identically on the packed vs dense paths through the
    PUBLIC entry (`run_fault_plan`, which dispatches on the envelope) —
    same heads, same rounds, same digests."""
    import hashlib

    from corrosion_tpu.sim.faults import compile_plan, run_fault_plan
    from corrosion_tpu.sim.runner import _write_storm, storm_fault_plan

    cfg, meta = _write_storm(4096, 512)
    cfg = dataclasses.replace(cfg, packed_min_cells=0)
    topo = Topology()
    plan = storm_fault_plan(4096, seed=3)
    assert packed_supported(cfg, topo)

    fplan = compile_plan(plan, cfg, topo)  # auto-factored at 4096
    fp, mp = run_fault_plan(new_sim(cfg, 7), meta, cfg, topo, fplan, 1000)

    cfgd = dataclasses.replace(cfg, allow_packed=False)
    fd, md = run_fault_plan(
        new_sim(cfgd, 7), meta, cfgd, topo,
        compile_plan(plan, cfgd, topo), 1000,
    )

    assert int(fp.t) == int(fd.t) >= plan.horizon
    digests = []
    for final in (fp, fd):
        h = hashlib.blake2b(digest_size=16)
        for name in ("have", "heads", "alive", "relay_left", "injected"):
            h.update(np.asarray(getattr(final, name)).tobytes())
        digests.append(h.hexdigest())
    assert digests[0] == digests[1]
    _assert_equal("storm converged_at", md.converged_at, mp.converged_at)
    _assert_equal("storm coverage_at", md.coverage_at, mp.coverage_at)
    # and it actually converged (all up nodes) after the schedule
    up = np.asarray(fp.alive) == 0
    assert (np.asarray(mp.converged_at)[up] >= 0).all()


def test_budget_prefix_words_matches_dense_mask():
    """Property check of the word-domain budget kernel against the dense
    budget_prefix_mask over random masks, mixed sizes, and budgets —
    including the two-lane large-P arithmetic."""
    from corrosion_tpu.sim.packed import budget_prefix_words
    from corrosion_tpu.sim.state import budget_prefix_mask

    rng = np.random.default_rng(7)
    for p, budget in ((256, 17_000), (256, 1), (256, None), (1024, 300_000),
                      (65536, 9_000_000)):  # 65536 > 32767: two-lane path
        sizes = rng.choice([1, 64, 512, 1024, 4096, 8192], size=p)
        mask = rng.random((8, p)) < 0.6
        dense = budget_prefix_mask(
            jnp.asarray(mask), budget, jnp.asarray(sizes, jnp.int32)
        )
        words = budget_prefix_words(
            pack_bits(jnp.asarray(mask)), budget,
            jnp.asarray(sizes, jnp.int32),
        )
        _assert_equal(
            f"budget p={p} b={budget}",
            np.asarray(dense),
            np.asarray(unpack_bits(words, p)),
        )
