"""The sim invariant catalog (SURVEY §4.5 analog) holds every round
across dissemination, loss, chunking, partitions, and membership modes."""

import jax.numpy as jnp
import numpy as np

from corrosion_tpu.sim.invariants import check_state
from corrosion_tpu.sim.round import new_metrics, new_sim, round_step
from corrosion_tpu.sim.state import ALIVE, DOWN, SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import Topology, regions


def drive_checked(cfg, topo=Topology(), seed=0, rounds=60, mutate=None,
                  dead=None):
    region = regions(cfg.n_nodes, topo.n_regions)
    meta = uniform_payloads(cfg)
    state = new_sim(cfg, seed)
    if mutate:
        state = mutate(state)
    metrics = new_metrics(cfg)
    for _ in range(rounds):
        state, metrics = round_step(state, metrics, meta, cfg, topo, region)
        check_state(state, cfg, dead_since_start=dead)
    return state


def test_invariants_chunked_lossy():
    cfg = SimConfig(n_nodes=48, n_payloads=24, n_writers=2,
                    chunks_per_version=3, gap_slots=4,
                    sync_interval_rounds=4)
    drive_checked(cfg, topo=Topology(loss=0.4), rounds=80)


def test_invariants_with_dead_nodes_and_partition():
    cfg = SimConfig(n_nodes=32, n_payloads=8, sync_interval_rounds=4)
    dead = np.zeros(32, bool)
    dead[8:12] = True

    def mutate(state):
        alive = state.alive.at[8:12].set(DOWN)
        group = (jnp.arange(32) >= 16).astype(jnp.int32)
        return state._replace(alive=alive, group=group)

    drive_checked(cfg, rounds=50, mutate=mutate, dead=dead)


def test_invariants_partial_view_swim():
    cfg = SimConfig.wan_tuned(
        64, n_payloads=8, swim_partial_view=True, member_slots=16,
        sync_interval_rounds=4,
    )
    drive_checked(cfg, rounds=50)
