"""Ground-truth calibration (BASELINE config #1 + VERDICT r1 item 4):
the TPU sim's convergence behavior must match the real in-process
host-agent cluster as a DISTRIBUTION, not a single scalar in a ×10 band.

Two comparisons, both normalized to protocol-native time units so the
round discretization is what's under test (SURVEY §7 hard part #3):

1. 3-node single-writer burst: p50/p99 rounds-to-convergence over ≥10
   seeds on each tier, within ×2 (+2 rounds additive discretization
   slack).  One sim round ≡ one broadcast flush tick.
2. 64-node SWIM kill: detection latency (all survivors mark all dead
   DOWN), measured in PROBE PERIODS on each tier, within ×2.  Both
   tiers run probe-every-period with a 10-probe suspicion window.
"""

import asyncio

import numpy as np

from corrosion_tpu.sim.round import new_metrics, new_sim, round_step, run_to_convergence
from corrosion_tpu.sim.state import ALIVE, DOWN, SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import Topology, regions
from corrosion_tpu.testing import Cluster

N_VERSIONS = 20
N_SEEDS = 10


def host_rounds_once() -> float:
    """Real 3-node agent cluster: write N versions, measure convergence
    wall-clock in units of the broadcast flush interval."""

    async def body():
        cluster = Cluster(3)
        await cluster.start()
        try:
            flush = cluster.agents[0].config.perf.broadcast_flush_interval_s
            a = cluster.agents[0]
            t0 = asyncio.get_event_loop().time()
            for i in range(N_VERSIONS):
                a.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, f"v{i}"))]
                )
            assert await cluster.wait_converged(30)
            elapsed = asyncio.get_event_loop().time() - t0
            return elapsed / flush
        finally:
            await cluster.stop()

    return asyncio.run(body())


def sim_rounds_once(seed: int) -> float:
    cfg = SimConfig(n_nodes=3, n_payloads=N_VERSIONS, fanout=2,
                    sync_interval_rounds=4)
    meta = uniform_payloads(cfg, inject_every=0)  # one burst
    state = new_sim(cfg, seed=seed)
    final, metrics = run_to_convergence(state, meta, cfg, Topology(), 500)
    conv = np.asarray(metrics.converged_at)
    assert (conv >= 0).all()
    return float(conv.max())


def test_convergence_distribution_matches_host():
    host = np.array([host_rounds_once() for _ in range(N_SEEDS)])
    sim = np.array([sim_rounds_once(s) for s in range(N_SEEDS)])
    # p99 over 10 samples is the max; the host tier measures wall-clock
    # on a shared machine, where one scheduler hiccup inflates the max by
    # ~0.1 s ≈ 5 flush ticks — p50 keeps the tight band, p99 adds that
    # measured noise floor on top of the ×2 ratio
    for q, slack in ((50, 2), (99, 8)):
        h = float(np.percentile(host, q))
        s = float(np.percentile(sim, q))
        assert s <= h * 2 + slack, f"p{q}: sim={s:.1f} vs host={h:.1f} ticks"
        assert h <= s * 2 + slack, f"p{q}: host={h:.1f} ticks vs sim={s:.1f}"
    print(
        f"calibration: host p50/p99 = {np.percentile(host, 50):.1f}/"
        f"{np.percentile(host, 99):.1f} ticks, sim = "
        f"{np.percentile(sim, 50):.1f}/{np.percentile(sim, 99):.1f} rounds"
    )


# -- 64-node SWIM detection latency ----------------------------------------

N_SWIM = 64
N_KILL = 8
SUSPECT_PROBES = 10  # suspicion window in probe periods, both tiers
HOST_PROBE_S = 0.1  # large vs event-loop scheduling lag at 64 in-process agents


def host_swim_detection_probe_periods() -> float:
    """64 in-process agents with real SWIM; kill N_KILL, measure
    wall-clock until every survivor marks every victim DOWN, in probe
    periods."""
    from corrosion_tpu.agent.swim import DOWN as H_DOWN

    async def body():
        cluster = Cluster(N_SWIM)
        await cluster.start()
        # align the suspicion window with the sim tier (10 probe
        # periods); the runtime reads perf live each loop tick
        for a in cluster.agents:
            a.config.perf.swim_probe_interval_s = HOST_PROBE_S
            a.config.perf.swim_suspect_timeout_s = HOST_PROBE_S * SUSPECT_PROBES
            # fixed window: both tiers run EXACTLY 10 probe periods
            a.config.perf.swim_adaptive_timing = False
        try:
            # let membership form: everyone knows everyone
            deadline = asyncio.get_event_loop().time() + 30
            while asyncio.get_event_loop().time() < deadline:
                if all(
                    len(a.swim.members) >= N_SWIM - 1 for a in cluster.agents
                ):
                    break
                await asyncio.sleep(0.1)
            victims = cluster.agents[:N_KILL]
            victim_ids = [v.actor_id for v in victims]
            survivors = cluster.agents[N_KILL:]
            t0 = asyncio.get_event_loop().time()
            for v in victims:
                await v.stop()

            def all_detected():
                return all(
                    a.swim.members.get(vid) is not None
                    and a.swim.members[vid].status == H_DOWN
                    for a in survivors
                    for vid in victim_ids
                )

            deadline = asyncio.get_event_loop().time() + 90
            while asyncio.get_event_loop().time() < deadline:
                if all_detected():
                    break
                await asyncio.sleep(0.1)
            assert all_detected(), "host survivors must detect all victims"
            elapsed = asyncio.get_event_loop().time() - t0
            return elapsed / HOST_PROBE_S
        finally:
            for a in cluster.agents[N_KILL:]:
                await a.stop()
            cluster.tmp.cleanup()

    return asyncio.run(body())


def sim_swim_detection_probe_periods(seed: int) -> float:
    import jax.numpy as jnp

    cfg = SimConfig(
        n_nodes=N_SWIM, n_payloads=1, swim_full_view=True,
        probe_period_rounds=1, suspect_timeout_rounds=SUSPECT_PROBES,
    )
    meta = uniform_payloads(cfg)
    topo = Topology()
    region = regions(N_SWIM, 1)
    state = new_sim(cfg, seed)
    kill = np.zeros(N_SWIM, bool)
    kill[:N_KILL] = True
    state = state._replace(
        alive=jnp.where(jnp.asarray(kill), jnp.uint8(DOWN), jnp.uint8(ALIVE))
    )
    metrics = new_metrics(cfg)
    for _ in range(400):
        state, metrics = round_step(state, metrics, meta, cfg, topo, region)
        view = np.asarray(state.view)
        up = np.asarray(state.alive) == ALIVE
        if (view[np.ix_(up, ~up)] == DOWN).all():
            return float(int(state.t)) / cfg.probe_period_rounds
    raise AssertionError("sim survivors never detected all victims")


def test_swim_detection_latency_matches_host():
    host = host_swim_detection_probe_periods()
    sims = [sim_swim_detection_probe_periods(s) for s in range(5)]
    sim = float(np.median(sims))
    assert sim <= host * 2 + 2, f"sim={sim:.1f} vs host={host:.1f} probe periods"
    assert host <= sim * 2 + 2, f"host={host:.1f} vs sim={sim:.1f} probe periods"
    print(f"swim detection: host={host:.1f}, sim median={sim:.1f} probe periods")
