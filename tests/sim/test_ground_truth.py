"""Ground-truth calibration (BASELINE config #1): the TPU sim's 3-node
convergence behavior must match the real in-process host-agent cluster.

Both tiers run the same scenario — 3 nodes, 1 writer, a burst of versions —
and we compare convergence latency measured in broadcast-flush ticks
(1 sim round ≡ 1 flush interval).  The sim is a round-synchronous
discretization, so the assertion is a band, not equality: the reference's
own tests accept seconds of slack (tests.rs:52 sleeps 1 s and checks)."""

import asyncio

import numpy as np

from corrosion_tpu.sim.round import new_sim, run_to_convergence
from corrosion_tpu.sim.state import SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import Topology
from corrosion_tpu.testing import Cluster

N_VERSIONS = 20


def host_rounds_to_convergence() -> float:
    """Real 3-node agent cluster: write N versions, measure convergence
    wall-clock in units of the broadcast flush interval."""

    async def body():
        cluster = Cluster(3)
        await cluster.start()
        try:
            flush = cluster.agents[0].config.perf.broadcast_flush_interval_s
            a = cluster.agents[0]
            t0 = asyncio.get_event_loop().time()
            for i in range(N_VERSIONS):
                a.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, f"v{i}"))]
                )
            assert await cluster.wait_converged(30)
            elapsed = asyncio.get_event_loop().time() - t0
            return elapsed / flush
        finally:
            await cluster.stop()

    return asyncio.run(body())


def sim_rounds_to_convergence() -> float:
    cfg = SimConfig(n_nodes=3, n_payloads=N_VERSIONS, fanout=2,
                    sync_interval_rounds=4)
    meta = uniform_payloads(cfg, inject_every=0)  # one burst
    state = new_sim(cfg, seed=0)
    final, metrics = run_to_convergence(state, meta, cfg, Topology(), 500)
    conv = np.asarray(metrics.converged_at)
    assert (conv >= 0).all()
    return float(conv.max())


def test_sim_matches_host_ground_truth():
    host = host_rounds_to_convergence()
    sim = sim_rounds_to_convergence()
    # both tiers must settle a 20-version burst within a handful of flush
    # ticks of each other; an order-of-magnitude drift means the round
    # discretization is distorting convergence (SURVEY §7 hard part #3)
    assert sim <= host * 10 + 10, f"sim={sim} rounds vs host={host:.1f} ticks"
    assert host <= sim * 10 + 10, f"host={host:.1f} ticks vs sim={sim} rounds"
    print(f"ground truth: host={host:.1f} flush-ticks, sim={sim} rounds")
