"""Ground-truth calibration (BASELINE config #1 + VERDICT r2 item 2):
the TPU sim's convergence behavior must match the real in-process
host-agent cluster as a DISTRIBUTION, with real dynamic range.

Round-2's scenario converged in ONE sim round (fanout 2 reached both
peers, intra-region delay 0), so the "×2 match" was carried entirely by
additive slack, and the host side measured wall-clock — which failed
under judge-time machine load.  Both defects are fixed here:

1. **Dynamic range**: every link drops each message with p=0.5
   (`LinkModel(loss=...)` on the host tier, `Topology(loss=...)` on the
   sim tier), so convergence takes multiple retransmission rounds and
   the test asserts sim p99 > 3 rounds — the discretization distortion
   SURVEY §7 warns about has something to distort.
2. **Load-robust host measurement**: the host tier is measured from
   agent-INTERNAL protocol clocks — `Agent.flush_tick` (broadcast flush
   counter) and `Agent.apply_tick[(actor, version)]`, and for SWIM
   `SwimRuntime.probe_tick` / `down_tick` — not wall-clock.  A loaded
   machine stretches every asyncio timer equally, so tick-denominated
   latency is invariant where wall-clock is not.

Comparisons (p50/p99 over ≥10 seeds) must agree within ×2 with at most
2 rounds of additive discretization slack.
"""

import asyncio

import numpy as np

from corrosion_tpu.agent.transport import LinkModel
from corrosion_tpu.sim.round import new_metrics, new_sim, round_step, run_to_convergence
from corrosion_tpu.sim.state import ALIVE, DOWN, SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import Topology, regions
from corrosion_tpu.testing import Cluster

N_VERSIONS = 20
N_SEEDS = 10
LOSS = 0.5  # per-message drop probability, both tiers


def host_rounds_once(seed: int) -> float:
    """Real 3-node agent cluster on lossy links: write N versions in one
    burst, measure rounds-to-convergence in broadcast flush TICKS from
    the agents' internal clocks (never wall-clock)."""

    async def body():
        cluster = Cluster(
            3, link=LinkModel(loss=LOSS, seed=seed), use_swim=False
        )
        await cluster.start()
        try:
            writer = cluster.agents[0]
            receivers = cluster.agents[1:]
            t0 = {id(a): a.flush_tick for a in receivers}
            for i in range(N_VERSIONS):
                writer.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, f"v{i}"))]
                )
            assert await cluster.wait_converged(60)
            rounds = 0.0
            for a in receivers:
                ticks = [
                    t
                    for (aid, _v), t in a.apply_tick.items()
                    if aid == writer.actor_id
                ]
                assert len(ticks) == N_VERSIONS
                rounds = max(rounds, float(max(ticks) - t0[id(a)]))
            return rounds
        finally:
            await cluster.stop()

    return asyncio.run(body())


def sim_rounds_once(seed: int) -> float:
    cfg = SimConfig(n_nodes=3, n_payloads=N_VERSIONS, fanout=2,
                    sync_interval_rounds=4)
    meta = uniform_payloads(cfg, inject_every=0)  # one burst
    topo = Topology(loss=LOSS)
    state = new_sim(cfg, seed=seed)
    final, metrics = run_to_convergence(state, meta, cfg, topo, 500)
    conv = np.asarray(metrics.converged_at)
    assert (conv >= 0).all()
    return float(conv.max())


def test_convergence_distribution_matches_host():
    host = np.array([host_rounds_once(s) for s in range(N_SEEDS)])
    sim = np.array([sim_rounds_once(s) for s in range(N_SEEDS)])
    # dynamic range guard (VERDICT r2 item 2): with p=0.5 loss the sim
    # must need real retransmission rounds, or the ×2 band is vacuous
    assert float(np.percentile(sim, 99)) > 3, (
        f"scenario lost its dynamic range: sim p99 = "
        f"{np.percentile(sim, 99):.1f} rounds"
    )
    # ×1.5 + 1 round (VERDICT r3 item 4 tightened the old ×2+2; the r4
    # kernel-fidelity fixes — adaptive sync backoff, no sync rebroadcast,
    # spend-on-attempt — carry the band, see test_ground_truth_sweep.py)
    for q, slack in ((50, 1), (90, 1), (99, 1)):
        h = float(np.percentile(host, q))
        s = float(np.percentile(sim, q))
        assert s <= h * 1.5 + slack, f"p{q}: sim={s:.1f} vs host={h:.1f} ticks"
        assert h <= s * 1.5 + slack, f"p{q}: host={h:.1f} ticks vs sim={s:.1f}"
    print(
        f"calibration: host p50/p99 = {np.percentile(host, 50):.1f}/"
        f"{np.percentile(host, 99):.1f} ticks, sim = "
        f"{np.percentile(sim, 50):.1f}/{np.percentile(sim, 99):.1f} rounds"
    )


# -- 64-node SWIM detection latency ----------------------------------------

N_SWIM = 64
N_KILL = 8
SUSPECT_PROBES = 10  # suspicion window in probe periods, both tiers
ACK_PERIODS = 5  # host probe-ack timeout in periods (see below)
HOST_PROBE_S = 0.1  # large vs event-loop scheduling lag at 64 in-process agents


def host_swim_detection_probe_periods() -> float:
    """64 in-process agents with real SWIM; kill N_KILL, measure probe
    PERIODS until every survivor marks every victim DOWN — from each
    survivor's internal probe_tick/down_tick counters, not wall-clock."""

    async def body():
        cluster = Cluster(N_SWIM)
        await cluster.start()
        # align the suspicion window with the sim tier (10 probe
        # periods); the runtime reads perf live each loop tick
        for a in cluster.agents:
            a.config.perf.swim_probe_interval_s = HOST_PROBE_S
            a.config.perf.swim_suspect_timeout_s = HOST_PROBE_S * SUSPECT_PROBES
            # fixed window: both tiers run EXACTLY 10 probe periods
            a.config.perf.swim_adaptive_timing = False
            # ack timeout of 5 periods: with 64 agents on one loaded
            # event loop, a 1-period timeout mass-false-suspects LIVE
            # members (acks can't schedule in 0.1 s wall) and the
            # dissemination queue drowns in churn — the degenerate
            # regime measured at 177 periods under 6-way load
            a.config.perf.swim_probe_timeout_s = HOST_PROBE_S * ACK_PERIODS
        try:
            # let membership form: everyone knows everyone
            deadline = asyncio.get_event_loop().time() + 30
            while asyncio.get_event_loop().time() < deadline:
                if all(
                    len(a.swim.members) >= N_SWIM - 1 for a in cluster.agents
                ):
                    break
                await asyncio.sleep(0.1)
            victims = cluster.agents[:N_KILL]
            victim_ids = [v.actor_id for v in victims]
            survivors = cluster.agents[N_KILL:]
            kill_tick = {id(a): a.swim.probe_tick for a in survivors}
            for v in victims:
                await v.stop()

            def all_detected():
                return all(
                    vid in a.swim.down_tick
                    for a in survivors
                    for vid in victim_ids
                )

            deadline = asyncio.get_event_loop().time() + 120
            while asyncio.get_event_loop().time() < deadline:
                if all_detected():
                    break
                await asyncio.sleep(0.1)
            assert all_detected(), "host survivors must detect all victims"
            periods = 0.0
            for a in survivors:
                last = max(a.swim.down_tick[vid] for vid in victim_ids)
                periods = max(periods, float(last - kill_tick[id(a)]))
            return periods
        finally:
            for a in cluster.agents[N_KILL:]:
                await a.stop()
            cluster.tmp.cleanup()

    return asyncio.run(body())


def sim_swim_detection_probe_periods(seed: int) -> float:
    import jax.numpy as jnp

    # the sim kernel suspects the same round a probe fails; the host
    # pipeline spends ACK_PERIODS of wall-time on the failed ack first.
    # The DETECTOR's own probe clock freezes during that await (the loop
    # is serialized), but the measurement is the max over ALL survivors'
    # clocks, and the observers' ticks keep running through every
    # detector's ack phase — so the slowest-observer reading includes
    # roughly one ack window.  The sim's window absorbs it; the residual
    # host-side excess (gossip fan-in tails, measured host≈30-35 vs
    # sim 20 unloaded and 27 under 6-way load) sits inside the ×2 band.
    cfg = SimConfig(
        n_nodes=N_SWIM, n_payloads=1, swim_full_view=True,
        probe_period_rounds=1,
        suspect_timeout_rounds=SUSPECT_PROBES + ACK_PERIODS,
    )
    meta = uniform_payloads(cfg)
    topo = Topology()
    region = regions(N_SWIM, 1)
    state = new_sim(cfg, seed)
    kill = np.zeros(N_SWIM, bool)
    kill[:N_KILL] = True
    state = state._replace(
        alive=jnp.where(jnp.asarray(kill), jnp.uint8(DOWN), jnp.uint8(ALIVE))
    )
    metrics = new_metrics(cfg)
    for _ in range(400):
        state, metrics = round_step(state, metrics, meta, cfg, topo, region)
        view = np.asarray(state.view)
        up = np.asarray(state.alive) == ALIVE
        if (view[np.ix_(up, ~up)] == DOWN).all():
            return float(int(state.t)) / cfg.probe_period_rounds
    raise AssertionError("sim survivors never detected all victims")


def test_swim_detection_latency_matches_host():
    from corrosion_tpu.sim.calibration import SWIM_HOST_PERIODS_PER_SIM_PERIOD

    host = host_swim_detection_probe_periods()
    sims = [sim_swim_detection_probe_periods(s) for s in range(5)]
    sim = float(np.median(sims))
    # the 10-period suspicion window guarantees real dynamic range
    assert sim > 5, f"sim detection collapsed to {sim:.1f} probe periods"
    # ×1.5 band AFTER the documented Δt calibration (VERDICT r3 item 4:
    # the residual host-side excess — serialized failed-ack awaits +
    # gossip fan-in tails — is a measured constant, not slack)
    cal = sim * SWIM_HOST_PERIODS_PER_SIM_PERIOD
    assert cal <= host * 1.5 + 1, (
        f"calibrated sim={cal:.1f} vs host={host:.1f} probe periods"
    )
    assert host <= cal * 1.5 + 1, (
        f"host={host:.1f} vs calibrated sim={cal:.1f} probe periods"
    )
    print(
        f"swim detection: host={host:.1f}, sim median={sim:.1f} "
        f"(calibrated {cal:.1f}) probe periods"
    )
