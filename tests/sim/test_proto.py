"""Protocol-variant subsystem (ISSUE 11).

Four contracts under test:

1. **Default byte-identity** — the default protocol point (every proto
   knob at its legacy value, `proto_family` unset AND explicitly
   ``"baseline"``) compiles to the pre-ISSUE-11 program: the digest
   constants captured on the pre-change tree (tests/sim/test_topo.py's
   pins) reproduce, and the jax-free `proto.DEFAULTS` table mirrors the
   SimConfig field defaults exactly.
2. **Variant correctness** — every named family builds a valid config,
   converges, and runs dense==packed bit-equal (telemetry included);
   unknown knob values and unsupported combos refuse loudly.
3. **Ordering invariant** — the enforced FIFO discipline ends every run
   at ZERO on-device delivery-order violations (and the host-snapshot
   twin agrees), while the ``fifo-unchecked`` negative control MUST
   trip it (the pinned violation test); both compose with FaultPlans.
4. **Campaign-spec resolution** — `proto_family` resolves through the
   registry with explicit keys overlaying the family, and the
   protocol-frontier builtin expands to the 4 × 2 variant grid.
"""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.faults import FaultEvent, FaultPlan
from corrosion_tpu.proto import DEFAULTS, FAMILIES, PROTO_KEYS, family_proto
from corrosion_tpu.sim.faults import compile_plan, run_fault_plan
from corrosion_tpu.sim.round import new_sim, run_to_convergence
from corrosion_tpu.sim.state import ALIVE, SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import Topology

VARIANT_FAMILIES = sorted(set(FAMILIES) - {"baseline"})


def _digest(state, skip=("pview",)):
    """The test_topo.py digest (pre-ISSUE-9 fields) so pins captured on
    the pre-change trees stay comparable."""
    h = hashlib.blake2b(digest_size=8)
    for f, v in zip(type(state)._fields, state):
        if f in skip:
            continue
        h.update(f.encode())
        h.update(np.ascontiguousarray(np.asarray(v)).tobytes())
    return h.hexdigest()


def _cfg(fam=None, **kw):
    base = dict(
        n_nodes=48, n_payloads=32, n_writers=2, fanout=3,
        sync_interval_rounds=4,
    )
    if fam:
        base.update(family_proto(fam))
    base.update(kw)  # explicit knobs overlay the family (the spec rule)
    return SimConfig(**base)


# -- 1. default byte-identity ------------------------------------------------


def test_defaults_table_mirrors_simconfig_fields():
    """`proto.DEFAULTS` is the jax-free copy `sim proto show` renders;
    it must mirror the SimConfig field defaults exactly (the drift
    guard the registry docstring promises)."""
    fields = SimConfig.__dataclass_fields__
    assert set(DEFAULTS) == set(PROTO_KEYS)
    for k, v in DEFAULTS.items():
        assert fields[k].default == v, k


def test_explicit_baseline_family_is_byte_identical_to_unset():
    """proto_family="baseline" resolved through the spec must build the
    IDENTICAL SimConfig — and its run must reproduce the digest pinned
    on the pre-ISSUE-11 tree (test_topo.py's constant)."""
    from corrosion_tpu.campaign.spec import CampaignSpec

    scenario = {
        "n_nodes": 24, "n_payloads": 16, "fanout": 2,
        "sync_interval_rounds": 4,
    }
    unset = CampaignSpec(name="t", scenario=dict(scenario))
    explicit = CampaignSpec(
        name="t", scenario=dict(scenario, proto_family="baseline")
    )
    cfg_unset = unset.sim_config({})
    cfg_explicit = explicit.sim_config({})
    assert cfg_unset == cfg_explicit
    meta = uniform_payloads(cfg_explicit, inject_every=1)
    final, _ = run_to_convergence(
        new_sim(cfg_explicit, 3), meta, cfg_explicit, Topology(), 200
    )
    assert int(final.t) == 20
    assert _digest(final) == "c5d4e8bcd80cb0ef"  # the pre-change pin


def test_default_metrics_carry_zero_order_violations():
    cfg = _cfg()
    meta = uniform_payloads(cfg, inject_every=1)
    _, m = run_to_convergence(new_sim(cfg, 3), meta, cfg, Topology(), 300)
    assert int(m.order_violations) == 0


# -- 2. variant correctness --------------------------------------------------


def test_simconfig_refuses_unknown_proto_values():
    with pytest.raises(ValueError, match="dissemination"):
        _cfg(dissemination="pull")
    with pytest.raises(ValueError, match="fanout_schedule"):
        _cfg(fanout_schedule="ramp")
    with pytest.raises(ValueError, match="fanout_decay_rounds"):
        _cfg(fanout_schedule="decay", fanout_decay_rounds=0)
    with pytest.raises(ValueError, match="sync_cadence"):
        _cfg(sync_cadence="lazy")
    with pytest.raises(ValueError, match="ordering"):
        _cfg(ordering="total")
    # ordering over a single version per writer has no order to impose
    with pytest.raises(ValueError, match="versions"):
        SimConfig(n_nodes=8, n_payloads=1, ordering="fifo")
    with pytest.raises(KeyError, match="unknown protocol family"):
        family_proto("no-such-family")


def test_every_family_builds_and_converges():
    topo = Topology(loss=0.2)
    for fam in FAMILIES:
        cfg = _cfg(fam)
        meta = uniform_payloads(cfg, inject_every=1)
        final, m = run_to_convergence(
            new_sim(cfg, 3), meta, cfg, topo, 600
        )
        conv = np.asarray(m.converged_at)
        assert (conv >= 0).all(), fam
        assert (np.asarray(final.have) > 0).all(), fam


@pytest.mark.parametrize(
    "fam",
    [
        # tier-1 keeps the variants with UNIQUE kernel seams: the pull
        # exchange, the enforced delivery gate, and the unchecked
        # violation counter; the schedule/cadence variants (pure mask /
        # due overrides on shared machinery) ride the nightly slow tier
        "push-pull",
        "lab-ordered",
        "lab-ordered-broken",
        pytest.param("swarm-aggressive", marks=pytest.mark.slow),
        pytest.param("fanout-decay", marks=pytest.mark.slow),
    ],
)
def test_variant_packed_matches_dense(fam):
    """Every variant family runs the packed round bit-identical to the
    dense one — state, metrics (order_violations included), and every
    telemetry channel — under a lossy topology so the pull/drop seams
    actually fire."""
    kw = dict(n_nodes=64, n_payloads=64, n_writers=4, fanout=3)
    kw.update(family_proto(fam))
    cfg = dataclasses.replace(SimConfig(**kw), packed_min_cells=0)
    dense_cfg = dataclasses.replace(cfg, allow_packed=False)
    meta = uniform_payloads(cfg, inject_every=1)
    topo = Topology(loss=0.1)
    packed = run_to_convergence(
        new_sim(cfg, 5), meta, cfg, topo, 600, telemetry=True
    )
    dense = run_to_convergence(
        new_sim(dense_cfg, 5), meta, dense_cfg, topo, 600, telemetry=True
    )
    for x, y in zip(jax.tree.leaves(packed), jax.tree.leaves(dense)):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"packed diverged from dense under {fam}",
        )


def test_fused_matrix_never_perturbs_state_or_trace(monkeypatch):
    """ISSUE 19 matrix: telemetry {off, on} × CORRO_FUSED_ROUND {1, 0}
    on the push-pull family (the richest kernel seam).  Two pins per
    cell: packed stays bit-identical to dense, and flipping the fusion
    seam moves NOTHING — not the state (fusion must not perturb RNG
    draw order), not the metrics, not a single telemetry channel (the
    fused counters are integer-identical to the loop oracles, not just
    close).  The seam is read at trace time, so each flip clears the
    jit caches."""
    kw = dict(n_nodes=48, n_payloads=32, n_writers=2, fanout=3)
    kw.update(family_proto("push-pull"))
    cfg = dataclasses.replace(SimConfig(**kw), packed_min_cells=0)
    dense_cfg = dataclasses.replace(cfg, allow_packed=False)
    meta = uniform_payloads(cfg, inject_every=1)
    topo = Topology(loss=0.1)
    out = {}
    for fused in ("1", "0"):
        monkeypatch.setenv("CORRO_FUSED_ROUND", fused)
        jax.clear_caches()
        for telemetry in (False, True):
            packed = run_to_convergence(
                new_sim(cfg, 5), meta, cfg, topo, 400, telemetry=telemetry
            )
            dense = run_to_convergence(
                new_sim(dense_cfg, 5), meta, dense_cfg, topo, 400,
                telemetry=telemetry,
            )
            for x, y in zip(jax.tree.leaves(packed), jax.tree.leaves(dense)):
                np.testing.assert_array_equal(
                    np.asarray(x), np.asarray(y),
                    err_msg=f"packed != dense (fused={fused}, "
                            f"telemetry={telemetry})",
                )
            out[fused, telemetry] = packed
    jax.clear_caches()  # drop the fused=0 traces before later tests
    for telemetry in (False, True):
        hot = jax.tree.leaves(out["1", telemetry])
        cold = jax.tree.leaves(out["0", telemetry])
        assert len(hot) == len(cold)
        for x, y in zip(hot, cold):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y),
                err_msg=f"fused flip moved results (telemetry={telemetry})",
            )


def test_variant_runs_are_deterministic():
    cfg = _cfg("push-pull")
    meta = uniform_payloads(cfg, inject_every=1)
    a, _ = run_to_convergence(new_sim(cfg, 7), meta, cfg, Topology(), 300)
    b, _ = run_to_convergence(new_sim(cfg, 7), meta, cfg, Topology(), 300)
    assert _digest(a, skip=()) == _digest(b, skip=())


def test_push_pull_pays_wire_for_rounds():
    """The exchange's trade on a lossy topology: push-pull must not be
    slower than push, and must transmit MORE wire bytes (the responses
    are real frames — the Pareto's cost axis)."""
    topo = Topology(loss=0.2)
    out = {}
    for fam in ("baseline", "push-pull"):
        cfg = _cfg(fam)
        meta = uniform_payloads(cfg, inject_every=1)
        final, m, trace = run_to_convergence(
            new_sim(cfg, 3), meta, cfg, topo, 400, telemetry=True
        )
        r = int(final.t)
        out[fam] = (
            r, float(np.asarray(trace.bcast_bytes)[:r].sum())
        )
    assert out["push-pull"][0] <= out["baseline"][0]
    assert out["push-pull"][1] > out["baseline"][1]


def test_fanout_decay_caps_active_slots():
    from corrosion_tpu.proto.schedule import active_fanout

    cfg = _cfg("fanout-decay", fanout=4, fanout_decay_rounds=4)
    f = [int(active_fanout(cfg, jnp.int32(t))) for t in (0, 3, 4, 8, 100)]
    assert f == [4, 4, 2, 1, 1]


# -- 3. the delivery-order invariant ----------------------------------------


def _lossy_order_run(fam, seed=3):
    cfg = _cfg(fam)
    meta = uniform_payloads(cfg, inject_every=1)
    topo = Topology(loss=0.3)  # per-payload loss reorders deliveries
    final, m = run_to_convergence(new_sim(cfg, seed), meta, cfg, topo, 800)
    return cfg, meta, final, m


def test_enforced_ordering_holds_the_invariant_at_zero():
    cfg, meta, final, m = _lossy_order_run("lab-ordered")
    assert (np.asarray(m.converged_at) >= 0).all()
    assert int(m.order_violations) == 0
    # the host-snapshot twin agrees (sim/invariants.py check_state)
    from corrosion_tpu.sim.invariants import check_state

    check_state(final, cfg, meta=meta)


def test_broken_ordering_trips_the_invariant():
    """The pinned violation test: the unchecked negative control runs
    the same on-device check without the delivery gate — gossip reorder
    under loss MUST trip it (deterministic for the pinned seed)."""
    _, _, _, m = _lossy_order_run("lab-ordered-broken")
    assert int(m.order_violations) > 0


def test_ordering_composes_with_fault_plans():
    """FIFO ordering under a loss + partition + crash-with-wipe plan:
    the cluster still converges and the enforced invariant still ends
    at zero (origin rows are exempt by design, so the wipe cannot
    page)."""
    cfg = dataclasses.replace(_cfg("lab-ordered"), n_delay_slots=4)
    meta = uniform_payloads(cfg, inject_every=1)
    plan = FaultPlan(
        n_nodes=cfg.n_nodes, seed=7,
        events=(
            FaultEvent("loss", 0, 12, p=0.3),
            FaultEvent("partition", 2, 8, src="0:8", dst="24:32",
                       symmetric=True),
            FaultEvent("crash", 6, 10, node=1, wipe=True),
        ),
    )
    fplan = compile_plan(plan, cfg, Topology())
    final, m = run_fault_plan(
        new_sim(cfg, 7), meta, cfg, Topology(), fplan, 800
    )
    conv = np.asarray(m.converged_at)
    alive = np.asarray(final.alive)
    assert ((conv >= 0) | (alive != ALIVE)).all()
    assert int(m.order_violations) == 0


def test_order_violation_count_counts_the_gap():
    """Unit form: a node holding v2 without v1 complete is exactly one
    violating (node, origin, version) triple; the origin row is
    exempt."""
    from corrosion_tpu.sim.invariants import order_violation_count
    from corrosion_tpu.sim.state import (
        complete_versions,
        touched_versions,
    )

    cfg = SimConfig(n_nodes=4, n_payloads=4, ordering="fifo-unchecked")
    meta = uniform_payloads(cfg, inject_every=1)
    state = new_sim(cfg, 0)
    have = state.have
    origin = int(np.asarray(meta.actor)[1])
    holder = (origin + 1) % cfg.n_nodes
    have = have.at[holder, 1].set(1)  # v2 without v1
    have = have.at[origin, 1].set(1)  # origin row: exempt
    touched = touched_versions(have, cfg)
    comp = complete_versions(have, cfg)
    assert int(order_violation_count(touched, comp, meta, cfg)) == 1

    # multi-chunk versions count ONE triple, not chunks_per_version of
    # them (the grid-domain counting contract): one chunk of v2 held
    # while v1 is incomplete is still exactly one violation
    cfg2 = SimConfig(
        n_nodes=4, n_payloads=8, chunks_per_version=2,
        ordering="fifo-unchecked",
    )
    meta2 = uniform_payloads(cfg2, inject_every=1)
    have2 = new_sim(cfg2, 0).have
    origin2 = int(np.asarray(meta2.actor)[0])
    holder2 = (origin2 + 1) % cfg2.n_nodes
    have2 = have2.at[holder2, 2].set(1)  # first chunk of v2, no v1
    assert int(order_violation_count(
        touched_versions(have2, cfg2),
        complete_versions(have2, cfg2),
        meta2, cfg2,
    )) == 1


# -- 4. campaign-spec resolution ---------------------------------------------


def test_spec_proto_family_resolution_and_overlay():
    from corrosion_tpu.campaign.spec import CampaignSpec

    spec = CampaignSpec(
        name="t",
        scenario={
            "n_nodes": 48, "n_payloads": 16,
            # the explicit key must OVERLAY the family's bundle
            "fanout_decay_rounds": 3,
        },
        grid={"proto_family": ["fanout-decay", "swarm-aggressive"]},
    )
    cells = spec.cells()
    cfgs = {c["proto_family"]: spec.sim_config(c) for c in cells}
    decay = cfgs["fanout-decay"]
    assert decay.fanout_schedule == "decay"
    assert decay.fanout_decay_rounds == 3  # explicit key wins
    swarm = cfgs["swarm-aggressive"]
    assert swarm.sync_cadence == "eager"
    assert swarm.fanout_schedule == "flat"
    with pytest.raises(KeyError, match="unknown protocol family"):
        spec.sim_config({"proto_family": "nope"})


def test_protocol_frontier_builtin_shape():
    from corrosion_tpu.campaign.spec import BUILTIN_SPECS

    spec = BUILTIN_SPECS["protocol-frontier"]()
    cells = spec.cells()
    assert len(cells) == 8  # 4 protocol families × 2 topologies
    protos = {c["proto_family"] for c in cells}
    assert protos == {
        "baseline", "swarm-aggressive", "push-pull", "lab-ordered",
    }
    assert {c["topo_family"] for c in cells} == {"wan-3x2", "flat-lossy"}
    assert spec.measure_wire(cells[0])
    # every cell builds a legal config/topology pair
    for c in cells:
        cfg = spec.sim_config(c)
        assert cfg.n_nodes == 96
        spec.topo(c)
