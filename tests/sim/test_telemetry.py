"""Flight-recorder telemetry (ISSUE 5): the RoundTrace contract.

Pins the four guarantees sim/telemetry.py makes:

1. **telemetry=None compiles out** — a telemetry-off run is byte-
   identical to the pre-telemetry build (state, metrics, AND the
   replay digests of the checked fault driver), and a telemetry-ON run
   perturbs nothing (same state/metrics bits, trace riding alongside);
2. **dense == packed traces** — every channel bit-equal under the same
   FaultPlan (integer channels count the same sets; byte channels fold
   identically-shaped per-edge totals);
3. **vmapped ensemble lane slices == solo runs** — the trace is
   allocated inside the jitted run, so vmap stacks per-lane buffers;
4. host-side exports (summary / JSONL / Registry) are deterministic.
"""

from __future__ import annotations

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# import the packed module before any tracing happens: its module-level
# u32 constants must not be created inside a jit trace (the lazy
# `from .packed import ...` in run_to_convergence would otherwise
# execute the module mid-trace)
import corrosion_tpu.sim.packed  # noqa: F401
from corrosion_tpu.faults import FaultEvent, FaultPlan
from corrosion_tpu.sim.faults import compile_plan, run_fault_plan
from corrosion_tpu.sim.round import new_sim, run_to_convergence
from corrosion_tpu.sim.state import ALIVE, DOWN, SimConfig, uniform_payloads
from corrosion_tpu.sim.telemetry import (
    RoundTrace,
    coverage_latency_rounds,
    trace_summary,
    trace_to_registry,
    write_flight_jsonl,
)
from corrosion_tpu.sim.topology import Topology


def _cfg(**kw):
    kw.setdefault("n_payloads", 64)  # 16 versions x 2 writers x 2 chunks
    kw.setdefault("n_writers", 2)
    kw.setdefault("chunks_per_version", 2)
    kw.setdefault("fanout", 2)
    kw.setdefault("sync_interval_rounds", 3)
    kw.setdefault("swim_partial_view", True)
    kw.setdefault("member_slots", 8)
    kw.setdefault("rate_limit_bytes_round", None)
    kw.setdefault("sync_budget_bytes", None)
    kw.setdefault("packed_min_cells", 0)
    kw.setdefault("n_delay_slots", 4)
    return SimConfig.wan_tuned(32, **kw)


_PLAN = FaultPlan(
    n_nodes=32, seed=5,
    events=(
        FaultEvent("loss", 0, 12, p=0.3),
        FaultEvent("partition", 2, 10, src="0:16", dst="16:32"),
        FaultEvent("delay", 2, 10, src="0:8", dst="*", delay_rounds=1),
        FaultEvent("jitter", 2, 10, src="0:8", dst="*", delay_rounds=1),
        FaultEvent("crash", 6, 14, node=2, wipe=True),
    ),
)


def _assert_traces_equal(a, b, tag=""):
    for name in RoundTrace._fields:
        x = np.asarray(getattr(a, name))
        y = np.asarray(getattr(b, name))
        assert (x == y).all(), (
            f"{tag}{name}: {int((x != y).sum())} mismatches, "
            f"first at {np.argwhere(x != y)[:5].tolist()}"
        )


def test_telemetry_off_is_byte_identical():
    """The acceptance gate: telemetry=None (the default) produces bit-
    identical results, and telemetry=True observes without perturbing —
    faultless and fault-plan entries both."""
    cfg = _cfg()
    topo = Topology()
    meta = uniform_payloads(cfg, inject_every=1)

    f0, m0 = run_to_convergence(new_sim(cfg, 3), meta, cfg, topo, 200)
    f1, m1, _tr = run_to_convergence(
        new_sim(cfg, 3), meta, cfg, topo, 200, telemetry=True
    )
    assert int(f0.t) == int(f1.t)
    for name in ("have", "relay_left", "heads", "alive", "key"):
        assert (
            np.asarray(getattr(f0, name)) == np.asarray(getattr(f1, name))
        ).all(), name
    assert (
        np.asarray(m0.converged_at) == np.asarray(m1.converged_at)
    ).all()
    assert (np.asarray(m0.coverage_at) == np.asarray(m1.coverage_at)).all()

    fplan = compile_plan(_PLAN, cfg, topo)
    g0, n0 = run_fault_plan(new_sim(cfg, 7), meta, cfg, topo, fplan, 300)
    g1, n1, _ftr = run_fault_plan(
        new_sim(cfg, 7), meta, cfg, topo, fplan, 300, telemetry=True
    )
    assert int(g0.t) == int(g1.t)
    assert (np.asarray(g0.have) == np.asarray(g1.have)).all()
    assert (
        np.asarray(n0.converged_at) == np.asarray(n1.converged_at)
    ).all()


@pytest.mark.chaos
def test_dense_and_packed_traces_bit_equal_under_faults():
    """ISSUE 5 satellite: dense-vs-packed RoundTrace equality under the
    same FaultPlan, through the public dispatching entry."""
    cfg = _cfg()
    cfgd = dataclasses.replace(cfg, allow_packed=False)
    topo = Topology()
    meta = uniform_payloads(cfg, inject_every=1)
    from corrosion_tpu.sim.packed import packed_supported

    assert packed_supported(cfg, topo)
    assert not packed_supported(cfgd, topo)

    fp, mp, tr_p = run_fault_plan(
        new_sim(cfg, 7), meta, cfg, topo, compile_plan(_PLAN, cfg, topo),
        300, telemetry=True,
    )
    fd, md, tr_d = run_fault_plan(
        new_sim(cfgd, 7), meta, cfgd, topo,
        compile_plan(_PLAN, cfgd, topo), 300, telemetry=True,
    )
    assert int(fp.t) == int(fd.t)
    _assert_traces_equal(tr_p, tr_d, "fault ")
    # the fault channels actually fired (a trivially-zero trace would
    # pass equality while recording nothing)
    r = int(fp.t)
    t = {f: np.asarray(getattr(tr_p, f))[:r] for f in RoundTrace._fields}
    assert t["bcast_dropped"].sum() > 0
    assert t["bcast_cut"].sum() > 0
    assert t["crashes"].sum() > 0
    assert t["wipes"].sum() == 1
    assert t["bcast_bytes"].sum() > 0
    assert t["sync_sessions"].sum() > 0


def test_dense_and_packed_traces_bit_equal_faultless():
    cfg = _cfg()
    cfgd = dataclasses.replace(cfg, allow_packed=False)
    topo = Topology()
    meta = uniform_payloads(cfg, inject_every=1)

    fp, mp, tr_p = run_to_convergence(
        new_sim(cfg, 3), meta, cfg, topo, 200, telemetry=True
    )
    fd, md, tr_d = run_to_convergence(
        new_sim(cfgd, 3), meta, cfgd, topo, 200, telemetry=True
    )
    assert int(fp.t) == int(fd.t)
    _assert_traces_equal(tr_p, tr_d, "faultless ")


@pytest.mark.campaign
def test_vmapped_ensemble_lane_traces_match_solo_runs():
    """ISSUE 5 satellite: lane k of a vmapped telemetry ensemble slices
    to exactly the solo run's trace (the trace is allocated inside the
    jitted run, so vmap batches the buffers per lane)."""
    from corrosion_tpu.campaign.ensemble import run_seed_ensemble

    cfg = _cfg()
    topo = Topology()
    meta = uniform_payloads(cfg, inject_every=1)
    seeds = (0, 1, 2)

    finals, metrics, traces = run_seed_ensemble(
        _PLAN, cfg, topo, meta, seeds, max_rounds=300, telemetry=True
    )
    for k, s in enumerate(seeds):
        fp = compile_plan(
            dataclasses.replace(_PLAN, seed=int(s)), cfg, topo
        )
        solo, _m, solo_trace = run_fault_plan(
            new_sim(cfg, int(s)), meta, cfg, topo, fp, 300, telemetry=True
        )
        lane = jax.tree.map(lambda x: x[k], traces)
        _assert_traces_equal(lane, solo_trace, f"lane{k} ")
        assert int(finals.t[k]) == int(solo.t)


def test_trace_channels_are_consistent():
    """Cross-channel sanity on a small faultless run: coverage is the
    cumulative delivered count per payload (no crashes), the final
    coverage row is full, and the latency percentiles derive from it."""
    cfg = _cfg()
    topo = Topology()
    meta = uniform_payloads(cfg, inject_every=1)
    final, metrics, trace = run_to_convergence(
        new_sim(cfg, 11), meta, cfg, topo, 200, telemetry=True
    )
    r = int(final.t)
    cov = np.asarray(trace.coverage)[:r]
    dlv = np.asarray(trace.delivered)[:r]
    up = np.asarray(trace.up_nodes)[:r]
    # no deaths in this scenario: coverage == running sum of delivered
    assert (up == cfg.n_nodes).all()
    assert (cov == np.cumsum(dlv, axis=0)).all()
    # converged ⇒ the last row is full coverage
    assert (cov[-1] == cfg.n_nodes).all()
    lat = coverage_latency_rounds(trace, r)
    assert (lat >= 0).all()
    # full coverage can't precede the payload's injection round
    assert (lat >= np.asarray(meta.round)).all()
    summ = trace_summary(trace, r, cfg)
    assert summ["rounds"] == r
    assert summ["coverage_latency_rounds"]["uncovered_payloads"] == 0
    assert summ["wire_bytes"]["broadcast"] > 0


def test_flight_jsonl_roundtrip_and_digest_stability(tmp_path):
    """The JSONL artifact: header + one row per round, deterministic
    across replays (same digest, same bytes)."""
    cfg = _cfg()
    topo = Topology()
    meta = uniform_payloads(cfg, inject_every=1)

    paths = []
    digests = []
    for i in range(2):
        final, _m, trace = run_to_convergence(
            new_sim(cfg, 13), meta, cfg, topo, 200, telemetry=True
        )
        p = tmp_path / f"run{i}.jsonl"
        write_flight_jsonl(
            str(p), trace, int(final.t), cfg, header={"seed": 13}
        )
        paths.append(p)
        digests.append(trace_summary(trace, int(final.t), cfg))
    assert digests[0] == digests[1]
    assert paths[0].read_bytes() == paths[1].read_bytes()

    with open(paths[0]) as f:
        head = json.loads(f.readline())
        rows = [json.loads(line) for line in f]
    assert head["kind"] == "flight_recorder"
    assert head["seed"] == 13
    assert head["rounds"] == len(rows)
    assert rows[0]["t"] == 0 and rows[-1]["t"] == head["rounds"] - 1
    assert rows[-1]["coverage_frac"] == 1.0
    # P = 64 ≤ 256: per-payload coverage vectors ride along
    assert len(rows[0]["coverage"]) == cfg.n_payloads


def test_trace_to_registry_families():
    """trace→Registry bridge: sim_* families land on a Registry and
    render in the Prometheus exposition MetricsServer scrapes."""
    from corrosion_tpu.metrics import Registry

    cfg = _cfg()
    topo = Topology()
    meta = uniform_payloads(cfg, inject_every=1)
    final, _m, trace = run_to_convergence(
        new_sim(cfg, 3), meta, cfg, topo, 200, telemetry=True
    )
    reg = Registry()
    trace_to_registry(trace, int(final.t), cfg, registry=reg, run="smoke")
    out = reg.render()
    for family in (
        "sim_rounds_total", "sim_wire_bytes_total", "sim_wire_frames_total",
        "sim_sync_sessions_total", "sim_coverage_latency_rounds_bucket",
        "sim_fault_dropped_frames_total",
    ):
        assert family in out, family
    assert 'path="broadcast"' in out and 'path="sync"' in out
    assert 'run="smoke"' in out
    assert f"sim_rounds_total{{run=\"smoke\"}} {int(final.t)}" in out


def test_membership_detect_driver_full_and_partial():
    """`run_membership_detect` (the engine-routed configs #2/#2b loop):
    detection fires, the trace's swim_down channel is monotone up to
    detection, and the full-view/partial-view predicates both compile."""
    from corrosion_tpu.sim.telemetry import run_membership_detect

    topo = Topology()
    for cfg in (
        SimConfig.wan_tuned(24, n_payloads=1, swim_full_view=True),
        SimConfig.wan_tuned(
            96, n_payloads=1, swim_partial_view=True, member_slots=16,
            probe_period_rounds=1,
        ),
    ):
        meta = uniform_payloads(cfg)
        state = new_sim(cfg, 0)
        kill = jnp.arange(cfg.n_nodes) % 3 == 0
        state = state._replace(
            alive=jnp.where(kill, jnp.uint8(DOWN), jnp.uint8(ALIVE))
        )
        s, m, dr, trace = run_membership_detect(
            state, meta, cfg, topo, 600, telemetry=True
        )
        dr = int(dr)
        assert dr >= 0, f"no detection at n={cfg.n_nodes}"
        downs = np.asarray(trace.swim_down)[:dr]
        assert downs[-1] > 0
        # killed nodes never rejoin in this scenario, so the DOWN-belief
        # total must grow monotonically up to the detection round
        assert (np.diff(downs.astype(np.int64)) >= 0).all()
        # the driver's early exit matches the recorded round count
        assert int(s.t) == dr


def test_perf_microbench_supports_telemetry():
    """measure_per_round(telemetry=True) — the flight-recorder round
    body is microbenchable — runs on both the plain and fault bodies."""
    from corrosion_tpu.sim.perf import measure_per_round

    cfg = _cfg()
    meta = uniform_payloads(cfg, inject_every=1)
    fplan = compile_plan(_PLAN, cfg, Topology())
    for fp in (None, fplan):
        pr = measure_per_round(
            cfg, meta, seed=1, k_rounds=2, reps=1, fplan=fp,
            telemetry=True,
        )
        assert pr > 0


def test_perf_overhead_pair_interleaved():
    """measure_overhead_pair — the defensible form of the ≤10% overhead
    ratio (interleaved A/B, per-variant min) — returns a positive
    (plain, telemetry) pair on the fault body."""
    from corrosion_tpu.sim.perf import measure_overhead_pair

    cfg = _cfg()
    meta = uniform_payloads(cfg, inject_every=1)
    fplan = compile_plan(_PLAN, cfg, Topology())
    pr_plain, pr_tel = measure_overhead_pair(
        cfg, meta, seed=1, k_rounds=2, reps=1, fplan=fplan
    )
    assert pr_plain > 0 and pr_tel > 0


def test_trace_every_decimation_samples_rows():
    """The decimated recorder (ISSUE 7 satellite): ``trace_every=k``
    allocates ceil(R/k)+1 rows (sampled rows + one scratch row the
    predicated non-sample writes land in), records exactly the rounds
    t ≡ 0 (mod k) with the SAME values the exact recorder writes, and
    never changes the run itself."""
    from corrosion_tpu.sim.telemetry import (
        trace_rows,
        trace_rows_for,
        trace_summary,
    )

    cfg = _cfg()
    cfg3 = dataclasses.replace(cfg, trace_every=3)
    meta = uniform_payloads(cfg, inject_every=1)
    topo = Topology()
    full = run_to_convergence(
        new_sim(cfg, 3), meta, cfg, topo, 60, telemetry=True
    )
    dec = run_to_convergence(
        new_sim(cfg3, 3), meta, cfg3, topo, 60, telemetry=True
    )
    # the run itself is untouched: trace_every only changes the recorder
    for x, y in zip(jax.tree.leaves(full[0]), jax.tree.leaves(dec[0])):
        assert (np.asarray(x) == np.asarray(y)).all()
    rounds = int(full[0].t)
    sampled = trace_rows_for(rounds, 3)
    assert sampled == -(-rounds // 3)
    # buffer allocation: sampled rows + 1 scratch
    assert dec[2].up_nodes.shape[0] == trace_rows_for(60, 3) + 1
    # every sampled row equals the exact recorder's row at t = 3·i
    for name in RoundTrace._fields:
        x = np.asarray(getattr(full[2], name))[:rounds:3]
        y = np.asarray(getattr(dec[2], name))[:sampled]
        assert (x == y).all(), name
    # exporters label rows with the REAL round they recorded
    rows = trace_rows(dec[2], rounds, cfg3)
    assert [r["t"] for r in rows] == [3 * i for i in range(sampled)]
    # the summary self-describes only when the knob is on
    s_full = trace_summary(full[2], rounds, cfg)
    s_dec = trace_summary(dec[2], rounds, cfg3)
    assert "trace_every" not in s_full
    assert s_dec["trace_every"] == 3


def test_trace_every_coverage_latency_upper_bound():
    """Decimated coverage latency reports the first SAMPLED round —
    an upper bound within one stride of the exact latency."""
    from corrosion_tpu.sim.telemetry import coverage_latency_rounds

    cfg = _cfg()
    cfg2 = dataclasses.replace(cfg, trace_every=2)
    meta = uniform_payloads(cfg, inject_every=1)
    full = run_to_convergence(
        new_sim(cfg, 5), meta, cfg, Topology(), 60, telemetry=True
    )
    dec = run_to_convergence(
        new_sim(cfg2, 5), meta, cfg2, Topology(), 60, telemetry=True
    )
    rounds = int(full[0].t)
    exact = coverage_latency_rounds(full[2], rounds)
    coarse = coverage_latency_rounds(dec[2], rounds, every=2)
    covered = (exact >= 0) & (coarse >= 0)
    assert (coarse[covered] >= exact[covered]).all()
    assert (coarse[covered] - exact[covered] < 2).all()


def test_trace_every_validates():
    with pytest.raises(ValueError, match="trace_every"):
        _cfg(trace_every=0)
