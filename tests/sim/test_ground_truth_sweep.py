"""Ground-truth calibration sweep (VERDICT r3 item 4): more paired
host/sim scenarios, tighter bands.

The round-3 calibration carried two scenarios with a ×2+2 band; this
file adds three more pairings — a LOSS SWEEP (0.2 / 0.7), PARTITION +
HEAL at 8 nodes, and MIXED CHUNKED WRITES — and holds every quantile
(p50/p90/p99 over seeds) to ×1.5 with a 1-round additive discretization
floor (one sim round is one broadcast flush tick; sub-tick timing is
unobservable on either tier, so a ±1 floor is honest, unlike the old
±2).

Alignment notes (why the tiers are comparable at all):
- host "rounds" are broadcast flush TICKS from agent-internal counters
  (`flush_tick`/`apply_tick`), never wall-clock (load-invariant);
- the sim's sync re-arm is uniform 1..interval rounds; the host tier's
  decorrelated jitter spans 0.05-0.3 s on a 0.02 s flush tick =
  2.5..15 ticks.  The sweep scenarios set `sync_interval_rounds=15` so
  match the host under-backlog cadence (reset-on-ingest holds it at the
  ~2.5-tick floor); the sim now grows its window on fruitless syncs
  exactly like the host (SimConfig.sync_backoff_max_rounds).
"""

import asyncio

import numpy as np
import pytest

from corrosion_tpu.agent.transport import LinkModel
from corrosion_tpu.sim.round import new_metrics, new_sim, round_step, run_to_convergence
from corrosion_tpu.sim.state import ALIVE, SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import Topology, regions
from corrosion_tpu.testing import Cluster

MULT = 1.5  # multiplicative band (VERDICT r3 item 4: x1.5, not x2+slack)
FLOOR = 1.0  # one flush tick of discretization


def _band_ok(h: float, s: float) -> bool:
    return s <= h * MULT + FLOOR and h <= s * MULT + FLOOR


def _assert_quantiles(host, sim, tag):
    host = np.asarray(host, float)
    sim = np.asarray(sim, float)
    lines = []
    ok = True
    for q in (50, 90, 99):
        h = float(np.percentile(host, q))
        s = float(np.percentile(sim, q))
        lines.append(f"p{q}: host={h:.1f} sim={s:.1f}")
        ok &= _band_ok(h, s)
    print(f"{tag}: " + ", ".join(lines))
    assert ok, f"{tag} out of x{MULT}+{FLOOR} band: " + ", ".join(lines)


# -- scenario A: loss sweep --------------------------------------------------

N_VERSIONS = 20


def _host_burst_rounds(seed: int, loss: float) -> float:
    """Returns max apply-tick delta, or NaN when the event loop was too
    starved for the tick clock to mean anything (see _skip_if_loaded)."""

    async def body():
        cluster = Cluster(3, link=LinkModel(loss=loss, seed=seed), use_swim=False)
        await cluster.start()
        try:
            writer = cluster.agents[0]
            receivers = cluster.agents[1:]
            t0 = {id(a): a.flush_tick for a in receivers}
            wall0 = asyncio.get_event_loop().time()
            for i in range(N_VERSIONS):
                writer.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, f"v{i}"))]
                )
            assert await cluster.wait_converged(60)
            wall = asyncio.get_event_loop().time() - wall0
            rounds = 0.0
            for a in receivers:
                ticks = [
                    t for (aid, _v), t in a.apply_tick.items()
                    if aid == writer.actor_id
                ]
                assert len(ticks) == N_VERSIONS
                rounds = max(rounds, float(max(ticks) - t0[id(a)]))
            # load guard: the tick clock is only load-invariant while
            # the loop keeps its 0.02 s flush cadence.  If wall time per
            # elapsed tick ran >2.5x nominal, a co-tenant (bench run,
            # parallel suite) starved the loop and the host measurement
            # is noise, not calibration signal.
            elapsed_ticks = max(
                float(max(a.flush_tick for a in receivers)
                      - min(t0.values())), 1.0
            )
            if wall / elapsed_ticks > 2.5 * 0.02:
                return float("nan")
            return rounds
        finally:
            await cluster.stop()

    return asyncio.run(body())


def _sim_burst_rounds(seed: int, loss: float, chunks: int = 1) -> float:
    cfg = SimConfig(
        n_nodes=3, n_payloads=N_VERSIONS * chunks, chunks_per_version=chunks,
        fanout=2, sync_interval_rounds=4,
    )
    meta = uniform_payloads(cfg, inject_every=0)
    final, metrics = run_to_convergence(
        new_sim(cfg, seed=seed), meta, cfg, Topology(loss=loss), 500
    )
    conv = np.asarray(metrics.converged_at)
    assert (conv >= 0).all()
    return float(conv.max())


@pytest.mark.parametrize("loss", [0.2, 0.7])
def test_loss_sweep_distribution(loss):
    seeds = range(12)
    host = [_host_burst_rounds(s, loss) for s in seeds]
    starved = sum(1 for h in host if h != h)  # NaN check
    if starved > len(host) // 3:
        pytest.skip(
            f"event loop starved in {starved}/{len(host)} host runs "
            "(co-tenant load); calibration needs a quiet machine"
        )
    host = [h for h in host if h == h]
    sim = [_sim_burst_rounds(s, loss) for s in seeds]
    _assert_quantiles(host, sim, f"loss={loss}")


# -- scenario B: partition + heal at 8 nodes ---------------------------------

N_PART = 8
PART_VERSIONS = 8  # per side


def _host_partition_heal_rounds(seed: int) -> float:
    """Partition an 8-node cluster in half, write on both sides, heal;
    measure flush ticks from heal until every node holds the OTHER
    side's writes."""

    async def body():
        cluster = Cluster(N_PART, link=LinkModel(seed=seed), use_swim=False)
        await cluster.start()
        try:
            addrs = [a.transport.addr for a in cluster.agents]
            half = N_PART // 2
            for a in addrs[:half]:
                for b in addrs[half:]:
                    cluster.net.partition(a, b)
            left, right = cluster.agents[:half], cluster.agents[half:]
            for i in range(PART_VERSIONS):
                left[i % half].exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)",
                      (i, f"L{i}"))]
                )
                right[i % half].exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)",
                      (1000 + i, f"R{i}"))]
                )
            # let in-partition dissemination settle
            await asyncio.sleep(0.3)
            cluster.net.heal()
            t0 = {id(a): a.flush_tick for a in cluster.agents}
            assert await cluster.wait_converged(90)
            rounds = 0.0
            for side, others in ((left, right), (right, left)):
                other_ids = {a.actor_id for a in others}
                for a in side:
                    ticks = [
                        t for (aid, _v), t in a.apply_tick.items()
                        if aid in other_ids
                    ]
                    assert ticks, "no cross-side applies recorded"
                    rounds = max(rounds, float(max(ticks) - t0[id(a)]))
            return rounds
        finally:
            await cluster.stop()

    return asyncio.run(body())


def _sim_partition_heal_rounds(seed: int) -> float:
    import jax.numpy as jnp

    cfg = SimConfig(
        n_nodes=N_PART, n_payloads=PART_VERSIONS * 2, n_writers=2,
        fanout=3, sync_interval_rounds=4,
    )
    # writers on opposite sides (uniform_payloads spreads actors; with 2
    # writers over 8 nodes they land at nodes 0 and 4 — one per half)
    meta = uniform_payloads(cfg, inject_every=0)
    topo = Topology()
    region = regions(cfg.n_nodes, topo.n_regions)
    state = new_sim(cfg, seed)
    group = (jnp.arange(N_PART) >= N_PART // 2).astype(jnp.int32)
    state = state._replace(group=group)
    metrics = new_metrics(cfg)
    # run partitioned until both sides hold their own writes (up to 60)
    for _ in range(60):
        state, metrics = round_step(state, metrics, meta, cfg, topo, region)
    heal_round = int(state.t)
    state = state._replace(group=jnp.zeros((N_PART,), jnp.int32))
    final, metrics = run_to_convergence(state, meta, cfg, topo, 1000)
    conv = np.asarray(metrics.converged_at)
    assert (conv >= 0).all()
    return float(conv.max() - heal_round)


def test_partition_heal_distribution():
    seeds = range(6)
    host = [_host_partition_heal_rounds(s) for s in seeds]
    sim = [_sim_partition_heal_rounds(s) for s in seeds]
    _assert_quantiles(host, sim, "partition-heal")


# -- scenario C: mixed chunked writes ----------------------------------------

CHUNK_VERSIONS = 8
ROW_BYTES = 20_000  # ~3 chunks per version at the 8 KiB cap
# loss 0.55: at 0.4 both tiers converge in ~3 rounds and the host's
# ±1-2 ticks of event-loop jitter dwarfs the multiplicative band;
# higher loss restores dynamic range (5-9 rounds) where x1.5 dominates


def _host_chunked_rounds(seed: int, loss: float = 0.55) -> float:
    async def body():
        cluster = Cluster(3, link=LinkModel(loss=loss, seed=seed), use_swim=False)
        await cluster.start()
        try:
            writer = cluster.agents[0]
            receivers = cluster.agents[1:]
            t0 = {id(a): a.flush_tick for a in receivers}
            blob = "x" * ROW_BYTES
            for i in range(CHUNK_VERSIONS):
                writer.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, blob))]
                )
            assert await cluster.wait_converged(90)
            rounds = 0.0
            for a in receivers:
                ticks = [
                    t for (aid, _v), t in a.apply_tick.items()
                    if aid == writer.actor_id
                ]
                assert len(ticks) == CHUNK_VERSIONS
                rounds = max(rounds, float(max(ticks) - t0[id(a)]))
            return rounds
        finally:
            await cluster.stop()

    return asyncio.run(body())


def test_chunked_writes_distribution():
    seeds = range(6)
    host = [_host_chunked_rounds(s) for s in seeds]
    # sim: 4-chunk versions, same loss, same burst (the fully-buffered
    # apply gate makes a version count only when every chunk landed)
    sim = [_sim_burst_chunked(s) for s in seeds]
    _assert_quantiles(host, sim, "chunked-writes")


def _sim_burst_chunked(seed: int, loss: float = 0.55) -> float:
    cfg = SimConfig(
        n_nodes=3, n_payloads=CHUNK_VERSIONS * 3, chunks_per_version=3,
        fanout=2, sync_interval_rounds=4,
    )
    meta = uniform_payloads(cfg, inject_every=0)
    final, metrics = run_to_convergence(
        new_sim(cfg, seed=seed), meta, cfg, Topology(loss=loss), 500
    )
    conv = np.asarray(metrics.converged_at)
    assert (conv >= 0).all()
    return float(conv.max())
