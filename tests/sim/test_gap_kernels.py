"""Kernel-vs-scalar-spec property tests for the gap/need algebra.

The device kernels (sim/gaps.py interval extraction, sim/sync.py
`edge_needs`) must transfer exactly the chunks the scalar spec
(`core.sync.compute_available_needs`, itself an exact port of reference
sync.rs:127-249 with its unit tests) would, on randomized two-node states —
the validation contract VERDICT r1 item 2 prescribes.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from corrosion_tpu.core.sync import compute_available_needs
from corrosion_tpu.core.types import ActorId, SyncState
from corrosion_tpu.sim.gaps import extract_gaps, gaps_to_mask
from corrosion_tpu.sim.round import new_sim
from corrosion_tpu.sim.state import (
    SimConfig,
    touched_versions,
    version_heads,
)
from corrosion_tpu.sim.sync import edge_needs


def _runs(mask_1d):
    """Maximal runs of True as inclusive (lo, hi) index pairs."""
    runs, start = [], None
    for i, m in enumerate(mask_1d):
        if m and start is None:
            start = i
        elif not m and start is not None:
            runs.append((start, i - 1))
            start = None
    if start is not None:
        runs.append((start, len(mask_1d) - 1))
    return runs


def _actor_id(a: int) -> ActorId:
    return ActorId(bytes([0xEE] * 15 + [a]))


def scalar_sync_state(have: np.ndarray, me: ActorId) -> SyncState:
    """Build the reference-shaped advertisement (generate_sync,
    sync.rs:284-333) from a chunk grid have[A, V, C]."""
    a_n, v_n, c_n = have.shape
    st = SyncState(actor_id=me)
    for a in range(a_n):
        aid = _actor_id(a)
        touched = have[a].any(axis=1)  # [V]
        if not touched.any():
            continue
        head = int(np.nonzero(touched)[0].max()) + 1  # 1-based
        st.heads[aid] = head
        # full-version gaps below the head
        need = [
            (lo + 1, hi + 1) for lo, hi in _runs(~touched[:head])
        ]
        if need:
            st.need[aid] = need
        # partial (seq-gap) versions
        for v in range(head):
            if touched[v] and not have[a, v].all():
                gaps = _runs(~have[a, v])
                st.partial_need.setdefault(aid, {})[v + 1] = gaps
    return st


def spec_transfer(have_i: np.ndarray, have_j: np.ndarray) -> set:
    """Chunks the scalar spec would move j→i: evaluate the need list, then
    serve each need from j's actual holdings (handle_need reads current +
    buffered rows, peer/mod.rs:371-790)."""
    me_i, me_j = ActorId(bytes([1] * 16)), ActorId(bytes([2] * 16))
    needs = compute_available_needs(
        scalar_sync_state(have_i, me_i), scalar_sync_state(have_j, me_j)
    )
    out = set()
    a_n, v_n, c_n = have_i.shape
    by_actor = {_actor_id(a): a for a in range(a_n)}
    for aid, entries in needs.items():
        a = by_actor[aid]
        for need in entries:
            if need.kind == "full":
                versions = range(need.versions[0], need.versions[1] + 1)
                chunk_ranges = [(0, c_n - 1)]
            else:
                versions = [need.version]
                chunk_ranges = need.seqs
            for v in versions:
                if v > v_n:
                    continue
                for slo, shi in chunk_ranges:
                    for c in range(slo, min(shi, c_n - 1) + 1):
                        if have_j[a, v - 1, c] and not have_i[a, v - 1, c]:
                            out.add((a, v, c))
    return out


def kernel_transfer(have_i, have_j, cfg: SimConfig) -> set:
    """Chunks the device kernel grants on the edge i←j (unlimited budget)."""
    state = new_sim(cfg, seed=0)
    have = jnp.zeros((2, cfg.n_payloads), jnp.uint8)
    grid_i = np.transpose(have_i, (1, 0, 2)).reshape(-1)  # (V,A,C) flat
    grid_j = np.transpose(have_j, (1, 0, 2)).reshape(-1)
    have = have.at[0].set(jnp.asarray(grid_i, jnp.uint8))
    have = have.at[1].set(jnp.asarray(grid_j, jnp.uint8))
    # refresh bookkeeping exactly the way round_step does
    touched = touched_versions(have, cfg)
    heads = version_heads(touched)
    gaps = extract_gaps(touched, heads, cfg)
    state = state._replace(
        have=have, heads=heads, gap_lo=gaps.lo, gap_hi=gaps.hi
    )
    grant = np.asarray(
        edge_needs(state, cfg, jnp.array([0]), jnp.array([1]))
    )[0]
    out = set()
    a_n, c_n = cfg.n_writers, cfg.chunks_per_version
    for p in np.nonzero(grant)[0]:
        v = int(p) // (a_n * c_n) + 1
        a = (int(p) % (a_n * c_n)) // c_n
        c = int(p) % c_n
        out.add((a, v, c))
    return out


@pytest.mark.parametrize("trial", range(40))
def test_kernel_matches_scalar_spec(trial):
    """Randomized two-node traces: identical effective transfers."""
    rng = np.random.default_rng(trial)
    a_n = int(rng.integers(1, 4))
    v_n = int(rng.integers(1, 13))
    c_n = int(rng.integers(1, 5))
    density = rng.uniform(0.1, 0.9)
    have_i = rng.random((a_n, v_n, c_n)) < density
    have_j = rng.random((a_n, v_n, c_n)) < rng.uniform(0.1, 0.9)
    cfg = SimConfig(
        n_nodes=2,
        n_payloads=a_n * v_n * c_n,
        n_writers=a_n,
        chunks_per_version=c_n,
        gap_slots=16,  # ≥ max runs at V ≤ 12: no overflow clamping
    )
    spec = spec_transfer(have_i, have_j)
    kern = kernel_transfer(have_i, have_j, cfg)
    assert kern == spec, (
        f"trial {trial}: kernel-only={sorted(kern - spec)[:5]} "
        f"spec-only={sorted(spec - kern)[:5]}"
    )


def test_gap_extraction_matches_bookkeeping_runs():
    """extract_gaps reproduces the scalar run decomposition, and the
    K-overflow clamp merges the tail conservatively."""
    rng = np.random.default_rng(7)
    touched = rng.random((5, 2, 20)) < 0.5
    touched_j = jnp.asarray(touched)
    heads = version_heads(touched_j)
    cfg = SimConfig(
        n_nodes=5, n_payloads=40, n_writers=2, chunks_per_version=1,
        gap_slots=3,
    )
    gaps = extract_gaps(touched_j, heads, cfg)
    lo, hi = np.asarray(gaps.lo), np.asarray(gaps.hi)
    for n in range(5):
        for a in range(2):
            t = touched[n, a]
            if not t.any():
                assert (lo[n, a] == 0).all()
                continue
            head = int(np.nonzero(t)[0].max()) + 1
            runs = [(l + 1, h + 1) for l, h in _runs(~t[:head])]
            got = [
                (int(l), int(h))
                for l, h in zip(lo[n, a], hi[n, a])
                if l > 0
            ]
            if len(runs) <= 3:
                assert got == runs, (n, a, got, runs)
                assert not bool(gaps.overflow[n, a])
            else:
                # clamped: first K-1 exact, last slot covers the tail
                assert got[:2] == runs[:2]
                assert got[2][0] == runs[2][0]
                assert got[2][1] == runs[-1][1]
                assert bool(gaps.overflow[n, a])


def test_gaps_to_mask_roundtrip():
    lo = jnp.array([[1, 5, 0], [2, 0, 0]], jnp.int32)
    hi = jnp.array([[2, 6, 0], [2, 0, 0]], jnp.int32)
    mask = np.asarray(gaps_to_mask(lo, hi, 8))
    assert mask[0].tolist() == [True, True, False, False, True, True, False, False]
    assert mask[1].tolist() == [False, True, False, False, False, False, False, False]
