"""Partial-view SWIM kernel tests (sim/pswim.py): detection, rejoin,
coupled dissemination, and partition/heal at the O(N·M) scale tier."""

import jax.numpy as jnp
import numpy as np

from corrosion_tpu.sim.round import new_metrics, new_sim, round_step, run_to_convergence
from corrosion_tpu.sim.state import ALIVE, DOWN, SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import Topology, regions


def drive(cfg, state, meta, rounds, topo=Topology()):
    region = regions(cfg.n_nodes, topo.n_regions)
    metrics = new_metrics(cfg)
    for _ in range(rounds):
        state, metrics = round_step(state, metrics, meta, cfg, topo, region)
    return state, metrics


def watched_state(state, watcher_up, member_mask):
    """For every up watcher, the believed state of watched members in
    member_mask; returns (n_watched, n_down) counts."""
    pid = np.asarray(state.pid)
    pkey = np.asarray(state.pkey)
    watched = n_down = 0
    for n in np.nonzero(watcher_up)[0]:
        for b in range(pid.shape[1]):
            mid = pid[n, b]
            if mid >= 0 and member_mask[mid]:
                watched += 1
                if pkey[n, b] % 4 == DOWN:
                    n_down += 1
    return watched, n_down


def test_pswim_detects_dead_members():
    cfg = SimConfig.wan_tuned(
        256, n_payloads=1, swim_partial_view=True, member_slots=16,
        probe_period_rounds=1,
    )
    meta = uniform_payloads(cfg)
    state = new_sim(cfg, 2)
    dead = np.zeros(256, bool)
    dead[::5] = True  # a fifth die
    state = state._replace(
        alive=jnp.where(jnp.asarray(dead), jnp.uint8(DOWN), jnp.uint8(ALIVE))
    )
    state, _ = drive(cfg, state, meta, 120)
    up = ~dead
    watched, n_down = watched_state(state, up, dead)
    assert watched > 0
    assert n_down / watched > 0.9, f"detected only {n_down}/{watched}"
    # no false downs of live members
    w_live, d_live = watched_state(state, up, up)
    assert d_live / max(w_live, 1) < 0.02, f"false downs {d_live}/{w_live}"


def test_pswim_rejoin_after_false_down():
    """A live node falsely marked DOWN in every watcher's table must be
    rehabilitated via the announce/refute path."""
    cfg = SimConfig.wan_tuned(
        64, n_payloads=1, swim_partial_view=True, member_slots=16,
        announce_interval_rounds=4,
    )
    meta = uniform_payloads(cfg)
    state = new_sim(cfg, 3)
    victim = 7
    pid = np.asarray(state.pid)
    pkey = np.asarray(state.pkey)
    psince = np.asarray(state.psince)
    mask = pid == victim
    pkey = np.where(mask, (pkey // 4) * 4 + DOWN, pkey)
    psince = np.where(mask, 0, psince)  # down-since t=0 (GC age stamp)
    state = state._replace(
        pkey=jnp.asarray(pkey), psince=jnp.asarray(psince)
    )
    state, _ = drive(cfg, state, meta, 150)
    v_mask = np.zeros(64, bool)
    v_mask[victim] = True
    watched, n_down = watched_state(state, np.ones(64, bool), v_mask)
    assert watched > 0, "victim must be re-learned by some watchers"
    assert n_down <= watched * 0.2, \
        f"victim still believed down by {n_down}/{watched}"
    assert int(np.asarray(state.incarnation)[victim]) > 0, \
        "victim must have refuted (incarnation bump)"


def test_pswim_coupled_dissemination_converges():
    cfg = SimConfig.wan_tuned(
        128, n_payloads=16, n_writers=2, chunks_per_version=2,
        swim_partial_view=True, member_slots=16, sync_interval_rounds=6,
    )
    meta = uniform_payloads(cfg)
    state = new_sim(cfg, 4)
    final, metrics = run_to_convergence(state, meta, cfg, Topology(), 500)
    conv = np.asarray(metrics.converged_at)
    assert (conv >= 0).all(), f"{(conv < 0).sum()} nodes unconverged"


def test_pswim_partition_heal_recovers():
    """Partition → mutual DOWN in tables → heal → announce rejoin →
    post-heal payloads converge (the config #4 shape with real SWIM)."""
    cfg = SimConfig.wan_tuned(
        64, n_payloads=8, swim_partial_view=True, member_slots=16,
        suspect_timeout_rounds=4, sync_interval_rounds=6,
        probe_period_rounds=1,
    )
    meta = uniform_payloads(cfg, inject_every=0)
    meta = meta._replace(round=jnp.full_like(meta.round, 70))
    topo = Topology()
    state = new_sim(cfg, 5)
    group = (jnp.arange(64) >= 32).astype(jnp.int32)
    state = state._replace(group=group)
    state, metrics = drive(cfg, state, meta, 50, topo)
    # cross-partition watched entries must be largely DOWN by now
    a_side = np.arange(64) < 32
    watched, n_down = watched_state(state, a_side, ~a_side)
    assert watched > 0 and n_down / watched > 0.8, (n_down, watched)
    # heal and converge on payloads injected at round 70
    state = state._replace(group=jnp.zeros((64,), jnp.int32))
    region = regions(cfg.n_nodes, topo.n_regions)
    final, metrics = run_to_convergence(state, meta, cfg, topo, 800)
    conv = np.asarray(metrics.converged_at)
    assert (conv >= 0).all(), \
        f"post-heal wedge: {(conv < 0).sum()} nodes never converged"


def test_partial_churn_config_detects_all():
    """The partial-view churn benchmark (config #2 scale tier) reaches
    full detection with its on-device predicate at a CI-sized cluster."""
    from corrosion_tpu.sim.runner import config_swim_churn_partial

    m = config_swim_churn_partial(seed=1, n=512, max_rounds=800)
    assert m["converged"], m
    assert m["detected_fraction"] == 1.0


def test_merge_gather_pack_boundary_values():
    """The merge's 2xu32 packed gather must decode EXACTLY at the
    envelope bounds: pid = ID_CAP-1, pkey = INC_CLAMP*4+3, and the -1
    empty markers (the +1 offsets absorb them)."""
    import jax

    from corrosion_tpu.sim.pswim import ID_CAP, INC_CLAMP, _merge_entries
    from corrosion_tpu.sim.state import SimConfig

    cfg = SimConfig(
        n_nodes=4, n_payloads=32, swim_partial_view=True, member_slots=4
    )
    max_key = INC_CLAMP * 4 + 3
    pid = jnp.array(
        [[ID_CAP - 1, -1, 2, 3]] * 4, jnp.int32
    )
    pkey = jnp.array([[max_key, -1, 0, 1]] * 4, jnp.int32)
    psince = jnp.array([[-1, -1, 5, -1]] * 4, jnp.int32)

    # entry about id = ID_CAP-1 (bucket (ID_CAP-1) % 4 = 3 ... pick a
    # bucket-0 id: ID_CAP-1 % 4 == 3, so use dst bucket 3's occupant)
    b = (ID_CAP - 1) % 4
    assert b == 3
    # matching-id merge at the boundary: higher key must win
    e_dst = jnp.array([0], jnp.int32)
    e_id = jnp.array([ID_CAP - 1], jnp.int32)
    e_key = jnp.array([max_key], jnp.int32)
    e_ok = jnp.ones((1,), bool)
    # place the boundary occupant in bucket 3 with a LOWER key
    pid = pid.at[0, 3].set(ID_CAP - 1)
    pkey = pkey.at[0, 3].set(4)  # inc 1, ALIVE
    out_pid, out_pkey, _ = jax.jit(
        lambda p, k, s: _merge_entries(
            p, k, s, e_dst, e_id, e_key, e_ok, jnp.int32(9), cfg
        )
    )(pid, pkey, psince)
    # the match was detected (decode of pid at ID_CAP-1 was exact) and
    # precedence took the higher boundary key
    assert int(out_pid[0, 3]) == ID_CAP - 1
    assert int(out_pkey[0, 3]) == max_key
    # empty marker slots stayed empty (-1 decode exact)
    assert int(out_pid[0, 1]) == -1
