"""Fused one-pass traversals vs their legacy per-bit-loop oracles.

The ISSUE 19 seam (``CORRO_FUSED_ROUND``) keeps both forms of every
counter traversal in `sim/fused.py`; these tests hold them EXACTLY equal
on randomized inputs — the property every pinned digest in the tree
stands on.  All calls here are eager (unjitted), so the env toggle takes
effect per call with no cache clearing; the jitted end-to-end matrix
(telemetry on/off × fused on/off through the full proto round) lives in
tests/sim/test_proto.py.
"""

import types

import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.sim import fused
from corrosion_tpu.sim.gaps import _extract_gaps_dense


def _words(rng, shape):
    return jnp.asarray(
        rng.integers(0, 2**32, size=shape, dtype=np.uint32)
    )


def _toggle(monkeypatch, fn, *args):
    """(fused_result, legacy_result) of ``fn(*args)`` across the seam."""
    monkeypatch.setenv("CORRO_FUSED_ROUND", "1")
    hot = fn(*args)
    monkeypatch.setenv("CORRO_FUSED_ROUND", "0")
    cold = fn(*args)
    return hot, cold


def _assert_tree_equal(a, b):
    fa = a if isinstance(a, tuple) else (a,)
    fb = b if isinstance(b, tuple) else (b,)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_bit_counts_fused_equals_legacy_and_reference(monkeypatch):
    rng = np.random.default_rng(7)
    words = _words(rng, (37, 3))  # N=37 rows, W=3 → P=96
    hot, cold = _toggle(monkeypatch, fused.word_bit_counts, words, 96)
    _assert_tree_equal(hot, cold)
    # independent bit-level reference
    w = np.asarray(words)
    bits = (w[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
    ref = bits.sum(axis=0).reshape(96).astype(np.int32)
    np.testing.assert_array_equal(np.asarray(hot), ref)


def test_byte_totals_fused_equals_legacy_and_reference(monkeypatch):
    rng = np.random.default_rng(11)
    words = _words(rng, (9, 2))  # P=64
    nbytes = jnp.asarray(
        rng.integers(1, 70_000, size=64).astype(np.int32)
    )
    hot, cold = _toggle(monkeypatch, fused.word_byte_totals, words, nbytes)
    _assert_tree_equal(hot, cold)
    w = np.asarray(words)
    bits = (
        (w[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
    ).astype(np.int64)
    ref = (bits * np.asarray(nbytes).reshape(2, 32)).sum(
        axis=(1, 2)
    ).astype(np.int32)
    np.testing.assert_array_equal(np.asarray(hot), ref)


def test_word_send_stats_fused_equals_legacy(monkeypatch):
    rng = np.random.default_rng(13)
    sending = _words(rng, (23, 4))  # P=128
    nbytes = jnp.asarray(
        rng.integers(1, 9000, size=128).astype(np.int32)
    )
    hot, cold = _toggle(
        monkeypatch, fused.word_send_stats, sending, nbytes
    )
    _assert_tree_equal(hot, cold)
    # frames must equal the popcount reference
    ref_frames = np.array(
        [bin(int(x)).count("1") for x in np.asarray(sending).reshape(-1)]
    ).reshape(23, 4).sum(axis=1)
    np.testing.assert_array_equal(np.asarray(hot[0]), ref_frames)


def test_dense_send_stats_fused_equals_legacy(monkeypatch):
    rng = np.random.default_rng(17)
    sending = jnp.asarray(rng.random((19, 50)) < 0.4)
    nbytes = jnp.asarray(
        rng.integers(1, 9000, size=50).astype(np.int32)
    )
    hot, cold = _toggle(
        monkeypatch, fused.dense_send_stats, sending, nbytes
    )
    _assert_tree_equal(hot, cold)
    s = np.asarray(sending)
    np.testing.assert_array_equal(np.asarray(hot[0]), s.sum(axis=1))
    np.testing.assert_array_equal(
        np.asarray(hot[1]), (s * np.asarray(nbytes)[None, :]).sum(axis=1)
    )


@pytest.mark.parametrize("density", [0.15, 0.5, 0.9])
def test_extract_gaps_dense_fused_equals_legacy(monkeypatch, density):
    """The one-pass slot expansion (lo/hi/last-missing in two fused
    reductions) against the legacy 2K+1-reduction form, on patterns
    dense enough to overflow the K slots."""
    rng = np.random.default_rng(int(density * 100))
    n, a, v, k = 11, 3, 70, 4  # V > 32 forces the dense gaps path
    touched = jnp.asarray(rng.random((n, a, v)) < density)
    heads = jnp.asarray(
        (np.asarray(touched) * np.arange(1, v + 1)).max(axis=2)
    ).astype(jnp.int32)
    cfg = types.SimpleNamespace(gap_slots=k)
    hot, cold = _toggle(
        monkeypatch, _extract_gaps_dense, touched, heads, cfg
    )
    np.testing.assert_array_equal(np.asarray(hot.lo), np.asarray(cold.lo))
    np.testing.assert_array_equal(np.asarray(hot.hi), np.asarray(cold.hi))
    np.testing.assert_array_equal(
        np.asarray(hot.overflow), np.asarray(cold.overflow)
    )
    # at high density with tiny K the clamp must actually fire somewhere
    if density <= 0.5:
        assert bool(np.asarray(hot.overflow).any())
