"""Sharded flagship bench equivalence (VERDICT r2 item 4): the write-storm
scenario run node-axis-sharded over the 8-device virtual CPU mesh must
produce EXACTLY the single-device result — same convergence round, same
per-node converged_at, same per-payload coverage — because sharding only
partitions the math, it never changes it.

This is the bench path itself (`run_scenario(..., mesh=...)` as called by
bench_child.py when `len(jax.devices()) > 1`), at the 100k storm's exact
payload structure (512 payloads = 8 versions × 16 writers × 4 chunks,
partial-view SWIM, member tables) with the node count scaled to CPU."""

import jax
import numpy as np

from corrosion_tpu.parallel.mesh import make_mesh
from corrosion_tpu.sim.runner import _write_storm, run_scenario


def _run(mesh, **cfg_replace):
    import dataclasses

    cfg, meta = _write_storm(2048, 512)
    if cfg_replace:
        cfg = dataclasses.replace(cfg, **cfg_replace)
    return run_scenario(cfg, meta, seed=5, max_rounds=600, mesh=mesh)


def _assert_sharded_matches_single(single, sharded):
    assert sharded["n_devices"] == 8
    assert single["converged"] and sharded["converged"]
    assert single["rounds"] == sharded["rounds"]
    for k in (
        "p50_payload_latency_rounds",
        "p99_payload_latency_rounds",
        "p99_node_convergence_round",
        "unconverged_nodes",
    ):
        assert single[k] == sharded[k], (k, single[k], sharded[k])


def test_sharded_storm_matches_single_device_exactly():
    assert len(jax.devices()) == 8, "conftest must provide the virtual mesh"
    single = _run(None)
    sharded = _run(make_mesh())
    _assert_sharded_matches_single(single, sharded)


def test_verified_storm_runs_on_mesh():
    """config_write_storm_verified (the bench_child entry) end-to-end on
    the mesh: microbench + sanity verdict machinery must work sharded."""
    from corrosion_tpu.sim.runner import config_write_storm_verified

    m = config_write_storm_verified(
        seed=2, n_nodes=1024, n_payloads=512, microbench_rounds=4,
        mesh=make_mesh(),
    )
    assert m["converged"]
    assert m["n_devices"] == 8
    assert m["sanity"]["verdict"] in (
        "ok", "overhead-flagged", "async-artifact-corrected"
    )


def test_sharded_packed_matches_single_device_exactly():
    """The PACKED convergence loop (what the headline bench dispatches
    to at storm scale) under GSPMD: node-axis-sharded over the 8-device
    mesh must equal the single-device run bit-for-bit, exactly like the
    dense loop above.  The size gate is forced open so the tiny CPU
    shape rides the packed path."""
    assert len(jax.devices()) == 8, "conftest must provide the virtual mesh"
    single = _run(None, packed_min_cells=0)
    sharded = _run(make_mesh(), packed_min_cells=0)
    assert single["round_path"] == sharded["round_path"] == "packed"
    _assert_sharded_matches_single(single, sharded)
