"""Simulator kernel tests (8-device CPU mesh via conftest):
broadcast dissemination, sync gap-filling, SWIM detection/refutation,
partition/heal, determinism, and sharded execution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from corrosion_tpu.sim.round import new_metrics, new_sim, round_step, run_to_convergence
from corrosion_tpu.sim.state import ALIVE, DOWN, SUSPECT, SimConfig, uniform_payloads
from corrosion_tpu.sim.topology import Topology, regions


def run(cfg, meta, topo=Topology(), seed=0, max_rounds=500, mutate=None):
    state = new_sim(cfg, seed)
    if mutate:
        state = mutate(state)
    return run_to_convergence(state, meta, cfg, topo, max_rounds)


def test_broadcast_only_full_coverage():
    """Pure epidemic broadcast (sync effectively off) reaches all nodes."""
    cfg = SimConfig(n_nodes=64, n_payloads=16, fanout=3,
                    sync_interval_rounds=10_000)
    meta = uniform_payloads(cfg)
    final, metrics = run(cfg, meta)
    assert bool((np.asarray(metrics.converged_at) >= 0).all())
    assert np.asarray(final.have).min() == 1


def test_sync_fills_what_broadcast_drops():
    """With heavy loss, broadcast alone stalls; anti-entropy converges."""
    cfg = SimConfig(n_nodes=64, n_payloads=16, fanout=2, max_transmissions=2,
                    sync_interval_rounds=4)
    meta = uniform_payloads(cfg)
    topo = Topology(loss=0.6)
    final, metrics = run(cfg, meta, topo=topo, max_rounds=800)
    assert bool((np.asarray(metrics.converged_at) >= 0).all()), \
        f"unconverged: {(np.asarray(metrics.converged_at) < 0).sum()}"


def test_down_nodes_excluded_from_convergence():
    cfg = SimConfig(n_nodes=32, n_payloads=8)
    meta = uniform_payloads(cfg)  # writer = node 0

    def kill_some(state):  # kill non-writers 8..15
        alive = state.alive.at[8:16].set(DOWN)
        return state._replace(alive=alive)

    final, metrics = run(cfg, meta, mutate=kill_some)
    conv = np.asarray(metrics.converged_at)
    assert (conv[:8] >= 0).all() and (conv[16:] >= 0).all()
    assert (np.asarray(final.have)[8:16] == 0).all()  # the dead received nothing


def test_dead_writer_payloads_never_activate():
    """Commits from an origin that was down at inject time don't exist and
    must not block cluster convergence."""
    cfg = SimConfig(n_nodes=16, n_payloads=4)
    meta = uniform_payloads(cfg)

    def kill_writer(state):
        return state._replace(alive=state.alive.at[0].set(DOWN))

    final, metrics = run(cfg, meta, mutate=kill_writer)
    assert np.asarray(final.injected).max() == 0
    assert int(final.t) < 500  # converged trivially, didn't spin to max


def test_partition_blocks_then_heal_converges():
    cfg = SimConfig(n_nodes=64, n_payloads=8, sync_interval_rounds=4)
    meta = uniform_payloads(cfg)  # writer is node 0 (group 0)
    topo = Topology()
    region = regions(cfg.n_nodes, 1)

    state = new_sim(cfg, 0)
    group = (jnp.arange(64) >= 32).astype(jnp.int32)
    state = state._replace(group=group)
    metrics = new_metrics(cfg)
    for _ in range(60):
        state, metrics = round_step(state, metrics, meta, cfg, topo, region)
    have = np.asarray(state.have)
    assert have[:32].min() == 1, "writer's side must converge during partition"
    assert have[32:].max() == 0, "other side must see nothing while cut"
    # heal
    state = state._replace(group=jnp.zeros((64,), jnp.int32))
    final, metrics = run_to_convergence(state, meta, cfg, topo, 500)
    assert bool((np.asarray(metrics.converged_at) >= 0).all())


def test_swim_detects_dead_nodes():
    cfg = SimConfig(n_nodes=48, n_payloads=1, swim_full_view=True)
    meta = uniform_payloads(cfg)
    topo = Topology()
    region = regions(cfg.n_nodes, 1)
    state = new_sim(cfg, 3)
    state = state._replace(alive=state.alive.at[::4].set(DOWN))
    metrics = new_metrics(cfg)
    for _ in range(120):
        state, metrics = round_step(state, metrics, meta, cfg, topo, region)
    view = np.asarray(state.view)
    up = np.asarray(state.alive) == ALIVE
    dead = ~up
    assert (view[np.ix_(up, dead)] == DOWN).all(), "survivors must detect all dead"
    assert (view[np.ix_(up, up)] != DOWN).all(), "no false-positive downs"


def test_swim_refutation_keeps_lossy_cluster_alive():
    """Heavy loss causes false suspicion; refutation (incarnation bump) must
    prevent live nodes from being permanently marked down."""
    cfg = SimConfig(n_nodes=32, n_payloads=1, swim_full_view=True,
                    suspect_timeout_rounds=12)
    meta = uniform_payloads(cfg)
    topo = Topology(loss=0.3)
    region = regions(cfg.n_nodes, 1)
    state = new_sim(cfg, 5)
    metrics = new_metrics(cfg)
    for _ in range(200):
        state, metrics = round_step(state, metrics, meta, cfg, topo, region)
    view = np.asarray(state.view)
    frac_down = (view == DOWN).mean()
    assert frac_down < 0.02, f"false-down fraction {frac_down}"
    assert np.asarray(state.incarnation).max() > 0, "refutations must have fired"


def test_false_suspicion_delays_convergence():
    """VERDICT r1 item 3: membership error must affect dissemination.
    Falsely marking half the cluster DOWN in everyone's view slows
    convergence vs a clean start — targets come from the believed member
    list, so starved nodes wait for refutation to rehabilitate them."""
    kw = dict(n_nodes=48, n_payloads=8, swim_full_view=True,
              sync_interval_rounds=8, fanout=2)
    cfg = SimConfig(**kw)
    # single burst at t0: convergence is a few rounds, so the victims'
    # refutation/rehabilitation latency is visible in the total
    meta = uniform_payloads(cfg, inject_every=0)

    def poison(state):
        # everyone (except the victims themselves) believes nodes 24..48
        # are DOWN at incarnation 0
        view = state.view.at[:, 24:].set(DOWN)
        view = view.at[jnp.arange(24, 48), jnp.arange(24, 48)].set(ALIVE)
        return state._replace(view=view)

    f_clean, m_clean = run(cfg, meta, max_rounds=600)
    f_poison, m_poison = run(cfg, meta, mutate=poison, max_rounds=600)
    clean_rounds = int(np.asarray(m_clean.converged_at).max())
    poison_rounds = int(np.asarray(m_poison.converged_at).max())
    assert (np.asarray(m_poison.converged_at) >= 0).all(), \
        "refutation must eventually rehabilitate falsely-downed nodes"
    # starved of push traffic, victims fall back to their own sync pulls /
    # announce rejoin — several rounds slower than the clean run (measured
    # 8-11 vs 5-6 across seeds)
    assert poison_rounds >= clean_rounds + 2, (poison_rounds, clean_rounds)
    # refutations fired: victims bumped incarnations past the false belief
    assert np.asarray(f_poison.incarnation)[24:].max() > 0


def test_uncoupled_membership_ignores_false_suspicion():
    """couple_membership=False restores the oracle behavior (targets
    uniform over the id space): poisoned views change nothing."""
    kw = dict(n_nodes=32, n_payloads=8, swim_full_view=True,
              couple_membership=False, probe_period_rounds=10_000)
    cfg = SimConfig(**kw)
    meta = uniform_payloads(cfg)

    def poison(state):
        view = state.view.at[:, 16:].set(DOWN)
        return state._replace(view=view)

    f_a, m_a = run(cfg, meta, max_rounds=400)
    f_b, m_b = run(cfg, meta, mutate=poison, max_rounds=400)
    assert (np.asarray(m_b.converged_at) >= 0).all()
    # uncoupled targeting ignores view entirely: same seed ⇒ identical
    # dissemination trajectory with or without the poisoned beliefs
    assert (
        np.asarray(m_a.converged_at) == np.asarray(m_b.converged_at)
    ).all()


def test_partition_heal_with_swim_recovers_mutual_down():
    """Code-review r2 finding: a symmetric partition drives both sides'
    views mutually DOWN; after heal, the announce/rejoin seam
    (spawn_swim_announcer analog) must rehabilitate membership and let
    payloads injected post-heal converge — not wedge forever."""
    cfg = SimConfig(n_nodes=32, n_payloads=8, swim_full_view=True,
                    suspect_timeout_rounds=4, sync_interval_rounds=6,
                    fanout=2)
    # payloads injected at round 80, well after the heal at 60
    meta = uniform_payloads(cfg, inject_every=0)
    meta = meta._replace(round=jnp.full_like(meta.round, 80))
    topo = Topology()
    region = regions(cfg.n_nodes, 1)

    state = new_sim(cfg, 1)
    group = (jnp.arange(32) >= 16).astype(jnp.int32)
    state = state._replace(group=group)
    metrics = new_metrics(cfg)
    for _ in range(60):
        state, metrics = round_step(state, metrics, meta, cfg, topo, region)
    view = np.asarray(state.view)
    assert (view[:16, 16:] == DOWN).all(), "A side must believe B down"
    assert (view[16:, :16] == DOWN).all(), "B side must believe A down"
    # heal
    state = state._replace(group=jnp.zeros((32,), jnp.int32))
    final, metrics = run_to_convergence(state, meta, cfg, topo, 800)
    conv = np.asarray(metrics.converged_at)
    assert (conv >= 0).all(), \
        f"post-heal wedge: {(conv < 0).sum()} nodes never converged"


def test_deterministic_replay():
    """Same seed ⇒ identical trajectory (the Antithesis-style determinism
    the reference outsources to a hypervisor, SURVEY §4.5)."""
    cfg = SimConfig(n_nodes=32, n_payloads=8, n_writers=2)
    meta = uniform_payloads(cfg)
    f1, m1 = run(cfg, meta, seed=9)
    f2, m2 = run(cfg, meta, seed=9)
    assert (np.asarray(f1.have) == np.asarray(f2.have)).all()
    assert (np.asarray(m1.converged_at) == np.asarray(m2.converged_at)).all()
    f3, _ = run(cfg, meta, seed=10)
    assert int(f3.t) != 0  # different seed still runs


def test_sharded_run_matches_single_device():
    """Node-axis sharding over the 8-device CPU mesh must not change the
    computation (same PRNG stream, same result)."""
    from corrosion_tpu.parallel.mesh import make_mesh, replicate_meta, shard_state

    cfg = SimConfig(n_nodes=64, n_payloads=16)
    meta = uniform_payloads(cfg)
    topo = Topology()

    final_a, metrics_a = run(cfg, meta, seed=4)

    mesh = make_mesh(8)
    state = shard_state(new_sim(cfg, 4), mesh)
    meta_r = replicate_meta(meta, mesh)
    final_b, metrics_b = run_to_convergence(state, meta_r, cfg, topo, 500)

    assert (np.asarray(final_a.have) == np.asarray(final_b.have)).all()
    assert (
        np.asarray(metrics_a.converged_at) == np.asarray(metrics_b.converged_at)
    ).all()


def test_rate_limit_slows_dissemination():
    """Choking the byte budget must strictly slow convergence."""
    fast_cfg = SimConfig(n_nodes=48, n_payloads=32,
                         default_payload_bytes=64 * 1024,
                         rate_limit_bytes_round=10**9,
                         sync_interval_rounds=10_000)
    slow_cfg = SimConfig(n_nodes=48, n_payloads=32,
                         default_payload_bytes=64 * 1024,
                         rate_limit_bytes_round=64 * 1024,  # 1 payload/round
                         sync_interval_rounds=10_000)
    fast_meta = uniform_payloads(fast_cfg)
    slow_meta = uniform_payloads(slow_cfg)
    f_fast, m_fast = run(fast_cfg, fast_meta, max_rounds=800)
    f_slow, m_slow = run(slow_cfg, slow_meta, max_rounds=800)
    assert int(f_slow.t) > int(f_fast.t), (int(f_slow.t), int(f_fast.t))


def test_chunked_versions_cover():
    """Multi-chunk versions: convergence requires every chunk (the
    seq-range/partial dimension, SURVEY §5 long-context analog)."""
    cfg = SimConfig(n_nodes=32, n_payloads=32, n_writers=2, chunks_per_version=4)
    meta = uniform_payloads(cfg)
    final, metrics = run(cfg, meta)
    assert bool((np.asarray(metrics.converged_at) >= 0).all())
    assert np.asarray(final.have).min() == 1


def test_budget_below_one_payload_sends_nothing():
    """Advisor r1-low: a byte budget smaller than one payload transmits
    ZERO payloads (the reference's governor blocks; no at-least-one floor)."""
    import jax.numpy as jnp

    from corrosion_tpu.sim.state import budget_prefix_mask

    nbytes = jnp.full((8,), 1024, jnp.int32)
    mask = jnp.ones((4, 8), bool)
    out = budget_prefix_mask(mask, budget_bytes=512, nbytes=nbytes)
    assert int(out.sum()) == 0
    out = budget_prefix_mask(mask, budget_bytes=2048, nbytes=nbytes)
    assert (out.sum(axis=-1) == 2).all()


def test_budget_meters_mixed_payload_sizes():
    """VERDICT r1 weak #8: the byte budget is size-accurate, not a count
    rank — many small changesets fit where few big ones would."""
    import jax.numpy as jnp

    from corrosion_tpu.sim.state import budget_prefix_mask

    # alternating 1 B and 8 KiB payloads (the reference's mixed reality)
    nbytes = jnp.asarray([1, 8192] * 4, jnp.int32)
    mask = jnp.ones((1, 8), bool)
    out = budget_prefix_mask(mask, budget_bytes=8193 + 1, nbytes=nbytes)
    # prefix: 1 + 8192 + 1 fits; the second 8 KiB does not
    assert out[0].tolist() == [True, True, True, False, False, False, False, False]
    # only-small mask: the same budget admits every 1 B payload
    small_only = jnp.asarray([[True, False] * 4])
    out = budget_prefix_mask(small_only, budget_bytes=8193 + 1, nbytes=nbytes)
    assert out[0].tolist() == [True, False] * 4


def test_mixed_size_write_storm_converges():
    """End-to-end: a storm of mixed 64 B / 8 KiB versions under a tight
    rate limit converges, with byte metering shaping dissemination."""
    cfg = SimConfig(n_nodes=32, n_payloads=16, n_writers=2,
                    rate_limit_bytes_round=16 * 1024,
                    sync_interval_rounds=4)
    import numpy as np

    sizes = np.where(np.arange(16) % 2 == 0, 64, 8 * 1024)
    meta = uniform_payloads(cfg, payload_bytes=sizes)
    final, metrics = run(cfg, meta, max_rounds=600)
    assert bool((np.asarray(metrics.converged_at) >= 0).all())


def test_ring0_first_speeds_local_coverage():
    """Ring0 tiering (members.rs:38-178, broadcast/mod.rs:589-651): with
    the first fanout slot pinned to a same-region member, the writer's
    region reaches full coverage no later (usually earlier) than with
    pure uniform fan-out, across seeds."""
    topo = Topology(n_regions=4, inter_delay=3, intra_delay=0)
    region = regions(64, 4)

    def rounds_to_local_coverage(ring0: bool, seed: int) -> int:
        cfg = SimConfig(n_nodes=64, n_payloads=4, fanout=2,
                        ring0_first=ring0, sync_interval_rounds=10_000)
        meta = uniform_payloads(cfg, inject_every=0)
        state = new_sim(cfg, seed)
        metrics = new_metrics(cfg)
        for t in range(200):
            state, metrics = round_step(state, metrics, meta, cfg, topo, region)
            have = np.asarray(state.have)
            if have[:16].min() > 0:  # writer's region (nodes 0..15) covered
                return t + 1
        return 200

    on = [rounds_to_local_coverage(True, s) for s in range(5)]
    off = [rounds_to_local_coverage(False, s) for s in range(5)]
    assert np.mean(on) <= np.mean(off), (on, off)
