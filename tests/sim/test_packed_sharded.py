"""Sharded-vs-single-device bit-equality for the PACKED envelope
(ISSUE 7): splitting the node-word axis of the bitpacked state across a
``nodes`` mesh partitions the math without changing it — final packed
state, RunMetrics, AND every RoundTrace telemetry channel must equal the
single-device run bit-for-bit, because the per-round coverage/delivery
reductions are exact integer folds whatever the layout.

Runs on the virtual 8-device CPU mesh the conftest arms
(``--xla_force_host_platform_device_count=8``), parametrized over mesh
sizes 1/2/8 and a NON-divisible node count (explicit NamedSharding
placement needs even shards, so a non-divisible cluster pads its node
axis and marks the tail permanently DOWN — `parallel.mesh.down_padding`;
the padding rows must never leak into coverage counts)."""

import dataclasses

import jax
import numpy as np
import pytest

from corrosion_tpu.parallel.mesh import (
    down_padding,
    make_mesh,
    padded_node_count,
    replicate_meta,
    shard_fault_plan,
    shard_state,
)
from corrosion_tpu.sim.faults import compile_plan, run_fault_plan
from corrosion_tpu.sim.packed import packed_supported
from corrosion_tpu.sim.round import new_sim, run_to_convergence
from corrosion_tpu.sim.runner import _write_storm, storm_fault_plan
from corrosion_tpu.sim.state import ALIVE
from corrosion_tpu.sim.topology import Topology

N_NODES = 512  # storm payload structure, scaled to the tier-1 budget
SEED = 7


def _storm(n_nodes=N_NODES, n_payloads=256):
    cfg, meta = _write_storm(n_nodes, n_payloads)
    # force the packed envelope open at test scale (the bench shape
    # clears the gate naturally at 100k × 512)
    cfg = dataclasses.replace(cfg, packed_min_cells=0)
    assert packed_supported(cfg, Topology())
    return cfg, meta


def _storm_fplan(cfg):
    # force the FACTORED form below its 1024-node auto threshold: the
    # sharded fault tensors under test are the rank-1 storm-scale ones
    return compile_plan(
        storm_fault_plan(cfg.n_nodes, SEED), cfg, Topology(),
        factored=True,
    )


def _assert_bit_identical(single, sharded, labels=("state", "metrics", "trace")):
    for label, a, b in zip(labels, single, sharded):
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y),
                err_msg=f"sharded diverged from single-device in {label}",
            )


@pytest.fixture(scope="module")
def fault_reference():
    """Single-device fault-storm run with telemetry — the bit-equality
    anchor every mesh size compares against (one compile, one run)."""
    cfg, meta = _storm()
    fplan = _storm_fplan(cfg)
    out = run_fault_plan(
        new_sim(cfg, SEED), meta, cfg, Topology(), fplan,
        max_rounds=600, telemetry=True,
    )
    jax.block_until_ready(out)
    return cfg, meta, fplan, out


@pytest.mark.parametrize("n_devices", [1, 2, 8])
def test_sharded_fault_storm_bit_identical(fault_reference, n_devices):
    """The tentpole contract: the storm fault schedule on the packed
    round path, node-axis-sharded, with the flight recorder on — state,
    metrics, and every telemetry channel equal single-device exactly,
    at every mesh size (1 exercises the mesh code path degenerately)."""
    cfg, meta, fplan, single = fault_reference
    mesh = make_mesh(n_devices)
    sharded = run_fault_plan(
        shard_state(new_sim(cfg, SEED), mesh),
        replicate_meta(meta, mesh),
        cfg, Topology(), shard_fault_plan(fplan, mesh),
        max_rounds=600, telemetry=True, mesh=mesh,
    )
    jax.block_until_ready(sharded)
    _assert_bit_identical(single, sharded)


def test_sharded_faultless_packed_bit_identical():
    """run_to_convergence (the faultless storm entry) sharded over the
    full mesh, telemetry on: same contract, no fault seam in the loop."""
    cfg, meta = _storm()
    single = run_to_convergence(
        new_sim(cfg, SEED), meta, cfg, Topology(), 600, telemetry=True
    )
    mesh = make_mesh()
    sharded = run_to_convergence(
        shard_state(new_sim(cfg, SEED), mesh),
        replicate_meta(meta, mesh),
        cfg, Topology(), 600, telemetry=True, mesh=mesh,
    )
    _assert_bit_identical(single, sharded)
    assert int(single[0].t) > 0  # the loop actually ran


def test_non_divisible_nodes_pad_down_without_leaking():
    """A cluster whose node count doesn't divide the mesh pads its node
    axis to the next multiple and marks the tail permanently DOWN: the
    padded run is bit-identical sharded-vs-single, the padding rows end
    the run with zero chunk bits and no convergence stamp, and the
    telemetry up-node counts never exceed the real population — padding
    can never leak into coverage."""
    n_real = 497
    n_pad = padded_node_count(n_real, 8)
    assert n_pad == 504 and n_pad % 8 == 0
    # 504 is NOT a multiple of 128: this shape is also the canary for
    # the shard-unaligned u8-draw bug aligned_u8_bits exists to fix
    cfg, meta = _storm(n_pad)
    fplan = _storm_fplan(cfg)

    def initial():
        return down_padding(new_sim(cfg, SEED), n_real)

    single = run_fault_plan(
        initial(), meta, cfg, Topology(), fplan, max_rounds=600,
        telemetry=True,
    )
    mesh = make_mesh(8)
    sharded = run_fault_plan(
        shard_state(initial(), mesh), replicate_meta(meta, mesh),
        cfg, Topology(), shard_fault_plan(fplan, mesh),
        max_rounds=600, telemetry=True, mesh=mesh,
    )
    _assert_bit_identical(single, sharded)

    final, metrics, trace = sharded
    alive = np.asarray(final.alive)
    have = np.asarray(final.have)
    conv = np.asarray(metrics.converged_at)
    rounds = int(final.t)
    # padding rows: permanently DOWN, zero knowledge, never converged
    assert (alive[n_real:] != ALIVE).all()
    assert have[n_real:].sum() == 0
    assert (conv[n_real:] == -1).all()
    # every real survivor converged (the padded storm still heals)
    assert ((conv[:n_real] >= 0) | (alive[:n_real] != ALIVE)).all()
    # telemetry coverage/up counts are bounded by the real population
    up = np.asarray(trace.up_nodes)[:rounds]
    assert up.max() <= n_real
    cov = np.asarray(trace.coverage)[:rounds]
    assert cov.max() <= n_real


def test_sharded_rung_config_smoke():
    """`config_packed_fault_storm_sharded` (the bench rung) end-to-end
    at smoke scale: the in-record single-device bit-equality check must
    pass and the record must carry the mesh + round_path."""
    from corrosion_tpu.sim.runner import config_packed_fault_storm_sharded

    m = config_packed_fault_storm_sharded(
        seed=1, n_nodes=256, n_payloads=64, microbench_rounds=2,
        n_devices=8,
    )
    assert m["n_devices"] == 8
    assert m["mesh"]["axes"] == {"nodes": 8}
    assert m["sharded_matches_single"] is True
    assert m["mismatched_keys"] == []
    assert m["converged"]


def _pswap_storm(n_nodes=96, n_payloads=64):
    """A packed-envelope scenario with the ISSUE 9 axes armed: PeerSwap
    sampler + the geo-tiered WAN family (partial-view SWIM dropped —
    the view IS the sampler; ground-truth membership, as the PeerSwap
    storm rung runs)."""
    from corrosion_tpu.topo import family_topology

    topo = Topology(**family_topology("wan-3x2"))
    cfg, meta = _write_storm(n_nodes, n_payloads, topo=topo,
                             sampler="peerswap")
    cfg = dataclasses.replace(cfg, packed_min_cells=0, view_slots=8)
    assert packed_supported(cfg, topo)
    return cfg, meta, topo


def test_topo_sampler_matrix_solo_vmapped_sharded_bit_identical():
    """ISSUE 9 determinism matrix: same seed ⇒ byte-identical topology
    tensors and PeerSwap view state across solo, vmapped-lane, and
    mesh-sharded runs of the SAME geo-tiered + peerswap scenario (the
    packed kernels, faults off — the fault matrix below covers the
    seam)."""
    from corrosion_tpu.campaign.ensemble import run_seed_ensemble

    cfg, meta, topo = _pswap_storm()
    solo = run_to_convergence(
        new_sim(cfg, SEED), meta, cfg, topo, 600
    )
    jax.block_until_ready(solo)

    # vmapped lane 0 of a 2-seed ensemble == the solo run, pview included
    lanes = run_seed_ensemble(
        None, cfg, topo, meta, (SEED, SEED + 1), max_rounds=600
    )
    lane0 = jax.tree.map(lambda x: x[0], lanes)
    _assert_bit_identical(solo, lane0, labels=("state", "metrics"))

    # mesh-sharded == solo (96 % 8 == 0; the node-split carry includes
    # the [N, V] view rows)
    mesh = make_mesh(8)
    sharded = run_to_convergence(
        shard_state(new_sim(cfg, SEED), mesh),
        replicate_meta(meta, mesh),
        cfg, topo, 600, mesh=mesh,
    )
    _assert_bit_identical(solo, sharded, labels=("state", "metrics"))
    # the topology tensors themselves are seed-free and layout-free:
    # compare the device values against an independent HOST (numpy)
    # reconstruction of the block/assignment rules
    from corrosion_tpu.sim.topology import azs, node_degrees, regions

    n = cfg.n_nodes
    per_r = max(1, n // topo.n_regions)
    ref_reg = np.minimum(np.arange(n) // per_r, topo.n_regions - 1)
    np.testing.assert_array_equal(
        np.asarray(regions(n, topo.n_regions)), ref_reg
    )
    per_az = max(1, per_r // topo.n_azs)
    local = np.arange(n) - ref_reg * per_r
    ref_az = ref_reg * topo.n_azs + np.minimum(
        local // per_az, topo.n_azs - 1
    )
    np.testing.assert_array_equal(np.asarray(azs(n, topo)), ref_az)
    het = Topology(degree_classes=(3, 2, 1))
    np.testing.assert_array_equal(
        np.asarray(node_degrees(n, het)),
        np.asarray([3, 2, 1] * (n // 3 + 1))[:n],
    )


def test_odd_mesh_6_devices_fault_storm_bit_identical():
    """An ODD-sized mesh (6 devices — the carried-edge shape): 510
    nodes divide the mesh but 510 is NOT a 128-multiple, so the
    [N]-flat fault-loss draws hit aligned_u8_bits' padded branch whose
    u32-word atoms keep shard boundaries word-aligned at d=6 (the old
    128-pad rule was only safe for power-of-two meshes)."""
    n = 510  # 510 % 6 == 0, 510 % 128 != 0, (510/6)=85 not a word multiple
    cfg, meta = _storm(n)
    fplan = _storm_fplan(cfg)
    single = run_fault_plan(
        new_sim(cfg, SEED), meta, cfg, Topology(), fplan,
        max_rounds=600, telemetry=True,
    )
    mesh = make_mesh(6)
    sharded = run_fault_plan(
        shard_state(new_sim(cfg, SEED), mesh), replicate_meta(meta, mesh),
        cfg, Topology(), shard_fault_plan(fplan, mesh),
        max_rounds=600, telemetry=True, mesh=mesh,
    )
    jax.block_until_ready(sharded)
    _assert_bit_identical(single, sharded)


@pytest.mark.parametrize("proto_family", [None, "baseline"])
def test_proto_default_point_solo_vmapped_sharded_bit_identical(
    proto_family,
):
    """ISSUE 11 byte-identity matrix for the DEFAULT protocol point,
    with ``proto_family`` unset AND explicitly "baseline": dense==packed
    bit-equal, and solo == vmapped-lane == mesh-sharded byte-identity on
    the packed path — the same matrix PR 9 pinned for topologies,
    extended over the protocol axis (an explicitly-resolved baseline
    family must compile the IDENTICAL program)."""
    from corrosion_tpu.campaign.ensemble import run_seed_ensemble
    from corrosion_tpu.campaign.spec import CampaignSpec

    scenario = {"n_nodes": 96, "n_payloads": 64, "n_writers": 4,
                "fanout": 3}
    if proto_family is not None:
        scenario["proto_family"] = proto_family
    spec = CampaignSpec(name="t", scenario=scenario)
    cfg = dataclasses.replace(spec.sim_config({}), packed_min_cells=0)
    meta = _write_storm(96, 64)[1]
    topo = Topology()
    assert packed_supported(cfg, topo)

    solo = run_to_convergence(new_sim(cfg, SEED), meta, cfg, topo, 600)
    jax.block_until_ready(solo)

    # dense == packed bit-equal at the default point
    dense_cfg = dataclasses.replace(cfg, allow_packed=False)
    dense = run_to_convergence(
        new_sim(dense_cfg, SEED), meta, dense_cfg, topo, 600
    )
    _assert_bit_identical(solo, dense, labels=("state", "metrics"))

    # vmapped lane 0 of a 2-seed ensemble == the solo run
    lanes = run_seed_ensemble(
        None, cfg, topo, meta, (SEED, SEED + 1), max_rounds=600
    )
    lane0 = jax.tree.map(lambda x: x[0], lanes)
    _assert_bit_identical(solo, lane0, labels=("state", "metrics"))

    # mesh-sharded == solo (96 % 8 == 0)
    mesh = make_mesh(8)
    sharded = run_to_convergence(
        shard_state(new_sim(cfg, SEED), mesh),
        replicate_meta(meta, mesh),
        cfg, topo, 600, mesh=mesh,
    )
    _assert_bit_identical(solo, sharded, labels=("state", "metrics"))


def test_proto_variant_sharded_bit_identical():
    """A NON-default protocol point through the sharded matrix: the
    push-pull exchange on the packed path, node-axis-split over the
    full virtual mesh, telemetry on — state, metrics, and every wire
    channel (the pull direction included) equal single-device
    exactly."""
    from corrosion_tpu.proto import family_proto

    cfg, meta = _storm(96, 64)
    cfg = dataclasses.replace(cfg, **family_proto("push-pull"))
    assert packed_supported(cfg, Topology())
    single = run_to_convergence(
        new_sim(cfg, SEED), meta, cfg, Topology(), 600, telemetry=True
    )
    mesh = make_mesh(8)
    sharded = run_to_convergence(
        shard_state(new_sim(cfg, SEED), mesh),
        replicate_meta(meta, mesh),
        cfg, Topology(), 600, telemetry=True, mesh=mesh,
    )
    _assert_bit_identical(single, sharded)


def test_ensemble_mesh_picks_largest_divisor():
    """Campaign cells never pad (padding would change trajectories):
    `ensemble_mesh` degrades to the largest dividing device count."""
    from corrosion_tpu.campaign.ensemble import ensemble_mesh

    cfg, _ = _storm(1024)
    mesh = ensemble_mesh(cfg, 8)
    assert len(mesh.devices.flat) == 8
    cfg6, _ = _storm(96)  # 96 % 8 == 0 → still 8
    assert len(ensemble_mesh(cfg6, 8).devices.flat) == 8
    cfg3 = dataclasses.replace(cfg, n_nodes=1023)  # 1023 = 3 × 341
    assert len(ensemble_mesh(cfg3, 8).devices.flat) == 3
    assert ensemble_mesh(cfg, 1) is None
    assert ensemble_mesh(cfg, None) is None
