"""Config #5b — the V ≫ K gap-stress storm (VERDICT r2 item 3).

Proves (a) the fixed-K clamp path actually RUNS at bench shape (gap
overflow observed, not just unit-tested), (b) convergence survives it,
and (c) the two-lane i32 byte-budget cumsum that replaced the 32767-
payload cap is exact against an int64 reference."""

import numpy as np

import jax.numpy as jnp

from corrosion_tpu.sim.runner import (
    config_write_storm_gapstress,
    gapstress_payload_sizes,
)
from corrosion_tpu.sim.state import budget_prefix_mask


def test_gapstress_overflows_and_converges():
    m = config_write_storm_gapstress(seed=3, n_nodes=128, max_rounds=600)
    assert m["converged"], m
    # the whole point of #5b: the clamp path must actually fire
    assert m["gap_overflow_frac_max"] > 0.01, m["gap_overflow_frac_max"]


def test_gapstress_sizes_are_mixed():
    sizes = gapstress_payload_sizes(8192)
    assert sizes.min() == 1 and sizes.max() == 8192
    assert len(np.unique(sizes)) == 6


def test_budget_mask_large_p_matches_int64_reference():
    """p > 32767 engages the two-lane exact path; compare against a
    straight int64 cumsum for random masks/sizes/budgets."""
    rng = np.random.default_rng(7)
    p = 40_000
    for budget in (0, 1, 8191, 1 << 20, 5 * 1 << 20, 1 << 30):
        mask = rng.random((3, p)) < 0.7
        sizes = rng.integers(0, 64 * 1024 + 1, p).astype(np.int32)
        got = np.asarray(
            budget_prefix_mask(
                jnp.asarray(mask), budget, jnp.asarray(sizes)
            )
        )
        cum = np.cumsum(np.where(mask, sizes.astype(np.int64), 0), axis=-1)
        want = mask & (cum <= budget)
        assert (got == want).all(), budget


def test_budget_mask_small_p_unchanged():
    rng = np.random.default_rng(8)
    p = 500
    mask = rng.random((2, p)) < 0.5
    sizes = rng.integers(1, 8193, p).astype(np.int32)
    budget = 100_000
    got = np.asarray(
        budget_prefix_mask(jnp.asarray(mask), budget, jnp.asarray(sizes))
    )
    cum = np.cumsum(np.where(mask, sizes.astype(np.int64), 0), axis=-1)
    want = mask & (cum <= budget)
    assert (got == want).all()
