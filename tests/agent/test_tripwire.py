"""Tripwire/spawn shutdown plumbing tests (tripwire/src/tripwire.rs,
spawn/src/lib.rs)."""

import asyncio

from corrosion_tpu.utils.tripwire import (
    Outcome,
    Tripwire,
    pending_count,
    preemptible,
    spawn_counted,
    wait_for_all_pending_handles,
)


def test_preemptible_completes():
    async def body():
        tw = Tripwire()

        async def work():
            return 42

        out = await preemptible(work(), tw)
        assert out and out.value == 42

    asyncio.run(body())


def test_preemptible_preempted_cancels():
    async def body():
        tw = Tripwire()
        cancelled = asyncio.Event()

        async def work():
            try:
                await asyncio.sleep(30)
            except asyncio.CancelledError:
                cancelled.set()
                raise

        async def tripper():
            await asyncio.sleep(0.01)
            tw.trip()

        asyncio.create_task(tripper())
        out = await preemptible(work(), tw)
        assert out.preempted and not out
        assert cancelled.is_set()

    asyncio.run(body())


def test_already_tripped_short_circuits():
    async def body():
        tw = Tripwire()
        tw.trip()
        ran = False

        async def work():
            nonlocal ran
            ran = True

        out = await preemptible(work(), tw)
        assert out.preempted
        # the coroutine was never started but must not leak a warning
        assert not ran

    asyncio.run(body())


def test_counted_drain():
    async def body():
        done = []

        async def work(i):
            await asyncio.sleep(0.02 * i)
            done.append(i)

        for i in range(3):
            spawn_counted(work(i))
        assert pending_count() >= 1
        ok = await wait_for_all_pending_handles(timeout=5.0)
        assert ok
        assert sorted(done) == [0, 1, 2]
        assert pending_count() == 0

    asyncio.run(body())


def test_drain_times_out_on_stuck_task():
    async def body():
        async def stuck():
            await asyncio.sleep(60)

        t = spawn_counted(stuck())
        ok = await wait_for_all_pending_handles(timeout=0.3)
        assert not ok
        t.cancel()
        try:
            await t
        except asyncio.CancelledError:
            pass

    asyncio.run(body())
