"""Tracing tests: span mechanics, W3C carrier round-trip, and the
cross-node property — one trace spans both ends of a sync exchange
(SyncTraceContextV1, corro-types/src/sync.rs:33-67)."""

import asyncio

from corrosion_tpu.tracing import (
    TRACER,
    SpanContext,
    Tracer,
    current_traceparent,
    extract,
    span,
)


def test_span_nesting_and_ids():
    tracer = Tracer()
    with span("outer", tracer=tracer) as outer:
        assert current_traceparent() == outer.context.traceparent()
        with span("inner", tracer=tracer) as inner:
            assert inner.context.trace_id == outer.context.trace_id
            assert inner.parent_span_id == outer.context.span_id
    assert current_traceparent() is None
    names = [s.name for s in tracer.finished]
    assert names == ["inner", "outer"]  # children finish first
    assert all(s.duration_s is not None for s in tracer.finished)


def test_traceparent_roundtrip():
    ctx = SpanContext(trace_id=0xABC123, span_id=0x42)
    tp = ctx.traceparent()
    assert tp == f"00-{0xABC123:032x}-{0x42:016x}-01"
    back = extract(tp)
    assert back.trace_id == 0xABC123
    assert back.span_id == 0x42
    assert back.sampled


def test_extract_rejects_garbage():
    assert extract(None) is None
    assert extract("") is None
    assert extract("zz-123") is None
    assert extract("00-0-0-01") is None
    assert extract("00-xyz-abc-01") is None


def test_error_status_recorded():
    tracer = Tracer()
    try:
        with span("boom", tracer=tracer):
            raise ValueError("x")
    except ValueError:
        pass
    assert tracer.finished[-1].status == "error: ValueError"


def test_exporter_receives_spans():
    tracer = Tracer()
    got = []
    tracer.set_exporter(got.append)
    with span("exported", tracer=tracer):
        pass
    assert [s.name for s in got] == ["exported"]


def test_sync_trace_spans_both_nodes():
    """Force a sync round and assert the server's serve_sync span joined
    the client's parallel_sync trace."""
    from corrosion_tpu.testing import Cluster, LinkModel

    async def body():
        # 100% broadcast loss: only sync can converge, guaranteeing a
        # sync exchange happens
        cluster = Cluster(2, use_swim=False, link=LinkModel(loss=1.0))
        await cluster.start()
        try:
            # clear, don't len-snapshot: the ring is bounded, so once the
            # suite has filled it len() saturates at maxlen and a
            # [before:] slice silently reads as empty
            TRACER.finished.clear()
            cluster.agents[0].exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (1, "t"))]
            )
            ok = await cluster.wait_converged(timeout=30.0)
            assert ok
            spans = list(TRACER.finished)
            clients = [s for s in spans if s.name == "parallel_sync"]
            servers = [s for s in spans if s.name == "serve_sync"]
            assert clients and servers
            client_traces = {s.context.trace_id for s in clients}
            # at least one server span continues a client trace with the
            # client span as its parent
            joined = [
                s
                for s in servers
                if s.context.trace_id in client_traces and s.parent_span_id
            ]
            assert joined, [s.to_dict() for s in servers]
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_campaign_seeded_trace_ids(monkeypatch):
    """ISSUE 5 satellite: with CORRO_CAMPAIGN_SEED set, span/trace ids
    come from a seeded stream — re-seeding replays the identical id
    sequence, so campaign artifacts that embed traceparents are
    digest-stable under seeded replay.  Unseeded runs stay random."""
    from corrosion_tpu import tracing

    monkeypatch.setenv("CORRO_CAMPAIGN_SEED", "1234")
    try:
        tracing.seed_trace_ids()
        tracer = Tracer()
        with span("a", tracer=tracer) as a:
            pass
        first = (a.context.trace_id, a.context.span_id)
        tracing.seed_trace_ids()
        with span("b", tracer=tracer) as b:
            pass
        assert (b.context.trace_id, b.context.span_id) == first
        # an explicit seed overrides the env
        tracing.seed_trace_ids(99)
        with span("c", tracer=tracer) as c:
            pass
        assert (c.context.trace_id, c.context.span_id) != first
        # a non-integer seed still seeds deterministically (sha512 fold)
        tracing.seed_trace_ids("storm-A")
        with span("d", tracer=tracer) as d:
            pass
        tracing.seed_trace_ids("storm-A")
        with span("e", tracer=tracer) as e:
            pass
        assert (d.context.trace_id, d.context.span_id) == (
            e.context.trace_id, e.context.span_id,
        )
    finally:
        # restore the unseeded stream for the rest of the suite
        monkeypatch.delenv("CORRO_CAMPAIGN_SEED", raising=False)
        tracing.seed_trace_ids()
