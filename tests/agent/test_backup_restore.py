"""Backup/restore: snapshot stripping, online swap, fresh-actor rejoin.

Covers the reference's backup/restore semantics (main.rs:160-331,
sqlite3-restore lib.rs:57-152) and the Antithesis backup/restore drivers
(.antithesis/client/test-templates/parallel_driver_backup_node.sh).
"""

import os
import sqlite3

import pytest

from corrosion_tpu.agent.backup import backup_db, db_lock, restore_db
from corrosion_tpu.agent.store import CrrStore
from corrosion_tpu.core.types import ActorId

SCHEMA = """
CREATE TABLE tests (
    id INTEGER PRIMARY KEY NOT NULL,
    text TEXT NOT NULL DEFAULT ''
);
"""


def _make_store(path: str) -> CrrStore:
    store = CrrStore(path, ActorId.random())
    store.execute_schema(SCHEMA)
    return store


def test_backup_strips_node_state(tmp_path):
    live = str(tmp_path / "live.db")
    store = _make_store(live)
    store.transact([("INSERT INTO tests (id, text) VALUES (?, ?)", (1, "a"))])
    store.conn.execute(
        "INSERT INTO __corro_members (actor_id, address) VALUES (?, ?)",
        (ActorId.random().bytes_, "peer:1"),
    )
    store.close()

    dest = str(tmp_path / "backup.db")
    backup_db(live, dest)

    snap = sqlite3.connect(dest)
    assert snap.execute(
        "SELECT COUNT(*) FROM __corro_state WHERE key = 'site_id'"
    ).fetchone()[0] == 0
    assert snap.execute("SELECT COUNT(*) FROM __corro_members").fetchone()[0] == 0
    # replicated data survives: base row + its clock entries
    assert snap.execute("SELECT text FROM tests WHERE id = 1").fetchone()[0] == "a"
    assert snap.execute("SELECT COUNT(*) FROM tests__crdt_clock").fetchone()[0] == 1
    snap.close()


def test_backup_refuses_overwrite(tmp_path):
    live = str(tmp_path / "live.db")
    _make_store(live).close()
    dest = str(tmp_path / "backup.db")
    backup_db(live, dest)
    with pytest.raises(FileExistsError):
        backup_db(live, dest)


def test_restore_swaps_and_stamps_fresh_actor(tmp_path):
    src = str(tmp_path / "src.db")
    store = _make_store(src)
    old_actor = store.site_id
    store.transact([("INSERT INTO tests (id, text) VALUES (?, ?)", (1, "a"))])
    store.close()
    snap = str(tmp_path / "backup.db")
    backup_db(src, snap)

    # restore over a different node's live DB
    live = str(tmp_path / "other.db")
    other = _make_store(live)
    other.transact([("INSERT INTO tests (id, text) VALUES (?, ?)", (99, "gone"))])
    other.close()

    new_actor = restore_db(snap, live)
    assert new_actor != old_actor

    restored = CrrStore(live, ActorId.random())  # random id must NOT win
    assert restored.site_id == new_actor
    rows = restored.query("SELECT id, text FROM tests ORDER BY id")
    assert [(r[0], r[1]) for r in rows] == [(1, "a")]
    # origin's version bookkeeping is cluster data and survives
    assert restored.db_version(old_actor) == 1
    # the restored node is a fresh actor: its own writes start at version 1
    _, info = restored.transact(
        [("INSERT INTO tests (id, text) VALUES (?, ?)", (2, "b"))]
    )
    assert info.db_version == 1
    restored.close()


def test_restore_pinned_site_id(tmp_path):
    src = str(tmp_path / "src.db")
    _make_store(src).close()
    snap = str(tmp_path / "backup.db")
    backup_db(src, snap)
    live = str(tmp_path / "live.db")
    pinned = ActorId.random()
    assert restore_db(snap, live, site_id=pinned) == pinned
    store = CrrStore(live, ActorId.random())
    assert store.site_id == pinned
    store.close()


def test_restore_rejects_non_backup(tmp_path):
    bogus = str(tmp_path / "bogus.db")
    sqlite3.connect(bogus).execute("CREATE TABLE x (a)").connection.close()
    with pytest.raises(ValueError):
        restore_db(bogus, str(tmp_path / "live.db"))


def test_db_lock_blocks_second_locker(tmp_path):
    # POSIX locks are per-process, so the contending locker must be a
    # separate process (the reference's protection is against other SQLite
    # *processes*, sqlite3-restore lib.rs:57).
    import subprocess
    import sys

    live = str(tmp_path / "live.db")
    _make_store(live).close()

    probe = (
        "import fcntl, os, sys\n"
        f"fd = os.open({live!r}, os.O_RDWR)\n"
        "try:\n"
        "    fcntl.lockf(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)\n"
        "    print('acquired')\n"
        "except BlockingIOError:\n"
        "    print('blocked')\n"
    )
    with db_lock(live):
        out = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True
        )
    assert out.stdout.strip() == "blocked"
    out = subprocess.run(
        [sys.executable, "-c", probe], capture_output=True, text=True
    )
    assert out.stdout.strip() == "acquired"
