"""Adaptive sync serving (VERDICT r1 item 6): chunk shrink on slow sends
and slow-peer abort, driven through `_serve_need` with an artificially
slow BiStream (the reference's handle_need behavior,
peer/mod.rs:365-368,729-790)."""

import asyncio
import tempfile

import pytest

from corrosion_tpu.agent.agent import AdaptiveSender, Agent, SlowPeerAbort
from corrosion_tpu.agent.config import Config
from corrosion_tpu.agent.transport import BiStream, MemoryNetwork
from corrosion_tpu.core.types import SyncNeed
from corrosion_tpu.testing import TEST_SCHEMA, fast_perf


class SlowBiStream(BiStream):
    """A stream whose sends take a configurable time (a congested peer)."""

    def __init__(self, delay_s: float, hang_after: int = 10**9):
        super().__init__()
        self.delay_s = delay_s
        self.hang_after = hang_after
        self.frames = []

    async def send(self, frame: bytes) -> None:
        if len(self.frames) >= self.hang_after:
            await asyncio.sleep(3600)  # stall forever
        await asyncio.sleep(self.delay_s)
        self.frames.append(frame)


def _make_agent(tmp, rows=400):
    net = MemoryNetwork()
    cfg = Config(
        db_path=f"{tmp}/a.db", gossip_addr="a", use_swim=False,
        perf=fast_perf(),
    )
    cfg.perf.sync_slow_send_s = 0.01
    cfg.perf.sync_stall_abort_s = 0.25
    agent = Agent(cfg, net.transport("a"))
    agent.store.execute_schema(TEST_SCHEMA)
    agent.exec_transaction(
        [
            ("INSERT INTO tests (id, text) VALUES (?, ?)", (i, "x" * 64))
            for i in range(rows)
        ]
    )
    return agent


def test_slow_sends_shrink_chunks():
    async def body():
        with tempfile.TemporaryDirectory() as tmp:
            agent = _make_agent(tmp)
            sender = AdaptiveSender(agent.config.perf)
            start_size = sender.chunk_size
            bi = SlowBiStream(delay_s=0.02)  # above the slow threshold
            need = SyncNeed.full(1, 1)
            await agent._serve_need(bi, agent.actor_id, need, sender)
            assert sender.shrinks > 0, "slow sends must shrink the chunk size"
            assert sender.chunk_size < start_size
            assert sender.chunk_size >= agent.config.perf.min_changes_byte_size
            # shrinking means MORE chunks than one 8 KiB stream would need
            assert len(bi.frames) > 3
            agent.store.close()

    asyncio.run(body())


def test_chunk_size_floors_at_min():
    async def body():
        with tempfile.TemporaryDirectory() as tmp:
            agent = _make_agent(tmp)
            sender = AdaptiveSender(agent.config.perf)
            bi = SlowBiStream(delay_s=0.02)
            await agent._serve_need(bi, agent.actor_id, SyncNeed.full(1, 1), sender)
            assert sender.chunk_size == agent.config.perf.min_changes_byte_size
            agent.store.close()

    asyncio.run(body())


def test_stalled_peer_aborts():
    async def body():
        with tempfile.TemporaryDirectory() as tmp:
            agent = _make_agent(tmp)
            sender = AdaptiveSender(agent.config.perf)
            bi = SlowBiStream(delay_s=0.0, hang_after=2)
            t0 = asyncio.get_event_loop().time()
            with pytest.raises(SlowPeerAbort):
                await agent._serve_need(
                    bi, agent.actor_id, SyncNeed.full(1, 1), sender
                )
            elapsed = asyncio.get_event_loop().time() - t0
            assert elapsed < 5.0, "abort must fire at the stall threshold"
            agent.store.close()

    asyncio.run(body())
