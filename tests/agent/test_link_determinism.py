"""LinkModel seed discipline (ISSUE 2 satellite): per-link-instance
independent, seed-derived RNG streams, and exact drop-sequence replay."""

import asyncio

from corrosion_tpu.agent.transport import LinkModel, MemoryNetwork
from corrosion_tpu.faults import derive_seed


def test_same_seed_replays_exact_drop_sequence():
    a = LinkModel(loss=0.5, seed=1234)
    seq = [a.drop() for _ in range(200)]
    b = LinkModel(loss=0.5, seed=1234)
    assert [b.drop() for _ in range(200)] == seq
    assert any(seq) and not all(seq)  # p=0.5 really flips both ways


def test_replay_covers_dup_and_jitter_draws_too():
    a = LinkModel(loss=0.3, jitter_s=0.05, duplicate=0.2, seed=9)
    trace = [(a.drop(), a.dup(), a.delay_s()) for _ in range(100)]
    b = LinkModel(loss=0.3, jitter_s=0.05, duplicate=0.2, seed=9)
    assert [(b.drop(), b.dup(), b.delay_s()) for _ in range(100)] == trace


def test_network_links_get_independent_seed_derived_streams():
    """Two edges of one network must NOT share an RNG stream: before the
    fix every edge read the shared default_link, so link A's traffic
    perturbed link B's drop sequence and no per-link schedule could
    replay."""
    net = MemoryNetwork(default_link=LinkModel(loss=0.5, seed=77))
    ab = net.link("a", "b")
    ba = net.link("b", "a")
    ac = net.link("a", "c")
    assert ab is not ba and ab is not ac  # distinct instances
    assert len({ab.seed, ba.seed, ac.seed}) == 3  # distinct derived seeds
    # the derivation is the documented rule, not an accident
    assert ab.seed == derive_seed(77, "link", "a", "b")
    # repeated lookup returns the SAME instance (the stream continues,
    # it doesn't restart per send)
    assert net.link("a", "b") is ab
    # derived streams are reproducible across networks from one base seed
    net2 = MemoryNetwork(default_link=LinkModel(loss=0.5, seed=77))
    seq = [net2.link("a", "b").drop() for _ in range(100)]
    net3 = MemoryNetwork(default_link=LinkModel(loss=0.5, seed=77))
    assert [net3.link("a", "b").drop() for _ in range(100)] == seq


def test_interleaved_traffic_does_not_perturb_other_links():
    """Link (a,b)'s decision sequence is identical whether or not (a,c)
    consumed draws in between — the per-edge independence property."""
    net1 = MemoryNetwork(default_link=LinkModel(loss=0.5, seed=5))
    pure = [net1.link("a", "b").drop() for _ in range(50)]

    net2 = MemoryNetwork(default_link=LinkModel(loss=0.5, seed=5))
    interleaved = []
    for i in range(50):
        interleaved.append(net2.link("a", "b").drop())
        net2.link("a", "c").drop()  # other-link traffic in between
    assert interleaved == pure


def test_duplicate_delivers_twice_and_jitter_reorders():
    """End-to-end through MemoryTransport: duplication produces two
    deliveries of one send; per-message jitter lets a later send land
    before an earlier one (the reorder fault)."""

    async def body():
        net = MemoryNetwork()
        t_src = net.transport("src")
        t_dst = net.transport("dst")
        got = []

        async def on_uni(src, data):
            got.append(data)

        async def settle(n, timeout=10.0):
            # poll, not a fixed sleep: a loaded machine stretches the
            # event loop, and a bounded wait can't strand the suite
            deadline = asyncio.get_event_loop().time() + timeout
            while len(got) < n and asyncio.get_event_loop().time() < deadline:
                await asyncio.sleep(0.01)

        t_dst.set_handlers(None, on_uni, None)
        # always-duplicate, no jitter: one send → two deliveries
        net.links[("src", "dst")] = LinkModel(duplicate=1.0, seed=1)
        await t_src.send_uni("dst", b"x")
        await settle(2)
        assert got == [b"x", b"x"]

        # deterministic reorder through REAL per-message jitter: seed 15's
        # first two uniform draws are 0.965 and 0.012, so message one
        # sleeps ~0.19 s and message two ~0.002 s and overtakes it.  A
        # broken jitter (e.g. one draw per link instead of per message)
        # would delay both equally, preserve FIFO order, and fail here.
        got.clear()
        net.links[("src", "dst")] = LinkModel(jitter_s=0.2, seed=15)
        await t_src.send_uni("dst", b"slow")
        await t_src.send_uni("dst", b"fast")
        await settle(2)
        assert got == [b"fast", b"slow"]

    asyncio.run(body())
