"""Load-generator tests: flood writes on one node, watch the
subscription + updates feeds on another, assert no lost writes
(.antithesis/client/src/main.rs:65-308)."""

import asyncio

from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.loadgen import LoadGenerator
from corrosion_tpu.testing import Cluster


async def _with_api_cluster(n, fn):
    cluster = Cluster(n)
    await cluster.start()
    servers = []
    try:
        for agent in cluster.agents:
            srv = ApiServer(agent)
            await srv.start()
            servers.append(srv)
        await fn(cluster, servers)
    finally:
        for srv in servers:
            await srv.stop()
        await cluster.stop()


def test_loadgen_same_node_consistent():
    async def body(cluster, servers):
        gen = LoadGenerator(servers[0].addr)
        report = await gen.run(n_writes=40, rate_hz=500.0, settle_timeout_s=20.0)
        assert report.writes_ok == 40
        assert report.consistent, report.to_dict()
        assert report.sub_rows_seen >= 40
        assert report.update_events_seen > 0

    asyncio.run(_with_api_cluster(1, body))


def test_loadgen_cross_node_convergence():
    async def body(cluster, servers):
        # write on node 0, watch node 1: consistency requires gossip
        gen = LoadGenerator(servers[0].addr, servers[1].addr)
        report = await gen.run(n_writes=25, rate_hz=500.0, settle_timeout_s=30.0)
        assert report.writes_ok == 25
        assert report.consistent, report.to_dict()

    asyncio.run(_with_api_cluster(2, body))


def test_loadgen_multi_writer_watcher_latency():
    """The measured driver (ISSUE 8): N writer lanes with disjoint ids,
    M watchers each requiring full visibility, client-observed
    publish→visible percentiles in the report."""

    async def body(cluster, servers):
        gen = LoadGenerator(
            [s.addr for s in servers],
            list(reversed([s.addr for s in servers])),
            n_writers=3, n_watchers=2,
        )
        report = await gen.run(
            n_writes=30, rate_hz=0.0, settle_timeout_s=30.0
        )
        assert report.writes_ok == 30
        assert report.consistent, report.to_dict()
        assert report.writers == 3 and report.watchers == 2
        vl = report.visible_latency_s
        assert vl is not None and vl["samples"] >= 30
        assert 0 <= vl["p50"] <= vl["p99"] <= vl["max"]
        assert report.write_latency_s["samples"] == 30
        assert report.throughput_wps > 0
        d = report.to_dict()
        assert d["lost_writes"] is False
        assert d["checker_broken"] is False

    asyncio.run(_with_api_cluster(2, body))


def test_loadgen_stream_death_reads_checker_broken():
    """Satellite (ISSUE 8): a watch stream whose serving node dies is a
    BROKEN CHECKER — missing rows on a dead stream must never classify
    as lost writes."""

    async def body(cluster, servers):
        gen = LoadGenerator(servers[0].addr, servers[1].addr)

        async def kill_reader():
            # 0.3 s lands strictly inside the flood: streams attach for
            # the first 0.2 s, and 40 paced writes at 100 Hz cannot
            # finish before ~0.4 s (the pacing jitter floor is 0.5x),
            # so the stream dies while writes are still outstanding
            await asyncio.sleep(0.3)
            await servers[1].stop()

        killer = asyncio.create_task(kill_reader())
        # settle long enough for the stream's capped reconnect chain to
        # exhaust against the dead node and surface the root cause
        report = await gen.run(
            n_writes=40, rate_hz=100.0, settle_timeout_s=15.0
        )
        await killer
        assert report.stream_errors, report.to_dict()
        assert report.checker_broken
        assert not report.lost_writes
        assert not report.consistent

    asyncio.run(_with_api_cluster(2, body))


def test_load_report_classification_matrix():
    """The stream-death vs lost-write distinction as a truth table."""
    from corrosion_tpu.loadgen import LoadReport

    healthy = LoadReport(writes_ok=5)
    assert healthy.consistent
    assert not healthy.lost_writes and not healthy.checker_broken

    lost = LoadReport(writes_ok=5, missing_on_sub=[3])
    assert lost.lost_writes
    assert not lost.checker_broken
    assert not lost.consistent

    dead = LoadReport(writes_ok=5, stream_errors=["subscription[0]: gone"])
    assert dead.checker_broken
    assert not dead.lost_writes  # inconclusive, not a replication bug
    assert not dead.consistent

    # both at once: missing_on_sub only ever holds HEALTHY watchers'
    # losses, so a dead stream elsewhere does not grant amnesty — this
    # is a real loss AND a broken checker
    both = LoadReport(
        writes_ok=5, missing_on_sub=[1],
        stream_errors=["subscription[0]: gone"],
    )
    assert both.checker_broken and both.lost_writes
