"""Load-generator tests: flood writes on one node, watch the
subscription + updates feeds on another, assert no lost writes
(.antithesis/client/src/main.rs:65-308)."""

import asyncio

from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.loadgen import LoadGenerator
from corrosion_tpu.testing import Cluster


async def _with_api_cluster(n, fn):
    cluster = Cluster(n)
    await cluster.start()
    servers = []
    try:
        for agent in cluster.agents:
            srv = ApiServer(agent)
            await srv.start()
            servers.append(srv)
        await fn(cluster, servers)
    finally:
        for srv in servers:
            await srv.stop()
        await cluster.stop()


def test_loadgen_same_node_consistent():
    async def body(cluster, servers):
        gen = LoadGenerator(servers[0].addr)
        report = await gen.run(n_writes=40, rate_hz=500.0, settle_timeout_s=20.0)
        assert report.writes_ok == 40
        assert report.consistent, report.to_dict()
        assert report.sub_rows_seen >= 40
        assert report.update_events_seen > 0

    asyncio.run(_with_api_cluster(1, body))


def test_loadgen_cross_node_convergence():
    async def body(cluster, servers):
        # write on node 0, watch node 1: consistency requires gossip
        gen = LoadGenerator(servers[0].addr, servers[1].addr)
        report = await gen.run(n_writes=25, rate_hz=500.0, settle_timeout_s=30.0)
        assert report.writes_ok == 25
        assert report.consistent, report.to_dict()

    asyncio.run(_with_api_cluster(2, body))
