"""Native C++ CRDT core parity: the compiled comparator/merger must agree
with the Python spec (`core.crdt`) on every input class — the rebuild's
answer to 'cr-sqlite semantic fidelity needs an oracle' (SURVEY §7)."""

import itertools
import random

import pytest

from corrosion_tpu import native
from corrosion_tpu.core.crdt import MergeOutcome, merge_cell, value_cmp
from corrosion_tpu.core.types import ActorId

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain"
)

VALUES = [
    None, 0, 1, -1, 2**40, -(2**40), 0.0, 1.5, -2.75, 1e300,
    "", "a", "ab", "b", "destroyed", "started", "ü",
    b"", b"\x00", b"\x00\x01", b"a", b"ab",
]


def test_value_cmp_parity_exhaustive():
    for a, b in itertools.product(VALUES, VALUES):
        py = value_cmp(a, b)
        cc = native.value_cmp_native(a, b)
        assert (py > 0) == (cc > 0) and (py < 0) == (cc < 0), (a, b, py, cc)


def test_merge_batch_parity_random():
    rng = random.Random(13)
    sites = [ActorId.random() for _ in range(4)]
    cells = [
        (cv, v, s)
        for cv in (1, 2, 3)
        for v in VALUES[:12]
        for s in sites[:2]
    ]
    existing, incoming = [], []
    for _ in range(500):
        existing.append(None if rng.random() < 0.2 else rng.choice(cells))
        incoming.append(rng.choice(cells))
    got = native.merge_batch(existing, incoming)
    want = [merge_cell(e, i) for e, i in zip(existing, incoming)]
    assert got == want


def test_merge_batch_no_equal_values_mode():
    s1, s2 = sorted([ActorId.random(), ActorId.random()])
    existing = [(1, "x", s2)]
    incoming = [(1, "x", s1)]
    assert native.merge_batch(existing, incoming, merge_equal_values=False) == [
        MergeOutcome.LOSE
    ]
    assert native.merge_batch(existing, incoming, merge_equal_values=True) == [
        MergeOutcome.EQUAL_METADATA
    ]


def test_int_float_cross_comparison():
    # SQLite compares ints and reals numerically
    assert native.value_cmp_native(1, 1.5) < 0
    assert native.value_cmp_native(2.0, 2) == 0
    assert native.value_cmp_native(2**62, 1e10) > 0
    # exactness above 2^53: double conversion would collapse these
    from corrosion_tpu.core.crdt import value_cmp

    for a, b in [
        (2**53 + 1, float(2**53)),
        (2**53, float(2**53)),
        (-(2**53) - 1, -float(2**53)),
        (2**63 - 1, 9.3e18),
        (-(2**63), -9.3e18),
    ]:
        assert native.value_cmp_native(a, b) == value_cmp(a, b), (a, b)
        assert native.value_cmp_native(b, a) == value_cmp(b, a), (b, a)
