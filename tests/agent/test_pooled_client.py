"""Pooled multi-address failover client (VERDICT r1 item 9,
corro-client/src/lib.rs:400+): requests and subscription streams survive
killing the node they were attached to."""

import asyncio

from corrosion_tpu.api.client import PooledClient
from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.testing import Cluster


def test_kill_one_node_keeps_subscription_alive():
    async def body():
        cluster = Cluster(2, use_swim=False)
        await cluster.start()
        servers = []
        try:
            for agent in cluster.agents:
                srv = ApiServer(agent)
                await srv.start()
                servers.append(srv)
            pool = PooledClient([s.addr for s in servers])

            await pool.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "before"]]]
            )
            stream = pool.subscribe("SELECT id, text FROM tests")
            got = []
            done = asyncio.Event()

            async def consume():
                async for ev in stream:
                    if "row" in ev:
                        got.append(tuple(ev["row"][1]))
                    elif "change" in ev:
                        got.append(tuple(ev["change"][2]))
                    if any(r[1] == "after-kill" for r in got):
                        done.set()
                        return

            task = asyncio.create_task(consume())
            # wait for the initial snapshot row to arrive
            for _ in range(100):
                if got:
                    break
                await asyncio.sleep(0.05)
            assert got, "initial snapshot must arrive"

            # the stream attached to node 0 (pool starts there): kill it
            await servers[0].stop()
            await cluster.agents[0].stop()

            # a write through the pool must fail over to node 1...
            await pool.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [2, "after-kill"]]]
            )
            # ...and the subscription stream must fail over and deliver it
            await asyncio.wait_for(done.wait(), 15)
            assert stream.failovers >= 1
            assert any(r[1] == "after-kill" for r in got)
            task.cancel()
            stream.close()
        finally:
            for srv in servers[1:]:
                await srv.stop()
            await cluster.agents[1].stop()
            cluster.tmp.cleanup()

    asyncio.run(body())


def test_request_failover_rotates_addresses():
    async def body():
        cluster = Cluster(1, use_swim=False)
        await cluster.start()
        srv = ApiServer(cluster.agents[0])
        await srv.start()
        try:
            # first address is dead: requests must rotate to the live one
            pool = PooledClient(["127.0.0.1:1", srv.addr])
            await pool.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "x"]]]
            )
            rows = await pool.query("SELECT id FROM tests")
            assert rows == [[1]]
        finally:
            await srv.stop()
            await cluster.stop()

    asyncio.run(body())
