"""DNS bootstrap resolution (VERDICT r3 item 7; bootstrap.rs:14-150).

Covers: literal passthrough, hostname expansion to ALL address records,
self/family filtering, the in-db `__corro_members` fallback, sampling,
and — the reference's key behavior — RE-resolution on every announce
(rejoin picks up changed DNS answers)."""

import asyncio

import pytest

from corrosion_tpu.agent.bootstrap import (
    DEFAULT_GOSSIP_PORT,
    RANDOM_NODES_CHOICES,
    _is_literal,
    _split_entry,
    generate_bootstrap,
    resolve_bootstrap,
)


def fake_resolver(table):
    calls = []

    async def resolve(host):
        calls.append(host)
        return table.get(host, [])

    resolve.calls = calls
    return resolve


# -- entry parsing ----------------------------------------------------------


def test_entry_forms():
    assert _split_entry("host") == ("host", DEFAULT_GOSSIP_PORT, None)
    assert _split_entry("host:9999") == ("host", 9999, None)
    assert _split_entry("host:9999@10.0.0.2") == ("host", 9999, "10.0.0.2")
    assert _split_entry("host@10.0.0.2") == ("host", DEFAULT_GOSSIP_PORT, "10.0.0.2")
    assert _is_literal("1.2.3.4:8787")
    assert not _is_literal("gossip.svc:8787")
    assert not _is_literal("gossip.svc")
    assert not _is_literal("1.2.3.4")  # ip without port still resolves? no — not literal form


def test_literal_passthrough_and_self_filter():
    async def run():
        return await resolve_bootstrap(
            ["1.2.3.4:8787", "5.6.7.8:9999", "1.1.1.1:1111"],
            our_addr="1.1.1.1:1111",
            resolver=fake_resolver({}),
        )

    addrs = asyncio.run(run())
    assert addrs == {"1.2.3.4:8787", "5.6.7.8:9999"}


def test_hostname_expands_to_all_records():
    r = fake_resolver({"gossip.svc": ["10.0.0.1", "10.0.0.2", "10.0.0.3"]})

    async def run():
        return await resolve_bootstrap(
            ["gossip.svc:9000"], our_addr="10.0.0.9:9000", resolver=r
        )

    addrs = asyncio.run(run())
    assert addrs == {"10.0.0.1:9000", "10.0.0.2:9000", "10.0.0.3:9000"}
    assert r.calls == ["gossip.svc"]


def test_default_port_and_family_filter():
    r = fake_resolver({"svc": ["10.0.0.1", "fd00::1"]})

    async def run():
        return await resolve_bootstrap(["svc"], our_addr="10.0.0.9:8787", resolver=r)

    addrs = asyncio.run(run())
    # AAAA answer dropped for a v4 node (bootstrap.rs:124-133)
    assert addrs == {f"10.0.0.1:{DEFAULT_GOSSIP_PORT}"}


def test_resolved_self_dropped():
    r = fake_resolver({"svc": ["10.0.0.9", "10.0.0.1"]})

    async def run():
        return await resolve_bootstrap(
            ["svc:8787"], our_addr="10.0.0.9:8787", resolver=r
        )

    assert asyncio.run(run()) == {"10.0.0.1:8787"}


# -- generate_bootstrap ------------------------------------------------------


class _FakeStore:
    def __init__(self, addresses):
        import sqlite3

        self.conn = sqlite3.connect(":memory:")
        self.conn.execute(
            "CREATE TABLE __corro_members "
            "(actor_id TEXT, address TEXT, foca_state TEXT)"
        )
        self.conn.executemany(
            "INSERT INTO __corro_members VALUES (?, ?, '{}')",
            [(f"a{i}", a) for i, a in enumerate(addresses)],
        )


def test_db_fallback_when_resolution_empty():
    store = _FakeStore(["10.0.0.1:8787", "10.0.0.2:8787", "10.0.0.9:8787"])

    async def run():
        return await generate_bootstrap(
            ["gone.svc"], our_addr="10.0.0.9:8787", store=store,
            resolver=fake_resolver({}),
        )

    got = set(asyncio.run(run()))
    # own address filtered; the two known peers come back
    assert got == {"10.0.0.1:8787", "10.0.0.2:8787"}


def test_sampling_cap():
    table = {"svc": [f"10.0.1.{i}" for i in range(1, 40)]}

    async def run():
        return await generate_bootstrap(
            ["svc:8787"], our_addr="10.0.0.9:8787",
            resolver=fake_resolver(table),
        )

    got = asyncio.run(run())
    assert len(got) == RANDOM_NODES_CHOICES
    assert len(set(got)) == RANDOM_NODES_CHOICES


# -- announce re-resolution (the rejoin seam) --------------------------------


def test_announce_reresolves_dns(monkeypatch):
    """Every SWIM announce re-resolves the bootstrap names, so a rejoin
    after DNS answers changed targets the NEW addresses
    (bootstrap.rs re-resolved per generate_bootstrap call)."""
    from corrosion_tpu.agent.agent import Agent
    from corrosion_tpu.agent.config import Config
    from corrosion_tpu.agent.transport import MemoryNetwork

    async def run():
        net = MemoryNetwork()
        cfg = Config(
            db_path=":memory:", gossip_addr="node0",
            bootstrap=["gossip.svc:8787"], use_swim=True,
        )
        agent = Agent(cfg, net.transport("node0"))

        table = {"gossip.svc": ["10.0.0.1"]}
        r = fake_resolver(table)
        sent = []

        await agent.start()
        try:
            rt = agent.swim
            rt.resolver = r

            async def spy_send(addr, msg):
                sent.append((addr, msg["k"]))

            rt._send = spy_send
            await rt._announce()
            assert ("10.0.0.1:8787", "join") in sent

            # DNS answer changes; the next announce targets the new addr
            table["gossip.svc"] = ["10.0.0.2"]
            sent.clear()
            await rt._announce()
            assert ("10.0.0.2:8787", "join") in sent
            assert r.calls.count("gossip.svc") == 2
        finally:
            await agent.stop()

    asyncio.run(run())
