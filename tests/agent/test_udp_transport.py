"""Real-socket transport: 2 agents over UDP/TCP on loopback converge
(the devcluster-style tier without spawning processes)."""

import asyncio
import tempfile

from corrosion_tpu.agent.agent import Agent
from corrosion_tpu.agent.config import Config
from corrosion_tpu.agent.transport import UdpTcpTransport
from corrosion_tpu.testing import TEST_SCHEMA, fast_perf


def test_two_agents_over_sockets():
    async def body():
        with tempfile.TemporaryDirectory() as tmp:
            transports = [UdpTcpTransport(), UdpTcpTransport()]
            addrs = [await t.start() for t in transports]
            agents = []
            for i, t in enumerate(transports):
                cfg = Config(
                    db_path=f"{tmp}/n{i}.db",
                    gossip_addr=addrs[i],
                    bootstrap=[a for a in addrs if a != addrs[i]],
                    perf=fast_perf(),
                )
                agent = Agent(cfg, t)
                agent.store.execute_schema(TEST_SCHEMA)
                agents.append(agent)
            for a in agents:
                await a.start()
            try:
                agents[0].exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (1, 'sock')", ())]
                )
                rows = []
                for _ in range(200):
                    rows = agents[1].store.query("SELECT id, text FROM tests")
                    if rows:
                        break
                    await asyncio.sleep(0.05)
                assert [tuple(r) for r in rows] == [(1, "sock")]
            finally:
                for a in agents:
                    await a.stop()

    asyncio.run(body())
