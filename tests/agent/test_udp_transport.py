"""Real-socket transport: 2 agents over UDP/TCP on loopback converge
(the devcluster-style tier without spawning processes)."""

import asyncio
import tempfile

import pytest

from corrosion_tpu.agent.agent import Agent
from corrosion_tpu.agent.config import Config
from corrosion_tpu.agent.transport import UdpTcpTransport
from corrosion_tpu.testing import TEST_SCHEMA, fast_perf


def test_two_agents_over_sockets():
    async def body():
        with tempfile.TemporaryDirectory() as tmp:
            transports = [UdpTcpTransport(), UdpTcpTransport()]
            addrs = [await t.start() for t in transports]
            agents = []
            for i, t in enumerate(transports):
                cfg = Config(
                    db_path=f"{tmp}/n{i}.db",
                    gossip_addr=addrs[i],
                    bootstrap=[a for a in addrs if a != addrs[i]],
                    perf=fast_perf(),
                )
                agent = Agent(cfg, t)
                agent.store.execute_schema(TEST_SCHEMA)
                agents.append(agent)
            for a in agents:
                await a.start()
            try:
                agents[0].exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (1, 'sock')", ())]
                )
                rows = []
                for _ in range(200):
                    rows = agents[1].store.query("SELECT id, text FROM tests")
                    if rows:
                        break
                    await asyncio.sleep(0.05)
                assert [tuple(r) for r in rows] == [(1, "sock")]
            finally:
                for a in agents:
                    await a.stop()

    asyncio.run(body())


def test_uni_connection_cache_reuses_conns():
    """VERDICT r1 item 5: broadcast frames must multiplex over a cached
    per-peer connection — connections opened ≪ frames sent (the QUIC conn
    cache analog, transport.rs:55-70,200-233)."""

    async def body():
        a, b = UdpTcpTransport(), UdpTcpTransport()
        got = []

        async def on_uni(peer, data):
            got.append(data)

        for t in (a, b):
            t.set_handlers(None, on_uni, None)
        addr_a = await a.start()
        addr_b = await b.start()
        try:
            for i in range(50):
                await a.send_uni(addr_b, b"frame-%d" % i)
            for _ in range(100):
                if len(got) == 50:
                    break
                await asyncio.sleep(0.02)
            assert len(got) == 50
            assert a.conns_opened == 1, a.conns_opened
            assert b.server_conns_accepted == 1, b.server_conns_accepted

            # liveness + reconnect: kill the cached conn server-side by
            # restarting the receiver; the sender must transparently
            # reconnect (one more conn), not fail
            a._evict(addr_b)
            await a.send_uni(addr_b, b"after-evict")
            for _ in range(100):
                if len(got) == 51:
                    break
                await asyncio.sleep(0.02)
            assert len(got) == 51
            assert a.conns_opened == 2
        finally:
            await a.close()
            await b.close()

    asyncio.run(body())


def test_open_bi_rechecks_faults_after_dial():
    """A FaultInjector installed WHILE a bi dial is suspended inside
    _connect must still block the stream: the socket is in no sever
    list at install time and bi streams are never fault-checked per
    frame, so without the post-dial re-check one racing sync session
    replicates straight across a fresh partition (the
    test_partition_heal_on_real_sockets full-suite flake)."""

    async def body():
        from corrosion_tpu.agent.transport import FaultInjector

        a, b = UdpTcpTransport(), UdpTcpTransport()
        for t in (a, b):
            t.set_handlers(None, None, None)
        await a.start()
        addr_b = await b.start()
        try:
            orig_connect = a._connect
            fi = FaultInjector()
            fi.partition(addr_b)

            async def racing_connect(addr):
                reader, writer = await orig_connect(addr)
                # the injector lands exactly between the dial completing
                # and open_bi registering/using the stream
                a.install_faults(fi)
                return reader, writer

            a._connect = racing_connect
            try:
                import pytest

                with pytest.raises(ConnectionError):
                    await a.open_bi(addr_b)
            finally:
                a._connect = orig_connect
        finally:
            await a.close()
            await b.close()

    asyncio.run(body())


def test_rtt_callback_sampled():
    async def body():
        samples = []
        a = UdpTcpTransport(on_rtt=lambda addr, rtt: samples.append((addr, rtt)))
        b = UdpTcpTransport()
        b.set_handlers(None, None, None)
        await a.start()
        addr_b = await b.start()
        try:
            await a.send_uni(addr_b, b"x")
            bi = await a.open_bi(addr_b)
            bi.close()
            assert len(samples) >= 2
            assert all(addr == addr_b and rtt >= 0 for addr, rtt in samples)
        finally:
            await a.close()
            await b.close()

    asyncio.run(body())


def test_mtls_cluster_converges_and_encrypts_datagrams():
    """Two agents over mutual TLS: gossip converges, SWIM datagrams ride
    the encrypted stream, and an un-certified client is rejected
    (api/peer/mod.rs:149-339)."""
    pytest.importorskip("cryptography")  # cert generation needs it
    from corrosion_tpu.agent.transport import transport_from_config
    from corrosion_tpu.utils import tls as tlsmod

    async def body(tmp):
        ca_cert, ca_key = tlsmod.generate_ca(f"{tmp}/tls")
        srv_cert, srv_key = tlsmod.generate_server_cert(
            ca_cert, ca_key, "127.0.0.1", f"{tmp}/tls"
        )
        cli_cert, cli_key = tlsmod.generate_client_cert(ca_cert, ca_key, f"{tmp}/tls")
        tls_section = {
            "cert_file": srv_cert,
            "key_file": srv_key,
            "ca_file": ca_cert,
            "client": {
                "cert_file": cli_cert,
                "key_file": cli_key,
                "required": True,
            },
        }
        cfgs, transports, agents = [], [], []
        for i in range(2):
            cfg = Config(
                db_path=f"{tmp}/n{i}.db",
                gossip_addr="127.0.0.1:0",
                gossip_tls=tls_section,
                perf=fast_perf(),
            )
            t = transport_from_config(cfg)
            cfg.gossip_addr = await t.start()
            cfgs.append(cfg)
            transports.append(t)
        for i, (cfg, t) in enumerate(zip(cfgs, transports)):
            cfg.bootstrap = [c.gossip_addr for c in cfgs if c is not cfg]
            agent = Agent(cfg, t)
            agent.store.execute_schema(TEST_SCHEMA)
            agents.append(agent)
        for a in agents:
            await a.start()
        try:
            assert transports[0].tls
            agents[0].exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (1, 'tls')", ())]
            )
            rows = []
            for _ in range(200):
                rows = agents[1].store.query("SELECT id, text FROM tests")
                if rows:
                    break
                await asyncio.sleep(0.05)
            assert [tuple(r) for r in rows] == [(1, "tls")]
            # SWIM datagrams rode the TLS stream, not bare UDP
            assert agents[1].members.states, "membership must have formed"

            # a TLS client WITHOUT a client cert must be rejected (with
            # TLS 1.3 the certificate-required alert surfaces on the
            # first post-handshake read)
            import ssl

            host, _, port = cfgs[0].gossip_addr.rpartition(":")
            rejected = False
            try:
                r, w = await asyncio.open_connection(
                    host,
                    int(port),
                    ssl=tlsmod.client_ssl_context(ca_cert),
                    server_hostname=host,
                )
                w.write(b"u")
                await w.drain()
                data = await asyncio.wait_for(r.read(1), 5)
                rejected = data == b""  # server aborted: EOF
                w.close()
            except (ConnectionError, OSError, ssl.SSLError):
                rejected = True
            assert rejected, "un-certified client must not stay connected"
        finally:
            for a in agents:
                await a.stop()

    def run():
        with tempfile.TemporaryDirectory() as tmp:
            asyncio.run(body(tmp))

    run()


def test_path_stats_surface_in_metrics():
    """Transport path statistics (VERDICT r3 missing #4,
    transport.rs:235-419): frames/bytes counted per peer, rolled up into
    the Prometheus scrape."""

    async def body():
        with tempfile.TemporaryDirectory() as tmp:
            transports = [UdpTcpTransport(), UdpTcpTransport()]
            addrs = [await t.start() for t in transports]
            agents = []
            for i, t in enumerate(transports):
                cfg = Config(
                    db_path=f"{tmp}/n{i}.db",
                    gossip_addr=addrs[i],
                    bootstrap=[a for a in addrs if a != addrs[i]],
                    perf=fast_perf(),
                )
                agent = Agent(cfg, t)
                agent.store.execute_schema(TEST_SCHEMA)
                agents.append(agent)
            for a in agents:
                await a.start()
            try:
                agents[0].exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (1, 'st')", ())]
                )
                for _ in range(200):
                    if agents[1].store.query("SELECT id FROM tests"):
                        break
                    await asyncio.sleep(0.05)

                st = transports[0].path_stats
                assert st, "sender recorded no path stats"
                agg_tx = sum(
                    p.frames_tx_uni + p.frames_tx_dgram for p in st.values()
                )
                assert agg_tx > 0
                assert sum(p.bytes_tx for p in st.values()) > 0
                assert sum(p.connects for p in st.values()) >= 1
                # receiver counted rx frames from the sender's addr
                rx = sum(
                    p.frames_rx_uni + p.frames_rx_dgram
                    for p in transports[1].path_stats.values()
                )
                assert rx > 0

                text = transports[0].path_samples()
                assert "corro_transport_connections" in text
                assert 'corro_transport_frames_tx{type="uni"}' in text
                assert "corro_transport_path_peer_bytes_tx" in text

                # and through the scrape endpoint
                from corrosion_tpu.metrics import MetricsServer

                srv = MetricsServer(agents[0])
                out = srv._agent_live_samples()
                assert "corro_transport_path_bytes_tx" in out
            finally:
                for a in agents:
                    await a.stop()

    asyncio.run(body())


def _tls_section(tmp):
    from corrosion_tpu.utils import tls as tlsmod

    ca_cert, ca_key = tlsmod.generate_ca(f"{tmp}/tls")
    srv_cert, srv_key = tlsmod.generate_server_cert(
        ca_cert, ca_key, "127.0.0.1", f"{tmp}/tls"
    )
    cli_cert, cli_key = tlsmod.generate_client_cert(ca_cert, ca_key, f"{tmp}/tls")
    return {
        "cert_file": srv_cert,
        "key_file": srv_key,
        "ca_file": ca_cert,
        "client": {"cert_file": cli_cert, "key_file": cli_key, "required": True},
    }


async def _detection_latency(tmp, n, tls_section):
    """Boot n real-socket agents (TLS or plaintext), kill one hard, and
    return the wall seconds until every survivor marks it DOWN."""
    import time as _time

    from corrosion_tpu.agent.swim import DOWN
    from corrosion_tpu.agent.transport import transport_from_config

    cfgs, transports, agents = [], [], []
    for i in range(n):
        cfg = Config(
            db_path=f"{tmp}/n{i}.db",
            gossip_addr="127.0.0.1:0",
            gossip_tls=tls_section,
            perf=fast_perf(),
        )
        t = transport_from_config(cfg)
        cfg.gossip_addr = await t.start()
        cfgs.append(cfg)
        transports.append(t)
    for cfg, t in zip(cfgs, transports):
        cfg.bootstrap = [c.gossip_addr for c in cfgs if c is not cfg]
        agent = Agent(cfg, t)
        agent.store.execute_schema(TEST_SCHEMA)
        agents.append(agent)
    for a in agents:
        await a.start()
    try:
        # full membership first, so detection is probe-driven, not join noise
        for _ in range(400):
            if all(len(a.members) == n - 1 for a in agents):
                break
            await asyncio.sleep(0.05)
        assert all(len(a.members) == n - 1 for a in agents)
        victim = agents[-1]
        victim_id = victim.actor_id
        await victim.stop()
        survivors = agents[:-1]
        t0 = _time.monotonic()
        deadline = t0 + 30.0
        while _time.monotonic() < deadline:
            if all(
                s.swim.members.get(victim_id) is not None
                and s.swim.members[victim_id].status == DOWN
                for s in survivors
            ):
                return _time.monotonic() - t0
            await asyncio.sleep(0.02)
        raise AssertionError("victim never detected DOWN")
    finally:
        for a in agents[:-1]:
            await a.stop()


def test_swim_detection_latency_tls_within_bounded_factor_of_udp():
    """VERDICT r4 missing #4: with TLS on, SWIM datagrams multiplex over
    the TCP uni stream (transport.py KIND_DGRAM) — head-of-line blocking
    changes failure-detector timing vs the reference's QUIC datagrams
    (transport.rs:79-104).  Pin the deviation: detection latency at the
    8-node tier must stay within a bounded factor of plaintext-UDP mode
    (doc/transport.md 'SWIM under TLS')."""
    pytest.importorskip("cryptography")  # cert generation needs it

    async def body(tmp):
        import os

        os.makedirs(f"{tmp}/udp")
        os.makedirs(f"{tmp}/tls8")
        udp = await _detection_latency(f"{tmp}/udp", 8, None)
        tls = await _detection_latency(f"{tmp}/tls8", 8, _tls_section(tmp))
        # generous bound: TCP multiplexing may cost conn setup + HoL
        # blocking, but the detector must stay the same order of
        # magnitude (a stream wedge would blow past this)
        assert tls <= max(4.0 * udp, 6.0), (udp, tls)

    with tempfile.TemporaryDirectory() as tmp:
        asyncio.run(body(tmp))
