"""Batched (native) apply path vs sequential path: identical outcomes on a
mixed workload including duplicates, conflicts, deletes, and resurrections."""

import random

from corrosion_tpu.agent.store import CrrStore
from corrosion_tpu.core.types import ActorId, Change, DELETE_SENTINEL

SCHEMA = """
CREATE TABLE t (
    id INTEGER PRIMARY KEY NOT NULL,
    a TEXT NOT NULL DEFAULT '',
    b INTEGER NOT NULL DEFAULT 0
);
"""


def make_workload(seed=0):
    rng = random.Random(seed)
    writer = CrrStore(":memory:", ActorId.random())
    writer.execute_schema(SCHEMA)
    versions = []
    for v in range(12):
        stmts = []
        for _ in range(rng.randint(1, 30)):
            rid = rng.randint(1, 40)
            op = rng.random()
            if op < 0.6:
                stmts.append(
                    ("INSERT INTO t (id, a, b) VALUES (?, ?, ?) "
                     "ON CONFLICT (id) DO UPDATE SET a = excluded.a, b = excluded.b",
                     (rid, f"v{v}r{rid}", rng.randint(0, 99)))
                )
            elif op < 0.8:
                stmts.append(("UPDATE t SET b = ? WHERE id = ?", (rng.randint(0, 99), rid)))
            else:
                stmts.append(("DELETE FROM t WHERE id = ?", (rid,)))
        _, info = writer.transact(stmts)
        if info:
            versions.append(info.db_version)
    changes = []
    for v in versions:
        changes.extend(writer.changes_for_version(writer.site_id, v))
    writer.close()
    return changes


def snapshot(store):
    rows = [tuple(r) for r in store.query("SELECT id, a, b FROM t ORDER BY id")]
    clock = [
        tuple(r)
        for r in store.query(
            'SELECT pk, cid, val, col_version FROM "t__crdt_clock" ORDER BY pk, cid'
        )
    ]
    return rows, clock


def test_batched_equals_sequential():
    changes = make_workload()
    assert len(changes) > 50

    a = CrrStore(":memory:", ActorId.random())
    a.execute_schema(SCHEMA)
    b = CrrStore(":memory:", ActorId.random())
    b.execute_schema(SCHEMA)

    # a: one big batch (native path); b: tiny batches (sequential path)
    impacted_a = a.apply_changes(changes)
    impacted_b = 0
    for i in range(0, len(changes), 3):
        impacted_b += b.apply_changes(changes[i : i + 3])

    assert snapshot(a) == snapshot(b)
    assert impacted_a == impacted_b
    a.close()
    b.close()


def test_batched_idempotent_redelivery():
    changes = make_workload(seed=2)
    s = CrrStore(":memory:", ActorId.random())
    s.execute_schema(SCHEMA)
    s.apply_changes(changes)
    before = snapshot(s)
    assert s.apply_changes(changes) == 0
    assert snapshot(s) == before
    s.close()
