"""Batched (native) apply path vs sequential path: identical outcomes on a
mixed workload including duplicates, conflicts, deletes, and resurrections."""

import random

from corrosion_tpu.agent.store import CrrStore
from corrosion_tpu.core.types import ActorId, Change, DELETE_SENTINEL

SCHEMA = """
CREATE TABLE t (
    id INTEGER PRIMARY KEY NOT NULL,
    a TEXT NOT NULL DEFAULT '',
    b INTEGER NOT NULL DEFAULT 0
);
"""


def make_workload(seed=0):
    rng = random.Random(seed)
    writer = CrrStore(":memory:", ActorId.random())
    writer.execute_schema(SCHEMA)
    versions = []
    for v in range(12):
        stmts = []
        for _ in range(rng.randint(1, 30)):
            rid = rng.randint(1, 40)
            op = rng.random()
            if op < 0.6:
                stmts.append(
                    ("INSERT INTO t (id, a, b) VALUES (?, ?, ?) "
                     "ON CONFLICT (id) DO UPDATE SET a = excluded.a, b = excluded.b",
                     (rid, f"v{v}r{rid}", rng.randint(0, 99)))
                )
            elif op < 0.8:
                stmts.append(("UPDATE t SET b = ? WHERE id = ?", (rng.randint(0, 99), rid)))
            else:
                stmts.append(("DELETE FROM t WHERE id = ?", (rid,)))
        _, info = writer.transact(stmts)
        if info:
            versions.append(info.db_version)
    changes = []
    for v in versions:
        changes.extend(writer.changes_for_version(writer.site_id, v))
    writer.close()
    return changes


def snapshot(store):
    rows = [tuple(r) for r in store.query("SELECT id, a, b FROM t ORDER BY id")]
    clock = [
        tuple(r)
        for r in store.query(
            'SELECT pk, cid, val, col_version FROM "t__crdt_clock" ORDER BY pk, cid'
        )
    ]
    return rows, clock


def test_batched_equals_sequential():
    changes = make_workload()
    assert len(changes) > 50

    a = CrrStore(":memory:", ActorId.random())
    a.execute_schema(SCHEMA)
    b = CrrStore(":memory:", ActorId.random())
    b.execute_schema(SCHEMA)

    # a: one big batch (native path); b: tiny batches (sequential path)
    impacted_a = a.apply_changes(changes)
    impacted_b = 0
    for i in range(0, len(changes), 3):
        impacted_b += b.apply_changes(changes[i : i + 3])

    assert snapshot(a) == snapshot(b)
    assert impacted_a == impacted_b
    a.close()
    b.close()


def test_batched_mixed_causal_lengths_in_one_batch():
    """Regression: a batch mixing changes at different causal lengths for the
    same (locally unknown) pk must not fold them by col_version alone — the
    resurrected lifecycle (higher cl) wins even at lower col_version."""
    site_a, site_b = ActorId(b"\x01" * 16), ActorId(b"\x02" * 16)
    stale = Change(table="t", pk=b"\x01\x01" + b"\x00" * 7 + b"\x07", cid="a",
                   val="old", col_version=5, db_version=1, seq=0,
                   site_id=site_a, cl=1)
    fresh = Change(table="t", pk=stale.pk, cid="a",
                   val="new", col_version=1, db_version=3, seq=0,
                   site_id=site_b, cl=3)
    # pad the batch over the native-path threshold with unrelated rows
    pad = [
        Change(table="t", pk=b"\x01\x01" + b"\x00" * 7 + bytes([100 + i]),
               cid="a", val=f"p{i}", col_version=1, db_version=2, seq=i,
               site_id=site_a, cl=1)
        for i in range(20)
    ]
    for batch in ([stale, fresh] + pad, [fresh, stale] + pad):
        s = CrrStore(":memory:", ActorId.random())
        s.execute_schema(SCHEMA)
        s.apply_changes(batch)
        row = s.query('SELECT val, col_version FROM "t__crdt_clock" '
                      "WHERE pk = ? AND cid = 'a'", (stale.pk,))[0]
        cl = s.query('SELECT cl FROM "t__crdt_rows" WHERE pk = ?', (stale.pk,))[0][0]
        assert (row[0], row[1], cl) == ("new", 1, 3), (tuple(row), cl)
        s.close()


def test_batched_idempotent_redelivery():
    changes = make_workload(seed=2)
    s = CrrStore(":memory:", ActorId.random())
    s.execute_schema(SCHEMA)
    s.apply_changes(changes)
    before = snapshot(s)
    assert s.apply_changes(changes) == 0
    assert snapshot(s) == before
    s.close()


def test_seen_cache_ttl_and_cap():
    """VERDICT r1 weak #6: the dedup cache is TTL'd and sized to the
    queue-cap envelope — an expired key is re-admitted (idempotent apply
    re-checks bookkeeping), and the cache never exceeds its cap."""
    import asyncio
    import tempfile

    from corrosion_tpu.agent.agent import Agent
    from corrosion_tpu.agent.config import Config
    from corrosion_tpu.agent.transport import MemoryNetwork
    from corrosion_tpu.core.types import ActorId, Change, Changeset, ChangesetPart, ChangeSource
    from corrosion_tpu.testing import TEST_SCHEMA, fast_perf

    async def body():
        net = MemoryNetwork()
        cfg = Config(db_path=":memory:", gossip_addr="a", use_swim=False,
                     perf=fast_perf())
        cfg.perf.seen_cache_cap = 8
        cfg.perf.seen_cache_ttl_s = 0.05
        agent = Agent(cfg, net.transport("a"))
        agent.store.execute_schema(TEST_SCHEMA)
        actor = ActorId(bytes([9] * 16))

        def cs(v):
            ch = Change(table="tests", pk=b"\x01", cid="text", val=f"v{v}",
                        col_version=1, db_version=v, seq=0,
                        site_id=actor, cl=1)
            return Changeset(actor_id=actor, version=v, changes=(ch,),
                             seqs=(0, 0), last_seq=0, part=ChangesetPart.FULL)

        # cap: 20 distinct keys, cache holds at most 8
        for v in range(1, 21):
            await agent._enqueue_changeset(cs(v), ChangeSource.BROADCAST)
        assert len(agent._seen) <= 8

        # TTL: a fresh duplicate is deduped; an expired one is re-admitted
        # (the idempotent apply path / bookkeeping re-check absorbs it)
        before = agent.stats["changes_deduped"]
        await agent._enqueue_changeset(cs(20), ChangeSource.BROADCAST)
        assert agent.stats["changes_deduped"] == before + 1
        await asyncio.sleep(0.08)  # expire
        q_before = agent._ingest_q.qsize()
        d_before = agent.stats["changes_deduped"]
        await agent._enqueue_changeset(cs(20), ChangeSource.BROADCAST)
        # nothing was applied yet (no ingest loop running), so the bookie
        # check can't dedup it either: the expired key MUST re-enqueue
        assert agent._ingest_q.qsize() == q_before + 1
        assert agent.stats["changes_deduped"] == d_before
        assert len(agent._seen) <= 8
        agent.store.close()

    asyncio.run(body())
