"""Analogs of reference agent tests not yet mirrored (SURVEY §4.2):

- ``process_failed_changes`` (agent/tests.rs:878-1000) — a malformed
  changeset must not poison the rest of the apply batch;
- ``test_sync_changes_order`` (api/peer/mod.rs:1678-1727) — sync serves
  newest version FIRST;
- ``test_clear_empty_versions`` (agent/tests.rs:778-876) — versions
  emptied by overwrites sync as Cleared/EMPTY runs and the puller
  converges through them.
"""

import asyncio

import pytest

from corrosion_tpu.agent.agent import ChangeSource
from corrosion_tpu.agent.codec import decode_message
from corrosion_tpu.agent.store import CrrStore
from corrosion_tpu.agent.transport import LinkModel
from corrosion_tpu.core.bookkeeping import RangeSet
from corrosion_tpu.core.types import ActorId, Change, Changeset, ChangesetPart
from corrosion_tpu.testing import TEST_SCHEMA, Cluster


def _writer_changes(n_versions: int, rows_per_version: int = 1):
    """A scratch origin store: n versions of the tests table, each
    committing ``rows_per_version`` rows (seqs 0..rows_per_version-1)."""
    writer = CrrStore(":memory:", ActorId.random())
    writer.execute_schema(TEST_SCHEMA)
    versions = []
    for i in range(1, n_versions + 1):
        _, info = writer.transact([
            ("INSERT INTO tests (id, text) VALUES (?, ?)",
             (i * rows_per_version + r, f"v{i}r{r}"))
            for r in range(rows_per_version)
        ])
        versions.append(info.db_version)
    out = {
        v: writer.changes_for_version(writer.site_id, v) for v in versions
    }
    actor = writer.site_id
    writer.close()
    return actor, out


async def _wait_until(cond, timeout_s: float = 5.0):
    """Poll the ASSERTED condition (not a queue-size proxy) so tests
    stay correct if the apply lane ever gains suspension points."""
    deadline = asyncio.get_event_loop().time() + timeout_s
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.02)
    return cond()


def test_process_failed_changes():
    """Good versions around a malformed one (a column the schema lacks)
    still apply; the bad version is skipped, never recorded, and the
    agent keeps serving (per-version savepoint isolation)."""

    async def body():
        cluster = Cluster(1, use_swim=False)
        await cluster.start()
        try:
            agent = cluster.agents[0]
            actor, by_version = _writer_changes(5)

            bad = Change(
                table="tests", pk=by_version[6 - 5][0].pk,  # any valid pk blob
                cid="nonexistent", val="six", col_version=1,
                db_version=6, seq=0, site_id=actor, cl=1,
            )
            batch = []
            for v, changes in by_version.items():
                last_seq = max(ch.seq for ch in changes)
                batch.append(Changeset(
                    actor_id=actor, version=v, changes=tuple(changes),
                    seqs=(0, last_seq), last_seq=last_seq,
                    part=ChangesetPart.FULL,
                ))
            # malformed version 6, sandwiched into the same batch
            batch.insert(2, Changeset(
                actor_id=actor, version=6, changes=(bad,),
                seqs=(0, 0), last_seq=0, part=ChangesetPart.FULL,
            ))
            for cs in batch:
                await agent._enqueue_changeset(cs, ChangeSource.SYNC)

            async def applied():
                rows = agent.store.query("SELECT id FROM tests ORDER BY id")
                return [r[0] for r in rows] == [1, 2, 3, 4, 5]

            for _ in range(100):
                if await applied():
                    break
                await asyncio.sleep(0.05)
            assert await applied(), agent.store.query("SELECT id FROM tests")
            assert agent.stats["changes_failed"] >= 1
            # the failed version is NOT recorded as known — anti-entropy
            # may re-request it later
            booked = agent.bookie.for_actor(actor)
            assert not booked.contains_all(
                (6, 6), None
            ), "failed version must stay unknown"
            # ...and versions 1..5 are all known
            assert booked.contains_all((1, 5), None)
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_failed_buffered_version_does_not_swallow_batch():
    """A malformed version arriving CHUNKED (buffered, then applied at
    completion) must not blow up the lane or suppress subscriptions for
    the batch's healthy changes."""

    async def body():
        cluster = Cluster(1, use_swim=False)
        await cluster.start()
        try:
            agent = cluster.agents[0]
            actor, by_version = _writer_changes(2)

            bad = Change(
                table="tests", pk=by_version[1][0].pk, cid="nonexistent",
                val="x", col_version=1, db_version=3, seq=0,
                site_id=actor, cl=1,
            )
            bad2 = Change(
                table="tests", pk=by_version[1][0].pk, cid="nonexistent",
                val="y", col_version=1, db_version=3, seq=1,
                site_id=actor, cl=1,
            )
            # two chunks of malformed version 3, then a good version
            await agent._enqueue_changeset(Changeset(
                actor_id=actor, version=3, changes=(bad,),
                seqs=(0, 0), last_seq=1, part=ChangesetPart.FULL,
            ), ChangeSource.SYNC)
            await agent._enqueue_changeset(Changeset(
                actor_id=actor, version=3, changes=(bad2,),
                seqs=(1, 1), last_seq=1, part=ChangesetPart.FULL,
            ), ChangeSource.SYNC)
            for v, changes in by_version.items():
                last_seq = max(ch.seq for ch in changes)
                await agent._enqueue_changeset(Changeset(
                    actor_id=actor, version=v, changes=tuple(changes),
                    seqs=(0, last_seq), last_seq=last_seq,
                    part=ChangesetPart.FULL,
                ), ChangeSource.SYNC)

            async def applied():
                rows = agent.store.query("SELECT id FROM tests ORDER BY id")
                return [r[0] for r in rows] == [1, 2]

            for _ in range(100):
                if await applied():
                    break
                await asyncio.sleep(0.05)
            assert await applied()
            assert agent.stats["changes_failed"] >= 1
            assert (actor, 3) in agent._buffered_retry

            # live migration repairs the schema → the buffered-retry
            # loop (apply_fully_buffered_changes_loop analog) heals the
            # wedged version without any re-delivery
            agent.store.execute_schema(
                TEST_SCHEMA.replace(
                    "text TEXT NOT NULL DEFAULT ''\n);",
                    "text TEXT NOT NULL DEFAULT '',\n"
                    "    nonexistent TEXT\n);",
                    1,
                )
            )
            for _ in range(80):
                if (actor, 3) not in agent._buffered_retry:
                    break
                await asyncio.sleep(0.1)
            assert (actor, 3) not in agent._buffered_retry, (
                "retry loop never healed the repaired version"
            )
            row = agent.store.query(
                "SELECT nonexistent FROM tests WHERE id = 1"
            )
            assert row and row[0][0] == "y"  # seq 1 won LWW over seq 0
        finally:
            await cluster.stop()

    asyncio.run(body())


class _CaptureSender:
    """AdaptiveSender stand-in recording decoded changesets."""

    def __init__(self):
        self.chunk_size = 8 * 1024
        self.messages = []

    async def send(self, _bi, frame: bytes):
        kind, payload, _ = decode_message(frame)
        self.messages.append((kind, payload))


def test_sync_changes_order_newest_first():
    """The serve path must stream newest versions first
    (test_sync_changes_order, peer/mod.rs:1678-1727): fresh state lands
    before a cold peer's backfill."""

    async def body():
        cluster = Cluster(1, use_swim=False)
        await cluster.start()
        try:
            agent = cluster.agents[0]
            for i in range(1, 8):
                agent.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)",
                      (i, f"v{i}"))]
                )
            from corrosion_tpu.agent.agent import SyncNeed

            cap = _CaptureSender()
            await agent._serve_need(
                None, agent.actor_id,
                SyncNeed(kind="full", versions=(1, 7)), sender=cap,
            )
            versions = [
                p["v"] for k, p in cap.messages if k == "changeset"
            ]
            assert versions == sorted(versions, reverse=True), versions
            assert len(versions) == 7
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_process_multiple_changes_bookkeeping():
    """test_process_multiple_changes (tests.rs:1002-1180): staged
    out-of-order deliveries must leave EXACTLY the right bookkeeping —
    known runs, needed gaps, partial seq coverage, and EMPTY-run
    recording for non-contiguous cleared versions."""

    async def body():
        cluster = Cluster(1, use_swim=False)
        await cluster.start()
        try:
            agent = cluster.agents[0]
            # 20 versions, TWO changes each (seqs 0,1) so partials can
            # split mid-version
            actor, by_version = _writer_changes(20, rows_per_version=2)

            def full_cs(v, seq_filter=None):
                changes = by_version[v]
                last_seq = max(ch.seq for ch in changes)
                if seq_filter is not None:
                    changes = [ch for ch in changes if ch.seq in seq_filter]
                    seqs = (min(seq_filter), max(seq_filter))
                else:
                    seqs = (0, last_seq)
                return Changeset(
                    actor_id=actor, version=v, changes=tuple(changes),
                    seqs=seqs, last_seq=last_seq, part=ChangesetPart.FULL,
                )

            async def deliver(*css):
                for cs in css:
                    await agent._enqueue_changeset(cs, ChangeSource.SYNC)

            booked = agent.bookie.for_actor(actor)

            # stage 1: versions 1-5 contiguous
            await deliver(*[full_cs(v) for v in range(1, 6)])
            assert await _wait_until(
                lambda: booked.contains_all((1, 5), None)
            )
            assert list(booked.needed()) == []

            # stage 2: versions 9-10 → gap 6-8
            await deliver(full_cs(9), full_cs(10))
            assert await _wait_until(
                lambda: list(booked.needed()) == [(6, 8)]
            ), list(booked.needed())

            # stage 3: version 20 + partial 15-16 (seq 0 only)
            await deliver(
                full_cs(20), full_cs(15, {0}), full_cs(16, {0})
            )
            assert await _wait_until(
                lambda: list(booked.needed())
                == [(6, 8), (11, 14), (17, 19)]
            ), list(booked.needed())
            for v in (15, 16):
                p = booked.partials.get(v)
                assert p is not None and not p.is_complete(), (v, p)
                assert list(p.seqs) == [(0, 0)]

            # stage 4: EMPTY (cleared) runs arrive non-contiguously
            await deliver(
                Changeset(actor_id=actor, version=22, versions_hi=22,
                          part=ChangesetPart.EMPTY),
                Changeset(actor_id=actor, version=25, versions_hi=25,
                          part=ChangesetPart.EMPTY),
            )
            assert await _wait_until(
                lambda: booked.contains_all((22, 22), None)
                and booked.contains_all((25, 25), None)
            )
            assert list(booked.needed()) == [
                (6, 8), (11, 14), (17, 19), (21, 21), (23, 24)
            ]

            # completing the partials closes them out
            await deliver(full_cs(15, {1}), full_cs(16, {1}))
            assert await _wait_until(
                lambda: booked.partials.get(15) is None
                and booked.partials.get(16) is None
            )
            assert booked.contains_all((15, 16), None)
            rows = agent.store.query(
                "SELECT count(*) FROM tests WHERE id IN (30, 31, 32, 33)"
            )
            assert rows[0][0] == 4  # versions 15+16 fully applied

        finally:
            await cluster.stop()

    asyncio.run(body())


def test_wedged_buffered_version_heals_across_restart():
    """A fully-buffered version whose apply fails, followed by a
    RESTART: the retry ledger is memory-only but partial records +
    buffered rows are durable, so start() must reseed the retry loop
    from restored complete partials (run_root.rs:180-194) — otherwise
    the version wedges forever (it is recorded known; sync never
    re-requests)."""

    async def body():
        import tempfile

        from corrosion_tpu.agent.agent import Agent
        from corrosion_tpu.agent.config import Config
        from corrosion_tpu.agent.transport import MemoryNetwork
        from corrosion_tpu.testing import fast_perf

        tmp = tempfile.TemporaryDirectory()
        net = MemoryNetwork()
        cfg = Config(
            db_path=f"{tmp.name}/node.db", gossip_addr="node0",
            bootstrap=[], use_swim=False, perf=fast_perf(),
        )
        agent = Agent(cfg, net.transport("node0"))
        agent.store.execute_schema(TEST_SCHEMA)
        await agent.start()
        actor, by_version = _writer_changes(1)
        bad = Change(
            table="tests", pk=by_version[1][0].pk, cid="nonexistent",
            val="x", col_version=1, db_version=2, seq=0, site_id=actor, cl=1,
        )
        bad2 = Change(
            table="tests", pk=by_version[1][0].pk, cid="nonexistent",
            val="y", col_version=1, db_version=2, seq=1, site_id=actor, cl=1,
        )
        try:
            await agent._enqueue_changeset(Changeset(
                actor_id=actor, version=2, changes=(bad,),
                seqs=(0, 0), last_seq=1, part=ChangesetPart.FULL,
            ), ChangeSource.SYNC)
            await agent._enqueue_changeset(Changeset(
                actor_id=actor, version=2, changes=(bad2,),
                seqs=(1, 1), last_seq=1, part=ChangesetPart.FULL,
            ), ChangeSource.SYNC)
            for _ in range(60):
                if (actor, 2) in agent._buffered_retry:
                    break
                await asyncio.sleep(0.05)
            assert (actor, 2) in agent._buffered_retry
        finally:
            await agent.stop()

        # restart on the same database; repair the schema; must heal
        agent2 = Agent(cfg, net.transport("node0b"))
        await agent2.start()
        try:
            assert (actor, 2) in agent2._buffered_retry, (
                "restart must reseed the retry ledger from durable "
                "complete partials"
            )
            agent2.store.execute_schema(
                TEST_SCHEMA.replace(
                    "text TEXT NOT NULL DEFAULT ''\n);",
                    "text TEXT NOT NULL DEFAULT '',\n"
                    "    nonexistent TEXT\n);",
                    1,
                )
            )
            for _ in range(80):
                if (actor, 2) not in agent2._buffered_retry:
                    break
                await asyncio.sleep(0.1)
            assert (actor, 2) not in agent2._buffered_retry
            row = agent2.store.query(
                "SELECT nonexistent FROM tests WHERE id = 1"
            )
            assert row and row[0][0] == "y"
        finally:
            await agent2.stop()
            tmp.cleanup()

    asyncio.run(body())


def test_loadshed_ingest_overflow_drops_oldest():
    """test_loadshed_handle_changes (handlers.rs:931-1015): with the
    apply lane stalled (write semaphore held hostage) and a tiny queue,
    incoming changesets displace the OLDEST queued ones; dropped
    versions are never recorded, the agent stays live."""

    async def body():
        cluster = Cluster(1, use_swim=False)
        await cluster.start()
        try:
            agent = cluster.agents[0]
            agent.config.perf.changes_queue_cap = 3
            actor, by_version = _writer_changes(10)

            async with agent.write_sema:  # lane hostage
                # give the ingest loop a chance to park on the semaphore
                await asyncio.sleep(0.05)
                for v in sorted(by_version, reverse=True):  # newest first
                    changes = by_version[v]
                    last_seq = max(ch.seq for ch in changes)
                    await agent._enqueue_changeset(Changeset(
                        actor_id=actor, version=v, changes=tuple(changes),
                        seqs=(0, last_seq), last_seq=last_seq,
                        part=ChangesetPart.FULL,
                    ), ChangeSource.SYNC)
                assert agent._ingest_q.qsize() <= 4  # cap + in-flight slack
                dropped = agent.stats["ingest_dropped"]
                assert dropped >= 5, f"expected overflow drops, got {dropped}"

            # lane released: the survivors apply, the agent is healthy
            await asyncio.sleep(0.3)
            rows = agent.store.query("SELECT count(*) FROM tests")
            assert 0 < rows[0][0] <= 10 - dropped + 1
            booked = agent.bookie.for_actor(actor)
            known = sum(
                1 for v in by_version if booked.contains_all((v, v), None)
            )
            assert known <= 10 - dropped, (
                "dropped versions must stay unknown (re-requestable)"
            )
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_broadcast_order_preserved_lossless():
    """test_broadcast_order (broadcast/mod.rs:1104-1199) analog: on a
    lossless link, a burst of local commits reaches the peer in version
    order (flush drains the queue front-first)."""

    async def body():
        cluster = Cluster(2, use_swim=False)
        await cluster.start()
        try:
            a, b = cluster.agents
            for i in range(1, 9):
                a.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)",
                      (i, f"v{i}"))]
                )
            assert await cluster.wait_converged(30)
            # apply_tick is insertion-ordered: its key order IS the
            # application order, which detects intra-flush-tick reorders
            # the tick VALUES cannot (they coincide within a batch)
            applied_versions = [
                v for (aid, v) in b.apply_tick if aid == a.actor_id
            ]
            assert applied_versions == sorted(applied_versions), (
                f"versions applied out of order: {applied_versions}"
            )
            assert len(applied_versions) == 8
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_clear_empty_versions_sync_through_overwrites():
    """Overwriting rows empties their earlier versions on the origin
    (LWW clock rows move to the new db_version); a partitioned peer
    healing back must converge THROUGH those versions via EMPTY/Cleared
    runs (tests.rs:778-876 + serve-side cleared-run algebra)."""

    async def body():
        cluster = Cluster(2, link=LinkModel(), use_swim=False)
        await cluster.start()
        try:
            a, b = cluster.agents
            addrs = [ag.transport.addr for ag in cluster.agents]
            cluster.net.partition(addrs[0], addrs[1])

            for i in range(1, 21):
                a.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)",
                      (i, f"orig{i}"))]
                )
            # overwrite scattered ranges — versions 1..20 now partly empty
            for i in (1, 2, 3, 10, 17, 18, 19, 20):
                a.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?) "
                      "ON CONFLICT (id) DO UPDATE SET text = excluded.text",
                      (i, f"new{i}"))]
                )
            # let the broadcast retransmission budget decay INSIDE the
            # partition, so recovery must go through anti-entropy sync
            # (the path that serves Cleared/EMPTY runs) rather than
            # queued broadcast retries delivering stale pre-overwrite
            # rows after heal
            await asyncio.sleep(1.5)
            cluster.net.heal()
            assert await cluster.wait_converged(60)

            rows_a = a.store.query("SELECT id, text FROM tests ORDER BY id")
            rows_b = b.store.query("SELECT id, text FROM tests ORDER BY id")
            assert rows_a == rows_b
            assert len(rows_b) == 20
            assert b.stats["empties_recv"] > 0, (
                "healing peer must have synced Cleared/EMPTY runs"
            )
            booked = b.bookie.for_actor(a.actor_id)
            assert booked.contains_all((1, 28), None)
        finally:
            await cluster.stop()

    asyncio.run(body())
