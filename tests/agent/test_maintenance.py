"""DB maintenance + interruptible-statement tests
(handlers.rs:372-540, sqlite-pool/src/lib.rs:116)."""

import asyncio
import os
import sqlite3
import tempfile

import pytest

from corrosion_tpu.agent.maintenance import (
    vacuum_db,
    wal_checkpoint_truncate,
)
from corrosion_tpu.agent.store import CrrStore
from corrosion_tpu.core.types import ActorId


@pytest.fixture
def file_store():
    with tempfile.TemporaryDirectory() as d:
        store = CrrStore(os.path.join(d, "m.db"), ActorId.random())
        store.execute_schema(
            "CREATE TABLE tests (id INTEGER PRIMARY KEY, text TEXT)"
        )
        yield store
        store.close()


def test_wal_checkpoint_truncates_file(file_store):
    for i in range(200):
        file_store.transact(
            [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, "x" * 512))]
        )
    wal = file_store.path + "-wal"
    assert os.path.getsize(wal) > 0
    assert wal_checkpoint_truncate(file_store)
    assert os.path.getsize(wal) == 0


def test_auto_vacuum_incremental_enabled(file_store):
    (mode,) = file_store.conn.execute("PRAGMA auto_vacuum").fetchone()
    assert mode == 2  # INCREMENTAL


def test_vacuum_reclaims_freelist(file_store):
    for i in range(300):
        file_store.transact(
            [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, "y" * 1024))]
        )
    # direct DELETE (not via CRDT) is fine for producing free pages
    file_store.conn.execute("DELETE FROM tests")
    (freelist,) = file_store.conn.execute("PRAGMA freelist_count").fetchone()
    assert freelist > 0
    reclaimed = vacuum_db(file_store, max_free_pages=0)
    assert reclaimed > 0
    (after,) = file_store.conn.execute("PRAGMA freelist_count").fetchone()
    assert after < freelist


def test_interruptible_read_times_out(file_store):
    """A pathological query is cut off by sqlite3_interrupt."""
    # recursive CTE that would run ~forever
    slow_sql = (
        "WITH RECURSIVE c(x) AS (SELECT 1 UNION ALL SELECT x+1 FROM c) "
        "SELECT count(*) FROM c"
    )
    with pytest.raises(sqlite3.OperationalError, match="interrupt"):
        with file_store.interruptible_read(timeout_s=0.2, label=slow_sql) as conn:
            conn.execute(slow_sql).fetchone()


def test_interruptible_read_normal_path(file_store):
    file_store.transact(
        [("INSERT INTO tests (id, text) VALUES (?, ?)", (1, "ok"))]
    )
    with file_store.interruptible_read(timeout_s=5.0, label="q") as conn:
        rows = conn.execute("SELECT text FROM tests").fetchall()
    assert [r[0] for r in rows] == ["ok"]


def test_slow_query_warns(file_store, caplog):
    import logging

    with caplog.at_level(logging.WARNING, logger="corrosion_tpu.store"):
        with file_store.interruptible_read(slow_warn_s=0.0, label="SELECT 1"):
            pass
    assert any("slow query" in r.message for r in caplog.records)


def test_api_query_timeout_surfaces_as_error_event():
    """End-to-end: a statement over the configured timeout yields an
    NDJSON {"error": ...} event, not a hung response."""
    from corrosion_tpu.api.client import ApiClient
    from corrosion_tpu.api.http import ApiServer

    async def body():
        # file-backed store required for a separate read conn
        import tempfile

        from corrosion_tpu.agent.agent import Agent
        from corrosion_tpu.agent.config import Config
        from corrosion_tpu.testing import MemoryNetwork

        with tempfile.TemporaryDirectory() as d:
            net = MemoryNetwork()
            cfg = Config(db_path=os.path.join(d, "t.db"), gossip_addr="n0")
            cfg.perf.statement_timeout_s = 0.2
            agent = Agent(cfg, net.transport("n0"))
            agent.store.execute_schema(
                "CREATE TABLE tests (id INTEGER PRIMARY KEY, text TEXT)"
            )
            await agent.start()
            srv = ApiServer(agent)
            await srv.start()
            try:
                client = ApiClient(srv.addr)
                slow = (
                    "WITH RECURSIVE c(x) AS "
                    "(SELECT 1 UNION ALL SELECT x+1 FROM c) "
                    "SELECT count(*) FROM c"
                )
                with pytest.raises(RuntimeError, match="interrupt"):
                    await client.query(slow)
            finally:
                await srv.stop()
                await agent.stop()

    asyncio.run(body())
