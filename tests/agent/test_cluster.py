"""In-process multi-agent integration tests — ports of the reference's
workhorse tests (corro-agent/src/agent/tests.rs): insert_rows_and_gossip,
large_tx_sync (chunked catch-up of a cold node), sync-driven convergence
under lossy links, and a small stress test."""

import asyncio

import pytest

from corrosion_tpu.core.types import ChangesetPart
from corrosion_tpu.agent.transport import LinkModel
from corrosion_tpu.testing import Cluster


async def _with_cluster(n, fn, **kw):
    cluster = Cluster(n, **kw)
    await cluster.start()
    try:
        await fn(cluster)
    finally:
        await cluster.stop()


def test_insert_rows_and_gossip():
    """tests.rs:52 — write on A, row appears on B; update propagates too."""

    async def body(cluster: Cluster):
        a, b = cluster.agents
        a.exec_transaction(
            [("INSERT INTO tests (id, text) VALUES (?, ?)", (1, "hello"))]
        )
        for _ in range(200):
            if cluster.rows(1, "SELECT id, text FROM tests") == [(1, "hello")]:
                break
            await asyncio.sleep(0.05)
        assert cluster.rows(1, "SELECT id, text FROM tests") == [(1, "hello")]

        b.exec_transaction(
            [("INSERT INTO tests (id, text) VALUES (?, ?)", (2, "world"))]
        )
        assert await cluster.wait_converged(10)
        assert cluster.rows(0, "SELECT id, text FROM tests ORDER BY id") == [
            (1, "hello"), (2, "world"),
        ]

    asyncio.run(_with_cluster(2, body))


def test_gossip_with_loss_converges_via_sync():
    """Broadcast loss forces the anti-entropy path to fill gaps."""

    async def body(cluster: Cluster):
        a = cluster.agents[0]
        for i in range(20):
            a.exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, f"t{i}"))]
            )
        assert await cluster.wait_converged(20)
        for node in range(3):
            assert len(cluster.rows(node, "SELECT id FROM tests")) == 20

    asyncio.run(_with_cluster(3, body, link=LinkModel(loss=0.4, seed=42), use_swim=False))


def test_large_tx_sync_cold_node():
    """tests.rs:602 large_tx_sync — a big chunked transaction reaches a node
    that joins late (pure sync catch-up, no broadcast)."""

    async def body(cluster: Cluster):
        a = cluster.agents[0]
        stmts = [
            ("INSERT INTO tests (id, text) VALUES (?, ?)", (i, "x" * 64))
            for i in range(2000)
        ]
        info = a.exec_transaction(stmts)
        assert info.last_seq + 1 == 2000  # one change per column write
        # multiple chunks were necessarily produced (8 KiB cap)
        assert len(a._bcast_q) > 1

        assert await cluster.wait_converged(30)
        for node in range(3):
            assert cluster.rows(node, "SELECT COUNT(*) FROM tests") == [(2000,)]

    asyncio.run(_with_cluster(3, body))


def test_partial_buffering_and_completion():
    """Drop-heavy link: partial chunks buffer in __corro_buffered_changes and
    only apply once every seq range arrived (util.rs:1053-1186 behavior)."""

    async def body(cluster: Cluster):
        a = cluster.agents[0]
        a.exec_transaction(
            [
                ("INSERT INTO tests (id, text) VALUES (?, ?)", (i, "y" * 128))
                for i in range(500)
            ]
        )
        assert await cluster.wait_converged(30)
        b = cluster.agents[1]
        assert cluster.rows(1, "SELECT COUNT(*) FROM tests") == [(500,)]
        # buffered staging is cleaned up after full application
        assert b.store.query("SELECT COUNT(*) FROM __corro_buffered_changes")[0][0] == 0
        assert b.store.query("SELECT COUNT(*) FROM __corro_seq_bookkeeping")[0][0] == 0

    asyncio.run(_with_cluster(2, body, link=LinkModel(loss=0.5, seed=7), use_swim=False))


def test_concurrent_writers_converge():
    """Every node writes; all converge to identical full state."""

    async def body(cluster: Cluster):
        for i, agent in enumerate(cluster.agents):
            for j in range(10):
                agent.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)",
                      (i * 100 + j, f"n{i}w{j}"))]
                )
        assert await cluster.wait_converged(30)
        ref = cluster.rows(0, "SELECT id, text FROM tests ORDER BY id")
        assert len(ref) == 50
        for node in range(1, 5):
            assert cluster.rows(node, "SELECT id, text FROM tests ORDER BY id") == ref

    asyncio.run(_with_cluster(5, body))


def test_conflict_update_lww_everywhere():
    """Conflicting updates on the same cell settle identically cluster-wide."""

    async def body(cluster: Cluster):
        a, b, c = cluster.agents
        a.exec_transaction([("INSERT INTO tests (id, text) VALUES (1, 'base')", ())])
        assert await cluster.wait_converged(10)
        # concurrent conflicting updates
        a.exec_transaction([("UPDATE tests SET text = 'started' WHERE id = 1", ())])
        b.exec_transaction([("UPDATE tests SET text = 'destroyed' WHERE id = 1", ())])
        assert await cluster.wait_converged(10)
        vals = {cluster.rows(i, "SELECT text FROM tests WHERE id = 1")[0][0] for i in range(3)}
        assert vals == {"started"}

    asyncio.run(_with_cluster(3, body))


def test_delete_propagates():
    async def body(cluster: Cluster):
        a, b = cluster.agents
        a.exec_transaction([("INSERT INTO tests (id, text) VALUES (1, 'gone')", ())])
        assert await cluster.wait_converged(10)
        a.exec_transaction([("DELETE FROM tests WHERE id = 1", ())])
        assert await cluster.wait_converged(10)
        assert cluster.rows(1, "SELECT * FROM tests") == []

    asyncio.run(_with_cluster(2, body))


@pytest.mark.slow
def test_stress_small():
    """configurable_stress_test analog (tests.rs:286) at a CI-friendly size:
    10 nodes, connectivity 3, 100 writes spread across writers."""

    async def body(cluster: Cluster):
        for i in range(100):
            agent = cluster.agents[i % 10]
            agent.exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (i, f"s{i}"))]
            )
        assert await cluster.wait_converged(60)
        for node in range(10):
            assert cluster.rows(node, "SELECT COUNT(*) FROM tests") == [(100,)]

    asyncio.run(_with_cluster(10, body, connectivity=3, seed=1))


def test_large_tx_sync_cold_node_reference_envelope():
    """tests.rs:602-650 at the REFERENCE envelope (VERDICT r1 item 7):
    a 10,000-row single transaction plus batches to 65,000 rows total,
    then a cold node joins and catches up through pure anti-entropy sync
    within a bounded time, served by the concurrent apply lanes."""
    import time

    async def body(cluster: Cluster):
        a = cluster.agents[0]
        t0 = time.monotonic()
        a.exec_transaction(
            [
                ("INSERT INTO tests (id, text) VALUES (?, ?)", (i, "x" * 32))
                for i in range(10_000)
            ]
        )
        for batch in range(11):
            base = 10_000 + batch * 5_000
            a.exec_transaction(
                [
                    ("INSERT INTO tests (id, text) VALUES (?, ?)", (i, "x" * 32))
                    for i in range(base, base + 5_000)
                ]
            )
        write_s = time.monotonic() - t0

        cold = await cluster.add_node()
        t0 = time.monotonic()
        deadline = t0 + 180
        count = 0
        while time.monotonic() < deadline:
            count = cold.store.query("SELECT COUNT(*) FROM tests")[0][0]
            if count == 65_000 and cluster.converged():
                break
            await asyncio.sleep(0.25)
        catchup_s = time.monotonic() - t0
        assert count == 65_000, f"cold node has {count}/65000 after {catchup_s:.0f}s"
        assert cluster.converged()
        print(f"envelope: wrote 65k rows in {write_s:.1f}s, "
              f"cold catch-up {catchup_s:.1f}s")

    asyncio.run(_with_cluster(2, body, use_swim=False))


def test_interactive_tx_requires_write_sema():
    """VERDICT r4 weak #6: interactive_tx() must refuse callers that do
    not hold the writer lane instead of trusting them."""

    async def body(cluster: Cluster):
        a = cluster.agents[0]
        with pytest.raises(RuntimeError, match="write_sema"):
            a.interactive_tx()
        # ownership, not mere lockedness: ANOTHER task holding the lane
        # (the ingest lane mid-apply) must not let this task through
        entered = asyncio.Event()
        release = asyncio.Event()

        async def holder():
            async with a.write_sema:
                entered.set()
                await release.wait()

        task = asyncio.ensure_future(holder())
        await entered.wait()
        with pytest.raises(RuntimeError, match="write_sema"):
            a.interactive_tx()
        release.set()
        await task
        async with a.write_sema:
            tx = a.interactive_tx()
            tx.begin()
            tx.execute("INSERT INTO tests (id, text) VALUES (1, 'guarded')")
            tx.commit()
        assert cluster.rows(0, "SELECT id, text FROM tests") == [(1, "guarded")]

    asyncio.run(_with_cluster(1, body))
