"""Host SWIM runtime tests: join via a single bootstrap seed, failure
detection (suspect → down), and member-state persistence — the reference's
Foca runtime behaviors (broadcast/mod.rs:122-386, util.rs:66-127)."""

import asyncio

from corrosion_tpu.agent.swim import ALIVE, DOWN
from corrosion_tpu.testing import Cluster


def test_join_through_single_seed_and_gossip():
    """Nodes 1..3 only know node0; SWIM must discover the full mesh, and a
    write must then reach everyone through the discovered members."""

    async def body():
        cluster = Cluster(4)
        await cluster.start()
        # rewrite bootstrap knowledge: only the seed (node0)
        try:
            # wait until every node knows the other 3
            for _ in range(200):
                if all(len(a.members) == 3 for a in cluster.agents):
                    break
                await asyncio.sleep(0.05)
            assert all(len(a.members) == 3 for a in cluster.agents), [
                len(a.members) for a in cluster.agents
            ]
            # SWIM-discovered members carry real actor ids
            known = {m.actor.id for m in cluster.agents[1].members.up_members()}
            real = {a.actor_id for a in cluster.agents} - {cluster.agents[1].actor_id}
            assert known == real

            cluster.agents[3].exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (1, 'via-swim')", ())]
            )
            assert await cluster.wait_converged(10)
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_seed_only_bootstrap():
    """Non-seed nodes bootstrap exclusively through node0 (star topology in
    bootstrap config; SWIM turns it into a full mesh)."""

    async def body():
        cluster = Cluster(3, connectivity=0)
        # connectivity=0 gives empty bootstrap; point all at node0 manually
        await cluster.start()
        try:
            seed = cluster.agents[0].transport.addr
            for agent in cluster.agents[1:]:
                await agent.swim._send(seed, {"k": "join", "me": agent.swim._self_member()})
            for _ in range(200):
                if all(len(a.members) == 2 for a in cluster.agents):
                    break
                await asyncio.sleep(0.05)
            assert all(len(a.members) == 2 for a in cluster.agents)
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_failure_detection_marks_down():
    async def body():
        cluster = Cluster(3)
        await cluster.start()
        try:
            for _ in range(100):
                if all(len(a.members) == 2 for a in cluster.agents):
                    break
                await asyncio.sleep(0.05)
            victim = cluster.agents[2]
            victim_id = victim.actor_id
            await victim.stop()
            # survivors must detect within probe+suspect window
            for _ in range(200):
                downs = [
                    a.swim.members.get(victim_id)
                    and a.swim.members[victim_id].status == DOWN
                    for a in cluster.agents[:2]
                ]
                if all(downs):
                    break
                await asyncio.sleep(0.05)
            for a in cluster.agents[:2]:
                assert a.swim.members[victim_id].status == DOWN
                assert victim_id not in {
                    m.actor.id for m in a.members.up_members()
                }
        finally:
            for a in cluster.agents[:2]:
                await a.stop()
            cluster.tmp.cleanup()

    asyncio.run(body())


def test_members_persisted_across_reboot(tmp_path):
    """Member state replayed from __corro_members on boot
    (reference broadcast/mod.rs:889-948)."""

    async def body():
        from corrosion_tpu.agent.agent import Agent
        from corrosion_tpu.agent.config import Config
        from corrosion_tpu.agent.transport import MemoryNetwork
        from corrosion_tpu.testing import TEST_SCHEMA, fast_perf

        net = MemoryNetwork()
        cfgs = [
            Config(
                db_path=str(tmp_path / f"n{i}.db"),
                gossip_addr=f"m{i}",
                bootstrap=[f"m{j}" for j in range(2) if j != i],
                perf=fast_perf(),
            )
            for i in range(2)
        ]
        agents = [Agent(c, net.transport(c.gossip_addr)) for c in cfgs]
        for a in agents:
            a.store.execute_schema(TEST_SCHEMA)
            await a.start()
        for _ in range(100):
            if all(len(a.members) == 1 for a in agents):
                break
            await asyncio.sleep(0.05)
        peer_of_0 = list(agents[0].swim.members)[0]
        for a in agents:
            await a.stop()

        # reboot node0: persisted member must be replayed (as suspect)
        a0 = Agent(cfgs[0], net.transport("m0"))
        await a0.start()
        try:
            assert peer_of_0 in a0.swim.members
        finally:
            await a0.stop()

    asyncio.run(body())


def test_down_member_gc():
    """foca remove_down_after analog: DOWN members are forgotten after
    swim_down_gc_s, and the adaptive suspicion window counts only live
    members."""
    import asyncio

    from corrosion_tpu.agent.swim import DOWN as S_DOWN

    async def body():
        cluster = Cluster(3)
        await cluster.start()
        try:
            a = cluster.agents[0]
            a.config.perf.swim_down_gc_s = 0.2
            a.config.perf.swim_probe_interval_s = 0.05
            # wait for membership to form
            for _ in range(100):
                if len(a.swim.members) >= 2:
                    break
                await asyncio.sleep(0.05)
            victim = cluster.agents[2]
            vid = victim.actor_id
            await victim.stop()
            # detected DOWN, then GC'd from the roster
            for _ in range(200):
                m = a.swim.members.get(vid)
                if m is not None and m.status == S_DOWN:
                    break
                await asyncio.sleep(0.05)
            assert a.swim.members[vid].status == S_DOWN
            for _ in range(200):
                if vid not in a.swim.members:
                    break
                await asyncio.sleep(0.05)
            assert vid not in a.swim.members, "down member must be GC'd"
        finally:
            for ag in cluster.agents[:2]:
                await ag.stop()
            cluster.tmp.cleanup()

    asyncio.run(body())


def test_cluster_size_feedback_retunes_config():
    """The reference re-derives the SWIM config from the live cluster
    size on every membership change (broadcast/mod.rs:236-256,
    make_foca_config :951-960).  Growing the membership must stretch
    the suspicion window and the transmission budget; members going
    DOWN must shrink them back (live size, not all-time size)."""
    from corrosion_tpu.core import swim_tuning

    async def body(cluster: Cluster):
        agent = cluster.agents[0]
        rt = agent.swim
        perf = agent.config.perf

        small_suspect = rt._suspect_timeout_s()
        small_mt = rt.effective_max_transmissions()
        small_probe = rt.effective_probe_interval_s()
        assert small_probe == perf.swim_probe_interval_s  # tiny cluster: base

        # synthesize a 100-member roster (feedback input is membership,
        # not the wire) — the same merge path real gossip drives
        from corrosion_tpu.agent.swim import MemberInfo
        from corrosion_tpu.core.types import ActorId

        fake = []
        for i in range(100):
            info = MemberInfo(
                actor_id=ActorId(bytes([9] * 14) + bytes(divmod(i, 256))),
                addr=f"fake{i}", incarnation=0, status=ALIVE, ts=0,
            )
            fake.append(info)
            rt._merge(info)
        assert rt.live_count() >= 100
        assert rt._suspect_timeout_s() > small_suspect
        assert rt.effective_max_transmissions() > small_mt
        assert rt.effective_max_transmissions() == (
            swim_tuning.max_transmissions_for(
                rt.live_count(), perf.swim_max_transmissions
            )
        )
        # the agent's broadcast lane consults the same live value
        assert agent.effective_max_transmissions() == (
            rt.effective_max_transmissions()
        )

        # members dying shrinks the LIVE size → config transitions back
        for info in fake:
            info.status = DOWN
            info.incarnation += 1
            rt._merge(MemberInfo(**{**info.__dict__}))
        assert rt._suspect_timeout_s() == small_suspect
        assert rt.effective_max_transmissions() == small_mt

    async def run():
        cluster = Cluster(2)
        await cluster.start()
        try:
            await body(cluster)
        finally:
            await cluster.stop()

    asyncio.run(run())


def test_swim_tuning_formulas_monotone():
    """Shared-formula sanity: all three outputs are monotone in N and
    floor at the configured base."""
    from corrosion_tpu.core import swim_tuning as st

    prev_s, prev_p, prev_m = 0.0, 0.0, 0
    for n in (1, 2, 8, 32, 45, 128, 1024, 100_000):
        s, p, m = (
            st.suspicion_factor(n),
            st.probe_interval_factor(n),
            st.max_transmissions_for(n, 10),
        )
        assert s >= prev_s and p >= prev_p and m >= prev_m
        prev_s, prev_p, prev_m = s, p, m
    assert st.suspicion_factor(2) == 1.0
    assert st.probe_interval_factor(8) == 1.0
    assert st.max_transmissions_for(4, 10) == 10  # never below base
    assert st.max_transmissions_for(45, 10) == 11  # first growth step
    assert st.max_transmissions_for(100_000, 10) > 30
