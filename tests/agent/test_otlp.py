"""OTLP/HTTP export (VERDICT r2 item 7): spans must actually leave the
process — batched OTLP JSON against a stub collector, plus the
[telemetry]-config wiring through a real agent lifecycle."""

import asyncio
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

from corrosion_tpu.otlp import OtlpHttpExporter, exporter_from_config
from corrosion_tpu.tracing import Tracer, span


class StubCollector:
    """Minimal OTLP/HTTP collector: records every POST body."""

    def __init__(self):
        self.requests = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers["content-length"]))
                outer.requests.append((self.path, json.loads(body)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):  # quiet
                pass

        self.httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.thread.start()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def spans(self):
        out = []
        for _path, body in self.requests:
            for rs in body["resourceSpans"]:
                for ss in rs["scopeSpans"]:
                    out.extend(ss["spans"])
        return out

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_exporter_batches_spans_to_collector():
    col = StubCollector()
    tracer = Tracer()
    exp = OtlpHttpExporter(
        col.endpoint, service_name="corro-test", batch_size=4,
        flush_interval_s=0.2,
    ).install(tracer)
    try:
        with span("outer", tracer=tracer, peer="n1") as outer:
            with span("inner", tracer=tracer):
                pass
        try:
            with span("boom", tracer=tracer):
                raise ValueError("x")
        except ValueError:
            pass
        deadline = 50
        while len(col.spans()) < 3 and deadline:
            deadline -= 1
            import time

            time.sleep(0.1)
        got = {s["name"]: s for s in col.spans()}
        assert set(got) == {"outer", "inner", "boom"}
        # parentage + trace continuity survive the wire format
        assert got["inner"]["parentSpanId"] == got["outer"]["spanId"]
        assert got["inner"]["traceId"] == got["outer"]["traceId"]
        assert got["boom"]["status"]["code"] == 2
        assert got["outer"]["attributes"] == [
            {"key": "peer", "value": {"stringValue": "n1"}}
        ]
        # resource carries the service identity
        res = col.requests[0][1]["resourceSpans"][0]["resource"]["attributes"]
        assert {"key": "service.name", "value": {"stringValue": "corro-test"}} in res
        assert col.requests[0][0] == "/v1/traces"
        assert exp.exported == 3 and exp.failures == 0
    finally:
        exp.shutdown(tracer)
        col.close()


def test_steady_trickle_flushes_on_interval_not_batch_size():
    """Spans arriving slower than batch_size must still export within
    ~flush_interval_s, not wait for 64 to accumulate."""
    import time

    col = StubCollector()
    tracer = Tracer()
    exp = OtlpHttpExporter(
        col.endpoint, batch_size=64, flush_interval_s=0.2
    ).install(tracer)
    try:
        t0 = time.monotonic()
        with span("trickle-1", tracer=tracer):
            pass
        while not col.spans() and time.monotonic() - t0 < 5:
            time.sleep(0.05)
        elapsed = time.monotonic() - t0
        assert col.spans(), "span never exported"
        assert elapsed < 2.0, f"interval flush took {elapsed:.1f}s"
    finally:
        exp.shutdown(tracer)
        col.close()


def test_two_exporters_coexist_and_detach_independently():
    """Several agents share the process TRACER: installing/removing one
    exporter must not clobber the other."""
    col1, col2 = StubCollector(), StubCollector()
    tracer = Tracer()
    e1 = OtlpHttpExporter(col1.endpoint, batch_size=1).install(tracer)
    e2 = OtlpHttpExporter(col2.endpoint, batch_size=1).install(tracer)
    try:
        with span("both", tracer=tracer):
            pass
        e1.shutdown(tracer)  # must leave e2 attached
        with span("only-2", tracer=tracer):
            pass
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if {"both", "only-2"} <= {s["name"] for s in col2.spans()}:
                break
            time.sleep(0.05)
        names2 = {s["name"] for s in col2.spans()}
        assert {"both", "only-2"} <= names2, names2
        names1 = {s["name"] for s in col1.spans()}
        assert "only-2" not in names1
    finally:
        e2.shutdown(tracer)
        col1.close()
        col2.close()


def test_config_tolerates_non_dict_open_telemetry():
    from corrosion_tpu.agent.config import Config

    cfg = Config.from_dict({"telemetry": {"open-telemetry": "otlp"}})
    assert cfg.otlp_endpoint == ""


def test_exporter_survives_dead_collector():
    tracer = Tracer()
    exp = OtlpHttpExporter(
        "http://127.0.0.1:9", batch_size=1, flush_interval_s=0.1
    ).install(tracer)
    try:
        for _ in range(5):
            with span("s", tracer=tracer):
                pass
        import time

        time.sleep(0.5)
        assert exp.failures > 0  # failed, logged, never raised
    finally:
        exp.shutdown(tracer)


def test_agent_telemetry_config_exports_spans():
    """[telemetry] wiring end-to-end: an agent with otlp_endpoint set
    exports its spans; shutdown flushes the final batch."""
    from corrosion_tpu.agent.agent import Agent
    from corrosion_tpu.agent.config import Config
    from corrosion_tpu.agent.transport import MemoryNetwork
    from corrosion_tpu.tracing import TRACER, span as tspan

    col = StubCollector()
    cfg = Config.from_dict(
        {"telemetry": {"open-telemetry": {"endpoint": col.endpoint},
                       "service_name": "agent-under-test"}}
    )
    assert cfg.otlp_endpoint == col.endpoint
    assert exporter_from_config(cfg) is not None

    async def body():
        net = MemoryNetwork()
        agent = Agent(cfg, net.transport("n0"))
        await agent.start()
        with tspan("from-agent-process"):
            pass
        await agent.stop()  # must flush the pending batch

    try:
        asyncio.run(body())
        names = [s["name"] for s in col.spans()]
        assert "from-agent-process" in names
        assert TRACER._exporter is None  # uninstalled on stop
    finally:
        col.close()
