"""CRR store tests: local write capture via triggers, changes feed, remote
merge application, delete/resurrect lifecycles, conflict convergence —
the behaviors the reference gets from cr-sqlite (doc/crdts.md)."""

import pytest

from corrosion_tpu.agent.store import CrrStore
from corrosion_tpu.core.types import ActorId, DELETE_SENTINEL

SCHEMA = """
CREATE TABLE machines (
    id INTEGER PRIMARY KEY NOT NULL,
    name TEXT NOT NULL DEFAULT '',
    status TEXT NOT NULL DEFAULT 'broken'
);
"""


@pytest.fixture
def store(tmp_path):
    s = CrrStore(str(tmp_path / "a.db"), ActorId.random())
    s.execute_schema(SCHEMA)
    yield s
    s.close()


@pytest.fixture
def store2(tmp_path):
    s = CrrStore(str(tmp_path / "b.db"), ActorId.random())
    s.execute_schema(SCHEMA)
    yield s
    s.close()


def test_local_write_captures_changes(store):
    _, info = store.transact(
        [("INSERT INTO machines (id, name, status) VALUES (?, ?, ?)", (1, "meow", "created")),
         ("INSERT INTO machines (id, name, status) VALUES (?, ?, ?)", (2, "woof", "created"))]
    )
    assert info.db_version == 1
    # 2 rows x 2 non-pk columns = 4 changes, seqs 0..3 (doc/crdts.md:66-74 shape)
    assert info.last_seq == 3
    changes = store.changes_for_version(store.site_id, 1)
    assert [c.seq for c in changes] == [0, 1, 2, 3]
    assert {(c.cid, c.val) for c in changes} == {
        ("name", "meow"), ("status", "created"), ("name", "woof"), ("status", "created"),
    }
    assert all(c.col_version == 1 and c.cl == 1 for c in changes)


def test_update_bumps_col_version_and_db_version(store):
    store.transact([("INSERT INTO machines (id, name) VALUES (1, 'meow')", ())])
    _, info = store.transact(
        [("UPDATE machines SET status = 'started' WHERE id = 1", ())]
    )
    assert info.db_version == 2
    changes = store.changes_for_version(store.site_id, 2)
    assert len(changes) == 1
    assert changes[0].cid == "status" and changes[0].col_version == 2


def test_noop_update_captures_nothing(store):
    store.transact([("INSERT INTO machines (id, status) VALUES (1, 'x')", ())])
    _, info = store.transact([("UPDATE machines SET status = 'x' WHERE id = 1", ())])
    assert info is None  # no change, no db_version burned


def test_replication_roundtrip(store, store2):
    store.transact(
        [("INSERT INTO machines (id, name, status) VALUES (1, 'meow', 'created')", ())]
    )
    changes = store.changes_for_version(store.site_id, 1)
    impacted = store2.apply_changes(changes)
    assert impacted == 2
    rows = store2.query("SELECT id, name, status FROM machines")
    assert [(r["id"], r["name"], r["status"]) for r in rows] == [(1, "meow", "created")]
    # idempotent redelivery
    assert store2.apply_changes(changes) == 0


def test_lww_conflict_converges(store, store2):
    base = [("INSERT INTO machines (id, name, status) VALUES (1, 'meow', 'created')", ())]
    store.transact(base)
    store2.apply_changes(store.changes_for_version(store.site_id, 1))

    # concurrent conflicting updates (both at col_version 2)
    store.transact([("UPDATE machines SET status = 'started' WHERE id = 1", ())])
    store2.transact([("UPDATE machines SET status = 'destroyed' WHERE id = 1", ())])

    a_changes = store.changes_for_version(store.site_id, 2)
    b_changes = store2.changes_for_version(store2.site_id, 2)
    store2.apply_changes(a_changes)
    store.apply_changes(b_changes)

    sa = store.query("SELECT status FROM machines WHERE id = 1")[0][0]
    sb = store2.query("SELECT status FROM machines WHERE id = 1")[0][0]
    # doc/crdts.md:235-248 — 'started' > 'destroyed'
    assert sa == sb == "started"


def test_delete_propagates_and_stale_insert_loses(store, store2):
    store.transact([("INSERT INTO machines (id, name) VALUES (1, 'meow')", ())])
    store2.apply_changes(store.changes_for_version(store.site_id, 1))

    _, info = store.transact([("DELETE FROM machines WHERE id = 1", ())])
    dels = store.changes_for_version(store.site_id, info.db_version)
    assert [c.cid for c in dels] == [DELETE_SENTINEL]
    assert dels[0].cl == 2

    store2.apply_changes(dels)
    assert store2.query("SELECT * FROM machines") == []

    # a change from the dead lifecycle (cl=1) must not resurrect the row
    stale = store.changes_for_version(store.site_id, 1)
    assert store2.apply_changes(stale) == 0
    assert store2.query("SELECT * FROM machines") == []


def test_resurrect_after_delete(store, store2):
    store.transact([("INSERT INTO machines (id, name) VALUES (1, 'meow')", ())])
    store.transact([("DELETE FROM machines WHERE id = 1", ())])
    store.transact([("INSERT INTO machines (id, name) VALUES (1, 'reborn')", ())])
    # cl back to odd (3), fresh col_versions
    changes = store.changes_for_version(store.site_id, 3)
    assert all(c.cl == 3 for c in changes)

    for v in (1, 2, 3):
        store2.apply_changes(store.changes_for_version(store.site_id, v))
    rows = store2.query("SELECT id, name FROM machines")
    assert [(r[0], r[1]) for r in rows] == [(1, "reborn")]


def test_out_of_order_delivery_converges(store, store2):
    store.transact([("INSERT INTO machines (id, name) VALUES (1, 'meow')", ())])
    store.transact([("UPDATE machines SET name = 'grr' WHERE id = 1", ())])
    v1 = store.changes_for_version(store.site_id, 1)
    v2 = store.changes_for_version(store.site_id, 2)
    # newest first: v2's col_version=2 must survive v1's late arrival
    store2.apply_changes(v2)
    store2.apply_changes(v1)
    assert store2.query("SELECT name FROM machines WHERE id = 1")[0][0] == "grr"


def test_site_id_persisted(tmp_path):
    sid = ActorId.random()
    s = CrrStore(str(tmp_path / "p.db"), sid)
    s.close()
    s2 = CrrStore(str(tmp_path / "p.db"), ActorId.random())
    assert s2.site_id == sid  # identity survives reboot (doc/crdts.md:42)
    s2.close()


def test_corro_json_contains_matrix():
    """Reference semantics (sqlite-functions/src/lib.rs:70-127): JSON
    object subset match, on both the writer and read-only connections."""
    import os
    import tempfile

    from corrosion_tpu.agent.store import CrrStore
    from corrosion_tpu.core.types import ActorId

    with tempfile.TemporaryDirectory() as d:
        store = CrrStore(os.path.join(d, "t.db"), ActorId.random())
        for conn in (store.conn, store.read_conn):
            q = lambda a, b: conn.execute(
                "SELECT corro_json_contains(?, ?)", (a, b)
            ).fetchone()[0]
            assert q("{}", "{}") == 1
            assert q("{}", '{"key": "value"}') == 1
            assert q('{"key": "value"}', "{}") == 0
            assert q('{"key": "value"}', '{"key": "value"}') == 1
            assert q('{"key": "value"}', '{"key": "value", "key2": "value2"}') == 1
            assert q('{"key": "value"}', '{"key": "wrong value"}') == 0
            assert q('{"m": {"key": "value"}}', '{"m": {"key": "value"}}') == 1
            assert q('{"m": {"key": "value"}}', '{"m": {"key": "wrong"}}') == 0
        store.close()


def test_read_pool_isolation(tmp_path):
    """An interrupt on one pooled read conn must not abort a concurrent
    read on another (VERDICT r1 weak #4: the reference's 20-conn RO pool)."""
    import threading
    import time as _time

    from corrosion_tpu.agent.store import CrrStore
    from corrosion_tpu.core.types import ActorId

    store = CrrStore(str(tmp_path / "pool.db"), ActorId.random())
    try:
        with store.interruptible_read() as a:
            with store.interruptible_read() as b:
                assert a is not b  # distinct pool members
        # a long "slow" read on one conn gets interrupted; a parallel read
        # on another conn finishes untouched
        errs, oks = [], []

        def slow():
            try:
                with store.interruptible_read(timeout_s=0.2) as conn:
                    conn.execute(
                        "WITH RECURSIVE c(x) AS (SELECT 1 UNION ALL "
                        "SELECT x+1 FROM c LIMIT 30000000) "
                        "SELECT count(*) FROM c"
                    ).fetchone()
            except Exception as e:
                errs.append(e)

        def quick():
            _time.sleep(0.05)
            try:
                with store.interruptible_read(timeout_s=30) as conn:
                    oks.append(conn.execute("SELECT 1").fetchone()[0])
            except Exception as e:
                errs.append(("quick", e))

        ts = [threading.Thread(target=slow), threading.Thread(target=quick)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert oks == [1]
        assert len(errs) == 1 and "interrupt" in str(errs[0]).lower()
    finally:
        store.close()
