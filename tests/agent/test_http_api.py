"""HTTP API tests: transactions write path, NDJSON queries, migrations,
authz, failover client — against real agents over real TCP sockets, gossiping
through the API like the reference's CLI black-box test
(integration-tests/tests/cli_test.rs:24)."""

import asyncio

import pytest

from corrosion_tpu.api.client import ApiClient, PooledClient
from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.testing import Cluster


async def _with_api_cluster(n, fn, token=None):
    cluster = Cluster(n)
    await cluster.start()
    servers = []
    clients = []
    try:
        for agent in cluster.agents:
            srv = ApiServer(agent, authz_token=token)
            await srv.start()
            servers.append(srv)
            clients.append(ApiClient(srv.addr, authz_token=token))
        await fn(cluster, servers, clients)
    finally:
        for srv in servers:
            await srv.stop()
        await cluster.stop()


def test_transactions_and_queries_roundtrip():
    async def body(cluster, servers, clients):
        resp = await clients[0].execute(
            [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "via-http"]]]
        )
        assert resp["version"] == 1
        rows = await clients[0].query("SELECT id, text FROM tests")
        assert rows == [[1, "via-http"]]

    asyncio.run(_with_api_cluster(1, body))


def test_write_on_a_read_on_b_over_http():
    async def body(cluster, servers, clients):
        await clients[0].execute(
            [["INSERT INTO tests (id, text) VALUES (?, ?)", [7, "gossip"]]]
        )
        for _ in range(100):
            rows = await clients[1].query("SELECT id, text FROM tests")
            if rows:
                break
            await asyncio.sleep(0.05)
        assert rows == [[7, "gossip"]]

    asyncio.run(_with_api_cluster(2, body))


def test_migrations_endpoint():
    async def body(cluster, servers, clients):
        await clients[0].schema(
            ["CREATE TABLE extra (pk INTEGER PRIMARY KEY NOT NULL, v TEXT DEFAULT '')"]
        )
        await clients[0].execute([["INSERT INTO extra (pk, v) VALUES (1, 'x')", []]])
        rows = await clients[0].query("SELECT pk, v FROM extra")
        assert rows == [[1, "x"]]
        stats = await clients[0].table_stats()
        assert stats["extra"]["count"] == 1

    asyncio.run(_with_api_cluster(1, body))


def test_authz_bearer_token():
    async def body(cluster, servers, clients):
        bad = ApiClient(servers[0].addr, authz_token="wrong")
        with pytest.raises(RuntimeError, match="401"):
            await bad.query("SELECT 1")
        ok = await clients[0].query("SELECT 1")
        assert ok == [[1]]

    asyncio.run(_with_api_cluster(1, body, token="sekrit"))


def test_bad_sql_is_400_500_not_crash():
    async def body(cluster, servers, clients):
        with pytest.raises(RuntimeError):
            await clients[0].execute([["INSERT INTO nope VALUES (1)", []]])
        # server still serves afterwards
        assert await clients[0].query("SELECT 42") == [[42]]

    asyncio.run(_with_api_cluster(1, body))


def test_pooled_client_failover():
    async def body(cluster, servers, clients):
        pooled = PooledClient(["127.0.0.1:1", servers[0].addr])  # first addr dead
        await pooled.execute([["INSERT INTO tests (id, text) VALUES (9, 'po')", []]])
        assert await pooled.query("SELECT text FROM tests WHERE id = 9") == [["po"]]

    asyncio.run(_with_api_cluster(1, body))
