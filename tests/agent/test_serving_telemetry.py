"""Host-tier flight recorder (ISSUE 8): per-write stage records,
serving metric families on the sub-ms ladder, host flight JSONL sharing
the sim recorder's schema, and the measured-no-op off state."""

import asyncio
import json

from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.core.hlc import ntp64_from_unix_ns
from corrosion_tpu.loadgen import run_serving_cluster_load
from corrosion_tpu.metrics import DEFAULT_BUCKETS, LATENCY_BUCKETS, Registry
from corrosion_tpu.telemetry import (
    HostFlightRecorder,
    attach_host_telemetry,
    detach_host_telemetry,
    write_host_flight_jsonl,
)
from corrosion_tpu.testing import Cluster


def test_latency_buckets_preset():
    """Log-spaced 100 µs … 10 s, strictly increasing, sub-ms resolved —
    and distinct from the default ladder, which keeps its buckets."""
    assert LATENCY_BUCKETS[0] == 0.0001
    assert LATENCY_BUCKETS[-1] == 10.0
    assert list(LATENCY_BUCKETS) == sorted(LATENCY_BUCKETS)
    assert sum(1 for b in LATENCY_BUCKETS if b < 0.001) >= 3
    assert DEFAULT_BUCKETS[0] == 0.001  # untouched


def test_recorder_stage_stamps_and_summary():
    t = [100.0]
    rec = HostFlightRecorder(clock=lambda: t[0])
    rec.publish("node0", "aa", 7, hlc_ts=ntp64_from_unix_ns(10**9), n_changes=2)
    t[0] = 100.010
    rec.broadcast_out("node0", "aa", 7)
    t[0] = 100.015
    rec.apply("node1", "aa", 7)
    t[0] = 100.020
    rec.visible("node1", "aa", 7, hlc_now=ntp64_from_unix_ns(10**9 + 4_000_000))
    s = rec.summary()
    assert s["writes"] == 1
    assert s["stages"] == {"broadcast_out": 1, "apply": 1, "visible": 1}
    assert abs(s["publish_to_visible_s"]["p50"] - 0.020) < 1e-6
    assert abs(s["publish_to_broadcast_out_s"]["max"] - 0.010) < 1e-6
    # HLC proxy is independent of the wall column: 4 ms of HLC lag vs
    # 20 ms of wall — the divergence is what MEASURES clock skew
    assert abs(s["hlc_lag_s"]["p50"] - 0.004) < 1e-4


def test_recorder_bounded_drop_oldest():
    rec = HostFlightRecorder(cap=4)
    for v in range(10):
        rec.publish("n", "aa", v)
    assert len(rec) == 4
    assert rec.dropped == 6
    assert rec.summary()["dropped_records"] == 6


def test_serving_families_and_flight_jsonl(tmp_path):
    """An instrumented cluster run lands every serving family on the
    registry and a schema-valid host flight artifact on disk."""
    out = asyncio.run(
        run_serving_cluster_load(
            n_nodes=2, n_writes=8, n_writers=1, n_watchers=1,
            rate_hz=0.0, settle_timeout_s=20.0, telemetry=True,
            trace_path=str(tmp_path / "host.jsonl"),
        )
    )
    assert out["consistent"], out
    tel = out["telemetry"]
    assert tel["writes"] == 8
    assert tel["stages"]["visible"] == 8
    assert tel["publish_to_visible_s"]["p99"] > 0

    with open(tmp_path / "host.jsonl") as f:
        head = json.loads(f.readline())
        rows = [json.loads(line) for line in f]
    # the shared flight-record schema (sim/telemetry.py writes the same
    # header keys), host-tier tagged
    assert head["kind"] == "flight_recorder"
    assert head["version"] == 1
    assert head["tier"] == "host"
    assert head["writes"] == 8
    assert head["summary"]["publish_to_visible_s"]["samples"] == 8
    assert len(rows) == 8
    for row in rows:
        assert {"actor", "version", "node", "t"} <= set(row)
        assert row["publish_to_visible_ms"] >= 0


def test_trace_show_renders_host_tier(tmp_path, capsys):
    """`sim trace show` renders a host flight file without jax."""
    from corrosion_tpu.cli.main import main

    rec = HostFlightRecorder()
    rec.publish("node0", "ab", 1, hlc_ts=ntp64_from_unix_ns(10**9))
    rec.visible("node1", "ab", 1, hlc_now=ntp64_from_unix_ns(10**9))
    path = str(tmp_path / "host.jsonl")
    write_host_flight_jsonl(path, rec, header={"seed": 3})
    rc = main(["sim", "trace", "show", "--in", path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "host tier" in out
    assert "publish_to_visible_ms" in out
    rc = main(["sim", "trace", "show", "--in", path, "--json"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["header"]["tier"] == "host"


def test_attach_detach_and_registry_families():
    async def body():
        cluster = Cluster(2, use_swim=False)
        await cluster.start()
        servers = []
        try:
            for agent in cluster.agents:
                srv = ApiServer(agent)
                await srv.start()
                servers.append(srv)
            reg = Registry()
            rec = HostFlightRecorder()
            for agent in cluster.agents:
                attach_host_telemetry(agent, recorder=rec, registry=reg)
            w = cluster.agents[0]
            from corrosion_tpu.api.client import ApiClient

            client = ApiClient(servers[0].addr)
            sub = await ApiClient(servers[1].addr).subscribe(
                ["SELECT id, text FROM tests", []]
            )
            await client.execute(
                [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "x"]]]
            )
            # wait for the remote visible stamp
            for _ in range(100):
                if rec.summary()["stages"]["visible"]:
                    break
                await asyncio.sleep(0.05)
            sub.close()
            text = reg.render()
            for family in (
                "corro_api_request_seconds",
                "corro_serving_commit_seconds",
                "corro_store_transact_seconds",
                "corro_serving_publish_broadcast_seconds",
                "corro_serving_publish_visible_seconds",
                "corro_serving_wire_bytes_total",
                "corro_serving_fanout_events_total",
            ):
                assert family in text, family
            # serving histograms ride the sub-ms ladder
            assert 'le="0.0001"' in text
            # detach restores the measured no-op state
            for agent in cluster.agents:
                detach_host_telemetry(agent)
            assert w.telemetry is None and w.subs.telemetry is None
            assert w.store.telemetry is None
        finally:
            for srv in servers:
                await srv.stop()
            await cluster.stop()

    asyncio.run(body())


def test_visible_stamp_waits_for_deferred_fallback_flush():
    """A fallback (non-keyed) matcher defers its fan-out inside the
    re-run budget window: the visible stamp must park until the
    trailing flush actually delivers, not antedate it at match time —
    and it must still LAND once the flush runs."""
    async def body():
        cluster = Cluster(1, use_swim=False)
        await cluster.start()
        try:
            agent = cluster.agents[0]
            rec = HostFlightRecorder()
            attach_host_telemetry(
                agent, recorder=rec, registry=Registry()
            )
            # aggregate defeats the keyed rewrite → fallback matcher
            handle, _ = agent.subs.get_or_insert(
                "SELECT count(*) AS n FROM tests", ()
            )
            assert not handle.matcher.keyed
            q = handle.attach()
            try:
                agent.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)",
                      (11, "a"))]
                )
                # two quick writes: the second lands inside the re-run
                # budget window and defers
                agent.exec_transaction(
                    [("INSERT INTO tests (id, text) VALUES (?, ?)",
                      (12, "b"))]
                )
                # eventually the trailing flush delivers AND the parked
                # visible stamps drain — both writes end up stamped
                for _ in range(200):
                    if rec.summary()["stages"]["visible"] >= 2:
                        break
                    await asyncio.sleep(0.05)
                assert rec.summary()["stages"]["visible"] == 2
            finally:
                handle.detach(q)
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_uninstrumented_agents_record_nothing():
    """telemetry=False runs touch neither recorder nor any serving
    family — the off path is `agent.telemetry is None` end to end."""
    async def body():
        cluster = Cluster(2, use_swim=False)
        await cluster.start()
        try:
            assert all(a.telemetry is None for a in cluster.agents)
            cluster.agents[0].exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (5, "y"))]
            )
            await cluster.wait_converged(20)
            assert all(a.telemetry is None for a in cluster.agents)
        finally:
            await cluster.stop()

    asyncio.run(body())
