"""Admin Unix-socket RPC tests (corro-admin analog): framed JSON commands
against a live agent."""

import asyncio

from corrosion_tpu.admin import AdminClient, AdminServer
from corrosion_tpu.testing import Cluster


async def _with_admin(n, fn):
    import tempfile

    cluster = Cluster(n)
    await cluster.start()
    servers, clients = [], []
    tmp = tempfile.TemporaryDirectory()
    try:
        for i, agent in enumerate(cluster.agents):
            path = f"{tmp.name}/admin{i}.sock"
            srv = AdminServer(agent, path)
            await srv.start()
            servers.append(srv)
            clients.append(AdminClient(path))
        await fn(cluster, clients)
    finally:
        for srv in servers:
            await srv.stop()
        await cluster.stop()
        tmp.cleanup()


def test_ping_and_sync_generate():
    async def body(cluster, clients):
        assert (await clients[0].send({"cmd": "ping"}))["ok"] == "pong"
        cluster.agents[0].exec_transaction(
            [("INSERT INTO tests (id, text) VALUES (1, 'a')", ())]
        )
        dump = (await clients[0].send({"cmd": "sync", "sub": "generate"}))["ok"]
        me = cluster.agents[0].actor_id.hex()
        assert dump["actor_id"] == me
        assert dump["heads"][me] == 1

    asyncio.run(_with_admin(1, body))


def test_cluster_members_and_membership_states():
    async def body(cluster, clients):
        # let SWIM converge membership
        for _ in range(100):
            resp = (await clients[0].send({"cmd": "cluster", "sub": "members"}))["ok"]
            if len(resp) >= 2:
                break
            await asyncio.sleep(0.05)
        assert len(resp) >= 2
        states = (
            await clients[0].send({"cmd": "cluster", "sub": "membership_states"})
        )["ok"]
        assert all(s["state"] in ("alive", "suspect", "down") for s in states)

    asyncio.run(_with_admin(3, body))


def test_actor_version_classification():
    async def body(cluster, clients):
        a = cluster.agents[0]
        a.exec_transaction([("INSERT INTO tests (id, text) VALUES (5, 'v')", ())])
        resp = (
            await clients[0].send(
                {"cmd": "actor", "sub": "version",
                 "actor_id": a.actor_id.hex(), "version": 1}
            )
        )["ok"]
        assert resp["kind"] == "current"
        resp = (
            await clients[0].send(
                {"cmd": "actor", "sub": "version",
                 "actor_id": a.actor_id.hex(), "version": 99}
            )
        )["ok"]
        assert resp["kind"] == "unknown"

    asyncio.run(_with_admin(1, body))


def test_subs_list_and_info_and_locks():
    async def body(cluster, clients):
        a = cluster.agents[0]
        handle, _ = a.subs.get_or_insert("SELECT id, text FROM tests")
        subs = (await clients[0].send({"cmd": "subs", "sub": "list"}))["ok"]
        assert len(subs) == 1 and subs[0]["id"] == handle.id
        info = (
            await clients[0].send({"cmd": "subs", "sub": "info", "id": handle.id})
        )["ok"]
        assert info["mode"] == "keyed"
        assert info["tables"] == ["tests"]
        locks = (await clients[0].send({"cmd": "locks", "top": 5}))["ok"]
        assert isinstance(locks, list)

    asyncio.run(_with_admin(1, body))


def test_cluster_set_id_and_log_level():
    async def body(cluster, clients):
        resp = await clients[0].send({"cmd": "cluster", "sub": "set_id", "id": 7})
        assert resp["ok"] == 7
        assert cluster.agents[0].config.cluster_id == 7
        assert (await clients[0].send({"cmd": "log", "sub": "set", "filter": "debug"}))[
            "ok"
        ] == "debug"
        assert (await clients[0].send({"cmd": "log", "sub": "reset"}))["ok"] == "reset"
        resp = await clients[0].send({"cmd": "nope"})
        assert "error" in resp

    asyncio.run(_with_admin(1, body))


def test_cluster_rejoin():
    async def body(cluster, clients):
        inc0 = cluster.agents[0].swim.incarnation
        resp = await clients[0].send({"cmd": "cluster", "sub": "rejoin"})
        assert resp["ok"] == "rejoined"
        assert cluster.agents[0].swim.incarnation == inc0 + 1

    asyncio.run(_with_admin(2, body))
