"""Metrics facade + Prometheus exporter tests (command/agent.rs:105-130,
agent/metrics.rs:8-110)."""

import asyncio
import urllib.request

from corrosion_tpu.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsServer,
    Registry,
)
from corrosion_tpu.testing import Cluster


def test_counter_gauge_render():
    reg = Registry()
    c = reg.counter("reqs_total")
    c.inc()
    c.inc(2, route="/v1/queries")
    g = reg.gauge("queue_len")
    g.set(7)
    out = reg.render()
    assert "# TYPE reqs_total counter" in out
    assert "reqs_total 1" in out
    assert 'reqs_total{route="/v1/queries"} 2' in out
    assert "queue_len 7" in out


def test_histogram_buckets_and_sum():
    reg = Registry()
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    out = reg.render()
    assert 'lat_seconds_bucket{le="0.1"} 1' in out
    assert 'lat_seconds_bucket{le="1"} 2' in out
    assert 'lat_seconds_bucket{le="10"} 3' in out
    assert 'lat_seconds_bucket{le="+Inf"} 4' in out
    assert "lat_seconds_count 4" in out


def test_registry_same_name_same_metric():
    reg = Registry()
    assert reg.counter("x") is reg.counter("x")


def test_scrape_live_agent():
    async def body():
        cluster = Cluster(2, use_swim=False)
        await cluster.start()
        srv = MetricsServer(cluster.agents[0])
        try:
            addr = await srv.start()
            cluster.agents[0].exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (1, "m"))]
            )
            text = await asyncio.to_thread(
                lambda: urllib.request.urlopen(
                    f"http://{addr}/metrics", timeout=5
                ).read().decode()
            )
            assert "# TYPE corro_build_info gauge" in text
            assert "corro_changes_committed 1" in text
            assert 'corro_db_table_rows_total{table="tests"} 1' in text
            assert "corro_gossip_members 1" in text
            assert "corro_db_gaps_versions_total 0" in text
        finally:
            await srv.stop()
            await cluster.stop()

    asyncio.run(body())


def test_scrape_reflects_apply_histogram():
    async def body():
        cluster = Cluster(2, use_swim=False)
        await cluster.start()
        srv = MetricsServer(cluster.agents[1])
        try:
            addr = await srv.start()
            cluster.agents[0].exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (5, "gossiped"))]
            )
            for _ in range(200):
                rows = cluster.agents[1].store.query(
                    "SELECT id FROM tests WHERE id = 5"
                )
                if rows:
                    break
                await asyncio.sleep(0.02)
            assert rows
            text = await asyncio.to_thread(
                lambda: urllib.request.urlopen(
                    f"http://{addr}/metrics", timeout=5
                ).read().decode()
            )
            # the remote apply went through the instrumented ingest loop
            assert "corro_agent_apply_seconds_count" in text
            assert "corro_changes_applied 1" in text
        finally:
            await srv.stop()
            await cluster.stop()

    asyncio.run(body())
