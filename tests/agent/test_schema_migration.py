"""Live schema migration: apply_schema diffing semantics.

Spec: corro-types/src/schema.rs:274-608 (apply_schema) and :113-168
(constrain).  New tables/columns/indexes are applied live; anything
destructive is rejected.
"""

import pytest

from corrosion_tpu.agent.store import CrrStore
from corrosion_tpu.core.schema import SchemaError, parse_schema
from corrosion_tpu.core.types import ActorId

V1 = """
CREATE TABLE tests (
    id INTEGER PRIMARY KEY NOT NULL,
    text TEXT NOT NULL DEFAULT ''
);
"""


def _store(tmp_path, schema=V1) -> CrrStore:
    store = CrrStore(str(tmp_path / "db.sqlite"), ActorId.random())
    store.execute_schema(schema)
    return store


def test_new_table_added_live(tmp_path):
    store = _store(tmp_path)
    out = store.apply_schema(V1 + "CREATE TABLE t2 (id INTEGER PRIMARY KEY NOT NULL, n INTEGER);")
    assert out["new_tables"] == ["t2"]
    assert "t2" in store._tables
    _, info = store.transact([("INSERT INTO t2 (id, n) VALUES (1, 5)", ())])
    assert info is not None  # triggers capture writes to the new table
    store.close()


def test_reapply_is_idempotent(tmp_path):
    store = _store(tmp_path)
    out = store.apply_schema(V1)
    assert out["new_tables"] == [] and out["new_columns"] == {}
    store.close()


def test_add_column_with_default(tmp_path):
    store = _store(tmp_path)
    store.transact([("INSERT INTO tests (id, text) VALUES (1, 'a')", ())])
    v2 = V1.replace(
        "text TEXT NOT NULL DEFAULT ''",
        "text TEXT NOT NULL DEFAULT '',\n    score INTEGER NOT NULL DEFAULT 0",
    )
    out = store.apply_schema(v2)
    assert out["new_columns"] == {"tests": ["score"]}
    # existing row got the default; new writes to the column are captured
    assert store.query("SELECT score FROM tests WHERE id = 1")[0][0] == 0
    _, info = store.transact([("UPDATE tests SET score = 9 WHERE id = 1", ())])
    assert info is not None
    row = store.conn.execute(
        "SELECT val FROM tests__crdt_clock WHERE cid = 'score'"
    ).fetchone()
    assert row[0] == 9
    store.close()


def test_new_column_replicates(tmp_path):
    (tmp_path / "a").mkdir(); (tmp_path / "b").mkdir()
    a = _store(tmp_path / "a")
    b = _store(tmp_path / "b")
    v2 = V1.replace(
        "text TEXT NOT NULL DEFAULT ''",
        "text TEXT NOT NULL DEFAULT '',\n    score INTEGER",
    )
    a.apply_schema(v2)
    b.apply_schema(v2)
    _, info = a.transact(
        [("INSERT INTO tests (id, text, score) VALUES (1, 'x', 7)", ())]
    )
    changes = a.changes_for_version(a.site_id, info.db_version)
    b.apply_changes(changes)
    assert b.query("SELECT score FROM tests WHERE id = 1")[0][0] == 7
    a.close(); b.close()


def test_drop_table_rejected(tmp_path):
    store = _store(tmp_path)
    with pytest.raises(SchemaError, match="drop table"):
        store.apply_schema("CREATE TABLE other (id INTEGER PRIMARY KEY);")
    store.close()


def test_drop_column_rejected(tmp_path):
    store = _store(tmp_path)
    with pytest.raises(SchemaError, match="remove column"):
        store.apply_schema("CREATE TABLE tests (id INTEGER PRIMARY KEY NOT NULL);")
    store.close()


def test_change_column_rejected(tmp_path):
    store = _store(tmp_path)
    with pytest.raises(SchemaError, match="change column"):
        store.apply_schema(V1.replace("TEXT NOT NULL DEFAULT ''", "BLOB"))
    store.close()


def test_add_notnull_without_default_rejected(tmp_path):
    store = _store(tmp_path)
    v2 = V1.replace(
        "text TEXT NOT NULL DEFAULT ''",
        "text TEXT NOT NULL DEFAULT '',\n    score INTEGER NOT NULL",
    )
    with pytest.raises(SchemaError, match="needs a DEFAULT|NOT NULL"):
        store.apply_schema(v2)
    store.close()


def test_constrain_rejects_bad_shapes():
    with pytest.raises(SchemaError, match="primary key"):
        parse_schema("CREATE TABLE t (a INTEGER);")
    with pytest.raises(SchemaError, match="unique"):
        parse_schema(
            "CREATE TABLE t (id INTEGER PRIMARY KEY, a INTEGER);"
            "CREATE UNIQUE INDEX t_a ON t (a);"
        )
    with pytest.raises(SchemaError, match="foreign key"):
        parse_schema(
            "CREATE TABLE p (id INTEGER PRIMARY KEY);"
            "CREATE TABLE t (id INTEGER PRIMARY KEY, p_id INTEGER REFERENCES p(id));"
        )
    with pytest.raises(SchemaError, match="needs a DEFAULT"):
        parse_schema("CREATE TABLE t (id INTEGER PRIMARY KEY, a INTEGER NOT NULL);")


def test_index_diffing(tmp_path):
    store = _store(tmp_path, V1 + "CREATE INDEX tests_text ON tests (text);")
    names = lambda: {
        r[0]
        for r in store.conn.execute(
            "SELECT name FROM sqlite_master WHERE type='index' "
            "AND tbl_name='tests' AND sql IS NOT NULL"
        )
    }
    assert "tests_text" in names()
    # index removed from schema → dropped; new index → created
    store.apply_schema(V1 + "CREATE INDEX tests_text2 ON tests (text, id);")
    assert "tests_text" not in names()
    assert "tests_text2" in names()
    store.close()


def test_failed_migration_leaves_no_ghost_tables(tmp_path):
    # one valid new table + one destructive change in the same apply: the
    # whole migration must roll back, including the in-memory registry
    store = _store(tmp_path)
    bad = (
        "CREATE TABLE fresh (id INTEGER PRIMARY KEY NOT NULL);\n"
        "CREATE TABLE tests (id INTEGER PRIMARY KEY NOT NULL, text BLOB);"
    )
    with pytest.raises(SchemaError):
        store.apply_schema(bad)
    assert "fresh" not in store._tables
    # the store still works: sync reads iterate _tables and must not hit
    # rolled-back clock tables
    _, info = store.transact([("INSERT INTO tests (id, text) VALUES (1, 'a')", ())])
    assert store.changes_for_version(store.site_id, info.db_version)
    store.close()


def test_unsupported_statements_rejected():
    for stmt in (
        "CREATE VIEW v AS SELECT 1",
        "INSERT INTO t VALUES (1)",
        "CREATE TEMP TABLE t (id INTEGER PRIMARY KEY)",
        "CREATE TABLE t AS SELECT 1 AS id",
        "CREATE TRIGGER trg AFTER INSERT ON t BEGIN SELECT 1; END",
    ):
        with pytest.raises(SchemaError, match="unsupported|not allowed"):
            parse_schema(
                "CREATE TABLE t0 (id INTEGER PRIMARY KEY NOT NULL);" + stmt
            )


def test_composite_pk_order_is_identity(tmp_path):
    # PK column *order* defines the pk blob encoding; a reordered PK is a
    # different table and must not be adopted
    store = CrrStore(str(tmp_path / "db.sqlite"), ActorId.random())
    store.conn.execute("CREATE TABLE t (a INTEGER, b INTEGER, PRIMARY KEY (b, a))")
    with pytest.raises(SchemaError, match="does not match"):
        store.apply_schema("CREATE TABLE t (a INTEGER, b INTEGER, PRIMARY KEY (a, b))")
    store.close()


def test_multi_file_schema_startup(tmp_path):
    # schema dirs with several files form ONE schema (run_root.rs:101-106)
    import asyncio

    from corrosion_tpu.agent.agent import Agent
    from corrosion_tpu.agent.config import Config
    from corrosion_tpu.agent.transport import MemoryNetwork

    d = tmp_path / "schemas"
    d.mkdir()
    (d / "a.sql").write_text("CREATE TABLE aa (id INTEGER PRIMARY KEY NOT NULL);")
    (d / "b.sql").write_text("CREATE TABLE bb (id INTEGER PRIMARY KEY NOT NULL);")

    async def body():
        net = MemoryNetwork()
        ag = Agent(
            Config(
                db_path=str(tmp_path / "n.db"), gossip_addr="n0",
                schema_paths=[str(d)], use_swim=False,
            ),
            net.transport("n0"),
        )
        await ag.start()
        assert {"aa", "bb"} <= set(ag.store._tables)
        await ag.stop()

    asyncio.run(body())


def test_comments_and_missing_trailing_semicolons(tmp_path):
    schema = (
        "-- the main table; with a sneaky semicolon\n"
        "CREATE TABLE t1 (id INTEGER PRIMARY KEY NOT NULL); \n"
        "/* block\n comment */\n"
        "CREATE TABLE t2 (id INTEGER PRIMARY KEY NOT NULL)"  # no trailing ;
    )
    parsed = parse_schema(schema)
    assert set(parsed.tables) == {"t1", "t2"}

    # multi-file join where the first file lacks a trailing semicolon
    import asyncio

    from corrosion_tpu.agent.agent import Agent
    from corrosion_tpu.agent.config import Config
    from corrosion_tpu.agent.transport import MemoryNetwork

    d = tmp_path / "schemas"
    d.mkdir()
    (d / "a.sql").write_text("CREATE TABLE aa (id INTEGER PRIMARY KEY NOT NULL)")
    (d / "b.sql").write_text("-- comment\nCREATE TABLE bb (id INTEGER PRIMARY KEY NOT NULL)")

    async def body():
        net = MemoryNetwork()
        ag = Agent(
            Config(db_path=str(tmp_path / "n.db"), gossip_addr="n0",
                   schema_paths=[str(d)], use_swim=False),
            net.transport("n0"),
        )
        await ag.start()
        assert {"aa", "bb"} <= set(ag.store._tables)
        await ag.stop()

    asyncio.run(body())


def test_add_generated_column_keeps_expression(tmp_path):
    store = _store(tmp_path)
    store.transact([("INSERT INTO tests (id, text) VALUES (1, 'hi')", ())])
    v2 = V1.replace(
        "text TEXT NOT NULL DEFAULT ''",
        "text TEXT NOT NULL DEFAULT '',\n"
        "    text_len INTEGER GENERATED ALWAYS AS (LENGTH(text)) VIRTUAL",
    )
    out = store.apply_schema(v2)
    assert out["new_columns"] == {"tests": ["text_len"]}
    assert store.query("SELECT text_len FROM tests WHERE id = 1")[0][0] == 2
    store.close()


def test_adopt_existing_identical_table(tmp_path):
    store = CrrStore(str(tmp_path / "db.sqlite"), ActorId.random())
    store.conn.execute(
        "CREATE TABLE tests (id INTEGER PRIMARY KEY NOT NULL, "
        "text TEXT NOT NULL DEFAULT '')"
    )
    out = store.apply_schema(V1)
    assert out["new_tables"] == ["tests"]
    with pytest.raises(SchemaError, match="does not match"):
        store2 = CrrStore(str(tmp_path / "db2.sqlite"), ActorId.random())
        store2.conn.execute("CREATE TABLE tests (id INTEGER PRIMARY KEY, other BLOB)")
        store2.apply_schema(V1)
    store.close()
