"""Cross-cluster isolation: two clusters sharing one network must never
exchange CRDT state, membership, or sync payloads.

The reference gates every receive path on the cluster id: incoming
broadcast frames (corro-agent/src/agent/uni.rs:73-75) and the sync
handshake, which answers a foreign cluster with a typed
`SyncRejectionV1::DifferentCluster` (corro-agent/src/api/peer/mod.rs:1431).
These tests put two full clusters on one MemoryNetwork, cross-wire their
bootstrap lists so frames really flow across the boundary, and assert
nothing leaks.
"""

import asyncio

from corrosion_tpu.agent.transport import LinkModel, MemoryNetwork
from corrosion_tpu.testing import Cluster


async def _two_clusters(use_swim: bool):
    net = MemoryNetwork(default_link=LinkModel())
    ca = Cluster(2, cluster_id=1, net=net, addr_prefix="a", use_swim=use_swim)
    cb = Cluster(2, cluster_id=2, net=net, addr_prefix="b", use_swim=use_swim)
    # cross-wire: every node also bootstraps against the FOREIGN cluster,
    # so broadcast/sync/SWIM traffic is actually attempted across clusters
    await ca.start(extra_bootstrap=["b0", "b1"])
    await cb.start(extra_bootstrap=["a0", "a1"])
    return ca, cb


async def _stop(ca, cb):
    await ca.stop()
    await cb.stop()


def _total_stat(cluster: Cluster, key: str) -> int:
    return sum(agent.stats[key] for agent in cluster.agents)


def test_static_membership_no_leak_and_typed_sync_rejection():
    """Static membership (no SWIM) forces frames onto the wire: foreign
    members ARE in the broadcast fan-out and the sync peer set, so the
    receive-path checks are what keeps the clusters apart."""

    async def body():
        ca, cb = await _two_clusters(use_swim=False)
        try:
            ca.agents[0].exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (1, "alpha"))]
            )
            cb.agents[0].exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (2, "beta"))]
            )
            assert await ca.wait_converged(15)
            assert await cb.wait_converged(15)
            # give the cross-wired broadcast/sync lanes time to fire
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                if (
                    _total_stat(ca, "cluster_mismatch_dropped")
                    + _total_stat(cb, "cluster_mismatch_dropped")
                    > 0
                ):
                    break
                await asyncio.sleep(0.05)
            # not a single row crossed the boundary
            for i in range(2):
                assert ca.rows(i, "SELECT id, text FROM tests") == [(1, "alpha")]
                assert cb.rows(i, "SELECT id, text FROM tests") == [(2, "beta")]
            # and the drop was an *explicit policy decision*, not silence
            assert (
                _total_stat(ca, "cluster_mismatch_dropped")
                + _total_stat(cb, "cluster_mismatch_dropped")
                > 0
            )
            # no foreign actor's CRDT state is booked anywhere
            a_actors = {ag.actor_id for ag in ca.agents}
            b_actors = {ag.actor_id for ag in cb.agents}
            for ag in ca.agents:
                assert not (set(ag.sync_state().heads) & b_actors)
            for ag in cb.agents:
                assert not (set(ag.sync_state().heads) & a_actors)
        finally:
            await _stop(ca, cb)

    asyncio.run(body())


def test_sync_handshake_rejected_with_typed_reason():
    """A direct cross-cluster sync attempt gets the typed rejection
    (peer/mod.rs:1431) and ingests nothing."""

    async def body():
        ca, cb = await _two_clusters(use_swim=False)
        try:
            cb.agents[0].exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (9, "secret"))]
            )
            got = await ca.agents[0]._sync_with("b0")
            assert got == 0
            assert (
                ca.agents[0].stats["sync_rejected_different_cluster"] >= 1
            )
            assert cb.agents[0].stats["cluster_mismatch_dropped"] >= 1
            assert ca.rows(0, "SELECT * FROM tests") == []
        finally:
            await _stop(ca, cb)

    asyncio.run(body())


def test_swim_membership_isolated():
    """With SWIM on, foreign join/gossip datagrams are dropped before any
    merge, so neither cluster ever learns a foreign member."""

    async def body():
        ca, cb = await _two_clusters(use_swim=True)
        try:
            ca.agents[0].exec_transaction(
                [("INSERT INTO tests (id, text) VALUES (?, ?)", (1, "alpha"))]
            )
            assert await ca.wait_converged(15)
            await asyncio.sleep(0.5)  # a few SWIM probe intervals
            a_ids = {ag.actor_id for ag in ca.agents}
            b_ids = {ag.actor_id for ag in cb.agents}
            for ag in ca.agents:
                member_ids = {st.actor.id for st in ag.members.up_members()}
                assert not (member_ids & b_ids)
            for ag in cb.agents:
                member_ids = {st.actor.id for st in ag.members.up_members()}
                assert not (member_ids & a_ids)
                assert list(ag.store.query("SELECT * FROM tests")) == []
        finally:
            await _stop(ca, cb)

    asyncio.run(body())
