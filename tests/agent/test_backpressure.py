"""Serving-tier backpressure (ISSUE 13): admission control, the
slow-consumer policy, write-lane batching, and the saturation
side-channel — each limit pinned with its explicit overflow policy."""

import asyncio

import pytest

from corrosion_tpu.api.client import ApiClient, Overloaded
from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.testing import Cluster


async def _one_node(fn, **server_kw):
    cluster = Cluster(1, use_swim=False)
    await cluster.start()
    srv = ApiServer(cluster.agents[0], **server_kw)
    await srv.start()
    try:
        await fn(cluster.agents[0], srv)
    finally:
        await srv.stop()
        await cluster.stop()


def test_admission_control_429_retry_after_and_retry():
    """Writes beyond max_inflight_tx get 429 + Retry-After (the typed
    `Overloaded`), the rejection is COUNTED, and `execute_with_retry`
    rides it to success — graceful degradation, not an error surface."""

    async def body(agent, srv):
        from corrosion_tpu.metrics import Registry
        from corrosion_tpu.telemetry import (
            HostFlightRecorder,
            attach_host_telemetry,
        )

        rec = HostFlightRecorder()
        attach_host_telemetry(agent, recorder=rec, registry=Registry())
        client = ApiClient(srv.addr)
        stmts = [["INSERT INTO tests (id, text) VALUES (?, ?)", [1, "x"]]]

        # stall the write lane so admitted writes pile up at the cap
        async with agent.write_sema:
            tasks = [
                asyncio.create_task(client.execute(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [10 + i, "x"]]]
                ))
                for i in range(6)
            ]
            await asyncio.sleep(0.3)  # all dialed; cap (2) reached
            with pytest.raises(Overloaded) as ei:
                await client.execute(stmts)
            assert ei.value.status == 429
            assert ei.value.retry_after_s and ei.value.retry_after_s > 0
        results = await asyncio.gather(*tasks, return_exceptions=True)
        ok = [r for r in results if isinstance(r, dict)]
        rejected = [r for r in results if isinstance(r, Overloaded)]
        assert len(ok) >= 2  # the admitted writes committed
        assert rejected, results  # overflow was refused, not queued
        sat = rec.saturation()
        assert sat["counters"]["admission_rejected"]["total"] >= 1
        assert sat["high_water"]["tx_inflight_max"]

        # the retry stack absorbs the refusal once the lane frees up
        counters = {}
        out = await client.execute_with_retry(
            stmts, counters=counters
        )
        assert out["results"][0]["rows_affected"] == 1

    asyncio.run(_one_node(body, max_inflight_tx=2))


def test_write_batching_drains_under_one_lane_hold():
    """Concurrent admitted writes drain in batches (one write_sema
    hold for up to write_batch commits) — visible as the
    write_batch_max high-water mark ≥ 2."""

    async def body(agent, srv):
        from corrosion_tpu.metrics import Registry
        from corrosion_tpu.telemetry import (
            HostFlightRecorder,
            attach_host_telemetry,
        )

        rec = HostFlightRecorder()
        attach_host_telemetry(agent, recorder=rec, registry=Registry())
        client = ApiClient(srv.addr)
        # hold the lane so a burst accumulates, then release: the
        # drainer must take them in one batch
        async with agent.write_sema:
            tasks = [
                asyncio.create_task(client.execute(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [100 + i, "b"]]]
                ))
                for i in range(8)
            ]
            await asyncio.sleep(0.3)
        results = await asyncio.gather(*tasks)
        assert all(r["results"][0]["rows_affected"] == 1 for r in results)
        assert rec.saturation()["high_water"]["write_batch_max"][
            agent.telemetry.node
        ] >= 2

    asyncio.run(_one_node(body, max_inflight_tx=64, write_batch=8))


def test_slow_consumer_disconnected_with_reason():
    """A subscriber that stops reading is disconnected at the queue
    bound with an explicit error event — never a silent drop, and the
    fan-out keeps serving the healthy subscribers."""
    from corrosion_tpu.pubsub.manager import SubQueue

    async def scenario():
        from corrosion_tpu.agent.config import Config
        from corrosion_tpu.agent.agent import Agent
        from corrosion_tpu.agent.transport import MemoryNetwork
        from corrosion_tpu.testing import TEST_SCHEMA, fast_perf

        perf = fast_perf()
        perf.sub_queue_cap = 8
        cfg = Config(use_swim=False, gossip_addr="n0", perf=perf)
        net = MemoryNetwork()
        agent = Agent(cfg, net.transport("n0"))
        agent.store.execute_schema(TEST_SCHEMA)
        await agent.start()
        try:
            handle, _ = agent.subs.get_or_insert(
                "SELECT id, text FROM tests", ()
            )
            slow = handle.attach()   # never read
            fast = handle.attach()
            assert isinstance(slow, SubQueue)
            fast_seen = 0
            for i in range(32):
                agent.exec_transaction(
                    [(f"INSERT INTO tests (id, text) VALUES ({i}, 'x')", ())]
                )
                while not fast.empty():  # a HEALTHY consumer keeps up
                    fast.get_nowait()
                    fast_seen += 1
            # the slow queue closed with a reason; the close event is
            # the ONLY thing left on it
            assert slow.closed
            assert "slow consumer" in slow.close_reason
            ev = slow.get_nowait()
            assert "slow consumer" in ev["error"]
            assert slow not in handle.queues
            assert handle.slow_disconnects == 1
            # the healthy subscriber stayed attached and saw every event
            assert not fast.closed
            assert fast in handle.queues
            assert fast_seen >= 32
        finally:
            await agent.stop()

    asyncio.run(scenario())


def test_updates_watcher_slow_consumer_policy():
    """The per-table updates notifier applies the same bound."""
    from corrosion_tpu.pubsub.manager import UpdatesManager
    from corrosion_tpu.core.types import Change
    from corrosion_tpu.core.pkcodec import encode_pk
    from corrosion_tpu.core.types import ActorId

    async def scenario():
        mgr = UpdatesManager(queue_cap=4)
        q = mgr.attach("tests")
        site = ActorId(bytes(16))
        for i in range(12):
            mgr.match_changes(
                [
                    Change(
                        table="tests", pk=encode_pk([i]), cid="text",
                        val="x", col_version=1, db_version=i + 1, seq=0,
                        site_id=site, cl=1,
                    )
                ]
            )
        assert q.closed
        assert "slow consumer" in q.close_reason
        assert q not in mgr.by_table["tests"]

    asyncio.run(scenario())


def test_saturation_block_reaches_flight_jsonl(tmp_path):
    """The recorder's saturation side-channel lands in the JSONL
    header summary — what `sim trace show` renders."""
    import json

    from corrosion_tpu.telemetry import (
        HostFlightRecorder,
        write_host_flight_jsonl,
    )

    rec = HostFlightRecorder()
    rec.sat_count("admission_rejected", "node0", 3)
    rec.sat_high("tx_inflight_max", "node0", 17)
    rec.sat_high("tx_inflight_max", "node0", 11)  # high-water keeps 17
    path = str(tmp_path / "flight.jsonl")
    write_host_flight_jsonl(path, rec)
    with open(path) as f:
        head = json.loads(f.readline())
    sat = head["summary"]["saturation"]
    assert sat["counters"]["admission_rejected"]["total"] == 3
    assert sat["high_water"]["tx_inflight_max"]["node0"] == 17
