"""Stress + invariant-coverage harness.

The reference's workhorse is `configurable_stress_test(num_nodes,
connectivity, input_count)` (corro-agent/src/agent/tests.rs:268-336): an
in-process cluster on a random bootstrap graph, flooded with writes,
polled to convergence.  This is that harness plus the Antithesis-style
invariant catalog checks: no `always` violated, every expected
`sometimes` coverage marker fired.

The default run includes the reference's CI-scale 30-node configuration
(agent/tests.rs:268-286 runs it un-ignored); export CORRO_STRESS=big for
the 45-node analog of their #[ignore]d variant.
"""

import asyncio
import os

import pytest

from corrosion_tpu.invariants import CATALOG
from corrosion_tpu.testing import Cluster


async def _stress(num_nodes: int, connectivity: int, input_count: int,
                  timeout: float = 120.0):
    """configurable_stress_test analog: random bootstrap graph, flood
    writes round-robin, poll until every node converges."""
    CATALOG.reset()
    cluster = Cluster(num_nodes, connectivity=connectivity, seed=7)
    await cluster.start()
    try:
        for i in range(input_count):
            agent = cluster.agents[i % num_nodes]
            agent.exec_transaction(
                [
                    (
                        "INSERT OR REPLACE INTO tests (id, text) VALUES (?, ?)",
                        (i, f"stress-{i}"),
                    )
                ]
            )
            if i % 16 == 0:
                await asyncio.sleep(0)  # let the loops breathe
        ok = await cluster.wait_converged(timeout=timeout)
        assert ok, "cluster did not converge"
        # every node holds every row (eventually_check_db.sh property)
        for agent in cluster.agents:
            (n,) = agent.store.query("SELECT count(*) FROM tests")[0]
            assert n == input_count, (agent.actor_id.hex(), n)
        # convergence also means equal heads and empty needs
        # (check_bookkeeping.py:6-27)
        heads = [
            tuple(sorted(
                (a.hex(), v)
                for a, v in agent.sync_state().heads.items()
            ))
            for agent in cluster.agents
        ]
        assert len(set(heads)) == 1
        # cluster-size feedback (broadcast/mod.rs:236-256): at reference
        # scale the SWIM config must have TRANSITIONED off its
        # single-node base — suspicion window stretched, and the
        # transmission budget tracking the shared formula exactly
        first = cluster.agents[0]
        if first.swim is not None and num_nodes >= 30:
            import time

            from corrosion_tpu.core.swim_tuning import max_transmissions_for

            perf = first.config.perf
            # liveness under SUITE load (VERDICT r5 weak #5): the runtime
            # now stretches probe-ack deadlines with the observed event-
            # loop lag, but a node suspected during an earlier stall
            # still needs its refutation to gossip back — give that a
            # bounded window instead of asserting a one-shot snapshot
            # (passes instantly in isolation; heals within seconds under
            # full-suite load)
            deadline = time.monotonic() + 30.0
            while (
                first.swim.live_count() < num_nodes - 2
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.25)
            assert first.swim.live_count() >= num_nodes - 2
            assert (
                first.swim._suspect_timeout_s()
                > perf.swim_suspect_timeout_s
            )
            eff = first.swim.effective_max_transmissions()
            assert eff == max_transmissions_for(
                first.swim.live_count(), perf.swim_max_transmissions
            )
            if num_nodes >= 45:
                # 45 live members crosses the budget's first growth step
                assert eff > perf.swim_max_transmissions
    finally:
        await cluster.stop()


def test_stress_small():
    """CI tier: 8 nodes, sparse bootstrap graph, 64 writes."""
    asyncio.run(_stress(num_nodes=8, connectivity=3, input_count=64))
    # invariant catalog: nothing violated, coverage markers fired
    assert CATALOG.violations() == {}
    report = CATALOG.report()
    assert report.get("broadcasts-happen", {}).get("passes", 0) > 0
    assert report.get("sync-happens", {}).get("passes", 0) > 0


def test_stress_reference_scale():
    """30 nodes / connectivity 10 / 200 writes (agent/tests.rs:268-286)
    — the reference runs this scale in ordinary CI, so the rebuild does
    too (VERDICT r3 item 8; ~28 s measured, 90 s budget)."""
    asyncio.run(
        _stress(num_nodes=30, connectivity=10, input_count=200, timeout=90.0)
    )
    assert CATALOG.violations() == {}


@pytest.mark.skipif(
    os.environ.get("CORRO_STRESS") != "big",
    reason="45-node tier (the reference #[ignore]s this scale; CORRO_STRESS=big)",
)
def test_stress_big():
    """45 nodes / connectivity 15 / 300 writes — the analog of the
    reference's #[ignore]d large variant."""
    asyncio.run(
        _stress(num_nodes=45, connectivity=15, input_count=300, timeout=300.0)
    )
    assert CATALOG.violations() == {}


def test_invariant_catalog_mechanics():
    from corrosion_tpu.invariants import Catalog, InvariantViolation, Timed

    cat = Catalog()
    cat.always(True, "fine")
    cat.sometimes(False, "never-yet")
    cat.sometimes(True, "fired")
    assert cat.violations() == {}
    assert cat.unfired_sometimes() == ["never-yet"]

    cat.always(False, "broken", {"x": 1})
    assert "broken" in cat.violations()

    cat.strict = True
    with pytest.raises(InvariantViolation):
        cat.unreachable("nope")
    with pytest.raises(InvariantViolation):
        with Timed("too-slow", budget_s=0.0, catalog=cat):
            pass
