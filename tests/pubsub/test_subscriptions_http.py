"""End-to-end subscription streaming over HTTP: initial snapshot + live
changes, gossip-fed events, catch-up with ?from=, updates streams, restore —
the reference's subscription HTTP endpoints (api/public/pubsub.rs) and
corro-client stream behavior."""

import asyncio

from corrosion_tpu.api.client import ApiClient
from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.testing import Cluster


async def _with_api_cluster(n, fn):
    cluster = Cluster(n)
    await cluster.start()
    servers, clients = [], []
    try:
        for agent in cluster.agents:
            srv = ApiServer(agent)
            await srv.start()
            servers.append(srv)
            clients.append(ApiClient(srv.addr))
        await fn(cluster, servers, clients)
    finally:
        for srv in servers:
            await srv.stop()
        await cluster.stop()


async def _next_event(it, want_key, timeout=5.0):
    """Pull events until one with the wanted key arrives."""
    async def pull():
        async for e in it:
            if want_key in e:
                return e
        raise AssertionError("stream ended")

    return await asyncio.wait_for(pull(), timeout)


def test_subscribe_snapshot_then_live_change():
    async def body(cluster, servers, clients):
        await clients[0].execute(
            [["INSERT INTO tests (id, text) VALUES (1, 'first')", []]]
        )
        stream = await clients[0].subscribe("SELECT id, text FROM tests")
        assert stream.id
        it = stream.__aiter__()
        cols = await _next_event(it, "columns")
        assert cols == {"columns": ["id", "text"]}
        row = await _next_event(it, "row")
        assert row["row"][1] == [1, "first"]
        await _next_event(it, "eoq")
        # live change
        await clients[0].execute(
            [["INSERT INTO tests (id, text) VALUES (2, 'second')", []]]
        )
        change = await _next_event(it, "change")
        assert change["change"][0] == "insert"
        assert change["change"][2] == [2, "second"]
        stream.close()

    asyncio.run(_with_api_cluster(1, body))


def test_subscription_sees_gossiped_writes():
    async def body(cluster, servers, clients):
        # subscribe on node B, write via node A → event rides the gossip
        stream = await clients[1].subscribe("SELECT id, text FROM tests")
        it = stream.__aiter__()
        await _next_event(it, "eoq")
        await clients[0].execute(
            [["INSERT INTO tests (id, text) VALUES (9, 'remote')", []]]
        )
        change = await _next_event(it, "change", timeout=10.0)
        assert change["change"][2] == [9, "remote"]
        stream.close()

    asyncio.run(_with_api_cluster(2, body))


def test_catchup_from_change_id():
    async def body(cluster, servers, clients):
        s1 = await clients[0].subscribe("SELECT id, text FROM tests")
        it = s1.__aiter__()
        await _next_event(it, "eoq")
        await clients[0].execute([["INSERT INTO tests (id, text) VALUES (1, 'a')", []]])
        await clients[0].execute([["INSERT INTO tests (id, text) VALUES (2, 'b')", []]])
        e1 = await _next_event(it, "change")
        assert e1["change"][3] == 1
        s1.close()
        # re-attach from change 1: only change 2 replays
        s2 = await clients[0].resubscribe(s1.id, from_change=1)
        it2 = s2.__aiter__()
        e2 = await _next_event(it2, "change")
        assert e2["change"][3] == 2
        assert e2["change"][2] == [2, "b"]
        s2.close()

    asyncio.run(_with_api_cluster(1, body))


def test_updates_stream():
    async def body(cluster, servers, clients):
        stream = await clients[0].updates("tests")
        it = stream.__aiter__()
        await clients[0].execute([["INSERT INTO tests (id, text) VALUES (5, 'u')", []]])
        ev = await asyncio.wait_for(it.__anext__(), 5.0)
        assert ev == {"notify": ["update", [5]]}
        await clients[0].execute([["DELETE FROM tests WHERE id = 5", []]])
        ev = await asyncio.wait_for(it.__anext__(), 5.0)
        assert ev == {"notify": ["delete", [5]]}
        stream.close()

    asyncio.run(_with_api_cluster(1, body))


def test_subscription_restored_after_restart():
    """Persisted subs reload at boot and resync missed writes
    (pubsub.rs:822-858 restore path)."""

    async def body():
        import tempfile

        from corrosion_tpu.agent.agent import Agent
        from corrosion_tpu.agent.config import Config
        from corrosion_tpu.agent.transport import MemoryNetwork
        from corrosion_tpu.testing import TEST_SCHEMA, fast_perf

        with tempfile.TemporaryDirectory() as tmp:
            net = MemoryNetwork()
            cfg = Config(
                db_path=f"{tmp}/n.db", gossip_addr="n", bootstrap=[],
                use_swim=False, perf=fast_perf(),
            )
            agent = Agent(cfg, net.transport("n"))
            agent.store.execute_schema(TEST_SCHEMA)
            await agent.start()
            handle, _ = agent.subs.get_or_insert("SELECT id, text FROM tests")
            sub_id = handle.id
            agent.exec_transaction([("INSERT INTO tests (id, text) VALUES (1, 'x')", ())])
            assert handle.matcher.last_change_id == 1
            await agent.stop()

            # reboot on the same DB; write happened while "down" is resynced
            agent2 = Agent(cfg, net.transport("n2"))
            await agent2.start()
            h2 = agent2.subs.get(sub_id)
            assert h2 is not None
            assert h2.matcher.last_change_id == 1  # change log persisted
            events = h2.matcher.snapshot_events()
            assert events[1]["row"][1] == [1, "x"]
            await agent2.stop()

    asyncio.run(body())
