"""Matcher unit tests: keyed incremental diffs, fallback mode, change log
catch-up — the reference's pubsub.rs inline test coverage equivalents."""

import asyncio

import pytest

from corrosion_tpu.agent.store import CrrStore
from corrosion_tpu.core.types import ActorId
from corrosion_tpu.pubsub import Matcher, MatcherError, SubsManager, UpdatesManager

SCHEMA = """
CREATE TABLE sandwiches (
    name TEXT PRIMARY KEY NOT NULL,
    filling TEXT NOT NULL DEFAULT '',
    price REAL NOT NULL DEFAULT 0
);
CREATE TABLE shops (
    id INTEGER PRIMARY KEY NOT NULL,
    city TEXT NOT NULL DEFAULT ''
);
"""


def make_store():
    store = CrrStore(":memory:", ActorId.random())
    store.execute_schema(SCHEMA)
    return store


def crr_tables(store):
    return {name: info.pk_cols for name, info in store._tables.items()}


def apply_local(store, sql, params=()):
    """Commit a local write and return the captured changes."""
    _, info = store.transact([(sql, params)])
    assert info is not None
    return store.changes_for_version(store.site_id, info.db_version)


def test_keyed_single_table_lifecycle():
    store = make_store()
    apply_local(store, "INSERT INTO sandwiches (name, filling) VALUES ('blt', 'bacon')")
    m = Matcher("s1", "SELECT name, filling FROM sandwiches", (), store.conn,
                crr_tables(store))
    events = m.run_initial()
    assert events[0] == {"columns": ["name", "filling"]}
    assert events[1]["row"][1] == ["blt", "bacon"]
    assert "eoq" in events[-1]
    assert m.keyed

    # insert
    ev = m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name, filling) VALUES ('ham', 'ham')")
    )
    assert ev == [{"change": ["insert", ev[0]["change"][1], ["ham", "ham"], 1]}]
    # update
    ev = m.handle_changes(
        apply_local(store, "UPDATE sandwiches SET filling = 'maple ham' WHERE name = 'ham'")
    )
    assert ev[0]["change"][0] == "update"
    assert ev[0]["change"][2] == ["ham", "maple ham"]
    # delete
    ev = m.handle_changes(apply_local(store, "DELETE FROM sandwiches WHERE name = 'blt'"))
    assert ev[0]["change"][0] == "delete"
    assert ev[0]["change"][2] == ["blt", "bacon"]
    assert m.last_change_id == 3


def test_keyed_where_clause_filters_rows():
    store = make_store()
    m = Matcher("s2", "SELECT name FROM sandwiches WHERE price > 5", (), store.conn,
                crr_tables(store))
    m.run_initial()
    ev = m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name, price) VALUES ('cheap', 1)")
    )
    assert ev == []  # filtered out
    ev = m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name, price) VALUES ('lux', 12)")
    )
    assert ev[0]["change"][:1] == ["insert"]
    # price drop moves it out of the result set → delete event
    ev = m.handle_changes(
        apply_local(store, "UPDATE sandwiches SET price = 2 WHERE name = 'lux'")
    )
    assert ev[0]["change"][0] == "delete"


def test_keyed_join_two_tables():
    store = make_store()
    apply_local(store, "INSERT INTO shops (id, city) VALUES (1, 'lisbon')")
    m = Matcher(
        "s3",
        "SELECT s.name, h.city FROM sandwiches s JOIN shops h ON h.id = 1",
        (), store.conn, crr_tables(store),
    )
    m.run_initial()
    assert m.keyed
    ev = m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name) VALUES ('paris')")
    )
    assert ev[0]["change"][2] == ["paris", "lisbon"]
    # change on the joined table side also lands
    ev = m.handle_changes(
        apply_local(store, "UPDATE shops SET city = 'porto' WHERE id = 1")
    )
    assert ev[0]["change"][0] == "update"
    assert ev[0]["change"][2] == ["paris", "porto"]


def test_aggregate_falls_back_to_full_mode():
    store = make_store()
    m = Matcher("s4", "SELECT COUNT(*) FROM sandwiches", (), store.conn,
                crr_tables(store))
    assert not m.keyed
    events = m.run_initial()
    assert events[1]["row"][1] == [0]
    ev = m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name) VALUES ('one')")
    )
    assert ev[0]["change"][0] == "update"
    assert ev[0]["change"][2] == [1]


def test_params_and_catchup():
    store = make_store()
    m = Matcher("s5", "SELECT name FROM sandwiches WHERE filling = ?", ("x",),
                store.conn, crr_tables(store))
    m.run_initial()
    m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name, filling) VALUES ('a', 'x')")
    )
    m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name, filling) VALUES ('b', 'x')")
    )
    assert [e["change"][3] for e in m.changes_since(0)] == [1, 2]
    assert [e["change"][3] for e in m.changes_since(1)] == [2]


def test_non_select_rejected():
    store = make_store()
    with pytest.raises(MatcherError):
        Matcher("bad", "DELETE FROM sandwiches", (), store.conn, crr_tables(store))
    with pytest.raises(MatcherError):
        Matcher("bad2", "SELECT 1", (), store.conn, crr_tables(store))


def test_subs_manager_share_and_remove():
    async def body():
        store = make_store()
        subs = SubsManager(store)
        h1, created1 = subs.get_or_insert("SELECT name FROM sandwiches")
        h2, created2 = subs.get_or_insert("select   name from sandwiches")
        assert created1 and not created2
        assert h1.id == h2.id
        q = h1.attach()
        subs.match_changes(
            apply_local(store, "INSERT INTO sandwiches (name) VALUES ('z')")
        )
        ev = q.get_nowait()
        assert ev["change"][0] == "insert"
        subs.remove(h1.id)
        assert subs.get(h1.id) is None
        row = store.conn.execute("SELECT COUNT(*) FROM __corro_subs").fetchone()
        assert row[0] == 0

    asyncio.run(body())


def test_updates_manager_notify_events():
    async def body():
        store = make_store()
        um = UpdatesManager()
        q = um.attach("sandwiches")
        um.match_changes(
            apply_local(store, "INSERT INTO sandwiches (name) VALUES ('n1')")
        )
        assert q.get_nowait() == {"notify": ["update", ["n1"]]}
        um.match_changes(apply_local(store, "DELETE FROM sandwiches WHERE name = 'n1'"))
        assert q.get_nowait() == {"notify": ["delete", ["n1"]]}

    asyncio.run(body())


# -- fallback re-run budget (VERDICT r3 item 6) ------------------------------


def test_fallback_rerun_budget_coalesces_storm():
    """A 100k-row GROUP-BY (fallback) sub under a write storm: re-runs
    must be rate-bounded (coalesced), not one O(result) pass per batch,
    and the trailing flush must land the final state."""
    from corrosion_tpu.metrics import REGISTRY

    store = make_store()
    # 100k-row base table so a full re-run has real O(result) cost
    store.conn.executemany(
        "INSERT INTO sandwiches (name, filling, price) VALUES (?, ?, ?)",
        [(f"s{i}", f"f{i % 50}", i % 13) for i in range(100_000)],
    )
    store.conn.commit()

    m = Matcher(
        "storm", "SELECT filling, count(*) FROM sandwiches GROUP BY filling",
        (), store.conn, crr_tables(store),
        rerun_min_interval_s=0.5,
    )
    assert not m.keyed  # GROUP BY degrades to the fallback path
    m.run_initial()

    reruns0 = REGISTRY.counter("corro_subs_rerun_total").get()
    coalesced0 = REGISTRY.counter("corro_subs_rerun_coalesced_total").get()

    # a storm of 40 separate committed batches, arriving faster than the
    # budget window
    for i in range(40):
        changes = apply_local(
            store,
            "INSERT INTO sandwiches (name, filling) VALUES (?, 'stormfill')",
            (f"storm-{i}",),
        )
        m.handle_changes(changes, allow_defer=True)

    reruns = REGISTRY.counter("corro_subs_rerun_total").get() - reruns0
    coalesced = (
        REGISTRY.counter("corro_subs_rerun_coalesced_total").get() - coalesced0
    )
    # bounded: the 40 batches collapsed into very few re-runs
    assert reruns <= 3, reruns
    assert coalesced >= 37, coalesced
    assert m._rerun_dirty  # trailing work is pending, not lost

    # the deferred flush (manager's call_later path) lands the final state
    m._last_rerun_at = 0.0  # window elapsed
    events = m.flush_if_due()
    assert not m._rerun_dirty
    rows = {
        tuple(e["change"][2])
        for e in events
        if "change" in e and e["change"][0] in ("insert", "update")
    }
    assert ("stormfill", 40) in rows


def test_manager_schedules_trailing_flush():
    """End-to-end through SubsManager.match_changes on a running loop:
    batches inside the window defer, and the scheduled flush emits the
    coalesced events without further writes."""

    async def run():
        store = make_store()
        store.conn.executemany(
            "INSERT INTO sandwiches (name, filling) VALUES (?, 'x')",
            [(f"p{i}",) for i in range(1000)],
        )
        store.conn.commit()
        mgr = SubsManager(store)
        handle, _created = mgr.get_or_insert(
            "SELECT filling, count(*) FROM sandwiches GROUP BY filling", ()
        )
        handle.matcher.rerun_min_interval_s = 0.2
        handle.matcher.run_initial()
        q = handle.attach()

        # burst: several batches inside one window
        for i in range(5):
            changes = apply_local(
                store,
                "INSERT INTO sandwiches (name, filling) VALUES (?, 'burst')",
                (f"b{i}",),
            )
            mgr.match_changes(changes)

        # wait past the window for the trailing flush
        deadline = asyncio.get_event_loop().time() + 5.0
        seen = []
        while asyncio.get_event_loop().time() < deadline:
            try:
                ev = await asyncio.wait_for(q.get(), timeout=0.5)
            except asyncio.TimeoutError:
                if not handle.matcher._rerun_dirty:
                    break
                continue
            if "change" in ev:
                seen.append(tuple(ev["change"][2]))
                if ("burst", 5) in seen:
                    break
        assert ("burst", 5) in seen
        assert not handle.matcher._rerun_dirty

    asyncio.run(run())


def test_subscription_using_store_custom_function_compiles():
    """Table discovery runs on a throwaway schema clone (the live
    connection must never carry an authorizer — broken None-clear +
    executor-thread deadlock on some CPython 3.10 sqlite3 builds), so
    the store's custom SQL functions (corro_json_contains, crdt_*) must
    be stubbed onto the clone or valid subscriptions using them would
    be rejected as invalid queries."""
    store = make_store()
    apply_local(
        store,
        "INSERT INTO sandwiches (name, filling) "
        "VALUES ('blt', '{\"a\": 1, \"b\": 2}')",
    )
    m = Matcher(
        "sfn",
        "SELECT name FROM sandwiches "
        "WHERE corro_json_contains('{\"a\": 1}', filling)",
        (),
        store.conn,
        crr_tables(store),
    )
    assert set(m.tables) == {"sandwiches"}
    m.run_initial()  # executes on the REAL connection, real function


def test_discovery_leaves_no_authorizer_on_live_connection():
    """After building a Matcher, the shared connection must still run
    PRAGMAs and reads freely — the maintenance loop's PRAGMAs died
    "not authorized" when discovery left a hook behind."""
    store = make_store()
    Matcher("sa", "SELECT name FROM sandwiches", (), store.conn,
            crr_tables(store))
    (mode,) = store.conn.execute("PRAGMA auto_vacuum").fetchone()
    assert mode in (0, 1, 2)
    store.conn.execute("SELECT count(*) FROM sandwiches").fetchone()


def test_generated_column_table_survives_schema_clone():
    """The scratch clone's function stubs must be DETERMINISTIC and
    registered BEFORE the DDL replay: a generated column referencing a
    custom function is rejected at CREATE time otherwise, the table
    silently never exists on the clone, and a valid subscription on it
    dies 'no such table'."""
    store = make_store()
    store.conn.execute(
        "CREATE TABLE g (a TEXT PRIMARY KEY NOT NULL, "
        "b AS (corro_json_contains('{}', a)) VIRTUAL)"
    )
    m = Matcher(
        "g1", "SELECT a FROM g", (), store.conn,
        {**crr_tables(store), "g": ("a",)},
    )
    assert set(m.tables) == {"g"}
