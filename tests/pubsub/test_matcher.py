"""Matcher unit tests: keyed incremental diffs, fallback mode, change log
catch-up — the reference's pubsub.rs inline test coverage equivalents."""

import asyncio

import pytest

from corrosion_tpu.agent.store import CrrStore
from corrosion_tpu.core.types import ActorId
from corrosion_tpu.pubsub import Matcher, MatcherError, SubsManager, UpdatesManager

SCHEMA = """
CREATE TABLE sandwiches (
    name TEXT PRIMARY KEY NOT NULL,
    filling TEXT NOT NULL DEFAULT '',
    price REAL NOT NULL DEFAULT 0
);
CREATE TABLE shops (
    id INTEGER PRIMARY KEY NOT NULL,
    city TEXT NOT NULL DEFAULT ''
);
"""


def make_store():
    store = CrrStore(":memory:", ActorId.random())
    store.execute_schema(SCHEMA)
    return store


def crr_tables(store):
    return {name: info.pk_cols for name, info in store._tables.items()}


def apply_local(store, sql, params=()):
    """Commit a local write and return the captured changes."""
    _, info = store.transact([(sql, params)])
    assert info is not None
    return store.changes_for_version(store.site_id, info.db_version)


def test_keyed_single_table_lifecycle():
    store = make_store()
    apply_local(store, "INSERT INTO sandwiches (name, filling) VALUES ('blt', 'bacon')")
    m = Matcher("s1", "SELECT name, filling FROM sandwiches", (), store.conn,
                crr_tables(store))
    events = m.run_initial()
    assert events[0] == {"columns": ["name", "filling"]}
    assert events[1]["row"][1] == ["blt", "bacon"]
    assert "eoq" in events[-1]
    assert m.keyed

    # insert
    ev = m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name, filling) VALUES ('ham', 'ham')")
    )
    assert ev == [{"change": ["insert", ev[0]["change"][1], ["ham", "ham"], 1]}]
    # update
    ev = m.handle_changes(
        apply_local(store, "UPDATE sandwiches SET filling = 'maple ham' WHERE name = 'ham'")
    )
    assert ev[0]["change"][0] == "update"
    assert ev[0]["change"][2] == ["ham", "maple ham"]
    # delete
    ev = m.handle_changes(apply_local(store, "DELETE FROM sandwiches WHERE name = 'blt'"))
    assert ev[0]["change"][0] == "delete"
    assert ev[0]["change"][2] == ["blt", "bacon"]
    assert m.last_change_id == 3


def test_keyed_where_clause_filters_rows():
    store = make_store()
    m = Matcher("s2", "SELECT name FROM sandwiches WHERE price > 5", (), store.conn,
                crr_tables(store))
    m.run_initial()
    ev = m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name, price) VALUES ('cheap', 1)")
    )
    assert ev == []  # filtered out
    ev = m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name, price) VALUES ('lux', 12)")
    )
    assert ev[0]["change"][:1] == ["insert"]
    # price drop moves it out of the result set → delete event
    ev = m.handle_changes(
        apply_local(store, "UPDATE sandwiches SET price = 2 WHERE name = 'lux'")
    )
    assert ev[0]["change"][0] == "delete"


def test_keyed_join_two_tables():
    store = make_store()
    apply_local(store, "INSERT INTO shops (id, city) VALUES (1, 'lisbon')")
    m = Matcher(
        "s3",
        "SELECT s.name, h.city FROM sandwiches s JOIN shops h ON h.id = 1",
        (), store.conn, crr_tables(store),
    )
    m.run_initial()
    assert m.keyed
    ev = m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name) VALUES ('paris')")
    )
    assert ev[0]["change"][2] == ["paris", "lisbon"]
    # change on the joined table side also lands
    ev = m.handle_changes(
        apply_local(store, "UPDATE shops SET city = 'porto' WHERE id = 1")
    )
    assert ev[0]["change"][0] == "update"
    assert ev[0]["change"][2] == ["paris", "porto"]


def test_aggregate_falls_back_to_full_mode():
    store = make_store()
    m = Matcher("s4", "SELECT COUNT(*) FROM sandwiches", (), store.conn,
                crr_tables(store))
    assert not m.keyed
    events = m.run_initial()
    assert events[1]["row"][1] == [0]
    ev = m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name) VALUES ('one')")
    )
    assert ev[0]["change"][0] == "update"
    assert ev[0]["change"][2] == [1]


def test_params_and_catchup():
    store = make_store()
    m = Matcher("s5", "SELECT name FROM sandwiches WHERE filling = ?", ("x",),
                store.conn, crr_tables(store))
    m.run_initial()
    m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name, filling) VALUES ('a', 'x')")
    )
    m.handle_changes(
        apply_local(store, "INSERT INTO sandwiches (name, filling) VALUES ('b', 'x')")
    )
    assert [e["change"][3] for e in m.changes_since(0)] == [1, 2]
    assert [e["change"][3] for e in m.changes_since(1)] == [2]


def test_non_select_rejected():
    store = make_store()
    with pytest.raises(MatcherError):
        Matcher("bad", "DELETE FROM sandwiches", (), store.conn, crr_tables(store))
    with pytest.raises(MatcherError):
        Matcher("bad2", "SELECT 1", (), store.conn, crr_tables(store))


def test_subs_manager_share_and_remove():
    async def body():
        store = make_store()
        subs = SubsManager(store)
        h1, created1 = subs.get_or_insert("SELECT name FROM sandwiches")
        h2, created2 = subs.get_or_insert("select   name from sandwiches")
        assert created1 and not created2
        assert h1.id == h2.id
        q = h1.attach()
        subs.match_changes(
            apply_local(store, "INSERT INTO sandwiches (name) VALUES ('z')")
        )
        ev = q.get_nowait()
        assert ev["change"][0] == "insert"
        subs.remove(h1.id)
        assert subs.get(h1.id) is None
        row = store.conn.execute("SELECT COUNT(*) FROM __corro_subs").fetchone()
        assert row[0] == 0

    asyncio.run(body())


def test_updates_manager_notify_events():
    async def body():
        store = make_store()
        um = UpdatesManager()
        q = um.attach("sandwiches")
        um.match_changes(
            apply_local(store, "INSERT INTO sandwiches (name) VALUES ('n1')")
        )
        assert q.get_nowait() == {"notify": ["update", ["n1"]]}
        um.match_changes(apply_local(store, "DELETE FROM sandwiches WHERE name = 'n1'"))
        assert q.get_nowait() == {"notify": ["delete", ["n1"]]}

    asyncio.run(body())
