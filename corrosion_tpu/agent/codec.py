"""Wire codec for gossip payloads.

The reference speedy-encodes enums into length-delimited frames
(`UniPayload`/`BiPayload`/`SyncMessage`, corro-types/src/broadcast.rs +
sync.rs).  Ours is a compact JSON encoding (bytes as base64) — both ends are
this framework, the framing/verb split carries the semantics, and the hot
path (the simulator) never touches this codec.  A binary C++ codec can slot
in here later without touching callers.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Dict, List, Optional, Tuple

from ..core.types import (
    ActorId,
    Change,
    Changeset,
    ChangesetPart,
    SyncNeed,
    SyncState,
)


def _b(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")


def _ub(s: str) -> bytes:
    return base64.b64decode(s)


def _enc_val(v):
    if isinstance(v, (bytes, bytearray, memoryview)):
        return {"$b": _b(bytes(v))}
    return v


def _dec_val(v):
    if isinstance(v, dict) and "$b" in v:
        return _ub(v["$b"])
    return v


def encode_change(ch: Change) -> list:
    return [
        ch.table, _b(ch.pk), ch.cid, _enc_val(ch.val), ch.col_version,
        ch.db_version, ch.seq, ch.site_id.hex(), ch.cl,
    ]


def decode_change(row: list) -> Change:
    return Change(
        table=row[0], pk=_ub(row[1]), cid=row[2], val=_dec_val(row[3]),
        col_version=row[4], db_version=row[5], seq=row[6],
        site_id=ActorId.from_hex(row[7]), cl=row[8],
    )


def encode_changeset(cs: Changeset) -> dict:
    return {
        "actor": cs.actor_id.hex(),
        "v": cs.version,
        "vhi": cs.versions_hi,
        "part": cs.part.value,
        "seqs": list(cs.seqs),
        "last_seq": cs.last_seq,
        "ts": cs.ts,
        "changes": [encode_change(c) for c in cs.changes],
    }


def decode_changeset(d: dict) -> Changeset:
    return Changeset(
        actor_id=ActorId.from_hex(d["actor"]),
        version=d["v"],
        versions_hi=d.get("vhi"),
        part=ChangesetPart(d["part"]),
        seqs=tuple(d["seqs"]),
        last_seq=d["last_seq"],
        ts=d["ts"],
        changes=tuple(decode_change(c) for c in d["changes"]),
    )


def encode_sync_state(s: SyncState) -> dict:
    return {
        "actor": s.actor_id.hex(),
        "heads": {a.hex(): v for a, v in s.heads.items()},
        "need": {a.hex(): [list(r) for r in v] for a, v in s.need.items()},
        "partial": {
            a.hex(): {str(ver): [list(r) for r in seqs] for ver, seqs in m.items()}
            for a, m in s.partial_need.items()
        },
        "cleared_ts": s.last_cleared_ts,
    }


def decode_sync_state(d: dict) -> SyncState:
    return SyncState(
        actor_id=ActorId.from_hex(d["actor"]),
        heads={ActorId.from_hex(a): v for a, v in d["heads"].items()},
        need={
            ActorId.from_hex(a): [tuple(r) for r in v] for a, v in d["need"].items()
        },
        partial_need={
            ActorId.from_hex(a): {int(ver): [tuple(r) for r in seqs] for ver, seqs in m.items()}
            for a, m in d["partial"].items()
        },
        last_cleared_ts=d.get("cleared_ts"),
    )


def encode_needs(needs: Dict[ActorId, List[SyncNeed]]) -> dict:
    out = {}
    for actor, lst in needs.items():
        out[actor.hex()] = [
            {"k": n.kind, "v": list(n.versions), "ver": n.version,
             "seqs": [list(r) for r in n.seqs]}
            for n in lst
        ]
    return out


def decode_needs(d: dict) -> Dict[ActorId, List[SyncNeed]]:
    out = {}
    for a, lst in d.items():
        out[ActorId.from_hex(a)] = [
            SyncNeed(
                kind=n["k"], versions=tuple(n["v"]), version=n["ver"],
                seqs=tuple(tuple(r) for r in n["seqs"]),
            )
            for n in lst
        ]
    return out


def encode_message(
    kind: str,
    body: Any,
    ts: Optional[int] = None,
    trace: Optional[dict] = None,
    cid: int = 0,
) -> bytes:
    """One framed gossip message: {"t": kind, "ts": clock, "b": body}.
    ``trace`` adds an optional "tr" carrier — the SyncTraceContextV1
    {traceparent, tracestate} riding the sync handshake
    (corro-types/src/sync.rs:33-67).  ``cid`` stamps the sender's cluster
    id; a missing "cid" key decodes as cluster 0 (the reference carries
    the cluster id on every BroadcastV1 frame and the sync handshake —
    uni.rs:73-75, peer/mod.rs:1431)."""
    env = {"t": kind, "ts": ts, "b": body}
    if trace:
        env["tr"] = trace
    if cid:
        env["cid"] = cid
    return json.dumps(env, separators=(",", ":")).encode("utf-8")


def decode_message(data: bytes) -> Tuple[str, Any, Optional[int]]:
    return decode_message_full(data)[:3]


def decode_message_full(
    data: bytes,
) -> Tuple[str, Any, Optional[int], Optional[dict], int]:
    """decode_message plus the optional trace carrier (serve_sync's
    extraction side, peer/mod.rs:1415-1417) plus the sender's cluster id
    (0 when the frame predates / omits the stamp)."""
    d = json.loads(data)
    return d["t"], d.get("b"), d.get("ts"), d.get("tr"), d.get("cid", 0)
