"""CRR SQLite store: CRDT-replicated tables without cr-sqlite.

This is the rebuild's L0 layer — the equivalent of the prebuilt cr-sqlite C
extension the reference loads (`corro-types/src/sqlite.rs:121-139`) plus the
`crsql_*` API surface it consumes (`crsql_as_crr`, `crsql_changes`,
`crsql_site_id`, `crsql_db_version`, `crsql_peek_next_db_version`,
`crsql_set_ts`, `crsql_rows_impacted`; see SURVEY.md §2.2).  Implemented
natively on sqlite3 with:

- a per-table clock table ``{T}__crdt_clock(pk, cid, val, col_version,
  db_version, seq, site_id, ts)`` — like cr-sqlite's ``__crsql_clock`` but
  denormalised with the current winning value so the changes feed is one scan;
- a per-table row table ``{T}__crdt_rows(pk, cl)`` holding causal length
  (odd = alive, even = deleted; tombstones survive row deletion);
- SQL triggers on the base table that capture **local** writes (gated on the
  ``crdt_applying()`` app function so remote merges don't re-trigger);
- Python-side merge application implementing the cr-sqlite rules via
  ``corrosion_tpu.core.crdt`` (optionally accelerated by the C++ core).

Like the reference (doc/crdts.md:29), all writes must go through the agent:
one writer connection, db_version allocated per committed write transaction,
seq = ordinal of the column change inside the transaction.
"""

from __future__ import annotations

import json
import logging
import sqlite3
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from ..core.crdt import MergeOutcome, merge_cell, row_alive
from ..core.hlc import HLC
from ..core.pkcodec import decode_pk, encode_pk
from ..core.schema import (
    SchemaError,
    SchemaTable,
    normalize_sql as _normalize_sql,
    parse_schema,
    table_columns as _table_columns,
    table_shape as _table_shape,
)
from ..core.types import Change, DELETE_SENTINEL, PKONLY_SENTINEL, ActorId, SqliteValue


@dataclass
class TableInfo:
    name: str
    pk_cols: Tuple[str, ...]
    non_pk_cols: Tuple[str, ...]

    @property
    def clock(self) -> str:
        return f"{self.name}__crdt_clock"

    @property
    def rows(self) -> str:
        return f"{self.name}__crdt_rows"


@dataclass
class CommitInfo:
    db_version: int
    last_seq: int
    ts: int


READ_POOL_SIZE = 5


class _ReadPool:
    """Small lazy pool of read-only connections (SplitPool's RO side,
    agent.rs:419-498, sized down: 5 vs the reference's 20 — Python threads
    saturate far fewer concurrent reads).

    Connections are created on demand up to ``size``; ``acquire`` blocks
    when all are checked out.  ``add_init`` replays a setup hook over
    existing and future connections (catalog attach etc.)."""

    def __init__(self, factory: Callable[[], sqlite3.Connection], size: int):
        self._factory = factory
        self._size = size
        self._cond = threading.Condition()
        self._free: List[sqlite3.Connection] = []
        self._all: List[sqlite3.Connection] = []
        self._inits: List[Callable[[sqlite3.Connection], None]] = []
        self._reserved = 0  # slots claimed by in-flight connection creation
        self._closed = False

    def add_init(self, fn: Callable[[sqlite3.Connection], None]) -> None:
        with self._cond:
            self._inits.append(fn)
            existing = list(self._all)
        for conn in existing:
            fn(conn)

    def acquire(self, timeout: Optional[float] = 30.0) -> sqlite3.Connection:
        grow = False
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while True:
                if self._closed:
                    raise sqlite3.ProgrammingError("read pool closed")
                if self._free:
                    return self._free.pop()
                if self._reserved + len(self._all) < self._size:
                    self._reserved += 1  # slot claimed; connect outside lock
                    grow = True
                    break
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("read pool exhausted")
                if not self._cond.wait(timeout=remaining):
                    raise TimeoutError("read pool exhausted")
        # connection creation + init (catalog attach etc.) can be slow;
        # never hold the pool lock across them
        assert grow
        try:
            conn = self._factory()
            with self._cond:
                inits = list(self._inits)
            for fn in inits:
                fn(conn)
        except BaseException:
            with self._cond:
                self._reserved -= 1
                self._cond.notify()
            raise
        with self._cond:
            self._reserved -= 1
            self._all.append(conn)
        return conn

    def release(self, conn: sqlite3.Connection) -> None:
        with self._cond:
            if self._closed:
                return
            self._free.append(conn)
            self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            for conn in self._all:
                try:
                    conn.close()
                except sqlite3.ProgrammingError:
                    pass
            self._all.clear()
            self._free.clear()
            self._cond.notify_all()


class CrrStore:
    """One node's storage: base tables + CRDT clocks + bookkeeping tables."""

    def __init__(self, path: str, site_id: ActorId, clock: Optional[HLC] = None):
        self.path = path
        self.clock = clock or HLC()
        # serving telemetry handle (ISSUE 8): None = off, one attribute
        # test per transact (telemetry.attach_host_telemetry arms it)
        self.telemetry = None
        self.conn = sqlite3.connect(path, check_same_thread=False, isolation_level=None)
        self.conn.row_factory = sqlite3.Row
        # before any table exists (setup.rs:84-93); a pre-existing DB in
        # another mode stays there until a manual VACUUM
        self.conn.execute("PRAGMA auto_vacuum = INCREMENTAL")
        self.conn.execute("PRAGMA journal_mode = WAL")
        self.conn.execute("PRAGMA synchronous = NORMAL")
        self._lock = threading.RLock()  # the ONE writer lane (agent.rs:97 write_sema)
        self._closed = False  # guards maintenance threads vs close()
        self._tables: Dict[str, TableInfo] = {}
        self._applying = False
        self._pending_dbv = 0
        self._seq = 0
        self._pending_ts = 0
        self._last_dml_changes = 0
        self._register_functions()
        self._migrate()
        self.site_id = self._init_site_id(site_id)
        self._load_tables()
        # read-only connection pool for client queries (the reference keeps
        # a 20-conn RO pool, agent.rs:419-498): keeps arbitrary SQL off the
        # trigger-armed writer, and an interrupted slow read only aborts the
        # statements on ITS connection, not every in-flight read
        if path not in (":memory:", ""):
            self._read_pool: Optional[_ReadPool] = _ReadPool(
                self._new_read_conn, size=READ_POOL_SIZE
            )
            # dedicated direct handle OUTSIDE the pool (metrics thread and
            # tests): pool checkouts can be watchdog-interrupted, and a
            # shared member would cross-abort the direct user's statements
            self.read_conn = self._new_read_conn()
        else:
            self._read_pool = None
            self.read_conn = self.conn  # in-memory: single-conn fallback

    def _new_read_conn(self) -> sqlite3.Connection:
        # autocommit (isolation_level=None): DML on ATTACHed scratch DBs
        # (pg_catalog refresh) must not open an implicit transaction that
        # would freeze this conn's read snapshot of the main DB forever
        conn = sqlite3.connect(
            f"file:{self.path}?mode=ro",
            uri=True,
            check_same_thread=False,
            isolation_level=None,
        )
        conn.row_factory = sqlite3.Row
        # client-facing SQL helpers must exist on the read lane too —
        # API queries and templates execute there
        conn.create_function(
            "corro_json_contains", 2, _corro_json_contains, deterministic=True
        )
        return conn

    def add_read_conn_init(self, fn: Callable[[sqlite3.Connection], None]) -> None:
        """Run ``fn`` on every read connection, existing and future (used by
        the PG bridge to attach pg_catalog + session functions on the read
        lane).  No-op target on in-memory stores where reads share the
        writer conn — callers must apply their init to ``conn`` themselves."""
        if self._read_pool is not None:
            self._read_pool.add_init(fn)

    @property
    def has_read_pool(self) -> bool:
        """False for in-memory stores, where reads share the writer conn
        and must stay serialized on the caller's thread/loop."""
        return self._read_pool is not None

    # -- setup ------------------------------------------------------------

    def _register_functions(self):
        c = self.conn
        c.create_function("crdt_applying", 0, lambda: 1 if self._applying else 0)
        c.create_function("crdt_dbv", 0, lambda: self._pending_dbv)
        c.create_function("crdt_ts", 0, lambda: self._pending_ts)
        c.create_function("crdt_site", 0, lambda: self.site_id.bytes_)
        c.create_function("crdt_seq", 0, self._next_seq)
        c.create_function(
            "crdt_pk", -1, lambda *vals: encode_pk(vals), deterministic=True
        )
        # custom SQL helpers (sqlite-functions/src/lib.rs:5-51): JSON
        # object-subset match, used by consul-state templates
        c.create_function(
            "corro_json_contains", 2, _corro_json_contains, deterministic=True
        )

    def _next_seq(self) -> int:
        s = self._seq
        self._seq += 1
        return s

    def _migrate(self):
        """Internal tables (reference migrate(), corro-types/agent.rs:282-365)."""
        self.conn.executescript(
            """
            CREATE TABLE IF NOT EXISTS __corro_state (key TEXT PRIMARY KEY, value);
            CREATE TABLE IF NOT EXISTS __crdt_tables (
                name TEXT PRIMARY KEY, pks TEXT NOT NULL, cols TEXT NOT NULL);
            CREATE TABLE IF NOT EXISTS __crdt_db_versions (
                site_id BLOB PRIMARY KEY, db_version INTEGER NOT NULL);
            CREATE TABLE IF NOT EXISTS __corro_bookkeeping_gaps (
                actor_id BLOB, start INTEGER, end INTEGER,
                PRIMARY KEY (actor_id, start)) WITHOUT ROWID;
            CREATE TABLE IF NOT EXISTS __corro_seq_bookkeeping (
                site_id BLOB, db_version INTEGER, start_seq INTEGER,
                end_seq INTEGER, last_seq INTEGER, ts INTEGER,
                PRIMARY KEY (site_id, db_version, start_seq)) WITHOUT ROWID;
            CREATE TABLE IF NOT EXISTS __corro_buffered_changes (
                "table" TEXT, pk BLOB, cid TEXT, val, col_version INTEGER,
                db_version INTEGER, seq INTEGER, site_id BLOB, cl INTEGER,
                ts INTEGER,
                PRIMARY KEY (site_id, db_version, seq)) WITHOUT ROWID;
            CREATE TABLE IF NOT EXISTS __corro_members (
                actor_id BLOB PRIMARY KEY, address TEXT NOT NULL,
                doomed INTEGER DEFAULT 0, foca_state TEXT);
            CREATE TABLE IF NOT EXISTS __corro_subs (
                id TEXT PRIMARY KEY, sql TEXT NOT NULL);
            """
        )

    def _init_site_id(self, site_id: ActorId) -> ActorId:
        row = self.conn.execute(
            "SELECT value FROM __corro_state WHERE key = 'site_id'"
        ).fetchone()
        if row is not None:
            return ActorId(row[0])
        self.conn.execute(
            "INSERT INTO __corro_state (key, value) VALUES ('site_id', ?)",
            (site_id.bytes_,),
        )
        return site_id

    @property
    def tables(self) -> Tuple[str, ...]:
        """Names of the replicated (CRR) tables."""
        return tuple(self._tables)

    def _load_tables(self):
        for name, pks, cols in self.conn.execute(
            "SELECT name, pks, cols FROM __crdt_tables"
        ):
            info = TableInfo(name, tuple(json.loads(pks)), tuple(json.loads(cols)))
            self._tables[name] = info
            self._create_triggers(info)

    # -- schema -----------------------------------------------------------

    def execute_schema(self, schema_sql: str) -> List[str]:
        """Apply a schema file with live-migration diffing (the reference's
        `apply_schema`, corro-types/src/schema.rs:274-608, plus the
        `constrain` pass, schema.rs:113-168).

        Returns the list of newly replicated table names."""
        return self.apply_schema(schema_sql)["new_tables"]

    def apply_schema(self, schema_sql: str) -> Dict[str, object]:
        """Diff the desired schema against the live DB and migrate:

        - new tables: created + CRR'd + their indexes (schema.rs:310-385);
          a pre-existing identical table is adopted (schema.rs:322-360)
        - dropped tables: rejected (DropTableWithoutDestructiveFlag,
          schema.rs:279-290)
        - dropped/changed columns: rejected (schema.rs:414-455)
        - new columns: must be non-PK and nullable-or-defaulted; applied via
          ALTER TABLE ADD COLUMN (schema.rs:458-510)
        - indexes on kept tables: created/dropped to match (schema.rs:585+)
        """
        desired = parse_schema(schema_sql)
        out: Dict[str, object] = {"new_tables": [], "new_columns": {}}
        with self._lock:
            current_names = set(self._tables)
            dropped = current_names - set(desired.tables)
            if dropped:
                raise SchemaError(
                    f"cannot drop table {sorted(dropped)[0]!r} without a "
                    "destructive migration"
                )
            # DDL (tables/triggers/indexes) is transactional in SQLite, but
            # the in-memory registry is not — snapshot it so a failed
            # migration leaves no ghost entries pointing at rolled-back
            # clock tables.
            tables_snapshot = dict(self._tables)
            self.conn.execute("BEGIN")
            try:
                for name, tbl in desired.tables.items():
                    if name in self._tables:
                        self._migrate_table(tbl, out)
                    else:
                        self._create_schema_table(tbl, out)
                self.conn.execute("COMMIT")
            except Exception:
                self.conn.execute("ROLLBACK")
                self._tables = tables_snapshot
                raise
        return out

    def merge_schema(self, statements: Sequence[str]) -> Dict[str, object]:
        """Merge partial schema statements into the live schema — the
        `/v1/migrations` semantics (api/public/mod.rs:540-562): tables in
        `statements` overwrite their previous definition ("users are
        expected to return a full table def"); unmentioned tables are kept.
        """
        partial_sql = ";\n".join(statements)
        partial = parse_schema(partial_sql)
        with self._lock:
            keep: List[str] = []
            for name in self._tables:
                if name in partial.tables:
                    continue
                # our clock/rows side tables and the _dbv index live under
                # their own tbl_name, so tbl_name = base-table already
                # excludes them
                for (sql,) in self.conn.execute(
                    "SELECT sql FROM sqlite_master WHERE tbl_name = ? AND "
                    "type IN ('table', 'index') AND sql IS NOT NULL",
                    (name,),
                ):
                    keep.append(sql)
            return self.apply_schema(";\n".join(keep + [partial_sql]))

    def _create_schema_table(self, tbl: "SchemaTable", out: Dict[str, object]):
        exists = self.conn.execute(
            "SELECT sql FROM sqlite_master WHERE type = 'table' AND name = ?",
            (tbl.name,),
        ).fetchone()
        if exists is None:
            self.conn.execute(tbl.sql)
        else:
            # reconcile an untracked pre-existing table (schema.rs:322-360):
            # adopt it only if pk + columns match exactly
            live = _table_shape(self.conn, tbl.name)
            if live != tbl.shape():
                raise SchemaError(
                    f"existing table {tbl.name!r} does not match schema: "
                    f"have {live}, want {tbl.shape()}"
                )
        for idx in tbl.indexes:
            self.conn.execute(f'DROP INDEX IF EXISTS "{idx.name}"')
            self.conn.execute(idx.sql)
        out["new_tables"].append(tbl.name)  # type: ignore[union-attr]
        self.create_crr(tbl.name)

    def _migrate_table(self, tbl: "SchemaTable", out: Dict[str, object]):
        info = self._tables[tbl.name]
        live_cols = {c.name: c for c in _table_columns(self.conn, tbl.name)}
        want_cols = {c.name: c for c in tbl.columns}

        dropped = set(live_cols) - set(want_cols)
        if dropped:
            raise SchemaError(
                f"cannot remove column {sorted(dropped)[0]!r} from "
                f"{tbl.name!r} without a destructive migration"
            )
        changed = [
            n for n in live_cols if n in want_cols and live_cols[n] != want_cols[n]
        ]
        if changed:
            raise SchemaError(
                f"cannot change column(s) {','.join(sorted(changed))} of "
                f"{tbl.name!r} without a destructive migration"
            )

        added = [want_cols[n] for n in want_cols if n not in live_cols]
        for col in added:
            if col.pk:
                raise SchemaError(
                    f"cannot add primary-key column {col.name!r} to {tbl.name!r}"
                )
            if col.notnull and col.default is None:
                raise SchemaError(
                    f"new column {tbl.name}.{col.name} is NOT NULL and has "
                    "no default"
                )
            # raw source DDL keeps GENERATED/COLLATE/CHECK clauses that
            # PRAGMA introspection can't reconstruct
            self.conn.execute(
                f'ALTER TABLE "{tbl.name}" ADD COLUMN '
                f"{tbl.column_ddl(col.name) or col.ddl()}"
            )
        # generated columns are derived, never clocked/replicated (matching
        # create_crr, whose table_info introspection omits them)
        replicated_added = [c for c in added if not c.generated]
        if replicated_added:
            non_pk = info.non_pk_cols + tuple(c.name for c in replicated_added)
            info = TableInfo(tbl.name, info.pk_cols, non_pk)
            self.conn.execute(
                "UPDATE __crdt_tables SET cols = ? WHERE name = ?",
                (json.dumps(non_pk), tbl.name),
            )
            self._tables[tbl.name] = info
            self._create_triggers(info)
        if added:
            out["new_columns"][tbl.name] = [c.name for c in added]  # type: ignore[index]

        # index diff: schema-managed indexes only (never our __crdt/_dbv ones)
        live_idx = {
            r[0]: r[1]
            for r in self.conn.execute(
                "SELECT name, sql FROM sqlite_master WHERE type = 'index' "
                "AND tbl_name = ? AND sql IS NOT NULL",
                (tbl.name,),
            )
            if not r[0].endswith("_dbv")
        }
        want_idx = {i.name: i for i in tbl.indexes}
        for name in set(live_idx) - set(want_idx):
            self.conn.execute(f'DROP INDEX IF EXISTS "{name}"')
        for name, idx in want_idx.items():
            if name not in live_idx:
                self.conn.execute(idx.sql)
            elif _normalize_sql(live_idx[name]) != _normalize_sql(idx.sql):
                self.conn.execute(f'DROP INDEX "{name}"')
                self.conn.execute(idx.sql)

    def create_crr(self, name: str) -> TableInfo:
        """`crsql_as_crr` equivalent: attach clock/rows tables + triggers."""
        cols = self.conn.execute(f'PRAGMA table_info("{name}")').fetchall()
        if not cols:
            raise ValueError(f"no such table: {name}")
        pk_cols = tuple(r["name"] for r in sorted(
            (r for r in cols if r["pk"] > 0), key=lambda r: r["pk"]
        ))
        if not pk_cols:
            raise ValueError(f"CRR table {name} must have a primary key")
        non_pk = tuple(r["name"] for r in cols if r["pk"] == 0)
        info = TableInfo(name, pk_cols, non_pk)
        self.conn.execute(
            f'''CREATE TABLE IF NOT EXISTS "{info.clock}" (
                pk BLOB NOT NULL, cid TEXT NOT NULL, val,
                col_version INTEGER NOT NULL, db_version INTEGER NOT NULL,
                seq INTEGER NOT NULL, site_id BLOB NOT NULL,
                ts INTEGER NOT NULL DEFAULT 0,
                PRIMARY KEY (pk, cid)) WITHOUT ROWID'''
        )
        self.conn.execute(
            f'CREATE INDEX IF NOT EXISTS "{info.clock}_dbv" ON "{info.clock}" (site_id, db_version)'
        )
        self.conn.execute(
            f'''CREATE TABLE IF NOT EXISTS "{info.rows}" (
                pk BLOB PRIMARY KEY, cl INTEGER NOT NULL) WITHOUT ROWID'''
        )
        self.conn.execute(
            "INSERT OR REPLACE INTO __crdt_tables (name, pks, cols) VALUES (?, ?, ?)",
            (name, json.dumps(pk_cols), json.dumps(non_pk)),
        )
        self._tables[name] = info
        self._create_triggers(info)
        return info

    def _create_triggers(self, info: TableInfo):
        """Local-write capture (cr-sqlite's generated triggers equivalent).
        Gated on crdt_applying() so remote merge writes don't loop."""
        t, q = info.name, lambda s: f'"{s}"'
        new_pk = "crdt_pk(" + ", ".join(f'NEW.{q(c)}' for c in info.pk_cols) + ")"
        old_pk = "crdt_pk(" + ", ".join(f'OLD.{q(c)}' for c in info.pk_cols) + ")"

        clock_upsert = (
            f'INSERT INTO {q(info.clock)} (pk, cid, val, col_version, db_version, seq, site_id, ts) '
            "VALUES ({pk}, {cid}, {val}, 1, crdt_dbv(), crdt_seq(), crdt_site(), crdt_ts()) "
            "ON CONFLICT (pk, cid) DO UPDATE SET col_version = col_version + 1, "
            "val = excluded.val, db_version = excluded.db_version, "
            "seq = excluded.seq, site_id = excluded.site_id, ts = excluded.ts;"
        )

        # INSERT: bump causal length to alive, clock every non-pk column
        body = [
            f'INSERT INTO {q(info.rows)} (pk, cl) VALUES ({new_pk}, 1) '
            "ON CONFLICT (pk) DO UPDATE SET cl = CASE WHEN cl % 2 = 0 THEN cl + 1 ELSE cl END;"
        ]
        if info.non_pk_cols:
            for c in info.non_pk_cols:
                body.append(clock_upsert.format(pk=new_pk, cid=f"'{c}'", val=f"NEW.{q(c)}"))
        else:
            body.append(clock_upsert.format(pk=new_pk, cid=f"'{PKONLY_SENTINEL}'", val="NULL"))
        self._trigger(f"{t}__crdt_ins", f'AFTER INSERT ON {q(t)}', body)

        # UPDATE: one trigger per column, only when the value actually changed
        for c in info.non_pk_cols:
            self._trigger(
                f"{t}__crdt_upd_{c}",
                f'AFTER UPDATE OF {q(c)} ON {q(t)}',
                [clock_upsert.format(pk=new_pk, cid=f"'{c}'", val=f"NEW.{q(c)}")],
                extra_when=f'OLD.{q(c)} IS NOT NEW.{q(c)}',
            )

        # DELETE: even causal length, clear column clocks, write tombstone clock
        self._trigger(
            f"{t}__crdt_delt",
            f'AFTER DELETE ON {q(t)}',
            [
                f'UPDATE {q(info.rows)} SET cl = cl + 1 WHERE pk = {old_pk} AND cl % 2 = 1;',
                f'DELETE FROM {q(info.clock)} WHERE pk = {old_pk};',
                clock_upsert.format(pk=old_pk, cid=f"'{DELETE_SENTINEL}'", val="NULL"),
            ],
        )

    def _trigger(self, name: str, event: str, body: List[str], extra_when: str = ""):
        when = "crdt_applying() = 0" + (f" AND ({extra_when})" if extra_when else "")
        self.conn.execute(f'DROP TRIGGER IF EXISTS "{name}"')
        self.conn.execute(
            f'CREATE TRIGGER "{name}" {event} WHEN {when} BEGIN\n'
            + "\n".join(body)
            + "\nEND"
        )

    # -- versions ---------------------------------------------------------

    def db_version(self, site_id: Optional[ActorId] = None) -> int:
        """Max applied db_version for a site (crsql_db_version equivalent)."""
        site = (site_id or self.site_id).bytes_
        row = self.conn.execute(
            "SELECT db_version FROM __crdt_db_versions WHERE site_id = ?", (site,)
        ).fetchone()
        return row[0] if row else 0

    def peek_next_db_version(self) -> int:
        return self.db_version() + 1

    # -- local writes -----------------------------------------------------

    def transact(
        self,
        statements: Sequence[Tuple[str, Sequence[SqliteValue]]],
        pre_commit: Optional[Callable[[sqlite3.Connection, CommitInfo], None]] = None,
    ) -> Tuple[List[sqlite3.Cursor], Optional[CommitInfo]]:
        """Run write statements in one transaction; triggers capture CRDT
        changes under a freshly allocated db_version (the reference's
        `make_broadcastable_changes`, api/public/mod.rs:53-138).

        ``pre_commit`` runs inside the transaction after changes exist —
        the agent uses it to persist bookkeeping atomically with the data
        (insert_local_changes, change.rs:189-260).

        Serving telemetry (ISSUE 8): ``self.telemetry`` (attached by
        `telemetry.attach_host_telemetry`, None otherwise — the
        measured-no-op rule every hook site follows) observes the
        whole-transaction wall on the sub-ms serving ladder
        (corro_store_transact_seconds — local commits on an in-memory
        store are ~100 µs, unresolvable on the default 1 ms+ ladder)."""
        tel = self.telemetry
        t0 = time.monotonic() if tel is not None else 0.0
        with self._lock:
            self.begin_interactive()
            try:
                results = []
                for sql, params in statements:
                    results.append(self.exec_interactive(sql, params))
                out = results, self.commit_interactive(pre_commit)
            except Exception:
                self.rollback_interactive()
                raise
        if tel is not None:
            tel.store_transact(time.monotonic() - t0)
        return out

    # -- interactive write transaction ------------------------------------
    # The PG front-end holds one of these open across wire messages
    # (corro-pg keeps the pooled write conn checked out for the explicit
    # tx, lib.rs:1950-2117).  Caller must serialize via the agent's
    # write semaphore; while open, reads on this conn see uncommitted
    # rows (the reference reads from separate RO conns instead).

    def begin_interactive(self) -> None:
        self._pending_dbv = self.peek_next_db_version()
        self._seq = 0
        self._pending_ts = self.clock.now()
        self._applying = False
        self.conn.execute("BEGIN IMMEDIATE")

    def exec_interactive(self, sql: str, params: Sequence[SqliteValue] = ()):
        cur = self.conn.execute(sql, tuple(params))
        if cur.rowcount >= 0:
            self._last_dml_changes = cur.rowcount
        else:
            # Python's sqlite3 only fills rowcount for statements it sniffs
            # as DML; a WITH-prefixed INSERT/UPDATE/DELETE reports -1, so
            # ask SQLite directly (command tags must be accurate — PG
            # clients branch on them)
            self._last_dml_changes = self.conn.execute(
                "SELECT changes()"
            ).fetchone()[0]
        return cur

    @property
    def last_dml_changes(self) -> int:
        """Rows changed by the most recent exec_interactive DML statement."""
        return self._last_dml_changes

    def commit_interactive(
        self,
        pre_commit: Optional[Callable[[sqlite3.Connection, CommitInfo], None]] = None,
    ) -> Optional[CommitInfo]:
        info = None
        if self._seq > 0:  # at least one captured change
            info = CommitInfo(
                db_version=self._pending_dbv,
                last_seq=self._seq - 1,
                ts=self._pending_ts,
            )
            self.conn.execute(
                "INSERT INTO __crdt_db_versions (site_id, db_version) VALUES (?, ?) "
                "ON CONFLICT (site_id) DO UPDATE SET db_version = excluded.db_version",
                (self.site_id.bytes_, info.db_version),
            )
            if pre_commit:
                pre_commit(self.conn, info)
        self.conn.execute("COMMIT")
        return info

    def rollback_interactive(self) -> None:
        try:
            self.conn.execute("ROLLBACK")
        except sqlite3.OperationalError:
            pass  # no tx active (e.g. BEGIN itself failed)

    # -- reads ------------------------------------------------------------

    @contextmanager
    def interruptible_read(
        self,
        timeout_s: Optional[float] = None,
        slow_warn_s: Optional[float] = 1.0,
        label: str = "",
    ):
        """Bound a read on ``read_conn``: a shared watchdog fires
        ``sqlite3_interrupt`` at the deadline (InterruptibleStatement,
        sqlite-pool/src/lib.rs:116,259) and statements at/over the slow
        threshold warn (the trace_v2 PROFILE hook, sqlite.rs:51-61).

        The connection comes from the RO pool, so an interrupt only aborts
        statements on THIS connection — concurrent reads on other pool
        members are untouched (the reference's SplitPool isolation,
        agent.rs:419-498)."""
        with self.read_lease() as conn:
            with self.interrupt_window(
                conn, timeout_s, slow_warn_s=slow_warn_s, label=label
            ):
                yield conn

    @contextmanager
    def read_lease(self):
        """Check out one RO connection for an extended read (e.g. a
        streaming query whose cursor must live across many fetch batches).
        Interrupt windows (``interrupt_window``) must target THIS conn —
        acquiring a fresh ``interruptible_read`` per batch would schedule
        the watchdog on a different pool member than the cursor's."""
        if self._read_pool is None:
            yield self.conn
            return
        conn = self._read_pool.acquire()
        try:
            yield conn
        finally:
            self._read_pool.release(conn)

    @contextmanager
    def interrupt_window(
        self,
        conn: sqlite3.Connection,
        timeout_s: Optional[float] = None,
        slow_warn_s: Optional[float] = 1.0,
        label: str = "",
    ):
        """Bound one window of SQLite work on ``conn`` with the interrupt
        watchdog + slow-statement warning.  No-op timeout on the shared
        writer conn (in-memory fallback) — interrupting it would abort
        writer transactions."""
        handle = None
        if timeout_s is not None and conn is not self.conn:
            handle = _watchdog().schedule(conn, timeout_s)
        t0 = time.monotonic()
        try:
            yield conn
        finally:
            if handle is not None:
                handle.cancel()
            elapsed = time.monotonic() - t0
            if slow_warn_s is not None and elapsed >= slow_warn_s:
                logging.getLogger("corrosion_tpu.store").warning(
                    "slow query (%.2fs): %s", elapsed, label[:200]
                )

    def query(self, sql: str, params: Sequence[SqliteValue] = ()) -> List[sqlite3.Row]:
        return self.conn.execute(sql, tuple(params)).fetchall()

    def changes_for_version(
        self, site_id: ActorId, db_version: int,
        seq_range: Optional[Tuple[int, int]] = None,
    ) -> List[Change]:
        """The `crsql_changes` feed for one (origin, version), seq-ordered
        (reference broadcast_changes / handle_need read path)."""
        out: List[Change] = []
        for info in self._tables.values():
            sql = (
                f'SELECT c.pk, c.cid, c.val, c.col_version, c.seq, c.ts, '
                f'COALESCE(r.cl, 1) AS cl '
                f'FROM "{info.clock}" c LEFT JOIN "{info.rows}" r ON r.pk = c.pk '
                f'WHERE c.site_id = ? AND c.db_version = ?'
            )
            args: List = [site_id.bytes_, db_version]
            if seq_range:
                sql += " AND c.seq BETWEEN ? AND ?"
                args += [seq_range[0], seq_range[1]]
            for row in self.conn.execute(sql, args):
                out.append(
                    Change(
                        table=info.name, pk=row["pk"], cid=row["cid"],
                        val=row["val"], col_version=row["col_version"],
                        db_version=db_version, seq=row["seq"],
                        site_id=site_id, cl=row["cl"],
                    )
                )
        out.sort(key=lambda ch: ch.seq)
        return out

    def changes_for_version_range(
        self, site_id: ActorId, lo: int, hi: int
    ) -> Dict[int, List[Change]]:
        """All changes for an inclusive version range in ONE scan per table,
        grouped by db_version (the serve-side sync read, newest first)."""
        out: Dict[int, List[Change]] = {}
        for info in self._tables.values():
            sql = (
                f'SELECT c.pk, c.cid, c.val, c.col_version, c.db_version, '
                f'c.seq, COALESCE(r.cl, 1) AS cl '
                f'FROM "{info.clock}" c LEFT JOIN "{info.rows}" r ON r.pk = c.pk '
                f'WHERE c.site_id = ? AND c.db_version BETWEEN ? AND ?'
            )
            for row in self.conn.execute(sql, (site_id.bytes_, lo, hi)):
                out.setdefault(row["db_version"], []).append(
                    Change(
                        table=info.name, pk=row["pk"], cid=row["cid"],
                        val=row["val"], col_version=row["col_version"],
                        db_version=row["db_version"], seq=row["seq"],
                        site_id=site_id, cl=row["cl"],
                    )
                )
        for changes in out.values():
            changes.sort(key=lambda ch: ch.seq)
        return out

    # -- remote change application ---------------------------------------

    # batches at least this large take the native-comparator bulk path
    BATCH_APPLY_THRESHOLD = 16

    def apply_changes(
        self,
        changes: Iterable[Change],
        in_tx: bool = False,
    ) -> int:
        """Merge remote changes (the crsql_changes INSERT + C-extension merge
        in the reference, util.rs:1225-1245).  Returns rows impacted
        (crsql_rows_impacted equivalent).  Trigger capture is disabled for
        the duration; caller may already hold an open transaction.

        Large batches run the bulk path: one prefetch of existing clock
        cells, merge decisions via the C++ core (native/crdt_core.cpp), and
        executemany writes — the sync cold-catch-up hot loop."""
        changes = list(changes)
        with self._lock:
            self._applying = True
            own_tx = not in_tx
            if own_tx:
                self.conn.execute("BEGIN IMMEDIATE")
            try:
                if len(changes) >= self.BATCH_APPLY_THRESHOLD:
                    impacted = self._apply_batched(changes)
                else:
                    impacted = sum(1 for ch in changes if self._apply_one(ch))
                if own_tx:
                    self.conn.execute("COMMIT")
                return impacted
            except Exception:
                if own_tx:
                    self.conn.execute("ROLLBACK")
                raise
            finally:
                self._applying = False

    def _apply_batched(self, changes: List[Change]) -> int:
        """Bulk merge.  Lifecycle-changing rows (deletes, resurrections,
        unknown pks) fall back to the sequential path; same-lifecycle column
        changes are folded per cell (merge is a join-semilattice, so batch
        order is irrelevant), decided in one native merge_batch call, and
        written with executemany."""
        from .. import native
        from ..core.crdt import merge_cell

        impacted = 0
        by_table: Dict[str, List[Change]] = {}
        for ch in changes:
            by_table.setdefault(ch.table, []).append(ch)

        for table, tchanges in by_table.items():
            info = self._tables.get(table)
            if info is None:
                continue
            # local causal lengths for every touched pk, one chunked query
            pks = list({ch.pk for ch in tchanges})
            local_cl: Dict[bytes, int] = {}
            for i in range(0, len(pks), 500):
                chunk = pks[i : i + 500]
                ph = ",".join("?" for _ in chunk)
                for row in self.conn.execute(
                    f'SELECT pk, cl FROM "{info.rows}" WHERE pk IN ({ph})', chunk
                ):
                    local_cl[row[0]] = row[1]

            # a pk with any lifecycle transition (delete, resurrection) takes
            # the sequential path for ALL its changes — interleaving bulk
            # column writes with lifecycle flips would resurrect zombies.
            # Changes at *different* causal lengths inside one batch are also
            # a lifecycle transition even when the pk is locally unknown:
            # folding them would compare col_versions across lifecycles
            batch_cls: Dict[bytes, set] = {}
            for ch in tchanges:
                batch_cls.setdefault(ch.pk, set()).add(ch.cl)
            lifecycle_pks = set()
            for ch in tchanges:
                cl = local_cl.get(ch.pk, 0)
                if (
                    ch.cid == DELETE_SENTINEL
                    or (0 < cl < ch.cl)
                    or len(batch_cls[ch.pk]) > 1
                ):
                    lifecycle_pks.add(ch.pk)

            slow: List[Change] = []
            fold: Dict[Tuple[bytes, str], Change] = {}
            for ch in tchanges:
                if ch.pk in lifecycle_pks:
                    slow.append(ch)
                    continue
                cl = local_cl.get(ch.pk, 0)
                if not row_alive(ch.cl) or ch.cl < cl:
                    continue  # dead lifecycle or stale
                key = (ch.pk, ch.cid)
                prev = fold.get(key)
                if prev is None:
                    fold[key] = ch
                elif (
                    merge_cell(
                        (prev.col_version, prev.val, prev.site_id),
                        (ch.col_version, ch.val, ch.site_id),
                    )
                    == MergeOutcome.WIN
                ):
                    fold[key] = ch

            for ch in slow:
                if self._apply_one(ch):
                    impacted += 1

            if not fold:
                continue
            cells = list(fold.items())
            # prefetch existing clock cells with row-value IN chunks
            existing: Dict[Tuple[bytes, str], Tuple[int, SqliteValue, ActorId]] = {}
            for i in range(0, len(cells), 250):
                chunk = cells[i : i + 250]
                ph = ",".join("(?,?)" for _ in chunk)
                args: List = []
                for (pk, cid), _ in chunk:
                    args += [pk, cid]
                for row in self.conn.execute(
                    f'SELECT pk, cid, col_version, val, site_id FROM "{info.clock}" '
                    f"WHERE (pk, cid) IN (VALUES {ph})",
                    args,
                ):
                    existing[(row[0], row[1])] = (row[2], row[3], ActorId(row[4]))

            e_list = [existing.get(key) for key, _ in cells]
            i_list = [
                (ch.col_version, ch.val, ch.site_id) for _, ch in cells
            ]
            outcomes = native.merge_batch(e_list, i_list)

            clock_rows, base_updates, wins = [], [], []
            for ((pk, cid), ch), out in zip(cells, outcomes):
                if out == MergeOutcome.LOSE:
                    continue
                clock_rows.append(
                    (pk, cid, ch.val, ch.col_version, ch.db_version, ch.seq,
                     ch.site_id.bytes_, 0)
                )
                if out == MergeOutcome.WIN:
                    wins.append(ch)
                    if cid != PKONLY_SENTINEL:
                        base_updates.append(ch)
            if clock_rows:
                self.conn.executemany(
                    f'INSERT INTO "{info.clock}" '
                    "(pk, cid, val, col_version, db_version, seq, site_id, ts) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT (pk, cid) DO UPDATE SET "
                    "val = excluded.val, col_version = excluded.col_version, "
                    "db_version = excluded.db_version, seq = excluded.seq, "
                    "site_id = excluded.site_id, ts = excluded.ts",
                    clock_rows,
                )
            if wins:
                # rows-table entries + bare base rows for brand-new pks
                new_pks = {ch.pk: ch.cl for ch in wins if ch.pk not in local_cl}
                if new_pks:
                    self.conn.executemany(
                        f'INSERT OR IGNORE INTO "{info.rows}" (pk, cl) VALUES (?, ?)',
                        list(new_pks.items()),
                    )
                    cols = ", ".join(f'"{c}"' for c in info.pk_cols)
                    ph = ", ".join("?" for _ in info.pk_cols)
                    self.conn.executemany(
                        f'INSERT OR IGNORE INTO "{info.name}" ({cols}) VALUES ({ph})',
                        [decode_pk(pk) for pk in new_pks],
                    )
                for ch in base_updates:
                    self.conn.execute(
                        f'UPDATE "{info.name}" SET "{ch.cid}" = ? WHERE '
                        + " AND ".join(f'"{c}" IS ?' for c in info.pk_cols),
                        (ch.val, *decode_pk(ch.pk)),
                    )
                impacted += len(wins)
        return impacted

    def write_session(self):
        """The writer RLock, exposed for multi-statement apply sessions.

        A worker-thread apply (the concurrent ingest lanes) must hold it
        across its WHOLE begin..commit + follow-up statements so that
        loop-side users of the shared write conn (WAL maintenance,
        exec_transaction, sync serving's buffered reads) serialize
        against it, and `close()` (which also takes the lock) waits for
        an in-flight session instead of closing the conn under it."""
        return self._lock

    def begin_apply(self):
        with self._lock:
            self._applying = True
            self.conn.execute("BEGIN IMMEDIATE")

    def end_apply(self, commit: bool = True):
        with self._lock:
            try:
                self.conn.execute("COMMIT" if commit else "ROLLBACK")
            finally:
                self._applying = False

    def _apply_one(self, ch: Change) -> bool:
        info = self._tables.get(ch.table)
        if info is None:
            return False  # unknown table: skipped (schema not yet applied here)
        q = lambda s: f'"{s}"'
        row = self.conn.execute(
            f'SELECT cl FROM {q(info.rows)} WHERE pk = ?', (ch.pk,)
        ).fetchone()
        local_cl = row[0] if row else 0

        if ch.cid == DELETE_SENTINEL:
            if ch.cl <= local_cl or row_alive(ch.cl):
                return False  # stale delete
            self._set_cl(info, ch.pk, ch.cl)
            self._delete_base_row(info, ch.pk)
            self.conn.execute(f'DELETE FROM {q(info.clock)} WHERE pk = ?', (ch.pk,))
            self._upsert_clock(info, ch, force=True)
            return True

        if not row_alive(ch.cl) or ch.cl < local_cl:
            return False  # column change from a dead or stale lifecycle

        if ch.cl > local_cl:
            # new causal lifecycle: reset clocks, (re)create the base row
            self.conn.execute(f'DELETE FROM {q(info.clock)} WHERE pk = ?', (ch.pk,))
            self._set_cl(info, ch.pk, ch.cl)
            self._ensure_base_row(info, ch.pk)
        elif row is None:
            self._set_cl(info, ch.pk, ch.cl)
            self._ensure_base_row(info, ch.pk)

        existing_row = self.conn.execute(
            f'SELECT col_version, val, site_id FROM {q(info.clock)} WHERE pk = ? AND cid = ?',
            (ch.pk, ch.cid),
        ).fetchone()
        existing = (
            (existing_row[0], existing_row[1], ActorId(existing_row[2]))
            if existing_row
            else None
        )
        outcome = merge_cell(existing, (ch.col_version, ch.val, ch.site_id))
        if outcome == MergeOutcome.LOSE:
            return False
        self._upsert_clock(info, ch, force=True)
        if outcome == MergeOutcome.WIN and ch.cid != PKONLY_SENTINEL:
            self._ensure_base_row(info, ch.pk)
            self.conn.execute(
                f'UPDATE {q(info.name)} SET {q(ch.cid)} = ? WHERE '
                + " AND ".join(f'{q(c)} IS ?' for c in info.pk_cols),
                (ch.val, *decode_pk(ch.pk)),
            )
            return True
        return outcome == MergeOutcome.WIN

    def _set_cl(self, info: TableInfo, pk: bytes, cl: int):
        self.conn.execute(
            f'INSERT INTO "{info.rows}" (pk, cl) VALUES (?, ?) '
            "ON CONFLICT (pk) DO UPDATE SET cl = excluded.cl",
            (pk, cl),
        )

    def _ensure_base_row(self, info: TableInfo, pk: bytes):
        cols = ", ".join(f'"{c}"' for c in info.pk_cols)
        ph = ", ".join("?" for _ in info.pk_cols)
        self.conn.execute(
            f'INSERT OR IGNORE INTO "{info.name}" ({cols}) VALUES ({ph})',
            decode_pk(pk),
        )

    def _delete_base_row(self, info: TableInfo, pk: bytes):
        self.conn.execute(
            f'DELETE FROM "{info.name}" WHERE '
            + " AND ".join(f'"{c}" IS ?' for c in info.pk_cols),
            decode_pk(pk),
        )

    def _upsert_clock(self, info: TableInfo, ch: Change, force: bool):
        self.conn.execute(
            f'INSERT INTO "{info.clock}" (pk, cid, val, col_version, db_version, seq, site_id, ts) '
            "VALUES (?, ?, ?, ?, ?, ?, ?, ?) "
            "ON CONFLICT (pk, cid) DO UPDATE SET "
            "val = excluded.val, col_version = excluded.col_version, "
            "db_version = excluded.db_version, seq = excluded.seq, "
            "site_id = excluded.site_id, ts = excluded.ts",
            (ch.pk, ch.cid, ch.val, ch.col_version, ch.db_version, ch.seq,
             ch.site_id.bytes_, 0),
        )

    def close(self):
        # taken under the writer lock: a maintenance thread mid-checkpoint
        # holds _lock, so close waits instead of yanking the conn from
        # under a C call (observed segfault); late threads see _closed
        with self._lock:
            self._closed = True
            if self._read_pool is not None:
                self._read_pool.close()
            self.conn.close()


def _corro_json_contains(selector: str, obj: str) -> int:
    """True iff the first JSON value is fully contained in the second:
    objects match when every selector key exists with a contained value;
    everything else matches by equality (sqlite-functions/src/lib.rs:34-51).
    Raises on malformed JSON, like the reference's UserFunctionError."""

    def contains(s, o) -> bool:
        if isinstance(s, dict) and isinstance(o, dict):
            return all(k in o and contains(v, o[k]) for k, v in s.items())
        return s == o

    return 1 if contains(json.loads(selector), json.loads(obj)) else 0


class _Handle:
    __slots__ = ("cancelled",)

    def __init__(self):
        self.cancelled = False

    def cancel(self):
        self.cancelled = True


class _InterruptWatchdog:
    """One daemon thread serving every statement deadline in the process
    (replaces a per-query threading.Timer — the hot read path must not
    create an OS thread per request)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._entries: list = []  # heap of (deadline, seq, conn, handle)
        self._seq = 0  # tiebreaker: conns aren't comparable
        self._thread: Optional[threading.Thread] = None

    def schedule(self, conn, timeout_s: float) -> _Handle:
        import heapq

        handle = _Handle()
        deadline = time.monotonic() + timeout_s
        with self._cond:
            self._seq += 1
            heapq.heappush(self._entries, (deadline, self._seq, conn, handle))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="sqlite-interrupt-watchdog"
                )
                self._thread.start()
            self._cond.notify()
        return handle

    def _run(self):
        import heapq

        with self._cond:
            while True:
                while self._entries:
                    deadline, _tie, conn, handle = self._entries[0]
                    now = time.monotonic()
                    if handle.cancelled:
                        heapq.heappop(self._entries)
                        continue
                    if deadline <= now:
                        heapq.heappop(self._entries)
                        try:
                            conn.interrupt()
                        # corrolint: disable=CT006 — expected benign
                        # race: the conn the watchdog is interrupting
                        # may close concurrently; nothing to report
                        except Exception:
                            pass  # conn may be closed already
                        continue
                    self._cond.wait(timeout=deadline - now)
                    break
                else:
                    # idle: park until new work (bounded so a dead store
                    # doesn't pin the thread forever).  _thread is cleared
                    # under the lock BEFORE returning so a concurrent
                    # schedule() either sees it None (starts a fresh
                    # thread) or got its entry in while we still loop.
                    if not self._cond.wait(timeout=60.0) and not self._entries:
                        self._thread = None
                        return


_WATCHDOG: Optional[_InterruptWatchdog] = None
_WATCHDOG_LOCK = threading.Lock()


def _watchdog() -> _InterruptWatchdog:
    global _WATCHDOG
    with _WATCHDOG_LOCK:
        if _WATCHDOG is None:
            _WATCHDOG = _InterruptWatchdog()
        return _WATCHDOG
