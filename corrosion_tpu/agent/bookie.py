"""Bookie: per-origin-actor version bookkeeping persisted in the store.

Rebuild of the reference's `Booked`/`Bookie` (`corro-types/src/agent.rs:
1446-1598`) minus the async lock machinery (our agent runs one asyncio loop
per node; SQLite writes are already serialized by the store's writer lock).
Persists to the same tables the reference uses: `__corro_bookkeeping_gaps`
(gap algebra, via the GapsSink hook) and `__corro_seq_bookkeeping`
(partial seq ranges), and mirrors per-site max versions in
`__crdt_db_versions` (the crsql_db_versions analog) so state survives reboot
(checkpoint/resume is "reload from tables", SURVEY.md §5).
"""

from __future__ import annotations

import sqlite3
from typing import Dict, Iterable, Optional

from ..core.bookkeeping import BookedVersions, PartialVersion, VersionsSnapshot
from ..core.intervals import RangeSet
from ..core.types import ActorId
from ..invariants import always
from .store import CrrStore


class SqliteGapsSink:
    """GapsSink writing `__corro_bookkeeping_gaps` rows inside the caller's
    transaction (reference agent.rs:1119-1162)."""

    def __init__(self, conn: sqlite3.Connection):
        self.conn = conn

    def delete_gap(self, actor_id: ActorId, lo: int, hi: int) -> None:
        cur = self.conn.execute(
            "DELETE FROM __corro_bookkeeping_gaps WHERE actor_id = ? AND start = ? AND end = ?",
            (actor_id.bytes_, lo, hi),
        )
        # catalog invariant, not a crash: the reference logs in prod and
        # fails only under the simulator (agent.rs:1129-1133)
        always(
            cur.rowcount == 1,
            "gaps-deleted-effectively",
            {"lo": lo, "hi": hi, "rowcount": cur.rowcount},
        )

    def insert_gap(self, actor_id: ActorId, lo: int, hi: int) -> None:
        self.conn.execute(
            "INSERT INTO __corro_bookkeeping_gaps (actor_id, start, end) VALUES (?, ?, ?)",
            (actor_id.bytes_, lo, hi),
        )


class Bookie:
    """All per-actor BookedVersions for one node."""

    def __init__(self, store: CrrStore):
        self.store = store
        self.by_actor: Dict[ActorId, BookedVersions] = {}
        self._load()

    def _load(self):
        """Reboot = reload from tables (reference BookedVersions::from_conn,
        agent.rs:1282-1351, driven per-actor in run_root.rs:133-203)."""
        conn = self.store.conn
        actors = {
            ActorId(r[0])
            for r in conn.execute("SELECT site_id FROM __crdt_db_versions")
        } | {
            ActorId(r[0])
            for r in conn.execute("SELECT DISTINCT actor_id FROM __corro_bookkeeping_gaps")
        } | {
            ActorId(r[0])
            for r in conn.execute("SELECT DISTINCT site_id FROM __corro_seq_bookkeeping")
        }
        for actor in actors:
            bv = BookedVersions(actor)
            row = conn.execute(
                "SELECT db_version FROM __crdt_db_versions WHERE site_id = ?",
                (actor.bytes_,),
            ).fetchone()
            snap = bv.snapshot()
            if row:
                snap.max = row[0]
            for dbv, s, e, last, ts in conn.execute(
                "SELECT db_version, start_seq, end_seq, last_seq, ts "
                "FROM __corro_seq_bookkeeping WHERE site_id = ?",
                (actor.bytes_,),
            ):
                snap.partials.setdefault(
                    dbv, PartialVersion(seqs=RangeSet(), last_seq=last, ts=ts)
                ).seqs.insert(s, e)
                if snap.max is None or dbv > snap.max:
                    snap.max = dbv
            for s, e in conn.execute(
                "SELECT start, end FROM __corro_bookkeeping_gaps WHERE actor_id = ?",
                (actor.bytes_,),
            ):
                snap.needed.insert(s, e)
            bv.commit_snapshot(snap)
            self.by_actor[actor] = bv

    def for_actor(self, actor_id: ActorId) -> BookedVersions:
        if actor_id not in self.by_actor:
            self.by_actor[actor_id] = BookedVersions(actor_id)
        return self.by_actor[actor_id]

    def sink(self) -> SqliteGapsSink:
        return SqliteGapsSink(self.store.conn)

    # -- persistence helpers (run inside the caller's transaction) --------

    def record_versions(
        self,
        actor_id: ActorId,
        snap: VersionsSnapshot,
        versions: RangeSet,
    ) -> None:
        """insert_db + mirror the origin's max version (the reference's
        process_multiple_changes bookkeeping step, util.rs:892-932)."""
        snap.insert_db(self.sink(), versions)
        self.store.conn.execute(
            "INSERT INTO __crdt_db_versions (site_id, db_version) VALUES (?, ?) "
            "ON CONFLICT (site_id) DO UPDATE SET db_version = MAX(db_version, excluded.db_version)",
            (actor_id.bytes_, snap.max or 0),
        )

    def persist_partial(
        self, actor_id: ActorId, db_version: int, partial: PartialVersion
    ) -> None:
        """Rewrite `__corro_seq_bookkeeping` rows for one partial version
        with the coalesced seq ranges (reference util.rs:1053-1186)."""
        conn = self.store.conn
        conn.execute(
            "DELETE FROM __corro_seq_bookkeeping WHERE site_id = ? AND db_version = ?",
            (actor_id.bytes_, db_version),
        )
        conn.executemany(
            "INSERT INTO __corro_seq_bookkeeping "
            "(site_id, db_version, start_seq, end_seq, last_seq, ts) VALUES (?, ?, ?, ?, ?, ?)",
            [
                (actor_id.bytes_, db_version, lo, hi, partial.last_seq, partial.ts)
                for lo, hi in partial.seqs
            ],
        )

    def clear_partial(self, actor_id: ActorId, db_version: int) -> None:
        self.store.conn.execute(
            "DELETE FROM __corro_seq_bookkeeping WHERE site_id = ? AND db_version = ?",
            (actor_id.bytes_, db_version),
        )
