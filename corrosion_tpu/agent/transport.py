"""The transport seam: three verbs over pluggable backends.

This is the plugin boundary the north star names (SURVEY.md L4,
`corro-agent/src/transport.rs:79-162`): SWIM rides fire-and-forget
datagrams, broadcast rides uni-directional streams, sync rides
bi-directional streams.  Backends:

- ``MemoryTransport`` — in-process cluster (the reference's
  `launch_test_agent` loopback analog) with an optional deterministic
  latency/loss model, used by tests and as ground truth for the simulator;
- ``UdpTcpTransport`` — real sockets: UDP datagrams + TCP streams (the
  reference uses QUIC/Quinn; TCP gives us the same three verbs without
  pulling a QUIC stack into the image);
- the ``tpu-sim`` backend lives in `corrosion_tpu.sim` — same verbs, entries
  in per-round message tensors.

Addresses are opaque strings ("host:port" for sockets, any token in memory).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
import struct
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, Optional, Tuple

DatagramHandler = Callable[[str, bytes], Awaitable[None]]
UniHandler = Callable[[str, bytes], Awaitable[None]]
BiHandler = Callable[[str, "BiStream"], Awaitable[None]]

_log = logging.getLogger("corrosion_tpu.transport")


def _close_quietly(writer) -> None:
    """Best-effort close of a (possibly already-dead) stream writer.
    Closing a torn-down transport raises on some asyncio backends; the
    sever/teardown paths must proceed regardless — but the failure is
    still LOGGED (debug) rather than swallowed, per CT006
    (doc/lint.md): a close that fails for an unexpected reason should
    at least leave a trace for the flaky-suite hunts."""
    try:
        writer.close()
    except Exception:
        _log.debug("best-effort writer close failed", exc_info=True)


class BiStream:
    """One side of a bidirectional message stream (QUIC bi analog):
    length-delimited frames both ways.

    The inbox is BOUNDED so `send` exerts backpressure when the receiver
    stops reading — the flow-control QUIC streams give the reference.
    Without it a stalled sync peer would buffer the whole backlog in
    memory and the server's slow-peer abort could never fire."""

    INBOX_FRAMES = 256

    def __init__(self):
        self._inbox: asyncio.Queue = asyncio.Queue(self.INBOX_FRAMES)
        self._eof = asyncio.Event()
        self.peer: Optional["BiStream"] = None
        self.closed = False

    @staticmethod
    def pair() -> Tuple["BiStream", "BiStream"]:
        a, b = BiStream(), BiStream()
        a.peer, b.peer = b, a
        return a, b

    async def send(self, frame: bytes) -> None:
        if self.peer is None or self.peer.closed:
            raise ConnectionError("peer closed")
        await self.peer._inbox.put(frame)

    async def recv(self, timeout: Optional[float] = None) -> Optional[bytes]:
        """Next frame, b"" at EOF (peer closed, inbox drained), None on
        timeout.  EOF rides an Event, not a queue sentinel — a sentinel
        is silently lost when the bounded inbox is full at close time,
        wedging the reader for its whole round timeout."""
        if not self._inbox.empty():
            return self._inbox.get_nowait()
        if self._eof.is_set():
            return b""
        get_t = asyncio.create_task(self._inbox.get())
        eof_t = asyncio.create_task(self._eof.wait())
        done, pending = await asyncio.wait(
            {get_t, eof_t}, timeout=timeout,
            return_when=asyncio.FIRST_COMPLETED,
        )
        for t in pending:
            t.cancel()
        if get_t in done:
            return get_t.result()
        if eof_t in done:
            # frames may have raced in alongside the close: drain first
            if not self._inbox.empty():
                return self._inbox.get_nowait()
            return b""
        return None  # timeout

    def close(self) -> None:
        self.closed = True
        if self.peer is not None:
            self.peer._eof.set()


@dataclass
class LinkModel:
    """Deterministic latency/loss/jitter/duplication injection for
    in-memory clusters (stands in for the WAN conditions Antithesis
    injects around the reference) — the host-tier compile target of the
    FaultPlan seam (`corrosion_tpu.faults`).

    Every stochastic decision (drop, duplicate, jitter draw) comes from
    ONE per-instance ``random.Random(seed)`` stream, so a replay with
    the same seed reproduces the exact decision sequence.  **Seed
    derivation**: links must never share a stream — `MemoryNetwork`
    derives each edge's instance via :meth:`derive`, which folds the
    directed ``(src, dst)`` pair into the base seed with
    ``faults.derive_seed(seed, "link", src, dst)`` (a blake2b fold;
    process-stable, unlike salted ``hash()``).  Two links configured
    from the same base LinkModel therefore draw INDEPENDENT sequences,
    and the k-th decision on a given link is a pure function of
    (base seed, src, dst, k)."""

    latency_s: float = 0.0
    loss: float = 0.0  # datagram/uni loss probability; bi streams are reliable
    seed: int = 0
    # per-message extra delay uniform in [0, jitter_s): messages overtake
    # each other — this is the REORDERING fault on the host tier
    jitter_s: float = 0.0
    duplicate: float = 0.0  # probability a delivered payload arrives twice
    _rng: random.Random = field(init=False)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def derive(self, src: str, dst: str) -> "LinkModel":
        """Same parameters, per-edge independent seed-derived stream."""
        from ..faults import derive_seed

        return dataclasses.replace(
            self, seed=derive_seed(self.seed, "link", src, dst)
        )

    def drop(self) -> bool:
        return self.loss > 0 and self._rng.random() < self.loss

    def dup(self) -> bool:
        return self.duplicate > 0 and self._rng.random() < self.duplicate

    def delay_s(self) -> float:
        """Per-message delivery delay: fixed latency + jitter draw."""
        if self.jitter_s > 0:
            return self.latency_s + self._rng.random() * self.jitter_s
        return self.latency_s


@dataclass
class FaultInjector(LinkModel):
    """Injectable network faults for the REAL-socket transport — the
    in-process analog of the network faults the reference's Antithesis
    rig throws at real nodes (.antithesis/config/docker-compose.yaml:
    1-45: partitions, crashes, degraded links).  Extends the in-memory
    tier's :class:`LinkModel` (same seeded loss semantics — the two
    tiers must not drift) with partitions, added delay, and a drop
    counter.  Installed via ``UdpTcpTransport.install_faults``; applied
    at the send boundary of every verb, so a partition behaves like an
    egress firewall on this node (install on both sides for a symmetric
    split, as the rig's network does).

    - ``partition(addr...)``: block sends to those peers ("*" = all) —
      ALSO severs this transport's established connections (a real
      partition cuts in-flight TCP, not just new dials)
    - ``loss``: drop probability for datagram/uni payloads (bi streams
      stay reliable once open, like TCP under real packet loss)
    - ``latency_s``: added delay before every send
    - ``links``: per-DESTINATION LinkModel overrides — the compile
      target of `faults.RealSocketFaultDriver`, which installs one
      seed-derived stream per directed edge (``derive_seed(seed,
      "link", src, dst, epoch)`` — the SAME derivation the host tier's
      `MemoryNetwork` and the sim compiler use), so a FaultPlan replays
      the exact per-draw decisions on real sockets too.  The injector's
      own loss/latency fields stay the default for unlisted peers.
    """

    blocked_peers: set = field(default_factory=set)
    dropped: int = 0  # counter for test assertions
    # per-destination LinkModel streams (addr -> model); each carries
    # its OWN seeded RNG so edges never share a stream
    links: Dict[str, LinkModel] = field(default_factory=dict)
    # wired by install_faults: severs the transport's established conns
    # whenever the partition set grows
    _sever_cb: Optional[Callable[[], None]] = None

    def partition(self, *addrs: str) -> None:
        self.blocked_peers.update(addrs or ("*",))
        if self._sever_cb is not None:
            self._sever_cb()

    def set_partition(self, addrs) -> None:
        """Replace the blocked-peer set wholesale (the per-round driver
        path); severs established conns only when NEW edges appear —
        healing must not cut surviving connections."""
        addrs = set(addrs)
        grew = bool(addrs - self.blocked_peers)
        self.blocked_peers = addrs
        if grew and self._sever_cb is not None:
            self._sever_cb()

    def heal(self) -> None:
        self.blocked_peers.clear()

    def _link(self, addr: Optional[str]) -> LinkModel:
        if addr is not None:
            lm = self.links.get(addr)
            if lm is not None:
                return lm
        return self

    def blocks(self, addr: str) -> bool:
        if "*" in self.blocked_peers or addr in self.blocked_peers:
            self.dropped += 1
            return True
        return False

    def drops(self, addr: Optional[str] = None) -> bool:
        if self._link(addr).drop():  # seeded loss (per-dst stream first)
            self.dropped += 1
            return True
        return False

    def dups(self, addr: Optional[str] = None) -> bool:
        return self._link(addr).dup()

    def delay_for(self, addr: Optional[str] = None) -> float:
        return self._link(addr).delay_s()

    async def apply_delay(self, addr: Optional[str] = None) -> None:
        d = self.delay_for(addr)
        if d > 0:
            await asyncio.sleep(d)


class Transport:
    """Abstract transport verbs (reference transport.rs:79-162)."""

    #: whether bootstrap entries may be DNS hostnames needing resolution
    #: (real socket transports only — MemoryTransport addrs are symbolic
    #: names like "node0" and must pass through literally)
    resolves_dns = False

    addr: str

    async def send_datagram(self, addr: str, data: bytes) -> None:
        raise NotImplementedError

    async def send_uni(self, addr: str, data: bytes) -> None:
        raise NotImplementedError

    async def open_bi(self, addr: str) -> BiStream:
        raise NotImplementedError

    def set_handlers(
        self,
        on_datagram: DatagramHandler,
        on_uni: UniHandler,
        on_bi: BiHandler,
    ) -> None:
        self.on_datagram = on_datagram
        self.on_uni = on_uni
        self.on_bi = on_bi

    async def close(self) -> None:
        pass


class MemoryNetwork:
    """Shared registry for in-process transports, with per-edge link models."""

    def __init__(self, default_link: Optional[LinkModel] = None):
        self.nodes: Dict[str, "MemoryTransport"] = {}
        self.links: Dict[Tuple[str, str], LinkModel] = {}
        self.default_link = default_link or LinkModel()
        self.partitioned: set = set()  # {(a, b)} directed blocked edges

    def transport(self, addr: str) -> "MemoryTransport":
        t = MemoryTransport(self, addr)
        self.nodes[addr] = t
        return t

    def link(self, src: str, dst: str) -> LinkModel:
        """The directed edge's link model.  Edges without an explicit
        entry get a lazily-created PER-EDGE instance derived from
        ``default_link`` (`LinkModel.derive`: same parameters, seed
        folded with the edge) — a single shared instance would make
        every link consume ONE RNG stream, so link A's traffic would
        perturb link B's drop sequence and no per-link schedule could
        ever replay."""
        lm = self.links.get((src, dst))
        if lm is None:
            lm = self.links[(src, dst)] = self.default_link.derive(src, dst)
        return lm

    def partition(self, a: str, b: str, bidirectional: bool = True):
        self.partitioned.add((a, b))
        if bidirectional:
            self.partitioned.add((b, a))

    def heal(self):
        self.partitioned.clear()

    def reachable(self, src: str, dst: str) -> bool:
        return (src, dst) not in self.partitioned and dst in self.nodes


class MemoryTransport(Transport):
    def __init__(self, net: MemoryNetwork, addr: str):
        self.net = net
        self.addr = addr
        self.on_datagram: Optional[DatagramHandler] = None
        self.on_uni: Optional[UniHandler] = None
        self.on_bi: Optional[BiHandler] = None
        self._tasks: set = set()

    def _spawn(self, coro):
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _deliver(self, addr: str, kind: str, payload) -> bool:
        if not self.net.reachable(self.addr, addr):
            return False
        link = self.net.link(self.addr, addr)
        if kind in ("datagram", "uni") and link.drop():
            return False
        dst = self.net.nodes[addr]
        # every stochastic decision is drawn HERE, at send time, in send
        # order — drawing inside the spawned delivery task would make the
        # stream's consumption order depend on scheduler interleaving and
        # break seed replay.  Jitter gives each message its own delay, so
        # later sends can overtake earlier ones: the reorder fault.
        copies = 2 if kind in ("datagram", "uni") and link.dup() else 1
        delays = [link.delay_s() for _ in range(copies)]

        async def run(delay: float):
            if delay > 0:
                await asyncio.sleep(delay)
            handler = getattr(dst, f"on_{kind}")
            if handler is not None:
                await handler(self.addr, payload)

        for d in delays:
            self._spawn(run(d))
        return True

    async def send_datagram(self, addr: str, data: bytes) -> None:
        await self._deliver(addr, "datagram", data)

    async def send_uni(self, addr: str, data: bytes) -> None:
        await self._deliver(addr, "uni", data)

    async def open_bi(self, addr: str) -> BiStream:
        if not self.net.reachable(self.addr, addr):
            raise ConnectionError(f"{addr} unreachable")
        ours, theirs = BiStream.pair()
        link = self.net.link(self.addr, addr)
        dst = self.net.nodes[addr]

        async def run():
            if link.latency_s:
                await asyncio.sleep(link.latency_s)
            if dst.on_bi is not None:
                await dst.on_bi(self.addr, theirs)

        self._spawn(run())
        return ours

    async def close(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        self.net.nodes.pop(self.addr, None)


# ---------------------------------------------------------------------------
# Real sockets: UDP datagrams + TCP framed streams


def _frame(data: bytes) -> bytes:
    return struct.pack(">I", len(data)) + data


async def _read_frame(reader: asyncio.StreamReader) -> Optional[bytes]:
    # deliberately unbounded: the bound lives at the call sites —
    # _TcpBiStream.recv wraps this whole coroutine in wait_for, and the
    # _on_tcp server pump reads long-lived conns where an idle peer is
    # normal (liveness is SWIM's job, not a read timeout's)
    try:
        # corrolint: disable=CT009 — bounded by callers (see above)
        hdr = await reader.readexactly(4)
        (n,) = struct.unpack(">I", hdr)
        # corrolint: disable=CT009 — bounded by callers (see above)
        return await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None


class _TcpBiStream(BiStream):
    # small write high-water mark so drain() actually blocks when the
    # peer stops reading — otherwise asyncio buffers 64 KiB+ in userspace
    # and slow-peer detection (AdaptiveSender) never sees the stall
    WRITE_HIGH_WATER = 16 * 1024

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        super().__init__()
        self.reader = reader
        self.writer = writer
        try:
            writer.transport.set_write_buffer_limits(high=self.WRITE_HIGH_WATER)
        except Exception:
            # transports without buffer limits (tests' in-memory pairs)
            # keep the default high-water mark; note it for diagnosis
            _log.debug("set_write_buffer_limits unsupported", exc_info=True)

    async def send(self, frame: bytes) -> None:
        self.writer.write(_frame(frame))
        await self.writer.drain()

    async def recv(self, timeout: Optional[float] = None) -> Optional[bytes]:
        try:
            return await asyncio.wait_for(_read_frame(self.reader), timeout)
        except asyncio.TimeoutError:
            return None

    def close(self) -> None:
        self.closed = True
        _close_quietly(self.writer)


class _CachedConn:
    """One cached outbound TCP connection per peer (the QUIC-connection
    analog of the reference's conn cache, transport.rs:55-70,200-233):
    broadcast frames and — under TLS — SWIM datagrams multiplex over it
    as tagged length-delimited frames instead of paying a fresh
    handshake per message."""

    __slots__ = ("reader", "writer", "lock")

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.lock = asyncio.Lock()

    @property
    def alive(self) -> bool:
        return not self.writer.is_closing()


@dataclasses.dataclass
class PathStats:
    """Per-peer transport path statistics, aggregated across reconnects
    (the TCP/UDP analog of the reference's per-connection QUIC
    path/frame stats rollup, transport.rs:235-419).  Surfaced by
    `UdpTcpTransport.path_samples()` into the Prometheus scrape."""

    frames_tx_uni: int = 0
    frames_tx_dgram: int = 0
    frames_rx_uni: int = 0
    frames_rx_dgram: int = 0
    bytes_tx: int = 0
    bytes_rx: int = 0
    bi_opened: int = 0
    connects: int = 0
    send_errors: int = 0
    rtt_last_s: float = 0.0


class UdpTcpTransport(Transport):
    resolves_dns = True
    """Datagrams over UDP, uni/bi streams over TCP, one port each.

    Wire shape (the reference's QUIC uni/bi distinction,
    api/peer/mod.rs:118-339, with TCP standing in for QUIC):

    - ``TAG_UNI`` connection — long-lived, cached per peer, carrying a
      stream of ``kind(1) + len(4) + payload`` frames where kind is
      ``u`` (broadcast uni payload) or ``d`` (SWIM datagram, used when
      TLS is on so membership traffic is encrypted too);
    - ``TAG_BI`` connection — one per sync session, framed both ways;
    - bare UDP datagrams for SWIM in plaintext mode (the
      quinn-plaintext analog, config.rs:187).

    With ``server_ssl``/``client_ssl`` contexts (utils/tls.py) all TCP
    traffic is (m)TLS — the rustls path of api/peer/mod.rs:149-339.
    Connection establishment time is sampled into ``on_rtt`` (the
    reference samples path RTT into rtt_tx, transport.rs:220)."""

    TAG_UNI = b"u"
    TAG_BI = b"b"
    KIND_UNI = b"u"
    KIND_DGRAM = b"d"

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        server_ssl=None,
        client_ssl=None,
        on_rtt: Optional[Callable[[str, float], None]] = None,
    ):
        self._host = host
        self._port = port
        self.addr = ""
        self.on_datagram = None
        self.on_uni = None
        self.on_bi = None
        self.on_rtt = on_rtt  # (addr, rtt_seconds)
        self._udp = None
        self._tcp_server = None
        self._tasks: set = set()
        self._server_ssl = server_ssl
        self._client_ssl = client_ssl
        self._conns: Dict[str, _CachedConn] = {}
        self._dial_locks: Dict[str, asyncio.Lock] = {}
        self._server_writers: set = set()
        # reuse metrics: tests assert conns_opened ≪ frames sent
        self.conns_opened = 0
        self.server_conns_accepted = 0
        # per-peer path statistics (bounded: one entry per peer addr,
        # evicted with the member; cap guards a churn pathology)
        self.path_stats: Dict[str, PathStats] = {}
        # injectable network faults (None = zero overhead); the fault
        # campaign installs a FaultInjector to partition/degrade REAL
        # sockets the way the Antithesis rig does to the reference
        self.faults: Optional[FaultInjector] = None
        # client-opened bi writers, tracked so install_faults can sever
        # in-flight sync sessions the way a real network partition cuts
        # established TCP conns (not just new dials)
        self._client_streams: set = set()

    _PATH_STATS_CAP = 4096

    def _pstats(self, addr: str) -> PathStats:
        st = self.path_stats.get(addr)
        if st is None:
            while len(self.path_stats) >= self._PATH_STATS_CAP:
                self.path_stats.pop(next(iter(self.path_stats)))
            st = self.path_stats[addr] = PathStats()
        return st

    @property
    def tls(self) -> bool:
        return self._server_ssl is not None or self._client_ssl is not None

    async def start(self) -> str:
        loop = asyncio.get_running_loop()

        outer = self

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                if outer.on_datagram is not None:
                    task = loop.create_task(outer.on_datagram(f"{addr[0]}:{addr[1]}", data))
                    outer._tasks.add(task)
                    task.add_done_callback(outer._tasks.discard)

        self._tcp_server = await asyncio.start_server(
            self._on_tcp, self._host, self._port, ssl=self._server_ssl
        )
        self._port = self._tcp_server.sockets[0].getsockname()[1]
        if self.tls:
            # ADVICE r2 (high): with TLS on, SWIM must be TLS-only in BOTH
            # directions.  Binding the plaintext UDP socket would let any
            # unauthenticated host inject forged SWIM messages (suspect/
            # down/alive, fake members) even though our sends are
            # encrypted — so the endpoint is simply never bound and the
            # OS rejects the packets.
            logging.getLogger("corrosion_tpu.transport").info(
                "TLS enabled: plaintext UDP endpoint NOT bound; SWIM "
                "datagrams ride the encrypted stream only"
            )
        else:
            self._udp, _ = await loop.create_datagram_endpoint(
                Proto, local_addr=(self._host, self._port)
            )
        self.addr = f"{self._host}:{self._port}"
        return self.addr

    async def _on_tcp(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        peer_addr = f"{peer[0]}:{peer[1]}" if peer else "?"
        self.server_conns_accepted += 1
        # tracked so close() can tear down long-lived server-side conns —
        # Server.wait_closed() (py3.12+) blocks until every connection is
        # gone, and cached uni conns live until the peer evicts them
        self._server_writers.add(writer)
        try:
            try:
                # server read, deliberately unbounded: an idle client is
                # normal on a long-lived conn; a dead one raises.  Peer
                # liveness is SWIM's job, not a read timeout's.
                # corrolint: disable=CT009
                tag = await reader.readexactly(1)
            except (asyncio.IncompleteReadError, ConnectionError):
                writer.close()
                return
            if tag == self.TAG_UNI:
                # cached-connection frame pump: serve frames until EOF.
                # One bad frame must not kill the long-lived conn (under
                # TLS it also carries every SWIM datagram from the peer)
                while True:
                    try:
                        # server pump read: unbounded for the same
                        # reason as the tag read above
                        # corrolint: disable=CT009
                        kind = await reader.readexactly(1)
                    except (asyncio.IncompleteReadError, ConnectionError):
                        break
                    data = await _read_frame(reader)
                    if data is None:
                        break
                    # rx keyed by the peer's IP: the inbound socket's
                    # source port is EPHEMERAL — keying by peername would
                    # mint a fresh label series per reconnect (cardinality
                    # churn) and never aggregate with the canonical
                    # gossip addr the tx stats use
                    st = self._pstats(peer_addr.rsplit(":", 1)[0])
                    if kind == self.KIND_UNI:
                        st.frames_rx_uni += 1
                    else:
                        st.frames_rx_dgram += 1
                    st.bytes_rx += len(data)
                    try:
                        if kind == self.KIND_UNI and self.on_uni is not None:
                            # awaited inline: broadcast ingestion is the
                            # natural backpressure point (handlers only
                            # decode + enqueue)
                            await self.on_uni(peer_addr, data)
                        elif (
                            kind == self.KIND_DGRAM
                            and self.on_datagram is not None
                        ):
                            # dispatched off the pump: a SWIM ack must not
                            # queue behind broadcast frame handling
                            task = asyncio.get_running_loop().create_task(
                                self.on_datagram(peer_addr, data)
                            )
                            self._tasks.add(task)
                            task.add_done_callback(self._tasks.discard)
                    except Exception:
                        logging.getLogger("corrosion_tpu.transport").warning(
                            "frame handler error from %s", peer_addr,
                            exc_info=True,
                        )
            elif tag == self.TAG_BI:
                if self.on_bi is not None:
                    await self.on_bi(peer_addr, _TcpBiStream(reader, writer))
        finally:
            self._server_writers.discard(writer)
            _close_quietly(writer)

    CONNECT_TIMEOUT_S = 5.0

    async def _connect(self, addr: str):
        host, port = addr.rsplit(":", 1)
        t0 = time.monotonic()
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(
                host,
                int(port),
                ssl=self._client_ssl,
                server_hostname=host if self._client_ssl is not None else None,
            ),
            self.CONNECT_TIMEOUT_S,
        )
        dt = time.monotonic() - t0
        if self.on_rtt is not None:
            self.on_rtt(addr, dt)
        self.conns_opened += 1
        st = self._pstats(addr)
        st.connects += 1
        st.rtt_last_s = dt
        from ..metrics import REGISTRY

        REGISTRY.histogram("corro_transport_connect_time_seconds").observe(dt)
        return reader, writer

    async def _uni_conn(self, addr: str) -> _CachedConn:
        conn = self._conns.get(addr)
        if conn is not None and conn.alive:
            return conn
        # single-flight dial: concurrent first sends to the same peer
        # must share one connection, not leak the loser's socket
        lock = self._dial_locks.setdefault(addr, asyncio.Lock())
        async with lock:
            conn = self._conns.get(addr)
            if conn is not None and conn.alive:
                return conn
            reader, writer = await self._connect(addr)
            writer.write(self.TAG_UNI)
            conn = _CachedConn(reader, writer)
            self._conns[addr] = conn
            return conn

    def _evict(self, addr: str) -> None:
        conn = self._conns.pop(addr, None)
        if conn is not None:
            _close_quietly(conn.writer)

    async def _send_frame(self, addr: str, kind: bytes, data: bytes) -> None:
        # liveness-checked reuse with one reconnect (the reference tests
        # the cached conn and reconnects on failure, transport.rs:200-233)
        for attempt in (0, 1):
            conn = await self._uni_conn(addr)
            try:
                async with conn.lock:
                    conn.writer.write(kind + _frame(data))
                    await conn.writer.drain()
                st = self._pstats(addr)
                if kind == self.KIND_UNI:
                    st.frames_tx_uni += 1
                else:
                    st.frames_tx_dgram += 1
                st.bytes_tx += len(data)
                return
            except (ConnectionError, OSError):
                self._evict(addr)
                if attempt:
                    self._pstats(addr).send_errors += 1
                    raise

    async def send_datagram(self, addr: str, data: bytes) -> None:
        dup = False
        if self.faults is not None:
            # UDP semantics: partitioned/lost datagrams vanish silently
            if self.faults.blocks(addr) or self.faults.drops(addr):
                return
            dup = self.faults.dups(addr)
            await self.faults.apply_delay(addr)
        if dup:
            # modeled duplication: the datagram arrives twice (the
            # receiver's dedup/idempotency must absorb it)
            await self._send_datagram_raw(addr, data)
        await self._send_datagram_raw(addr, data)

    async def _send_datagram_raw(self, addr: str, data: bytes) -> None:
        if self.tls:
            # SWIM rides the encrypted stream: plaintext UDP would leak
            # membership traffic QUIC encrypts in the reference.  The
            # datagram contract stays fire-and-forget: never block the
            # probe loop on a TCP/TLS dial — warm the conn in the
            # background and drop this datagram (SWIM tolerates loss)
            conn = self._conns.get(addr)
            if conn is None or not conn.alive:
                self._background_dial(addr)
                return
            try:
                async with conn.lock:
                    conn.writer.write(self.KIND_DGRAM + _frame(data))
                    await asyncio.wait_for(conn.writer.drain(), 2.0)
                st = self._pstats(addr)
                st.frames_tx_dgram += 1
                st.bytes_tx += len(data)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                self._evict(addr)
                self._pstats(addr).send_errors += 1
            return
        host, port = addr.rsplit(":", 1)
        self._udp.sendto(data, (host, int(port)))
        st = self._pstats(addr)
        st.frames_tx_dgram += 1
        st.bytes_tx += len(data)

    def _background_dial(self, addr: str) -> None:
        async def dial():
            try:
                await self._uni_conn(addr)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass

        task = asyncio.get_running_loop().create_task(dial())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def send_uni(self, addr: str, data: bytes) -> None:
        dup = False
        if self.faults is not None:
            if self.faults.blocks(addr):
                raise ConnectionError(f"fault injection: {addr} partitioned")
            if self.faults.drops(addr):
                return  # modeled payload loss: frame never delivered
            dup = self.faults.dups(addr)
            await self.faults.apply_delay(addr)
        if dup:
            await self._send_frame(addr, self.KIND_UNI, data)
        await self._send_frame(addr, self.KIND_UNI, data)

    async def open_bi(self, addr: str) -> BiStream:
        if self.faults is not None:
            if self.faults.blocks(addr):
                raise ConnectionError(f"fault injection: {addr} partitioned")
            # bi streams are reliable (no loss/dup), but fault latency
            # delays session establishment like any other send
            await self.faults.apply_delay(addr)
        reader, writer = await self._connect(addr)
        # re-check AFTER the dial: install_faults severs established
        # conns, but a dial suspended inside _connect when the injector
        # landed resumes with a socket that was in no sever list and —
        # unlike uni frames, which re-check per send — a bi stream is
        # never fault-checked again, so one racing sync session would
        # replicate straight across a fresh partition
        if self.faults is not None and self.faults.blocks(addr):
            _close_quietly(writer)
            raise ConnectionError(f"fault injection: {addr} partitioned")
        writer.write(self.TAG_BI)
        await writer.drain()
        self._pstats(addr).bi_opened += 1
        self._client_streams = {
            w for w in self._client_streams if not w.is_closing()
        }
        self._client_streams.add(writer)
        return _TcpBiStream(reader, writer)

    def install_faults(self, faults: Optional[FaultInjector]) -> None:
        """Install (or clear, with None) a FaultInjector AND sever every
        established connection — cached uni conns, server-accepted conns,
        and in-flight client bi streams.  A real partition (the rig's
        iptables-style fault) cuts established TCP flows, not just new
        dials; without severing, a sync session opened pre-partition
        would keep replicating straight across the 'partition'.  Later
        ``partition()`` calls on the installed injector sever again via
        the wired callback, so extending a split mid-test is also safe."""
        self.faults = faults
        if faults is None:
            return
        faults._sever_cb = self._sever_connections
        self._sever_connections()

    def _sever_connections(self) -> None:
        for addr in list(self._conns):
            self._evict(addr)
        for writer in list(self._server_writers) + list(self._client_streams):
            _close_quietly(writer)
        self._client_streams.clear()

    def path_samples(self) -> str:
        """Prometheus text families for the per-path stats (the
        reference's emit_metrics aggregation, transport.rs:235-419:
        per-addr gauges + cluster-wide totals)."""
        live = sum(1 for c in self._conns.values() if c.alive)
        lines = [
            "# TYPE corro_transport_connections gauge",
            f"corro_transport_connections {live}",
        ]
        agg = PathStats()
        for st in self.path_stats.values():
            agg.frames_tx_uni += st.frames_tx_uni
            agg.frames_tx_dgram += st.frames_tx_dgram
            agg.frames_rx_uni += st.frames_rx_uni
            agg.frames_rx_dgram += st.frames_rx_dgram
            agg.bytes_tx += st.bytes_tx
            agg.bytes_rx += st.bytes_rx
            agg.bi_opened += st.bi_opened
            agg.connects += st.connects
            agg.send_errors += st.send_errors
        lines += [
            "# TYPE corro_transport_frames_tx counter",
            f'corro_transport_frames_tx{{type="uni"}} {agg.frames_tx_uni}',
            f'corro_transport_frames_tx{{type="dgram"}} {agg.frames_tx_dgram}',
            "# TYPE corro_transport_frames_rx counter",
            f'corro_transport_frames_rx{{type="uni"}} {agg.frames_rx_uni}',
            f'corro_transport_frames_rx{{type="dgram"}} {agg.frames_rx_dgram}',
            "# TYPE corro_transport_path_bytes_tx counter",
            f"corro_transport_path_bytes_tx {agg.bytes_tx}",
            "# TYPE corro_transport_path_bytes_rx counter",
            f"corro_transport_path_bytes_rx {agg.bytes_rx}",
            "# TYPE corro_transport_bi_streams_opened counter",
            f"corro_transport_bi_streams_opened {agg.bi_opened}",
            "# TYPE corro_transport_connects counter",
            f"corro_transport_connects {agg.connects}",
            "# TYPE corro_transport_send_errors counter",
            f"corro_transport_send_errors {agg.send_errors}",
        ]
        # per-addr rollup (the reference labels cwnd/congestion per addr;
        # here bytes + last connect RTT are the TCP-visible analogs)
        lines.append("# TYPE corro_transport_path_peer_bytes_tx counter")
        for addr, st in sorted(self.path_stats.items()):
            lines.append(
                f'corro_transport_path_peer_bytes_tx{{addr="{addr}"}} '
                f"{st.bytes_tx}"
            )
        lines.append("# TYPE corro_transport_path_peer_rtt_seconds gauge")
        for addr, st in sorted(self.path_stats.items()):
            lines.append(
                f'corro_transport_path_peer_rtt_seconds{{addr="{addr}"}} '
                f"{st.rtt_last_s:.6f}"
            )
        return "\n".join(lines) + "\n"

    async def close(self) -> None:
        for addr in list(self._conns):
            self._evict(addr)
        for w in list(self._server_writers):
            _close_quietly(w)
        for t in list(self._tasks):
            t.cancel()
        if self._udp:
            self._udp.close()
        if self._tcp_server:
            self._tcp_server.close()
            try:
                await asyncio.wait_for(self._tcp_server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                pass


def transport_from_config(cfg) -> UdpTcpTransport:
    """Build the socket transport from an agent Config, wiring the
    [gossip.tls] section into ssl contexts (config.rs:170-193 →
    api/peer/mod.rs:149-339; plaintext mode when the section is absent,
    the quinn-plaintext analog)."""
    tls_cfg = getattr(cfg, "gossip_tls", None) or {}
    server_ssl = client_ssl = None
    if tls_cfg:
        from ..utils import tls as tlsmod

        missing = [k for k in ("cert_file", "key_file") if not tls_cfg.get(k)]
        if missing:
            raise ValueError(
                "[gossip.tls] requires cert_file and key_file "
                f"(missing: {', '.join(missing)}) — generate them with "
                "`corrosion-tpu tls ca generate` + `tls server generate`"
            )
        client = tls_cfg.get("client", {})
        if not isinstance(client, dict):
            client = {}
        server_ssl = tlsmod.server_ssl_context(
            tls_cfg["cert_file"],
            tls_cfg["key_file"],
            ca_cert_path=tls_cfg.get("ca_file"),
            require_client_cert=bool(client.get("required")),
        )
        client_ssl = tlsmod.client_ssl_context(
            tls_cfg.get("ca_file"),
            cert_path=client.get("cert_file"),
            key_path=client.get("key_file"),
            insecure=bool(tls_cfg.get("insecure")),
        )
    host, _, port = cfg.gossip_addr.rpartition(":")
    return UdpTcpTransport(
        host or "127.0.0.1",
        int(port or 0),
        server_ssl=server_ssl,
        client_ssl=client_ssl,
    )
