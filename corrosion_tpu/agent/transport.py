"""The transport seam: three verbs over pluggable backends.

This is the plugin boundary the north star names (SURVEY.md L4,
`corro-agent/src/transport.rs:79-162`): SWIM rides fire-and-forget
datagrams, broadcast rides uni-directional streams, sync rides
bi-directional streams.  Backends:

- ``MemoryTransport`` — in-process cluster (the reference's
  `launch_test_agent` loopback analog) with an optional deterministic
  latency/loss model, used by tests and as ground truth for the simulator;
- ``UdpTcpTransport`` — real sockets: UDP datagrams + TCP streams (the
  reference uses QUIC/Quinn; TCP gives us the same three verbs without
  pulling a QUIC stack into the image);
- the ``tpu-sim`` backend lives in `corrosion_tpu.sim` — same verbs, entries
  in per-round message tensors.

Addresses are opaque strings ("host:port" for sockets, any token in memory).
"""

from __future__ import annotations

import asyncio
import random
import struct
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, Optional, Tuple

DatagramHandler = Callable[[str, bytes], Awaitable[None]]
UniHandler = Callable[[str, bytes], Awaitable[None]]
BiHandler = Callable[[str, "BiStream"], Awaitable[None]]


class BiStream:
    """One side of a bidirectional message stream (QUIC bi analog):
    length-delimited frames both ways."""

    def __init__(self):
        self._inbox: asyncio.Queue = asyncio.Queue()
        self.peer: Optional["BiStream"] = None
        self.closed = False

    @staticmethod
    def pair() -> Tuple["BiStream", "BiStream"]:
        a, b = BiStream(), BiStream()
        a.peer, b.peer = b, a
        return a, b

    async def send(self, frame: bytes) -> None:
        if self.peer is None or self.peer.closed:
            raise ConnectionError("peer closed")
        await self.peer._inbox.put(frame)

    async def recv(self, timeout: Optional[float] = None) -> Optional[bytes]:
        try:
            frame = await asyncio.wait_for(self._inbox.get(), timeout)
        except asyncio.TimeoutError:
            return None
        return frame

    def close(self) -> None:
        self.closed = True
        if self.peer is not None:
            self.peer._inbox.put_nowait(b"")  # EOF marker


@dataclass
class LinkModel:
    """Deterministic latency/loss injection for in-memory clusters (stands in
    for the WAN conditions Antithesis injects around the reference)."""

    latency_s: float = 0.0
    loss: float = 0.0  # datagram/uni loss probability; bi streams are reliable
    seed: int = 0
    _rng: random.Random = field(init=False)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def drop(self) -> bool:
        return self.loss > 0 and self._rng.random() < self.loss


class Transport:
    """Abstract transport verbs (reference transport.rs:79-162)."""

    addr: str

    async def send_datagram(self, addr: str, data: bytes) -> None:
        raise NotImplementedError

    async def send_uni(self, addr: str, data: bytes) -> None:
        raise NotImplementedError

    async def open_bi(self, addr: str) -> BiStream:
        raise NotImplementedError

    def set_handlers(
        self,
        on_datagram: DatagramHandler,
        on_uni: UniHandler,
        on_bi: BiHandler,
    ) -> None:
        self.on_datagram = on_datagram
        self.on_uni = on_uni
        self.on_bi = on_bi

    async def close(self) -> None:
        pass


class MemoryNetwork:
    """Shared registry for in-process transports, with per-edge link models."""

    def __init__(self, default_link: Optional[LinkModel] = None):
        self.nodes: Dict[str, "MemoryTransport"] = {}
        self.links: Dict[Tuple[str, str], LinkModel] = {}
        self.default_link = default_link or LinkModel()
        self.partitioned: set = set()  # {(a, b)} directed blocked edges

    def transport(self, addr: str) -> "MemoryTransport":
        t = MemoryTransport(self, addr)
        self.nodes[addr] = t
        return t

    def link(self, src: str, dst: str) -> LinkModel:
        return self.links.get((src, dst), self.default_link)

    def partition(self, a: str, b: str, bidirectional: bool = True):
        self.partitioned.add((a, b))
        if bidirectional:
            self.partitioned.add((b, a))

    def heal(self):
        self.partitioned.clear()

    def reachable(self, src: str, dst: str) -> bool:
        return (src, dst) not in self.partitioned and dst in self.nodes


class MemoryTransport(Transport):
    def __init__(self, net: MemoryNetwork, addr: str):
        self.net = net
        self.addr = addr
        self.on_datagram: Optional[DatagramHandler] = None
        self.on_uni: Optional[UniHandler] = None
        self.on_bi: Optional[BiHandler] = None
        self._tasks: set = set()

    def _spawn(self, coro):
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _deliver(self, addr: str, kind: str, payload) -> bool:
        if not self.net.reachable(self.addr, addr):
            return False
        link = self.net.link(self.addr, addr)
        if kind in ("datagram", "uni") and link.drop():
            return False
        dst = self.net.nodes[addr]

        async def run():
            if link.latency_s:
                await asyncio.sleep(link.latency_s)
            handler = getattr(dst, f"on_{kind}")
            if handler is not None:
                await handler(self.addr, payload)

        self._spawn(run())
        return True

    async def send_datagram(self, addr: str, data: bytes) -> None:
        await self._deliver(addr, "datagram", data)

    async def send_uni(self, addr: str, data: bytes) -> None:
        await self._deliver(addr, "uni", data)

    async def open_bi(self, addr: str) -> BiStream:
        if not self.net.reachable(self.addr, addr):
            raise ConnectionError(f"{addr} unreachable")
        ours, theirs = BiStream.pair()
        link = self.net.link(self.addr, addr)
        dst = self.net.nodes[addr]

        async def run():
            if link.latency_s:
                await asyncio.sleep(link.latency_s)
            if dst.on_bi is not None:
                await dst.on_bi(self.addr, theirs)

        self._spawn(run())
        return ours

    async def close(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        self.net.nodes.pop(self.addr, None)


# ---------------------------------------------------------------------------
# Real sockets: UDP datagrams + TCP framed streams


def _frame(data: bytes) -> bytes:
    return struct.pack(">I", len(data)) + data


async def _read_frame(reader: asyncio.StreamReader) -> Optional[bytes]:
    try:
        hdr = await reader.readexactly(4)
        (n,) = struct.unpack(">I", hdr)
        return await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None


class _TcpBiStream(BiStream):
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        super().__init__()
        self.reader = reader
        self.writer = writer

    async def send(self, frame: bytes) -> None:
        self.writer.write(_frame(frame))
        await self.writer.drain()

    async def recv(self, timeout: Optional[float] = None) -> Optional[bytes]:
        try:
            return await asyncio.wait_for(_read_frame(self.reader), timeout)
        except asyncio.TimeoutError:
            return None

    def close(self) -> None:
        self.closed = True
        try:
            self.writer.close()
        except Exception:
            pass


class UdpTcpTransport(Transport):
    """Datagrams over UDP, uni/bi streams over TCP, one port each.

    A uni stream is a TCP connection opened with a 1-byte tag; a bi stream
    stays open for framed request/response exchange (the reference's QUIC
    uni/bi distinction, api/peer/mod.rs:118-339)."""

    TAG_UNI = b"u"
    TAG_BI = b"b"

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self.addr = ""
        self.on_datagram = None
        self.on_uni = None
        self.on_bi = None
        self._udp = None
        self._tcp_server = None
        self._tasks: set = set()

    async def start(self) -> str:
        loop = asyncio.get_running_loop()

        outer = self

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                if outer.on_datagram is not None:
                    task = loop.create_task(outer.on_datagram(f"{addr[0]}:{addr[1]}", data))
                    outer._tasks.add(task)
                    task.add_done_callback(outer._tasks.discard)

        self._tcp_server = await asyncio.start_server(
            self._on_tcp, self._host, self._port
        )
        self._port = self._tcp_server.sockets[0].getsockname()[1]
        self._udp, _ = await loop.create_datagram_endpoint(
            Proto, local_addr=(self._host, self._port)
        )
        self.addr = f"{self._host}:{self._port}"
        return self.addr

    async def _on_tcp(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        peer_addr = f"{peer[0]}:{peer[1]}" if peer else "?"
        try:
            tag = await reader.readexactly(1)
        except (asyncio.IncompleteReadError, ConnectionError):
            writer.close()
            return
        if tag == self.TAG_UNI:
            data = await _read_frame(reader)
            writer.close()
            if data is not None and self.on_uni is not None:
                await self.on_uni(peer_addr, data)
        elif tag == self.TAG_BI:
            if self.on_bi is not None:
                await self.on_bi(peer_addr, _TcpBiStream(reader, writer))
        else:
            writer.close()

    async def send_datagram(self, addr: str, data: bytes) -> None:
        host, port = addr.rsplit(":", 1)
        self._udp.sendto(data, (host, int(port)))

    async def send_uni(self, addr: str, data: bytes) -> None:
        host, port = addr.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        writer.write(self.TAG_UNI + _frame(data))
        await writer.drain()
        writer.close()

    async def open_bi(self, addr: str) -> BiStream:
        host, port = addr.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        writer.write(self.TAG_BI)
        await writer.drain()
        return _TcpBiStream(reader, writer)

    async def close(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        if self._udp:
            self._udp.close()
        if self._tcp_server:
            self._tcp_server.close()
            await self._tcp_server.wait_closed()
