"""The host agent runtime: local writes → broadcast; gossip receive → ingest;
periodic anti-entropy sync.

Rebuild of the reference's corro-agent runtime re-architected for asyncio:

- local commit path = `make_broadcastable_changes` + `broadcast_changes`
  (api/public/mod.rs:53-138, broadcast.rs:511-579);
- `handle_changes` ingest loop with dedup, known-version check, rebroadcast
  decision, queue-overflow drop (agent/handlers.rs:548-786);
- partial/buffered change tracking (`process_incomplete_version` /
  `process_fully_buffered_changes`, agent/util.rs:487-1303);
- broadcast dissemination with ring-0-first fan-out, max_transmissions decay
  and 500 ms flush (broadcast/mod.rs:410-1042);
- anti-entropy `sync_loop`/`parallel_sync`/`serve_sync` with need
  computation (api/peer/mod.rs:1003-1649, util.rs:347-393).

SWIM membership rides the datagram verb (corrosion_tpu.agent.swim); with it
disabled membership is static (bootstrap list), which is the M1 slice.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..core import swim_tuning
from ..core.bookkeeping import PartialVersion
from ..core.changes import ChunkedChanges
from ..core.intervals import RangeSet
from ..core.sync import compute_available_needs, generate_sync
from ..core.types import (
    Actor,
    ActorId,
    Change,
    ChangeSource,
    Changeset,
    ChangesetPart,
    SyncNeed,
)
from ..core.hlc import HLC, ClockDriftError
from ..invariants import CATALOG, Timed, always, sometimes
from ..metrics import REGISTRY
from ..utils.backoff import Backoff
from ..utils.locks import LockRegistry
from . import codec
from .bookie import Bookie
from .config import Config
from .members import Members
from .store import CommitInfo, CrrStore
from .transport import BiStream, Transport


# hot-path histograms (corro_sqlite_pool_execution_seconds /
# corro_sync_* families in doc/telemetry/prometheus.md)
_apply_hist = REGISTRY.histogram("corro_agent_apply_seconds")
_sync_hist = REGISTRY.histogram("corro_sync_round_seconds")

log = logging.getLogger("corrosion_tpu.agent")


class SlowPeerAbort(ConnectionError):
    """A sync peer stalled past the abort threshold while being served
    (the reference kills 5 s-stalled senders, peer/mod.rs:729-790)."""


class AdaptiveSender:
    """Adaptive chunk sizing for sync serving (peer/mod.rs:365-368):
    every send is timed; a send slower than ``sync_slow_send_s`` halves
    the chunk size down to ``min_changes_byte_size``, and a send that
    stalls past ``sync_stall_abort_s`` raises SlowPeerAbort.  This turns
    MIN_CHANGES_BYTE_SIZE from a dead constant into live behavior
    (VERDICT r1 item 6)."""

    def __init__(self, perf, telemetry=None):
        self.chunk_size = perf.max_changes_byte_size
        self.min_size = perf.min_changes_byte_size
        self.slow_send_s = perf.sync_slow_send_s
        self.abort_send_s = perf.sync_stall_abort_s
        self.shrinks = 0
        self.telemetry = telemetry

    async def send(self, bi: "BiStream", frame: bytes) -> None:
        t0 = time.monotonic()
        try:
            await asyncio.wait_for(bi.send(frame), self.abort_send_s)
        except asyncio.TimeoutError:
            raise SlowPeerAbort(
                f"send stalled > {self.abort_send_s}s"
            ) from None
        if self.telemetry is not None:
            self.telemetry.wire("sync_out", len(frame))
        if (
            time.monotonic() - t0 >= self.slow_send_s
            and self.chunk_size > self.min_size
        ):
            self.chunk_size = max(self.chunk_size // 2, self.min_size)
            self.shrinks += 1

# coverage markers registered statically so a dead code path still shows
# as an unfired gap (the reference's assert_sometimes catalog)
CATALOG.expect_sometimes(
    "broadcasts-happen",
    "sync-happens",
    "partial-version-buffered",
    "ingest-queue-overflow-drop",
)


@dataclass
class _PendingBroadcast:
    frame: bytes
    send_count: int = 0
    is_local: bool = True
    # replication identity of LOCAL frames (flight-recorder stage key);
    # relayed frames leave these unset — their broadcast_out belongs to
    # the origin node's record
    actor_id: Optional[ActorId] = None
    version: int = -1


class _WriterLock(asyncio.Lock):
    """asyncio.Lock that records its owning task, so ``interactive_tx``
    can verify the CALLER holds the writer lane — ``locked()`` alone
    would pass precisely when another lane (e.g. ingest mid-apply) holds
    it, which is the interleaving the guard must reject."""

    def __init__(self):
        super().__init__()
        self.owner: Optional[asyncio.Task] = None

    async def acquire(self) -> bool:
        ok = await super().acquire()
        self.owner = asyncio.current_task()
        return ok

    def release(self) -> None:
        self.owner = None
        super().release()

    def held_by_current_task(self) -> bool:
        return self.locked() and self.owner is asyncio.current_task()


class Agent:
    """One node: storage + bookkeeping + gossip runtime."""

    def __init__(self, config: Config, transport: Transport):
        self.config = config
        self.clock = HLC()
        self.store = CrrStore(config.db_path, ActorId.random(), self.clock)
        self.actor_id = self.store.site_id
        self.bookie = Bookie(self.store)
        self.members = Members(self.actor_id)
        self.transport = transport
        transport.set_handlers(self._on_datagram, self._on_uni, self._on_bi)
        # transport-level RTT samples feed the member RTT rings
        # (transport.rs:220 → Members rtt buckets, members.rs:38-179)
        if getattr(transport, "on_rtt", "absent") is None:
            transport.on_rtt = self._on_transport_rtt

        # bounded by the flush tick's drop-most-sent-oldest trim to
        # perf.broadcast_max_inflight (_broadcast_loop) — a maxlen here
        # would drop NEWEST-first, the wrong end of the epidemic
        # corrolint: disable=CT008
        self._bcast_q: deque = deque()  # _PendingBroadcast
        # bounded by the counted drop-OLDEST policy at enqueue
        # (perf.changes_queue_cap in _enqueue_changeset, the reference's
        # handlers.rs:729-749 overflow rule) — Queue(maxsize) would
        # BLOCK the receive path instead of shedding
        # corrolint: disable=CT008
        self._ingest_q: asyncio.Queue = asyncio.Queue()
        self._seen: OrderedDict = OrderedDict()  # dedup cache (handlers.rs:671)
        self._sync_inbound = 0
        self._tasks: List[asyncio.Task] = []
        self._stopped = asyncio.Event()
        # the ONE writer lane at the event-loop level (agent.rs:97
        # write_sema): held across PG explicit transactions, acquired by
        # the ingest loop so remote applies can't interleave with one
        self.write_sema = _WriterLock()
        # `slow` gray-failure stall gate (faults.py, ISSUE 15): seconds
        # every gated operation (commit drain, sync need serve, SWIM
        # datagram handling) sleeps while armed.  0.0 = healthy; fault
        # drivers arm it via set_slow_inject()
        self.slow_inject_s = 0.0
        self._rng = random.Random(self.actor_id.bytes_)
        self.swim = None  # attached by SwimRuntime.attach()
        # host-tier flight recorder + serving metric families (ISSUE 8):
        # None = off, and every hook site below is a single attribute
        # test — the uninstrumented serving path is a measured no-op
        # (telemetry.attach_host_telemetry arms it)
        self.telemetry = None
        # labeled critical-section registry + watchdog (agent.rs:830-1055)
        self.locks = LockRegistry()
        # pubsub engine (L9): SQL subscriptions + per-table updates
        from ..pubsub import SubsManager, UpdatesManager

        subs_dir = (
            None if config.db_path in (":memory:", "") else config.db_path + ".subs"
        )
        self.subs = SubsManager(
            self.store, subs_dir, queue_cap=config.perf.sub_queue_cap
        )
        self.updates = UpdatesManager(queue_cap=config.perf.sub_queue_cap)
        # metrics counters (metrics facade analog)
        self.stats = {
            "changes_committed": 0, "changes_applied": 0, "changes_deduped": 0,
            "broadcasts_sent": 0, "broadcasts_recv": 0, "sync_rounds": 0,
            "ingest_dropped": 0, "empties_recv": 0, "changes_failed": 0,
            "cluster_mismatch_dropped": 0, "sync_rejected_different_cluster": 0,
        }
        # protocol-native clock for calibration (VERDICT r2 item 2): the
        # broadcast flush tick counter and per-version apply ticks.  A
        # loaded machine stretches every timer equally, so latency
        # DENOMINATED IN TICKS stays stable where wall-clock does not —
        # the ground-truth tests read these instead of the wall.
        self.flush_tick = 0
        self.apply_tick: Dict[Tuple[ActorId, int], int] = {}
        # fully-buffered versions whose final apply failed: (actor,
        # version) -> attempts.  Drained by _buffered_retry_loop (the
        # reference's apply_fully_buffered_changes_loop, util.rs:395-422)
        self._buffered_retry: Dict[Tuple[ActorId, int], int] = {}

    _APPLY_TICK_CAP = 65536  # calibration-only record; never unbounded

    def _record_apply_tick(self, actor_id: ActorId, version: int) -> None:
        self.apply_tick.setdefault((actor_id, version), self.flush_tick)
        while len(self.apply_tick) > self._APPLY_TICK_CAP:
            self.apply_tick.pop(next(iter(self.apply_tick)))

    # -- lifecycle --------------------------------------------------------

    async def start(self):
        if self.config.schema_paths:
            from ..utils.files import read_sql_files

            # all files form ONE schema (the reference joins every parsed
            # file into a single Schema before apply, run_root.rs:101-106) —
            # applying files separately would read each as a full schema
            # and reject the tables the other files own as drops
            sql = ";\n".join(
                s
                for path in self.config.schema_paths
                for s in read_sql_files(path)
            )
            if sql.strip():
                self.store.execute_schema(sql)
        self.subs.restore()
        # schedule applies for fully-buffered partials that survived a
        # restart (run_root.rs:180-194): the wedged-version ledger is
        # memory-only, but the partial records + buffered rows are
        # durable — reseed the retry loop from them so a crash between
        # buffering completion and apply cannot wedge a version forever
        for actor_id, booked in self.bookie.by_actor.items():
            for version, partial in booked.partials.items():
                if partial.is_complete():
                    self._buffered_retry[(actor_id, version)] = 0
        # [telemetry] OTLP pipeline (main.rs:57-150): spans leave the
        # process once an endpoint is configured; otherwise they stay in
        # the in-process ring only
        from ..otlp import exporter_from_config

        self._otlp = exporter_from_config(self.config)
        if self._otlp is not None:
            from ..tracing import TRACER

            self._otlp.install(TRACER)
        if self.config.use_swim:
            from .swim import SwimRuntime

            SwimRuntime.attach(self)
            await self.swim.start()
        else:
            # static membership from the bootstrap list; on real network
            # transports DNS entries resolve to all their records
            # (agent/bootstrap.py) — memory-transport addrs are symbolic
            # and pass through literally
            if self.transport.resolves_dns:
                from .bootstrap import resolve_bootstrap

                resolved = sorted(
                    await resolve_bootstrap(
                        self.config.bootstrap,
                        self.transport.addr,
                        resolver=getattr(self, "bootstrap_resolver", None),
                    )
                )
            else:
                resolved = list(self.config.bootstrap)
            for i, addr in enumerate(resolved):
                if addr != self.transport.addr:
                    self.members.add_member(
                        Actor(id=ActorId(bytes([0] * 15 + [i + 1])), addr=addr, ts=0)
                    )
        # counted so wait_for_all_pending_handles can drain them at
        # shutdown (spawn_counted, spawn/src/lib.rs:17)
        from ..utils.tripwire import spawn_counted

        self._tasks.append(spawn_counted(self._broadcast_loop(), "broadcast"))
        # ONE apply lane.  The reference runs ≤5 concurrent
        # process_multiple_changes jobs (handlers.rs:561-613) because its
        # tokio workers overlap parsing with the single write conn; under
        # Python's GIL that shape inverts — a hot event loop starves a
        # worker thread into 30s+ applies (measured in round 2) and extra
        # lanes just contend on write_sema.  Cost-capped batching
        # (apply_queue_cost) provides the same throughput shape; the
        # max_concurrent_applies knob documents the reference envelope.
        self._tasks.append(spawn_counted(self._ingest_loop(), "ingest"))
        self._tasks.append(spawn_counted(self._sync_loop(), "sync"))
        self._tasks.append(spawn_counted(self._lock_watchdog(), "lock-watchdog"))
        self._tasks.append(
            spawn_counted(self._buffered_retry_loop(), "buffered-retry")
        )
        from .maintenance import db_maintenance_loop

        # (no-op for in-memory stores — the loop gates itself)
        self._tasks.append(
            spawn_counted(
                db_maintenance_loop(
                    self,
                    interval_s=self.config.perf.db_maintenance_interval_s,
                ),
                "db-maintenance",
            )
        )

    async def _lock_watchdog(self):
        """Warn on long-held critical sections (setup.rs:188-246)."""
        while not self._stopped.is_set():
            await asyncio.sleep(5.0)
            worst = self.locks.check()
            if worst is not None:
                import logging

                logging.getLogger("corrosion_tpu.locks").warning(
                    "long lock hold: %s", worst
                )

    async def stop(self):
        self._stopped.set()
        if self.swim is not None:
            await self.swim.stop()
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        await self.transport.close()
        self.store.close()
        if getattr(self, "_otlp", None) is not None:
            from ..tracing import TRACER

            # final batch flush happens off-loop; bounded join
            await asyncio.to_thread(self._otlp.shutdown, TRACER)

    # -- slow gray failure (ISSUE 15) -------------------------------------

    def set_slow_inject(self, stall_s: float) -> None:
        """Arm (or, with 0.0, clear) the `slow` gray-failure gate.  The
        node stays alive and correct — it just crawls: commits stall in
        the write lane (→ admission 429s), sync serves stall per need
        (→ the peer's adaptive sender shrinks chunks / aborts), and SWIM
        datagram handling stalls (→ delayed acks → suspects).  Exposed
        as a gauge so the gray failure is visible from /metrics, not
        just inferable from symptoms."""
        self.slow_inject_s = stall_s
        from ..metrics import REGISTRY

        REGISTRY.gauge("corro_fault_slow_inject_seconds").set(stall_s)

    async def slow_gate(self) -> None:
        """The stall itself, sliced so a heal mid-stall cuts the tail
        short instead of serving the whole original sentence."""
        remaining = self.slow_inject_s
        while remaining > 0 and self.slow_inject_s > 0:
            slice_s = min(remaining, 0.1)
            await asyncio.sleep(slice_s)
            remaining -= slice_s

    # -- write path (L10 → L6) -------------------------------------------

    def exec_transaction(
        self, statements: Sequence[Tuple[str, Sequence]]
    ) -> Optional[CommitInfo]:
        """Apply local writes and queue the changeset for broadcast
        (reference api_v1_transactions → make_broadcastable_changes)."""
        return self.exec_transaction_cursors(statements)[1]

    def exec_transaction_cursors(self, statements: Sequence[Tuple[str, Sequence]]):
        booked = self.bookie.for_actor(self.actor_id)
        snap = booked.snapshot()

        def pre_commit(conn, info: CommitInfo):
            self.bookie.record_versions(
                self.actor_id, snap, RangeSet([(info.db_version, info.db_version)])
            )

        _t0 = time.monotonic()
        with self.locks.track("make_broadcastable_changes"):
            cursors, info = self.store.transact(statements, pre_commit=pre_commit)
        if info is None:
            return cursors, None
        booked.commit_snapshot(snap)
        self.stats["changes_committed"] += info.last_seq + 1
        tel = self.telemetry
        if tel is not None:
            # the PUBLISH stamp: the write is durable locally and about
            # to enter dissemination — publish→visible is measured from
            # here (doc/telemetry/host.md)
            tel.commit(time.monotonic() - _t0)
            tel.publish(
                self.actor_id, info.db_version, info.ts,
                n_changes=info.last_seq + 1,
            )
        self._queue_local_broadcast(info)
        return cursors, info

    def interactive_tx(self) -> "InteractiveTx":
        """Explicit client transaction spanning wire messages (the PG
        front-end's BEGIN..COMMIT).  Caller must hold ``write_sema`` —
        enforced, not trusted (VERDICT r4 weak #6): a second front-end
        opening a tx without the writer lane would silently interleave
        with the ingest lane's applies.  The check is OWNERSHIP, not
        mere lockedness — 'someone else holds the lane' is exactly the
        interleaving case the guard exists for."""
        if not self.write_sema.held_by_current_task():
            raise RuntimeError(
                "interactive_tx() requires write_sema to be held by the "
                "calling task; acquire the writer lane before opening an "
                "explicit transaction"
            )
        return InteractiveTx(self)

    def effective_max_transmissions(self) -> int:
        """Cluster-size-adaptive per-payload transmission budget — the
        reference re-derives this whenever its cluster-size estimate
        moves (broadcast/mod.rs:236-256); with SWIM attached, the live
        member count drives the shared formula (core/swim_tuning.py),
        otherwise the static configured budget applies."""
        if self.swim is not None:
            return self.swim.effective_max_transmissions()
        perf = self.config.perf
        if not perf.swim_adaptive_timing:
            return perf.swim_max_transmissions
        return swim_tuning.max_transmissions_for(
            1 + len(self.members.up_members()), perf.swim_max_transmissions
        )

    def _queue_local_broadcast(self, info: CommitInfo):
        """Chunk the committed version and queue frames (broadcast_changes,
        broadcast.rs:511-579)."""
        changes = self.store.changes_for_version(self.actor_id, info.db_version)
        self._match_changes(changes)
        for chunk, seqs in ChunkedChanges(
            changes, 0, info.last_seq, self.config.perf.max_changes_byte_size
        ):
            cs = Changeset(
                actor_id=self.actor_id,
                version=info.db_version,
                changes=tuple(chunk),
                seqs=seqs,
                last_seq=info.last_seq,
                ts=info.ts,
                part=ChangesetPart.FULL,
            )
            frame = codec.encode_message(
                "bcast", codec.encode_changeset(cs), ts=self.clock.now(),
                cid=self.config.cluster_id,
            )
            self._bcast_q.append(
                _PendingBroadcast(
                    frame=frame, is_local=True,
                    actor_id=self.actor_id, version=info.db_version,
                )
            )
        sometimes(True, "broadcasts-happen")

    # -- broadcast dissemination (L6) ------------------------------------

    async def _broadcast_loop(self):
        """Flush tick: ring0 first for local payloads, then random fan-out,
        decrementing a per-payload transmission budget
        (broadcast/mod.rs:589-778)."""
        perf = self.config.perf
        interval = perf.broadcast_flush_interval_s
        while not self._stopped.is_set():
            await asyncio.sleep(interval)
            self.flush_tick += 1
            tel = self.telemetry
            if tel is not None:
                # queue depths sampled once per flush tick (the scrape
                # cadence that matters), never per frame
                tel.queue_depths(self._ingest_q.qsize(), len(self._bcast_q))
            budget = perf.broadcast_rate_limit_bytes_s * interval
            requeue = []
            # one O(members) derivation per flush tick, not per item —
            # membership can't move mid-pump on the single-threaded loop
            max_tx = self.effective_max_transmissions()
            while self._bcast_q and budget > 0:
                item = self._bcast_q.popleft()
                targets = self._choose_targets(item, max_tx)
                sent_any = False
                for st in targets:
                    try:
                        await self.transport.send_uni(st.addr, item.frame)
                        self.stats["broadcasts_sent"] += 1
                        sent_any = True
                        budget -= len(item.frame)
                        if tel is not None:
                            tel.wire("broadcast_out", len(item.frame))
                    except (ConnectionError, OSError):
                        continue
                if (
                    tel is not None
                    and sent_any
                    and item.actor_id is not None
                ):
                    # the version's first SUCCESSFUL frame hit the wire:
                    # the broadcast_out stamp.  Not gated on send_count —
                    # a pass whose sends all failed must not eat the
                    # stamp forever; the recorder dedupes re-sends
                    tel.broadcast_out(item.actor_id, item.version)
                item.send_count += 1
                if targets and item.send_count < max_tx:
                    requeue.append(item)
            # re-queue with remaining budget; overflow drops most-sent-oldest
            self._bcast_q.extend(requeue)
            cap = perf.broadcast_max_inflight
            while len(self._bcast_q) > cap:
                self._bcast_q.remove(
                    max(self._bcast_q, key=lambda it: it.send_count)
                )

    def _choose_targets(self, item: _PendingBroadcast, max_tx: int):
        members = self.members.up_members()
        if not members:
            return []
        perf = self.config.perf
        chosen: dict = {}
        if item.is_local and item.send_count == 0:
            for st in self.members.ring0():
                chosen[st.actor.id] = st
        rest = [st for st in members if st.actor.id not in chosen]
        # choose_count formula, broadcast/mod.rs:653-680; max_tx is the
        # cluster-size-adaptive budget, derived once per flush tick
        n = max(
            perf.swim_num_indirect_probes,
            len(rest) // (max_tx * 10),
        )
        for st in self._rng.sample(rest, min(n, len(rest))):
            chosen[st.actor.id] = st
        return list(chosen.values())

    # -- receive path (L8) ------------------------------------------------

    async def _on_datagram(self, src: str, data: bytes):
        if self.slow_inject_s > 0:
            # slow-node gray failure: probe handling crawls, so acks
            # leave late and peers' probe timeouts mark us SUSPECT —
            # degraded-not-dead, exactly the signal SWIM exists to raise
            # (runs off the frame pump, so only this datagram stalls)
            await self.slow_gate()
        if self.swim is not None:
            await self.swim.handle_datagram(src, data)

    async def _on_uni(self, src: str, data: bytes):
        kind, body, ts, _tr, cid = codec.decode_message_full(data)
        if kind != "bcast":
            return
        if cid != self.config.cluster_id:
            # cross-cluster broadcasts are dropped before any CRDT state is
            # touched (uni.rs:73-75 checks the cluster id on every incoming
            # BroadcastV1 frame)
            self.stats["cluster_mismatch_dropped"] += 1
            return
        if ts is not None:
            try:
                self.clock.update(ts)
            except ClockDriftError:
                return
        cs = codec.decode_changeset(body)
        self.stats["broadcasts_recv"] += 1
        if self.telemetry is not None:
            self.telemetry.wire("broadcast_in", len(data))
        await self._enqueue_changeset(cs, ChangeSource.BROADCAST, raw=data)

    async def _enqueue_changeset(
        self, cs: Changeset, source: ChangeSource, raw: Optional[bytes] = None
    ):
        """handle_changes front half (handlers.rs:548-786): self-skip, dedup,
        known-check, overflow drop, rebroadcast decision."""
        if cs.actor_id == self.actor_id:
            return
        key = (cs.actor_id, cs.versions, cs.seqs, cs.part)
        now = time.monotonic()
        perf = self.config.perf
        seen_at = self._seen.get(key)
        if seen_at is not None and now - seen_at < perf.seen_cache_ttl_s:
            self.stats["changes_deduped"] += 1
            return
        booked = self.bookie.for_actor(cs.actor_id)
        seqs = cs.seqs if cs.part is ChangesetPart.FULL else None
        if booked.contains_all(cs.versions, seqs):
            self.stats["changes_deduped"] += 1
            return  # already known: stop disseminating
        # TTL'd insertion-ordered cache sized to the queue-cap envelope
        # (VERDICT r1 weak #6: a 4096 FIFO with no TTL re-admitted
        # evicted keys at 30+ nodes); expired heads drain lazily
        self._seen.pop(key, None)
        self._seen[key] = now
        while len(self._seen) > perf.seen_cache_cap:
            self._seen.popitem(last=False)
        while self._seen:
            k0, t0 = next(iter(self._seen.items()))
            if now - t0 < perf.seen_cache_ttl_s:
                break
            self._seen.pop(k0, None)
        if self._ingest_q.qsize() >= perf.changes_queue_cap:
            # overflow: drop oldest (handlers.rs:729-749)
            try:
                self._ingest_q.get_nowait()
                self.stats["ingest_dropped"] += 1
                sometimes(True, "ingest-queue-overflow-drop")
            except asyncio.QueueEmpty:
                pass
        await self._ingest_q.put(cs)
        if source is ChangeSource.BROADCAST and cs.changes and raw is not None:
            # epidemic relay (handlers.rs:768-779)
            self._bcast_q.append(
                _PendingBroadcast(frame=raw, send_count=1, is_local=False)
            )

    async def _ingest_loop(self):
        """Batched apply (process_multiple_changes, util.rs:691-1037;
        the reference's concurrency envelope, handlers.rs:561-613, maps
        to cost-capped batches on one lane under the GIL)."""
        while not self._stopped.is_set():
            cs = await self._ingest_q.get()
            batch = [cs]
            cost = cs.processing_cost()
            while cost < self.config.perf.apply_queue_cost:
                try:
                    nxt = self._ingest_q.get_nowait()
                except asyncio.QueueEmpty:
                    break
                batch.append(nxt)
                cost += nxt.processing_cost()
            try:
                async with self.write_sema:
                    with _apply_hist.time(), Timed(
                        "changes-processing-under-budget", 60.0
                    ):
                        # the session runs INLINE on the loop (no awaits
                        # inside): atomic w.r.t. all other loop code, and
                        # the store's write_session lock serializes it
                        # against genuinely threaded conn users (the
                        # interrupt watchdog, close())
                        matched = self._process_changesets_db(batch)
                self._match_changes(matched)
            except Exception:  # keep the loop alive; reference logs + drops
                import traceback

                traceback.print_exc()

    def _process_changesets(self, batch: List[Changeset]):
        """Synchronous apply entry (tests + non-loop callers)."""
        self._match_changes(self._process_changesets_db(batch))

    def _process_changesets_db(self, batch: List[Changeset]) -> List[Change]:
        """One snapshot per origin actor for the whole batch, committed to
        memory only after the data transaction lands (util.rs:691-1037,
        892-932).  Runs inline on the event loop under write_sema; the
        store's write_session lock additionally guards the shared conn
        against threaded users (watchdog, close).  Returns the committed
        changes for subscription matching."""
        store = self.store
        snaps: Dict[ActorId, Tuple] = {}  # actor -> (booked, snap)

        def snap_for(actor_id: ActorId):
            if actor_id not in snaps:
                booked = self.bookie.for_actor(actor_id)
                snaps[actor_id] = (booked, booked.snapshot())
            return snaps[actor_id][1]

        partials: List[Changeset] = []
        matched: List[Change] = []
        # the store's writer lock is held for the WHOLE session so this
        # can safely run in a worker thread: loop-side conn users (WAL
        # maintenance, exec_transaction) serialize against it and close()
        # waits for it instead of yanking the conn mid-transaction
        with store.write_session():
            with self.locks.track("process_multiple_changes"):
                self._apply_batch_tx(batch, store, snap_for, partials, matched)
            # in-memory bookkeeping only after the data commit succeeded
            for booked, snap in snaps.values():
                booked.commit_snapshot(snap)
            for actor_id, version in dict.fromkeys(partials):
                partial = self.bookie.for_actor(actor_id).get_partial(version)
                if partial is not None and partial.is_complete():
                    try:
                        self._apply_fully_buffered(actor_id, version)
                    except Exception:
                        # same isolation as the complete path: a
                        # malformed buffered version must not swallow
                        # the batch's `matched` list (subscriptions for
                        # already-committed changes) or kill the lane.
                        # Rows stay buffered and the version goes on the
                        # retry ledger drained by _buffered_retry_loop
                        # (the reference's apply_fully_buffered_changes
                        # _loop, util.rs:395-422) — it is already
                        # recorded as known, so sync will NOT
                        # re-request it; without the retry it would
                        # wedge unapplied forever
                        self.stats["changes_failed"] += 1
                        self._buffered_retry[(actor_id, version)] = 0
                        log.warning(
                            "buffered apply failed for %s v%s; queued for "
                            "retry", actor_id, version, exc_info=True,
                        )
        # subscriptions match committed changes only (util.rs:1026-1030);
        # returned so the async lanes can match on the event loop
        return matched

    def _apply_batch_tx(self, batch, store, snap_for, partials, matched):
        store.begin_apply()
        try:
            for cs in batch:
                snap = snap_for(cs.actor_id)
                if cs.part is ChangesetPart.EMPTY:
                    lo, hi = cs.versions
                    self.bookie.record_versions(cs.actor_id, snap, RangeSet([(lo, hi)]))
                    self.stats["empties_recv"] += 1
                    continue
                if snap.contains_all(cs.versions, cs.seqs):
                    continue
                # a version already tracked partial must go through the
                # buffered-merge path even if this chunk claims completeness:
                # a partial-need reply's last_seq only spans the served range,
                # and the authoritative last_seq lives in our existing partial
                if cs.is_complete() and snap.partials.get(cs.version) is None:
                    # per-version failure isolation (process_single_version
                    # runs in its own savepoint, util.rs:487-539 + the
                    # process_failed_changes test): a malformed changeset —
                    # e.g. a column the local schema lacks — must not
                    # poison the other versions in this batch.  Nothing is
                    # recorded for the failed version, so anti-entropy
                    # re-requests it later (possibly repaired).
                    store.conn.execute("SAVEPOINT corro_apply_cs")
                    try:
                        impacted = store.apply_changes(cs.changes, in_tx=True)
                    except Exception:
                        store.conn.execute("ROLLBACK TO corro_apply_cs")
                        store.conn.execute("RELEASE corro_apply_cs")
                        self.stats["changes_failed"] += 1
                        log.warning(
                            "changeset apply failed for %s v%s; version "
                            "left unknown for anti-entropy re-request",
                            cs.actor_id, cs.version, exc_info=True,
                        )
                        continue
                    store.conn.execute("RELEASE corro_apply_cs")
                    self.bookie.record_versions(
                        cs.actor_id, snap, RangeSet([(cs.version, cs.version)])
                    )
                    snap.partials.pop(cs.version, None)
                    self.bookie.clear_partial(cs.actor_id, cs.version)
                    self._clear_buffered(cs.actor_id, cs.version)
                    self.stats["changes_applied"] += impacted
                    self._record_apply_tick(cs.actor_id, cs.version)
                    if self.telemetry is not None:
                        self.telemetry.apply(cs.actor_id, cs.version)
                    matched.extend(cs.changes)
                else:
                    # version-level knowledge is recorded FIRST — and even
                    # when incomplete (the reference insert_db's partial
                    # versions too, util.rs:892-932); seq gaps ride
                    # partial_need instead.  Order matters: insert_db pops
                    # partial records for versions whose needed-gap it
                    # removes (supersede semantics), so recording AFTER
                    # inserting the partial would destroy it whenever the
                    # version arrived out of order (below the current max)
                    # — versions would look known while their rows sat in
                    # the buffer table forever
                    self.bookie.record_versions(
                        cs.actor_id, snap, RangeSet([(cs.version, cs.version)])
                    )
                    # merge seq coverage into the snapshot so later chunks of
                    # the same version in this batch aren't mistaken for known
                    p = snap.partials.get(cs.version)
                    if p is None:
                        p = PartialVersion(
                            seqs=RangeSet([cs.seqs]), last_seq=cs.last_seq, ts=cs.ts
                        )
                        snap.partials[cs.version] = p
                    else:
                        p.seqs.insert(*cs.seqs)
                    self._buffer_rows(cs)
                    self.bookie.persist_partial(cs.actor_id, cs.version, p)
                    partials.append((cs.actor_id, cs.version))
            store.end_apply(commit=True)
        except Exception:
            store.end_apply(commit=False)
            raise

    def _buffer_rows(self, cs: Changeset):
        """process_incomplete_version row staging (util.rs:1053-1186):
        stash rows, applied only once every seq arrived."""
        sometimes(True, "partial-version-buffered")
        got = sorted(ch.seq for ch in cs.changes)
        always(
            all(b - a == 1 for a, b in zip(got, got[1:])),
            "buffered-seqs-contiguous",
            {"versions": repr(cs.versions), "n": len(got)},
        )
        self.store.conn.executemany(
            'INSERT OR REPLACE INTO __corro_buffered_changes '
            '("table", pk, cid, val, col_version, db_version, seq, site_id, cl, ts) '
            "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
            [
                (ch.table, ch.pk, ch.cid, ch.val, ch.col_version, ch.db_version,
                 ch.seq, ch.site_id.bytes_, ch.cl, cs.ts)
                for ch in cs.changes
            ],
        )

    def _apply_fully_buffered(self, actor_id: ActorId, version: int):
        """process_fully_buffered_changes (util.rs:541-688)."""
        conn = self.store.conn
        rows = conn.execute(
            'SELECT "table", pk, cid, val, col_version, db_version, seq, site_id, cl '
            "FROM __corro_buffered_changes WHERE site_id = ? AND db_version = ? "
            "ORDER BY seq",
            (actor_id.bytes_, version),
        ).fetchall()
        changes = [
            Change(
                table=r[0], pk=r[1], cid=r[2], val=r[3], col_version=r[4],
                db_version=r[5], seq=r[6], site_id=ActorId(r[7]), cl=r[8],
            )
            for r in rows
        ]
        booked = self.bookie.for_actor(actor_id)
        self.store.begin_apply()
        try:
            impacted = self.store.apply_changes(changes, in_tx=True)
            snap = booked.snapshot()
            self.bookie.record_versions(actor_id, snap, RangeSet([(version, version)]))
            self.bookie.clear_partial(actor_id, version)
            self._clear_buffered(actor_id, version)
            self.store.end_apply(commit=True)
        except Exception:
            self.store.end_apply(commit=False)
            raise
        booked.commit_snapshot(snap)
        booked.partials.pop(version, None)
        self.stats["changes_applied"] += impacted
        self._record_apply_tick(actor_id, version)
        if self.telemetry is not None:
            self.telemetry.apply(actor_id, version)
        self._match_changes(changes)

    async def _buffered_retry_loop(self):
        """apply_fully_buffered_changes_loop (util.rs:395-422): retry
        fully-buffered versions whose final apply failed.  Transient
        errors (a busy writer, a schema later repaired by migration)
        heal here; persistent ones keep logging at a decaying cadence so
        the operator can see WHICH version is stuck — without this loop
        a failed buffered version wedges forever, because it is already
        recorded as known and sync never re-requests it."""
        while not self._stopped.is_set():
            await asyncio.sleep(1.0)
            for key in list(self._buffered_retry):
                actor_id, version = key
                ticks = self._buffered_retry[key]
                # decaying cadence: ticks 0,1,2, then powers of 2, CAPPED
                # at one retry per 64 ticks so a repaired schema heals
                # within ~a minute no matter how long the wedge lasted
                if (
                    ticks > 2
                    and ticks & (ticks - 1)
                    and ticks % 64
                ):
                    self._buffered_retry[key] = ticks + 1
                    continue
                try:
                    async with self.write_sema:
                        with self.store.write_session():
                            self._apply_fully_buffered(actor_id, version)
                    self._buffered_retry.pop(key, None)
                    log.info(
                        "buffered retry healed %s v%s on tick %d",
                        actor_id, version, ticks,
                    )
                except Exception:
                    self._buffered_retry[key] = ticks + 1
                    log.warning(
                        "buffered retry failed for %s v%s (tick %d)",
                        actor_id, version, ticks, exc_info=True,
                    )

    def _match_changes(self, changes: List[Change]):
        """Feed committed changes to subscriptions + updates notifiers
        (match_changes, updates.rs:420; broadcast.rs:544-545)."""
        if not changes:
            return
        self.subs.match_changes(changes)
        self.updates.match_changes(changes)
        tel = self.telemetry
        if tel is not None:
            # the VISIBLE stamp: keyed matchers deliver synchronously
            # (put_nowait inside match_changes), so the batch's versions
            # are subscriber-visible NOW — but a fallback (non-keyed)
            # matcher inside its re-run budget only marked itself dirty,
            # and stamping now would antedate visibility by the whole
            # defer window.  Those versions park in the SubsManager and
            # stamp when the trailing flush actually delivers.  hlc_now
            # is the node's LOCAL clock reading: the skew-surviving
            # proxy column (doc/telemetry/host.md)
            hlc_now = self.clock.peek()
            pairs = list(dict.fromkeys(
                (ch.site_id, ch.db_version) for ch in changes
            ))
            tables = {ch.table for ch in changes}
            if self.subs.has_dirty(tables):
                self.subs.defer_visible(pairs, hlc_now, tables)
            else:
                for actor_id, version in pairs:
                    tel.visible(actor_id, version, hlc_now=hlc_now)

    def _clear_buffered(self, actor_id: ActorId, version: int):
        self.store.conn.execute(
            "DELETE FROM __corro_buffered_changes WHERE site_id = ? AND db_version = ?",
            (actor_id.bytes_, version),
        )

    # -- anti-entropy sync (L7) -------------------------------------------

    def sync_state(self):
        return generate_sync(self.bookie.by_actor, self.actor_id)

    async def _sync_loop(self):
        """Periodic client-side sync with decorrelated backoff
        (util.rs:347-393, handlers.rs:793-894)."""
        perf = self.config.perf
        backoff = Backoff(
            perf.sync_backoff_min_s, perf.sync_backoff_max_s, rng=self._rng
        )
        while not self._stopped.is_set():
            await asyncio.sleep(next(backoff))
            try:
                synced = await self.parallel_sync()
                if synced:
                    backoff.reset()
            except Exception:
                # peers being down is routine (the backoff absorbs it),
                # but a swallowed failure here once hid real sync bugs
                # for whole flaky-suite hunts — leave a debug trace
                log.debug("parallel sync pass failed", exc_info=True)
                continue

    def _choose_sync_peers(self) -> List:
        """(candidates/100).clamp(3,10) peers, need-first then rtt ring
        (handlers.rs:808-863)."""
        candidates = self.members.up_members()
        if not candidates:
            return []
        state = self.sync_state()
        desired = max(3, min(10, len(candidates) // 100 or 3))
        pool = self._rng.sample(candidates, min(len(candidates), desired * 2))
        pool.sort(key=lambda st: (-state.need_len_for_actor(st.actor.id), st.ring or 0))
        return pool[:desired]

    async def parallel_sync(self) -> int:
        """One client sync round against chosen peers (peer/mod.rs:1003-1403).
        Returns number of changesets ingested."""
        peers = self._choose_sync_peers()
        if not peers:
            return 0
        self.stats["sync_rounds"] += 1
        sometimes(True, "sync-happens")
        with _sync_hist.time():
            results = await asyncio.gather(
                *(self._sync_with(st.addr) for st in peers), return_exceptions=True
            )
        return sum(r for r in results if isinstance(r, int))

    async def _sync_with(self, addr: str, timeout: float = 30.0) -> int:
        from ..tracing import span

        with span("parallel_sync", peer=addr) as sp:
            return await self._sync_with_traced(addr, timeout, sp)

    def _on_transport_rtt(self, addr: str, rtt_s: float) -> None:
        self.members.record_rtt(addr, rtt_s * 1000.0)

    async def _sync_with_traced(self, addr: str, timeout: float, sp) -> int:
        ours = self.sync_state()
        _t0 = time.monotonic()
        bi = await self.transport.open_bi(addr)
        try:
            # trace context rides the handshake so the trace spans both
            # ends (SyncTraceContextV1, peer/mod.rs:1019-1022)
            await bi.send(
                codec.encode_message(
                    "sync_start",
                    codec.encode_sync_state(ours),
                    ts=self.clock.now(),
                    trace={"traceparent": sp.context.traceparent()},
                    cid=self.config.cluster_id,
                )
            )
            frame = await bi.recv(timeout)
            if not frame:
                return 0
            # handshake round-trip = a fresh RTT sample for the peer's
            # ring bucket (the reference samples path RTT per exchange)
            self.members.record_rtt(addr, (time.monotonic() - _t0) * 1000.0)
            kind, body, ts, _tr, cid = codec.decode_message_full(frame)
            if kind == "sync_reject":
                if body == "different_cluster":
                    self.stats["sync_rejected_different_cluster"] += 1
                    # the peer told us it belongs to another cluster:
                    # demote it so it leaves the sync rotation and the
                    # broadcast fan-out instead of being retried forever
                    aid = self.members.by_addr.get(addr)
                    st = self.members.get(aid) if aid is not None else None
                    if st is not None:
                        self.members.remove_member(st.actor)
                return 0
            if kind != "sync_state":
                return 0
            if cid != self.config.cluster_id:
                # symmetric client-side guard: never ingest state served by
                # a foreign cluster (the server normally rejects first —
                # peer/mod.rs:1431 SyncRejectionV1::DifferentCluster)
                self.stats["cluster_mismatch_dropped"] += 1
                return 0
            if ts is not None:
                try:
                    self.clock.update(ts)
                except ClockDriftError:
                    return 0
            theirs = codec.decode_sync_state(body)
            needs = compute_available_needs(ours, theirs)
            if not needs:
                await bi.send(codec.encode_message("sync_request", {}))
                return 0
            await bi.send(codec.encode_message("sync_request", codec.encode_needs(needs)))
            count = 0
            while True:
                frame = await bi.recv(timeout)
                if not frame:
                    break
                if self.telemetry is not None:
                    self.telemetry.wire("sync_in", len(frame))
                kind, body, _ = codec.decode_message(frame)
                if kind == "sync_done" or kind == "":
                    break
                if kind == "changeset":
                    cs = codec.decode_changeset(body)
                    await self._enqueue_changeset(cs, ChangeSource.SYNC)
                    count += 1
            sp.set_attribute("changesets", count)
            return count
        finally:
            bi.close()

    async def _on_bi(self, src: str, bi: BiStream):
        """serve_sync (peer/mod.rs:1406-1649)."""
        if self._sync_inbound >= self.config.perf.sync_max_concurrent_inbound:
            await bi.send(codec.encode_message("sync_reject", "max_concurrency"))
            bi.close()
            return
        self._sync_inbound += 1
        try:
            frame = await bi.recv(30.0)
            if not frame:
                return
            kind, body, ts, tr, cid = codec.decode_message_full(frame)
            if kind != "sync_start":
                return
            if cid != self.config.cluster_id:
                # typed rejection so the initiator can tell policy from
                # failure (peer/mod.rs:1431 SyncRejectionV1::DifferentCluster)
                self.stats["cluster_mismatch_dropped"] += 1
                await bi.send(codec.encode_message("sync_reject", "different_cluster"))
                return
            # continue the client's trace (serve_sync extraction,
            # peer/mod.rs:1415-1417)
            from ..tracing import extract, span

            # a malformed carrier from a peer must never break sync
            remote = (
                extract(tr.get("traceparent"), tr.get("tracestate", ""))
                if isinstance(tr, dict)
                else None
            )
            with span("serve_sync", parent=remote, peer=src):
                await self._serve_sync_traced(bi, ts)
        except ConnectionError:
            pass
        finally:
            self._sync_inbound -= 1
            bi.close()

    async def _serve_sync_traced(self, bi: BiStream, ts: Optional[int]):
        if ts is not None:
            try:
                self.clock.update(ts)
            except ClockDriftError:
                return
        await bi.send(
            codec.encode_message(
                "sync_state",
                codec.encode_sync_state(self.sync_state()),
                ts=self.clock.now(),
                cid=self.config.cluster_id,
            )
        )
        frame = await bi.recv(30.0)
        if not frame:
            return
        kind, body, _ = codec.decode_message(frame)
        if kind != "sync_request" or not body:
            return
        needs = codec.decode_needs(body)
        sender = AdaptiveSender(self.config.perf, telemetry=self.telemetry)
        try:
            for actor_id, need_list in needs.items():
                for need in need_list:
                    await self._serve_need(bi, actor_id, need, sender)
            await bi.send(codec.encode_message("sync_done", None))
        except SlowPeerAbort:
            # the caller's finally closes the stream; the peer re-requests
            # what it still needs next sync round (peer/mod.rs:729-790)
            log.warning(
                "sync serve aborted: peer stalled > %.1fs (chunk size %d)",
                sender.abort_send_s, sender.chunk_size,
            )

    async def _serve_need(
        self,
        bi: BiStream,
        actor_id: ActorId,
        need: SyncNeed,
        sender: Optional["AdaptiveSender"] = None,
    ):
        """handle_need (peer/mod.rs:371-790): stream chunked changesets,
        newest version first; versions with no remaining rows are Cleared
        (Empty changesets).  Sends go through an AdaptiveSender: chunk
        size halves 8 KiB→1 KiB on slow sends, 5 s stalls abort."""
        perf = self.config.perf
        if sender is None:
            sender = AdaptiveSender(perf)
        if self.slow_inject_s > 0:
            # slow-node gray failure: the sync stream stalls per served
            # need — the puller sees slow sends (its adaptive sender
            # telemetry) but every chunk still arrives; nothing is lost
            await self.slow_gate()
        if need.kind == "full":
            lo, hi = need.versions
            booked = self.bookie.for_actor(actor_id)
            # ONE consistent bookkeeping view taken BEFORE the row scan:
            # anything the view counts as known committed before the scan
            # (its rows are visible below); anything newer is capped out
            # by known_hi — so no freshly-committed version can fall into
            # the cleared runs
            needed, partial_keys, last = booked.serve_view()
            by_version = self.store.changes_for_version_range(actor_id, lo, hi)
            # versions we know but hold no rows for → cleared (Empty) runs,
            # computed with range algebra instead of a per-version scan.
            # Versions held only as PARTIALS (rows still buffered, not in
            # the clock tables) are NOT cleared — advertising them EMPTY
            # poisons the puller into marking data it never got as known
            # (the round-2 cold-catch-up stall)
            known_hi = min(hi, last or 0)
            empty_runs = RangeSet([(lo, known_hi)] if lo <= known_hi else [])
            for glo, ghi in list(needed.overlapping(lo, hi)):
                empty_runs.remove(glo, ghi)
            for v in by_version:
                empty_runs.remove(v, v)
            for v in partial_keys:
                empty_runs.remove(v, v)
            for version in sorted(by_version, reverse=True):  # newest first
                changes = by_version[version]
                last_seq = max(ch.seq for ch in changes)
                chunker = ChunkedChanges(changes, 0, last_seq, sender.chunk_size)
                for chunk, seqs in chunker:
                    cs = Changeset(
                        actor_id=actor_id, version=version, changes=tuple(chunk),
                        seqs=seqs, last_seq=last_seq, part=ChangesetPart.FULL,
                    )
                    await sender.send(
                        bi,
                        codec.encode_message("changeset", codec.encode_changeset(cs)),
                    )
                    # a slow send during this version shrinks the NEXT chunk
                    chunker.max_buf_size = sender.chunk_size
            for elo, ehi in empty_runs:
                cs = Changeset(
                    actor_id=actor_id, version=elo, versions_hi=ehi,
                    part=ChangesetPart.EMPTY,
                )
                await sender.send(
                    bi,
                    codec.encode_message("changeset", codec.encode_changeset(cs)),
                )
        elif need.kind == "partial":
            version = need.version
            for slo, shi in need.seqs:
                changes = self.store.changes_for_version(
                    actor_id, version, seq_range=(slo, shi)
                )
                changes += self._buffered_changes(actor_id, version, (slo, shi))
                if not changes:
                    continue
                last_seq = self._partial_last_seq(actor_id, version, changes)
                chunker = ChunkedChanges(
                    sorted(changes, key=lambda c: c.seq), slo, shi,
                    sender.chunk_size,
                )
                for chunk, seqs in chunker:
                    cs = Changeset(
                        actor_id=actor_id, version=version, changes=tuple(chunk),
                        seqs=seqs, last_seq=last_seq, part=ChangesetPart.FULL,
                    )
                    await sender.send(
                        bi,
                        codec.encode_message("changeset", codec.encode_changeset(cs)),
                    )
                    chunker.max_buf_size = sender.chunk_size

    def _buffered_changes(
        self, actor_id: ActorId, version: int, seq_range: Tuple[int, int]
    ) -> List[Change]:
        with self.store.write_session():
            rows = self.store.conn.execute(
                'SELECT "table", pk, cid, val, col_version, db_version, seq, site_id, cl '
                "FROM __corro_buffered_changes WHERE site_id = ? AND db_version = ? "
                "AND seq BETWEEN ? AND ? ORDER BY seq",
                (actor_id.bytes_, version, seq_range[0], seq_range[1]),
            ).fetchall()
        return [
            Change(
                table=r[0], pk=r[1], cid=r[2], val=r[3], col_version=r[4],
                db_version=r[5], seq=r[6], site_id=ActorId(r[7]), cl=r[8],
            )
            for r in rows
        ]

    def _partial_last_seq(
        self, actor_id: ActorId, version: int, changes: List[Change]
    ) -> int:
        partial = self.bookie.for_actor(actor_id).get_partial(version)
        if partial is not None:
            return partial.last_seq
        row = self.store.conn.execute(
            "SELECT last_seq FROM __corro_seq_bookkeeping WHERE site_id = ? AND db_version = ? LIMIT 1",
            (actor_id.bytes_, version),
        ).fetchone()
        return row[0] if row else max(ch.seq for ch in changes)


class InteractiveTx:
    """One explicit write transaction held open across client messages.

    Mirrors exec_transaction_cursors but split into begin/execute/commit
    phases so the PG front-end can interleave wire round-trips (the
    reference checks out the pooled write connection for the whole
    explicit tx, corro-pg/src/lib.rs:1950-2117).  On commit the captured
    changeset flows through the same bookkeeping + broadcast path as the
    HTTP API."""

    def __init__(self, agent: Agent):
        self.agent = agent
        self._booked = agent.bookie.for_actor(agent.actor_id)
        self._snap = None
        self._open = False

    def begin(self):
        self._snap = self._booked.snapshot()
        self._lock_id = self.agent.locks.acquire("pg_interactive_tx")
        try:
            self.agent.store.begin_interactive()
        except Exception:
            self.agent.locks.release(self._lock_id)
            raise
        self._open = True

    def execute(self, sql: str, params=()):
        return self.agent.store.exec_interactive(sql, params)

    def commit(self) -> Optional[CommitInfo]:
        agent = self.agent
        snap = self._snap

        def pre_commit(conn, info: CommitInfo):
            agent.bookie.record_versions(
                agent.actor_id, snap, RangeSet([(info.db_version, info.db_version)])
            )

        try:
            info = agent.store.commit_interactive(pre_commit)
        except Exception:
            agent.store.rollback_interactive()
            raise
        finally:
            self._open = False
            agent.locks.release(self._lock_id)
        if info is not None:
            self._booked.commit_snapshot(snap)
            agent.stats["changes_committed"] += info.last_seq + 1
            if agent.telemetry is not None:
                # same publish stamp as the HTTP write path — the PG
                # front-end's explicit transactions are publishes too
                agent.telemetry.publish(
                    agent.actor_id, info.db_version, info.ts,
                    n_changes=info.last_seq + 1,
                )
            agent._queue_local_broadcast(info)
        return info

    def rollback(self):
        if self._open:
            self.agent.store.rollback_interactive()
            self._open = False
            self.agent.locks.release(self._lock_id)
