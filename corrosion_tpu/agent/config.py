"""Agent configuration.

Rebuild of the reference's TOML config (`corro-types/src/config.rs:62-329`)
including the PerfConfig envelope (config.rs:197-253) whose defaults are the
operating constants in BASELINE.md.  Loaded from TOML (stdlib tomllib) with
``CORRO__SECTION__KEY`` env-var overrides, or built programmatically for
tests (the reference's ConfigBuilder, config.rs:331-452).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class PerfConfig:
    """Every tunable the reference exposes (config.rs:10-59,197-253)."""

    # broadcast (broadcast/mod.rs:401-463)
    broadcast_flush_interval_s: float = 0.5
    broadcast_buffer_cutoff: int = 64 * 1024
    broadcast_rate_limit_bytes_s: int = 10 * 1024 * 1024
    broadcast_max_inflight: int = 500
    # sync cadence (config.rs:49-59, util.rs:367-369)
    sync_backoff_min_s: float = 1.0
    sync_backoff_max_s: float = 15.0
    sync_round_timeout_s: float = 300.0
    sync_max_concurrent_inbound: int = 3  # agent.rs:143
    # ingest (config.rs:15-47, handlers.rs:561-613)
    apply_queue_cost: int = 50
    apply_queue_timeout_s: float = 0.01
    changes_queue_cap: int = 20000
    max_concurrent_applies: int = 5
    # dedup (seen) cache: sized to the queue-cap envelope with a TTL so
    # re-gossip of long-evicted keys re-enters the (idempotent) apply
    # path instead of aging forever (handlers.rs:671-686 seen cache)
    seen_cache_cap: int = 20000
    seen_cache_ttl_s: float = 60.0
    # chunking (change.rs:180, peer/mod.rs:365-368)
    max_changes_byte_size: int = 8 * 1024
    min_changes_byte_size: int = 1024
    # adaptive sync serving: halve the chunk size when a send takes this
    # long (peer/mod.rs:365-368), abort the peer when one stalls this
    # long (peer/mod.rs:729-790)
    sync_slow_send_s: float = 0.5
    sync_stall_abort_s: float = 5.0
    # SWIM (broadcast/mod.rs:951-960)
    swim_probe_interval_s: float = 1.0
    swim_probe_timeout_s: float = 0.5
    swim_suspect_timeout_s: float = 3.0
    swim_num_indirect_probes: int = 3
    swim_max_transmissions: int = 10
    swim_max_packet_size: int = 1178
    swim_down_gc_s: float = 48 * 3600.0
    # scale the suspicion window ~log2(cluster size) like the reference
    # re-tuning foca's WAN config live (broadcast/mod.rs:236-256,951-960);
    # off = the configured window verbatim (calibration tests)
    swim_adaptive_timing: bool = True
    # db maintenance (handlers.rs:470-540, config.rs PerfConfig wal)
    wal_threshold_bytes: int = 10 * 1024 * 1024
    db_maintenance_interval_s: float = 300.0
    # statement interruption (sqlite-pool/src/lib.rs:116)
    statement_timeout_s: float = 30.0
    slow_query_warn_s: float = 1.0
    # serving-tier backpressure (ISSUE 13, doc/serving.md).  Every
    # bound here surfaces as a saturation counter / queue-depth gauge
    # through the host flight recorder — a limit the operator can't see
    # is a silent drop waiting to happen.
    # per-subscriber event queue bound: a consumer that falls this many
    # events behind is DISCONNECTED with an explicit reason (never a
    # silent drop; it re-syncs via the snapshot/?from= path on reconnect)
    sub_queue_cap: int = 1024
    # admission control on /v1/transactions: writes admitted beyond this
    # in-flight count get 429 + Retry-After instead of queueing unbounded
    api_max_inflight_tx: int = 256
    # write-lane batching: how many admitted writes one write_sema hold
    # drains back-to-back before yielding the lane
    api_write_batch: int = 32


@dataclass
class Config:
    db_path: str = ":memory:"
    gossip_addr: str = ""
    api_addr: str = ""  # "host:port" or "" to disable HTTP
    pg_addr: str = ""  # "host:port" for the PG wire front-end; "" disables
    bootstrap: List[str] = field(default_factory=list)
    schema_paths: List[str] = field(default_factory=list)
    cluster_id: int = 0
    # SWIM membership (L5); False = static membership from the bootstrap list
    use_swim: bool = True
    perf: PerfConfig = field(default_factory=PerfConfig)
    admin_path: str = ""  # unix socket path; "" disables
    prometheus_addr: str = ""  # "host:port" scrape endpoint; "" disables
    # [telemetry] OTLP/HTTP trace export (the reference's open-telemetry
    # batch pipeline, corrosion/src/main.rs:57-150); "" disables
    otlp_endpoint: str = ""  # collector base URL or full /v1/traces path
    otlp_service_name: str = "corrosion-tpu"
    # [telemetry] host flight recorder (ISSUE 13): a path arms
    # `attach_host_telemetry` on the agent and periodically writes the
    # per-write stage stamps + saturation gauges as host flight JSONL
    # (atomic replace, so a kill -9'd node leaves its last snapshot) —
    # what makes a devcluster node's backpressure visible from outside
    # the process; "" disables
    telemetry_flight_path: str = ""
    # [gossip.tls] — (m)TLS on the gossip transport (config.rs:170-193,
    # api/peer/mod.rs:149-339).  Keys: cert_file, key_file, ca_file,
    # insecure (bool), client.cert_file/key_file (mTLS),
    # client.required (bool, server demands client certs)
    gossip_tls: dict = field(default_factory=dict)
    # [faults] — in-process fault replay (ISSUE 15; devcluster.py writes
    # it).  Keys: plan (FaultPlan JSON, faults.plan_to_dict), node_index
    # (this node's position in gossip_addrs), gossip_addrs (every node's
    # gossip addr in plan-index order), control_path (the parent
    # driver's round file).  Empty dict = no fault runtime armed.
    faults: dict = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Config":
        """TOML + `CORRO__SECTION__KEY` env overrides (config.rs:315-329)."""
        try:
            import tomllib  # 3.11+ stdlib
        except ModuleNotFoundError:  # 3.10: the API-compatible backport
            import tomli as tomllib

        with open(path, "rb") as f:
            raw = tomllib.load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: dict) -> "Config":
        db = raw.get("db", {})
        api = raw.get("api", {})
        gossip = raw.get("gossip", {})
        admin = raw.get("admin", {})
        tel = raw.get("telemetry", {})
        tel_prom = tel.get("prometheus")
        # reference-style nested `open-telemetry = { endpoint = ... }`;
        # tolerate non-dict shapes (e.g. a bare exporter string)
        tel_otel = tel.get("open-telemetry") or tel.get("open_telemetry")
        if not isinstance(tel_otel, dict):
            tel_otel = {}
        perf_raw = {**raw.get("perf", {})}
        cfg = cls(
            db_path=db.get("path", ":memory:"),
            schema_paths=db.get("schema_paths", []),
            api_addr=api.get("addr", ""),
            pg_addr=api.get("pg", {}).get("addr", "")
            if isinstance(api.get("pg"), dict)
            else api.get("pg_addr", ""),
            gossip_addr=gossip.get("addr", ""),
            bootstrap=gossip.get("bootstrap", []),
            cluster_id=gossip.get("cluster_id", 0),
            gossip_tls=gossip.get("tls", {}),
            admin_path=admin.get("path", ""),
            prometheus_addr=(
                tel_prom.get("addr", "")
                if isinstance(tel_prom, dict)
                else tel.get("prometheus_addr", "")
            ),
            otlp_endpoint=(
                tel.get("otlp_endpoint", "") or tel_otel.get("endpoint", "")
            ),
            otlp_service_name=tel.get("service_name", "corrosion-tpu"),
            telemetry_flight_path=tel.get("flight_path", ""),
            faults=raw.get("faults", {}),
        )
        for k, v in perf_raw.items():
            if hasattr(cfg.perf, k):
                setattr(cfg.perf, k, v)
        cfg._apply_env()
        return cfg

    def _apply_env(self):
        for key, val in os.environ.items():
            if not key.startswith("CORRO__"):
                continue
            parts = key[len("CORRO__"):].lower().split("__")
            if len(parts) == 2 and parts[0] == "perf" and hasattr(self.perf, parts[1]):
                cur = getattr(self.perf, parts[1])
                setattr(self.perf, parts[1], type(cur)(val))
            elif len(parts) == 2 and parts[0] == "db" and parts[1] == "path":
                self.db_path = val
            elif len(parts) == 2 and parts[0] == "gossip" and parts[1] == "addr":
                self.gossip_addr = val
            elif len(parts) == 2 and parts[0] == "api" and parts[1] == "addr":
                self.api_addr = val
            elif len(parts) == 2 and parts[0] == "api" and parts[1] == "pg_addr":
                self.pg_addr = val
