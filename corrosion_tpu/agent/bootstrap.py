"""Bootstrap address resolution (DNS + in-db fallback).

Rebuild of `generate_bootstrap`/`resolve_bootstrap`
(corro-agent/src/agent/bootstrap.rs:14-150): bootstrap entries that are
not literal `ip:port` pairs are DNS names resolved to ALL their A/AAAA
records (real deploys bootstrap via a headless-service name that
resolves to every pod), the node's own address and mismatched address
families are filtered out, and when nothing resolves the agent falls
back to a random sample of previously-known members persisted in
``__corro_members``.  Resolution happens at every (re)join attempt —
the announcer loop calls back in here, so a changed DNS answer is
picked up on rejoin, as in the reference.

Entry forms accepted (bootstrap.rs:73-97):
- ``1.2.3.4:8787``            — literal, used as-is
- ``gossip.svc``              — resolved, default gossip port
- ``gossip.svc:9999``         — resolved, explicit port
- ``gossip.svc:9999@10.0.0.2``— resolved via a specific DNS server; the
  stdlib has no per-server resolver, so this form resolves through the
  system resolver and the `@server` part is recorded in the returned
  diagnostics (callers may inject a custom ``resolver`` for real
  split-horizon setups — the seam the tests use).
"""

from __future__ import annotations

import asyncio
import ipaddress
import logging
import random
import socket
from typing import Awaitable, Callable, Iterable, List, Optional, Sequence, Set

log = logging.getLogger("corrosion_tpu.bootstrap")

#: the reference's default gossip port (bootstrap.rs DEFAULT_GOSSIP_PORT)
DEFAULT_GOSSIP_PORT = 8787
#: how many resolved/fallback nodes a join round targets
#: (bootstrap.rs RANDOM_NODES_CHOICES)
RANDOM_NODES_CHOICES = 10

#: resolver(host) -> list of IP strings (A + AAAA answers)
Resolver = Callable[[str], Awaitable[List[str]]]


async def system_resolver(host: str) -> List[str]:
    """All A/AAAA answers via the system resolver (getaddrinfo)."""
    loop = asyncio.get_running_loop()
    try:
        infos = await loop.getaddrinfo(
            host, None, type=socket.SOCK_DGRAM, proto=socket.IPPROTO_UDP
        )
    except socket.gaierror:
        return []
    out: List[str] = []
    for _family, _type, _proto, _canon, sockaddr in infos:
        ip = sockaddr[0]
        if ip not in out:
            out.append(ip)
    return out


def _split_entry(entry: str) -> tuple[str, int, Optional[str]]:
    """(host, port, dns_server) from ``host[:port][@dns_server]``."""
    host_port, _, dns_server = entry.partition("@")
    host, sep, port_s = host_port.rpartition(":")
    if not sep:
        return host_port, DEFAULT_GOSSIP_PORT, dns_server or None
    try:
        port = int(port_s)
    except ValueError:
        # "host:notaport" — treat the whole thing as a hostname
        return host_port, DEFAULT_GOSSIP_PORT, dns_server or None
    return host, port, dns_server or None


def _is_literal(entry: str) -> bool:
    host, _, _ = entry.partition("@")
    addr, sep, port = host.rpartition(":")
    if not sep:
        return False
    try:
        int(port)
        ipaddress.ip_address(addr.strip("[]"))
        return True
    except ValueError:
        return False


def _family(addr: str) -> int:
    """4 or 6 for a bare IP or an ``ip:port`` / ``[ip6]:port`` string."""
    for candidate in (addr, addr.strip("[]"),
                      addr.rpartition(":")[0].strip("[]")):
        if not candidate:
            continue
        try:
            return ipaddress.ip_address(candidate).version
        except ValueError:
            continue
    return 4


async def resolve_bootstrap(
    bootstrap: Sequence[str],
    our_addr: str,
    resolver: Optional[Resolver] = None,
) -> Set[str]:
    """Resolve every bootstrap entry to ``ip:port`` strings: literals
    pass through, hostnames expand to ALL their address records; our own
    address and cross-family answers are dropped (bootstrap.rs:124-133).
    """
    resolver = resolver or system_resolver
    our_family = _family(our_addr) if our_addr else 4
    addrs: Set[str] = set()
    for entry in bootstrap:
        if not entry:
            continue
        if _is_literal(entry):
            host, port, _ = _split_entry(entry)
            addr = f"{host}:{port}"
            if addr != our_addr:
                addrs.add(addr)
            continue
        host, port, dns_server = _split_entry(entry)
        if dns_server:
            log.debug(
                "bootstrap %s requests resolver %s; using injected/system "
                "resolver", host, dns_server,
            )
        try:
            ips = await resolver(host)
        except Exception as e:  # noqa: BLE001 — resolution is best-effort
            log.warning("could not resolve %r: %s", host, e)
            continue
        for ip in ips:
            if _family(ip) != our_family:
                continue
            addr = f"[{ip}]:{port}" if ":" in ip else f"{ip}:{port}"
            if addr != our_addr:
                addrs.add(addr)
    return addrs


def _db_fallback(store, our_addr: str) -> Set[str]:
    """Random previously-known members from ``__corro_members``
    (bootstrap.rs:28-48) — lets a node rejoin a cluster whose bootstrap
    DNS is gone."""
    try:
        rows = store.conn.execute(
            "SELECT address FROM __corro_members ORDER BY RANDOM() LIMIT 5"
        ).fetchall()
    # corrolint: disable=CT006 — first boot: __corro_members may not
    # exist yet; the empty fallback IS the contract, not an error
    except Exception:  # noqa: BLE001 — schema may not exist yet
        return set()
    return {
        r[0]
        for r in rows
        if r[0] and r[0] != our_addr and _family(r[0]) == _family(our_addr)
    }


async def generate_bootstrap(
    bootstrap: Sequence[str],
    our_addr: str,
    store=None,
    resolver: Optional[Resolver] = None,
    rng: Optional[random.Random] = None,
) -> List[str]:
    """The join-target list for one (re)announce round: resolved
    bootstrap addrs, or the in-db member fallback when resolution comes
    up empty, sampled down to ``RANDOM_NODES_CHOICES``."""
    addrs = await resolve_bootstrap(bootstrap, our_addr, resolver)
    if not addrs and store is not None:
        addrs = _db_fallback(store, our_addr)
    pool = sorted(addrs)
    if len(pool) <= RANDOM_NODES_CHOICES:
        return pool
    return (rng or random).sample(pool, RANDOM_NODES_CHOICES)
