"""DB maintenance: WAL checkpoint/truncate + incremental vacuum.

Rebuild of spawn_handle_db_maintenance (corro-agent/src/agent/
handlers.rs:372-540): an initial WAL truncate at boot, then a periodic
loop that (a) runs ``PRAGMA incremental_vacuum`` whenever the freelist
exceeds a page budget and (b) truncates the WAL whenever the ``-wal``
file outgrows a byte threshold — with a raised busy timeout when it has
grown far past it (the reference escalates to the write conn at 5x).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import TYPE_CHECKING, Optional

from ..invariants import sometimes
from ..metrics import REGISTRY

if TYPE_CHECKING:
    from .agent import Agent

log = logging.getLogger("corrosion_tpu.maintenance")

_wal_hist = REGISTRY.histogram("corro_db_wal_truncate_seconds")
_wal_busy = REGISTRY.counter("corro_db_wal_truncate_busy")

MAX_DB_FREE_PAGES = 10_000
VACUUM_CHUNK_PAGES = 1_000


def wal_checkpoint_truncate(store, busy_timeout_ms: int = 1_000) -> bool:
    """PRAGMA wal_checkpoint(TRUNCATE) with a temporary busy timeout
    (wal_checkpoint, handlers.rs:372-392).  True if the WAL truncated.

    Runs under the store's writer lock: this executes on a worker thread,
    and without the lock a concurrent ``store.close()`` would close the
    connection out from under the C call (segfault)."""
    with store._lock:
        if store._closed:
            return False
        conn = store.conn
        t0 = time.monotonic()
        (orig,) = conn.execute("PRAGMA busy_timeout").fetchone()
        conn.execute(f"PRAGMA busy_timeout = {busy_timeout_ms}")
        try:
            busy, _log_pages, _ckpt_pages = conn.execute(
                "PRAGMA wal_checkpoint(TRUNCATE)"
            ).fetchone()
        finally:
            conn.execute(f"PRAGMA busy_timeout = {orig}")
    sometimes(not busy, "wal-truncated")
    if busy:
        log.warning(
            "could not truncate sqlite WAL, database busy "
            "(timeout %d ms)", busy_timeout_ms,
        )
        _wal_busy.inc()
        return False
    _wal_hist.observe(time.monotonic() - t0)
    return True


def _vacuum_enabled(conn) -> bool:
    (mode,) = conn.execute("PRAGMA auto_vacuum").fetchone()
    return mode == 2


def _freelist(conn) -> int:
    (n,) = conn.execute("PRAGMA freelist_count").fetchone()
    return n


def _vacuum_chunk(store, pages: int) -> None:
    # chunked so the write lane is never held long (the reference
    # vacuums N pages per txn for the same reason)
    with store._lock:
        if store._closed:
            return
        store.conn.execute(f"PRAGMA incremental_vacuum({pages})")


def vacuum_db(store, max_free_pages: int = MAX_DB_FREE_PAGES) -> int:
    """Incremental-vacuum until the freelist drops below the budget
    (vacuum_db, handlers.rs:396-468).  Returns pages reclaimed.
    No-op (silent — callers warn once) unless auto_vacuum=INCREMENTAL.
    Synchronous variant for tools/tests; the agent loop drives the same
    primitives via vacuum_db_async."""
    if not _vacuum_enabled(store.conn):
        return 0
    reclaimed = 0
    freelist = _freelist(store.conn)
    while freelist > max_free_pages:
        _vacuum_chunk(store, VACUUM_CHUNK_PAGES)
        now_free = _freelist(store.conn)
        if now_free >= freelist:
            break  # no progress; don't spin
        reclaimed += freelist - now_free
        freelist = now_free
    return reclaimed


async def vacuum_db_async(agent: "Agent", max_free_pages: int = MAX_DB_FREE_PAGES) -> int:
    """vacuum_db's loop with each chunk run off-loop under the agent
    write semaphore — the vacuum must never execute inside someone
    else's open write transaction on the shared connection (the
    reference vacuums on the pooled low-priority write conn)."""
    store = agent.store
    if not _vacuum_enabled(store.conn):
        return 0
    reclaimed = 0
    freelist = _freelist(store.conn)
    while freelist > max_free_pages and not agent._stopped.is_set():
        async with agent.write_sema:
            await asyncio.to_thread(_vacuum_chunk, store, VACUUM_CHUNK_PAGES)
        now_free = _freelist(store.conn)
        if now_free >= freelist:
            break  # no progress; don't spin
        reclaimed += freelist - now_free
        freelist = now_free
    return reclaimed


async def db_maintenance_loop(
    agent: "Agent",
    interval_s: float = 300.0,
    initial_delay_s: float = 60.0,
) -> None:
    """spawn_handle_db_maintenance (handlers.rs:470-540): initial WAL
    truncate, then periodic vacuum + threshold-triggered truncation."""
    store = agent.store
    if store.path in (":memory:", ""):
        return
    wal_path = store.path + "-wal"
    threshold = agent.config.perf.wal_threshold_bytes

    # checkpoints run in a worker thread (never on the loop — a 5 s busy
    # wait would stall gossip); write_sema keeps async writers out, and
    # SQLite's serialized mode handles any concurrent loop-side read.
    try:
        async with agent.write_sema:
            await asyncio.to_thread(wal_checkpoint_truncate, store)
    except Exception as e:
        log.error("could not initially truncate WAL: %s", e)

    (mode,) = store.conn.execute("PRAGMA auto_vacuum").fetchone()
    if mode != 2:
        log.warning("auto_vacuum isn't set to INCREMENTAL; vacuums disabled")

    # the reference sleeps 60 s first to give the node time to sync
    await asyncio.sleep(initial_delay_s)
    while not agent._stopped.is_set():
        try:
            await vacuum_db_async(agent)
        except Exception as e:
            log.error("could not check freelist and vacuum: %s", e)
        try:
            wal_size = os.path.getsize(wal_path) if os.path.exists(wal_path) else 0
            if wal_size > threshold:
                # far past threshold: wait longer for stragglers (the
                # reference escalates to the write conn at 5x)
                busy_ms = 5_000 if wal_size > 5 * threshold else 1_000
                async with agent.write_sema:
                    await asyncio.to_thread(
                        wal_checkpoint_truncate, store, busy_ms
                    )
        except Exception as e:
            log.error("could not wal_checkpoint truncate: %s", e)
        try:
            await asyncio.wait_for(agent._stopped.wait(), timeout=interval_s)
        except asyncio.TimeoutError:
            pass
