"""Host SWIM membership runtime (L5).

Rebuild of the reference's Foca-driven `runtime_loop`
(`corro-agent/src/broadcast/mod.rs:122-386`; Foca is the SWIM library the
reference embeds) on the transport's datagram verb:

- periodic **probe** of a sampled member, falling back to
  ``num_indirect_probes`` ping-req relays (SWIM's indirect probe);
- **suspect → down** after a timeout, with suspicion disseminated;
- **refutation**: a node seeing itself suspected bumps its incarnation and
  re-asserts ALIVE (the reference's `Actor::renew` auto-rejoin pattern,
  actor.rs:199-209);
- **piggyback dissemination**: membership updates ride probe/ack datagrams,
  each retransmitted up to ``max_transmissions`` times, datagrams capped at
  ``swim_max_packet_size`` (1178 B, broadcast/mod.rs:958);
- **join**: announce to bootstrap addresses; peers answer with a membership
  snapshot (foca's Announce/feed);
- member state persisted to ``__corro_members`` and replayed on boot
  (broadcast/mod.rs:889-948, util.rs:66-101).

State per known member: (addr, incarnation, status, hlc_ts).  Status
precedence for merging is SWIM's: higher incarnation wins; at equal
incarnation DOWN > SUSPECT > ALIVE.
"""

from __future__ import annotations

import asyncio
import json
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, TYPE_CHECKING

from ..core import swim_tuning
from ..core.types import Actor, ActorId
from ..utils.backoff import Backoff

if TYPE_CHECKING:
    from .agent import Agent

ALIVE, SUSPECT, DOWN = 0, 1, 2


@dataclass
class MemberInfo:
    actor_id: ActorId
    addr: str
    incarnation: int = 0
    status: int = ALIVE
    ts: int = 0  # identity timestamp (renew() bumps)
    suspect_since: float = -1.0
    down_since: float = -1.0  # monotonic stamp for down-member GC
    # probe tick at suspicion start (transient, not persisted): the
    # suspicion window expires after N probe PERIODS of our own probe
    # clock, so an overloaded node (stretched event loop) suspects and
    # expires on the same stretched timescale — load cannot skew
    # detection latency measured in periods (VERDICT r2 item 2)
    suspect_tick: int = -1

    def key(self):
        return (self.incarnation, self.status)


@dataclass
class _Update:
    """A disseminating membership update with a retransmission budget."""

    info: MemberInfo
    sends_left: int


def _encode_member(m: MemberInfo) -> list:
    return [m.actor_id.hex(), m.addr, m.incarnation, m.status, m.ts]


def _decode_member(row: list) -> MemberInfo:
    return MemberInfo(
        actor_id=ActorId.from_hex(row[0]), addr=row[1],
        incarnation=row[2], status=row[3], ts=row[4],
    )


class SwimRuntime:
    def __init__(self, agent: "Agent"):
        self.agent = agent
        self.transport = agent.transport
        self.incarnation = 0
        self.members: Dict[ActorId, MemberInfo] = {}
        self._updates: List[_Update] = []
        self._pending_acks: Dict[int, asyncio.Event] = {}
        self._seq = 0
        self._rng = random.Random(agent.actor_id.bytes_ + b"swim")
        self._tasks: List[asyncio.Task] = []
        self._stopped = False
        # injectable DNS resolver for bootstrap hostname entries
        # (agent/bootstrap.py); None = system getaddrinfo
        self.resolver = None
        # protocol-native clock for calibration (VERDICT r2 item 2): probe
        # periods elapsed and the period at which each member went DOWN —
        # load-robust detection latency in probe periods, not wall-clock
        self.probe_tick = 0
        self.down_tick: Dict[ActorId, int] = {}
        # observed event-loop stretch (actual probe-period sleep over the
        # requested interval): under suite load the scheduler stretches
        # the whole node — probe cadence AND the peer's ack path — so the
        # ack deadline must stretch with it or an overloaded-but-healthy
        # peer gets falsely suspected (the full-suite stress flake: 27/30
        # live under load, clean in isolation).  The suspicion WINDOW
        # already runs on the probe-tick clock; this is its wall-clock
        # sibling for the probe timeout.
        self._lag_factor = 1.0

    # -- lifecycle --------------------------------------------------------

    @classmethod
    def attach(cls, agent: "Agent") -> "SwimRuntime":
        rt = cls(agent)
        agent.swim = rt
        return rt

    async def start(self):
        self._load_members()
        await self._announce()
        self._tasks.append(asyncio.create_task(self._probe_loop()))
        self._tasks.append(asyncio.create_task(self._announcer_loop()))

    async def _announce(self):
        """Send a join to every bootstrap peer.  The bootstrap list is
        RE-RESOLVED on every announce (DNS names expand to all their
        A/AAAA records; in-db member fallback when resolution is empty —
        bootstrap.rs:14-150 via agent/bootstrap.py), so a changed DNS
        answer is picked up on rejoin.  ``self.resolver`` is the
        injectable DNS seam (None = system resolver)."""
        if self.transport.resolves_dns or self.resolver is not None:
            from .bootstrap import generate_bootstrap

            targets = await generate_bootstrap(
                self.agent.config.bootstrap,
                self.transport.addr,
                store=self.agent.store,
                resolver=self.resolver,
            )
        else:
            # memory-transport addrs are symbolic names, not resolvable
            targets = list(self.agent.config.bootstrap)
        for addr in targets:
            if addr != self.transport.addr:
                await self._send(addr, {"k": "join", "me": self._self_member()})

    async def _announcer_loop(self):
        """Re-announce to the bootstrap set with backoff whenever the node
        knows no live peers (spawn_swim_announcer, handlers.rs:193-246) —
        a lone join datagram is lost if the peer isn't up yet, and a node
        whose peers all died must keep trying to rejoin."""
        backoff = Backoff(min_s=1.0, max_s=15.0)
        while not self._stopped:
            await asyncio.sleep(next(backoff))
            if any(
                m.status == ALIVE and m.actor_id != self.agent.actor_id
                for m in self.members.values()
            ):
                backoff.reset()  # joined; stay cheap until peers vanish
                continue
            await self._announce()

    async def stop(self):
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._persist_members()

    def _self_member(self) -> list:
        return _encode_member(
            MemberInfo(
                actor_id=self.agent.actor_id, addr=self.transport.addr,
                incarnation=self.incarnation, status=ALIVE,
                ts=self.agent.clock.peek(),
            )
        )

    def rejoin(self):
        """Explicit rejoin (`FocaCmd::Rejoin`, broadcast/mod.rs:263-274):
        bump incarnation (a renewed identity, actor.rs:199-209), re-assert
        ALIVE, and re-announce to the bootstrap set."""
        self.incarnation += 1
        me = _decode_member(self._self_member())
        self._disseminate(me)
        self._tasks.append(asyncio.create_task(self._announce()))

    # -- persistence (reference __corro_members) --------------------------

    def _load_members(self):
        for row in self.agent.store.conn.execute(
            "SELECT actor_id, address, foca_state FROM __corro_members"
        ):
            try:
                info = _decode_member(json.loads(row[2]))
            except (TypeError, json.JSONDecodeError):
                continue
            if info.actor_id != self.agent.actor_id:
                # replayed members start as suspects until a probe confirms
                info.status = min(info.status, SUSPECT)
                info.suspect_since = time.monotonic()
                self.members[info.actor_id] = info
                self._apply_to_agent(info)

    def _persist_members(self):
        conn = self.agent.store.conn
        conn.execute("DELETE FROM __corro_members")
        conn.executemany(
            "INSERT OR REPLACE INTO __corro_members (actor_id, address, foca_state) "
            "VALUES (?, ?, ?)",
            [
                (m.actor_id.bytes_, m.addr, json.dumps(_encode_member(m)))
                for m in self.members.values()
            ],
        )

    # -- wire -------------------------------------------------------------

    async def _send(self, addr: str, msg: dict):
        # every SWIM datagram carries the cluster id so foreign-cluster
        # membership gossip can never merge (the reference's foca runtime
        # is isolated the same way — uni.rs:73-75 gates broadcast frames,
        # and membership rides the same identity envelope)
        if self.agent.config.cluster_id:
            msg["cid"] = self.agent.config.cluster_id
        msg["gossip"] = self._pick_gossip()
        data = json.dumps(msg, separators=(",", ":")).encode()
        # stay under the SWIM datagram budget by shedding gossip entries
        while len(data) > self.agent.config.perf.swim_max_packet_size and msg["gossip"]:
            msg["gossip"].pop()
            data = json.dumps(msg, separators=(",", ":")).encode()
        try:
            await self.transport.send_datagram(addr, data)
        except (ConnectionError, OSError):
            pass

    def _pick_gossip(self) -> list:
        out = []
        for upd in list(self._updates):
            if upd.sends_left <= 0:
                self._updates.remove(upd)
                continue
            upd.sends_left -= 1
            out.append(_encode_member(upd.info))
            if len(out) >= 6:
                break
        return out

    def _disseminate(self, info: MemberInfo):
        self._updates.insert(
            0,
            _Update(
                info=info,
                sends_left=self.effective_max_transmissions(),
            ),
        )

    async def handle_datagram(self, src: str, data: bytes):
        try:
            msg = json.loads(data)
        except json.JSONDecodeError:
            return
        if msg.get("cid", 0) != self.agent.config.cluster_id:
            # drop foreign-cluster datagrams before merging any gossip —
            # two clusters sharing a network must not exchange membership
            self.agent.stats["cluster_mismatch_dropped"] += 1
            return
        kind = msg.get("k")
        for row in msg.get("gossip", []):
            self._merge(_decode_member(row))
        if kind == "join":
            joiner = _decode_member(msg["me"])
            self._merge(joiner)
            # feed the joiner a membership snapshot (foca Announce reply)
            snapshot = [self._self_member()] + [
                _encode_member(m)
                for m in self.members.values()
                if m.status == ALIVE
            ][:12]
            await self._send(joiner.addr, {"k": "feed", "members": snapshot})
        elif kind == "feed":
            for row in msg.get("members", []):
                self._merge(_decode_member(row))
        elif kind == "ping":
            await self._send(msg["from"], {"k": "ack", "seq": msg["seq"]})
        elif kind == "ping_req":
            # relay: probe the target on behalf of the requester
            seq, target, back = msg["seq"], msg["target"], msg["from"]

            async def relay():
                ok = await self._probe_once(target)
                if ok:
                    await self._send(back, {"k": "ack", "seq": seq})

            self._tasks.append(asyncio.create_task(relay()))
        elif kind == "ack":
            ev = self._pending_acks.get(msg["seq"])
            if ev is not None:
                ev.set()

    # -- merge rules ------------------------------------------------------

    _STATUS_EVENT = {ALIVE: "alive", SUSPECT: "suspect", DOWN: "down"}

    def _swim_event(self, event: str) -> None:
        """Serving-telemetry counter for a membership event (ISSUE 8):
        corro_serving_swim_events_total{event=...} — SWIM belief churn
        alongside the write-path stages, the host twin of the sim
        trace's swim_suspect/swim_down channels."""
        tel = self.agent.telemetry
        if tel is not None:
            tel.swim_event(event)

    def _merge(self, info: MemberInfo):
        if info.actor_id == self.agent.actor_id:
            # refutation: someone thinks we're suspect/down
            if info.status != ALIVE and info.incarnation >= self.incarnation:
                self.incarnation = info.incarnation + 1
                me = _decode_member(self._self_member())
                self._disseminate(me)
                self._swim_event("refute")
            return
        cur = self.members.get(info.actor_id)
        if cur is not None and cur.key() >= info.key():
            return  # stale
        prev_status = cur.status if cur is not None else None
        prev_inc = cur.incarnation if cur is not None else -1
        if cur is None:
            info = MemberInfo(**{**info.__dict__})
        else:
            cur.incarnation = info.incarnation
            cur.status = info.status
            cur.addr = info.addr
            cur.ts = max(cur.ts, info.ts)
            info = cur
        if info.status == SUSPECT:
            # stamp a FRESH suspicion window on every transition INTO
            # suspect AND on every incarnation advance — reusing a stale
            # stamp from a previous episode (DOWN at inc N then
            # re-suspected at inc N+1, or SUSPECT at inc N superseded by
            # SUSPECT at inc N+1) would expire the new suspicion
            # instantly and deny the refutation window
            if (
                prev_status != SUSPECT
                or prev_inc != info.incarnation
                or info.suspect_since < 0
            ):
                info.suspect_since = time.monotonic()
                info.suspect_tick = self.probe_tick
        else:
            # ALIVE clears the episode; DOWN must not carry suspect
            # stamps into a future episode either
            info.suspect_since = -1.0
            info.suspect_tick = -1
        if info.status == ALIVE:
            # a refuted member was never really down: drop the mark so
            # detection-latency readers only see DOWNs that stuck
            self.down_tick.pop(info.actor_id, None)
        if info.status == DOWN:
            self._record_down_tick(info.actor_id)
        if info.status != prev_status:
            # .get: a wire status outside {ALIVE, SUSPECT, DOWN} (skewed
            # or byzantine peer) must not crash the merge path
            ev = self._STATUS_EVENT.get(info.status)
            if ev is not None:
                self._swim_event(ev)
        self.members[info.actor_id] = info
        self._apply_to_agent(info)
        self._disseminate(info)

    def _record_down_tick(self, actor_id: ActorId) -> None:
        """Calibration record (see probe_tick); capped, never unbounded."""
        self.down_tick.setdefault(actor_id, self.probe_tick)
        while len(self.down_tick) > 65536:
            self.down_tick.pop(next(iter(self.down_tick)))

    def _apply_to_agent(self, info: MemberInfo):
        """Bridge to the agent's Members (the reference's DispatchRuntime →
        MemberEvent notifications path, handlers.rs:279-366)."""
        actor = Actor(id=info.actor_id, addr=info.addr, ts=info.ts)
        if info.status == DOWN:
            self.agent.members.remove_member(actor)
        else:
            self.agent.members.add_member(actor)

    # -- probing ----------------------------------------------------------

    async def _probe_once(self, addr: str) -> bool:
        self._seq += 1
        seq = self._seq
        ev = asyncio.Event()
        self._pending_acks[seq] = ev
        try:
            await self._send(
                addr, {"k": "ping", "seq": seq, "from": self.transport.addr}
            )
            try:
                await asyncio.wait_for(
                    ev.wait(),
                    self.agent.config.perf.swim_probe_timeout_s
                    * self._lag_factor,
                )
                return True
            except asyncio.TimeoutError:
                return False
        finally:
            self._pending_acks.pop(seq, None)

    async def _probe_loop(self):
        perf = self.agent.config.perf
        while not self._stopped:
            # cadence re-derived each tick from live membership
            interval = self.effective_probe_interval_s()
            slept_at = time.monotonic()
            await asyncio.sleep(interval)
            # re-measure the loop stretch every tick (EWMA so one long GC
            # pause doesn't stick); clamp ≥1 (never shrink below config)
            # and ≤8 (a truly dead peer must still be suspectable)
            stretch = (time.monotonic() - slept_at) / max(interval, 1e-6)
            self._lag_factor = min(
                max(0.5 * self._lag_factor + 0.5 * stretch, 1.0), 8.0
            )
            self.probe_tick += 1
            self._expire_suspects()
            candidates = [
                m for m in self.members.values() if m.status != DOWN
            ]
            if not candidates:
                continue
            target = self._rng.choice(candidates)
            ok = await self._probe_once(target.addr)
            if not ok:
                # indirect probes through sampled relays
                relays = [
                    m for m in candidates
                    if m.actor_id != target.actor_id
                ]
                self._rng.shuffle(relays)
                self._seq += 1
                seq = self._seq
                ev = asyncio.Event()
                self._pending_acks[seq] = ev
                for relay in relays[: perf.swim_num_indirect_probes]:
                    await self._send(
                        relay.addr,
                        {
                            "k": "ping_req", "seq": seq,
                            "target": target.addr, "from": self.transport.addr,
                        },
                    )
                try:
                    await asyncio.wait_for(
                        ev.wait(),
                        perf.swim_probe_timeout_s * 2 * self._lag_factor,
                    )
                    ok = True
                except asyncio.TimeoutError:
                    ok = False
                finally:
                    self._pending_acks.pop(seq, None)
            if not ok and target.status == ALIVE:
                target.status = SUSPECT
                target.suspect_since = time.monotonic()
                target.suspect_tick = self.probe_tick
                self._swim_event("suspect")
                self._disseminate(target)

    # -- cluster-size feedback (broadcast/mod.rs:236-256, 951-960) --------
    #
    # Every read of these effective_* values re-derives the parameter
    # from the LIVE membership count, which is the same feedback loop the
    # reference runs through FocaInput::ClusterSize → make_foca_config →
    # foca.set_config on every membership change — just without the
    # config-object churn (the formulas live in core/swim_tuning.py,
    # shared with the simulator's SimConfig.wan_tuned).

    def live_count(self) -> int:
        """LIVE cluster size (self + non-DOWN members): DOWN members
        linger until their GC window and would otherwise inflate the
        timing with all-time churn."""
        return 1 + sum(1 for m in self.members.values() if m.status != DOWN)

    def effective_probe_interval_s(self) -> float:
        perf = self.agent.config.perf
        if not perf.swim_adaptive_timing:
            return perf.swim_probe_interval_s
        return perf.swim_probe_interval_s * swim_tuning.probe_interval_factor(
            self.live_count()
        )

    def effective_max_transmissions(self) -> int:
        perf = self.agent.config.perf
        if not perf.swim_adaptive_timing:
            return perf.swim_max_transmissions
        return swim_tuning.max_transmissions_for(
            self.live_count(), perf.swim_max_transmissions
        )

    def _suspect_timeout_s(self) -> float:
        """Cluster-size-adaptive suspicion window: suspicion must outlast
        the longer gossip paths of a bigger cluster, scaling ~log₂(N)."""
        base = self.agent.config.perf.swim_suspect_timeout_s
        if not self.agent.config.perf.swim_adaptive_timing:
            return base
        # normalized so a small test cluster keeps the configured window
        return base * swim_tuning.suspicion_factor(self.live_count() - 1)

    def _expired(self, m: MemberInfo, timeout_s: float, now: float) -> bool:
        """Suspicion expiry in probe PERIODS when the tick is known (the
        load-invariant clock); wall-clock fallback for entries whose
        suspicion predates this runtime (persisted/legacy)."""
        if m.suspect_tick >= 0:
            # ticks and timeout must use the SAME (effective) cadence or
            # the window would shrink as the probe interval stretches
            interval = max(self.effective_probe_interval_s(), 1e-6)
            return self.probe_tick - m.suspect_tick > timeout_s / interval
        return now - m.suspect_since > timeout_s

    def _expire_suspects(self):
        timeout = self._suspect_timeout_s()
        now = time.monotonic()
        gc_after = self.agent.config.perf.swim_down_gc_s
        drop = []
        for m in self.members.values():
            if m.status == SUSPECT and self._expired(m, timeout, now):
                m.status = DOWN
                m.down_since = now
                self._record_down_tick(m.actor_id)
                self._swim_event("down")
                self._apply_to_agent(m)
                self._disseminate(m)
            elif m.status == DOWN:
                # down-member GC (foca remove_down_after=48h,
                # broadcast/mod.rs:951-960): forget long-dead members so
                # the roster reflects the live cluster
                if m.down_since < 0:
                    m.down_since = now
                elif now - m.down_since > gc_after:
                    drop.append(m.actor_id)
        for actor_id in drop:
            self.members.pop(actor_id, None)
        if drop:
            self._persist_members()
