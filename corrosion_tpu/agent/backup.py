"""Cluster-aware backup and online restore.

Rebuild of the reference's `corrosion backup` / `corrosion restore` commands
(`crates/corrosion/src/main.rs:160-331`) and the `sqlite3-restore` crate
(`crates/sqlite3-restore/src/lib.rs:57-152`):

- **backup**: `VACUUM INTO` a snapshot, then strip everything node-specific
  (the local site id, member list, persisted subscriptions, sync bookkeeping)
  so the snapshot can be restored on *any* node — the analog of the reference
  deleting the ordinal-0 `crsql_site_id` row and `__corro_*` per-node state.
- **restore**: swap the snapshot over a live DB file while holding POSIX
  locks on the main/-wal/-shm file handles (blocking every other SQLite
  client, exactly `lock_all`, sqlite3-restore lib.rs:152), truncate-copy the
  backup over the live file, drop the stale WAL, and stamp a fresh (or
  caller-chosen) site id so the restored node is a brand-new actor.

Replicated CRDT data (base tables, clock tables, row causal lengths,
per-origin db_versions) is preserved verbatim: it is cluster state, not node
state, and anti-entropy reconciles it from wherever the snapshot lands.
"""

from __future__ import annotations

import fcntl
import os
import sqlite3
from contextlib import contextmanager
from typing import Iterator, List, Optional

from ..core.types import ActorId

# Tables whose contents are per-node, not cluster data (main.rs:183-212).
_NODE_STATE_TABLES = (
    "__corro_members",
    "__corro_subs",
    "__corro_bookkeeping_gaps",
    "__corro_seq_bookkeeping",
    "__corro_buffered_changes",
)


def backup_db(src_path: str, dest_path: str) -> None:
    """Snapshot `src_path` into `dest_path`, stripped of node identity.

    Uses `VACUUM INTO` (same primitive as main.rs:172) so the snapshot is a
    compact, consistent single file even while the source is being written.
    """
    if os.path.exists(dest_path):
        raise FileExistsError(f"backup target already exists: {dest_path}")
    src = sqlite3.connect(src_path)
    try:
        src.execute("VACUUM INTO ?", (dest_path,))
    finally:
        src.close()

    dest = sqlite3.connect(dest_path)
    try:
        dest.execute("BEGIN")
        dest.execute("DELETE FROM __corro_state WHERE key = 'site_id'")
        for table in _NODE_STATE_TABLES:
            try:
                dest.execute(f'DELETE FROM "{table}"')
            except sqlite3.OperationalError:
                pass  # snapshot predates the table: nothing to strip
        dest.execute("COMMIT")
        dest.execute("VACUUM")
    finally:
        dest.close()


@contextmanager
def _locked_db_files(live_path: str) -> Iterator[List[int]]:
    """POSIX-write-lock the main/-wal/-shm files of a live SQLite DB.

    The reference locks every file handle before overwriting so concurrent
    SQLite clients block rather than read torn state
    (sqlite3-restore lib.rs:57-152). O_CREAT matches its behavior of locking
    side files even if they don't exist yet.
    """
    fds: List[int] = []
    try:
        for suffix in ("", "-wal", "-shm"):
            fd = os.open(live_path + suffix, os.O_RDWR | os.O_CREAT, 0o644)
            fcntl.lockf(fd, fcntl.LOCK_EX)
            fds.append(fd)
        yield fds
    finally:
        for fd in fds:
            try:
                fcntl.lockf(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)


def restore_db(
    backup_path: str,
    live_path: str,
    site_id: Optional[ActorId] = None,
) -> ActorId:
    """Swap `backup_path` over `live_path` under POSIX locks and stamp a
    node identity.  Returns the ActorId the restored DB now runs as.

    The restored node is a *new actor* (fresh site id unless the caller
    pins one): its future writes must not collide with versions the
    snapshot's origin already gossiped (main.rs:227-331).
    """
    if not os.path.exists(backup_path):
        raise FileNotFoundError(backup_path)
    # Validate the snapshot is actually node-stripped corrosion state before
    # touching the live file.
    check = sqlite3.connect(f"file:{backup_path}?mode=ro", uri=True)
    try:
        tables = {
            r[0]
            for r in check.execute(
                "SELECT name FROM sqlite_master WHERE type = 'table'"
            )
        }
        if "__corro_state" not in tables:
            raise ValueError(f"not a corrosion backup: {backup_path}")
    finally:
        check.close()

    actor = site_id or ActorId.random()
    with _locked_db_files(live_path) as (main_fd, wal_fd, shm_fd):
        # Truncate-copy the backup over the live main file through the
        # locked fd (lib.rs:107-133), then drop the now-stale WAL/SHM.
        os.lseek(main_fd, 0, os.SEEK_SET)
        os.ftruncate(main_fd, 0)
        with open(backup_path, "rb") as src:
            while chunk := src.read(1 << 20):
                os.write(main_fd, chunk)
        os.ftruncate(wal_fd, 0)
        os.ftruncate(shm_fd, 0)
        os.fsync(main_fd)

    conn = sqlite3.connect(live_path)
    try:
        conn.execute("BEGIN")
        conn.execute("DELETE FROM __corro_state WHERE key = 'site_id'")
        conn.execute(
            "INSERT INTO __corro_state (key, value) VALUES ('site_id', ?)",
            (actor.bytes_,),
        )
        conn.execute("COMMIT")
    finally:
        conn.close()
    return actor


@contextmanager
def db_lock(live_path: str) -> Iterator[None]:
    """Hold exclusive POSIX locks on a live DB's files (`corrosion db lock`
    command, main.rs:478-497): blocks writers while an operator inspects or
    copies the files out-of-band."""
    with _locked_db_files(live_path):
        yield
