"""Cluster membership view with RTT locality rings.

Rebuild of the reference's `Members` (`corro-types/src/members.rs:38-179`):
known actor states keyed by id, an addr index, and per-member RTT summaries
bucketed into rings — ring 0 (lowest RTT) gets local broadcasts first
(broadcast/mod.rs:589-651).  Ring bucket boundaries match members.rs:38:
[0,6) [6,15) [15,50) [50,100) [100,200) [200,300) ms.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..core.types import Actor, ActorId

RING_BUCKETS_MS = [(0, 6), (6, 15), (15, 50), (50, 100), (100, 200), (200, 300)]


@dataclass
class MemberState:
    actor: Actor
    is_up: bool = True
    ring: Optional[int] = None
    rtts: deque = field(default_factory=lambda: deque(maxlen=20))

    @property
    def addr(self) -> str:
        return self.actor.addr


class Members:
    def __init__(self, self_actor_id: ActorId):
        self.self_id = self_actor_id
        self.states: Dict[ActorId, MemberState] = {}
        self.by_addr: Dict[str, ActorId] = {}

    def add_member(self, actor: Actor) -> bool:
        """Returns True if this made the member newly up (reference
        members.rs add_member)."""
        if actor.id == self.self_id:
            return False
        existing = self.states.get(actor.id)
        if existing is not None:
            was_up = existing.is_up
            if actor.ts >= existing.actor.ts:
                existing.actor = actor
            existing.is_up = True
            self.by_addr[actor.addr] = actor.id
            return not was_up
        self.states[actor.id] = MemberState(actor=actor)
        self.by_addr[actor.addr] = actor.id
        return True

    def remove_member(self, actor: Actor) -> bool:
        """Mark down; True if it was up (we keep state for RTT history)."""
        st = self.states.get(actor.id)
        if st is None or not st.is_up:
            return False
        if actor.ts < st.actor.ts:
            return False  # stale notification about an older identity
        st.is_up = False
        return True

    def record_rtt(self, addr: str, rtt_ms: float) -> None:
        actor_id = self.by_addr.get(addr)
        if actor_id is None:
            return
        st = self.states.get(actor_id)
        if st is None:
            return
        st.rtts.append(rtt_ms)
        avg = sum(st.rtts) / len(st.rtts)
        st.ring = len(RING_BUCKETS_MS)  # beyond last bucket
        for i, (lo, hi) in enumerate(RING_BUCKETS_MS):
            if lo <= avg < hi:
                st.ring = i
                break

    def up_members(self) -> List[MemberState]:
        return [s for s in self.states.values() if s.is_up]

    def ring0(self) -> List[MemberState]:
        """Lowest-populated-ring members (reference broadcast/mod.rs:589-651
        sends local broadcasts here first).  Members with unmeasured RTT
        default to ring 0 so fresh clusters still broadcast."""
        ups = self.up_members()
        if not ups:
            return []
        rings = [s.ring if s.ring is not None else 0 for s in ups]
        lowest = min(rings)
        return [s for s, r in zip(ups, rings) if r == lowest]

    def get(self, actor_id: ActorId) -> Optional[MemberState]:
        return self.states.get(actor_id)

    def __len__(self) -> int:
        return len(self.up_members())
